"""Ring flash attention: sequence-parallel exact attention whose per-block
compute runs the Pallas flash kernels.

The jnp ring (``parallel/sequence.py::_ring_attention_local``) materializes a
[B, H, S_loc, S_loc] probability block per ring step in XLA; this module does
the same ring schedule but each block runs the VMEM-resident online-softmax
kernels from ``flash_attention.py``, so HBM traffic per step is O(S_loc·D)
instead of O(S_loc²). Capability analog of the reference's fused attention
kernels (csrc/transformer softmax/attention fusions) composed with its
sequence-parallel goal; the schedule follows the public Ring Attention
construction (blockwise attention with K/V rotating over the ring,
PAPERS.md) — merging per-block outputs by their logsumexp.

Gradients are exact: the whole ring is one ``jax.custom_vjp``. Backward is a
second ring pass — dK/dV accumulators travel WITH their K/V block around the
ring and arrive home after n steps, the ``ppermute`` analog of the
reference's gradient reduce in sequence parallelism. Per-block dq/dk/dv use
the flash backward kernels with the GLOBAL logsumexp/delta, which is the
flash recomputation identity (p = exp(s - lse_global) is each block's true
probability slice).

Layout: per-device [B, S_loc, H, D]; runs under ``shard_map`` over the sp
axis. S_loc must be a multiple of 128 and the received K/V block must fit
the kernel's VMEM budget (else callers keep the jnp ring).
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax

from .flash_attention import NUM_LANES, _bwd_auto as _bwd, _fwd_auto as _fwd, flash_ok

NEG_BIG = -1e30


def ring_flash_ok(s_loc: int, d: int, itemsize: int) -> bool:
    """Same constraints as the single-device dispatch, per sequence shard:
    each ring step runs the auto-dispatched flash compute (resident kernels
    inside the whole-K/V VMEM budget, KV-blocked grid past it), so a shard
    is admitted up to the grid kernel's ceiling. ``itemsize`` is kept for
    callers' signatures; the budget split happens inside _fwd_auto."""
    return flash_ok(s_loc, d)


def _merge(u, m, l, o_j, lse_j):
    """Online logsumexp merge of one block's (normalized o_j, lse_j) into the
    running (unnormalized u at max m, mass l) accumulators."""
    m_new = jnp.maximum(m, lse_j)
    m_safe = jnp.where(m_new <= NEG_BIG / 2, 0.0, m_new)
    alpha = jnp.where(m <= NEG_BIG / 2, 0.0, jnp.exp(m - m_safe))
    w = jnp.where(lse_j <= NEG_BIG / 2, 0.0, jnp.exp(lse_j - m_safe))
    u = u * alpha[..., None] + o_j.astype(jnp.float32) * w[..., None]
    l = l * alpha + w
    return u, m_new, l


def _ring_fwd_loop(q3, k3, v3, axis_name, sm_scale, causal, interpret):
    n = lax.psum(1, axis_name)
    idx = lax.axis_index(axis_name)
    BH, S, D = q3.shape
    perm = [(j, (j - 1) % n) for j in range(n)]

    u0 = jnp.zeros((BH, S, D), jnp.float32)
    m0 = jnp.full((BH, S), NEG_BIG, jnp.float32)
    l0 = jnp.zeros((BH, S), jnp.float32)

    def diag(kb, vb):
        o, lse = _fwd(q3, kb, vb, sm_scale, True, interpret)
        return o, lse[..., 0]

    def full(kb, vb):
        o, lse = _fwd(q3, kb, vb, sm_scale, False, interpret)
        return o, lse[..., 0]

    def masked(kb, vb):
        return jnp.zeros_like(q3), jnp.full((BH, S), NEG_BIG, jnp.float32)

    def step(carry, j):
        u, m, l, kb, vb = carry
        src = (idx + j) % n
        if causal:
            # src == idx: the diagonal block (causal mask); src < idx: fully
            # visible; src > idx: fully masked — skipped (the cond's cost
            # asymmetry cannot shorten the ring step, but it saves the HBM
            # reads/flops of a guaranteed-zero block)
            branch = jnp.where(src == idx, 0, jnp.where(src < idx, 1, 2))
            o_j, lse_j = lax.switch(branch, [diag, full, masked], kb, vb)
        else:
            o_j, lse_j = full(kb, vb)
        u, m, l = _merge(u, m, l, o_j, lse_j)
        kb = lax.ppermute(kb, axis_name, perm)
        vb = lax.ppermute(vb, axis_name, perm)
        return (u, m, l, kb, vb), None

    (u, m, l, _, _), _ = lax.scan(step, (u0, m0, l0, k3, v3), jnp.arange(n))
    l_safe = jnp.maximum(l, 1e-30)
    o = (u / l_safe[..., None]).astype(q3.dtype)
    lse = m + jnp.log(l_safe)  # [BH, S]
    return o, lse


def _ring_bwd_loop(q3, k3, v3, o3, lse, do3, axis_name, sm_scale, causal, interpret):
    n = lax.psum(1, axis_name)
    idx = lax.axis_index(axis_name)
    BH, S, D = q3.shape
    perm = [(j, (j - 1) % n) for j in range(n)]
    lse_b = jnp.broadcast_to(lse[..., None], (BH, S, NUM_LANES))

    def diag(kb, vb):
        return _bwd(q3, kb, vb, o3, lse_b, do3, sm_scale, True, interpret)

    def full(kb, vb):
        return _bwd(q3, kb, vb, o3, lse_b, do3, sm_scale, False, interpret)

    def masked(kb, vb):
        z = jnp.zeros_like(q3)
        return z, z, z

    def step(carry, j):
        dq, kb, vb, dkb, dvb = carry
        src = (idx + j) % n
        if causal:
            branch = jnp.where(src == idx, 0, jnp.where(src < idx, 1, 2))
            dq_j, dk_j, dv_j = lax.switch(branch, [diag, full, masked], kb, vb)
        else:
            dq_j, dk_j, dv_j = full(kb, vb)
        dq = dq + dq_j.astype(jnp.float32)
        # the block's grad accumulators ride the ring WITH the block and
        # arrive back at the owner after n steps (p2p grad reduce analog)
        dkb = dkb + dk_j.astype(jnp.float32)
        dvb = dvb + dv_j.astype(jnp.float32)
        kb = lax.ppermute(kb, axis_name, perm)
        vb = lax.ppermute(vb, axis_name, perm)
        dkb = lax.ppermute(dkb, axis_name, perm)
        dvb = lax.ppermute(dvb, axis_name, perm)
        return (dq, kb, vb, dkb, dvb), None

    dq0 = jnp.zeros((BH, S, D), jnp.float32)
    z0 = jnp.zeros((BH, S, D), jnp.float32)
    (dq, _, _, dk, dv), _ = lax.scan(
        step, (dq0, k3, v3, z0, z0), jnp.arange(n)
    )
    return dq.astype(q3.dtype), dk.astype(k3.dtype), dv.astype(v3.dtype)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def _ring_flash(q3, k3, v3, axis_name, sm_scale, causal, interpret):
    o, _ = _ring_fwd_loop(q3, k3, v3, axis_name, sm_scale, causal, interpret)
    return o


def _ring_flash_fwd_rule(q3, k3, v3, axis_name, sm_scale, causal, interpret):
    o, lse = _ring_fwd_loop(q3, k3, v3, axis_name, sm_scale, causal, interpret)
    return o, (q3, k3, v3, o, lse)


def _ring_flash_bwd_rule(axis_name, sm_scale, causal, interpret, res, do3):
    q3, k3, v3, o3, lse = res
    return _ring_bwd_loop(
        q3, k3, v3, o3, lse, do3, axis_name, sm_scale, causal, interpret
    )


_ring_flash.defvjp(_ring_flash_fwd_rule, _ring_flash_bwd_rule)


def ring_flash_attention(
    q,
    k,
    v,
    axis_name: str,
    causal: bool = True,
    sm_scale: Optional[float] = None,
    interpret: bool = False,
):
    """Per-device entry (call under shard_map): q/k/v [B, S_loc, H, D] →
    [B, S_loc, H, D], attending over the full ring-distributed sequence."""
    B, S, H, D = q.shape
    if not ring_flash_ok(S, D, q.dtype.itemsize):
        raise ValueError(
            f"ring flash needs S_loc % 128 == 0, D % 64 == 0 and S_loc within "
            f"the grid kernel's bookkeeping ceiling (got S_loc={S}, D={D}); "
            "raise sp_size to shrink the per-device shard"
        )
    scale = float(sm_scale) if sm_scale is not None else 1.0 / (D**0.5)

    def to3(x):
        return x.transpose(0, 2, 1, 3).reshape(B * H, S, D)

    o3 = _ring_flash(
        to3(q), to3(k), to3(v), axis_name, scale, bool(causal), bool(interpret)
    )
    return o3.reshape(B, H, S, D).transpose(0, 2, 1, 3)
