"""Block-sparse flash attention as a Pallas TPU kernel (fwd + bwd).

TPU-native analog of the reference's Triton block-sparse attention
(``ops/sparse_attention/matmul.py`` SDD/DSD kernels + ``softmax.py``,
~1350 LoC of Triton 1.0): instead of Triton's lookup tables, the static
block layout [H, nQ, nK] is compiled into per-row index lists
(``kidx [H, nQ, maxK]`` + counts) delivered to SMEM via scalar prefetch
(the splash-attention pattern); each kernel instance walks its list with
dynamic slices — inactive blocks are never read from HBM, so compute and
bandwidth scale with layout density, the same asymptotics as the reference
(docs claim ~6.3x over dense at high sparsity).

The sparsity block size IS the kernel tile size: use >= 64 (ideally 128) on
real TPUs for MXU efficiency; any multiple of 8 works functionally.
Within-block causal masking handles the diagonal blocks of unidirectional
layouts.
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = -1e30
NUM_LANES = 128  # lse/delta carry a broadcast 128-lane trailing dim (Mosaic
                 # block-tiling requirement; official flash kernel layout)


def layout_to_index_lists(layout: np.ndarray) -> Tuple[np.ndarray, np.ndarray, np.ndarray, np.ndarray]:
    """[H, nQ, nK] bool → (kidx [H,nQ,maxK], kcnt [H,nQ], qidx [H,nK,maxQ],
    qcnt [H,nK]) — forward walks kidx, backward-dkv walks qidx."""
    H, nQ, nK = layout.shape
    kcnt = layout.sum(axis=2).astype(np.int32)
    qcnt = layout.sum(axis=1).astype(np.int32)
    maxK = max(1, int(kcnt.max()))
    maxQ = max(1, int(qcnt.max()))
    kidx = np.zeros((H, nQ, maxK), np.int32)
    qidx = np.zeros((H, nK, maxQ), np.int32)
    for h in range(H):
        for i in range(nQ):
            cols = np.nonzero(layout[h, i])[0]
            kidx[h, i, : len(cols)] = cols
        for j in range(nK):
            rows = np.nonzero(layout[h, :, j])[0]
            qidx[h, j, : len(rows)] = rows
    return kidx, kcnt, qidx, qcnt


def _block_mask(s, qrow0, krow0, causal):
    if not causal:
        return s
    row = qrow0 + jax.lax.broadcasted_iota(jnp.int32, s.shape, 0)
    col = krow0 + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
    return jnp.where(row >= col, s, NEG_INF)


def _fwd_kernel(kidx_ref, kcnt_ref, q_ref, k_ref, v_ref, o_ref, lse_ref, *,
                sm_scale, causal, blk):
    h = pl.program_id(1)
    qi = pl.program_id(2)
    # dots take storage-dtype operands with f32 accumulation (bf16 inputs
    # ride the MXU's native path; products stay exact in the accumulator);
    # sm_scale applies to the f32 scores, exact for any scale
    q = q_ref[0, 0]  # [blk, D]
    cnt = kcnt_ref[h, qi]

    def body(j, carry):
        acc, m_prev, l_prev = carry
        kj = kidx_ref[h, qi, j]
        k = k_ref[0, 0, pl.ds(kj * blk, blk), :]
        v = v_ref[0, 0, pl.ds(kj * blk, blk), :]
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32) * sm_scale
        s = _block_mask(s, qi * blk, kj * blk, causal)
        m_cur = jnp.max(s, axis=1)
        m_new = jnp.maximum(m_prev, m_cur)
        p = jnp.exp(s - m_new[:, None])
        alpha = jnp.exp(m_prev - m_new)
        l_new = l_prev * alpha + jnp.sum(p, axis=1)
        acc = acc * alpha[:, None] + jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32)
        return acc, m_new, l_new

    acc0 = jnp.zeros((blk, q_ref.shape[-1]), jnp.float32)
    m0 = jnp.full((blk,), NEG_INF, jnp.float32)
    l0 = jnp.zeros((blk,), jnp.float32)
    acc, m, l = jax.lax.fori_loop(0, cnt, body, (acc0, m0, l0))
    # A query row whose every active block is fully masked (a custom layout
    # with only above-diagonal blocks) leaves m at NEG_INF, where p=exp(0)=1
    # would average V instead of producing 0 — match the dense path: zero the
    # output and poison lse to +inf so backward contributions vanish too.
    valid = m > NEG_INF * 0.5
    l = jnp.maximum(l, 1e-30)
    o_ref[0, 0] = jnp.where(valid[:, None], acc / l[:, None], 0.0).astype(o_ref.dtype)
    lse = jnp.where(valid, m + jnp.log(l), -NEG_INF)
    lse_ref[0, 0] = jax.lax.broadcast_in_dim(lse, (l.shape[0], NUM_LANES), (0,))


def _bwd_dq_kernel(kidx_ref, kcnt_ref, q_ref, k_ref, v_ref, do_ref, lse_ref,
                   delta_ref, dq_ref, *, sm_scale, causal, blk):
    h = pl.program_id(1)
    qi = pl.program_id(2)
    q = q_ref[0, 0]
    do = do_ref[0, 0]
    # load full lanes, slice the value (width-1 lane ref slices are fragile
    # in Mosaic; the value slice is free — lanes hold broadcast copies)
    lse = lse_ref[0, 0][:, 0:1]  # [blk, 1]
    delta = delta_ref[0, 0][:, 0:1]
    cnt = kcnt_ref[h, qi]

    def body(j, dq):
        kj = kidx_ref[h, qi, j]
        k = k_ref[0, 0, pl.ds(kj * blk, blk), :]
        v = v_ref[0, 0, pl.ds(kj * blk, blk), :]
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32) * sm_scale
        s = _block_mask(s, qi * blk, kj * blk, causal)
        p = jnp.exp(s - lse)
        dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32)
        ds = p * (dp - delta)
        return dq + jax.lax.dot_general(ds.astype(k.dtype), k, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32)

    dq = jax.lax.fori_loop(0, cnt, body, jnp.zeros((blk, q_ref.shape[-1]), jnp.float32))
    dq_ref[0, 0] = (dq * sm_scale).astype(dq_ref.dtype)


def _bwd_dkv_kernel(qidx_ref, qcnt_ref, q_ref, k_ref, v_ref, do_ref, lse_ref,
                    delta_ref, dk_ref, dv_ref, *, sm_scale, causal, blk):
    h = pl.program_id(1)
    ki = pl.program_id(2)
    k = k_ref[0, 0]
    v = v_ref[0, 0]
    cnt = qcnt_ref[h, ki]

    def body(i, carry):
        dk, dv = carry
        qi = qidx_ref[h, ki, i]
        q = q_ref[0, 0, pl.ds(qi * blk, blk), :]
        do = do_ref[0, 0, pl.ds(qi * blk, blk), :]
        # dynamic sublane slice at full lanes, then slice the value — the
        # combined dynamic-sublane + width-1-lane ref slice is a Mosaic
        # hazard (same fix as flash_attention._bwd_dkv_kernel)
        lse = lse_ref[0, 0, pl.ds(qi * blk, blk), :][:, 0:1]  # [blk, 1]
        delta = delta_ref[0, 0, pl.ds(qi * blk, blk), :][:, 0:1]
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32) * sm_scale
        s = _block_mask(s, qi * blk, ki * blk, causal)
        p = jnp.exp(s - lse)
        dv = dv + jax.lax.dot_general(p.astype(do.dtype), do, (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32)
        dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32)
        ds = p * (dp - delta)
        dk = dk + jax.lax.dot_general(ds.astype(q.dtype), q, (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32)
        return dk, dv

    D = k_ref.shape[-1]
    dk, dv = jax.lax.fori_loop(
        0, cnt, body, (jnp.zeros((blk, D), jnp.float32), jnp.zeros((blk, D), jnp.float32))
    )
    dk_ref[0, 0] = (dk * sm_scale).astype(dk_ref.dtype)
    dv_ref[0, 0] = dv.astype(dv_ref.dtype)


def _grid_spec(num_prefetch, grid, in_specs, out_specs):
    return pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=num_prefetch, grid=grid, in_specs=in_specs, out_specs=out_specs
    )


def _fwd(q4, k4, v4, kidx, kcnt, sm_scale, causal, blk, interpret):
    """q4: [B, H, S, D]; kidx [H, nQ, maxK] (scalar-prefetched); → (o, lse)."""
    B, H, S, D = q4.shape
    grid = (B, H, S // blk)
    o, lse = pl.pallas_call(
        functools.partial(_fwd_kernel, sm_scale=sm_scale, causal=causal, blk=blk),
        grid_spec=_grid_spec(
            2, grid,
            [
                pl.BlockSpec((1, 1, blk, D), lambda b, h, i, *_: (b, h, i, 0)),
                pl.BlockSpec((1, 1, S, D), lambda b, h, i, *_: (b, h, 0, 0)),
                pl.BlockSpec((1, 1, S, D), lambda b, h, i, *_: (b, h, 0, 0)),
            ],
            [
                pl.BlockSpec((1, 1, blk, D), lambda b, h, i, *_: (b, h, i, 0)),
                pl.BlockSpec((1, 1, blk, NUM_LANES), lambda b, h, i, *_: (b, h, i, 0)),
            ],
        ),
        interpret=interpret,
        out_shape=[
            jax.ShapeDtypeStruct((B, H, S, D), q4.dtype),
            jax.ShapeDtypeStruct((B, H, S, NUM_LANES), jnp.float32),
        ],
    )(kidx, kcnt, q4, k4, v4)
    return o, lse


def _bwd(q4, k4, v4, o4, lse, do4, kidx, kcnt, qidx, qcnt, sm_scale, causal, blk, interpret):
    B, H, S, D = q4.shape
    delta = jnp.sum(do4.astype(jnp.float32) * o4.astype(jnp.float32), axis=-1)  # [B,H,S]
    delta = jnp.broadcast_to(delta[..., None], (B, H, S, NUM_LANES))
    blk_q = lambda b, h, i, *_: (b, h, i, 0)
    blk_lanes = lambda b, h, i, *_: (b, h, i, 0)
    full = lambda b, h, i, *_: (b, h, 0, 0)
    full_lanes = lambda b, h, i, *_: (b, h, 0, 0)

    dq = pl.pallas_call(
        functools.partial(_bwd_dq_kernel, sm_scale=sm_scale, causal=causal, blk=blk),
        grid_spec=_grid_spec(
            2, (B, H, S // blk),
            [
                pl.BlockSpec((1, 1, blk, D), blk_q),
                pl.BlockSpec((1, 1, S, D), full),
                pl.BlockSpec((1, 1, S, D), full),
                pl.BlockSpec((1, 1, blk, D), blk_q),
                pl.BlockSpec((1, 1, blk, NUM_LANES), blk_lanes),
                pl.BlockSpec((1, 1, blk, NUM_LANES), blk_lanes),
            ],
            pl.BlockSpec((1, 1, blk, D), blk_q),
        ),
        interpret=interpret,
        out_shape=jax.ShapeDtypeStruct((B, H, S, D), q4.dtype),
    )(kidx, kcnt, q4, k4, v4, do4, lse, delta)

    dk, dv = pl.pallas_call(
        functools.partial(_bwd_dkv_kernel, sm_scale=sm_scale, causal=causal, blk=blk),
        grid_spec=_grid_spec(
            2, (B, H, S // blk),
            [
                pl.BlockSpec((1, 1, S, D), full),
                pl.BlockSpec((1, 1, blk, D), blk_q),
                pl.BlockSpec((1, 1, blk, D), blk_q),
                pl.BlockSpec((1, 1, S, D), full),
                pl.BlockSpec((1, 1, S, NUM_LANES), full_lanes),
                pl.BlockSpec((1, 1, S, NUM_LANES), full_lanes),
            ],
            [
                pl.BlockSpec((1, 1, blk, D), blk_q),
                pl.BlockSpec((1, 1, blk, D), blk_q),
            ],
        ),
        interpret=interpret,
        out_shape=[
            jax.ShapeDtypeStruct((B, H, S, D), q4.dtype),
            jax.ShapeDtypeStruct((B, H, S, D), q4.dtype),
        ],
    )(qidx, qcnt, q4, k4, v4, do4, lse, delta)
    return dq, dk, dv


@functools.partial(jax.custom_vjp, nondiff_argnums=(7, 8, 9, 10))
def _sparse(q4, k4, v4, kidx, kcnt, qidx, qcnt, sm_scale, causal, blk, interpret):
    o, _ = _fwd(q4, k4, v4, kidx, kcnt, sm_scale, causal, blk, interpret)
    return o


def _sparse_fwd_rule(q4, k4, v4, kidx, kcnt, qidx, qcnt, sm_scale, causal, blk, interpret):
    o, lse = _fwd(q4, k4, v4, kidx, kcnt, sm_scale, causal, blk, interpret)
    return o, (q4, k4, v4, o, lse, kidx, kcnt, qidx, qcnt)


def _sparse_bwd_rule(sm_scale, causal, blk, interpret, res, do4):
    q4, k4, v4, o4, lse, kidx, kcnt, qidx, qcnt = res
    dq, dk, dv = _bwd(q4, k4, v4, o4, lse, do4, kidx, kcnt, qidx, qcnt,
                      sm_scale, causal, blk, interpret)
    return dq, dk, dv, None, None, None, None


_sparse.defvjp(_sparse_fwd_rule, _sparse_bwd_rule)


def block_sparse_attention(
    q, k, v,
    layout: np.ndarray,
    block: int,
    causal: bool = False,
    sm_scale: Optional[float] = None,
    interpret: bool = False,
):
    """[B,S,H,D] block-sparse attention under a static [H,nQ,nK] layout."""
    B, S, H, D = q.shape
    nQ = S // block
    assert layout.shape == (H, nQ, nQ), (layout.shape, (H, nQ, nQ))
    scale = sm_scale if sm_scale is not None else 1.0 / (D**0.5)
    kidx, kcnt, qidx, qcnt = layout_to_index_lists(np.asarray(layout, bool))

    def to4(x):
        return x.transpose(0, 2, 1, 3)  # [B,H,S,D]

    o4 = _sparse(
        to4(q), to4(k), to4(v),
        jnp.asarray(kidx), jnp.asarray(kcnt), jnp.asarray(qidx), jnp.asarray(qcnt),
        float(scale), bool(causal), int(block), bool(interpret),
    )
    return o4.transpose(0, 2, 1, 3)
