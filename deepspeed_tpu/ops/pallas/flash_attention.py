"""Flash attention as a Pallas TPU kernel (fwd + bwd), causal.

TPU-native replacement for the attention core of the reference's fused
transformer kernels (``csrc/transformer/ds_transformer_cuda.cpp`` — attention
score softmax/dropout fused ops; ``softmax_kernels.cu``): one VMEM-resident
online-softmax kernel instead of materializing the [S,S] score matrix in HBM.

Layout: inputs [B, S, H, D]; internally processed as [B*H, S, D].
Block sizes: BQ=BK=128 (MXU-tile aligned); D may be 64/128/256 (sub-128 head
dims are lane-padded by Mosaic).

Backward follows the standard flash recomputation: forward also emits the
per-row logsumexp; dq and dk/dv are computed by two kernels that recompute
P = exp(S - lse) blockwise, using delta = rowsum(dO * O).
"""

from __future__ import annotations

import functools
import os
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

# Q/K block sizes, MXU-tile aligned. Env-tunable (read once at import) so
# the hardware sweep can A/B larger blocks — at D=64 the per-block dots run
# with a half-width MXU contraction, and bigger blocks amortize more of the
# grid/DMA overhead per dot — without a code change. All kernels require
# S % BQ == 0 and S % BK == 0 (flash_ok / windowed_flash_ok enforce).
def _block_env(name: str, default: int) -> int:
    """Validated block-size override: must be a positive multiple of 128
    (MXU lane width — anything else yields opaque Mosaic lowering errors,
    and odd sizes silently flip flash_ok dispatch for S % B != 0 shapes)."""
    raw = os.environ.get(name)
    if raw is None:
        return default
    try:
        v = int(raw)
    except ValueError:
        v = -1
    if v <= 0 or v % 128:
        import warnings

        warnings.warn(
            f"{name}={raw!r} ignored: flash block sizes must be positive "
            f"multiples of 128 (using {default})"
        )
        return default
    if v != default:
        import warnings

        warnings.warn(
            f"{name}={v}: non-default flash block size changes dispatch "
            f"eligibility (kernels require S % {v} == 0)"
        )
    return v


BQ = _block_env("DS_FLASH_BQ", 128)
BK = _block_env("DS_FLASH_BK", 128)
NUM_LANES = 128  # lse/delta carry a broadcast 128-lane trailing dim (Mosaic
                 # requires >=(8,128)-tileable blocks; same layout as the
                 # official jax TPU flash kernel)
NEG_INF = -1e30


def _causal_mask(s, q_block, k_block, window=None):
    """Mask scores where key position > query position (shared by all
    kernels). ``window`` (traced i32 scalar; 0 = global) additionally masks
    keys older than ``window`` positions: kept iff row - window < col <= row
    (GPT-Neo local attention / Mistral sliding window semantics)."""
    row = q_block * BQ + jax.lax.broadcasted_iota(jnp.int32, (BQ, BK), 0)
    col = k_block * BK + jax.lax.broadcasted_iota(jnp.int32, (BQ, BK), 1)
    keep = row >= col
    if window is not None:
        keep = keep & ((window <= 0) | (col > row - window))
    return jnp.where(keep, s, NEG_INF)


# ---- shared per-block math (one copy for the resident AND grid kernels) ----
#
# Dots take q/k/v/do in their STORAGE dtype with an f32 accumulator: bf16
# inputs then ride the MXU's native bf16 path (4x the f32 matmul rate on
# v4/v5) and the products are still exact in the f32 accumulator, so QK^T
# and dp are bit-identical to an upcast-first formulation. sm_scale is
# applied to the f32 scores AFTER the dot (matches ops.attention's jnp
# reference; exact for any scale, where pre-scaling a bf16 q would round).
# The second GEMM of each pass casts its f32 left operand (p / ds) down to
# the storage dtype — the standard flash-kernel precision contract.

def _online_softmax_step(q, k, v, carry, qi, ki, causal: bool, sm_scale, window=None):
    """One K/V block of the online-softmax forward.
    carry = (acc [BQ,D], m [BQ,1], l [BQ,1]) in f32."""
    acc, m_prev, l_prev = carry
    s = jax.lax.dot_general(
        q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
    ) * sm_scale
    if causal:
        s = _causal_mask(s, qi, ki, window)
    m_new = jnp.maximum(m_prev, jnp.max(s, axis=1, keepdims=True))
    p = jnp.exp(s - m_new)
    alpha = jnp.exp(m_prev - m_new)
    l_new = l_prev * alpha + jnp.sum(p, axis=1, keepdims=True)
    acc = acc * alpha + jax.lax.dot_general(
        p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    return acc, m_new, l_new


def _dq_block(q, k, v, do, lse, delta, qi, ki, causal: bool, sm_scale, window=None):
    """One K/V block's contribution to dq (unscaled: caller multiplies the
    accumulated dq by sm_scale once). lse/delta [BQ,1] f32."""
    s = jax.lax.dot_general(
        q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
    ) * sm_scale
    if causal:
        s = _causal_mask(s, qi, ki, window)
    p = jnp.exp(s - lse)
    dp = jax.lax.dot_general(
        do, v, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
    )
    ds = p * (dp - delta)
    return jax.lax.dot_general(
        ds.astype(k.dtype), k, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )


def _dkv_block(q, k, v, do, lse, delta, qi, ki, causal: bool, sm_scale, window=None):
    """One Q block's contributions to (dk, dv); dk unscaled (caller applies
    sm_scale once at finalize)."""
    s = jax.lax.dot_general(
        q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
    ) * sm_scale
    if causal:
        s = _causal_mask(s, qi, ki, window)
    p = jnp.exp(s - lse)  # [BQ, BK] f32
    dv = jax.lax.dot_general(
        p.astype(do.dtype), do, (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    dp = jax.lax.dot_general(
        do, v, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
    )
    ds = p * (dp - delta)
    dk = jax.lax.dot_general(
        ds.astype(q.dtype), q, (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    return dk, dv


def _joint_bwd_block(q, k, v, do, lse, delta, qi, ki, causal: bool, sm_scale, window=None):
    """One (q,k) block pair's contributions to (dq, dk, dv) from a SINGLE
    recompute of s/p/dp/ds — the fused-backward building block. The split
    dq/dkv kernels each recompute QK^T, exp, dp and ds for every pair; this
    shares them (7 MXU dots -> 5 per pair, softmax VPU work halved).
    dq/dk returned unscaled (caller applies sm_scale once)."""
    s = jax.lax.dot_general(
        q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
    ) * sm_scale
    if causal:
        s = _causal_mask(s, qi, ki, window)
    p = jnp.exp(s - lse)  # [BQ, BK] f32
    dv = jax.lax.dot_general(
        p.astype(do.dtype), do, (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    dp = jax.lax.dot_general(
        do, v, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
    )
    ds = p * (dp - delta)
    ds_c = ds.astype(q.dtype)
    dq = jax.lax.dot_general(
        ds_c, k, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
    )
    dk = jax.lax.dot_general(
        ds_c, q, (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32
    )
    return dq, dk, dv


def _causal_hi(qi, num_k_blocks):
    """Number of k blocks a q block attends into (correct for any BQ/BK)."""
    return jnp.minimum(pl.cdiv((qi + 1) * BQ, BK), num_k_blocks)


def _causal_lo(ki):
    """First q block that can attend to k block ki (correct for any BQ/BK)."""
    return (ki * BK) // BQ


def _window_lo(qi, window):
    """First k block a windowed q block can see (window 0 = global). The
    oldest visible key for q row i is i - window + 1; the block's oldest
    row is qi*BQ."""
    return jnp.where(
        window > 0, jnp.maximum(0, (qi * BQ - window + 1) // BK), 0
    )


def _window_hi_q(ki, num_q_blocks, window):
    """One-past-last q block that can see k block ki under a window: the
    newest key of the block (ki*BK + BK - 1) is visible to q rows up to
    key + window - 1."""
    return jnp.where(
        window > 0,
        jnp.minimum(num_q_blocks, (ki * BK + BK + window - 2) // BQ + 1),
        num_q_blocks,
    )


# This kernel keeps the full per-(batch,head) K/V (fwd, dq) or Q/dO (dkv) block
# resident in VMEM (~16 MB/core). Budget for the largest such array; beyond it
# callers must shard the sequence (ring attention over the sp axis).
VMEM_RESIDENT_BYTES = 4 * 1024 * 1024


# ---------------------------------------------------------------------------
# forward
# ---------------------------------------------------------------------------

def _fwd_kernel(win_ref, q_ref, k_ref, v_ref, o_ref, lse_ref, *, sm_scale: float, causal: bool, seq_len: int):
    qi = pl.program_id(1)
    win = win_ref[0]  # i32 scalar; 0 = global (pure causal)
    q = q_ref[0]  # [BQ, D], storage dtype (bf16 dots ride the native MXU path)

    num_k_blocks = pl.cdiv(seq_len, BK)
    hi = _causal_hi(qi, num_k_blocks) if causal else num_k_blocks
    lo = _window_lo(qi, win) if causal else 0

    def body(j, carry):
        k = k_ref[0, pl.ds(j * BK, BK), :]  # [BK, D]
        v = v_ref[0, pl.ds(j * BK, BK), :]
        return _online_softmax_step(q, k, v, carry, qi, j, causal, sm_scale, win)

    acc0 = jnp.zeros((BQ, q_ref.shape[-1]), jnp.float32)
    m0 = jnp.full((BQ, 1), NEG_INF, jnp.float32)
    l0 = jnp.zeros((BQ, 1), jnp.float32)
    acc, m, l = jax.lax.fori_loop(lo, hi, body, (acc0, m0, l0))
    l = jnp.maximum(l, 1e-30)
    o_ref[0] = (acc / l).astype(o_ref.dtype)
    lse_ref[0] = jax.lax.broadcast_in_dim((m + jnp.log(l))[:, 0], (BQ, NUM_LANES), (0,))


def _win_arr(window) -> jnp.ndarray:
    """Scalar-prefetch operand for the resident kernels (i32[1]; 0=global)."""
    return jnp.asarray(0 if window is None else window, jnp.int32).reshape(1)


def _fwd_call(q, k, v, window, *, S, D, grid, head_idx, kv_idx, lse_idx,
              o_shape, lse_shape, sm_scale, causal, interpret):
    """ONE pallas_call site for the resident forward, shared by the 3D
    ([BH,S,D]) and S-major ([B,S,E]) layouts — they differ only in index
    maps and output shapes; the kernel body is identical."""
    kernel = functools.partial(_fwd_kernel, sm_scale=sm_scale, causal=causal, seq_len=S)
    return pl.pallas_call(
        kernel,
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=grid,
            in_specs=[
                pl.BlockSpec((1, BQ, D), head_idx),
                pl.BlockSpec((1, S, D), kv_idx),
                pl.BlockSpec((1, S, D), kv_idx),
            ],
            out_specs=[
                pl.BlockSpec((1, BQ, D), head_idx),
                pl.BlockSpec((1, BQ, NUM_LANES), lse_idx),
            ],
        ),
        interpret=interpret,
        out_shape=[o_shape, lse_shape],
    )(_win_arr(window), q, k, v)


def _fwd(q3, k3, v3, sm_scale: float, causal: bool, interpret: bool = False, kv_rep: int = 1, window=None):
    """q3: [BH, S, D], k3/v3: [BH // kv_rep, S, D] → (o [BH,S,D], lse).

    ``kv_rep`` > 1 is grouped-query attention: the flattened batch dim packs
    q heads group-major (bh = (b*KV + g)*rep + r), so the K/V index maps
    simply divide by rep — every q head in a group reads the SAME K/V block
    and the repeated cache is never materialized.

    ``window`` (i32 scalar, traced OK; None/0 = global): sliding-window
    causal attention — key j visible to query i iff i-window < j <= i. Rides
    a scalar-prefetch operand so one compiled kernel serves every per-layer
    window (GPT-Neo alternating local/global layers under one lax.scan)."""
    BH, S, D = q3.shape
    return _fwd_call(
        q3, k3, v3, window, S=S, D=D, grid=(BH, S // BQ),
        head_idx=lambda b, i, w: (b, i, 0),
        kv_idx=lambda b, i, w: (b // kv_rep, 0, 0),
        lse_idx=lambda b, i, w: (b, i, 0),
        o_shape=jax.ShapeDtypeStruct((BH, S, D), q3.dtype),
        lse_shape=jax.ShapeDtypeStruct((BH, S, NUM_LANES), jnp.float32),
        sm_scale=sm_scale, causal=causal, interpret=interpret,
    )


# ---------------------------------------------------------------------------
# backward
# ---------------------------------------------------------------------------

def _bwd_dq_kernel(win_ref, q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref, *, sm_scale, causal, seq_len):
    qi = pl.program_id(1)
    win = win_ref[0]
    q = q_ref[0]
    do = do_ref[0]
    # load full lanes, slice the VALUE: a width-1 lane slice in the ref
    # indexer is a Mosaic hazard; the value slice is free (lanes broadcast)
    lse = lse_ref[0][:, 0:1]  # [BQ, 1]
    delta = delta_ref[0][:, 0:1]

    num_k_blocks = pl.cdiv(seq_len, BK)
    hi = _causal_hi(qi, num_k_blocks) if causal else num_k_blocks
    lo = _window_lo(qi, win) if causal else 0

    def body(j, dq):
        k = k_ref[0, pl.ds(j * BK, BK), :]
        v = v_ref[0, pl.ds(j * BK, BK), :]
        return dq + _dq_block(q, k, v, do, lse, delta, qi, j, causal, sm_scale, win)

    dq = jax.lax.fori_loop(lo, hi, body, jnp.zeros((BQ, q_ref.shape[-1]), jnp.float32))
    dq_ref[0] = (dq * sm_scale).astype(dq_ref.dtype)


def _bwd_dkv_kernel(win_ref, q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dk_ref, dv_ref, *, sm_scale, causal, seq_len):
    ki = pl.program_id(1)
    win = win_ref[0]
    k = k_ref[0]  # [BK, D]
    v = v_ref[0]

    num_q_blocks = pl.cdiv(seq_len, BQ)
    lo = _causal_lo(ki) if causal else 0
    hi = _window_hi_q(ki, num_q_blocks, win) if causal else num_q_blocks

    def body(i, carry):
        dk, dv = carry
        q = q_ref[0, pl.ds(i * BQ, BQ), :]
        do = do_ref[0, pl.ds(i * BQ, BQ), :]
        # dynamic sublane slice at full lanes, then slice the value (the
        # combined dynamic-sublane + width-1-lane ref slice is a Mosaic hazard)
        lse = lse_ref[0, pl.ds(i * BQ, BQ), :][:, 0:1]  # [BQ, 1]
        delta = delta_ref[0, pl.ds(i * BQ, BQ), :][:, 0:1]
        dkc, dvc = _dkv_block(q, k, v, do, lse, delta, i, ki, causal, sm_scale, win)
        return dk + dkc, dv + dvc

    D = k_ref.shape[-1]
    dk0 = jnp.zeros((BK, D), jnp.float32)
    dv0 = jnp.zeros((BK, D), jnp.float32)
    dk, dv = jax.lax.fori_loop(lo, hi, body, (dk0, dv0))
    dk_ref[0] = (dk * sm_scale).astype(dk_ref.dtype)
    dv_ref[0] = dv.astype(dv_ref.dtype)


# Fused-backward VMEM budget per element of [S,D]: K + V (bf16, resident,
# 2+2 B) + whole-sequence dk/dv f32 scratch (4+4 B) + the revisited dk/dv
# output blocks (2+2 B bf16 MHA; 4+4 B f32 when GQA stages per-q-head
# grads) = 16 B (20 B GQA). 8 MB keeps the kernel comfortably inside VMEM
# next to the per-block operands; larger resident shapes fall back to the
# split dq/dkv kernels.
FUSED_BWD_BYTES = 8 * 1024 * 1024
_FUSED_BWD_ENABLED = os.environ.get("DS_FLASH_FUSED_BWD", "1") != "0"


def _fused_bwd_ok(S: int, D: int, kv_rep: int = 1) -> bool:
    per_elem = 20 if kv_rep > 1 else 16
    return _FUSED_BWD_ENABLED and S * D * per_elem <= FUSED_BWD_BYTES


def _bwd_fused_kernel(win_ref, q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                      dq_ref, dk_ref, dv_ref, dk_acc, dv_acc,
                      *, sm_scale, causal, seq_len, num_q_blocks):
    """dq + dk + dv in ONE pass over the (q,k) block pairs (resident shapes):
    dk/dv accumulate in whole-sequence VMEM f32 scratch across the
    sequential q-block grid dimension and are written once at the last q
    step. Each pair's s/p/dp/ds are computed once (_joint_bwd_block) instead
    of once per split kernel."""
    qi = pl.program_id(1)
    win = win_ref[0]

    @pl.when(qi == 0)
    def _init():
        dk_acc[...] = jnp.zeros_like(dk_acc)
        dv_acc[...] = jnp.zeros_like(dv_acc)

    q = q_ref[0]
    do = do_ref[0]
    # load full lanes, slice the VALUE (width-1 lane ref slices are a
    # Mosaic hazard — same pattern as the split kernels)
    lse = lse_ref[0][:, 0:1]
    delta = delta_ref[0][:, 0:1]
    num_k_blocks = pl.cdiv(seq_len, BK)
    hi = _causal_hi(qi, num_k_blocks) if causal else num_k_blocks
    lo = _window_lo(qi, win) if causal else 0

    def body(j, dq):
        k = k_ref[0, pl.ds(j * BK, BK), :]
        v = v_ref[0, pl.ds(j * BK, BK), :]
        dqc, dkc, dvc = _joint_bwd_block(
            q, k, v, do, lse, delta, qi, j, causal, sm_scale, win
        )
        dk_acc[pl.ds(j * BK, BK), :] = dk_acc[pl.ds(j * BK, BK), :] + dkc
        dv_acc[pl.ds(j * BK, BK), :] = dv_acc[pl.ds(j * BK, BK), :] + dvc
        return dq + dqc

    dq = jax.lax.fori_loop(lo, hi, body, jnp.zeros((BQ, q_ref.shape[-1]), jnp.float32))
    dq_ref[0] = (dq * sm_scale).astype(dq_ref.dtype)

    @pl.when(qi == num_q_blocks - 1)
    def _finalize():
        dk_ref[0] = (dk_acc[...] * sm_scale).astype(dk_ref.dtype)
        dv_ref[0] = dv_acc[...].astype(dv_ref.dtype)


def _bwd_fused_call(q, k, v, do, lse, delta, win, *, S, D, grid, head_idx,
                    kv_idx, dkv_idx, lse_idx, dq_shape, dkv_shape,
                    sm_scale, causal, interpret):
    """ONE pallas_call site for the fused backward, shared by the 3D and
    S-major layouts (index maps + output shapes differ, body is shared)."""
    nq = grid[1]
    return pl.pallas_call(
        functools.partial(
            _bwd_fused_kernel, sm_scale=sm_scale, causal=causal,
            seq_len=S, num_q_blocks=nq,
        ),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=grid,
            in_specs=[
                pl.BlockSpec((1, BQ, D), head_idx),
                pl.BlockSpec((1, S, D), kv_idx),
                pl.BlockSpec((1, S, D), kv_idx),
                pl.BlockSpec((1, BQ, D), head_idx),
                pl.BlockSpec((1, BQ, NUM_LANES), lse_idx),
                pl.BlockSpec((1, BQ, NUM_LANES), lse_idx),
            ],
            out_specs=[
                pl.BlockSpec((1, BQ, D), head_idx),
                pl.BlockSpec((1, S, D), dkv_idx),
                pl.BlockSpec((1, S, D), dkv_idx),
            ],
            scratch_shapes=[
                pltpu.VMEM((S, D), jnp.float32),
                pltpu.VMEM((S, D), jnp.float32),
            ],
        ),
        interpret=interpret,
        out_shape=[dq_shape, dkv_shape, dkv_shape],
    )(win, q, k, v, do, lse, delta)


def _bwd_fused(q3, k3, v3, delta, lse, do3, sm_scale, causal, interpret, kv_rep, win):
    BH, S, D = q3.shape
    return _bwd_fused_call(
        q3, k3, v3, do3, lse, delta, win, S=S, D=D, grid=(BH, S // BQ),
        head_idx=lambda b, i, w: (b, i, 0),
        kv_idx=lambda b, i, w: (b // kv_rep, 0, 0),
        # dk/dv staged PER Q HEAD (b, not b//kv_rep): under GQA the group is
        # summed outside in f32 so the storage rounding happens exactly once
        dkv_idx=lambda b, i, w: (b, 0, 0),
        lse_idx=lambda b, i, w: (b, i, 0),
        dq_shape=jax.ShapeDtypeStruct((BH, S, D), q3.dtype),
        dkv_shape=jax.ShapeDtypeStruct(
            (BH, S, D), jnp.float32 if kv_rep > 1 else q3.dtype
        ),
        sm_scale=sm_scale, causal=causal, interpret=interpret,
    )


def _bwd(q3, k3, v3, o3, lse, do3, sm_scale: float, causal: bool, interpret: bool = False, kv_rep: int = 1, window=None):
    """Grads for _fwd. With ``kv_rep`` > 1 (GQA) the dk/dv kernels run at
    per-q-head resolution ([BH,S,D], each reading its group's K/V block via
    the divided index map); the caller sums the rep axis to get the true
    [BH//rep, S, D] K/V grads (gradient of a shared tensor accumulates over
    the q heads sharing it).

    Deliberate tradeoff: the per-q-head f32 staging transiently costs
    rep x 4 bytes over the final dk/dv footprint. It buys exactly-once
    rounding AND keeps the (batch*head) grid dimension parallel —
    accumulating the group inside the kernel would force sequential
    output-block revisiting over that dimension. dk/dv are layer-local
    transients, so the peak coexists with one layer's backward only;
    revisit if profiles show it matters at rep >= 8."""
    BH, S, D = q3.shape
    delta = jnp.sum(do3.astype(jnp.float32) * o3.astype(jnp.float32), axis=-1)  # [BH,S]
    delta = jnp.broadcast_to(delta[..., None], (BH, S, NUM_LANES))

    full = lambda b, i, w: (b, 0, 0)
    kv_full = lambda b, i, w: (b // kv_rep, 0, 0)
    win = _win_arr(window)
    if _fused_bwd_ok(S, D, kv_rep):
        dq, dk, dv = _bwd_fused(
            q3, k3, v3, delta, lse, do3, sm_scale, causal, interpret, kv_rep, win
        )
        if kv_rep > 1:
            dk = dk.reshape(BH // kv_rep, kv_rep, S, D).sum(axis=1).astype(k3.dtype)
            dv = dv.reshape(BH // kv_rep, kv_rep, S, D).sum(axis=1).astype(v3.dtype)
        return dq, dk, dv
    dq = pl.pallas_call(
        functools.partial(_bwd_dq_kernel, sm_scale=sm_scale, causal=causal, seq_len=S),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=(BH, S // BQ),
            in_specs=[
                pl.BlockSpec((1, BQ, D), lambda b, i, w: (b, i, 0)),
                pl.BlockSpec((1, S, D), kv_full),
                pl.BlockSpec((1, S, D), kv_full),
                pl.BlockSpec((1, BQ, D), lambda b, i, w: (b, i, 0)),
                pl.BlockSpec((1, BQ, NUM_LANES), lambda b, i, w: (b, i, 0)),
                pl.BlockSpec((1, BQ, NUM_LANES), lambda b, i, w: (b, i, 0)),
            ],
            out_specs=pl.BlockSpec((1, BQ, D), lambda b, i, w: (b, i, 0)),
        ),
        interpret=interpret,
        out_shape=jax.ShapeDtypeStruct((BH, S, D), q3.dtype),
    )(win, q3, k3, v3, do3, lse, delta)

    dk, dv = pl.pallas_call(
        functools.partial(_bwd_dkv_kernel, sm_scale=sm_scale, causal=causal, seq_len=S),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=(BH, S // BK),
            in_specs=[
                pl.BlockSpec((1, S, D), full),
                pl.BlockSpec((1, BK, D), lambda b, i, w: (b // kv_rep, i, 0)),
                pl.BlockSpec((1, BK, D), lambda b, i, w: (b // kv_rep, i, 0)),
                pl.BlockSpec((1, S, D), full),
                pl.BlockSpec((1, S, NUM_LANES), full),
                pl.BlockSpec((1, S, NUM_LANES), full),
            ],
            out_specs=[
                pl.BlockSpec((1, BK, D), lambda b, i, w: (b, i, 0)),
                pl.BlockSpec((1, BK, D), lambda b, i, w: (b, i, 0)),
            ],
        ),
        interpret=interpret,
        out_shape=[
            # GQA: per-q-head grads stay f32 so the rep-axis sum below
            # rounds to the storage dtype exactly once (like the MHA path)
            jax.ShapeDtypeStruct((BH, S, D), jnp.float32 if kv_rep > 1 else q3.dtype),
            jax.ShapeDtypeStruct((BH, S, D), jnp.float32 if kv_rep > 1 else q3.dtype),
        ],
    )(win, q3, k3, v3, do3, lse, delta)
    if kv_rep > 1:
        dk = dk.reshape(BH // kv_rep, kv_rep, S, D).sum(axis=1).astype(k3.dtype)
        dv = dv.reshape(BH // kv_rep, kv_rep, S, D).sum(axis=1).astype(v3.dtype)
    return dq, dk, dv


# ---------------------------------------------------------------------------
# KV-blocked (grid) variant: K/V stream block-by-block through the grid's
# innermost dimension with the online-softmax state carried in VMEM scratch,
# so nothing sequence-length-sized is ever VMEM-resident. Removes the
# whole-K/V budget bound of the kernels above: single-device sequence length
# is then limited by HBM (q/k/v/o + the [BH,S,128] lse), not VMEM. Same
# math, same outputs, same custom-VJP structure.
# ---------------------------------------------------------------------------

# HBM-level ceiling for the grid variant: the broadcast-lane lse residual is
# [B*H, S, 128] f32 (plus a same-sized delta in backward), so the bookkeeping
# itself gets large past ~256k tokens per device.
GRID_KERNEL_MAX_SEQ = 128 * 2048

# jax version compat: the params class was renamed TPUCompilerParams ->
# CompilerParams; older jaxlib pins only carry the old name
_GRID_PARAMS = getattr(pltpu, "CompilerParams", getattr(pltpu, "TPUCompilerParams", None))(
    dimension_semantics=("parallel", "parallel", "arbitrary")
)


def _causal_block_live(qi, ki):
    """True when k block ki intersects the causal triangle of q block qi."""
    return ki * BK <= qi * BQ + (BQ - 1)


def _kv_index_causal(b, i, j):
    """K/V index map for causal fwd/dq grids: dead steps (past the triangle)
    clamp to the last live block, so their iteration revisits the resident
    block instead of DMAing K/V it will never use."""
    return (b, jnp.minimum(j, (i * BQ + BQ - 1) // BK), 0)


def _q_index_causal(b, j, i):
    """Q-side index map for the causal dkv grid: steps before the first live
    q block clamp up to it (same DMA-elision trick, from below)."""
    return (b, jnp.maximum(i, (j * BK) // BQ), 0)


def _fwd_grid_kernel(
    q_ref, k_ref, v_ref, o_ref, lse_ref, acc_ref, m_ref, l_ref,
    *, sm_scale: float, causal: bool, num_k_blocks: int,
):
    qi = pl.program_id(1)
    ki = pl.program_id(2)

    @pl.when(ki == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)
        m_ref[...] = jnp.full_like(m_ref, NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)

    def compute():
        q = q_ref[0]
        k = k_ref[0]
        v = v_ref[0]
        carry = (acc_ref[...], m_ref[:, 0:1], l_ref[:, 0:1])
        acc, m_new, l_new = _online_softmax_step(q, k, v, carry, qi, ki, causal, sm_scale)
        acc_ref[...] = acc
        m_ref[...] = jax.lax.broadcast_in_dim(m_new[:, 0], m_ref.shape, (0,))
        l_ref[...] = jax.lax.broadcast_in_dim(l_new[:, 0], l_ref.shape, (0,))

    if causal:
        @pl.when(_causal_block_live(qi, ki))
        def _():
            compute()
    else:
        compute()

    @pl.when(ki == num_k_blocks - 1)
    def _finalize():
        l = jnp.maximum(l_ref[:, 0:1], 1e-30)
        o_ref[0] = (acc_ref[...] / l).astype(o_ref.dtype)
        lse_ref[0] = m_ref[...] + jnp.log(jnp.maximum(l_ref[...], 1e-30))


def _fwd_grid(q3, k3, v3, sm_scale: float, causal: bool, interpret: bool = False, kv_rep: int = 1):
    BH, S, D = q3.shape
    nq, nk = S // BQ, S // BK
    kernel = functools.partial(
        _fwd_grid_kernel, sm_scale=sm_scale, causal=causal, num_k_blocks=nk
    )
    if causal:
        kv_idx = lambda b, i, j: _kv_index_causal(b // kv_rep, i, j)
    else:
        kv_idx = lambda b, i, j: (b // kv_rep, j, 0)
    o, lse = pl.pallas_call(
        kernel,
        grid=(BH, nq, nk),
        interpret=interpret,
        in_specs=[
            pl.BlockSpec((1, BQ, D), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, BK, D), kv_idx),
            pl.BlockSpec((1, BK, D), kv_idx),
        ],
        out_specs=[
            pl.BlockSpec((1, BQ, D), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, BQ, NUM_LANES), lambda b, i, j: (b, i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((BH, S, D), q3.dtype),
            jax.ShapeDtypeStruct((BH, S, NUM_LANES), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((BQ, D), jnp.float32),
            pltpu.VMEM((BQ, NUM_LANES), jnp.float32),
            pltpu.VMEM((BQ, NUM_LANES), jnp.float32),
        ],
        compiler_params=_GRID_PARAMS,
    )(q3, k3, v3)
    return o, lse


def _bwd_dq_grid_kernel(
    q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref, dq_acc,
    *, sm_scale: float, causal: bool, num_k_blocks: int,
):
    qi = pl.program_id(1)
    ki = pl.program_id(2)

    @pl.when(ki == 0)
    def _init():
        dq_acc[...] = jnp.zeros_like(dq_acc)

    def compute():
        q = q_ref[0]
        k = k_ref[0]
        v = v_ref[0]
        do = do_ref[0]
        lse = lse_ref[0, :, 0:1]
        delta = delta_ref[0, :, 0:1]
        dq_acc[...] = dq_acc[...] + _dq_block(q, k, v, do, lse, delta, qi, ki, causal, sm_scale)

    if causal:
        @pl.when(_causal_block_live(qi, ki))
        def _():
            compute()
    else:
        compute()

    @pl.when(ki == num_k_blocks - 1)
    def _finalize():
        dq_ref[0] = (dq_acc[...] * sm_scale).astype(dq_ref.dtype)


def _bwd_dkv_grid_kernel(
    q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dk_ref, dv_ref,
    dk_acc, dv_acc, *, sm_scale: float, causal: bool, num_q_blocks: int,
):
    ki = pl.program_id(1)
    qi = pl.program_id(2)

    @pl.when(qi == 0)
    def _init():
        dk_acc[...] = jnp.zeros_like(dk_acc)
        dv_acc[...] = jnp.zeros_like(dv_acc)

    def compute():
        q = q_ref[0]
        k = k_ref[0]
        v = v_ref[0]
        do = do_ref[0]
        lse = lse_ref[0, :, 0:1]
        delta = delta_ref[0, :, 0:1]
        dkc, dvc = _dkv_block(q, k, v, do, lse, delta, qi, ki, causal, sm_scale)
        dk_acc[...] = dk_acc[...] + dkc
        dv_acc[...] = dv_acc[...] + dvc

    if causal:
        @pl.when(_causal_block_live(qi, ki))
        def _():
            compute()
    else:
        compute()

    @pl.when(qi == num_q_blocks - 1)
    def _finalize():
        dk_ref[0] = (dk_acc[...] * sm_scale).astype(dk_ref.dtype)
        dv_ref[0] = dv_acc[...].astype(dv_ref.dtype)


def _bwd_grid(q3, k3, v3, o3, lse, do3, sm_scale: float, causal: bool, interpret: bool = False, kv_rep: int = 1):
    BH, S, D = q3.shape
    nq, nk = S // BQ, S // BK
    delta = jnp.sum(do3.astype(jnp.float32) * o3.astype(jnp.float32), axis=-1)
    delta = jnp.broadcast_to(delta[..., None], (BH, S, NUM_LANES))

    if causal:
        kv_idx = lambda b, i, j: _kv_index_causal(b // kv_rep, i, j)
    else:
        kv_idx = lambda b, i, j: (b // kv_rep, j, 0)
    dq = pl.pallas_call(
        functools.partial(
            _bwd_dq_grid_kernel, sm_scale=sm_scale, causal=causal, num_k_blocks=nk
        ),
        grid=(BH, nq, nk),
        interpret=interpret,
        in_specs=[
            pl.BlockSpec((1, BQ, D), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, BK, D), kv_idx),
            pl.BlockSpec((1, BK, D), kv_idx),
            pl.BlockSpec((1, BQ, D), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, BQ, NUM_LANES), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, BQ, NUM_LANES), lambda b, i, j: (b, i, 0)),
        ],
        out_specs=pl.BlockSpec((1, BQ, D), lambda b, i, j: (b, i, 0)),
        out_shape=jax.ShapeDtypeStruct((BH, S, D), q3.dtype),
        scratch_shapes=[pltpu.VMEM((BQ, D), jnp.float32)],
        compiler_params=_GRID_PARAMS,
    )(q3, k3, v3, do3, lse, delta)

    q_idx = _q_index_causal if causal else (lambda b, j, i: (b, i, 0))
    dk, dv = pl.pallas_call(
        functools.partial(
            _bwd_dkv_grid_kernel, sm_scale=sm_scale, causal=causal, num_q_blocks=nq
        ),
        grid=(BH, nk, nq),
        interpret=interpret,
        in_specs=[
            pl.BlockSpec((1, BQ, D), q_idx),
            pl.BlockSpec((1, BK, D), lambda b, j, i: (b // kv_rep, j, 0)),
            pl.BlockSpec((1, BK, D), lambda b, j, i: (b // kv_rep, j, 0)),
            pl.BlockSpec((1, BQ, D), q_idx),
            pl.BlockSpec((1, BQ, NUM_LANES), q_idx),
            pl.BlockSpec((1, BQ, NUM_LANES), q_idx),
        ],
        out_specs=[
            pl.BlockSpec((1, BK, D), lambda b, j, i: (b, j, 0)),
            pl.BlockSpec((1, BK, D), lambda b, j, i: (b, j, 0)),
        ],
        out_shape=[
            # GQA: f32 per-q-head grads, one rounding after the rep sum
            jax.ShapeDtypeStruct((BH, S, D), jnp.float32 if kv_rep > 1 else q3.dtype),
            jax.ShapeDtypeStruct((BH, S, D), jnp.float32 if kv_rep > 1 else q3.dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((BK, D), jnp.float32),
            pltpu.VMEM((BK, D), jnp.float32),
        ],
        compiler_params=_GRID_PARAMS,
    )(q3, k3, v3, do3, lse, delta)
    if kv_rep > 1:
        dk = dk.reshape(BH // kv_rep, kv_rep, S, D).sum(axis=1).astype(k3.dtype)
        dv = dv.reshape(BH // kv_rep, kv_rep, S, D).sum(axis=1).astype(v3.dtype)
    return dq, dk, dv


def resident_ok(S: int, D: int, itemsize: int) -> bool:
    """THE resident-vs-grid split: whether one (batch, head)'s K or V slab
    fits the whole-K/V VMEM budget. Shared by the auto dispatchers and any
    telemetry that reports which variant served a shape."""
    return S * D * itemsize <= VMEM_RESIDENT_BYTES


def _fwd_auto(q3, k3, v3, sm_scale: float, causal: bool, interpret: bool = False, kv_rep: int = 1, window=None):
    """Resident kernels inside the whole-K/V VMEM budget, grid variant past
    it — the one dispatch point shared by flash_attention AND the ring(sp)
    per-block compute. Sliding windows ride the resident kernels only
    (callers gate via windowed_flash_ok)."""
    BH, S, D = q3.shape
    if resident_ok(S, D, q3.dtype.itemsize):
        return _fwd(q3, k3, v3, sm_scale, causal, interpret, kv_rep, window)
    if window is not None:
        raise NotImplementedError(
            "windowed attention requires the resident kernels (shape past "
            "the VMEM budget); silently dropping the window would compute "
            "global attention"
        )
    return _fwd_grid(q3, k3, v3, sm_scale, causal, interpret, kv_rep)


def _bwd_auto(q3, k3, v3, o3, lse, do3, sm_scale: float, causal: bool, interpret: bool = False, kv_rep: int = 1, window=None):
    BH, S, D = q3.shape
    if resident_ok(S, D, q3.dtype.itemsize):
        return _bwd(q3, k3, v3, o3, lse, do3, sm_scale, causal, interpret, kv_rep, window)
    if window is not None:
        raise NotImplementedError(
            "windowed attention requires the resident kernels (shape past "
            "the VMEM budget); silently dropping the window would compute "
            "global attention"
        )
    return _bwd_grid(q3, k3, v3, o3, lse, do3, sm_scale, causal, interpret, kv_rep)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def _flash_grid(q3, k3, v3, sm_scale: float, causal: bool, interpret: bool):
    o, _ = _fwd_grid(q3, k3, v3, sm_scale, causal, interpret)
    return o


def _flash_grid_fwd_rule(q3, k3, v3, sm_scale, causal, interpret):
    o, lse = _fwd_grid(q3, k3, v3, sm_scale, causal, interpret)
    return o, (q3, k3, v3, o, lse)


def _flash_grid_bwd_rule(sm_scale, causal, interpret, res, do3):
    q3, k3, v3, o3, lse = res
    dq, dk, dv = _bwd_grid(q3, k3, v3, o3, lse, do3, sm_scale, causal, interpret)
    return dq, dk, dv


_flash_grid.defvjp(_flash_grid_fwd_rule, _flash_grid_bwd_rule)


# ---------------------------------------------------------------------------
# public API with custom VJP
# ---------------------------------------------------------------------------

@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6, 7))
def _flash(q3, k3, v3, window, sm_scale: float, causal: bool, interpret: bool, kv_rep: int = 1):
    """``window``: i32[1] (may be traced; [0] = global). Rides the primal
    argument list because a traced value cannot be a nondiff argnum; its
    cotangent is float0 (integer dtype)."""
    o, _ = _fwd_auto(q3, k3, v3, sm_scale, causal, interpret, kv_rep, window)
    return o


def _flash_fwd_rule(q3, k3, v3, window, sm_scale, causal, interpret, kv_rep=1):
    o, lse = _fwd_auto(q3, k3, v3, sm_scale, causal, interpret, kv_rep, window)
    return o, (q3, k3, v3, o, lse, window)


def _flash_bwd_rule(sm_scale, causal, interpret, kv_rep, res, do3):
    q3, k3, v3, o3, lse, window = res
    dq, dk, dv = _bwd_auto(q3, k3, v3, o3, lse, do3, sm_scale, causal, interpret, kv_rep, window)
    # integer-dtype primal → float0 cotangent (None when no window was passed)
    win_ct = None if window is None else np.zeros((1,), jax.dtypes.float0)
    return dq, dk, dv, win_ct


_flash.defvjp(_flash_fwd_rule, _flash_bwd_rule)


# ---------------------------------------------------------------------------
# S-major ([B, S, H*D]) entry: the kernels read each head's D-lane slice
# straight out of the fused [B,S,E] activations via lane-offset index maps
# ((bh // H, i, bh % H) block coords), so the [B,S,H,D] <-> [B*H,S,D]
# physical transposes around the 3D entry — XLA copies, ~30 ms each at the
# r4 bench shape, 8+ per layer across fwd/recompute/bwd — never exist.
# Kernel BODIES are shared with the 3D path; only the pallas_call block
# maps differ. MHA resident shapes with the fused backward only (GQA dk/dv
# would need cross-grid-step output accumulation over the group).
# ---------------------------------------------------------------------------

# OPT-IN until hardware-proven (DS_FLASH_BSE=1): the D-lane blocks sit at
# h*D lane offsets inside E, and for D=64 those are sub-128-lane origins —
# a Mosaic tiling surface interpret mode cannot validate. The hardware CI
# (TestBSEFlashHardware) compiles it on a chip; flip the default only with
# that evidence.
_BSE_ENABLED = os.environ.get("DS_FLASH_BSE", "0") == "1"


def _bse_ok(S: int, D: int, itemsize: int = 2) -> bool:
    return _BSE_ENABLED and resident_ok(S, D, itemsize) and _fused_bwd_ok(S, D)


def _fwd_bse(q2, k2, v2, H: int, sm_scale, causal, interpret, window):
    B, S, E = q2.shape
    D = E // H
    return _fwd_call(
        q2, k2, v2, window, S=S, D=D, grid=(B * H, S // BQ),
        head_idx=lambda bh, i, w: (bh // H, i, bh % H),
        kv_idx=lambda bh, i, w: (bh // H, 0, bh % H),
        lse_idx=lambda bh, i, w: (bh, i, 0),
        o_shape=jax.ShapeDtypeStruct((B, S, E), q2.dtype),
        lse_shape=jax.ShapeDtypeStruct((B * H, S, NUM_LANES), jnp.float32),
        sm_scale=sm_scale, causal=causal, interpret=interpret,
    )


def _bwd_fused_bse(q2, k2, v2, o2, lse, do2, H: int, sm_scale, causal, interpret, window):
    B, S, E = q2.shape
    D = E // H
    BH = B * H
    d4 = do2.astype(jnp.float32).reshape(B, S, H, D)
    o4 = o2.astype(jnp.float32).reshape(B, S, H, D)
    delta = jnp.sum(d4 * o4, axis=-1).transpose(0, 2, 1).reshape(BH, S)  # [B,S,H] transpose: E-free, cheap
    delta = jnp.broadcast_to(delta[..., None], (BH, S, NUM_LANES))
    return _bwd_fused_call(
        q2, k2, v2, do2, lse, delta, _win_arr(window), S=S, D=D,
        grid=(BH, S // BQ),
        head_idx=lambda bh, i, w: (bh // H, i, bh % H),
        kv_idx=lambda bh, i, w: (bh // H, 0, bh % H),
        dkv_idx=lambda bh, i, w: (bh // H, 0, bh % H),
        lse_idx=lambda bh, i, w: (bh, i, 0),
        dq_shape=jax.ShapeDtypeStruct((B, S, E), q2.dtype),
        dkv_shape=jax.ShapeDtypeStruct((B, S, E), k2.dtype),
        sm_scale=sm_scale, causal=causal, interpret=interpret,
    )


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6, 7))
def _flash_bse(q2, k2, v2, window, H: int, sm_scale: float, causal: bool, interpret: bool):
    o, _ = _fwd_bse(q2, k2, v2, H, sm_scale, causal, interpret, window)
    return o


def _flash_bse_fwd_rule(q2, k2, v2, window, H, sm_scale, causal, interpret):
    o, lse = _fwd_bse(q2, k2, v2, H, sm_scale, causal, interpret, window)
    return o, (q2, k2, v2, o, lse, window)


def _flash_bse_bwd_rule(H, sm_scale, causal, interpret, res, do2):
    q2, k2, v2, o2, lse, window = res
    dq, dk, dv = _bwd_fused_bse(
        q2, k2, v2, o2, lse, do2, H, sm_scale, causal, interpret, window
    )
    win_ct = None if window is None else np.zeros((1,), jax.dtypes.float0)
    return dq, dk, dv, win_ct


_flash_bse.defvjp(_flash_bse_fwd_rule, _flash_bse_bwd_rule)


def validate_kv_heads(H: int, k, v) -> int:
    """THE kv-head rule (one copy; decode + dispatch share it): K/V head
    counts must match and divide the q head count. Returns rep = H // KV."""
    KV = k.shape[-2]
    if v.shape[-2] != KV or H % KV != 0:
        raise ValueError(
            f"kv heads ({KV}/{v.shape[-2]}) must match and divide q heads ({H})"
        )
    return H // KV


def flash_ok(S: int, D: int) -> bool:
    """THE shape predicate for single-device flash dispatch: tiling-legal and
    within the grid kernel's ceiling. One copy, used by the ops dispatchers,
    so they can never disagree with flash_attention's own checks (the ring
    path adds its per-shard VMEM bound on top via ring_flash_ok)."""
    return S % BQ == 0 and S % BK == 0 and D % 64 == 0 and S <= GRID_KERNEL_MAX_SEQ


def windowed_flash_ok(S: int, D: int, itemsize: int = 2) -> bool:
    """Whether a sliding-window sequence can ride the kernels: windows are
    implemented in the resident variant only (the grid variant's static
    index maps cannot elide a traced window's dead blocks)."""
    return flash_ok(S, D) and resident_ok(S, D, itemsize)


def flash_attention(q, k, v, causal: bool = True, sm_scale: Optional[float] = None,
                    interpret: bool = False, window=None):
    """[B,S,H,D] flash attention (causal by default). S must be a multiple of
    128. Sequences within the whole-K/V VMEM budget use the resident kernels
    (fewer grid steps, chip-validated first); longer sequences stream K/V
    block-by-block through the grid variant, whose only length bound is HBM.

    Grouped-query attention: ``k``/``v`` may carry fewer heads than ``q``
    ([B,S,KV,D] with H % KV == 0). The kernels read each group's shared K/V
    block through a divided batch index map — the repeated cache is never
    materialized in HBM or VMEM, and dk/dv accumulate over the group.

    ``window`` (int or traced i32 scalar; None/0 = global): sliding-window
    causal attention — key j visible to query i iff i-window < j <= i
    (Mistral sliding_window / GPT-Neo local-layer semantics). The loop
    bounds skip blocks wholly outside the band, so FLOPs scale with
    S*window, not S^2; requires ``causal`` and the resident kernels
    (gate with windowed_flash_ok)."""
    B, S, H, D = q.shape
    rep = validate_kv_heads(H, k, v)
    if S % BQ != 0 or S % BK != 0:
        raise ValueError(f"seq {S} must be a multiple of {BQ}/{BK}")
    if S > GRID_KERNEL_MAX_SEQ:
        raise ValueError(
            f"seq {S} exceeds the grid kernel's bookkeeping ceiling "
            f"({GRID_KERNEL_MAX_SEQ}): the [B*H, S, 128] f32 lse/delta "
            "residuals dominate HBM past it — shard the sequence (sp axis / "
            "ring attention) instead"
        )
    if window is not None:
        if not causal:
            raise ValueError("window requires causal attention")
        if not resident_ok(S, D, q.dtype.itemsize):
            raise ValueError(
                f"windowed attention needs the resident kernels "
                f"(S*D*itemsize <= {VMEM_RESIDENT_BYTES}); got S={S} D={D}"
            )
    scale = sm_scale if sm_scale is not None else 1.0 / (D**0.5)

    win = None if window is None else _win_arr(window)
    if rep == 1 and _bse_ok(S, D, q.dtype.itemsize):
        # S-major path: head slices read via lane-offset index maps — the
        # reshapes below are free (contiguous), no physical transposes
        E = H * D
        o2 = _flash_bse(
            q.reshape(B, S, E), k.reshape(B, S, E), v.reshape(B, S, E),
            win, H, float(scale), bool(causal), bool(interpret),
        )
        return o2.reshape(B, S, H, D)

    def to3(x):
        nh = x.shape[2]
        return x.transpose(0, 2, 1, 3).reshape(B * nh, S, D)

    # batch-major flattening makes bh = (b*KV + g)*rep + r for q and
    # b*KV + g for k/v, so bh // rep recovers the kv row exactly
    o3 = _flash(to3(q), to3(k), to3(v), win, float(scale),
                bool(causal), bool(interpret), rep)
    return o3.reshape(B, H, S, D).transpose(0, 2, 1, 3)
