"""Flash attention as a Pallas TPU kernel (fwd + bwd), causal.

TPU-native replacement for the attention core of the reference's fused
transformer kernels (``csrc/transformer/ds_transformer_cuda.cpp`` — attention
score softmax/dropout fused ops; ``softmax_kernels.cu``): one VMEM-resident
online-softmax kernel instead of materializing the [S,S] score matrix in HBM.

Layout: inputs [B, S, H, D]; internally processed as [B*H, S, D].
Block sizes: BQ=BK=128 (MXU-tile aligned); D may be 64/128/256 (sub-128 head
dims are lane-padded by Mosaic).

Backward follows the standard flash recomputation: forward also emits the
per-row logsumexp; dq and dk/dv are computed by two kernels that recompute
P = exp(S - lse) blockwise, using delta = rowsum(dO * O).
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

BQ = 128
BK = 128
NUM_LANES = 128  # lse/delta carry a broadcast 128-lane trailing dim (Mosaic
                 # requires >=(8,128)-tileable blocks; same layout as the
                 # official jax TPU flash kernel)
NEG_INF = -1e30


def _causal_mask(s, q_block, k_block):
    """Mask scores where key position > query position (shared by all kernels)."""
    row = q_block * BQ + jax.lax.broadcasted_iota(jnp.int32, (BQ, BK), 0)
    col = k_block * BK + jax.lax.broadcasted_iota(jnp.int32, (BQ, BK), 1)
    return jnp.where(row >= col, s, NEG_INF)


def _causal_hi(qi, num_k_blocks):
    """Number of k blocks a q block attends into (correct for any BQ/BK)."""
    return jnp.minimum(pl.cdiv((qi + 1) * BQ, BK), num_k_blocks)


def _causal_lo(ki):
    """First q block that can attend to k block ki (correct for any BQ/BK)."""
    return (ki * BK) // BQ


# This kernel keeps the full per-(batch,head) K/V (fwd, dq) or Q/dO (dkv) block
# resident in VMEM (~16 MB/core). Budget for the largest such array; beyond it
# callers must shard the sequence (ring attention over the sp axis).
VMEM_RESIDENT_BYTES = 4 * 1024 * 1024


# ---------------------------------------------------------------------------
# forward
# ---------------------------------------------------------------------------

def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, *, sm_scale: float, causal: bool, seq_len: int):
    qi = pl.program_id(1)
    q = q_ref[0].astype(jnp.float32) * sm_scale  # [BQ, D]

    num_k_blocks = pl.cdiv(seq_len, BK)
    hi = _causal_hi(qi, num_k_blocks) if causal else num_k_blocks

    def body(j, carry):
        acc, m_prev, l_prev = carry
        k = k_ref[0, pl.ds(j * BK, BK), :].astype(jnp.float32)  # [BK, D]
        v = v_ref[0, pl.ds(j * BK, BK), :].astype(jnp.float32)
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )  # [BQ, BK]
        if causal:
            s = _causal_mask(s, qi, j)
        m_cur = jnp.max(s, axis=1)
        m_new = jnp.maximum(m_prev, m_cur)
        p = jnp.exp(s - m_new[:, None])
        alpha = jnp.exp(m_prev - m_new)
        l_new = l_prev * alpha + jnp.sum(p, axis=1)
        acc = acc * alpha[:, None] + jax.lax.dot_general(
            p, v, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )
        return acc, m_new, l_new

    acc0 = jnp.zeros((BQ, q_ref.shape[-1]), jnp.float32)
    m0 = jnp.full((BQ,), NEG_INF, jnp.float32)
    l0 = jnp.zeros((BQ,), jnp.float32)
    acc, m, l = jax.lax.fori_loop(0, hi, body, (acc0, m0, l0))
    l = jnp.maximum(l, 1e-30)
    o_ref[0] = (acc / l[:, None]).astype(o_ref.dtype)
    lse_ref[0] = jax.lax.broadcast_in_dim(m + jnp.log(l), (BQ, NUM_LANES), (0,))


def _fwd(q3, k3, v3, sm_scale: float, causal: bool, interpret: bool = False):
    """q3/k3/v3: [BH, S, D] → (o [BH,S,D], lse [BH,S])."""
    BH, S, D = q3.shape
    grid = (BH, S // BQ)
    kernel = functools.partial(_fwd_kernel, sm_scale=sm_scale, causal=causal, seq_len=S)
    o, lse = pl.pallas_call(
        kernel,
        grid=grid,
        interpret=interpret,
        in_specs=[
            pl.BlockSpec((1, BQ, D), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, S, D), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((1, S, D), lambda b, i: (b, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, BQ, D), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, BQ, NUM_LANES), lambda b, i: (b, i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((BH, S, D), q3.dtype),
            jax.ShapeDtypeStruct((BH, S, NUM_LANES), jnp.float32),
        ],
    )(q3, k3, v3)
    return o, lse


# ---------------------------------------------------------------------------
# backward
# ---------------------------------------------------------------------------

def _bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref, *, sm_scale, causal, seq_len):
    qi = pl.program_id(1)
    q = q_ref[0].astype(jnp.float32) * sm_scale
    do = do_ref[0].astype(jnp.float32)
    lse = lse_ref[0, :, 0:1]  # [BQ, 1] (value broadcast across lanes)
    delta = delta_ref[0, :, 0:1]

    num_k_blocks = pl.cdiv(seq_len, BK)
    hi = _causal_hi(qi, num_k_blocks) if causal else num_k_blocks

    def body(j, dq):
        k = k_ref[0, pl.ds(j * BK, BK), :].astype(jnp.float32)
        v = v_ref[0, pl.ds(j * BK, BK), :].astype(jnp.float32)
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32)
        if causal:
            s = _causal_mask(s, qi, j)
        p = jnp.exp(s - lse)
        dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32)
        ds = p * (dp - delta)
        return dq + jax.lax.dot_general(ds, k, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32)

    dq = jax.lax.fori_loop(0, hi, body, jnp.zeros((BQ, q_ref.shape[-1]), jnp.float32))
    dq_ref[0] = (dq * sm_scale).astype(dq_ref.dtype)


def _bwd_dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dk_ref, dv_ref, *, sm_scale, causal, seq_len):
    ki = pl.program_id(1)
    k = k_ref[0].astype(jnp.float32)  # [BK, D]
    v = v_ref[0].astype(jnp.float32)

    num_q_blocks = pl.cdiv(seq_len, BQ)
    lo = _causal_lo(ki) if causal else 0

    def body(i, carry):
        dk, dv = carry
        q = q_ref[0, pl.ds(i * BQ, BQ), :].astype(jnp.float32) * sm_scale
        do = do_ref[0, pl.ds(i * BQ, BQ), :].astype(jnp.float32)
        lse = lse_ref[0, pl.ds(i * BQ, BQ), 0:1]  # [BQ, 1]
        delta = delta_ref[0, pl.ds(i * BQ, BQ), 0:1]
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32)
        if causal:
            s = _causal_mask(s, i, ki)
        p = jnp.exp(s - lse)  # [BQ, BK]
        dv = dv + jax.lax.dot_general(p, do, (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32)
        dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32)
        ds = p * (dp - delta)
        dk = dk + jax.lax.dot_general(ds, q, (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32)
        return dk, dv

    D = k_ref.shape[-1]
    dk0 = jnp.zeros((BK, D), jnp.float32)
    dv0 = jnp.zeros((BK, D), jnp.float32)
    dk, dv = jax.lax.fori_loop(lo, num_q_blocks, body, (dk0, dv0))
    dk_ref[0] = dk.astype(dk_ref.dtype)  # sm_scale already folded into q
    dv_ref[0] = dv.astype(dv_ref.dtype)


def _bwd(q3, k3, v3, o3, lse, do3, sm_scale: float, causal: bool, interpret: bool = False):
    BH, S, D = q3.shape
    delta = jnp.sum(do3.astype(jnp.float32) * o3.astype(jnp.float32), axis=-1)  # [BH,S]
    delta = jnp.broadcast_to(delta[..., None], (BH, S, NUM_LANES))

    full = lambda b, i: (b, 0, 0)
    dq = pl.pallas_call(
        functools.partial(_bwd_dq_kernel, sm_scale=sm_scale, causal=causal, seq_len=S),
        grid=(BH, S // BQ),
        interpret=interpret,
        in_specs=[
            pl.BlockSpec((1, BQ, D), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, S, D), full),
            pl.BlockSpec((1, S, D), full),
            pl.BlockSpec((1, BQ, D), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, BQ, NUM_LANES), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, BQ, NUM_LANES), lambda b, i: (b, i, 0)),
        ],
        out_specs=pl.BlockSpec((1, BQ, D), lambda b, i: (b, i, 0)),
        out_shape=jax.ShapeDtypeStruct((BH, S, D), q3.dtype),
    )(q3, k3, v3, do3, lse, delta)

    dk, dv = pl.pallas_call(
        functools.partial(_bwd_dkv_kernel, sm_scale=sm_scale, causal=causal, seq_len=S),
        grid=(BH, S // BK),
        interpret=interpret,
        in_specs=[
            pl.BlockSpec((1, S, D), full),
            pl.BlockSpec((1, BK, D), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, BK, D), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, S, D), full),
            pl.BlockSpec((1, S, NUM_LANES), full),
            pl.BlockSpec((1, S, NUM_LANES), full),
        ],
        out_specs=[
            pl.BlockSpec((1, BK, D), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, BK, D), lambda b, i: (b, i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((BH, S, D), q3.dtype),
            jax.ShapeDtypeStruct((BH, S, D), q3.dtype),
        ],
    )(q3, k3, v3, do3, lse, delta)
    return dq, dk, dv


# ---------------------------------------------------------------------------
# public API with custom VJP
# ---------------------------------------------------------------------------

@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def _flash(q3, k3, v3, sm_scale: float, causal: bool, interpret: bool):
    o, _ = _fwd(q3, k3, v3, sm_scale, causal, interpret)
    return o


def _flash_fwd_rule(q3, k3, v3, sm_scale, causal, interpret):
    o, lse = _fwd(q3, k3, v3, sm_scale, causal, interpret)
    return o, (q3, k3, v3, o, lse)


def _flash_bwd_rule(sm_scale, causal, interpret, res, do3):
    q3, k3, v3, o3, lse = res
    dq, dk, dv = _bwd(q3, k3, v3, o3, lse, do3, sm_scale, causal, interpret)
    return dq, dk, dv


_flash.defvjp(_flash_fwd_rule, _flash_bwd_rule)


def flash_attention(q, k, v, causal: bool = True, sm_scale: Optional[float] = None, interpret: bool = False):
    """[B,S,H,D] causal flash attention. S must be a multiple of 128."""
    B, S, H, D = q.shape
    if S % BQ != 0 or S % BK != 0:
        raise ValueError(f"seq {S} must be a multiple of {BQ}/{BK}")
    if S * D * q.dtype.itemsize > VMEM_RESIDENT_BYTES:
        raise ValueError(
            f"seq {S} x head_dim {D} exceeds the whole-K/V-in-VMEM budget of this "
            f"kernel ({VMEM_RESIDENT_BYTES} B); shard the sequence (sp axis / ring "
            "attention) or reduce per-device sequence length"
        )
    scale = sm_scale if sm_scale is not None else 1.0 / (D**0.5)

    def to3(x):
        return x.transpose(0, 2, 1, 3).reshape(B * H, S, D)

    o3 = _flash(to3(q), to3(k), to3(v), float(scale), bool(causal), bool(interpret))
    return o3.reshape(B, H, S, D).transpose(0, 2, 1, 3)
