"""Pallas decode-attention kernel: one query step against a KV cache.

Analog of the reference's fused inference attention (``softmax_context`` with
``layer_past``: ``csrc/transformer/inference/csrc/pt_binding.cpp:1323``-region,
``ops/transformer/inference/transformer_inference.py:231``): at decode time
the hot op is q·K^T → masked softmax → ·V over the cache, with the valid
length ``pos`` known only at runtime. The XLA fallback materializes the
[B,H,1,Smax] score tensor in HBM; this kernel streams K/V blocks through
VMEM with an online softmax, writing only the [B,H,D] output.

Grid: one program per (batch, head). ``pos`` arrives as a scalar-prefetch
operand so the same compiled kernel serves every decode step (no recompile
as the cache fills); keys at positions > pos are masked, not skipped —
compute is bounded by Smax, the usual TPU static-shape tradeoff.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

S_BLOCK = 512  # cache rows per online-softmax tile


def _decode_kernel(pos_ref, q_ref, k_ref, v_ref, o_ref, *, sm_scale: float,
                   s_max: int, s_block: int):
    pos = pos_ref[0]
    D = q_ref.shape[-1]
    # dots take the cache's storage dtype with f32 accumulation (bf16
    # products are exact in the accumulator; skips two full-block VPU
    # upcast passes per tile); scores/softmax state stay f32
    q = q_ref[...].reshape(1, D)
    n_blocks = s_max // s_block

    def body(j, carry):
        m_prev, l_prev, acc = carry
        k = k_ref[0, 0, pl.dslice(j * s_block, s_block), :]
        v = v_ref[0, 0, pl.dslice(j * s_block, s_block), :]
        s = jnp.dot(k, q.T, preferred_element_type=jnp.float32) * sm_scale  # [S,1]
        idx = jax.lax.broadcasted_iota(jnp.int32, (s_block, 1), 0) + j * s_block
        s = jnp.where(idx <= pos, s, -1e30)
        m_cur = jnp.maximum(m_prev, jnp.max(s))
        corr = jnp.exp(m_prev - m_cur)
        p = jnp.exp(s - m_cur)  # [S,1] f32
        l_cur = l_prev * corr + jnp.sum(p)
        acc = acc * corr + jnp.dot(
            p.astype(v.dtype).T, v, preferred_element_type=jnp.float32
        )
        return m_cur, l_cur, acc

    init = (
        jnp.float32(-1e30),
        jnp.float32(0.0),
        jnp.zeros((1, D), jnp.float32),
    )
    m, l, acc = jax.lax.fori_loop(0, n_blocks, body, init)
    o_ref[...] = (acc / jnp.maximum(l, 1e-30)).reshape(o_ref.shape).astype(o_ref.dtype)


def decode_attention(
    q: jnp.ndarray,  # [B, H, D] current-step queries
    k_cache: jnp.ndarray,  # [B, Smax, KV, D]; KV == H or H % KV == 0 (GQA)
    v_cache: jnp.ndarray,  # [B, Smax, KV, D]
    pos: jnp.ndarray,  # i32: highest valid cache index (inclusive)
    sm_scale: Optional[float] = None,
    interpret: bool = False,
) -> jnp.ndarray:
    """Single-token cached attention → [B, H, D].

    GQA (KV < H): each q head's program reads its group's cache column via
    a divided head index map — the cache stays at KV heads, never repeated
    (the memory saving that motivates GQA serving)."""
    from .flash_attention import validate_kv_heads

    B, H, D = q.shape
    S = k_cache.shape[1]
    rep = validate_kv_heads(H, k_cache, v_cache)
    s_block = S if S < S_BLOCK else S_BLOCK
    assert S % s_block == 0, f"cache length {S} not a multiple of {s_block}"
    scale = sm_scale if sm_scale is not None else 1.0 / (D**0.5)

    kernel = functools.partial(
        _decode_kernel, sm_scale=float(scale), s_max=S, s_block=s_block
    )
    # Mosaic requires every block's trailing two dims to be (8,128)-divisible
    # or equal to the array's; [B,Smax,KV,D] caches with a (1,S,1,D) block
    # violate that whenever KV>1, so the kernel consumes a [B,KV,S,D] view
    # (trailing (S,D) block == array dims) and q/o gain a singleton row.
    k_t = jnp.swapaxes(k_cache, 1, 2)
    v_t = jnp.swapaxes(v_cache, 1, 2)
    q4 = q.reshape(B, H, 1, D)
    out = pl.pallas_call(
        kernel,
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=(B, H),
            in_specs=[
                pl.BlockSpec((1, 1, 1, D), lambda b, h, pos: (b, h, 0, 0)),
                pl.BlockSpec((1, 1, S, D), lambda b, h, pos: (b, h // rep, 0, 0)),
                pl.BlockSpec((1, 1, S, D), lambda b, h, pos: (b, h // rep, 0, 0)),
            ],
            out_specs=pl.BlockSpec((1, 1, 1, D), lambda b, h, pos: (b, h, 0, 0)),
        ),
        out_shape=jax.ShapeDtypeStruct((B, H, 1, D), q.dtype),
        interpret=interpret,
    )(jnp.asarray(pos, jnp.int32).reshape(1), q4, k_t, v_t)
    return out.reshape(B, H, D)


# ---------------------------------------------------------------------------
# paged variant: K/V live in a shared page pool, gathered through a per-slot
# block table (the serving subsystem's cache layout, serving/kv_cache.py).
# Reference analog: vLLM's paged_attention kernel — but expressed TPU-natively:
# the gather IS the BlockSpec index map (scalar-prefetched block table drives
# which pool page each grid step DMAs into VMEM), so no dense copy of the
# cache ever materializes.
# ---------------------------------------------------------------------------


def _paged_kernel(bt_ref, pos_ref, q_ref, k_ref, v_ref, *rest,
                  sm_scale: float, page: int, rep: int = 1,
                  quantized: bool = False):
    """Online-softmax accumulation over one slot's pages.

    Grid (B, H, n_pages): TPU grids run sequentially, so the (m, l, acc)
    scratch persists across the innermost page dimension — reset at page 0,
    emitted at the last page. Pages wholly past ``pos`` skip their compute
    (their DMA still runs; block-table rows pad with the scratch page, so the
    wasted bandwidth is one page per padded entry).

    ``quantized`` (ISSUE 12): K/V blocks arrive as int8 codes and a fourth
    input carries the page's [1, KV, 2] scales (gathered by the SAME
    block-table index map) — dequantization happens here in VMEM, so the
    HBM read per page is the halved code bytes plus 8 bytes of scale."""
    if quantized:
        s_ref, o_ref, m_ref, l_ref, acc_ref = rest
    else:
        s_ref, (o_ref, m_ref, l_ref, acc_ref) = None, rest
    b = pl.program_id(0)
    g = pl.program_id(1) // rep  # this program's kv-head column
    j = pl.program_id(2)
    D = q_ref.shape[-1]

    @pl.when(j == 0)
    def _reset():
        m_ref[0] = jnp.float32(-1e30)
        l_ref[0] = jnp.float32(0.0)
        acc_ref[...] = jnp.zeros_like(acc_ref)

    pos = pos_ref[b]

    @pl.when(j * page <= pos)
    def _update():
        q = q_ref[...].reshape(1, D)
        k = k_ref[0, 0]  # [page, D]
        v = v_ref[0, 0]
        if quantized:
            k = k.astype(jnp.float32) * s_ref[0, g, 0]
            v = v.astype(jnp.float32) * s_ref[0, g, 1]
        s = jnp.dot(k, q.T, preferred_element_type=jnp.float32) * sm_scale  # [page,1]
        idx = jax.lax.broadcasted_iota(jnp.int32, (page, 1), 0) + j * page
        s = jnp.where(idx <= pos, s, -1e30)
        m_prev, l_prev = m_ref[0], l_ref[0]
        m_cur = jnp.maximum(m_prev, jnp.max(s))
        corr = jnp.exp(m_prev - m_cur)
        p = jnp.exp(s - m_cur)
        m_ref[0] = m_cur
        l_ref[0] = l_prev * corr + jnp.sum(p)
        acc_ref[...] = acc_ref[...] * corr + jnp.dot(
            p.astype(v.dtype).T, v, preferred_element_type=jnp.float32
        )

    @pl.when(j == pl.num_programs(2) - 1)
    def _emit():
        o_ref[...] = (
            acc_ref[...] / jnp.maximum(l_ref[0], 1e-30)
        ).reshape(o_ref.shape).astype(o_ref.dtype)


def paged_decode_attention(
    q: jnp.ndarray,  # [B, H, D] current-step queries (one per serving slot)
    k_pool: jnp.ndarray,  # [P, KV, page, D] shared page pool
    v_pool: jnp.ndarray,  # [P, KV, page, D]
    block_tables: jnp.ndarray,  # [B, n_pages] i32 pool-page ids per slot
    pos: jnp.ndarray,  # [B] i32: highest valid cache index per slot (inclusive)
    sm_scale: Optional[float] = None,
    interpret: bool = False,
    scales: Optional[jnp.ndarray] = None,  # [P, KV, 2] f32 for int8 pools
) -> jnp.ndarray:
    """Single-token attention against a PAGED cache → [B, H, D].

    Each slot's logical cache is ``block_tables[b]``'s pages concatenated;
    the index map gathers page ``j`` of slot ``b`` straight from the pool
    (scalar-prefetched table), streaming one page per grid step through VMEM
    with an online softmax. GQA as in :func:`decode_attention` (KV < H reads
    the group's pool column). ``scales`` (ISSUE 12): int8 pools ride the
    same index map — page ``bt[b, j]``'s [KV, 2] scale row DMAs beside the
    code block and the dequantize runs in VMEM, so the memory-bound decode
    read is half the bf16 bytes."""
    B, H, D = q.shape
    P, KV, page, _ = k_pool.shape
    n_pages = block_tables.shape[1]
    if H % KV != 0:
        raise ValueError(f"q heads {H} must divide by KV heads {KV}")
    rep = H // KV
    scale = sm_scale if sm_scale is not None else 1.0 / (D**0.5)
    quantized = scales is not None

    kernel = functools.partial(
        _paged_kernel, sm_scale=float(scale), page=page, rep=rep,
        quantized=quantized,
    )
    q4 = q.reshape(B, H, 1, D)
    pool_spec = pl.BlockSpec(
        (1, 1, page, D), lambda b, h, j, bt, pos: (bt[b, j], h // rep, 0, 0)
    )
    in_specs = [
        pl.BlockSpec((1, 1, 1, D), lambda b, h, j, bt, pos: (b, h, 0, 0)),
        pool_spec,
        pool_spec,
    ]
    operands = [q4, k_pool, v_pool]
    if quantized:
        # the scale row rides the block-table gather: trailing (KV, 2)
        # block == the array's own trailing dims, Mosaic-legal for any KV
        in_specs.append(pl.BlockSpec(
            (1, KV, 2), lambda b, h, j, bt, pos: (bt[b, j], 0, 0)
        ))
        operands.append(jnp.asarray(scales, jnp.float32))
    out = pl.pallas_call(
        kernel,
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=2,  # block table + per-slot positions
            grid=(B, H, n_pages),
            in_specs=in_specs,
            out_specs=pl.BlockSpec((1, 1, 1, D), lambda b, h, j, bt, pos: (b, h, 0, 0)),
            scratch_shapes=[
                pltpu.SMEM((1,), jnp.float32),  # running max
                pltpu.SMEM((1,), jnp.float32),  # running denominator
                pltpu.VMEM((1, D), jnp.float32),  # output accumulator
            ],
        ),
        out_shape=jax.ShapeDtypeStruct((B, H, 1, D), q.dtype),
        interpret=interpret,
    )(
        jnp.asarray(block_tables, jnp.int32),
        jnp.asarray(pos, jnp.int32),
        *operands,
    )
    return out.reshape(B, H, D)


def _paged_multitoken_kernel(bt_ref, base_ref, q_ref, k_ref, v_ref, *rest,
                             sm_scale: float, page: int, T: int,
                             rep: int = 1, quantized: bool = False):
    """Online-softmax over one slot's pages for T query tokens at once.

    The verify-step / chunked-prefill analog of :func:`_paged_kernel`
    (ISSUE 10): query t of slot b sits at absolute position
    ``base[b] + t`` and may attend keys at positions ``<= base[b] + t`` —
    the extra column dimension turns the scalar (m, l) softmax state into
    [1, T] rows and the accumulator into [T, D], everything else is the
    same sequential-grid accumulation. Pages wholly past ``base + T - 1``
    skip their compute. ``quantized``: int8 K/V codes dequantize in VMEM
    through the page's [1, KV, 2] scale row (ISSUE 12)."""
    if quantized:
        s_ref, o_ref, m_ref, l_ref, acc_ref = rest
    else:
        s_ref, (o_ref, m_ref, l_ref, acc_ref) = None, rest
    b = pl.program_id(0)
    g = pl.program_id(1) // rep
    j = pl.program_id(2)
    D = q_ref.shape[-1]

    @pl.when(j == 0)
    def _reset():
        m_ref[...] = jnp.full_like(m_ref, -1e30)
        l_ref[...] = jnp.zeros_like(l_ref)
        acc_ref[...] = jnp.zeros_like(acc_ref)

    base = base_ref[b]

    @pl.when(j * page <= base + T - 1)
    def _update():
        q = q_ref[...].reshape(T, D)
        k = k_ref[0, 0]  # [page, D]
        v = v_ref[0, 0]
        if quantized:
            k = k.astype(jnp.float32) * s_ref[0, g, 0]
            v = v.astype(jnp.float32) * s_ref[0, g, 1]
        s = jnp.dot(k, q.T, preferred_element_type=jnp.float32) * sm_scale  # [page,T]
        idx = jax.lax.broadcasted_iota(jnp.int32, (page, T), 0) + j * page
        t_col = jax.lax.broadcasted_iota(jnp.int32, (page, T), 1)
        s = jnp.where(idx <= base + t_col, s, -1e30)
        m_prev, l_prev = m_ref[...], l_ref[...]           # [1, T]
        m_cur = jnp.maximum(m_prev, jnp.max(s, axis=0, keepdims=True))
        corr = jnp.exp(m_prev - m_cur)                    # [1, T]
        p = jnp.exp(s - m_cur)                            # [page, T]
        m_ref[...] = m_cur
        l_ref[...] = l_prev * corr + jnp.sum(p, axis=0, keepdims=True)
        acc_ref[...] = acc_ref[...] * corr.T + jnp.dot(
            p.astype(v.dtype).T, v, preferred_element_type=jnp.float32
        )

    @pl.when(j == pl.num_programs(2) - 1)
    def _emit():
        o_ref[...] = (
            acc_ref[...] / jnp.maximum(l_ref[...].T, 1e-30)
        ).reshape(o_ref.shape).astype(o_ref.dtype)


def paged_multitoken_attention(
    q: jnp.ndarray,  # [B, T, H, D] T query tokens per slot
    k_pool: jnp.ndarray,  # [P, KV, page, D] shared page pool
    v_pool: jnp.ndarray,  # [P, KV, page, D]
    block_tables: jnp.ndarray,  # [B, n_pages] i32 pool-page ids per slot
    base: jnp.ndarray,  # [B] i32: query t of slot b sits at position base[b]+t
    sm_scale: Optional[float] = None,
    interpret: bool = False,
    scales: Optional[jnp.ndarray] = None,  # [P, KV, 2] f32 for int8 pools
) -> jnp.ndarray:
    """T-token causal attention against a PAGED cache → [B, T, H, D].

    Serves the speculative verify step (T = k+1 drafted tokens, base =
    per-slot cached length) and chunked prefill (T = chunk width, base =
    chunk start) — the chunk's own K/V must already be scattered into the
    pool (update-then-attend, as in the single-token decode step). GQA as
    in :func:`paged_decode_attention`."""
    B, T, H, D = q.shape
    P, KV, page, _ = k_pool.shape
    n_pages = block_tables.shape[1]
    if H % KV != 0:
        raise ValueError(f"q heads {H} must divide by KV heads {KV}")
    rep = H // KV
    scale = sm_scale if sm_scale is not None else 1.0 / (D**0.5)
    quantized = scales is not None

    kernel = functools.partial(
        _paged_multitoken_kernel, sm_scale=float(scale), page=page, T=T,
        rep=rep, quantized=quantized,
    )
    q4 = jnp.swapaxes(q, 1, 2)  # [B, H, T, D]: trailing block == array dims
    pool_spec = pl.BlockSpec(
        (1, 1, page, D), lambda b, h, j, bt, base: (bt[b, j], h // rep, 0, 0)
    )
    in_specs = [
        pl.BlockSpec((1, 1, T, D), lambda b, h, j, bt, base: (b, h, 0, 0)),
        pool_spec,
        pool_spec,
    ]
    operands = [q4, k_pool, v_pool]
    if quantized:
        in_specs.append(pl.BlockSpec(
            (1, KV, 2), lambda b, h, j, bt, base: (bt[b, j], 0, 0)
        ))
        operands.append(jnp.asarray(scales, jnp.float32))
    out = pl.pallas_call(
        kernel,
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=2,  # block table + per-slot base positions
            grid=(B, H, n_pages),
            in_specs=in_specs,
            out_specs=pl.BlockSpec(
                (1, 1, T, D), lambda b, h, j, bt, base: (b, h, 0, 0)
            ),
            scratch_shapes=[
                pltpu.VMEM((1, T), jnp.float32),  # running max per query
                pltpu.VMEM((1, T), jnp.float32),  # running denominator
                pltpu.VMEM((T, D), jnp.float32),  # output accumulator
            ],
        ),
        out_shape=jax.ShapeDtypeStruct((B, H, T, D), q.dtype),
        interpret=interpret,
    )(
        jnp.asarray(block_tables, jnp.int32),
        jnp.asarray(base, jnp.int32),
        *operands,
    )
    return jnp.swapaxes(out, 1, 2)  # [B, T, H, D]


def paged_decode_attention_ok(page: int, D: int, itemsize: int = 2) -> bool:
    """Trace-time gate for the paged kernel: TPU backend, lane-friendly head
    dim, sublane-aligned page length, and one page's K+V fitting VMEM (per-
    program cost is pool/B/H independent — that's the point of paging)."""
    from .flash_attention import VMEM_RESIDENT_BYTES

    sublane = max(1, 32 // max(1, itemsize))
    return (
        jax.default_backend() == "tpu"
        and D % 64 == 0
        and page % sublane == 0
        and 2 * page * D * itemsize <= VMEM_RESIDENT_BYTES
    )


def paged_multitoken_attention_ok(
    page: int, D: int, T: int, itemsize: int = 2
) -> bool:
    """Gate for the multitoken paged kernel: the single-token gate plus the
    [T, D] query/accumulator slabs staying VMEM-resident."""
    from .flash_attention import VMEM_RESIDENT_BYTES

    return (
        paged_decode_attention_ok(page, D, itemsize)
        and (2 * page * D * itemsize + T * D * (itemsize + 4)
             <= VMEM_RESIDENT_BYTES)
    )


def decode_attention_ok(S: int, D: int, itemsize: int = 2) -> bool:
    """Trace-time gate mirroring ops.attention._pallas_ok: TPU backend,
    lane-friendly head dim, and the K+V slabs of one (batch, head) program
    fitting the kernel's VMEM budget (per-program cost is B/H independent)."""
    from .flash_attention import VMEM_RESIDENT_BYTES

    return (
        jax.default_backend() == "tpu"
        and D % 64 == 0
        and (S < S_BLOCK or S % S_BLOCK == 0)
        and 2 * S * D * itemsize <= VMEM_RESIDENT_BYTES
    )
