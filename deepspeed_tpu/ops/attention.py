"""Attention dispatch: Pallas flash kernel on TPU, jnp reference elsewhere.

The capability analog of the reference's fused transformer kernels
(``csrc/transformer/ds_transformer_cuda.cpp`` softmax/attention pieces): the
FLOPs-heavy attention inner loop runs as a hand-written TPU kernel
(``deepspeed_tpu/ops/pallas/flash_attention.py``) when shapes allow, with a
pure-XLA fallback that still fuses well (MXU einsums + f32 softmax).

Layout convention here is [B, S, H, D] (batch, seq, heads, head_dim).
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp

from ..utils.logging import warning_once


def causal_attention_jnp(q, k, v, sm_scale: Optional[float] = None):
    """Reference implementation: [B,S,H,D] → [B,S,H,D], causal, f32 softmax.
    Accepts GQA k/v ([B,S,KV,D], H % KV == 0) by repeating — a fallback
    path, so the materialized repeat is acceptable. Exactly the window<=0
    case of :func:`causal_attention_windowed_jnp` (one masked-softmax
    reference to keep in sync, not two)."""
    return causal_attention_windowed_jnp(q, k, v, 0, sm_scale)


def _pallas_ok(q) -> bool:
    B, S, H, D = q.shape
    if jax.default_backend() not in ("tpu",):
        return False
    # the shape rule lives in ONE place (pallas/flash_attention.flash_ok) so
    # this dispatcher can never disagree with the kernel's own checks. Within
    # the whole-K/V VMEM budget the resident kernels serve; past it,
    # flash_attention streams K/V through the KV-blocked grid variant. The
    # ring (sp) dispatcher keeps the stricter per-shard bound (ring_flash_ok).
    from .pallas.flash_attention import flash_ok

    return flash_ok(S, D)


# public name for model code deciding whether the kernel path will engage
# (e.g. the decoder zoo's GQA prefill keeps its no-repeat grouped einsum
# off-TPU instead of the jnp fallback's materialized repeat)
pallas_attention_ok = _pallas_ok


def cached_attention(q, k_cache, v_cache, pos, impl: str = "auto", sm_scale: Optional[float] = None):
    """Single-token decode attention against a KV cache: q [B,H,D],
    caches [B,Smax,KV,D] (KV == H, or H % KV == 0 for GQA),
    pos = highest valid index → [B,H,D].

    Dispatch mirrors :func:`causal_attention`: the Pallas online-softmax
    decode kernel on TPU (reference softmax_context fused inference kernel),
    jnp fallback elsewhere, with the same warn-and-fall-back contract. The
    jnp GQA fallback is a grouped einsum — the cache is never repeated on
    either path.
    """
    from .pallas.flash_attention import validate_kv_heads

    B, H, D = q.shape
    S, KV = k_cache.shape[1], k_cache.shape[2]
    # validate the head ratio HERE: raised inside the kernel, the auto
    # dispatch would swallow it as a "pallas unavailable" warning and the
    # fallback would then fail with an unrelated reshape error
    validate_kv_heads(H, k_cache, v_cache)
    if impl in ("auto", "pallas"):
        from .pallas.decode_attention import decode_attention, decode_attention_ok

        if impl == "pallas" or decode_attention_ok(S, D, k_cache.dtype.itemsize):
            try:
                return decode_attention(q, k_cache, v_cache, pos, sm_scale=sm_scale)
            except Exception as e:  # pragma: no cover
                if impl == "pallas":
                    raise
                warning_once(f"pallas decode attention unavailable ({e}); using jnp path")
    elif impl != "jnp":
        raise ValueError(f"unknown attention impl {impl}")
    scale = sm_scale if sm_scale is not None else 1.0 / (D**0.5)
    mask = jnp.arange(S)[None, None, :] <= pos
    # one grouped form covers MHA too (rep == 1): no duplicated math
    rep = H // KV
    qg = q.reshape(B, KV, rep, D)
    scores = jnp.einsum(
        "bgrd,bsgd->bgrs", qg.astype(jnp.float32), k_cache.astype(jnp.float32)
    ) * scale
    probs = jax.nn.softmax(jnp.where(mask[:, :, None], scores, -1e30), axis=-1)
    o = jnp.einsum("bgrs,bsgd->bgrd", probs, v_cache.astype(jnp.float32))
    return o.reshape(B, H, D).astype(q.dtype)


def gather_pool_pages(k_pool, v_pool, block_tables, scales=None):
    """Gather each slot's pages ([B,n,KV,page,D]) and, for int8 pools,
    dequantize them through ``scales [P,KV,2]`` (per-page per-kv-head block
    scales, K at index 0 / V at 1 — ISSUE 12). Pure data movement when
    ``scales`` is None. The ONE dense-view gather both the serving-model
    jnp branches and the dispatcher fallbacks below share — a scale-layout
    change lands everywhere or nowhere."""
    kd = k_pool[block_tables]
    vd = v_pool[block_tables]
    if scales is not None:
        st = scales[block_tables]  # [B, n, KV, 2]
        kd = kd.astype(jnp.float32) * st[..., 0][..., None, None]
        vd = vd.astype(jnp.float32) * st[..., 1][..., None, None]
    return kd, vd


def paged_cached_attention(
    q, k_pool, v_pool, block_tables, pos, impl: str = "auto",
    sm_scale: Optional[float] = None, scales=None,
):
    """Single-token decode attention against a PAGED KV cache (the serving
    subsystem's layout): q [B,H,D], pools [P,KV,page,D] (KV == H or
    H % KV == 0), block_tables [B,n] i32 pool-page ids per slot, pos [B] i32
    per-slot highest valid index (inclusive) → [B,H,D]. ``scales``
    [P,KV,2] dequantizes int8 pools (ISSUE 12) — required iff the pool
    dtype is int8.

    Dispatch mirrors :func:`cached_attention`: the Pallas paged kernel on TPU
    (the block-table gather IS the kernel's index map — no dense copy; int8
    pages dequantize INSIDE the kernel, so HBM traffic is the halved code
    bytes), and a pure-jnp fallback that gathers the slot's pages into a
    dense view and runs the exact grouped einsum of :func:`cached_attention`
    with a per-slot mask, so the two paths agree with the dense cache."""
    B, H, D = q.shape
    P, KV, page, _ = k_pool.shape
    if H % KV != 0:
        raise ValueError(f"q heads {H} must divide by KV heads {KV}")
    if (scales is None) == (k_pool.dtype == jnp.int8):
        raise ValueError(
            "paged_cached_attention: scales must be given exactly when the "
            f"pool is int8 (pool dtype {k_pool.dtype}, scales "
            f"{'given' if scales is not None else 'missing'})"
        )
    if impl in ("auto", "pallas"):
        from .pallas.decode_attention import (
            paged_decode_attention,
            paged_decode_attention_ok,
        )

        if impl == "pallas" or paged_decode_attention_ok(page, D, k_pool.dtype.itemsize):
            try:
                return paged_decode_attention(
                    q, k_pool, v_pool, block_tables, pos, sm_scale=sm_scale,
                    scales=scales,
                )
            except Exception as e:  # pragma: no cover
                if impl == "pallas":
                    raise
                warning_once(f"pallas paged attention unavailable ({e}); using jnp path")
    elif impl != "jnp":
        raise ValueError(f"unknown attention impl {impl}")
    # gather [B,n,KV,page,D] → logical [B,T,KV,D] per slot (pure data
    # movement; int8 pools dequantize here), then the same grouped math as
    # cached_attention's fallback
    kd, vd = gather_pool_pages(k_pool, v_pool, block_tables, scales)
    kd = jnp.swapaxes(kd, 2, 3).reshape(B, -1, KV, D)
    vd = jnp.swapaxes(vd, 2, 3).reshape(B, -1, KV, D)
    scale = sm_scale if sm_scale is not None else 1.0 / (D**0.5)
    S = kd.shape[1]
    mask = jnp.arange(S)[None, None, :] <= pos[:, None, None]  # [B,1,S]
    rep = H // KV
    qg = q.reshape(B, KV, rep, D)
    scores = jnp.einsum(
        "bgrd,bsgd->bgrs", qg.astype(jnp.float32), kd.astype(jnp.float32)
    ) * scale
    probs = jax.nn.softmax(jnp.where(mask[:, :, None], scores, -1e30), axis=-1)
    o = jnp.einsum("bgrs,bsgd->bgrd", probs, vd.astype(jnp.float32))
    return o.reshape(B, H, D).astype(q.dtype)


def paged_multitoken_cached_attention(
    q, k_pool, v_pool, block_tables, base, impl: str = "auto",
    sm_scale: Optional[float] = None, scales=None,
):
    """T-token causal decode attention against a PAGED KV cache (ISSUE 10:
    the speculative verify step and chunked prefill): q [B,T,H,D], pools
    [P,KV,page,D], block_tables [B,n] i32, base [B] i32 — query t of slot b
    sits at absolute position ``base[b] + t`` and attends keys ``<= base[b]
    + t`` → [B,T,H,D]. The chunk's own K/V must already be scattered into
    the pool (update-then-attend, exactly like the single-token step).

    Dispatch mirrors :func:`paged_cached_attention`: the multitoken Pallas
    kernel on TPU, and a pure-jnp fallback whose T == 1 slice is the exact
    grouped einsum of the single-token fallback (same casts, same masked
    softmax) so the verify step's first query agrees with the decode step
    bit for bit."""
    B, T, H, D = q.shape
    P, KV, page, _ = k_pool.shape
    if H % KV != 0:
        raise ValueError(f"q heads {H} must divide by KV heads {KV}")
    if (scales is None) == (k_pool.dtype == jnp.int8):
        raise ValueError(
            "paged_multitoken_cached_attention: scales must be given "
            f"exactly when the pool is int8 (pool dtype {k_pool.dtype})"
        )
    if impl in ("auto", "pallas"):
        from .pallas.decode_attention import (
            paged_multitoken_attention,
            paged_multitoken_attention_ok,
        )

        if impl == "pallas" or paged_multitoken_attention_ok(
            page, D, T, k_pool.dtype.itemsize
        ):
            try:
                return paged_multitoken_attention(
                    q, k_pool, v_pool, block_tables, base, sm_scale=sm_scale,
                    scales=scales,
                )
            except Exception as e:  # pragma: no cover
                if impl == "pallas":
                    raise
                warning_once(
                    f"pallas multitoken paged attention unavailable ({e}); "
                    "using jnp path"
                )
    elif impl != "jnp":
        raise ValueError(f"unknown attention impl {impl}")
    kd, vd = gather_pool_pages(k_pool, v_pool, block_tables, scales)
    kd = jnp.swapaxes(kd, 2, 3).reshape(B, -1, KV, D)
    vd = jnp.swapaxes(vd, 2, 3).reshape(B, -1, KV, D)
    scale = sm_scale if sm_scale is not None else 1.0 / (D**0.5)
    S = kd.shape[1]
    # [B, T, S]: key j visible to query t iff j <= base + t
    mask = (
        jnp.arange(S)[None, None, :]
        <= base[:, None, None] + jnp.arange(T)[None, :, None]
    )
    rep = H // KV
    qg = q.reshape(B, T, KV, rep, D)
    scores = jnp.einsum(
        "btgrd,bsgd->btgrs", qg.astype(jnp.float32), kd.astype(jnp.float32)
    ) * scale
    probs = jax.nn.softmax(
        jnp.where(mask[:, :, None, None, :], scores, -1e30), axis=-1
    )
    o = jnp.einsum("btgrs,bsgd->btgrd", probs, vd.astype(jnp.float32))
    return o.reshape(B, T, H, D).astype(q.dtype)


def windowed_attention_ok(q) -> bool:
    """Whether sliding-window causal attention will ride the Pallas kernels
    for this shape: the ordinary dispatch gate plus the resident-kernel
    bound (windows are not implemented in the grid variant). The shape rule
    is windowed_flash_ok — shared with the kernel's own checks so the two
    gates can never disagree."""
    B, S, H, D = q.shape
    from .pallas.flash_attention import windowed_flash_ok

    if jax.default_backend() not in ("tpu",):
        return False
    return windowed_flash_ok(S, D, q.dtype.itemsize)


def causal_attention_windowed_jnp(q, k, v, window, sm_scale: Optional[float] = None):
    """Sliding-window reference path: key j visible to query i iff
    i - window < j <= i; ``window`` may be a traced i32 scalar (<=0 =
    global). GQA k/v accepted by repeating (fallback path).
    The unwindowed :func:`causal_attention_jnp` is the window<=0 case."""
    B, S, H, D = q.shape
    if k.shape[2] != H:
        rep = H // k.shape[2]
        k = jnp.repeat(k, rep, axis=2)
        v = jnp.repeat(v, rep, axis=2)
    scale = sm_scale if sm_scale is not None else 1.0 / (D**0.5)
    logits = jnp.einsum(
        "bqhd,bkhd->bhqk", q, k, preferred_element_type=jnp.float32
    ) * scale
    i = jnp.arange(S)[:, None]
    j = jnp.arange(S)[None, :]
    win = jnp.asarray(window, jnp.int32)
    keep = (j <= i) & ((win <= 0) | (j > i - win))
    logits = jnp.where(keep[None, None], logits, jnp.float32(-1e30))
    probs = jax.nn.softmax(logits, axis=-1).astype(q.dtype)
    return jnp.einsum("bhqk,bkhd->bqhd", probs, v)


def causal_attention(q, k, v, impl: str = "auto", sm_scale: Optional[float] = None,
                     window=None):
    if impl == "jnp":
        if window is not None:
            return causal_attention_windowed_jnp(q, k, v, window, sm_scale)
        return causal_attention_jnp(q, k, v, sm_scale)
    if impl in ("auto", "pallas"):
        ok = windowed_attention_ok(q) if window is not None else _pallas_ok(q)
        if impl == "pallas" or ok:
            try:
                from .pallas.flash_attention import flash_attention

                return flash_attention(
                    q, k, v, causal=True, sm_scale=sm_scale, window=window
                )
            except Exception as e:  # pragma: no cover
                if impl == "pallas":
                    raise
                warning_once(f"pallas flash attention unavailable ({e}); using jnp path")
        if window is not None:
            return causal_attention_windowed_jnp(q, k, v, window, sm_scale)
        return causal_attention_jnp(q, k, v, sm_scale)
    raise ValueError(f"unknown attention impl {impl}")


def bidirectional_attention_jnp(q, k, v, mask=None, sm_scale: Optional[float] = None):
    """Encoder attention: [B,S,H,D] -> [B,S,H,D], optional padding ``mask``
    [B,S] (1 = attend), f32 softmax."""
    B, S, H, D = q.shape
    scale = sm_scale if sm_scale is not None else 1.0 / (D**0.5)
    logits = jnp.einsum(
        "bqhd,bkhd->bhqk", q, k, preferred_element_type=jnp.float32
    ) * scale
    if mask is not None:
        logits = jnp.where(mask[:, None, None, :].astype(bool), logits, jnp.float32(-1e30))
    probs = jax.nn.softmax(logits, axis=-1).astype(q.dtype)
    return jnp.einsum("bhqk,bkhd->bqhd", probs, v)


def bidirectional_attention(
    q, k, v, mask=None, impl: str = "auto", sm_scale: Optional[float] = None
):
    """Non-causal dispatcher with the same warn-and-fall-back contract as
    :func:`causal_attention`. The Pallas flash kernel serves the unmasked
    case; a padding mask routes to the jnp path (the kernel has no mask
    input — masked encoder batches are typically short enough that the
    materialized [S,S] is cheap)."""
    if impl == "jnp" or mask is not None:
        return bidirectional_attention_jnp(q, k, v, mask, sm_scale)
    if impl in ("auto", "pallas"):
        if impl == "pallas" or _pallas_ok(q):
            try:
                from .pallas.flash_attention import flash_attention

                return flash_attention(q, k, v, causal=False, sm_scale=sm_scale)
            except Exception as e:  # pragma: no cover
                if impl == "pallas":
                    raise
                warning_once(f"pallas flash attention unavailable ({e}); using jnp path")
        return bidirectional_attention_jnp(q, k, v, None, sm_scale)
    raise ValueError(f"unknown attention impl {impl}")
