"""Standalone fused transformer (encoder) layer — the public kernel-layer API.

Capability analog of the reference's ``DeepSpeedTransformerLayer``
(``ops/transformer/transformer.py:459`` wrapping the ~6.4k-LoC fused CUDA
encoder kernel ``csrc/transformer/ds_transformer_cuda.cpp``): one layer =
QKV matmul + self-attention + output projection + residual/dropout + GELU
MLP, pre- or post-LayerNorm, fwd AND bwd. TPU-first formulation: the layer
is a pure function jitted as one XLA program — the elementwise chain fuses
into the matmuls, attention dispatches to the Pallas flash kernel when
shapes/backing allow (``ops/attention.py``), and the backward pass is
autodiff over the same fused program rather than a second hand-written
kernel. Config mirrors the reference's ``DeepSpeedTransformerConfig``
(``transformer.py:38``) where the concept transfers.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional

import jax
import jax.numpy as jnp

from .attention import bidirectional_attention
from .layer_norm import layer_norm


@dataclass
class DeepSpeedTransformerConfig:
    """Reference DeepSpeedTransformerConfig (ops/transformer/transformer.py:38)
    minus CUDA-only knobs (streams, seeds are per-call rngs here; fp16 flag is
    the ``dtype``). ``stochastic_mode`` has no analog: XLA programs are
    deterministic for fixed rng."""

    hidden_size: int = 768
    intermediate_size: Optional[int] = None  # defaults to 4*hidden
    heads: int = 12
    attn_dropout_ratio: float = 0.1
    hidden_dropout_ratio: float = 0.1
    layer_norm_eps: float = 1e-12
    initializer_range: float = 0.02
    pre_layer_norm: bool = True
    dtype: jnp.dtype = jnp.float32

    def __post_init__(self):
        if self.intermediate_size is None:
            self.intermediate_size = 4 * self.hidden_size
        assert self.hidden_size % self.heads == 0


def _dropout(x, rate, rng, train):
    if not train or rate <= 0.0 or rng is None:
        return x
    keep = 1.0 - rate
    mask = jax.random.bernoulli(rng, keep, x.shape)
    return jnp.where(mask, x / keep, 0.0).astype(x.dtype)


class DeepSpeedTransformerLayer:
    """Functional encoder layer: ``params = layer.init(rng)``;
    ``y = layer(params, x, attention_mask, train, rng)``.

    ``x`` is [B, S, E]; ``attention_mask`` (optional) is the HF convention
    [B, S] with 1 = attend, 0 = padding. Bidirectional (encoder) attention;
    for causal decoders use the model families in ``deepspeed_tpu.models``.
    """

    def __init__(self, config: DeepSpeedTransformerConfig):
        self.config = config

    # -- params -------------------------------------------------------------
    def init(self, rng):
        c = self.config
        E, I = c.hidden_size, c.intermediate_size
        k = jax.random.split(rng, 4)
        s = c.initializer_range

        def norm(key, shape):
            return (jax.random.normal(key, shape) * s).astype(c.dtype)

        return {
            "attn": {
                "qkv_w": norm(k[0], (E, 3 * E)),
                "qkv_b": jnp.zeros((3 * E,), c.dtype),
                "out_w": norm(k[1], (E, E)),
                "out_b": jnp.zeros((E,), c.dtype),
            },
            "mlp": {
                "fc_w": norm(k[2], (E, I)),
                "fc_b": jnp.zeros((I,), c.dtype),
                "proj_w": norm(k[3], (I, E)),
                "proj_b": jnp.zeros((E,), c.dtype),
            },
            "ln1": {"scale": jnp.ones((E,), c.dtype), "bias": jnp.zeros((E,), c.dtype)},
            "ln2": {"scale": jnp.ones((E,), c.dtype), "bias": jnp.zeros((E,), c.dtype)},
        }

    # -- forward ------------------------------------------------------------
    def __call__(self, params, x, attention_mask=None, train: bool = False, rng=None):
        c = self.config
        B, S, E = x.shape
        H, D = c.heads, c.hidden_size // c.heads
        rngs = jax.random.split(rng, 3) if rng is not None else (None, None, None)

        def attn_block(h):
            qkv = h @ params["attn"]["qkv_w"] + params["attn"]["qkv_b"]
            q, k, v = jnp.split(qkv.reshape(B, S, 3, H, D), 3, axis=2)
            out = bidirectional_attention(
                q[:, :, 0], k[:, :, 0], v[:, :, 0], mask=attention_mask
            )
            out = _dropout(out, c.attn_dropout_ratio, rngs[0], train)
            return out.reshape(B, S, E) @ params["attn"]["out_w"] + params["attn"]["out_b"]

        def mlp_block(h):
            h = jax.nn.gelu(h @ params["mlp"]["fc_w"] + params["mlp"]["fc_b"])
            return h @ params["mlp"]["proj_w"] + params["mlp"]["proj_b"]

        ln1 = lambda h: layer_norm(h, params["ln1"]["scale"], params["ln1"]["bias"], c.layer_norm_eps)
        ln2 = lambda h: layer_norm(h, params["ln2"]["scale"], params["ln2"]["bias"], c.layer_norm_eps)

        if c.pre_layer_norm:
            x = x + _dropout(attn_block(ln1(x)), c.hidden_dropout_ratio, rngs[1], train)
            return x + _dropout(mlp_block(ln2(x)), c.hidden_dropout_ratio, rngs[2], train)
        x = ln1(x + _dropout(attn_block(x), c.hidden_dropout_ratio, rngs[1], train))
        return ln2(x + _dropout(mlp_block(x), c.hidden_dropout_ratio, rngs[2], train))
