"""In-graph token sampling for autoregressive decode.

Shared by ``models/gpt2.generate`` and ``models/decoder.generate`` (the
reference leaves sampling to HF's generate loop on host; here the whole
decode — including top-k/top-p filtering — stays inside the compiled
``lax.scan`` so no per-token host round trip exists).

All transforms are shape-static and jit-safe: top-k masks via
``jax.lax.top_k`` threshold, top-p (nucleus) masks in sorted space and
scatters back through the inverse permutation.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

NEG = -1e30


def top_k_mask(logits: jnp.ndarray, k: int) -> jnp.ndarray:
    """Keep exactly the k highest logits per row, mask the rest to -inf.

    Rank-based (scatter of ``top_k`` indices), not threshold-based: a
    ``logits < kth`` comparison keeps EVERY token tied with the k-th logit,
    so ties would let more than k tokens survive — ``lax.top_k`` breaks ties
    deterministically by index, and the mask inherits that tie-break."""
    if k <= 0 or k >= logits.shape[-1]:
        return logits
    idx = jax.lax.top_k(logits, k)[1]  # [.., k] winner indices, ties → lowest index
    # one-hot over the vocab, folded over the k winners: [.., k, V] -> [.., V]
    keep = jax.nn.one_hot(idx, logits.shape[-1], dtype=jnp.bool_).any(axis=-2)
    return jnp.where(keep, logits, NEG)


def top_p_mask(logits: jnp.ndarray, p: float) -> jnp.ndarray:
    """Nucleus filtering: keep the smallest prefix of the probability-sorted
    vocab whose cumulative mass reaches ``p`` (always keeps the argmax)."""
    if p >= 1.0:
        return logits
    sort_idx = jnp.argsort(-logits, axis=-1)
    sorted_logits = jnp.take_along_axis(logits, sort_idx, axis=-1)
    probs = jax.nn.softmax(sorted_logits, axis=-1)
    # exclusive cumulative mass: the first token always survives. The
    # threshold backs off by one ulp-ish relative epsilon so a prefix whose
    # true mass EQUALS p doesn't leak an extra token when cumsum rounds down
    # (e.g. 0.5 + 0.3 -> 0.79999995 < 0.8).
    cum = jnp.cumsum(probs, axis=-1) - probs
    keep_sorted = cum < p * (1.0 - 1e-6)
    masked_sorted = jnp.where(keep_sorted, sorted_logits, NEG)
    inv = jnp.argsort(sort_idx, axis=-1)
    return jnp.take_along_axis(masked_sorted, inv, axis=-1)


def sample_logits(
    logits: jnp.ndarray,
    key,
    temperature: float = 0.0,
    top_k: int = 0,
    top_p: float = 1.0,
) -> jnp.ndarray:
    """[.., V] logits → token ids. temperature<=0 = greedy (top_k/top_p are
    then irrelevant — argmax always survives both filters)."""
    logits = logits.astype(jnp.float32)
    if not temperature or temperature <= 0.0:
        return jnp.argmax(logits, axis=-1)
    logits = logits / temperature
    logits = top_k_mask(logits, int(top_k))
    logits = top_p_mask(logits, float(top_p))
    return jax.random.categorical(key, logits, axis=-1)
