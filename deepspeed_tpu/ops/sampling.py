"""In-graph token sampling for autoregressive decode.

Shared by ``models/gpt2.generate`` and ``models/decoder.generate`` (the
reference leaves sampling to HF's generate loop on host; here the whole
decode — including top-k/top-p filtering — stays inside the compiled
``lax.scan`` so no per-token host round trip exists).

All transforms are shape-static and jit-safe: top-k masks via
``jax.lax.top_k`` threshold, top-p (nucleus) masks in sorted space and
scatters back through the inverse permutation.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

NEG = -1e30


def top_k_mask(logits: jnp.ndarray, k: int) -> jnp.ndarray:
    """Keep the k highest logits per row, mask the rest to -inf."""
    if k <= 0 or k >= logits.shape[-1]:
        return logits
    kth = jax.lax.top_k(logits, k)[0][..., -1:]  # [.., 1] k-th largest
    return jnp.where(logits < kth, NEG, logits)


def top_p_mask(logits: jnp.ndarray, p: float) -> jnp.ndarray:
    """Nucleus filtering: keep the smallest prefix of the probability-sorted
    vocab whose cumulative mass reaches ``p`` (always keeps the argmax)."""
    if p >= 1.0:
        return logits
    sort_idx = jnp.argsort(-logits, axis=-1)
    sorted_logits = jnp.take_along_axis(logits, sort_idx, axis=-1)
    probs = jax.nn.softmax(sorted_logits, axis=-1)
    # exclusive cumulative mass: the first token always survives
    cum = jnp.cumsum(probs, axis=-1) - probs
    keep_sorted = cum < p
    masked_sorted = jnp.where(keep_sorted, sorted_logits, NEG)
    inv = jnp.argsort(sort_idx, axis=-1)
    return jnp.take_along_axis(masked_sorted, inv, axis=-1)


def sample_logits(
    logits: jnp.ndarray,
    key,
    temperature: float = 0.0,
    top_k: int = 0,
    top_p: float = 1.0,
) -> jnp.ndarray:
    """[.., V] logits → token ids. temperature<=0 = greedy (top_k/top_p are
    then irrelevant — argmax always survives both filters)."""
    logits = logits.astype(jnp.float32)
    if not temperature or temperature <= 0.0:
        return jnp.argmax(logits, axis=-1)
    logits = logits / temperature
    logits = top_k_mask(logits, int(top_k))
    logits = top_p_mask(logits, float(top_p))
    return jax.random.categorical(key, logits, axis=-1)
