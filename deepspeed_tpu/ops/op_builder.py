"""Native-op build system — analog of reference ``op_builder/builder.py``.

The reference JIT-compiles CUDA/C++ extensions with ninja+nvcc behind an
``OpBuilder.load()`` API, gated by ``DS_BUILD_*`` env vars and compatibility
probes (builder.py:105 OpBuilder, :524 CUDAOpBuilder, jit_load). The TPU build
has no device code to compile — Pallas kernels trace inside JAX — so the only
native artifacts are host-side C++ shared libraries (async NVMe I/O, SIMD
optimizers). This module compiles them with g++ on first use, caches the .so
by source hash, and loads it via ctypes (no pybind11 in the image).

Env gating (reference ``DS_BUILD_*``):
  DS_BUILD_OPS=0        disable all native builds (pure-Python fallbacks)
  DS_BUILD_<NAME>=0/1   per-op override
"""

from __future__ import annotations

import ctypes
import hashlib
import os
import shutil
import subprocess
import tempfile
from typing import Dict, List, Optional

from ..utils.logging import logger

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
CSRC_DIR = os.path.join(_REPO_ROOT, "csrc")

_loaded: Dict[str, ctypes.CDLL] = {}


def _cache_dir() -> str:
    d = os.environ.get(
        "DS_BUILD_CACHE",
        os.path.join(os.path.expanduser("~"), ".cache", "deepspeed_tpu", "ops"),
    )
    os.makedirs(d, exist_ok=True)
    return d


class OpBuilder:
    """Compile one C++ source set into a cached .so and load it.

    Subclass (or instantiate) with NAME and SOURCES; ``load()`` returns a
    ctypes.CDLL with restype/argtypes left to the caller's wrapper module.
    """

    NAME: str = ""
    SOURCES: List[str] = []
    EXTRA_FLAGS: List[str] = []

    def __init__(self, name: Optional[str] = None, sources: Optional[List[str]] = None,
                 extra_flags: Optional[List[str]] = None):
        self.name = name or self.NAME
        self.sources = [
            s if os.path.isabs(s) else os.path.join(CSRC_DIR, s)
            for s in (sources or self.SOURCES)
        ]
        self.extra_flags = extra_flags if extra_flags is not None else list(self.EXTRA_FLAGS)

    # -- compatibility probing (reference builder.py is_compatible) ---------
    def is_compatible(self) -> bool:
        if os.environ.get("DS_BUILD_OPS", "1") == "0":
            return False
        gate = os.environ.get(f"DS_BUILD_{self.name.upper()}")
        if gate is not None:
            return gate != "0"
        return shutil.which("g++") is not None and all(os.path.exists(s) for s in self.sources)

    def _source_hash(self, flags: List[str]) -> str:
        h = hashlib.sha256()
        for s in sorted(self.sources):
            with open(s, "rb") as f:
                h.update(f.read())
        h.update(" ".join(flags).encode())
        return h.hexdigest()[:16]

    def cflags(self) -> List[str]:
        flags = ["-O3", "-std=c++17", "-fPIC", "-shared", "-pthread", "-fopenmp"]
        if os.environ.get("DS_BUILD_NATIVE_ARCH", "1") != "0":
            flags.append("-march=native")
        return flags + self.extra_flags

    def so_path(self, flags: Optional[List[str]] = None) -> str:
        flags = flags if flags is not None else self.cflags()
        return os.path.join(_cache_dir(), f"{self.name}_{self._source_hash(flags)}.so")

    def build(self) -> str:
        # Concurrency-safe (8 host procs cold-starting at once on a pod slice):
        # compile to a per-process unique tmp, publish with atomic os.replace;
        # losers of the race simply overwrite with identical bytes. Each flag
        # set caches under its own hash, so a -march=native fallback never
        # masquerades as the native build.
        flags = self.cflags()
        while True:
            out = self.so_path(flags)
            if os.path.exists(out):
                return out
            tmp = f"{out}.tmp.{os.getpid()}.{os.urandom(4).hex()}"
            cmd = ["g++", *flags, *self.sources, "-o", tmp]
            logger.info(f"building native op '{self.name}': {' '.join(cmd)}")
            try:
                subprocess.run(cmd, check=True, capture_output=True, text=True)
            except subprocess.CalledProcessError as e:
                if os.path.exists(tmp):
                    os.remove(tmp)
                if "-march=native" in flags:
                    flags = [f for f in flags if f != "-march=native"]
                    continue
                raise RuntimeError(
                    f"native build of {self.name} failed:\n{e.stderr}"
                ) from e
            os.replace(tmp, out)
            return out

    def load(self) -> ctypes.CDLL:
        if self.name in _loaded:
            return _loaded[self.name]
        if not self.is_compatible():
            raise RuntimeError(
                f"native op '{self.name}' unavailable (DS_BUILD gating or missing toolchain)"
            )
        lib = ctypes.CDLL(self.build())
        _loaded[self.name] = lib
        return lib


class AsyncIOBuilder(OpBuilder):
    NAME = "aio"
    SOURCES = ["aio/deepspeed_aio.cpp"]


class CPUAdamBuilder(OpBuilder):
    NAME = "cpu_adam"
    SOURCES = ["adam/cpu_adam.cpp"]


ALL_OPS = {b.NAME: b for b in (AsyncIOBuilder, CPUAdamBuilder)}


def op_report() -> List[tuple]:
    """(name, compatible, built) rows — the ``ds_report`` op table."""
    rows = []
    for name, cls in ALL_OPS.items():
        b = cls()
        rows.append((name, b.is_compatible(), os.path.exists(b.so_path())))
    return rows
