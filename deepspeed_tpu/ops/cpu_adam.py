"""Host-side SIMD optimizers over numpy shards (ctypes wrappers).

Analog of reference ``ops/adam/cpu_adam.py`` (DeepSpeedCPUAdam:12),
``ops/adagrad/cpu_adagrad.py`` and the host half of ``ops/lamb``: the
optimizer step runs on TPU-VM host cores over fp32 master shards living in
host DRAM (ZeRO-Offload), leaving HBM for params/activations. The native
kernels live in ``csrc/adam/cpu_adam.cpp`` (OpenMP + auto-vectorized AVX).
"""

from __future__ import annotations

import ctypes
from typing import Dict, List, Optional

import numpy as np

from .op_builder import CPUAdamBuilder


def _lib():
    lib = CPUAdamBuilder().load()
    if not getattr(lib, "_ds_typed", False):
        f32p = ctypes.POINTER(ctypes.c_float)
        u16p = ctypes.POINTER(ctypes.c_uint16)
        lib.ds_adam_step.argtypes = [f32p, f32p, f32p, f32p, ctypes.c_int64,
                                     ctypes.c_int, ctypes.c_float, ctypes.c_float,
                                     ctypes.c_float, ctypes.c_float, ctypes.c_float,
                                     ctypes.c_int, ctypes.c_int]
        lib.ds_adagrad_step.argtypes = [f32p, f32p, f32p, ctypes.c_int64,
                                        ctypes.c_float, ctypes.c_float, ctypes.c_float]
        lib.ds_lamb_phase1.argtypes = [f32p, f32p, f32p, f32p, f32p, ctypes.c_int64,
                                       ctypes.c_int, ctypes.c_float, ctypes.c_float,
                                       ctypes.c_float, ctypes.c_float]
        lib.ds_lamb_phase2.argtypes = [f32p, f32p, ctypes.c_int64, ctypes.c_float,
                                       ctypes.c_float]
        lib.ds_sumsq.restype = ctypes.c_double
        lib.ds_sumsq.argtypes = [f32p, ctypes.c_int64]
        lib.ds_f32_to_bf16.argtypes = [u16p, f32p, ctypes.c_int64]
        lib.ds_bf16_to_f32.argtypes = [f32p, u16p, ctypes.c_int64]
        lib._ds_typed = True
    return lib


def _f32p(a: np.ndarray):
    assert a.dtype == np.float32 and a.flags["C_CONTIGUOUS"]
    return a.ctypes.data_as(ctypes.POINTER(ctypes.c_float))


def f32_to_bf16(src: np.ndarray, out: Optional[np.ndarray] = None) -> np.ndarray:
    """Round-to-nearest-even fp32→bf16 on host (returns uint16 view array)."""
    lib = _lib()
    flat = np.ascontiguousarray(src, np.float32).ravel()
    if out is None:
        out = np.empty(flat.shape, np.uint16)
    lib.ds_f32_to_bf16(out.ctypes.data_as(ctypes.POINTER(ctypes.c_uint16)), _f32p(flat), flat.size)
    return out.reshape(src.shape)


def bf16_to_f32(src: np.ndarray) -> np.ndarray:
    lib = _lib()
    flat = np.ascontiguousarray(src, np.uint16).ravel()
    out = np.empty(flat.shape, np.float32)
    lib.ds_bf16_to_f32(_f32p(out), flat.ctypes.data_as(ctypes.POINTER(ctypes.c_uint16)), flat.size)
    return out.reshape(src.shape)


class DeepSpeedCPUAdam:
    """Adam/AdamW stepping flat fp32 host shards in place.

    One instance per parameter group; ``step(params, grads)`` mutates params
    and internal moments. Matches reference DeepSpeedCPUAdam semantics
    (bias correction, adamw_mode) within fp32 rounding.
    """

    def __init__(self, lr: float = 1e-3, betas=(0.9, 0.999), eps: float = 1e-8,
                 weight_decay: float = 0.0, adamw_mode: bool = True,
                 bias_correction: bool = True):
        self.lr = lr
        self.beta1, self.beta2 = betas
        self.eps = eps
        self.weight_decay = weight_decay
        self.adamw_mode = adamw_mode
        self.bias_correction = bias_correction
        # per-shard step counts: bias correction must track each shard's own
        # update count (reference keeps per-param state['step'], cpu_adam.py:163)
        self._step: Dict[int, int] = {}
        self._m: Dict[int, np.ndarray] = {}
        self._v: Dict[int, np.ndarray] = {}

    def state_tensors(self, key: int, n: int):
        if key not in self._m:
            self._m[key] = np.zeros(n, np.float32)
            self._v[key] = np.zeros(n, np.float32)
            self._step[key] = 0
        return self._m[key], self._v[key]

    def step(self, params: np.ndarray, grads: np.ndarray, key: int = 0,
             lr: Optional[float] = None) -> None:
        assert params.shape == grads.shape
        m, v = self.state_tensors(key, params.size)
        self._step[key] += 1
        _lib().ds_adam_step(
            _f32p(params), _f32p(np.ascontiguousarray(grads, np.float32)),
            _f32p(m), _f32p(v), params.size, self._step[key],
            lr if lr is not None else self.lr, self.beta1, self.beta2,
            self.eps, self.weight_decay, int(self.adamw_mode),
            int(self.bias_correction))

    @property
    def step_count(self) -> int:
        """Max step across shards (informational)."""
        return max(self._step.values(), default=0)

    # state swap hooks used by the NVMe optimizer swapper
    def get_state(self, key: int) -> List[np.ndarray]:
        return [self._m[key], self._v[key],
                np.asarray([self._step.get(key, 0)], np.float32)]

    def set_state(self, key: int, tensors: List[np.ndarray]) -> None:
        self._m[key], self._v[key] = tensors[0], tensors[1]
        if len(tensors) > 2:
            self._step[key] = int(tensors[2][0])


class DeepSpeedCPUAdagrad:
    """Adagrad over flat fp32 host shards (reference cpu_adagrad.py:10)."""

    def __init__(self, lr: float = 1e-2, eps: float = 1e-10, weight_decay: float = 0.0):
        self.lr = lr
        self.eps = eps
        self.weight_decay = weight_decay
        self._sq: Dict[int, np.ndarray] = {}

    def step(self, params: np.ndarray, grads: np.ndarray, key: int = 0) -> None:
        if key not in self._sq:
            self._sq[key] = np.zeros(params.size, np.float32)
        _lib().ds_adagrad_step(
            _f32p(params), _f32p(np.ascontiguousarray(grads, np.float32)),
            _f32p(self._sq[key]), params.size, self.lr, self.eps, self.weight_decay)


class DeepSpeedCPULamb:
    """LAMB with per-tensor trust ratio on host shards (reference ops/lamb)."""

    def __init__(self, lr: float = 1e-3, betas=(0.9, 0.999), eps: float = 1e-6,
                 weight_decay: float = 0.0, min_trust: float = 0.01, max_trust: float = 10.0):
        self.lr = lr
        self.beta1, self.beta2 = betas
        self.eps = eps
        self.weight_decay = weight_decay
        self.min_trust = min_trust
        self.max_trust = max_trust
        self._step: Dict[int, int] = {}
        self._m: Dict[int, np.ndarray] = {}
        self._v: Dict[int, np.ndarray] = {}

    def step(self, params: np.ndarray, grads: np.ndarray, key: int = 0) -> None:
        lib = _lib()
        if key not in self._m:
            self._m[key] = np.zeros(params.size, np.float32)
            self._v[key] = np.zeros(params.size, np.float32)
            self._step[key] = 0
        self._step[key] += 1
        update = np.empty(params.size, np.float32)
        lib.ds_lamb_phase1(
            _f32p(params), _f32p(np.ascontiguousarray(grads, np.float32)),
            _f32p(self._m[key]), _f32p(self._v[key]), _f32p(update),
            params.size, self._step[key], self.beta1, self.beta2, self.eps,
            self.weight_decay)
        w_norm = float(np.sqrt(lib.ds_sumsq(_f32p(params), params.size)))
        u_norm = float(np.sqrt(lib.ds_sumsq(_f32p(update), params.size)))
        trust = 1.0
        if w_norm > 0 and u_norm > 0:
            trust = float(np.clip(w_norm / u_norm, self.min_trust, self.max_trust))
        lib.ds_lamb_phase2(_f32p(params), _f32p(update), params.size, self.lr, trust)
