from .sparse_attention_utils import (
    extend_position_embedding,
    pad_to_block_size,
    sparse_bert_module,
    unpad_sequence_output,
    update_tokenizer_model_max_length,
)
from .sparse_self_attention import SparseSelfAttention, sparse_attention
from .sparsity_config import (
    BigBirdSparsityConfig,
    BSLongformerSparsityConfig,
    DenseSparsityConfig,
    FixedSparsityConfig,
    SparsityConfig,
    VariableSparsityConfig,
    from_ds_config,
    layout_density,
    layout_to_dense_mask,
)

__all__ = [
    "BigBirdSparsityConfig",
    "BSLongformerSparsityConfig",
    "DenseSparsityConfig",
    "FixedSparsityConfig",
    "SparseSelfAttention",
    "SparsityConfig",
    "VariableSparsityConfig",
    "extend_position_embedding",
    "from_ds_config",
    "layout_density",
    "layout_to_dense_mask",
    "pad_to_block_size",
    "sparse_attention",
    "sparse_bert_module",
    "unpad_sequence_output",
    "update_tokenizer_model_max_length",
]
