from .sparse_self_attention import SparseSelfAttention, sparse_attention
from .sparsity_config import (
    BigBirdSparsityConfig,
    BSLongformerSparsityConfig,
    DenseSparsityConfig,
    FixedSparsityConfig,
    SparsityConfig,
    VariableSparsityConfig,
    from_ds_config,
    layout_density,
    layout_to_dense_mask,
)

__all__ = [
    "BigBirdSparsityConfig",
    "BSLongformerSparsityConfig",
    "DenseSparsityConfig",
    "FixedSparsityConfig",
    "SparseSelfAttention",
    "SparsityConfig",
    "VariableSparsityConfig",
    "from_ds_config",
    "layout_density",
    "layout_to_dense_mask",
    "sparse_attention",
]
