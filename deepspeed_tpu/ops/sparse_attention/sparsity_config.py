"""Block-sparsity pattern generators.

Analog of reference ``deepspeed/ops/sparse_attention/sparsity_config.py``
(743 LoC: DenseSparsityConfig, FixedSparsityConfig, BSLongformerSparsityConfig,
BigBirdSparsityConfig, VariableSparsityConfig). A config produces a *layout*:
a bool array [num_heads, n_blocks, n_blocks] marking which (query-block,
key-block) pairs are computed. The layout feeds either the Pallas block-sparse
kernel (skips inactive blocks entirely) or the masked-dense jnp reference.

Patterns (same vocabulary as the reference):
- **Dense**: everything active (causality applied at runtime).
- **Fixed** (Sparse Transformers): blocks attend locally within their stride
  window plus to designated global blocks (the tail blocks of each window);
  optionally different global choices per head.
- **BSLongformer**: sliding diagonal window + designated global blocks with
  full rows and columns.
- **BigBird**: sliding window + global first/last blocks + per-row random
  blocks.
- **Variable**: custom-size local windows + explicit global block indices.

All layouts are plain numpy (static at trace time).
"""

from __future__ import annotations

from typing import List, Optional, Sequence

import numpy as np


class SparsityConfig:
    """Base: common fields + helpers (reference SparsityConfig)."""

    def __init__(self, num_heads: int, block: int = 16, different_layout_per_head: bool = False):
        self.num_heads = num_heads
        self.block = block
        self.different_layout_per_head = different_layout_per_head

    def setup_layout(self, seq_len: int) -> np.ndarray:
        if seq_len % self.block != 0:
            raise ValueError(f"seq_len {seq_len} not a multiple of block {self.block}")
        n = seq_len // self.block
        return np.zeros((self.num_heads, n, n), dtype=bool)

    def make_layout(self, seq_len: int) -> np.ndarray:
        raise NotImplementedError


class DenseSparsityConfig(SparsityConfig):
    def make_layout(self, seq_len: int) -> np.ndarray:
        layout = self.setup_layout(seq_len)
        layout[:] = True
        return layout


class FixedSparsityConfig(SparsityConfig):
    """Sparse-Transformers-style fixed pattern (reference FixedSparsityConfig).

    Each block attends to all blocks of its own local stride window
    (``num_local_blocks``); additionally the last ``num_global_blocks`` of
    each window act as global summary blocks every later block attends to.
    ``attention='unidirectional'`` restricts to j <= i at runtime;
    ``horizontal_global_attention`` gives global blocks full rows too.
    ``num_different_global_patterns`` rotates which window-tail block is
    global across head groups.
    """

    def __init__(
        self,
        num_heads: int,
        block: int = 16,
        different_layout_per_head: bool = False,
        num_local_blocks: int = 4,
        num_global_blocks: int = 1,
        attention: str = "bidirectional",
        horizontal_global_attention: bool = False,
        num_different_global_patterns: int = 1,
    ):
        super().__init__(num_heads, block, different_layout_per_head)
        if attention not in ("unidirectional", "bidirectional"):
            raise ValueError(f"invalid attention {attention!r}")
        if horizontal_global_attention and attention != "bidirectional":
            raise ValueError("horizontal_global_attention requires bidirectional attention")
        if num_different_global_patterns > 1 and not different_layout_per_head:
            raise ValueError("num_different_global_patterns > 1 requires different_layout_per_head")
        if num_different_global_patterns > num_local_blocks // num_global_blocks:
            raise ValueError(
                f"num_different_global_patterns {num_different_global_patterns} exceeds "
                f"num_local_blocks/num_global_blocks = {num_local_blocks}/{num_global_blocks}"
            )
        self.num_local_blocks = num_local_blocks
        self.num_global_blocks = num_global_blocks
        self.attention = attention
        self.horizontal_global_attention = horizontal_global_attention
        self.num_different_global_patterns = num_different_global_patterns

    def make_layout(self, seq_len: int) -> np.ndarray:
        layout = self.setup_layout(seq_len)
        H, n, _ = layout.shape
        L, G = self.num_local_blocks, self.num_global_blocks
        for h in range(H):
            pat = (h % self.num_different_global_patterns) if self.different_layout_per_head else 0
            for i in range(n):
                w = i // L
                # local: own window
                lo = w * L
                layout[h, i, lo : min(lo + L, n)] = True
                # global columns: the pattern-selected tail blocks of every window
                for w2 in range(n // L + 1):
                    g_end = min((w2 + 1) * L, n)
                    g_start = max(0, g_end - G * (pat + 1))
                    g_stop = max(0, g_end - G * pat)
                    layout[h, i, g_start:g_stop] = True
            if self.horizontal_global_attention:
                for w2 in range(n // L + 1):
                    g_end = min((w2 + 1) * L, n)
                    pat0 = 0
                    g_start = max(0, g_end - G * (pat0 + 1))
                    layout[h, g_start:g_end, :] = True
        if self.attention == "unidirectional":
            tril = np.tril(np.ones((n, n), dtype=bool))
            layout &= tril[None]
        return layout


class BSLongformerSparsityConfig(SparsityConfig):
    """Block-sparse Longformer: sliding window + global blocks
    (reference BSLongformerSparsityConfig)."""

    def __init__(
        self,
        num_heads: int,
        block: int = 16,
        different_layout_per_head: bool = False,
        num_sliding_window_blocks: int = 3,
        global_block_indices: Sequence[int] = (0,),
        global_block_end_indices: Optional[Sequence[int]] = None,
        attention: str = "bidirectional",
    ):
        super().__init__(num_heads, block, different_layout_per_head)
        self.num_sliding_window_blocks = num_sliding_window_blocks
        self.global_block_indices = list(global_block_indices)
        self.global_block_end_indices = (
            list(global_block_end_indices) if global_block_end_indices else None
        )
        self.attention = attention

    def _global_ranges(self, n: int):
        if self.global_block_end_indices is None:
            return [(i, i + 1) for i in self.global_block_indices if i < n]
        return [
            (s, min(e, n))
            for s, e in zip(self.global_block_indices, self.global_block_end_indices)
            if s < n
        ]

    def make_layout(self, seq_len: int) -> np.ndarray:
        layout = self.setup_layout(seq_len)
        H, n, _ = layout.shape
        w = self.num_sliding_window_blocks // 2
        for i in range(n):
            layout[:, i, max(0, i - w) : min(n, i + w + 1)] = True
        for s, e in self._global_ranges(n):
            layout[:, :, s:e] = True  # global columns
            layout[:, s:e, :] = True  # global rows
        if self.attention == "unidirectional":
            layout &= np.tril(np.ones((n, n), dtype=bool))[None]
        return layout


class BigBirdSparsityConfig(SparsityConfig):
    """BigBird: window + global + random blocks (reference BigBirdSparsityConfig)."""

    def __init__(
        self,
        num_heads: int,
        block: int = 16,
        different_layout_per_head: bool = False,
        num_random_blocks: int = 1,
        num_sliding_window_blocks: int = 3,
        num_global_blocks: int = 1,
        attention: str = "bidirectional",
        seed: int = 0,
    ):
        super().__init__(num_heads, block, different_layout_per_head)
        self.num_random_blocks = num_random_blocks
        self.num_sliding_window_blocks = num_sliding_window_blocks
        self.num_global_blocks = num_global_blocks
        self.attention = attention
        self.seed = seed

    def make_layout(self, seq_len: int) -> np.ndarray:
        layout = self.setup_layout(seq_len)
        H, n, _ = layout.shape
        g, w = self.num_global_blocks, self.num_sliding_window_blocks // 2
        rng = np.random.RandomState(self.seed)
        for i in range(n):
            layout[:, i, max(0, i - w) : min(n, i + w + 1)] = True
        layout[:, :g, :] = True
        layout[:, :, :g] = True
        layout[:, -g:, :] = True
        layout[:, :, -g:] = True
        n_heads_random = H if self.different_layout_per_head else 1
        for h in range(n_heads_random):
            for i in range(n):
                k = min(self.num_random_blocks, n)
                cols = rng.choice(n, size=k, replace=False)
                layout[h, i, cols] = True
        if not self.different_layout_per_head:
            layout[1:] = layout[0]
        if self.attention == "unidirectional":
            layout &= np.tril(np.ones((n, n), dtype=bool))[None]
        return layout


class VariableSparsityConfig(SparsityConfig):
    """Custom local windows + explicit global blocks (reference
    VariableSparsityConfig). ``local_window_blocks`` lists consecutive window
    sizes from sequence start; the last size repeats to cover the rest."""

    def __init__(
        self,
        num_heads: int,
        block: int = 16,
        different_layout_per_head: bool = False,
        num_random_blocks: int = 0,
        local_window_blocks: Sequence[int] = (4,),
        global_block_indices: Sequence[int] = (0,),
        global_block_end_indices: Optional[Sequence[int]] = None,
        attention: str = "bidirectional",
        horizontal_global_attention: bool = False,
        seed: int = 0,
    ):
        super().__init__(num_heads, block, different_layout_per_head)
        self.num_random_blocks = num_random_blocks
        self.local_window_blocks = list(local_window_blocks)
        self.global_block_indices = list(global_block_indices)
        self.global_block_end_indices = (
            list(global_block_end_indices) if global_block_end_indices else None
        )
        self.attention = attention
        self.horizontal_global_attention = horizontal_global_attention
        self.seed = seed

    def make_layout(self, seq_len: int) -> np.ndarray:
        layout = self.setup_layout(seq_len)
        H, n, _ = layout.shape
        # local windows of varying size
        start = 0
        sizes = list(self.local_window_blocks)
        while start < n:
            size = sizes.pop(0) if sizes else self.local_window_blocks[-1]
            end = min(start + size, n)
            layout[:, start:end, start:end] = True
            start = end
        # globals
        if self.global_block_end_indices is None:
            ranges = [(i, i + 1) for i in self.global_block_indices if i < n]
        else:
            ranges = [
                (s, min(e, n))
                for s, e in zip(self.global_block_indices, self.global_block_end_indices)
                if s < n
            ]
        for s, e in ranges:
            layout[:, :, s:e] = True
            if self.horizontal_global_attention:
                layout[:, s:e, :] = True
        # random
        if self.num_random_blocks:
            rng = np.random.RandomState(self.seed)
            n_heads_random = H if self.different_layout_per_head else 1
            for h in range(n_heads_random):
                for i in range(n):
                    cols = rng.choice(n, size=min(self.num_random_blocks, n), replace=False)
                    layout[h, i, cols] = True
            if not self.different_layout_per_head:
                layout[1:] = layout[0]
        if self.attention == "unidirectional":
            layout &= np.tril(np.ones((n, n), dtype=bool))[None]
        return layout


def layout_to_dense_mask(layout: np.ndarray, block: int) -> np.ndarray:
    """[H, nQ, nK] block layout → [H, S, S] element mask."""
    return np.repeat(np.repeat(layout, block, axis=1), block, axis=2)


def layout_density(layout: np.ndarray) -> float:
    return float(layout.mean())


def from_ds_config(section, num_heads: int) -> SparsityConfig:
    """Map the ``sparse_attention`` config section (runtime/config.py
    SparseAttentionConfig; reference ``get_sparse_attention_config``,
    deepspeed/__init__.py + ops/sparse_attention) to a SparsityConfig.

    ``section`` may be the typed dataclass or a plain dict with the DS JSON
    keys (``mode`` selects the pattern class; remaining keys are that
    pattern's constructor args)."""
    get = section.get if isinstance(section, dict) else lambda k, d=None: getattr(section, k, d)
    mode = (get("mode", "fixed") or "fixed").lower()
    common = dict(
        num_heads=num_heads,
        block=int(get("block", 16)),
        different_layout_per_head=bool(get("different_layout_per_head", False)),
    )
    if mode == "dense":
        return DenseSparsityConfig(**common)
    if mode == "fixed":
        return FixedSparsityConfig(
            **common,
            num_local_blocks=int(get("num_local_blocks", 4)),
            num_global_blocks=int(get("num_global_blocks", 1)),
            attention=get("attention", "bidirectional"),
            horizontal_global_attention=bool(get("horizontal_global_attention", False)),
            num_different_global_patterns=int(get("num_different_global_patterns", 1)),
        )
    nrb = get("num_random_blocks", None)  # None = mode-specific default
    if mode == "bigbird":
        return BigBirdSparsityConfig(
            **common,
            num_random_blocks=1 if nrb is None else int(nrb),
            num_sliding_window_blocks=int(get("num_sliding_window_blocks", 3)),
            num_global_blocks=int(get("num_global_blocks", 1)),
            attention=get("attention", "bidirectional"),
        )
    if mode == "bslongformer":
        return BSLongformerSparsityConfig(
            **common,
            num_sliding_window_blocks=int(get("num_sliding_window_blocks", 3)),
            global_block_indices=get("global_block_indices", [0]) or [0],
            global_block_end_indices=get("global_block_end_indices", None),
            attention=get("attention", "bidirectional"),
        )
    if mode == "variable":
        return VariableSparsityConfig(
            **common,
            num_random_blocks=0 if nrb is None else int(nrb),
            local_window_blocks=get("local_window_blocks", [4]) or [4],
            global_block_indices=get("global_block_indices", [0]) or [0],
            global_block_end_indices=get("global_block_end_indices", None),
            attention=get("attention", "bidirectional"),
            horizontal_global_attention=bool(get("horizontal_global_attention", False)),
        )
    raise ValueError(f"unknown sparse_attention mode {mode!r}")
