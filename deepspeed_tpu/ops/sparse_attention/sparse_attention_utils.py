"""Model-integration helpers for block-sparse attention.

Analog of reference ``ops/sparse_attention/sparse_attention_utils.py:1-225``
(SparseAttentionUtils): pad ragged real-model inputs up to the kernel's
block granularity, unpad the outputs, extend position embeddings past the
pretrained window, and convert a (HF) BERT into a sparse-attention model.
The reference mutates live torch modules; here models are functional, so
"replacement" = building the same model config with ``attn_impl="sparse"``
(models/bert.py routes attention through the Pallas block-sparse kernel)
and the tensor helpers are pure functions usable inside or outside jit.
"""

from __future__ import annotations

from typing import Any, Optional, Tuple

import jax.numpy as jnp
import numpy as np

PyTree = Any


def pad_to_block_size(
    block_size: int,
    input_ids,
    attention_mask=None,
    token_type_ids=None,
    position_ids=None,
    pad_token_id: int = 0,
) -> Tuple[int, Any, Any, Any, Any]:
    """Pad ``[B, S]`` inputs so S becomes a multiple of ``block_size``
    (reference SparseAttentionUtils.pad_to_block_size:151 — the kernels
    require whole blocks). Returns ``(pad_len, input_ids, attention_mask,
    token_type_ids, position_ids)`` with every given tensor padded:

    - input_ids / token_type_ids with ``pad_token_id`` / 0,
    - attention_mask with 0 (padded keys masked out),
    - position_ids by continuing the running index (keeps wpe lookups valid).
    """
    S = input_ids.shape[1]
    pad_len = (-S) % block_size
    if pad_len == 0:
        return 0, input_ids, attention_mask, token_type_ids, position_ids

    def pad(x, value):
        if x is None:
            return None
        widths = [(0, 0)] * x.ndim
        widths[1] = (0, pad_len)
        return jnp.pad(jnp.asarray(x), widths, constant_values=value)

    input_ids = pad(input_ids, pad_token_id)
    attention_mask = pad(attention_mask, 0)
    token_type_ids = pad(token_type_ids, 0)
    if position_ids is not None:
        tail = jnp.arange(S, S + pad_len, dtype=jnp.asarray(position_ids).dtype)
        position_ids = jnp.concatenate(
            [jnp.asarray(position_ids), jnp.broadcast_to(tail, (position_ids.shape[0], pad_len))],
            axis=1,
        )
    return pad_len, input_ids, attention_mask, token_type_ids, position_ids


def unpad_sequence_output(pad_len: int, sequence_output):
    """Strip the padding positions added by :func:`pad_to_block_size`
    (reference :210)."""
    if pad_len == 0:
        return sequence_output
    return sequence_output[:, :-pad_len]


def extend_position_embedding(params: PyTree, max_position: int) -> PyTree:
    """Extend ``wpe`` beyond the pretrained window by tiling the learned
    table (reference :19 copies the original weights k times — positions
    past the window reuse the pretrained positional geometry). Returns a
    new param tree; ``max_position`` must be a multiple-extension target."""
    wpe = np.asarray(params["wpe"])
    orig = wpe.shape[0]
    assert max_position > orig, (max_position, orig)
    reps = -(-max_position // orig)  # ceil
    new = np.concatenate([wpe] * reps, axis=0)[:max_position]
    out = dict(params)
    out["wpe"] = jnp.asarray(new)
    return out


def update_tokenizer_model_max_length(tokenizer, max_position: int):
    """Reference :68 — keep the tokenizer's window in sync after
    :func:`extend_position_embedding`."""
    tokenizer.model_max_length = max_position
    if hasattr(tokenizer, "init_kwargs"):
        tokenizer.init_kwargs["model_max_length"] = max_position
    return tokenizer


def sparse_bert_module(name_or_cfg="bert-large", sparsity_config=None,
                       **overrides):
    """Build our BERT with block-sparse self-attention (the functional
    analog of reference replace_model_self_attention_with_sparse_self_
    attention:85). ``name_or_cfg``: a models/bert preset name or a
    BertConfig; returns ``(cfg, ModuleSpec)``."""
    from ...models import bert

    if isinstance(name_or_cfg, str):
        cfg = bert.get_config(
            name_or_cfg, attn_impl="sparse",
            sparsity_config=sparsity_config, **overrides,
        )
    else:
        import dataclasses

        cfg = dataclasses.replace(
            name_or_cfg, attn_impl="sparse", sparsity_config=sparsity_config,
            **overrides,
        )
    return cfg, bert.make_module(cfg)
