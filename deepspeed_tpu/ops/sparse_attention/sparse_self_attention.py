"""Sparse self-attention over a block-sparsity config.

Analog of reference ``ops/sparse_attention/sparse_self_attention.py``
(SparseSelfAttention:11) which dispatches to the Triton block-sparse
matmul/softmax kernels. Here:

- ``impl='pallas'``: the block-sparse flash kernel
  (``ops/pallas/block_sparse_attention.py``) — inactive blocks are never
  touched, compute scales with layout density.
- ``impl='jnp'``: masked dense attention (exact reference semantics, used for
  parity tests and CPU).
- ``impl='auto'``: pallas on TPU, jnp elsewhere.
"""

from __future__ import annotations

from typing import Any, Optional

import jax
import jax.numpy as jnp
import numpy as np

from .sparsity_config import SparsityConfig, layout_to_dense_mask

NEG_INF = -1e30


def _dense_masked(q, k, v, mask_hss: np.ndarray, causal: bool, sm_scale: float,
                  key_mask=None):
    """[B,S,H,D] dense attention under an [H,S,S] element mask (reference
    path); optional [B,S] key padding mask (1 = attend) ANDed in."""
    B, S, H, D = q.shape
    scores = jnp.einsum("bshd,bthd->bhst", q.astype(jnp.float32), k.astype(jnp.float32)) * sm_scale
    mask = jnp.asarray(mask_hss)[None]  # [1,H,S,S]
    if causal:
        tri = jnp.tril(jnp.ones((S, S), bool))
        mask = mask & tri[None, None]
    if key_mask is not None:
        mask = mask & jnp.asarray(key_mask).astype(bool)[:, None, None, :]
    scores = jnp.where(mask, scores, NEG_INF)
    probs = jax.nn.softmax(scores, axis=-1)
    # fully-masked rows (possible in exotic layouts): zero them like flash does
    any_active = jnp.any(mask, axis=-1, keepdims=True)
    probs = jnp.where(any_active, probs, 0.0)
    return jnp.einsum("bhst,bthd->bshd", probs.astype(v.dtype), v)


def sparse_attention(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    sparsity_config: SparsityConfig,
    causal: bool = False,
    sm_scale: Optional[float] = None,
    impl: str = "auto",
    interpret: bool = False,
    key_mask: Optional[jnp.ndarray] = None,
) -> jnp.ndarray:
    """q/k/v: [B, S, H, D] → [B, S, H, D]. ``key_mask`` [B,S] (1 = attend)
    masks padded keys — ragged real-model inputs padded by
    ``sparse_attention_utils.pad_to_block_size``. The Pallas kernel has no
    mask input, so a mask routes to the jnp path (same contract as
    ``ops.attention.bidirectional_attention``)."""
    B, S, H, D = q.shape
    assert H == sparsity_config.num_heads, (H, sparsity_config.num_heads)
    scale = sm_scale if sm_scale is not None else 1.0 / (D**0.5)
    layout = sparsity_config.make_layout(S)

    if impl == "auto":
        impl = "pallas" if jax.default_backend() == "tpu" else "jnp"
    if impl == "pallas" and key_mask is None:
        from ..pallas.block_sparse_attention import block_sparse_attention

        return block_sparse_attention(
            q, k, v, layout, sparsity_config.block,
            causal=causal, sm_scale=scale, interpret=interpret,
        )
    if impl == "pallas" and key_mask is not None and S >= 2048:
        # the long-sequence regime the kernel exists for: make the O(S^2)
        # dense fallback loud instead of silent (drop the mask — e.g. run
        # unpadded full-length batches — to regain the kernel path)
        import warnings

        warnings.warn(
            f"sparse_attention: key_mask at S={S} routes to the dense jnp "
            "fallback (the Pallas block-sparse kernel has no mask input); "
            "materializes [B,H,S,S] scores"
        )
    mask = layout_to_dense_mask(layout, sparsity_config.block)
    return _dense_masked(q, k, v, mask, causal, scale, key_mask=key_mask)


class SparseSelfAttention:
    """Callable module mirroring the reference class surface."""

    def __init__(
        self,
        sparsity_config: Optional[SparsityConfig] = None,
        attn_mask_mode: str = "mul",
        max_seq_length: int = 2048,
        impl: str = "auto",
    ):
        from .sparsity_config import FixedSparsityConfig

        self.sparsity_config = sparsity_config or FixedSparsityConfig(num_heads=4)
        self.attn_mask_mode = attn_mask_mode
        self.max_seq_length = max_seq_length
        self.impl = impl

    def __call__(self, query, key, value, causal: bool = True, sm_scale: Optional[float] = None):
        return sparse_attention(
            query, key, value, self.sparsity_config,
            causal=causal, sm_scale=sm_scale, impl=self.impl,
        )
