"""Sparse self-attention over a block-sparsity config.

Analog of reference ``ops/sparse_attention/sparse_self_attention.py``
(SparseSelfAttention:11) which dispatches to the Triton block-sparse
matmul/softmax kernels. Here:

- ``impl='pallas'``: the block-sparse flash kernel
  (``ops/pallas/block_sparse_attention.py``) — inactive blocks are never
  touched, compute scales with layout density.
- ``impl='jnp'``: masked dense attention (exact reference semantics, used for
  parity tests and CPU).
- ``impl='auto'``: pallas on TPU, jnp elsewhere.
"""

from __future__ import annotations

from typing import Any, Optional

import jax
import jax.numpy as jnp
import numpy as np

from .sparsity_config import SparsityConfig, layout_to_dense_mask

NEG_INF = -1e30


def _dense_masked(q, k, v, mask_hss: np.ndarray, causal: bool, sm_scale: float):
    """[B,S,H,D] dense attention under an [H,S,S] element mask (reference path)."""
    B, S, H, D = q.shape
    scores = jnp.einsum("bshd,bthd->bhst", q.astype(jnp.float32), k.astype(jnp.float32)) * sm_scale
    mask = jnp.asarray(mask_hss)[None]  # [1,H,S,S]
    if causal:
        tri = jnp.tril(jnp.ones((S, S), bool))
        mask = mask & tri[None, None]
    scores = jnp.where(mask, scores, NEG_INF)
    probs = jax.nn.softmax(scores, axis=-1)
    # fully-masked rows (possible in exotic layouts): zero them like flash does
    any_active = jnp.any(mask, axis=-1, keepdims=True)
    probs = jnp.where(any_active, probs, 0.0)
    return jnp.einsum("bhst,bthd->bshd", probs.astype(v.dtype), v)


def sparse_attention(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    sparsity_config: SparsityConfig,
    causal: bool = False,
    sm_scale: Optional[float] = None,
    impl: str = "auto",
    interpret: bool = False,
) -> jnp.ndarray:
    """q/k/v: [B, S, H, D] → [B, S, H, D]."""
    B, S, H, D = q.shape
    assert H == sparsity_config.num_heads, (H, sparsity_config.num_heads)
    scale = sm_scale if sm_scale is not None else 1.0 / (D**0.5)
    layout = sparsity_config.make_layout(S)

    if impl == "auto":
        impl = "pallas" if jax.default_backend() == "tpu" else "jnp"
    if impl == "pallas":
        from ..pallas.block_sparse_attention import block_sparse_attention

        return block_sparse_attention(
            q, k, v, layout, sparsity_config.block,
            causal=causal, sm_scale=scale, interpret=interpret,
        )
    mask = layout_to_dense_mask(layout, sparsity_config.block)
    return _dense_masked(q, k, v, mask, causal, scale)


class SparseSelfAttention:
    """Callable module mirroring the reference class surface."""

    def __init__(
        self,
        sparsity_config: Optional[SparsityConfig] = None,
        attn_mask_mode: str = "mul",
        max_seq_length: int = 2048,
        impl: str = "auto",
    ):
        from .sparsity_config import FixedSparsityConfig

        self.sparsity_config = sparsity_config or FixedSparsityConfig(num_heads=4)
        self.attn_mask_mode = attn_mask_mode
        self.max_seq_length = max_seq_length
        self.impl = impl

    def __call__(self, query, key, value, causal: bool = True, sm_scale: Optional[float] = None):
        return sparse_attention(
            query, key, value, self.sparsity_config,
            causal=causal, sm_scale=sm_scale, impl=self.impl,
        )
