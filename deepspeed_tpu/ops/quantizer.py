"""Group-wise symmetric int8 weight quantization (weight-only inference).

Analog of reference ``deepspeed/ops/quantizer`` + ``csrc/quantization/``
(quantizer.cu, 1037 LoC of symmetric/asymmetric kernels) and the inference
``GroupQuantizer`` (module_inject/replace_module.py:139). On TPU the
quant/dequant arithmetic is ordinary XLA ops fused into the surrounding
matmul; what must be engineered is the storage format (int8 + per-group
scales → ~4x HBM and bandwidth savings) and the model-side hook
(``maybe_dequantize``) that lets one forward serve both full-precision and
quantized param trees.

Scheme: groups along the input (contraction) dimension of each weight —
``w[..., I, O] → q[..., G, I/G, O] int8`` with fp scale ``[..., G, 1, O]`` —
i.e. per-(group, output-channel) scales, symmetric, round-to-nearest.
"""

from __future__ import annotations

from typing import Any, NamedTuple

import jax
import jax.numpy as jnp

PyTree = Any


class QuantizedWeight(NamedTuple):
    """int8 weight + per-group scales; a pytree node (leaves: q, scale)."""

    q: jnp.ndarray  # [..., G, I/G, O] int8
    scale: jnp.ndarray  # [..., G, 1, O] float
    # original [..., I, O] shape is recovered as q.reshape(*q.shape[:-3], -1, O)


def quantize(w: jnp.ndarray, groups: int = 64, scale_dtype=jnp.bfloat16) -> QuantizedWeight:
    """Symmetric group int8 quantization of ``w [..., I, O]``."""
    *lead, I, O = w.shape
    g = min(groups, I)
    while I % g:  # largest divisor of I not above requested groups
        g -= 1
    wg = w.reshape(*lead, g, I // g, O).astype(jnp.float32)
    amax = jnp.max(jnp.abs(wg), axis=-2, keepdims=True)
    scale = jnp.where(amax > 0, amax / 127.0, 1.0)
    q = jnp.clip(jnp.round(wg / scale), -127, 127).astype(jnp.int8)
    return QuantizedWeight(q=q, scale=scale.astype(scale_dtype))


def dequantize(qw: QuantizedWeight, dtype=jnp.float32) -> jnp.ndarray:
    *lead, g, gsz, O = qw.q.shape
    w = qw.q.astype(jnp.float32) * qw.scale.astype(jnp.float32)
    return w.reshape(*lead, g * gsz, O).astype(dtype)


def maybe_dequantize(x, dtype=None):
    """Model-side hook: pass arrays through, expand QuantizedWeight."""
    if isinstance(x, QuantizedWeight):
        return dequantize(x, dtype or x.scale.dtype)
    return x


def quantize_tree(params: PyTree, groups: int = 64, dtype=jnp.bfloat16) -> PyTree:
    """Quantize the stacked transformer matmul weights (ndim >= 3 float
    leaves — the [L, I, O] blocks); cast everything else to ``dtype``.
    Embeddings ([V, E], ndim 2) stay full precision like the reference
    (only attention/MLP tensors go through GroupQuantizer)."""

    def visit(x):
        if isinstance(x, jnp.ndarray) and jnp.issubdtype(x.dtype, jnp.floating):
            if x.ndim >= 3:
                return quantize(x, groups=groups, scale_dtype=dtype)
            return x.astype(dtype)
        return x

    return jax.tree.map(visit, params)


def quantization_error(w: jnp.ndarray, groups: int = 64) -> float:
    """Relative L2 reconstruction error (diagnostic, reference quantizer
    tests assert bounded error)."""
    deq = dequantize(quantize(w, groups=groups), jnp.float32)
    return float(jnp.linalg.norm(deq - w) / (jnp.linalg.norm(w) + 1e-12))
