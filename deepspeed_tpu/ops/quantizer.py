"""Group-wise int8 weight quantization (weight-only inference + training).

Analog of reference ``deepspeed/ops/quantizer`` + ``csrc/quantization/``
(quantizer.cu:1037 — symmetric/asymmetric kernels with round-to-nearest AND
stochastic-rounding variants) and the inference ``GroupQuantizer``
(module_inject/replace_module.py:139). On TPU the quant/dequant arithmetic
is ordinary XLA ops fused into the surrounding matmul — including the
stochastic rounding, which is one uniform draw + floor and fuses the same
way, so the reference's dedicated SR CUDA kernels need no Pallas analog;
what must be engineered is the storage format (int8 + per-group scales →
~4x HBM and bandwidth savings) and the model-side hook
(``maybe_dequantize``) that lets one forward serve both full-precision and
quantized param trees.

Scheme: groups along the input (contraction) dimension of each weight —
``w[..., I, O] → q[..., G, I/G, O] int8`` with fp scale ``[..., G, 1, O]`` —
i.e. per-(group, output-channel) scales, symmetric round-to-nearest by
default; ``key=`` engages unbiased stochastic rounding
(``E[dequant(q)] == w``, the property MoQ low-bit training relies on), and
``quantize_asym`` adds the zero-point variant.
"""

from __future__ import annotations

from typing import Any, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp

# ONE block-scale codec (ISSUE 12): the scale/round/clip rule lives in
# comm/compressed.py and is re-exported here so the weight quantizer, the
# KV page codec below, and the compressed collectives can never drift —
# the shared round-trip bound test exercises it through both import paths.
from ..comm.compressed import dequantize_blocks, quantize_blocks

__all__ = [
    "QuantizedWeight", "AsymQuantizedWeight", "quantize", "quantize_asym",
    "dequantize", "dequantize_asym", "maybe_dequantize", "quantize_tree",
    "quantization_error", "quantize_blocks", "dequantize_blocks",
    "quantize_kv_pages", "dequantize_kv_pages", "kv_page_scale",
    "quantize_kv_token",
]

PyTree = Any


class QuantizedWeight(NamedTuple):
    """int8 weight + per-group scales; a pytree node (leaves: q, scale)."""

    q: jnp.ndarray  # [..., G, I/G, O] int8
    scale: jnp.ndarray  # [..., G, 1, O] float
    # original [..., I, O] shape is recovered as q.reshape(*q.shape[:-3], -1, O)


class AsymQuantizedWeight(NamedTuple):
    """Asymmetric variant: int8 codes + per-group (scale, zero_point)."""

    q: jnp.ndarray  # [..., G, I/G, O] int8 (codes 0..2^bits-1 biased by -128)
    scale: jnp.ndarray  # [..., G, 1, O] float
    zero_point: jnp.ndarray  # [..., G, 1, O] float (real value of code -128)


def _round(x: jnp.ndarray, key: Optional[jax.Array]) -> jnp.ndarray:
    """Round-to-nearest, or unbiased stochastic rounding when ``key`` given:
    floor(x + u), u ~ U[0,1) — E[result] = x exactly (reference
    quantizer.cu:1037 stochastic_rounding path)."""
    if key is None:
        return jnp.round(x)
    return jnp.floor(x + jax.random.uniform(key, x.shape, x.dtype))


def _grouped(w: jnp.ndarray, groups: int):
    *lead, I, O = w.shape
    g = min(groups, I)
    while I % g:  # largest divisor of I not above requested groups
        g -= 1
    return w.reshape(*lead, g, I // g, O).astype(jnp.float32)


def quantize(w: jnp.ndarray, groups: int = 64, scale_dtype=jnp.bfloat16,
             key: Optional[jax.Array] = None) -> QuantizedWeight:
    """Symmetric group int8 quantization of ``w [..., I, O]``; stochastic
    rounding when ``key`` is given.

    The round-to-nearest path delegates to the shared block codec
    (``comm/compressed.quantize_blocks``) — groups run along the
    contraction dim (axis -2), so the weight is transposed to put each
    group's elements on the trailing axis, coded, and transposed back;
    the codes and scales are bit-identical to the historical in-place
    formula. Stochastic rounding keeps its own arithmetic (the codec is
    deterministic by contract — the collectives depend on every rank
    producing identical codes)."""
    wg = _grouped(w, groups)
    if key is None:
        wt = jnp.swapaxes(wg, -1, -2)  # [..., G, O, I/G]: group elems last
        q, s = quantize_blocks(wt, "int8", wt.shape[-1])
        # s: [..., G, O, 1] (one block per row) -> the [..., G, 1, O] layout
        return QuantizedWeight(
            q=jnp.swapaxes(q, -1, -2),
            scale=jnp.swapaxes(s, -1, -2).astype(scale_dtype),
        )
    amax = jnp.max(jnp.abs(wg), axis=-2, keepdims=True)
    scale = jnp.where(amax > 0, amax / 127.0, 1.0)
    q = jnp.clip(_round(wg / scale, key), -127, 127).astype(jnp.int8)
    return QuantizedWeight(q=q, scale=scale.astype(scale_dtype))


def quantize_asym(w: jnp.ndarray, groups: int = 64, scale_dtype=jnp.bfloat16,
                  key: Optional[jax.Array] = None) -> AsymQuantizedWeight:
    """Asymmetric group int8: codes span [min, max] exactly (non-centered
    distributions waste no range); stochastic rounding when ``key`` given."""
    wg = _grouped(w, groups)
    lo = jnp.min(wg, axis=-2, keepdims=True)
    hi = jnp.max(wg, axis=-2, keepdims=True)
    scale = jnp.where(hi > lo, (hi - lo) / 255.0, 1.0)
    q = jnp.clip(_round((wg - lo) / scale, key), 0, 255) - 128
    return AsymQuantizedWeight(
        q=q.astype(jnp.int8),
        scale=scale.astype(scale_dtype),
        zero_point=lo.astype(scale_dtype),
    )


def dequantize_asym(qw: AsymQuantizedWeight, dtype=jnp.float32) -> jnp.ndarray:
    *lead, g, gsz, O = qw.q.shape
    w = (qw.q.astype(jnp.float32) + 128.0) * qw.scale.astype(jnp.float32) \
        + qw.zero_point.astype(jnp.float32)
    return w.reshape(*lead, g * gsz, O).astype(dtype)


def dequantize(qw: QuantizedWeight, dtype=jnp.float32) -> jnp.ndarray:
    *lead, g, gsz, O = qw.q.shape
    w = qw.q.astype(jnp.float32) * qw.scale.astype(jnp.float32)
    return w.reshape(*lead, g * gsz, O).astype(dtype)


def maybe_dequantize(x, dtype=None):
    """Model-side hook: pass arrays through, expand quantized weights."""
    if isinstance(x, QuantizedWeight):
        return dequantize(x, dtype or x.scale.dtype)
    if isinstance(x, AsymQuantizedWeight):
        return dequantize_asym(x, dtype or x.scale.dtype)
    return x


def quantize_tree(params: PyTree, groups: int = 64, dtype=jnp.bfloat16,
                  key: Optional[jax.Array] = None) -> PyTree:
    """Quantize the stacked transformer matmul weights (ndim >= 3 float
    leaves — the [L, I, O] blocks); cast everything else to ``dtype``.
    Embeddings ([V, E], ndim 2) stay full precision like the reference
    (only attention/MLP tensors go through GroupQuantizer). ``key``
    engages stochastic rounding (fresh fold per leaf)."""
    box = [key]

    def visit(x):
        if isinstance(x, jnp.ndarray) and jnp.issubdtype(x.dtype, jnp.floating):
            if x.ndim >= 3:
                leaf_key = None
                if box[0] is not None:
                    box[0], leaf_key = jax.random.split(box[0])
                return quantize(x, groups=groups, scale_dtype=dtype, key=leaf_key)
            return x.astype(dtype)
        return x

    return jax.tree.map(visit, params)


# ---------------------------------------------------------------------------
# KV page codec (ISSUE 12): int8 KV cache pages with per-(page, kv-head)
# scales. A page's (page_size, head_dim) slab per head is ONE block of the
# shared codec — exact multiple by construction, so quantization is the
# zero-copy fast path of quantize_blocks.
# ---------------------------------------------------------------------------


def quantize_kv_pages(chunks: jnp.ndarray) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """``[..., KV, page, D]`` float K/V chunks -> (codes int8 same shape,
    scales ``[..., KV]`` fp32): one symmetric block scale per page per
    kv-head (``serving/kv_cache.init_pools`` keeps the scales beside the
    pool). Delegates to the shared block codec with block = page * D."""
    *lead, kv, page, d = chunks.shape
    flat = chunks.reshape(*lead, kv, page * d)
    q, s = quantize_blocks(flat, "int8", page * d)
    return q.reshape(chunks.shape), s.reshape(*lead, kv)


def dequantize_kv_pages(codes: jnp.ndarray, scales: jnp.ndarray) -> jnp.ndarray:
    """Inverse of :func:`quantize_kv_pages`: ``codes [..., KV, page, D]``
    int8 + ``scales [..., KV]`` -> fp32. A fresh pool's scale is 0, so
    never-written pages dequantize to exact zeros."""
    return codes.astype(jnp.float32) * scales[..., None, None]


def kv_page_scale(values: jnp.ndarray) -> jnp.ndarray:
    """The codec's scale for ``values [..., D]`` reduced over the trailing
    axis — the single-token write path uses it to ESTABLISH a page's scale
    from the first token written at offset 0 (the scale is then frozen for
    the page's lifetime, so later writes never re-code earlier positions:
    the order-independence the speculative-verify bit-equivalence contract
    rests on). Matches ``quantize_blocks``'s rule exactly: amax/127, zero
    content -> 1.0."""
    amax = jnp.max(jnp.abs(values.astype(jnp.float32)), axis=-1)
    return jnp.where(amax > 0, amax / 127.0, 1.0)


def quantize_kv_token(values: jnp.ndarray, scale: jnp.ndarray) -> jnp.ndarray:
    """Code one token's ``[..., D]`` K/V slab against an already-frozen page
    ``scale [...]`` (clipping saturates at the codec's qmax — the price of
    the frozen scale; the parity suite bounds the effect)."""
    y = values.astype(jnp.float32) / scale[..., None]
    return jnp.clip(jnp.round(y), -127.0, 127.0).astype(jnp.int8)


def quantization_error(w: jnp.ndarray, groups: int = 64) -> float:
    """Relative L2 reconstruction error (diagnostic, reference quantizer
    tests assert bounded error)."""
    deq = dequantize(quantize(w, groups=groups), jnp.float32)
    return float(jnp.linalg.norm(deq - w) / (jnp.linalg.norm(w) + 1e-12))
