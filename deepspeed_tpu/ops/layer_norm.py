"""Layer norm with fp32 statistics — the one shared implementation.

The reference's fused LN kernels accumulate mean/variance in fp32 regardless
of the activation dtype (``csrc/transformer/normalize_kernels.cu``); doing the
statistics in fp16 overflows the variance/rsqrt chain. Every model family
(gpt2/decoder/bert) routes through this helper so the numerics cannot drift
apart between copies.
"""

from __future__ import annotations

import jax.numpy as jnp
from jax import lax


def layer_norm(x, scale, bias, eps):
    xf = x.astype(jnp.float32)
    m = jnp.mean(xf, axis=-1, keepdims=True)
    v = jnp.var(xf, axis=-1, keepdims=True)
    y = (xf - m) * lax.rsqrt(v + eps)
    return (y * scale.astype(jnp.float32) + bias.astype(jnp.float32)).astype(x.dtype)


def rms_norm(x, scale, eps):
    """RMSNorm (no mean subtraction, no bias) with fp32 statistics — the
    LLaMA-family normalization."""
    xf = x.astype(jnp.float32)
    y = xf * lax.rsqrt(jnp.mean(xf * xf, axis=-1, keepdims=True) + eps)
    return (y * scale.astype(jnp.float32)).astype(x.dtype)
