"""Fused AdamW as a Pallas TPU kernel over flat parameter shards.

Capability analog of reference ``csrc/adam/multi_tensor_adam.cu:163`` +
``ops/adam/fused_adam.py:15`` (multi-tensor-apply fused CUDA Adam). Under XLA
the optax update already fuses into the train step, so this kernel exists to
answer SURVEY §2.7's own question — "Pallas fused optimizer kernel over flat
param shards (or jax.jit fused update — **measure**)" — with a measurement:
``benchmarks/fused_adam_bench.py`` times both at large param counts. The
number has NOT yet been captured on hardware (no working TPU window since
the harness landed — that file's RESULTS section tracks the status); optax
stays the default optimizer until the kernel measures a material edge.

Design: the update is purely elementwise and HBM-bandwidth-bound (reads
p,g,m,v + writes p,m,v = 28 B/param fp32). The kernel streams 2D tiles
through VMEM; hyperparameters arrive as a small traced vector so lr changes
never recompile. Bias correction follows optax/AdamW (mhat = m/(1-b1^t)).
"""

from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

LANES = 1024  # last-dim tile (multiple of the 128-lane VPU width)
ROWS = 8  # sublane tile rows per grid step


def _adam_kernel(scal_ref, p_ref, g_ref, m_ref, v_ref, op_ref, om_ref, ov_ref):
    lr = scal_ref[0]
    b1 = scal_ref[1]
    b2 = scal_ref[2]
    eps = scal_ref[3]
    wd = scal_ref[4]
    bc1 = scal_ref[5]  # 1 - b1**t
    bc2 = scal_ref[6]  # 1 - b2**t
    g = g_ref[...].astype(jnp.float32)
    m = b1 * m_ref[...] + (1.0 - b1) * g
    v = b2 * v_ref[...] + (1.0 - b2) * g * g
    update = (m / bc1) / (jnp.sqrt(v / bc2) + eps)
    p = p_ref[...]
    op_ref[...] = p - lr * (update + wd * p)
    om_ref[...] = m
    ov_ref[...] = v


def fused_adamw_flat(
    p: jnp.ndarray,
    g: jnp.ndarray,
    m: jnp.ndarray,
    v: jnp.ndarray,
    step: jnp.ndarray,
    lr,
    betas: Tuple[float, float] = (0.9, 0.999),
    eps: float = 1e-8,
    weight_decay: float = 0.0,
    interpret: bool = False,
) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """One AdamW step on a flat fp32 shard. Returns (p', m', v').

    ``step`` is the 1-based step count (traced i32/f32); ``lr`` may be traced.
    Grads may be bf16 (upcast in-kernel, the multi-tensor-apply behavior).
    """
    assert p.ndim == 1, "flat shards only (ravel the leaf)"
    n = p.shape[0]
    b1, b2 = float(betas[0]), float(betas[1])
    t = step.astype(jnp.float32)
    scal = jnp.stack(
        [
            jnp.asarray(lr, jnp.float32),
            jnp.float32(b1),
            jnp.float32(b2),
            jnp.float32(eps),
            jnp.float32(weight_decay),
            1.0 - jnp.float32(b1) ** t,
            1.0 - jnp.float32(b2) ** t,
        ]
    )

    tile = ROWS * LANES
    n_pad = (-n) % tile
    if n_pad:
        pad = lambda x: jnp.pad(x, (0, n_pad))
        p, g, m, v = pad(p), pad(g), pad(m), pad(v)
    rows = (n + n_pad) // LANES
    shape2d = (rows, LANES)
    p2, g2, m2, v2 = (x.reshape(shape2d) for x in (p, g, m, v))

    grid = (rows // ROWS,)
    block = pl.BlockSpec((ROWS, LANES), lambda i: (i, 0))
    scal_spec = pl.BlockSpec((7,), lambda i: (0,))
    out_shape = [jax.ShapeDtypeStruct(shape2d, jnp.float32)] * 3
    op, om, ov = pl.pallas_call(
        _adam_kernel,
        grid=grid,
        in_specs=[scal_spec, block, block, block, block],
        out_specs=[block, block, block],
        out_shape=out_shape,
        interpret=interpret,
    )(scal, p2, g2, m2, v2)
    unpad = lambda x: x.reshape(-1)[:n]
    return unpad(op), unpad(om), unpad(ov)


def fused_adamw_tree(params, grads, mu, nu, step, lr, betas=(0.9, 0.999), eps=1e-8, weight_decay=0.0, interpret=False):
    """Multi-tensor apply over a pytree: each leaf raveled through the kernel
    (the reference chunks many tensors into one launch; here each leaf is one
    pallas_call and XLA schedules them back-to-back)."""
    flat_p, tree = jax.tree.flatten(params)
    flat_g = jax.tree.leaves(grads)
    flat_m = jax.tree.leaves(mu)
    flat_v = jax.tree.leaves(nu)
    new_p, new_m, new_v = [], [], []
    for p, g, m, v in zip(flat_p, flat_g, flat_m, flat_v):
        sh = p.shape
        op, om, ov = fused_adamw_flat(
            p.reshape(-1), g.reshape(-1), m.reshape(-1), v.reshape(-1),
            step, lr, betas, eps, weight_decay, interpret=interpret,
        )
        new_p.append(op.reshape(sh))
        new_m.append(om.reshape(sh))
        new_v.append(ov.reshape(sh))
    unflat = functools.partial(jax.tree.unflatten, tree)
    return unflat(new_p), unflat(new_m), unflat(new_v)


def _lamb_stage1_kernel(scal_ref, p_ref, g_ref, m_ref, v_ref, u_ref, om_ref, ov_ref):
    b1 = scal_ref[0]
    b2 = scal_ref[1]
    eps = scal_ref[2]
    wd = scal_ref[3]
    bc1 = scal_ref[4]
    bc2 = scal_ref[5]
    g = g_ref[...].astype(jnp.float32)
    m = b1 * m_ref[...] + (1.0 - b1) * g
    v = b2 * v_ref[...] + (1.0 - b2) * g * g
    u_ref[...] = (m / bc1) / (jnp.sqrt(v / bc2) + eps) + wd * p_ref[...]
    om_ref[...] = m
    ov_ref[...] = v


def fused_lamb_flat(
    p: jnp.ndarray,
    g: jnp.ndarray,
    m: jnp.ndarray,
    v: jnp.ndarray,
    step: jnp.ndarray,
    lr,
    betas: Tuple[float, float] = (0.9, 0.999),
    eps: float = 1e-6,
    weight_decay: float = 0.0,
    min_trust: float = 0.01,
    max_trust: float = 10.0,
    interpret: bool = False,
) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """One LAMB step on a flat fp32 shard (reference
    ``csrc/lamb/fused_lamb_cuda_kernel.cu``: elementwise stage computing the
    Adam-style update direction runs in the kernel; the trust-ratio norms are
    tree-level reductions XLA already fuses, then the final scaled apply is a
    trivial fused axpy). Returns (p', m', v')."""
    assert p.ndim == 1
    n = p.shape[0]
    b1, b2 = float(betas[0]), float(betas[1])
    t = step.astype(jnp.float32)
    scal = jnp.stack(
        [
            jnp.float32(b1),
            jnp.float32(b2),
            jnp.float32(eps),
            jnp.float32(weight_decay),
            1.0 - jnp.float32(b1) ** t,
            1.0 - jnp.float32(b2) ** t,
        ]
    )
    tile = ROWS * LANES
    n_pad = (-n) % tile
    pg, gg, mg, vg = (jnp.pad(x, (0, n_pad)) if n_pad else x for x in (p, g, m, v))
    rows = (n + n_pad) // LANES
    shape2d = (rows, LANES)
    p2, g2, m2, v2 = (x.reshape(shape2d) for x in (pg, gg, mg, vg))
    grid = (rows // ROWS,)
    block = pl.BlockSpec((ROWS, LANES), lambda i: (i, 0))
    scal_spec = pl.BlockSpec((6,), lambda i: (0,))
    u2, om, ov = pl.pallas_call(
        _lamb_stage1_kernel,
        grid=grid,
        in_specs=[scal_spec, block, block, block, block],
        out_specs=[block, block, block],
        out_shape=[jax.ShapeDtypeStruct(shape2d, jnp.float32)] * 3,
        interpret=interpret,
    )(scal, p2, g2, m2, v2)
    unpad = lambda x: x.reshape(-1)[:n]
    u = unpad(u2)
    # trust ratio (XLA reductions; reference computes these with a two-pass
    # block reduction in the CUDA kernel)
    p_norm = jnp.linalg.norm(p)
    u_norm = jnp.linalg.norm(u)
    trust = jnp.where(
        (p_norm > 0.0) & (u_norm > 0.0),
        jnp.clip(p_norm / u_norm, min_trust, max_trust),
        1.0,
    )
    new_p = p - jnp.asarray(lr, jnp.float32) * trust * u
    return new_p, unpad(om), unpad(ov)
