"""Python handle over the native async-I/O engine (ctypes).

Analog of reference ``deepspeed_py_aio_handle.{h,cpp}`` (csrc/aio): an
``AsyncIOHandle`` with sync/async pread/pwrite of numpy buffers against local
NVMe files, plus aligned "pinned" host buffer allocation. Used by the
ZeRO-Infinity tensor swappers (``runtime/swap_tensor``).
"""

from __future__ import annotations

import ctypes
import os
from typing import Optional

import numpy as np

from .op_builder import AsyncIOBuilder


class AsyncIOHandle:
    """Thread-pooled async file I/O over host buffers.

    Parameters mirror the reference handle (block_size, queue_depth,
    thread_count — deepspeed_py_aio_handle.h:12 region).
    """

    @classmethod
    def from_config(cls, aio_cfg) -> Optional["AsyncIOHandle"]:
        """Build a handle from the ``aio`` config section (reference
        swap_tensor/aio_config.py), or return None when ``aio_cfg`` is None
        (callers then get each swapper's default handle).
        ``single_submit``/``overlap_events`` tune the reference's libaio
        submission batching; the thread-pool design here has no equivalent
        modes, so they are accepted and ignored."""
        if aio_cfg is None:
            return None
        return cls(
            block_size=int(aio_cfg.block_size),
            queue_depth=int(aio_cfg.queue_depth),
            thread_count=int(aio_cfg.thread_count),
        )

    def __init__(self, block_size: int = 1 << 20, queue_depth: int = 32,
                 thread_count: int = 8):
        self._lib = AsyncIOBuilder().load()
        lib = self._lib
        lib.aio_handle_new.restype = ctypes.c_void_p
        lib.aio_handle_new.argtypes = [ctypes.c_long, ctypes.c_int, ctypes.c_int]
        lib.aio_handle_free.argtypes = [ctypes.c_void_p]
        lib.aio_submit_pread.argtypes = [
            ctypes.c_void_p, ctypes.c_void_p, ctypes.c_char_p, ctypes.c_long, ctypes.c_long]
        lib.aio_submit_pwrite.argtypes = [
            ctypes.c_void_p, ctypes.c_void_p, ctypes.c_char_p, ctypes.c_long,
            ctypes.c_long, ctypes.c_int]
        lib.aio_wait.restype = ctypes.c_long
        lib.aio_wait.argtypes = [ctypes.c_void_p]
        lib.aio_pending.restype = ctypes.c_long
        lib.aio_pending.argtypes = [ctypes.c_void_p]
        lib.aio_alloc_aligned.restype = ctypes.c_void_p
        lib.aio_alloc_aligned.argtypes = [ctypes.c_long]
        lib.aio_free_aligned.argtypes = [ctypes.c_void_p]
        self._h = lib.aio_handle_new(block_size, queue_depth, thread_count)
        self.block_size = block_size
        self.queue_depth = queue_depth
        self.thread_count = thread_count

    # -- async API ---------------------------------------------------------
    def async_pread(self, buf: np.ndarray, path: str, file_offset: int = 0) -> None:
        assert buf.flags["C_CONTIGUOUS"]
        self._lib.aio_submit_pread(
            self._h, buf.ctypes.data_as(ctypes.c_void_p), path.encode(),
            buf.nbytes, file_offset)

    def async_pwrite(self, buf: np.ndarray, path: str, file_offset: int = 0,
                     fsync: bool = False) -> None:
        assert buf.flags["C_CONTIGUOUS"]
        self._lib.aio_submit_pwrite(
            self._h, buf.ctypes.data_as(ctypes.c_void_p), path.encode(),
            buf.nbytes, file_offset, int(fsync))

    def wait(self) -> int:
        """Block until all submitted ops retire; returns ops completed.

        Raises IOError if any op failed (negative return from native side)."""
        n = self._lib.aio_wait(self._h)
        if n < 0:
            raise IOError(f"aio: {-n} operation(s) failed")
        return n

    def pending(self) -> int:
        return self._lib.aio_pending(self._h)

    # -- sync convenience --------------------------------------------------
    def sync_pread(self, buf: np.ndarray, path: str, file_offset: int = 0) -> int:
        self.async_pread(buf, path, file_offset)
        return self.wait()

    def sync_pwrite(self, buf: np.ndarray, path: str, file_offset: int = 0,
                    fsync: bool = False) -> int:
        self.async_pwrite(buf, path, file_offset, fsync)
        return self.wait()

    def new_aligned_buffer(self, nbytes: int, dtype=np.uint8) -> np.ndarray:
        """4096-aligned host buffer suitable for O_DIRECT (pinned-buffer analog).

        The allocation is owned by the returned array: it is released when the
        array (and every view of it) is garbage-collected — NOT when the
        handle is freed, so buffers may safely outlive the handle."""
        import weakref

        ptr = self._lib.aio_alloc_aligned(nbytes)
        if not ptr:
            raise MemoryError("aio_alloc_aligned failed")
        raw = (ctypes.c_uint8 * nbytes).from_address(ptr)
        arr = np.frombuffer(raw, dtype=dtype)
        arr = arr.view()
        arr.flags.writeable = True
        # every numpy view's .base chain bottoms out at `raw` (numpy collapses
        # view bases to the buffer owner), so the finalizer fires only once no
        # array at all references the allocation
        weakref.finalize(raw, self._lib.aio_free_aligned, ptr)
        return arr

    def free(self):
        """Drain in-flight ops and destroy the native handle. Aligned buffers
        from ``new_aligned_buffer`` stay valid (freed by their own GC)."""
        if getattr(self, "_h", None):
            self.wait()
            self._lib.aio_handle_free(self._h)
            self._h = None

    def __del__(self):
        try:
            self.free()
        except Exception:
            pass
