"""deepspeed_tpu — a TPU-native large-model training & inference framework.

Brand-new implementation of the capabilities of DeepSpeed (reference:
OpenGPTX/DeepSpeed v0.7.3) designed for TPU from the ground up: JAX/XLA with
``pjit``-sharded state over a named device mesh, Pallas kernels for hot ops,
XLA collectives over ICI/DCN for communication, and host-side C++ for async
NVMe I/O. See SURVEY.md for the reference structural map.

Public API parity (reference ``deepspeed/__init__.py``):
- ``initialize``       (:51)  → engine construction
- ``init_inference``   (:225) → inference engine
- ``init_distributed`` (:28 re-export)
- ``add_config_arguments`` (:209)
- ``zero`` namespace (Init/GatheredParameters analogs)
"""

from __future__ import annotations

from typing import Any, Optional, Tuple

__version__ = "0.5.0"
__git_branch__ = "main"

from . import comm  # noqa: F401
from . import serving  # noqa: F401
from . import telemetry  # noqa: F401
from .comm.comm import init_distributed  # noqa: F401
from .module_inject import (  # noqa: F401
    replace_transformer_layer,
    revert_transformer_layer,
)
from .ops.transformer import (  # noqa: F401
    DeepSpeedTransformerConfig,
    DeepSpeedTransformerLayer,
)
from .runtime.config import DeepSpeedConfig, DeepSpeedConfigError  # noqa: F401
from .runtime.lr_schedules import add_tuning_arguments  # noqa: F401
from .utils.init_on_device import OnDevice  # noqa: F401
from .runtime.engine import DeepSpeedEngine  # noqa: F401
from .runtime.module import ModuleSpec  # noqa: F401
from .parallel.topology import (  # noqa: F401
    MeshSpec,
    PipeDataParallelTopology,
    PipeModelDataParallelTopology,
    ProcessTopology,
)
from .runtime.zero import partitioning as zero  # noqa: F401
from .utils.logging import log_dist, logger  # noqa: F401


def initialize(
    args: Any = None,
    model: Optional[ModuleSpec] = None,
    optimizer: Any = None,
    model_parameters: Any = None,
    training_data: Any = None,
    lr_scheduler: Any = None,
    mesh: Any = None,
    mpu: Any = None,
    dist_init_required: Optional[bool] = None,
    collate_fn: Any = None,
    config: Any = None,
    config_params: Any = None,
    seed: int = 0,
) -> Tuple[DeepSpeedEngine, Any, Any, Any]:
    """Create a :class:`DeepSpeedEngine` (reference ``deepspeed.initialize``).

    Args mirror the reference where the concept transfers:
      model: a :class:`ModuleSpec` (functional model bundle) — the analog of
        the reference's ``nn.Module``.
      model_parameters: optional pre-built param pytree (else ``model.init``
        runs sharded — the ``zero.Init`` analog).
      training_data: indexable dataset → a deterministic loader is built.
      lr_scheduler: a ``step -> lr`` callable overriding config ``scheduler``.
      mesh: a ``jax.sharding.Mesh`` (else built from config ``mesh`` section).
      config: path / dict / JSON string (ds_config.json schema).

    Returns ``(engine, optimizer, training_dataloader, lr_scheduler)``.
    """
    assert model is not None, "deepspeed_tpu.initialize: model is required"
    if config is None:
        config = config_params
    if config is None and args is not None and hasattr(args, "deepspeed_config") and args.deepspeed_config:
        config = args.deepspeed_config
    assert config is not None, "deepspeed_tpu.initialize: config is required"

    if dist_init_required is None or dist_init_required:
        if not comm.comm.is_initialized():
            init_distributed()

    # pass the raw document through — the engine finalizes the batch triple
    # against the actual dp mesh size
    engine = DeepSpeedEngine(
        model=model,
        config=config,
        mesh=mesh,
        params=model_parameters,
        lr_schedule=lr_scheduler if callable(lr_scheduler) else None,
        seed=seed,
        training_data=training_data,
        collate_fn=collate_fn,
    )

    # monitor wiring (reference engine.py:278 MonitorMaster)
    try:
        from .monitor.monitor import MonitorMaster

        monitor = MonitorMaster(engine.config)
        engine.monitor = monitor if monitor.enabled else None
    except Exception:
        engine.monitor = None
    if engine.monitor is not None and engine.telemetry is not None:
        # registry gauges fan out to every Monitor backend at steps_per_print
        engine.telemetry.attach_monitor(engine.monitor)

    return engine, engine.optimizer, engine.training_dataloader, engine.lr_schedule


def init_inference(model=None, **kwargs):
    """Create an inference engine (reference ``deepspeed.init_inference``)."""
    from .inference.engine import InferenceEngine

    return InferenceEngine(model=model, **kwargs)


def add_config_arguments(parser):
    """Add --deepspeed / --deepspeed_config CLI args (reference __init__.py:209)."""
    group = parser.add_argument_group("DeepSpeed-TPU", "DeepSpeed-TPU configurations")
    group.add_argument("--deepspeed", default=False, action="store_true")
    group.add_argument("--deepspeed_config", default=None, type=str)
    group.add_argument("--deepscale", default=False, action="store_true", help=argparse_suppress())
    group.add_argument("--local_rank", type=int, default=-1)
    return parser


def argparse_suppress():
    import argparse

    return argparse.SUPPRESS
