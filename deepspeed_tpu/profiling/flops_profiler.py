"""FLOPs / params / latency profiler.

Analog of reference ``deepspeed/profiling/flops_profiler/profiler.py``
(FlopsProfiler:17, 1315 LoC). The reference monkey-patches
``torch.nn.functional`` with flop-counting shims and walks module hooks. On
TPU the compiler already knows: ``jit(fn).lower(...).compile().cost_analysis()``
returns XLA's own flop/byte counts for the exact fused executable — more
truthful than shim arithmetic, and free of instrumentation overhead. This
module wraps that, adds measured latency (achieved FLOPS), and prints the
reference-style summary.
"""

from __future__ import annotations

import time
from typing import Any, Callable, Dict, Optional, Tuple

import jax
import numpy as np

PyTree = Any


def _num_params(params: PyTree) -> int:
    return sum(int(np.prod(x.shape)) if hasattr(x, "shape") else 1 for x in jax.tree.leaves(params))


def _cost_analysis(fn: Callable, *args) -> Dict[str, float]:
    compiled = jax.jit(fn).lower(*args).compile()
    ca = compiled.cost_analysis()
    if isinstance(ca, (list, tuple)):  # older jax returns [dict]
        ca = ca[0] if ca else {}
    return dict(ca or {})


def verify_against_hlo(fn: Callable, *args, tolerance: float = 0.05) -> Dict[str, Any]:
    """Reconcile this profiler's flop source (XLA ``cost_analysis``) with the
    telemetry HLO cost analyzer's independent instruction walk
    (``telemetry/introspect.py``) on the same compiled program.

    Two independent counters agreeing is the guard against both failure
    modes: cost_analysis silently under-counting (scan bodies counted once,
    Pallas calls invisible) and the text walk mis-parsing an opcode. Both
    sides count a loop body once (the analyzer's loop multiplier is
    deliberately not applied), so the comparison is apples-to-apples even
    for scanned programs. Returns ``{xla_flops, hlo_flops, rel_err, agree,
    categories}``; ``agree`` is ``rel_err <= tolerance`` (default 5%).
    """
    from ..telemetry import introspect as _intro

    compiled = jax.jit(fn).lower(*args).compile()
    ca = compiled.cost_analysis()
    if isinstance(ca, (list, tuple)):
        ca = ca[0] if ca else {}
    xla_flops = float((ca or {}).get("flops", 0.0))
    ana = _intro.analyze_compiled(compiled, loop_iterations=1)
    hlo_flops = ana.total_flops
    rel = (
        abs(hlo_flops - xla_flops) / xla_flops if xla_flops > 0
        else (0.0 if hlo_flops == 0 else float("inf"))
    )
    return {
        "xla_flops": xla_flops,
        "hlo_flops": hlo_flops,
        "rel_err": rel,
        "agree": rel <= tolerance,
        "tolerance": tolerance,
        "categories": {k: v.to_dict() for k, v in ana.categories.items()},
    }


def get_model_profile(
    fn: Callable,
    args: Tuple,
    params: Optional[PyTree] = None,
    warmup: int = 1,
    runs: int = 3,
) -> Dict[str, float]:
    """Profile a jittable ``fn(*args)``.

    Returns {flops, bytes_accessed, params, latency_s, achieved_tflops}.
    ``flops`` comes from XLA cost analysis of the compiled executable.
    """
    cost = _cost_analysis(fn, *args)
    flops = float(cost.get("flops", 0.0))
    jfn = jax.jit(fn)
    out = jfn(*args)
    jax.block_until_ready(out)
    for _ in range(max(0, warmup - 1)):
        jax.block_until_ready(jfn(*args))
    t0 = time.perf_counter()
    for _ in range(runs):
        out = jfn(*args)
    jax.block_until_ready(out)
    latency = (time.perf_counter() - t0) / runs
    return {
        "flops": flops,
        "macs": flops / 2.0,
        "bytes_accessed": float(cost.get("bytes accessed", 0.0)),
        "params": _num_params(params) if params is not None else 0,
        "latency_s": latency,
        "achieved_tflops": flops / latency / 1e12 if latency > 0 else 0.0,
    }


class FlopsProfiler:
    """Engine-attached profiler (reference profile_step semantics): arm it,
    run a training step, read/print the profile."""

    def __init__(self, engine=None):
        self.engine = engine
        self.profile: Optional[Dict[str, float]] = None
        self._t0 = 0.0
        self._armed = False

    def start_profile(self) -> None:
        self._armed = True
        self._t0 = time.perf_counter()

    def stop_profile(self) -> None:
        self._armed = False

    def profile_train_step(self, batch) -> Dict[str, float]:
        """Cost-analyse + time the engine's compiled train step on ``batch``."""
        assert self.engine is not None, "attach an engine"
        e = self.engine
        device_batch = e.shard_batch(batch)
        rng = jax.random.PRNGKey(0)
        if getattr(e, "onebit", False) or getattr(e, "offload_enabled", False):
            # explicit-host paths: measure wall latency only
            t0 = time.perf_counter()
            state, m = e._train_step(e.state, device_batch, rng)
            jax.block_until_ready(m["loss"])
            self.profile = {"flops": 0.0, "latency_s": time.perf_counter() - t0,
                            "params": _num_params(e.state.params)}
            return self.profile
        step = e._train_step
        cost = step.lower(e.state, device_batch, rng).compile().cost_analysis()
        if isinstance(cost, (list, tuple)):
            cost = cost[0] if cost else {}
        flops = float((cost or {}).get("flops", 0.0))
        # the step donates its state argument — keep the engine's state
        # pointing at the live buffers
        state, m = step(e.state, device_batch, rng)
        e.state = state
        jax.block_until_ready(m["loss"])
        t0 = time.perf_counter()
        state, m = step(state, device_batch, rng)
        e.state = state
        jax.block_until_ready(m["loss"])
        latency = time.perf_counter() - t0
        self.profile = {
            "flops": flops,
            "macs": flops / 2.0,
            "params": _num_params(e.state.params),
            "latency_s": latency,
            "achieved_tflops": flops / latency / 1e12 if latency else 0.0,
        }
        return self.profile

    def print_model_profile(self) -> None:
        """Reference print_model_profile:235-style summary."""
        p = self.profile or {}
        print("-" * 60)
        print("DeepSpeed-TPU Flops Profiler")
        print(f"params:           {p.get('params', 0):,}")
        print(f"fwd+bwd+opt flops:{p.get('flops', 0):,.0f}")
        print(f"MACs:             {p.get('macs', 0):,.0f}")
        print(f"step latency:     {p.get('latency_s', 0) * 1e3:.2f} ms")
        print(f"achieved:         {p.get('achieved_tflops', 0):.2f} TFLOPS")
        print("-" * 60)
