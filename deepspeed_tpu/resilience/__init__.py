"""Fault-tolerance plane (ISSUE 7): a production run survives its failures.

Three coupled parts, all opt-in via the ``resilience`` config section:

- :mod:`~deepspeed_tpu.resilience.writer` /
  :mod:`~deepspeed_tpu.resilience.manifest` — async checkpointing with an
  atomic, checksummed commit protocol (snapshot to host off the step path,
  background write, ``<tag>.tmp`` → fsync → rename → atomic ``latest``).
- :mod:`~deepspeed_tpu.resilience.recovery` — manifest-validated restore
  that walks back across corrupt/torn tags, plus the in-memory
  :class:`~deepspeed_tpu.resilience.recovery.RollbackManager` behind the
  watchdog's ``rollback`` policy.
- :mod:`~deepspeed_tpu.resilience.faults` — seeded deterministic fault
  injection (NaN loss, crash-mid-checkpoint, SIGTERM, serving-slot stalls)
  so every recovery path above is exercised by tests.

Serving-side resilience (graceful drain, retry-with-backoff) lives on
:class:`~deepspeed_tpu.serving.scheduler.ServingEngine` directly; the
preemption grace-window flush on
:class:`~deepspeed_tpu.elasticity.preemption.PreemptionGuard`.
See docs/RESILIENCE.md.
"""

from .faults import FaultInjected, FaultInjector
from .manifest import (
    CheckpointIntegrityError,
    atomic_write_text,
    find_latest_valid,
    validate_tag,
    write_tag,
)
from .recovery import (
    RollbackLimitError,
    RollbackManager,
    is_resilient_dir,
    load_resilient_state,
)
from .writer import AsyncCheckpointWriter, snapshot_to_host

__all__ = [
    "AsyncCheckpointWriter",
    "CheckpointIntegrityError",
    "FaultInjected",
    "FaultInjector",
    "RollbackLimitError",
    "RollbackManager",
    "atomic_write_text",
    "find_latest_valid",
    "is_resilient_dir",
    "load_resilient_state",
    "snapshot_to_host",
    "validate_tag",
    "write_tag",
]
