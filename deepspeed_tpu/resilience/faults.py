"""Deterministic fault injection: recovery paths are tested, not hoped for.

TPU practice (arXiv:2605.25645) makes preemption the *common* case; the only
way the recovery machinery in this package stays honest is to exercise it on
demand. :class:`FaultInjector` turns the ``resilience.fault_injection``
config into seeded, reproducible fault decisions at four sites:

- ``nan_loss``    — poison the step's loss scalar after ``train_batch``
                    (indices = the 1-based ``train_batch`` invocation
                    ordinal, monotonic — NOT ``global_steps``, which a
                    rollback rewinds): trips the watchdog's non-finite
                    detector → rollback/kill policy paths.
- ``sigterm``     — deliver a real SIGTERM to this process after a step
                    (same ordinal; only when a handler is installed):
                    exercises the PreemptionGuard grace-window flush.
- ``checkpoint_crash`` — abort a checkpoint write after the array files but
                    before the manifest/rename (indices = per-writer save
                    ordinal, 1-based): leaves the torn ``<tag>.tmp`` a
                    mid-write process death would, which the walk-back
                    loader must skip.
- ``serving_stall`` — mark the Nth admitted serving request (1-based) to
                    fail transiently mid-decode: exercises slot eviction +
                    retry-with-backoff re-enqueue.

Explicit index schedules are the test-friendly mode; ``probability`` adds a
chaos mode where each (site, index) fires independently with probability p,
derived from a stable hash of ``(seed, site, index)`` — the same seed always
injects the same faults, across restarts and processes.
"""

from __future__ import annotations

import hashlib
import os
import signal
from typing import Dict, List

from ..utils.logging import log_dist, logger

SITES = ("nan_loss", "sigterm", "checkpoint_crash", "serving_stall")


class FaultInjected(RuntimeError):
    """Raised at an injection site that simulates a crash."""


class FaultInjector:
    """Seeded, deterministic fault decisions; one per engine.

    ``fire(site, index)`` is pure given (config, site, index) — calling it
    twice for the same coordinates gives the same answer, so a restarted
    run re-injects the same faults (the point: recovery is replayable).
    """

    def __init__(self, config):
        self.config = config
        self.seed = int(getattr(config, "seed", 0))
        self.probability = float(getattr(config, "probability", 0.0))
        self._sched: Dict[str, set] = {
            "nan_loss": set(getattr(config, "nan_loss_steps", ()) or ()),
            "sigterm": set(getattr(config, "sigterm_steps", ()) or ()),
            "checkpoint_crash": set(getattr(config, "crash_saves", ()) or ()),
            "serving_stall": set(getattr(config, "stall_requests", ()) or ()),
        }
        self.fired: Dict[str, List[int]] = {}

    def _chaos(self, site: str, index: int) -> bool:
        if self.probability <= 0.0:
            return False
        blob = f"{self.seed}:{site}:{index}".encode()
        h = int.from_bytes(hashlib.sha256(blob).digest()[:8], "big")
        return (h / 2**64) < self.probability

    def fire(self, site: str, index: int) -> bool:
        """Should fault ``site`` fire at occurrence ``index``? Records and
        logs every hit."""
        if site not in self._sched:
            raise ValueError(f"unknown fault site {site!r} (know {SITES})")
        hit = index in self._sched[site] or self._chaos(site, index)
        if hit:
            self.fired.setdefault(site, []).append(index)
            log_dist(f"fault injection: {site} fires at index {index}")
        return hit

    def counts(self) -> Dict[str, int]:
        return {site: len(ix) for site, ix in self.fired.items()}

    # -- site helpers ---------------------------------------------------
    def deliver_sigterm(self) -> bool:
        """Send this process a real SIGTERM — but only when a handler is
        installed (a PreemptionGuard, a launcher): injecting process death
        into an unguarded test runner is not a recovery test."""
        cur = signal.getsignal(signal.SIGTERM)
        if cur in (signal.SIG_DFL, signal.SIG_IGN, None):
            logger.warning(
                "fault injection: sigterm scheduled but no handler installed "
                "(install a PreemptionGuard); skipping delivery"
            )
            return False
        os.kill(os.getpid(), signal.SIGTERM)
        return True


def from_config(config) -> "FaultInjector | None":
    if config is None or not getattr(config, "enabled", False):
        return None
    return FaultInjector(config)
