"""Integrity-checked checkpoint format: per-array checksums + atomic commit.

The reference's Nebula engine (nebula_checkpoint_engine.py) gets integrity
from a managed service; here the commit protocol is explicit and local so a
torn write, a corrupt block, or a half-renamed directory is *detectable at
load time* instead of surfacing as a silently wrong resume:

Layout on disk::

    <save_dir>/<tag>/manifest.json     format, step, fingerprint, client
                                       state, per-array {file, dtype, shape,
                                       bytes, crc32}
    <save_dir>/<tag>/00000.bin ...     raw array bytes, one file per leaf
    <save_dir>/latest                  text file naming the newest GOOD tag

Commit protocol (write_tag):

1. write every array file into ``<tag>.tmp`` and fsync each;
2. write ``manifest.json`` (checksums computed from the bytes actually
   written) and fsync it;
3. fsync the tmp directory, then ``rename(<tag>.tmp, <tag>)`` — the tag
   becomes visible atomically, fully checksummed or not at all;
4. atomically swap ``latest`` (temp file + fsync + rename).

A crash at any point leaves either the previous state intact (steps 1-3) or
a fully-committed tag without the ``latest`` swap (after 3) — both are
recovered by :func:`find_latest_valid`'s walk-back. Raw ``.bin`` + manifest
dtype strings (not ``.npy``) so bf16 and other ml_dtypes round-trip without
depending on numpy descriptor support.
"""

from __future__ import annotations

import json
import os
import shutil
import zlib
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

FORMAT = "dstpu-resilient-ckpt-v1"
MANIFEST = "manifest.json"
LATEST_FILE = "latest"


class CheckpointIntegrityError(RuntimeError):
    """A tag failed manifest validation (torn write / corruption)."""


def checksum(data: bytes) -> int:
    """crc32 (unsigned) — fast enough to run per-array on every save."""
    return zlib.crc32(data) & 0xFFFFFFFF


def _fsync_file(path: str) -> None:
    fd = os.open(path, os.O_RDONLY)
    try:
        os.fsync(fd)
    finally:
        os.close(fd)


def _fsync_dir(path: str) -> None:
    try:
        fd = os.open(path, os.O_RDONLY)
    except OSError:
        return  # platforms without O_RDONLY dir opens: rename is still atomic
    try:
        os.fsync(fd)
    except OSError:
        pass
    finally:
        os.close(fd)


def atomic_write_text(path: str, text: str) -> None:
    """temp file + fsync + rename: readers see the old or the new content,
    never a torn write (the ``latest`` swap primitive). The temp name is
    unique per process+thread so a background async writer and a forced
    blocking save racing on the same ``latest`` never clobber each other's
    temp file — last rename wins, both renames succeed."""
    import threading

    tmp = f"{path}.tmp.{os.getpid()}.{threading.get_ident()}"
    with open(tmp, "w") as fh:
        fh.write(text)
        fh.flush()
        os.fsync(fh.fileno())
    os.replace(tmp, path)
    _fsync_dir(os.path.dirname(os.path.abspath(path)))


def write_tag(
    save_dir: str,
    tag: str,
    arrays: Dict[str, np.ndarray],
    client_state: Optional[Dict[str, Any]] = None,
    fingerprint: str = "",
    step: int = 0,
    save_latest: bool = True,
    crash_before_manifest: bool = False,
) -> str:
    """Write one checkpoint tag under the atomic commit protocol; returns the
    committed tag directory.

    ``crash_before_manifest`` is the deterministic fault-injection hook
    (resilience.fault_injection ``crash_saves``): raise after the array
    files are on disk but before the manifest/rename, leaving exactly the
    torn ``<tag>.tmp`` a mid-write process death would.
    """
    base = os.path.abspath(save_dir)
    os.makedirs(base, exist_ok=True)
    final = os.path.join(base, str(tag))
    tmp = final + ".tmp"
    if os.path.isdir(tmp):
        shutil.rmtree(tmp)
    os.makedirs(tmp)

    entries: Dict[str, Dict[str, Any]] = {}
    for i, (name, arr) in enumerate(arrays.items()):
        # np.asarray, NOT ascontiguousarray: the latter promotes 0-d scalars
        # to [1], corrupting every scalar leaf's recorded shape; tobytes()
        # already emits C-order regardless of the source layout
        arr = np.asarray(arr)
        data = arr.tobytes()
        fname = f"{i:05d}.bin"
        fpath = os.path.join(tmp, fname)
        with open(fpath, "wb") as fh:
            fh.write(data)
            fh.flush()
            os.fsync(fh.fileno())
        entries[name] = {
            "file": fname,
            "dtype": arr.dtype.name,
            "shape": list(arr.shape),
            "bytes": len(data),
            "crc32": checksum(data),
        }

    if crash_before_manifest:
        from .faults import FaultInjected

        raise FaultInjected(
            f"injected crash mid-checkpoint-write of tag {tag!r} "
            f"(arrays on disk, no manifest — torn {os.path.basename(tmp)})"
        )

    manifest = {
        "format": FORMAT,
        "tag": str(tag),
        "step": int(step),
        "fingerprint": fingerprint,
        "client_state": client_state or {},
        "arrays": entries,
    }
    mpath = os.path.join(tmp, MANIFEST)
    with open(mpath, "w") as fh:
        json.dump(manifest, fh, indent=1)
        fh.flush()
        os.fsync(fh.fileno())
    _fsync_dir(tmp)

    # the one visibility point: a fully-written, checksummed directory
    # appears under the final name in a single rename. Overwriting an
    # existing tag (re-save of the same step) moves the stale dir aside
    # first — rename onto a non-empty dir is not atomic-or-anything.
    if os.path.isdir(final):
        stale = final + ".stale"
        if os.path.isdir(stale):
            shutil.rmtree(stale)
        os.rename(final, stale)
        os.rename(tmp, final)
        shutil.rmtree(stale, ignore_errors=True)
    else:
        os.rename(tmp, final)
    _fsync_dir(base)

    if save_latest:
        atomic_write_text(os.path.join(base, LATEST_FILE), str(tag))
    return final


def read_manifest(tag_dir: str) -> Dict[str, Any]:
    with open(os.path.join(tag_dir, MANIFEST)) as fh:
        return json.load(fh)


def validate_tag(tag_dir: str) -> Tuple[bool, str]:
    """Full integrity check of one committed tag: manifest present and
    parseable, every array file present, size and crc32 matching. Returns
    ``(ok, reason)`` — reason names the first failure."""
    mpath = os.path.join(tag_dir, MANIFEST)
    if not os.path.isfile(mpath):
        return False, "no manifest.json (torn write)"
    try:
        manifest = read_manifest(tag_dir)
    except (OSError, ValueError) as e:
        return False, f"unreadable manifest: {e}"
    if manifest.get("format") != FORMAT:
        return False, f"unknown format {manifest.get('format')!r}"
    for name, ent in manifest.get("arrays", {}).items():
        fpath = os.path.join(tag_dir, ent["file"])
        try:
            with open(fpath, "rb") as fh:
                data = fh.read()
        except OSError:
            return False, f"missing array file {ent['file']} ({name})"
        if len(data) != int(ent["bytes"]):
            return False, (
                f"array {name}: {len(data)} bytes on disk, manifest says "
                f"{ent['bytes']} (truncated write)"
            )
        if checksum(data) != int(ent["crc32"]):
            return False, f"array {name}: crc32 mismatch (corruption)"
    return True, "ok"


def load_arrays(tag_dir: str, manifest: Optional[Dict] = None) -> Dict[str, np.ndarray]:
    """Read every array of a VALIDATED tag back as host numpy. dtype comes
    from the manifest string via ``jnp.dtype`` so bf16/fp8 (ml_dtypes)
    round-trip exactly."""
    import jax.numpy as jnp

    manifest = manifest or read_manifest(tag_dir)
    out: Dict[str, np.ndarray] = {}
    for name, ent in manifest["arrays"].items():
        with open(os.path.join(tag_dir, ent["file"]), "rb") as fh:
            data = fh.read()
        arr = np.frombuffer(data, dtype=jnp.dtype(ent["dtype"]))
        out[name] = arr.reshape(tuple(ent["shape"]))
    return out


def read_latest_tag(load_dir: str) -> Optional[str]:
    p = os.path.join(load_dir, LATEST_FILE)
    if os.path.isfile(p):
        with open(p) as fh:
            return fh.read().strip() or None
    return None


def list_tags(load_dir: str) -> List[str]:
    """Manifest-bearing tag directories, newest first (manifest step desc,
    mtime as the tiebreak)."""
    base = os.path.abspath(load_dir)
    cands = []
    try:
        entries = os.listdir(base)
    except OSError:
        return []
    for name in entries:
        d = os.path.join(base, name)
        if not os.path.isdir(d) or name.endswith((".tmp", ".stale")):
            continue
        if not os.path.isfile(os.path.join(d, MANIFEST)):
            continue
        try:
            step = int(read_manifest(d).get("step", -1))
        except (OSError, ValueError):
            step = -1
        cands.append((step, os.path.getmtime(d), name))
    cands.sort(reverse=True)
    return [name for _, _, name in cands]


def find_latest_valid(
    load_dir: str, tag: Optional[str] = None
) -> Tuple[str, List[Dict[str, str]]]:
    """The newest tag that passes full validation, walking back across
    corrupt/torn tags. Returns ``(tag, skipped)`` where ``skipped`` records
    every invalid tag passed over (for the recovery event log). An
    explicitly requested ``tag`` is validated strictly — asking for a
    specific tag and getting a different one would be a silent lie."""
    base = os.path.abspath(load_dir)
    if tag is not None:
        ok, why = validate_tag(os.path.join(base, str(tag)))
        if not ok:
            raise CheckpointIntegrityError(
                f"checkpoint tag {tag!r} in {load_dir} failed validation: {why}"
            )
        return str(tag), []
    skipped: List[Dict[str, str]] = []
    seen = set()
    candidates: List[str] = []
    latest = read_latest_tag(base)
    if latest is not None:
        candidates.append(latest)
        seen.add(latest)
    for t in list_tags(base):
        if t not in seen:
            candidates.append(t)
            seen.add(t)
    for t in candidates:
        ok, why = validate_tag(os.path.join(base, t))
        if ok:
            return t, skipped
        skipped.append({"tag": t, "reason": why})
    raise CheckpointIntegrityError(
        f"no valid checkpoint tag in {load_dir} "
        f"(tried {[s['tag'] for s in skipped] or 'none'})"
    )
