"""Recovery: manifest-validated restore with walk-back + in-memory rollback.

Two recovery axes, matching the two failure classes:

- **Across restarts** (:func:`load_resilient_state`): find the newest tag
  whose manifest validates (skipping corrupt/torn tags — see
  ``manifest.find_latest_valid``), restore every leaf onto the engine's
  current shardings, and hand back the client state (step counters, RNG,
  telemetry counters) so the resumed run is bit-identical to the saved one.

- **Within a run** (:class:`RollbackManager`): a bounded host-side snapshot
  of the last known-good TrainState. When the watchdog trips under the
  ``rollback`` policy, the engine restores the snapshot and skips the
  poisoned batch instead of dying — the NaN-spike remediation that keeps a
  production run alive through one bad batch.
"""

from __future__ import annotations

import os
from typing import Any, Dict, List, Optional, Tuple

import jax
import numpy as np

from ..utils.logging import log_dist, logger
from ..utils.pytree import path_str as _path_str
from . import manifest as mf

PyTree = Any


class RollbackLimitError(RuntimeError):
    """Too many rollbacks: the pathology is persistent, not a bad batch."""


def is_resilient_dir(load_dir: str, tag: Optional[str] = None) -> bool:
    """Does ``load_dir`` hold manifest-format checkpoints (vs orbax)?"""
    if tag is not None:
        return os.path.isfile(os.path.join(load_dir, str(tag), mf.MANIFEST))
    return bool(mf.list_tags(load_dir))


def load_resilient_state(
    load_dir: str,
    tag: Optional[str],
    like_state: PyTree,
    shardings: PyTree,
    load_optimizer_states: bool = True,
    registry=None,
) -> Tuple[PyTree, Dict[str, Any], str, Dict[str, np.ndarray]]:
    """Restore the newest VALID tag onto ``shardings``.

    Returns ``(state, client_state, tag_used, extras)`` where ``extras``
    holds non-state arrays the save added (``__rng__``, …). Leaf matching is
    by pytree path name; ``comm_error`` leaves are allowed to differ between
    save and resume (compression toggled) — missing ones keep the engine's
    current zeros, extra ones are dropped with a warning. Any other
    missing leaf raises: a partial state restore is corruption, not
    flexibility."""
    tag_used, skipped = mf.find_latest_valid(load_dir, tag)
    if skipped:
        names = [s["tag"] for s in skipped]
        logger.warning(
            f"checkpoint walk-back: skipped invalid tag(s) {names} in "
            f"{load_dir}; recovering from {tag_used!r} "
            f"({'; '.join(s['reason'] for s in skipped)})"
        )
        if registry is not None:
            registry.counter(
                "recovery_events_total", "recovery actions by kind",
                labelnames=("kind",),
            ).inc(len(skipped), kind="walk_back")
    tag_dir = os.path.join(os.path.abspath(load_dir), tag_used)
    manifest = mf.read_manifest(tag_dir)
    arrays = mf.load_arrays(tag_dir, manifest)

    flat, treedef = jax.tree_util.tree_flatten_with_path(like_state)
    shard_leaves = jax.tree.leaves(shardings)
    assert len(shard_leaves) == len(flat), (
        f"shardings tree ({len(shard_leaves)} leaves) does not match state "
        f"({len(flat)} leaves)"
    )
    new_leaves = []
    used = set()
    for (path, cur), sh in zip(flat, shard_leaves):
        name = _path_str(path)
        skip_opt = not load_optimizer_states and name.startswith("opt_state")
        arr = arrays.get(name)
        if arr is None or skip_opt:
            if skip_opt or name.startswith("comm_error"):
                if arr is None and not skip_opt:
                    logger.warning(
                        f"checkpoint {tag_used!r} has no {name!r} (comm "
                        "compression residuals restart from zero)"
                    )
                new_leaves.append(cur)
                if arr is not None:
                    used.add(name)
                continue
            raise KeyError(
                f"checkpoint {tag_used!r} is missing state leaf {name!r} "
                "(engine/checkpoint structure mismatch)"
            )
        used.add(name)
        if tuple(arr.shape) != tuple(cur.shape):
            raise ValueError(
                f"state leaf {name!r}: checkpoint shape {tuple(arr.shape)} "
                f"!= engine shape {tuple(cur.shape)}"
            )
        if np.dtype(arr.dtype) != np.dtype(cur.dtype):
            # a silent dtype swap corrupts training exactly like a shape
            # mismatch would — fail loud instead of retracing at the wrong
            # precision
            raise ValueError(
                f"state leaf {name!r}: checkpoint dtype {arr.dtype} "
                f"!= engine dtype {cur.dtype}"
            )
        new_leaves.append(jax.device_put(arr, sh))
    extras = {
        n: a for n, a in arrays.items()
        if n not in used and n.startswith("__")
    }
    dropped = [
        n for n in arrays
        if n not in used and not n.startswith("__")
    ]
    if dropped:
        logger.warning(
            f"checkpoint {tag_used!r} carries leaves this engine does not: "
            f"{dropped[:5]}{'...' if len(dropped) > 5 else ''}; dropping them"
        )
    state = jax.tree_util.tree_unflatten(treedef, new_leaves)
    log_dist(f"restored checkpoint tag {tag_used!r} from {load_dir}")
    return state, dict(manifest.get("client_state", {})), tag_used, extras


class RollbackManager:
    """Last-known-good in-memory snapshot + bounded restore.

    ``snapshot`` keeps ONE host copy of the state (overwritten each call);
    ``restore`` hands it back and counts — past ``max_rollbacks`` it raises
    :class:`RollbackLimitError`, because a run that needs its Nth rollback
    is diverging, not hitting bad batches."""

    def __init__(self, max_rollbacks: int = 8, registry=None):
        self.max_rollbacks = int(max_rollbacks)
        self.rollbacks = 0
        self._snap: Optional[Tuple[Any, int]] = None
        self._snap_step: Optional[int] = None
        if registry is not None:
            self._c_rolled = registry.counter(
                "rolled_back_steps_total",
                "train steps undone by watchdog rollback",
            )
            self._c_events = registry.counter(
                "recovery_events_total", "recovery actions by kind",
                labelnames=("kind",),
            )
        else:
            self._c_rolled = self._c_events = None

    def snapshot(self, state: PyTree, global_steps: int) -> None:
        """Host-copy the state (blocks until its producing step finished —
        by snapshot time the engine already synced on the step's metrics, so
        this is a device→host copy, not an extra device sync)."""
        host = jax.device_get(state)
        self._snap = (host, int(global_steps))
        self._snap_step = int(global_steps)

    @property
    def can_restore(self) -> bool:
        return self._snap is not None

    @property
    def snapshot_step(self) -> Optional[int]:
        return self._snap_step

    def restore(self) -> Tuple[Any, int]:
        if self._snap is None:
            raise RuntimeError("no snapshot taken yet")
        self.rollbacks += 1
        if self.rollbacks > self.max_rollbacks:
            raise RollbackLimitError(
                f"rollback #{self.rollbacks} exceeds "
                f"resilience.max_rollbacks={self.max_rollbacks} — the "
                "anomaly is persistent, not a poisoned batch; stopping"
            )
        if self._c_rolled is not None:
            self._c_rolled.inc()
            self._c_events.inc(kind="rollback")
        return self._snap
