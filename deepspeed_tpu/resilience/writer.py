"""Async integrity-checked checkpoint writer.

The ZeRO-Infinity overlap trick (arXiv:2104.07857) applied to checkpoints:
the step path pays only the HBM→host snapshot (``jax.device_get`` — it must
complete before the next step donates the state buffers), and the disk write
— serialization, checksums, fsync, atomic rename — runs on a background
thread while training proceeds. The on-disk format and commit protocol live
in :mod:`.manifest`; this module owns the threading, the telemetry, and the
fault-injection crash hook.

One writer per (engine, save_dir). ``save(..., blocking=True)`` bypasses the
worker and writes in the caller's thread — the PreemptionGuard's forced
fresh snapshot when an in-flight async write overruns the grace window.
"""

from __future__ import annotations

import queue
import threading
import time
from typing import Any, Dict, Optional

import jax
import numpy as np

from ..utils.logging import log_dist, logger
from ..utils.pytree import path_str as _path_str
from . import manifest as mf

# checkpoint write-duration histogram buckets (seconds)
WRITE_BUCKETS = (0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0,
                 10.0, 30.0, 60.0, 120.0)


def snapshot_to_host(state, extra: Optional[Dict[str, np.ndarray]] = None) -> Dict[str, np.ndarray]:
    """Flatten a (possibly sharded) TrainState pytree to ``{path: np.ndarray}``
    host copies. Blocks until the state's producing computation is done and
    the copy lands — after this returns, later steps may freely donate the
    device buffers."""
    flat, _ = jax.tree_util.tree_flatten_with_path(state)
    # dslint: disable=host-sync-in-step — the snapshot IS the sync: the host
    # copy must complete before the next step donates these buffers
    host = jax.device_get([leaf for _, leaf in flat])
    out = {_path_str(path): np.asarray(a) for (path, _), a in zip(flat, host)}
    out.update(extra or {})
    return out


class AsyncCheckpointWriter:
    """Background checkpoint writer with the atomic, checksummed commit
    protocol. Construct once per save directory; ``save()`` enqueues,
    ``wait()`` drains (the preemption grace-window flush), ``close()``
    drains and stops the worker."""

    def __init__(
        self,
        save_dir: str,
        fingerprint: str = "",
        registry=None,
        injector=None,
        telemetry=None,
    ):
        self.save_dir = save_dir
        self.fingerprint = fingerprint
        self.injector = injector
        self.telemetry = telemetry
        self._q: "queue.Queue" = queue.Queue()
        self._idle = threading.Event()
        self._idle.set()
        self._thread: Optional[threading.Thread] = None
        # via the dsan shim: sanitizer-enabled runs observe this lock's
        # schedule against the StepTracer's (ISSUE 8)
        from ..analysis.runtime_sanitizer import maybe_lock

        self._lock = maybe_lock("AsyncCheckpointWriter._lock")
        self.saves_started = 0  # the checkpoint_crash injection index
        self.saves_committed = 0
        self.errors: list = []  # (tag, exception), newest last
        if registry is not None:
            self._h_write = registry.histogram(
                "checkpoint_write_seconds",
                "background checkpoint write duration (snapshot excluded)",
                buckets=WRITE_BUCKETS,
            )
            self._c_writes = registry.counter(
                "checkpoint_writes_total", "committed checkpoint writes"
            )
            self._c_failures = registry.counter(
                "checkpoint_write_failures_total",
                "checkpoint writes that died before commit (incl. injected)",
            )
            self._g_inflight = registry.gauge(
                "checkpoint_writes_in_flight", "queued + running async writes"
            )
        else:
            self._h_write = self._c_writes = self._c_failures = None
            self._g_inflight = None

    # -- public surface -------------------------------------------------
    def save(
        self,
        tag: str,
        arrays: Dict[str, np.ndarray],
        client_state: Optional[Dict[str, Any]] = None,
        step: int = 0,
        save_latest: bool = True,
        blocking: bool = False,
    ) -> str:
        """Commit ``arrays`` under ``tag``. Non-blocking by default: the job
        is queued for the worker and the expected final path returns
        immediately (``wait()``/``last_error`` report the outcome).
        ``blocking=True`` writes in this thread — failures raise."""
        with self._lock:
            self.saves_started += 1
            ordinal = self.saves_started
        job = (tag, arrays, dict(client_state or {}), int(step), save_latest, ordinal)
        if blocking:
            return self._write(*job)
        self._ensure_worker()
        self._idle.clear()
        self._q.put(job)
        if self._g_inflight is not None:
            self._g_inflight.set(self._q.qsize() + (0 if self._idle.is_set() else 1))
        import os

        return os.path.join(os.path.abspath(self.save_dir), str(tag))

    def wait(self, timeout: Optional[float] = None) -> bool:
        """Block until every queued write committed (or failed). True when
        drained inside the timeout — the grace-window contract: False means
        an in-flight save may be torn and the caller should force a fresh
        blocking snapshot before exiting."""
        deadline = None if timeout is None else time.monotonic() + timeout
        while True:
            if self._q.empty() and self._idle.is_set():
                return True
            if deadline is not None and time.monotonic() >= deadline:
                return self._q.empty() and self._idle.is_set()
            time.sleep(0.005)

    @property
    def in_flight(self) -> int:
        return self._q.qsize() + (0 if self._idle.is_set() else 1)

    @property
    def last_error(self) -> Optional[BaseException]:
        with self._lock:
            return self.errors[-1][1] if self.errors else None

    def close(self, timeout: Optional[float] = None) -> bool:
        ok = self.wait(timeout)
        t = self._thread
        if t is not None:
            self._q.put(None)
            t.join(timeout=5.0)
            self._thread = None
        return ok

    # -- worker ---------------------------------------------------------
    def _ensure_worker(self) -> None:
        with self._lock:
            if self._thread is None or not self._thread.is_alive():
                self._thread = threading.Thread(
                    target=self._run, name="ckpt-writer", daemon=True
                )
                self._thread.start()

    def _run(self) -> None:
        while True:
            job = self._q.get()
            if job is None:
                self._idle.set()
                return
            self._idle.clear()
            try:
                self._write(*job)
            except BaseException as e:  # a failed write must not kill the run
                logger.warning(
                    f"async checkpoint write of tag {job[0]!r} failed: "
                    f"{type(e).__name__}: {e}"
                )
            finally:
                if self._q.empty():
                    self._idle.set()
                if self._g_inflight is not None:
                    self._g_inflight.set(self.in_flight)

    def _write(self, tag, arrays, client_state, step, save_latest, ordinal) -> str:
        crash = bool(
            self.injector is not None
            and self.injector.fire("checkpoint_crash", ordinal)
        )
        t0 = time.perf_counter()
        try:
            path = mf.write_tag(
                self.save_dir, tag, arrays,
                client_state=client_state,
                fingerprint=self.fingerprint,
                step=step,
                save_latest=save_latest,
                crash_before_manifest=crash,
            )
        except BaseException as e:
            # _write runs on the worker thread AND (blocking=True) on the
            # caller's — the error ledger and commit counter are read from
            # either side, so both mutate under the writer lock (dsan
            # shared-state-unlocked)
            with self._lock:
                self.errors.append((tag, e))
                del self.errors[:-16]
            if self._c_failures is not None:
                self._c_failures.inc()
            raise
        dt = time.perf_counter() - t0
        with self._lock:
            self.saves_committed += 1
        if self._h_write is not None:
            self._h_write.observe(dt)
            self._c_writes.inc()
        if self.telemetry is not None:
            self.telemetry.record_event(
                "checkpoint_write", dt, {"step": step, "tag": str(tag), "path": path}
            )
        log_dist(f"checkpoint committed: {path} ({dt * 1e3:.1f} ms)")
        return path
