"""Unified causal decoder — one scan-over-layers graph for the HF GPT family.

The reference serves OPT/BLOOM/GPT-J/GPT-Neo/GPT-NeoX/Megatron through ONE
fused CUDA module (``DeepSpeedTransformerInference``) parameterised by policy
(module_inject/replace_policy.py:129-501 + transformer_inference.py:735:
rotary/alibi/triangular-masking flags). This is the TPU analog: one jitted
decode graph whose config covers the architectural axes that differ:

- position encoding: learned | rope (gptj-interleaved / neox-half) | alibi
- residual topology: sequential (GPT2/OPT/BLOOM) | parallel (GPT-J/NeoX)
- activation: gelu_new | gelu | relu
- attention scale override (GPT-Neo uses none), per-layer local windows
  (GPT-Neo alternating global/local)
- lm head: tied to embeddings or separate (+optional bias)
- BLOOM's embedding LayerNorm; OPT's position offset

Params are normalised by policies to: separate per-layer wq/wk/wv/wo
[L, E, E], mlp fc_in [L, E, F] / fc_out [L, F, E], ln scales/biases — the
fused-QKV torch layouts (BLOOM/NeoX [H,3,D] interleave) are de-interleaved at
conversion time so the decode graph never branches on checkpoint layout.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from ..ops.layer_norm import layer_norm
from ..ops.quantizer import maybe_dequantize as _deq
from ..runtime.module import ModuleSpec

PyTree = Any


@dataclass(frozen=True)
class DecoderConfig:
    vocab_size: int
    n_positions: int
    n_embd: int
    n_layer: int
    n_head: int
    ffn_dim: int
    layer_norm_epsilon: float = 1e-5
    pos_emb: str = "learned"  # learned | rope | alibi | none
    rope_style: str = "gptj"  # gptj (interleaved) | neox (half-split)
    rotary_dim: int = 0  # 0 → full head_dim
    activation: str = "gelu_new"  # gelu_new | gelu | relu
    parallel_residual: bool = False  # GPT-J/NeoX: h + attn(ln(h)) + mlp(ln(h))
    use_ln2: bool = True  # parallel_residual with a single shared ln (GPT-J) → False
    tie_embeddings: bool = True
    lm_head_bias: bool = False
    embed_ln: bool = False  # BLOOM word_embeddings_layernorm
    pos_offset: int = 0  # OPT's embed_positions offset (2)
    attn_scale: Optional[float] = None  # None → 1/sqrt(head_dim); GPT-Neo → 1.0
    local_windows: Tuple[int, ...] = ()  # per-layer window, 0 = global (GPT-Neo)
    # LLaMA-family axes (beyond the reference snapshot's zoo):
    norm: str = "layernorm"  # layernorm | rmsnorm (rmsnorm params: scale only)
    mlp_type: str = "dense"  # dense | swiglu (adds fc_gate_w) | moe_swiglu (Mixtral)
    n_kv_head: Optional[int] = None  # grouped-query attention; None → n_head
    rope_theta: float = 10000.0
    # mlp_type="moe_swiglu": per-layer expert-parallel SwiGLU FFN
    # (moe/sharded_moe.py). Routing is Mixtral-exact in eval mode: top-2
    # argmax second expert, no token dropping, weights g_i/sum(topk g).
    moe_experts: int = 0
    moe_top_k: int = 2
    moe_aux_loss_weight: float = 0.01
    # mesh enables tp token de-duplication inside the MoE layer
    # (moe/mappings.py); the inference engine threads its mesh in here
    mesh: Any = None
    # >0: chunked LM cross-entropy (models/lm_loss.py) — at BLOOM-class
    # vocabs (250k) the full [B,S,V] logits dwarf every other activation
    ce_chunk: int = 0

    @property
    def head_dim(self) -> int:
        return self.n_embd // self.n_head

    @property
    def kv_heads(self) -> int:
        return self.n_kv_head or self.n_head


class KVCache(NamedTuple):
    k: jnp.ndarray  # [L, B, Smax, H, D]
    v: jnp.ndarray
    pos: jnp.ndarray


def init_cache(cfg: DecoderConfig, batch_size: int, max_len: int, dtype=jnp.bfloat16) -> KVCache:
    # GQA caches only kv_heads — the memory saving that motivates it
    shape = (cfg.n_layer, batch_size, max_len, cfg.kv_heads, cfg.head_dim)
    return KVCache(jnp.zeros(shape, dtype), jnp.zeros(shape, dtype), jnp.int32(0))


# ---------------------------------------------------------------------------
# building blocks
# ---------------------------------------------------------------------------

def _norm(cfg: DecoderConfig, x, p, eps):
    """Norm dispatch: LayerNorm (scale+bias) or RMSNorm (scale only)."""
    if cfg.norm == "rmsnorm":
        from ..ops.layer_norm import rms_norm

        return rms_norm(x, p["scale"], eps)
    return layer_norm(x, p["scale"], p["bias"], eps)


def _act(cfg: DecoderConfig, x):
    if cfg.activation == "relu":
        return jax.nn.relu(x)
    return jax.nn.gelu(x, approximate=(cfg.activation == "gelu_new"))


def alibi_slopes(n_head: int) -> np.ndarray:
    """Standard ALiBi slopes (power-of-two geometric; BLOOM formula)."""

    def pow2_slopes(n):
        start = 2.0 ** (-(2.0 ** -(np.log2(n) - 3)))
        return [start * (start ** i) for i in range(n)]

    if np.log2(n_head).is_integer():
        return np.asarray(pow2_slopes(n_head), np.float32)
    closest = 2 ** int(np.floor(np.log2(n_head)))
    extra = pow2_slopes(2 * closest)[0::2][: n_head - closest]
    return np.asarray(pow2_slopes(closest) + extra, np.float32)


def _rope_angles(cfg: DecoderConfig, positions: jnp.ndarray) -> Tuple[jnp.ndarray, jnp.ndarray]:
    rot = cfg.rotary_dim or cfg.head_dim
    inv_freq = 1.0 / (cfg.rope_theta ** (jnp.arange(0, rot, 2, dtype=jnp.float32) / rot))
    ang = positions.astype(jnp.float32)[:, None] * inv_freq[None, :]  # [S, rot/2]
    return jnp.sin(ang), jnp.cos(ang)


def _apply_rope(cfg: DecoderConfig, x: jnp.ndarray, sin, cos) -> jnp.ndarray:
    """x [B,S,H,D]; rotate the first rotary_dim dims per rope_style."""
    rot = cfg.rotary_dim or cfg.head_dim
    xr, xp = x[..., :rot], x[..., rot:]
    s = sin[None, :, None, :]
    c = cos[None, :, None, :]
    if cfg.rope_style == "gptj":  # interleaved pairs (rotate_every_two)
        x1, x2 = xr[..., 0::2], xr[..., 1::2]
        r1 = x1 * c - x2 * s
        r2 = x2 * c + x1 * s
        rotated = jnp.stack([r1, r2], axis=-1).reshape(xr.shape)
    else:  # neox: half-split (rotate_half), angles repeated across halves
        half = rot // 2
        x1, x2 = xr[..., :half], xr[..., half:]
        r1 = x1 * c - x2 * s
        r2 = x2 * c + x1 * s
        rotated = jnp.concatenate([r1, r2], axis=-1)
    return jnp.concatenate([rotated, xp], axis=-1).astype(x.dtype)


def _windows_inert(cfg: DecoderConfig, span: int) -> bool:
    """True when every layer's local window cannot mask anything within
    ``span`` positions (w == 0 means global; w >= span is a no-op mask).
    Mistral-class models declare window 4096: at train/serve lengths inside
    it, the fast unwindowed kernels are exact."""
    return not cfg.local_windows or all(
        w == 0 or w >= span for w in cfg.local_windows
    )


def _attention(cfg: DecoderConfig, lp, h, k_cache, v_cache, pos, layer_window):
    """Causal (optionally local-windowed / alibi-biased) attention with cache.
    GQA (kv_heads < n_head): K/V project and cache at kv_heads and broadcast
    to the query heads only at score time."""
    B, S, E = h.shape
    H, D = cfg.n_head, cfg.head_dim
    KV = cfg.kv_heads

    def proj(w, b, nh):
        out = h @ _deq(w, h.dtype)
        out = out + b if b is not None else out
        return out.reshape(B, S, nh, D)

    def out_proj(o):
        out = o @ _deq(lp["wo"], o.dtype)
        if lp.get("bo") is not None:
            out = out + lp["bo"]
        return out

    q = proj(lp["wq"], lp.get("bq"), H)
    k_ = proj(lp["wk"], lp.get("bk"), KV)
    v = proj(lp["wv"], lp.get("bv"), KV)

    if cfg.pos_emb == "rope":
        sin, cos = _rope_angles(cfg, pos + jnp.arange(S))
        q = _apply_rope(cfg, q, sin, cos)
        k_ = _apply_rope(cfg, k_, sin, cos)

    k_cache = lax.dynamic_update_slice(k_cache, k_.astype(k_cache.dtype), (0, pos, 0, 0))
    v_cache = lax.dynamic_update_slice(v_cache, v.astype(v_cache.dtype), (0, pos, 0, 0))

    Smax = k_cache.shape[1]
    scale = cfg.attn_scale if cfg.attn_scale is not None else 1.0 / np.sqrt(D)

    full_seq_no_bias = (
        isinstance(pos, int)
        and pos == 0
        and S == Smax
        and cfg.pos_emb != "alibi"
    )
    static_full_seq = full_seq_no_bias and _windows_inert(cfg, S)
    if full_seq_no_bias and not static_full_seq:
        # real sliding windows (Mistral past its window, GPT-Neo local
        # layers): the windowed flash kernels take the per-layer window as
        # a traced scalar-prefetch operand, so ONE compiled kernel serves
        # every layer of the scan and the loop bounds skip blocks wholly
        # outside the band (FLOPs ~ S*window). Gated on the kernel actually
        # engaging: the jnp fallback would repeat GQA K/V, while the
        # grouped-einsum path below never materializes the repeat.
        from ..ops.attention import causal_attention, windowed_attention_ok

        if windowed_attention_ok(q):
            o = causal_attention(q, k_, v, sm_scale=scale, window=layer_window)
            return out_proj(o.reshape(B, S, E).astype(h.dtype)), k_cache, v_cache
    if static_full_seq and KV == H:
        # training/eval full-sequence path (hidden() passes pos=0 as a
        # STATIC int): plain causal attention with no score biasing —
        # dispatch through the shared op so MHA decoders (LLaMA-7B-class,
        # OPT, GPT-J, NeoX, GPT-2-style) ride the Pallas flash kernels on
        # TPU instead of materializing [S,S] scores
        from ..ops.attention import causal_attention

        o = causal_attention(q, k_, v, sm_scale=scale).reshape(B, S, E).astype(h.dtype)
        return out_proj(o), k_cache, v_cache
    if static_full_seq and KV != H:
        # GQA (Mistral/Mixtral/LLaMA-70B class): the flash kernels read each
        # group's shared K/V block through a divided batch index map — the
        # repeated cache is never materialized. Routed through the shared
        # dispatcher (same warn-and-fall-back contract as the MHA branch);
        # gated on the kernel actually engaging, because the dispatcher's
        # jnp fallback repeats K/V while the grouped-einsum path below
        # doesn't — off-TPU the no-repeat path wins
        from ..ops.attention import causal_attention, pallas_attention_ok

        if pallas_attention_ok(q):
            o = causal_attention(q, k_, v, sm_scale=scale)
            return out_proj(o.reshape(B, S, E).astype(h.dtype)), k_cache, v_cache

    if S == 1 and cfg.pos_emb != "alibi" and _windows_inert(cfg, Smax):
        # single-token decode without score biasing (MHA and GQA): route
        # through the decode-attention dispatch (Pallas online-softmax
        # kernel on TPU — GQA reads the KV-headed cache via a divided head
        # index map, never repeated; grouped-einsum jnp fallback) — RoPE is
        # already applied pre-cache so the kernel sees plain dot products
        from ..ops.attention import cached_attention

        o1 = cached_attention(q[:, 0], k_cache, v_cache, pos, sm_scale=scale)
        o = o1.reshape(B, 1, E).astype(h.dtype)
        return out_proj(o), k_cache, v_cache

    if KV != H:
        # grouped-query scores without materializing a repeated cache: the
        # kv head is a shared contraction group (HBM traffic stays at KV)
        rep = H // KV
        qg = q.reshape(B, S, KV, rep, D)
        scores = jnp.einsum(
            "bsgrd,btgd->bgrst", qg.astype(jnp.float32), k_cache.astype(jnp.float32)
        ) * scale
        scores = scores.reshape(B, H, S, Smax)
    else:
        scores = jnp.einsum(
            "bshd,bthd->bhst", q.astype(jnp.float32), k_cache.astype(jnp.float32)
        ) * scale

    j_idx = jnp.arange(Smax)
    i_idx = pos + jnp.arange(S)
    mask = j_idx[None, :] <= i_idx[:, None]
    # GPT-Neo local layers: window w keeps keys with i - w < j <= i
    mask = jnp.where(
        layer_window > 0,
        mask & (j_idx[None, :] > i_idx[:, None] - layer_window),
        mask,
    )
    if cfg.pos_emb == "alibi":
        slopes = jnp.asarray(alibi_slopes(H))  # [H]
        # per-query-row-constant shift makes slopes*j equivalent to slopes*(j-i)
        scores = scores + slopes[None, :, None, None] * j_idx[None, None, None, :]
    scores = jnp.where(mask[None, None, :, :], scores, -1e30)
    probs = jax.nn.softmax(scores, axis=-1).astype(v_cache.dtype)
    if KV != H:
        pg = probs.reshape(B, KV, rep, S, Smax)
        o = jnp.einsum("bgrst,btgd->bsgrd", pg, v_cache).reshape(B, S, E).astype(h.dtype)
    else:
        o = jnp.einsum("bhst,bthd->bshd", probs, v_cache).reshape(B, S, E).astype(h.dtype)
    return out_proj(o), k_cache, v_cache


def _mlp(cfg: DecoderConfig, lp, x, train: bool = False, rng=None):
    """Returns (out, aux_loss) — aux is 0 except for the MoE FFN."""
    if cfg.mlp_type == "moe_swiglu":
        from ..moe.sharded_moe import MoEConfig, moe_mlp

        mcfg = MoEConfig(
            num_experts=cfg.moe_experts, k=cfg.moe_top_k,
            drop_tokens=False, use_rts=False, second_policy="argmax",
        )
        deq = {k: _deq(v, x.dtype) for k, v in lp.items()}
        out, aux = moe_mlp(deq, x, mcfg, rng=rng, train=train, mesh=cfg.mesh)
        return out, aux
    if cfg.mlp_type == "swiglu":
        # LLaMA FFN: silu(x @ gate) * (x @ up) @ down — no biases
        g = jax.nn.silu(x @ _deq(lp["fc_gate_w"], x.dtype))
        y = g * (x @ _deq(lp["fc_in_w"], x.dtype))
        return y @ _deq(lp["fc_out_w"], y.dtype), jnp.float32(0.0)
    y = x @ _deq(lp["fc_in_w"], x.dtype)
    if lp.get("fc_in_b") is not None:
        y = y + lp["fc_in_b"]
    y = _act(cfg, y)
    y = y @ _deq(lp["fc_out_w"], y.dtype)
    if lp.get("fc_out_b") is not None:
        y = y + lp["fc_out_b"]
    return y, jnp.float32(0.0)


def _block(cfg: DecoderConfig, lp, h, k_c, v_c, pos, window, train: bool = False, rng=None):
    eps = cfg.layer_norm_epsilon
    ln1 = _norm(cfg, h, lp["ln_1"], eps)
    a, k_c, v_c = _attention(cfg, lp["attn"], ln1, k_c, v_c, pos, window)
    if cfg.parallel_residual:
        mlp_in = ln1 if not cfg.use_ln2 else _norm(cfg, h, lp["ln_2"], eps)
        m, aux = _mlp(cfg, lp["mlp"], mlp_in, train, rng)
        return h + a + m, k_c, v_c, aux
    h = h + a
    m, aux = _mlp(cfg, lp["mlp"], _norm(cfg, h, lp["ln_2"], eps), train, rng)
    return h + m, k_c, v_c, aux


# ---------------------------------------------------------------------------
# forward paths
# ---------------------------------------------------------------------------

def _embed(cfg: DecoderConfig, params, input_ids, pos):
    S = input_ids.shape[1]
    h = params["wte"][input_ids]
    if cfg.pos_emb == "learned":
        positions = pos + jnp.arange(S) + cfg.pos_offset
        h = h + params["wpe"][positions][None, :, :]
    if cfg.embed_ln:
        h = _norm(cfg, h, params["emb_ln"], cfg.layer_norm_epsilon)
    return h


def _head(cfg: DecoderConfig, params, h):
    if cfg.tie_embeddings:
        logits = h @ params["wte"].T
    else:
        logits = h @ params["lm_head_w"]
        if cfg.lm_head_bias:
            logits = logits + params["lm_head_b"]
    return logits


def _windows(cfg: DecoderConfig) -> jnp.ndarray:
    if cfg.local_windows:
        return jnp.asarray(cfg.local_windows, jnp.int32)
    return jnp.zeros(cfg.n_layer, jnp.int32)


def forward_cached(cfg: DecoderConfig, params, input_ids, cache: KVCache):
    """[B,S] starting at cache.pos → (last-token logits [B,V], cache)."""
    pos = cache.pos
    h = _embed(cfg, params, input_ids, pos)

    def body(carry, xs):
        h = carry
        lp, k_c, v_c, window = xs
        h, k_c, v_c, _aux = _block(cfg, lp, h, k_c, v_c, pos, window)
        return h, (k_c, v_c)

    h, (new_k, new_v) = lax.scan(body, h, (params["blocks"], cache.k, cache.v, _windows(cfg)))
    h = _norm(cfg, h[:, -1], params["ln_f"], cfg.layer_norm_epsilon)
    return _head(cfg, params, h), KVCache(new_k, new_v, pos + input_ids.shape[1])


def hidden(cfg: DecoderConfig, params, input_ids, train: bool = False, rng=None):
    """Full-sequence final-LN hidden states [B,S,E] (pre-head trunk).
    Returns (h, moe_aux_sum)."""
    B, S = input_ids.shape
    h = _embed(cfg, params, input_ids, 0)
    k0 = jnp.zeros((cfg.n_layer, B, S, cfg.kv_heads, cfg.head_dim), h.dtype)
    keys = (
        jax.random.split(rng, cfg.n_layer)
        if (rng is not None and train and cfg.mlp_type == "moe_swiglu")
        else None
    )

    def body(carry, xs):
        h, aux_sum = carry
        if keys is not None:
            lp, k_c, v_c, window, key = xs
        else:
            lp, k_c, v_c, window = xs
            key = None
        h, _, _, aux = _block(cfg, lp, h, k_c, v_c, 0, window, train, key)
        return (h, aux_sum + aux), None

    xs = (params["blocks"], k0, k0, _windows(cfg))
    if keys is not None:
        xs = xs + (keys,)
    (h, aux), _ = lax.scan(body, (h, jnp.float32(0.0)), xs)
    return _norm(cfg, h, params["ln_f"], cfg.layer_norm_epsilon), aux


def forward(cfg: DecoderConfig, params, input_ids, train: bool = False, rng=None):
    """Full-sequence logits [B,S,V] (training/eval path, no cache)."""
    h, _aux = hidden(cfg, params, input_ids, train=train, rng=rng)
    return _head(cfg, params, h)


def generate(
    cfg: DecoderConfig,
    params,
    input_ids,
    max_new_tokens: int,
    temperature: float = 0.0,
    rng=None,
    max_len: Optional[int] = None,
    cache_dtype=jnp.bfloat16,
    top_k: int = 0,
    top_p: float = 1.0,
):
    """Prefill + lax.scan decode (same structure as models/gpt2.generate)."""
    B, S = input_ids.shape
    if max_len is None:
        max_len = S + max_new_tokens
    if max_len > cfg.n_positions or max_len < S + max_new_tokens:
        raise ValueError(
            f"prompt ({S}) + max_new_tokens ({max_new_tokens}) needs a cache of "
            f"{S + max_new_tokens} but max_len={max_len} (n_positions={cfg.n_positions}); "
            "a shorter cache would silently overwrite KV entries"
        )
    if rng is None:
        rng = jax.random.PRNGKey(0)
    cache = init_cache(cfg, B, max_len, dtype=cache_dtype)
    logits, cache = forward_cached(cfg, params, input_ids, cache)

    from ..ops.sampling import sample_logits

    def sample(logits, key):
        return sample_logits(logits, key, temperature, top_k, top_p)

    first = sample(logits, rng)
    if max_new_tokens == 1:
        return first[:, None]

    def step(carry, key):
        token, cache = carry
        logits, cache = forward_cached(cfg, params, token[:, None].astype(input_ids.dtype), cache)
        return (sample(logits, key), cache), token

    keys = jax.random.split(jax.random.fold_in(rng, 1), max_new_tokens - 1)
    (last, _), tokens = lax.scan(step, (first, cache), keys)
    return jnp.concatenate([jnp.moveaxis(tokens, 0, 1), last[:, None]], axis=1)


def logical_axes(cfg: DecoderConfig) -> PyTree:
    """Sharding annotations (column-parallel q/k/v/fc_in, row-parallel o/fc_out)."""
    attn = {
        "wq": ("layers", "embed", "heads"), "wk": ("layers", "embed", "heads"),
        "wv": ("layers", "embed", "heads"), "wo": ("layers", "heads", "embed"),
        "bq": ("layers", "heads"), "bk": ("layers", "heads"),
        "bv": ("layers", "heads"), "bo": ("layers", "embed"),
    }
    if cfg.mlp_type == "moe_swiglu":
        from ..moe.sharded_moe import moe_mlp_logical_axes

        mlp = {
            k: ("layers",) + tuple(v)
            for k, v in moe_mlp_logical_axes(swiglu=True).items()
        }
    else:
        mlp = {
            "fc_in_w": ("layers", "embed", "mlp"), "fc_in_b": ("layers", "mlp"),
            "fc_out_w": ("layers", "mlp", "embed"), "fc_out_b": ("layers", "embed"),
            # swiglu gate (LLaMA): column-parallel like fc_in
            "fc_gate_w": ("layers", "embed", "mlp"),
        }
    ln = {"scale": ("layers", "embed"), "bias": ("layers", "embed")}
    axes = {
        "wte": ("vocab", "embed"),
        "ln_f": {"scale": ("embed",), "bias": ("embed",)},
        "blocks": {"ln_1": ln, "ln_2": ln, "attn": attn, "mlp": mlp},
    }
    if cfg.pos_emb == "learned":
        axes["wpe"] = (None, "embed")
    if cfg.embed_ln:
        axes["emb_ln"] = {"scale": ("embed",), "bias": ("embed",)}
    if not cfg.tie_embeddings:
        axes["lm_head_w"] = ("embed", "vocab")
        if cfg.lm_head_bias:
            axes["lm_head_b"] = ("vocab",)
    return axes


def lm_loss(cfg: DecoderConfig, params, batch, rng, train: bool):
    from .lm_loss import head_token_loss

    h, aux = hidden(cfg, params, batch["input_ids"], train=train, rng=rng)
    loss, _ntok = head_token_loss(
        lambda x: _head(cfg, params, x), h, batch, cfg.ce_chunk
    )
    # MoE load-balancing penalty shapes training only (gpt2.lm_loss parity)
    if cfg.mlp_type == "moe_swiglu" and train:
        loss = loss + cfg.moe_aux_loss_weight * aux
    return loss, {"moe_aux": aux}


def make_module(cfg: DecoderConfig) -> ModuleSpec:
    return ModuleSpec(
        init=None,  # decoder models are built from converted checkpoints
        loss_fn=lambda params, batch, rng, train: lm_loss(cfg, params, batch, rng, train),
        apply_fn=lambda params, batch: forward(cfg, params, batch["input_ids"]),
        logical_axes=logical_axes(cfg),
        num_layers=cfg.n_layer,
        extra={"config": cfg},
    )
