"""Shared next-token cross-entropy, full-logits or sequence-chunked.

Used by every LM family (gpt2, decoder zoo): one shift/mask convention and
one chunked path, so a label-convention change can't silently diverge
between models. The chunked path (``ce_chunk > 0``) never materializes the
full [B, S, V] logits — at GPT-2's 50k (or BLOOM's 250k) vocab those are
the dominant activation — and ``jax.checkpoint`` recomputes each chunk's
logits in backward, keeping gradients exact.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax


def shift_labels_mask(batch):
    """Next-token shift + ignore-index/attention masking shared by every LM
    loss path: returns (labels [B,S-1] clamped >=0, mask f32 [B,S-1])."""
    ids = batch["input_ids"]
    labels = batch.get("labels", ids)[:, 1:]
    mask = (labels != -100).astype(jnp.float32)
    if "attention_mask" in batch:
        mask = mask * batch["attention_mask"][:, 1:].astype(jnp.float32)
    return jnp.maximum(labels, 0), mask


def token_loss(logits_full, batch):
    """Shifted CE given full logits [B,S,V]. Returns (mean nll, ntokens)."""
    logits = logits_full[:, :-1]
    labels, mask = shift_labels_mask(batch)
    logz = jax.nn.logsumexp(logits.astype(jnp.float32), axis=-1)
    gold = jnp.take_along_axis(logits.astype(jnp.float32), labels[..., None], axis=-1)[..., 0]
    nll = (logz - gold) * mask
    return jnp.sum(nll) / jnp.maximum(jnp.sum(mask), 1.0), jnp.sum(mask)


def chunked_token_loss(project, h, batch, ce_chunk: int):
    """Shifted CE from final hidden states in sequence chunks of ``ce_chunk``
    positions: per chunk, ``project`` maps [..., E] hidden states to
    [..., V] logits (tied-embedding matmul or a separate lm head) and the
    chunk reduces to a scalar nll sum. Peak logits memory drops from
    [B,S,V] to [B,C,V]. Numerically identical to :func:`token_loss`."""
    labels_all, mask = shift_labels_mask(batch)
    h = h[:, :-1]
    B, S1, E = h.shape
    C = int(ce_chunk)
    pad = (-S1) % C
    if pad:
        h = jnp.pad(h, ((0, 0), (0, pad), (0, 0)))
        labels_all = jnp.pad(labels_all, ((0, 0), (0, pad)))
        mask = jnp.pad(mask, ((0, 0), (0, pad)))
    n_chunks = h.shape[1] // C
    h_c = h.reshape(B, n_chunks, C, E).transpose(1, 0, 2, 3)  # [nc,B,C,E]
    lab_c = labels_all.reshape(B, n_chunks, C).transpose(1, 0, 2)
    mask_c = mask.reshape(B, n_chunks, C).transpose(1, 0, 2)

    @jax.checkpoint
    def chunk_nll(carry, xs):
        hc, lc, mc = xs
        logits = project(hc).astype(jnp.float32)  # [B,C,V]
        logz = jax.nn.logsumexp(logits, axis=-1)
        gold = jnp.take_along_axis(logits, lc[..., None], axis=-1)[..., 0]
        return carry + jnp.sum((logz - gold) * mc), None

    total, _ = lax.scan(chunk_nll, jnp.float32(0.0), (h_c, lab_c, mask_c))
    ntokens = jnp.sum(mask)
    return total / jnp.maximum(ntokens, 1.0), ntokens


def head_token_loss(project, h, batch, ce_chunk: int = 0):
    """Head projection + shifted CE from final hidden states; chunked when
    ``ce_chunk`` > 0. ``project``: [..., E] -> [..., V]."""
    if ce_chunk > 0:
        return chunked_token_loss(project, h, batch, ce_chunk)
    return token_loss(project(h), batch)
