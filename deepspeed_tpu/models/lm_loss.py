"""Shared next-token cross-entropy, full-logits or sequence-chunked.

Used by every LM family (gpt2, decoder zoo): one shift/mask convention and
one chunked path, so a label-convention change can't silently diverge
between models. The chunked path (``ce_chunk > 0``) never materializes the
full [B, S, V] logits — at GPT-2's 50k (or BLOOM's 250k) vocab those are
the dominant activation — and ``jax.checkpoint`` recomputes each chunk's
logits in backward, keeping gradients exact.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax


def shift_labels_mask(batch):
    """Next-token shift + ignore-index/attention masking shared by every LM
    loss path: returns (labels [B,S-1] clamped >=0, mask f32 [B,S-1])."""
    ids = batch["input_ids"]
    labels = batch.get("labels", ids)[:, 1:]
    mask = (labels != -100).astype(jnp.float32)
    if "attention_mask" in batch:
        mask = mask * batch["attention_mask"][:, 1:].astype(jnp.float32)
    return jnp.maximum(labels, 0), mask


def mask_pad_vocab(logits, logical_vocab):
    """-inf the padded vocab columns (cols >= ``logical_vocab``) so a
    Megatron-style padded embedding (models/gpt2.py pad_vocab_multiple)
    contributes nothing to softmax/sampling and its rows get zero grad.
    No-op when the logits are unpadded or ``logical_vocab`` is None."""
    V = logits.shape[-1]
    if logical_vocab is None or V == int(logical_vocab):
        return logits
    col = lax.broadcasted_iota(jnp.int32, logits.shape, logits.ndim - 1)
    return jnp.where(col < int(logical_vocab), logits, jnp.asarray(-1e30, logits.dtype))


def token_loss(logits_full, batch, logical_vocab=None):
    """Shifted CE given full logits [B,S,V]. Returns (mean nll, ntokens)."""
    logits = logits_full[:, :-1]
    labels, mask = shift_labels_mask(batch)
    lf = mask_pad_vocab(logits.astype(jnp.float32), logical_vocab)
    logz = jax.nn.logsumexp(lf, axis=-1)
    gold = jnp.take_along_axis(lf, labels[..., None], axis=-1)[..., 0]
    nll = (logz - gold) * mask
    return jnp.sum(nll) / jnp.maximum(jnp.sum(mask), 1.0), jnp.sum(mask)


def chunked_token_loss(project, h, batch, ce_chunk: int, logical_vocab=None):
    """Shifted CE from final hidden states in sequence chunks of ``ce_chunk``
    positions: per chunk, ``project`` maps [..., E] hidden states to
    [..., V] logits (tied-embedding matmul or a separate lm head) and the
    chunk reduces to a scalar nll sum. Peak logits memory drops from
    [B,S,V] to [B,C,V]. Numerically identical to :func:`token_loss`.

    Data-movement design (r4 xplane profile: the old transpose-then-scan
    shape put ~44% of device time into copy/layout ops): ``h`` is consumed
    UNSLICED — the final position is excluded by a zero mask column rather
    than an ``h[:, :-1]`` slice (a full [B,S-1,E] copy on TPU) — and chunks
    are taken as static S-slices XLA can fuse into the projection matmul's
    operand read, instead of transposing all hiddens to [nc,B,C,E] and
    paying the scan's per-iteration gathers. Sequences longer than 32
    chunks fall back to a dynamic-slice scan (bounded program size), still
    layout-preserving."""
    labels_all, mask = shift_labels_mask(batch)  # [B,S-1]
    S = h.shape[1]
    # pad labels/mask back to S columns (mask 0 at the final position) so h
    # itself never needs the [:, :-1] slice; the masked position's logits
    # cost one extra row of matmul and contribute exactly 0 to the nll
    labels_all = jnp.pad(labels_all, ((0, 0), (0, 1)))
    mask = jnp.pad(mask, ((0, 0), (0, 1)))
    C = int(ce_chunk)

    @jax.checkpoint
    def chunk_nll(hc, lc, mc):
        logits = mask_pad_vocab(project(hc).astype(jnp.float32), logical_vocab)
        logz = jax.nn.logsumexp(logits, axis=-1)
        gold = jnp.take_along_axis(logits, lc[..., None], axis=-1)[..., 0]
        return jnp.sum((logz - gold) * mc)

    n_chunks = -(-S // C)
    if n_chunks <= 32:
        total = jnp.float32(0.0)
        for i in range(n_chunks):
            sl = slice(i * C, min((i + 1) * C, S))
            total = total + chunk_nll(h[:, sl], labels_all[:, sl], mask[:, sl])
    else:
        pad = (-S) % C
        hp, lp, mp = h, labels_all, mask
        if pad:
            hp = jnp.pad(hp, ((0, 0), (0, pad), (0, 0)))
            lp = jnp.pad(lp, ((0, 0), (0, pad)))
            mp = jnp.pad(mp, ((0, 0), (0, pad)))

        def body(carry, i):
            hc = lax.dynamic_slice_in_dim(hp, i * C, C, axis=1)
            lc = lax.dynamic_slice_in_dim(lp, i * C, C, axis=1)
            mc = lax.dynamic_slice_in_dim(mp, i * C, C, axis=1)
            return carry + chunk_nll(hc, lc, mc), None

        total, _ = lax.scan(
            body, jnp.float32(0.0), jnp.arange(hp.shape[1] // C)
        )
    ntokens = jnp.sum(mask)
    return total / jnp.maximum(ntokens, 1.0), ntokens


def head_token_loss(project, h, batch, ce_chunk: int = 0, logical_vocab=None):
    """Head projection + shifted CE from final hidden states; chunked when
    ``ce_chunk`` > 0. ``project``: [..., E] -> [..., V]. ``logical_vocab``
    masks padded vocab columns when the head is wider than the vocabulary
    (see :func:`mask_pad_vocab`)."""
    if ce_chunk > 0:
        return chunked_token_loss(project, h, batch, ce_chunk, logical_vocab)
    return token_loss(project(h), batch, logical_vocab)
