"""GPT-2 model family, TPU-first.

This is the flagship training workload (BASELINE.md configs: GPT-2 125M ZeRO-1,
GPT-2-XL 1.5B ZeRO-3). It is NOT a port of any torch modeling code — it is
written for XLA:

- **scan-over-layers**: all transformer blocks are stacked into one pytree
  with a leading ``layers`` dim and executed with ``lax.scan`` → O(1) HLO
  size regardless of depth, fast compiles, and a natural unit for pipeline
  stage partitioning later.
- **logical axis annotations** on every param (consumed by
  ``ZeroShardingPolicy``): Megatron-style column-parallel QKV/FC1 (out-dim on
  ``tp``) and row-parallel proj/FC2 (in-dim on ``tp``); ``vocab`` on ``tp``;
  ZeRO then shards the biggest free dim over ``dp``. XLA inserts the TP
  allreduces the reference does by hand inside fused kernels
  (ops/transformer/inference/transformer_inference.py TP allreduce).
- **remat** per block via ``jax.checkpoint`` (the activation-checkpointing
  analog of runtime/activation_checkpointing/checkpointing.py).
- attention runs through ``deepspeed_tpu.ops.attention`` which picks a Pallas
  flash kernel on TPU or a reference jnp path elsewhere.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec

from ..runtime.module import ModuleSpec

PyTree = Any


@dataclass
class GPT2Config:
    vocab_size: int = 50257
    n_positions: int = 1024
    n_embd: int = 768
    n_layer: int = 12
    n_head: int = 12
    dropout: float = 0.0
    layer_norm_epsilon: float = 1e-5
    use_bias: bool = True
    remat: bool = False
    attn_impl: str = "auto"  # auto | pallas | jnp
    dtype: Any = jnp.float32  # param init dtype (master)

    @property
    def head_dim(self) -> int:
        return self.n_embd // self.n_head


# name → config, sizes per the GPT-2 paper / HF checkpoints
PRESETS: Dict[str, Dict] = {
    "gpt2-tiny": dict(n_embd=64, n_layer=2, n_head=4, vocab_size=512, n_positions=128),
    "gpt2": dict(n_embd=768, n_layer=12, n_head=12),
    "gpt2-125m": dict(n_embd=768, n_layer=12, n_head=12),
    "gpt2-medium": dict(n_embd=1024, n_layer=24, n_head=16),
    "gpt2-large": dict(n_embd=1280, n_layer=36, n_head=20),
    "gpt2-xl": dict(n_embd=1600, n_layer=48, n_head=25),
}


def get_config(name: str, **overrides) -> GPT2Config:
    base = dict(PRESETS[name])
    base.update(overrides)
    return GPT2Config(**base)


# ---------------------------------------------------------------------------
# params
# ---------------------------------------------------------------------------

def init_params(cfg: GPT2Config, rng) -> PyTree:
    """Initializer; runs under jit with sharded out_shardings (zero.Init analog)."""
    E, L, V, P = cfg.n_embd, cfg.n_layer, cfg.vocab_size, cfg.n_positions
    k = iter(jax.random.split(rng, 16))
    std = 0.02
    # residual-projection init scaled by 1/sqrt(2L) (GPT-2 scheme)
    pstd = std / jnp.sqrt(2.0 * L)
    dt = cfg.dtype

    def normal(key, shape, s):
        return (jax.random.normal(key, shape) * s).astype(dt)

    params = {
        "wte": normal(next(k), (V, E), std),
        "wpe": normal(next(k), (P, E), std),
        "ln_f": {"scale": jnp.ones((E,), dt), "bias": jnp.zeros((E,), dt)},
        "blocks": {
            "ln_1": {"scale": jnp.ones((L, E), dt), "bias": jnp.zeros((L, E), dt)},
            "ln_2": {"scale": jnp.ones((L, E), dt), "bias": jnp.zeros((L, E), dt)},
            "attn": {
                "c_attn_w": normal(next(k), (L, E, 3 * E), std),
                "c_attn_b": jnp.zeros((L, 3 * E), dt),
                "c_proj_w": normal(next(k), (L, E, E), pstd),
                "c_proj_b": jnp.zeros((L, E), dt),
            },
            "mlp": {
                "c_fc_w": normal(next(k), (L, E, 4 * E), std),
                "c_fc_b": jnp.zeros((L, 4 * E), dt),
                "c_proj_w": normal(next(k), (L, 4 * E, E), pstd),
                "c_proj_b": jnp.zeros((L, E), dt),
            },
        },
    }
    return params


def logical_axes() -> PyTree:
    """Logical-axis names per param (see zero/partitioning.DEFAULT_LOGICAL_RULES)."""
    return {
        "wte": ("vocab", "embed"),
        "wpe": (None, "embed"),
        "ln_f": {"scale": ("embed",), "bias": ("embed",)},
        "blocks": {
            "ln_1": {"scale": ("layers", "embed"), "bias": ("layers", "embed")},
            "ln_2": {"scale": ("layers", "embed"), "bias": ("layers", "embed")},
            "attn": {
                "c_attn_w": ("layers", "embed", "qkv"),
                "c_attn_b": ("layers", "qkv"),
                "c_proj_w": ("layers", "heads", "embed"),
                "c_proj_b": ("layers", "embed"),
            },
            "mlp": {
                "c_fc_w": ("layers", "embed", "mlp"),
                "c_fc_b": ("layers", "mlp"),
                "c_proj_w": ("layers", "mlp", "embed"),
                "c_proj_b": ("layers", "embed"),
            },
        },
    }


# ---------------------------------------------------------------------------
# forward
# ---------------------------------------------------------------------------

def _layer_norm(x, scale, bias, eps):
    m = jnp.mean(x, axis=-1, keepdims=True)
    v = jnp.var(x, axis=-1, keepdims=True)
    return (x - m) * lax.rsqrt(v + eps) * scale + bias


def _dropout(x, rate: float, rng, train: bool):
    if not train or rate <= 0.0 or rng is None:
        return x
    keep = jax.random.bernoulli(rng, 1.0 - rate, x.shape)
    return jnp.where(keep, x / (1.0 - rate), jnp.zeros((), x.dtype))


def _attention(cfg: GPT2Config, lp, h, train: bool, rng=None):
    B, S, E = h.shape
    H, D = cfg.n_head, cfg.head_dim
    qkv = h @ lp["c_attn_w"] + lp["c_attn_b"]  # [B,S,3E]
    q, k_, v = jnp.split(qkv, 3, axis=-1)

    def heads(x):
        return x.reshape(B, S, H, D)

    q, k_, v = heads(q), heads(k_), heads(v)

    from ..ops.attention import causal_attention

    o = causal_attention(q, k_, v, impl=cfg.attn_impl)  # [B,S,H,D]
    o = o.reshape(B, S, E)
    out = o @ lp["c_proj_w"] + lp["c_proj_b"]
    return out


def _mlp(lp, h):
    x = h @ lp["c_fc_w"] + lp["c_fc_b"]
    x = jax.nn.gelu(x, approximate=True)
    return x @ lp["c_proj_w"] + lp["c_proj_b"]


def _block(cfg: GPT2Config, layer_params, h, train: bool, rng=None):
    eps = cfg.layer_norm_epsilon
    r1 = r2 = None
    if rng is not None:
        r1, r2 = jax.random.split(rng)
    a = _attention(cfg, layer_params["attn"], _layer_norm(h, layer_params["ln_1"]["scale"], layer_params["ln_1"]["bias"], eps), train, r1)
    h = h + _dropout(a, cfg.dropout, r1, train)
    m = _mlp(layer_params["mlp"], _layer_norm(h, layer_params["ln_2"]["scale"], layer_params["ln_2"]["bias"], eps))
    return h + _dropout(m, cfg.dropout, r2, train)


def forward(
    cfg: GPT2Config,
    params: PyTree,
    input_ids: jnp.ndarray,
    train: bool = False,
    rng=None,
) -> jnp.ndarray:
    """input_ids [B,S] → logits [B,S,V]. ``rng`` enables dropout when train."""
    B, S = input_ids.shape
    h = params["wte"][input_ids] + params["wpe"][:S][None, :, :]
    use_dropout = train and cfg.dropout > 0.0 and rng is not None
    if use_dropout:
        h = _dropout(h, cfg.dropout, jax.random.fold_in(rng, -1), train)
        layer_keys = jax.random.split(jax.random.fold_in(rng, 0), cfg.n_layer)

        def body(carry, x):
            layer_params, key = x
            return _block(cfg, layer_params, carry, train, key), None

        xs = (params["blocks"], layer_keys)
    else:

        def body(carry, layer_params):
            return _block(cfg, layer_params, carry, train, None), None

        xs = params["blocks"]

    if cfg.remat:
        body = jax.checkpoint(body, prevent_cse=False)
    h, _ = lax.scan(body, h, xs)
    h = _layer_norm(h, params["ln_f"]["scale"], params["ln_f"]["bias"], cfg.layer_norm_epsilon)
    logits = h @ params["wte"].T  # tied embeddings
    return logits


def lm_loss(cfg: GPT2Config, params: PyTree, batch: Dict[str, jnp.ndarray], rng, train: bool) -> Tuple[jnp.ndarray, Dict]:
    """Next-token cross-entropy. batch: {"input_ids": [B,S]} and optional
    {"labels": [B,S]} (-100 = ignore, HF convention) / {"attention_mask"}."""
    ids = batch["input_ids"]
    logits = forward(cfg, params, ids, train=train, rng=rng)[:, :-1]
    labels = batch.get("labels", ids)[:, 1:]
    mask = (labels != -100).astype(jnp.float32)
    if "attention_mask" in batch:
        mask = mask * batch["attention_mask"][:, 1:].astype(jnp.float32)
    labels = jnp.maximum(labels, 0)
    logz = jax.nn.logsumexp(logits.astype(jnp.float32), axis=-1)
    gold = jnp.take_along_axis(logits.astype(jnp.float32), labels[..., None], axis=-1)[..., 0]
    nll = (logz - gold) * mask
    loss = jnp.sum(nll) / jnp.maximum(jnp.sum(mask), 1.0)
    return loss, {"ntokens": jnp.sum(mask)}


def make_module(cfg: GPT2Config) -> ModuleSpec:
    return ModuleSpec(
        init=lambda rng: init_params(cfg, rng),
        loss_fn=lambda params, batch, rng, train: lm_loss(cfg, params, batch, rng, train),
        apply_fn=lambda params, batch: forward(cfg, params, batch["input_ids"], train=False),
        logical_axes=logical_axes(),
        num_layers=cfg.n_layer,
        extra={"config": cfg},
    )
