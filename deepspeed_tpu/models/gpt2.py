"""GPT-2 model family, TPU-first.

This is the flagship training workload (BASELINE.md configs: GPT-2 125M ZeRO-1,
GPT-2-XL 1.5B ZeRO-3). It is NOT a port of any torch modeling code — it is
written for XLA:

- **scan-over-layers**: all transformer blocks are stacked into one pytree
  with a leading ``layers`` dim and executed with ``lax.scan`` → O(1) HLO
  size regardless of depth, fast compiles, and a natural unit for pipeline
  stage partitioning later.
- **logical axis annotations** on every param (consumed by
  ``ZeroShardingPolicy``): Megatron-style column-parallel QKV/FC1 (out-dim on
  ``tp``) and row-parallel proj/FC2 (in-dim on ``tp``); ``vocab`` on ``tp``;
  ZeRO then shards the biggest free dim over ``dp``. XLA inserts the TP
  allreduces the reference does by hand inside fused kernels
  (ops/transformer/inference/transformer_inference.py TP allreduce).
- **remat** per block via ``jax.checkpoint`` (the activation-checkpointing
  analog of runtime/activation_checkpointing/checkpointing.py).
- attention runs through ``deepspeed_tpu.ops.attention`` which picks a Pallas
  flash kernel on TPU or a reference jnp path elsewhere.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Dict, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.ad_checkpoint import checkpoint_name
from jax import lax
from jax.sharding import PartitionSpec

from ..ops.quantizer import maybe_dequantize as _deq
from ..ops.layer_norm import layer_norm
from ..runtime.module import ModuleSpec

PyTree = Any


@dataclass(frozen=True)
class GPT2Config:
    vocab_size: int = 50257
    n_positions: int = 1024
    n_embd: int = 768
    n_layer: int = 12
    n_head: int = 12
    dropout: float = 0.0
    layer_norm_epsilon: float = 1e-5
    use_bias: bool = True
    remat: bool = False
    # activation-checkpointing extensions (reference checkpointing.py:367/:480):
    # shard the saved per-layer boundary activation over tp (needs cfg.mesh),
    # and/or offload it to pinned host RAM between forward and backward
    partition_activations: bool = False
    cpu_checkpointing: bool = False
    # remat granularity: "full" recomputes the whole block in backward
    # (cheapest memory, +~1/3 executed flops); "dots" saves every matmul
    # output PLUS the attention-kernel output and recomputes only the cheap
    # elementwise ops (memory between no-remat and full remat,
    # near-no-remat flops); "attn" saves ONLY the attention output — one
    # extra [B,S,E] per layer beyond full remat, but the backward never
    # re-runs the (flash-kernel) attention forward, the most expensive
    # recompute in the block
    remat_policy: str = "full"
    attn_impl: str = "auto"  # auto | pallas | jnp | ring | ring_flash | ulysses | sparse
    # >0: compute the LM cross-entropy in sequence chunks of this many
    # positions, never materializing the full [B,S,V] logits (at GPT-2
    # vocab 50257 and seq 1024 those are ~100 MB/sample in f32 — the
    # dominant activation). Backward recomputes each chunk's logits
    # (jax.checkpoint). 0 = classic full-logits path.
    ce_chunk: int = 0
    # for attn_impl="sparse": a SparsityConfig instance (or None → Fixed
    # defaults). Built from the engine config's ``sparse_attention`` section
    # via ops.sparse_attention.from_ds_config (reference
    # get_sparse_attention_config, deepspeed/__init__.py)
    sparsity: Any = None
    # mesh is required for the sequence-parallel attention impls ("ring",
    # "ulysses") — they shard_map over its sp axis (parallel/sequence.py)
    mesh: Any = None
    dtype: Any = jnp.float32  # param init dtype (master)
    # MoE (DeepSpeed-MoE capability, Switch-style: every MLP is an expert
    # layer so scan-over-layers stays homogeneous). 0 = dense.
    moe_experts: int = 0
    moe_top_k: int = 1
    moe_capacity_factor: float = 1.25
    moe_eval_capacity_factor: Optional[float] = None  # None → moe_capacity_factor
    moe_aux_loss_weight: float = 0.01
    moe_drop_tokens: bool = True  # False → static no-drop capacity (C = T)
    moe_use_rts: bool = True  # Random Token Selection on capacity overflow
    moe_second_policy: str = "random"  # top-2 second expert: random | argmax

    # Megatron-style vocab padding (make-vocab-size-divisible-by): pad the
    # embedding table to a multiple of this so every head matmul runs on an
    # MXU-lane-aligned vocab dim (GPT-2's 50257 is not 128-divisible).
    # vocab_size stays the LOGICAL vocab everywhere — ids, labels, analytic
    # FLOPs; only the wte array and logits carry padded_vocab_size columns,
    # which the loss and sampling paths mask to -inf (pad rows are
    # zero-initialized and receive exactly zero gradient). 1 = off.
    pad_vocab_multiple: int = 1

    @property
    def padded_vocab_size(self) -> int:
        m = max(1, int(self.pad_vocab_multiple))
        return -(-self.vocab_size // m) * m

    @property
    def head_dim(self) -> int:
        return self.n_embd // self.n_head

    @property
    def is_moe(self) -> bool:
        return self.moe_experts > 0


# name → config, sizes per the GPT-2 paper / HF checkpoints
PRESETS: Dict[str, Dict] = {
    "gpt2-tiny": dict(n_embd=64, n_layer=2, n_head=4, vocab_size=512, n_positions=128),
    "gpt2": dict(n_embd=768, n_layer=12, n_head=12),
    "gpt2-125m": dict(n_embd=768, n_layer=12, n_head=12),
    "gpt2-medium": dict(n_embd=1024, n_layer=24, n_head=16),
    "gpt2-large": dict(n_embd=1280, n_layer=36, n_head=20),
    "gpt2-xl": dict(n_embd=1600, n_layer=48, n_head=25),
}


def get_config(name: str, **overrides) -> GPT2Config:
    base = dict(PRESETS[name])
    base.update(overrides)
    # the engine config's ``sparse_attention`` section (dict or typed) turns
    # on the block-sparse kernel with the requested pattern (reference
    # get_sparse_attention_config consumption in client models)
    section = base.pop("sparse_attention", None)
    if section is not None:
        from ..ops.sparse_attention import from_ds_config

        # an explicit attn_impl override wins (e.g. attn_impl="jnp" to A/B
        # the dense path with the section still present)
        base.setdefault("attn_impl", "sparse")
        base["sparsity"] = from_ds_config(section, base.get("n_head", 12))
    return GPT2Config(**base)


# ---------------------------------------------------------------------------
# params
# ---------------------------------------------------------------------------

def init_params(cfg: GPT2Config, rng) -> PyTree:
    """Initializer; runs under jit with sharded out_shardings (zero.Init analog)."""
    E, L, V, P = cfg.n_embd, cfg.n_layer, cfg.vocab_size, cfg.n_positions
    k = iter(jax.random.split(rng, 16))
    std = 0.02
    # residual-projection init scaled by 1/sqrt(2L) (GPT-2 scheme)
    pstd = std / jnp.sqrt(2.0 * L)
    dt = cfg.dtype

    def normal(key, shape, s):
        return (jax.random.normal(key, shape) * s).astype(dt)

    Vp = cfg.padded_vocab_size
    wte = normal(next(k), (Vp, E), std)
    if Vp > V:  # pad rows exactly zero: masked out of loss/sampling, zero grad
        wte = wte.at[V:].set(0)
    params = {
        "wte": wte,
        "wpe": normal(next(k), (P, E), std),
        "ln_f": {"scale": jnp.ones((E,), dt), "bias": jnp.zeros((E,), dt)},
        "blocks": {
            "ln_1": {"scale": jnp.ones((L, E), dt), "bias": jnp.zeros((L, E), dt)},
            "ln_2": {"scale": jnp.ones((L, E), dt), "bias": jnp.zeros((L, E), dt)},
            "attn": {
                "c_attn_w": normal(next(k), (L, E, 3 * E), std),
                "c_attn_b": jnp.zeros((L, 3 * E), dt),
                "c_proj_w": normal(next(k), (L, E, E), pstd),
                "c_proj_b": jnp.zeros((L, E), dt),
            },
            "mlp": _init_mlp(cfg, [next(k), next(k), next(k)], std, pstd, dt),
        },
    }
    return params


def _init_mlp(cfg: GPT2Config, keys, std, pstd, dt):
    E, L = cfg.n_embd, cfg.n_layer

    def normal(key, shape, s):
        return (jax.random.normal(key, shape) * s).astype(dt)

    if not cfg.is_moe:
        return {
            "c_fc_w": normal(keys[0], (L, E, 4 * E), std),
            "c_fc_b": jnp.zeros((L, 4 * E), dt),
            "c_proj_w": normal(keys[1], (L, 4 * E, E), pstd),
            "c_proj_b": jnp.zeros((L, E), dt),
        }
    X = cfg.moe_experts
    return {
        "gate_w": normal(keys[2], (L, E, X), std).astype(jnp.float32),
        "w_in": normal(keys[0], (L, X, E, 4 * E), std),
        "b_in": jnp.zeros((L, X, 4 * E), dt),
        "w_out": normal(keys[1], (L, X, 4 * E, E), pstd),
        "b_out": jnp.zeros((L, X, E), dt),
    }


def logical_axes(cfg: Optional[GPT2Config] = None) -> PyTree:
    """Logical-axis names per param (see zero/partitioning.DEFAULT_LOGICAL_RULES)."""
    moe = cfg is not None and cfg.is_moe
    if moe:
        mlp = {
            "gate_w": ("layers", "embed", None),
            "w_in": ("layers", "expert", "embed", "expert_mlp"),
            "b_in": ("layers", "expert", "expert_mlp"),
            "w_out": ("layers", "expert", "expert_mlp", "embed"),
            "b_out": ("layers", "expert", "embed"),
        }
    else:
        mlp = {
            "c_fc_w": ("layers", "embed", "mlp"),
            "c_fc_b": ("layers", "mlp"),
            "c_proj_w": ("layers", "mlp", "embed"),
            "c_proj_b": ("layers", "embed"),
        }
    return {
        "wte": ("vocab", "embed"),
        "wpe": (None, "embed"),
        "ln_f": {"scale": ("embed",), "bias": ("embed",)},
        "blocks": {
            "ln_1": {"scale": ("layers", "embed"), "bias": ("layers", "embed")},
            "ln_2": {"scale": ("layers", "embed"), "bias": ("layers", "embed")},
            "attn": {
                "c_attn_w": ("layers", "embed", "qkv"),
                "c_attn_b": ("layers", "qkv"),
                "c_proj_w": ("layers", "heads", "embed"),
                "c_proj_b": ("layers", "embed"),
            },
            "mlp": mlp,
        },
    }


# ---------------------------------------------------------------------------
# forward
# ---------------------------------------------------------------------------

def _layer_norm(x, scale, bias, eps):
    return layer_norm(x, scale, bias, eps)


def _dropout(x, rate: float, rng, train: bool):
    if not train or rate <= 0.0 or rng is None:
        return x
    keep = jax.random.bernoulli(rng, 1.0 - rate, x.shape)
    return jnp.where(keep, x / (1.0 - rate), jnp.zeros((), x.dtype))


def _attention(cfg: GPT2Config, lp, h, train: bool, rng=None):
    B, S, E = h.shape
    H, D = cfg.n_head, cfg.head_dim
    qkv = h @ _deq(lp["c_attn_w"], h.dtype) + lp["c_attn_b"]  # [B,S,3E]
    q, k_, v = jnp.split(qkv, 3, axis=-1)

    def heads(x):
        return x.reshape(B, S, H, D)

    q, k_, v = heads(q), heads(k_), heads(v)

    if cfg.attn_impl in ("ring", "ring_flash", "ulysses"):
        from ..parallel.sequence import sequence_parallel_attention

        assert cfg.mesh is not None, f"attn_impl={cfg.attn_impl} requires cfg.mesh"
        o = sequence_parallel_attention(q, k_, v, cfg.mesh, impl=cfg.attn_impl)
    elif cfg.attn_impl == "sparse":
        from ..ops.sparse_attention import FixedSparsityConfig, sparse_attention

        sp = cfg.sparsity or FixedSparsityConfig(num_heads=H)
        o = sparse_attention(q, k_, v, sp, causal=True)
    else:
        from ..ops.attention import causal_attention

        o = causal_attention(q, k_, v, impl=cfg.attn_impl)  # [B,S,H,D]
    # name the kernel output so remat policies can save it: a Pallas
    # custom_vjp output is not a dot_general, so even dots_saveable would
    # otherwise re-run the whole flash forward to rebuild c_proj's input
    o = checkpoint_name(o.reshape(B, S, E), "attn_out")
    out = o @ _deq(lp["c_proj_w"], o.dtype) + lp["c_proj_b"]
    return out


def _mlp(cfg: GPT2Config, lp, h, train: bool, rng=None, tp_axis=None):
    """Dense or MoE FFN; returns (out, aux_loss).

    ``tp_axis`` (ISSUE 14): under the TP-sharded serving ``shard_map``, the
    dense branch's weights arrive column-parallel (``c_fc``) / row-parallel
    (``c_proj``) slices — the projection's partial product is psum-reduced
    over the named axis BEFORE the replicated bias is added once. None (the
    default, and every training caller) is the exact historical graph."""
    if cfg.is_moe:
        from ..moe.sharded_moe import MoEConfig, moe_mlp

        mcfg = MoEConfig(
            num_experts=cfg.moe_experts,
            k=cfg.moe_top_k,
            capacity_factor=cfg.moe_capacity_factor,
            eval_capacity_factor=(
                cfg.moe_eval_capacity_factor
                if cfg.moe_eval_capacity_factor is not None
                else cfg.moe_capacity_factor
            ),
            drop_tokens=cfg.moe_drop_tokens,
            use_rts=cfg.moe_use_rts,
            second_policy=cfg.moe_second_policy,
        )
        return moe_mlp(lp, h, mcfg, rng=rng, train=train, mesh=cfg.mesh)
    x = h @ _deq(lp["c_fc_w"], h.dtype) + lp["c_fc_b"]
    x = jax.nn.gelu(x, approximate=True)
    out = x @ _deq(lp["c_proj_w"], x.dtype)
    if tp_axis is not None:
        out = jax.lax.psum(out, tp_axis)
    return out + lp["c_proj_b"], jnp.float32(0.0)


def _block(cfg: GPT2Config, layer_params, h, train: bool, rng=None):
    eps = cfg.layer_norm_epsilon
    r1 = r2 = r3 = None
    if rng is not None:
        # distinct keys per stochastic op: attn dropout, MoE routing, mlp dropout
        r1, r2, r3 = jax.random.split(rng, 3)
    a = _attention(cfg, layer_params["attn"], _layer_norm(h, layer_params["ln_1"]["scale"], layer_params["ln_1"]["bias"], eps), train, r1)
    h = h + _dropout(a, cfg.dropout, r1, train)
    m, aux = _mlp(cfg, layer_params["mlp"], _layer_norm(h, layer_params["ln_2"]["scale"], layer_params["ln_2"]["bias"], eps), train, r2)
    return h + _dropout(m, cfg.dropout, r3, train), aux


def _tag_boundary(cfg: GPT2Config, h):
    """Mark the block-input boundary activation for host offload under
    ``cpu_checkpointing`` (reference checkpointing.py:480). With the
    save-and-offload remat policy the saved residual — the checkpointed
    body's input — lives in pinned host RAM between forward and backward."""
    if cfg.remat and cfg.cpu_checkpointing:
        from ..runtime.activation_checkpointing.checkpointing import offload_name

        return offload_name(h)
    return h


def _partition_boundary(cfg: GPT2Config, h):
    """Shard the block-output boundary activation over tp (reference
    partition_activations, checkpointing.py:367): the scan saves each carry
    as a residual, so constraining the produced carry makes every saved
    checkpoint live as 1/tp slices; XLA all-gathers in backward exactly where
    the reference calls gather_partitioned_activations:259."""
    if (
        cfg.partition_activations
        and cfg.mesh is not None
        and "tp" in cfg.mesh.axis_names
        and cfg.mesh.shape["tp"] > 1
        and h.shape[-1] % cfg.mesh.shape["tp"] == 0
    ):
        from jax.sharding import NamedSharding

        return lax.with_sharding_constraint(
            h, NamedSharding(cfg.mesh, PartitionSpec(None, None, "tp"))
        )
    return h


def _remat_policy(cfg: GPT2Config):
    """jax.checkpoint policy for the block body: offload-capable when
    cpu_checkpointing; "dots" saves matmul + attention-kernel outputs
    (recompute only the cheap elementwise tail); "attn" saves only the
    attention output (backward never re-runs the flash forward); default
    full remat (save nothing, recompute)."""
    if cfg.cpu_checkpointing:
        from ..runtime.activation_checkpointing.checkpointing import _offload_policy

        return _offload_policy()
    if cfg.remat_policy == "dots":
        return jax.checkpoint_policies.save_from_both_policies(
            jax.checkpoint_policies.dots_saveable,
            jax.checkpoint_policies.save_only_these_names("attn_out"),
        )
    if cfg.remat_policy == "attn":
        return jax.checkpoint_policies.save_only_these_names("attn_out")
    if cfg.remat_policy != "full":
        raise ValueError(
            f"unknown remat_policy {cfg.remat_policy!r} (full|dots|attn)"
        )
    return None


def _pld_block(cfg: GPT2Config, layer_params, h, train: bool, key, theta, layer_id, pld_key):
    """Stochastic-depth block for Progressive Layer Drop (reference
    progressive_layer_drop.py:5). Layer i of L keeps with probability
    ``1 - (i/L)*(1-theta)``; ``lax.cond`` actually skips the dropped block's
    FLOPs (the training-speedup point of PLD), and the kept output's residual
    delta is scaled by 1/keep_prob so the eval forward (all layers, no
    scaling) matches in expectation."""
    kp = 1.0 - (layer_id / cfg.n_layer) * (1.0 - theta)
    keep = jax.random.bernoulli(pld_key, kp)
    hb, aux = lax.cond(
        keep,
        lambda hh: _block(cfg, layer_params, hh, train, key),
        lambda hh: (hh, jnp.float32(0.0)),
        h,
    )
    # both the residual delta and the MoE aux loss are inverse-scaled so their
    # expectations match the all-layers forward (aux fires only when kept)
    return h + (hb - h) / kp.astype(h.dtype), aux / kp


def hidden_with_aux(
    cfg: GPT2Config,
    params: PyTree,
    input_ids: jnp.ndarray,
    train: bool = False,
    rng=None,
    pld_theta=None,
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """input_ids [B,S] → (final-LN hidden states [B,S,E], moe_aux_loss
    scalar) — the pre-head trunk, so losses can choose whether to
    materialize full logits. ``pld_theta`` (traced scalar) engages
    progressive layer drop during training."""
    B, S = input_ids.shape
    h = params["wte"][input_ids] + params["wpe"][:S][None, :, :]
    # rng per layer when dropout or MoE stochastic routing needs it
    need_rng = rng is not None and (
        (train and cfg.dropout > 0.0)
        or (cfg.is_moe and train and (cfg.moe_top_k == 2 or cfg.moe_use_rts))
    )
    use_pld = pld_theta is not None and train and rng is not None
    if need_rng or use_pld:
        if train and cfg.dropout > 0.0:
            h = _dropout(h, cfg.dropout, jax.random.fold_in(rng, cfg.n_layer), train)
        xs = {
            "lp": params["blocks"],
            "key": jax.random.split(jax.random.fold_in(rng, 0), cfg.n_layer),
        }
        if use_pld:
            theta = jnp.asarray(pld_theta, jnp.float32)
            xs["pld_key"] = jax.random.split(jax.random.fold_in(rng, 1), cfg.n_layer)
            xs["layer_id"] = jnp.arange(cfg.n_layer, dtype=jnp.float32)

        def body(carry, x):
            h, aux_sum = carry
            h = _tag_boundary(cfg, h)
            key = x["key"] if need_rng else None
            if use_pld:
                h, aux = _pld_block(
                    cfg, x["lp"], h, train, key, theta, x["layer_id"], x["pld_key"]
                )
            else:
                h, aux = _block(cfg, x["lp"], h, train, key)
            return (_partition_boundary(cfg, h), aux_sum + aux), None

    else:

        def body(carry, layer_params):
            h, aux_sum = carry
            h = _tag_boundary(cfg, h)
            h, aux = _block(cfg, layer_params, h, train, None)
            return (_partition_boundary(cfg, h), aux_sum + aux), None

        xs = params["blocks"]

    if cfg.remat:
        body = jax.checkpoint(body, policy=_remat_policy(cfg), prevent_cse=False)
    (h, aux_total), _ = lax.scan(body, (h, jnp.float32(0.0)), xs)
    h = _layer_norm(h, params["ln_f"]["scale"], params["ln_f"]["bias"], cfg.layer_norm_epsilon)
    return h, aux_total


def forward_with_aux(
    cfg: GPT2Config,
    params: PyTree,
    input_ids: jnp.ndarray,
    train: bool = False,
    rng=None,
    pld_theta=None,
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """input_ids [B,S] → (logits [B,S,V], moe_aux_loss scalar)."""
    h, aux_total = hidden_with_aux(
        cfg, params, input_ids, train=train, rng=rng, pld_theta=pld_theta
    )
    # tied embeddings; the public contract is [B,S,V] LOGICAL vocab — slice
    # off padded head columns (pad_vocab_multiple) rather than masking, so
    # shape-checking consumers (one_hot sizing, tokenizer tables) stay right
    logits = (h @ params["wte"].T)[..., : cfg.vocab_size]
    return logits, aux_total


def forward(cfg: GPT2Config, params: PyTree, input_ids: jnp.ndarray, train: bool = False, rng=None) -> jnp.ndarray:
    """input_ids [B,S] → logits [B,S,V]. ``rng`` enables dropout when train."""
    return forward_with_aux(cfg, params, input_ids, train=train, rng=rng)[0]


def lm_loss(
    cfg: GPT2Config,
    params: PyTree,
    batch: Dict[str, jnp.ndarray],
    rng,
    train: bool,
    pld_theta=None,
) -> Tuple[jnp.ndarray, Dict]:
    """Next-token cross-entropy. batch: {"input_ids": [B,S]} and optional
    {"labels": [B,S]} (-100 = ignore, HF convention) / {"attention_mask"}."""
    ids = batch["input_ids"]
    h, moe_aux = hidden_with_aux(
        cfg, params, ids, train=train, rng=rng, pld_theta=pld_theta
    )
    loss, ntokens = _head_token_loss(cfg, params["wte"], h, batch)
    # aux load-balancing penalty only shapes the training objective; eval loss
    # stays pure LM cross-entropy (comparable to dense baselines)
    if cfg.is_moe and train:
        loss = loss + cfg.moe_aux_loss_weight * moe_aux
    return loss, {"ntokens": ntokens, "moe_aux": moe_aux}


def _head_token_loss(cfg: GPT2Config, wte, h, batch):
    """Head projection + shifted CE from final hidden states; chunked when
    cfg.ce_chunk > 0 (shared by the plain, pipeline, and offload paths so
    the knob works everywhere). Math lives in models/lm_loss.py."""
    from .lm_loss import head_token_loss

    return head_token_loss(
        lambda x: x @ wte.T, h, batch, cfg.ce_chunk, logical_vocab=cfg.vocab_size
    )


def pipeline_lm_loss(cfg: GPT2Config, params: PyTree, batch_micro, rng, train: bool, mesh):
    """All-microbatch LM loss through the pp pipeline.

    batch_micro leaves are [M, mb, ...]; blocks run as pipeline stages
    (parallel/pipeline.py), embedding/head replicated (tied-grad psum is
    automatic — the _exec_reduce_tied_grads analog).
    """
    from ..parallel.pipeline import pipeline_apply

    ids = batch_micro["input_ids"]  # [M, mb, S]
    M, mb, S = ids.shape
    h0 = params["wte"][ids] + params["wpe"][:S][None, None, :, :]  # [M, mb, S, E]
    use_rng = rng is not None and train and cfg.dropout > 0.0
    if use_rng:
        h0 = _dropout(h0, cfg.dropout, jax.random.fold_in(rng, 2), train)

        def stage_fn(local_layers, h, key):
            def body(carry, lp):
                hh, j = carry
                out, _aux = _block(cfg, lp, hh, train, jax.random.fold_in(key, j))
                return (out, j + 1), None

            (h, _), _ = lax.scan(body, (h, jnp.int32(0)), local_layers)
            return h

    else:

        def stage_fn(local_layers, h):
            def body(carry, lp):
                out, _aux = _block(cfg, lp, carry, train, None)
                return out, None

            h, _ = lax.scan(body, h, local_layers)
            return h

    h_out = pipeline_apply(
        stage_fn,
        params["blocks"],
        h0,
        mesh,
        remat_stage=cfg.remat,
        rng=jax.random.fold_in(rng, 1) if use_rng else None,
    )
    h_out = _layer_norm(h_out, params["ln_f"]["scale"], params["ln_f"]["bias"], cfg.layer_norm_epsilon)

    # head matmul + loss per microbatch: materializing [M, mb, S, V] logits at
    # once would cost M× the activation memory the pipeline exists to save
    def per_micro(i, acc):
        micro_batch = jax.tree.map(lambda x: x[i], batch_micro)
        return acc + _head_token_loss(cfg, params["wte"], h_out[i], micro_batch)[0]

    total = lax.fori_loop(0, M, per_micro, jnp.float32(0.0))
    return total / M, {}


# ---------------------------------------------------------------------------
# incremental decode with KV cache (reference transformer_inference
# softmax_context path: ops/transformer/inference/transformer_inference.py:231,
# csrc/transformer/inference attention kernels with layer_past)
# ---------------------------------------------------------------------------

class KVCache(NamedTuple):
    """Per-layer stacked KV cache. ``pos`` is the filled length (i32)."""

    k: jnp.ndarray  # [L, B, Smax, H, D]
    v: jnp.ndarray  # [L, B, Smax, H, D]
    pos: jnp.ndarray  # i32


def init_cache(cfg: GPT2Config, batch_size: int, max_len: int, dtype=jnp.bfloat16) -> KVCache:
    shape = (cfg.n_layer, batch_size, max_len, cfg.n_head, cfg.head_dim)
    return KVCache(k=jnp.zeros(shape, dtype), v=jnp.zeros(shape, dtype), pos=jnp.int32(0))


def cache_logical_axes() -> KVCache:
    """Shard the cache over heads (tp) like attention activations."""
    return KVCache(k=(None, None, None, "heads", None), v=(None, None, None, "heads", None), pos=None)


def _attention_cached(cfg: GPT2Config, lp, h, k_cache, v_cache, pos):
    """Attention for h [B,S,E] against a KV cache.

    Writes this chunk's K/V at [pos, pos+S), attends causally to everything
    ≤ its absolute position. S=prompt length at prefill, 1 at decode."""
    B, S, E = h.shape
    H, D = cfg.n_head, cfg.head_dim
    qkv = h @ _deq(lp["c_attn_w"], h.dtype) + lp["c_attn_b"]
    q, k_, v = jnp.split(qkv, 3, axis=-1)
    q = q.reshape(B, S, H, D)
    k_ = k_.reshape(B, S, H, D).astype(k_cache.dtype)
    v = v.reshape(B, S, H, D).astype(v_cache.dtype)

    k_cache = lax.dynamic_update_slice(k_cache, k_, (0, pos, 0, 0))
    v_cache = lax.dynamic_update_slice(v_cache, v, (0, pos, 0, 0))

    Smax = k_cache.shape[1]
    scale = 1.0 / np.sqrt(D)

    if S == 1 and cfg.attn_impl in ("auto", "pallas"):
        # single-token decode: ops.cached_attention dispatches to the Pallas
        # online-softmax kernel on TPU (streams the cache through VMEM
        # instead of materializing [B,H,1,Smax] scores — the reference
        # softmax_context fused kernel) with a jnp fallback built in
        from ..ops.attention import cached_attention

        o1 = cached_attention(q[:, 0], k_cache, v_cache, pos, impl=cfg.attn_impl, sm_scale=scale)
        o = o1.reshape(B, 1, E).astype(h.dtype)  # [B,H,D] -> [B,1,E]
        return o @ _deq(lp["c_proj_w"], h.dtype) + lp["c_proj_b"], k_cache, v_cache

    scores = jnp.einsum("bshd,bthd->bhst", q.astype(jnp.float32), k_cache.astype(jnp.float32)) * scale
    # query i sits at absolute position pos+i; may see keys j <= pos+i
    j_idx = jnp.arange(Smax)
    i_idx = pos + jnp.arange(S)
    mask = j_idx[None, :] <= i_idx[:, None]  # [S, Smax]
    scores = jnp.where(mask[None, None, :, :], scores, -1e30)
    probs = jax.nn.softmax(scores, axis=-1).astype(v_cache.dtype)
    o = jnp.einsum("bhst,bthd->bshd", probs, v_cache)
    o = o.reshape(B, S, E).astype(h.dtype)
    return o @ _deq(lp["c_proj_w"], h.dtype) + lp["c_proj_b"], k_cache, v_cache


def forward_cached(
    cfg: GPT2Config, params: PyTree, input_ids: jnp.ndarray, cache: KVCache,
    logits_at=None,
) -> Tuple[jnp.ndarray, KVCache]:
    """input_ids [B,S] (S tokens starting at cache.pos) → (last-token logits
    [B,V], updated cache). One function serves prefill (S=prompt) and decode
    (S=1) — the reference splits these across qkv_gemm/softmax_context kernels.

    ``logits_at`` (optional traced i32): read the head at this in-chunk
    position instead of the last one — the bucket-padded prefill
    (serving/model.generate_padded) feeds a right-padded chunk and needs the
    logits of the true last prompt token.
    """
    B, S = input_ids.shape
    pos = cache.pos
    eps = cfg.layer_norm_epsilon
    positions = pos + jnp.arange(S)
    h = params["wte"][input_ids] + params["wpe"][positions][None, :, :]

    def body(carry, xs):
        h = carry
        lp, k_c, v_c = xs
        a, k_c, v_c = _attention_cached(
            cfg, lp["attn"], _layer_norm(h, lp["ln_1"]["scale"], lp["ln_1"]["bias"], eps), k_c, v_c, pos
        )
        h = h + a
        m, _aux = _mlp(cfg, lp["mlp"], _layer_norm(h, lp["ln_2"]["scale"], lp["ln_2"]["bias"], eps), False, None)
        return h + m, (k_c, v_c)

    h, (new_k, new_v) = lax.scan(body, h, (params["blocks"], cache.k, cache.v))
    h = h[:, -1] if logits_at is None else jnp.take(h, logits_at, axis=1)
    h = _layer_norm(h, params["ln_f"]["scale"], params["ln_f"]["bias"], eps)
    # [B, V] logical vocab: padded head columns sliced off (see forward_with_aux)
    logits = (h @ params["wte"].T)[..., : cfg.vocab_size]
    return logits, KVCache(k=new_k, v=new_v, pos=pos + S)


def generate(
    cfg: GPT2Config,
    params: PyTree,
    input_ids: jnp.ndarray,
    max_new_tokens: int,
    temperature: float = 0.0,
    rng=None,
    max_len: Optional[int] = None,
    cache_dtype=jnp.bfloat16,
    top_k: int = 0,
    top_p: float = 1.0,
) -> jnp.ndarray:
    """Fully jitted autoregressive generation: prefill once, then a
    ``lax.scan`` of single-token decode steps over the KV cache (the
    compiled-executable analog of the reference's CUDA-graph decode replay,
    inference/engine.py:486). Returns [B, max_new_tokens]."""
    B, S = input_ids.shape
    if max_len is None:
        max_len = S + max_new_tokens
    if max_len > cfg.n_positions or max_len < S + max_new_tokens:
        raise ValueError(
            f"prompt ({S}) + max_new_tokens ({max_new_tokens}) needs a cache of "
            f"{S + max_new_tokens} but max_len={max_len} (n_positions={cfg.n_positions}); "
            "a shorter cache would silently overwrite KV entries"
        )
    if rng is None:
        rng = jax.random.PRNGKey(0)

    cache = init_cache(cfg, B, max_len, dtype=cache_dtype)
    logits, cache = forward_cached(cfg, params, input_ids, cache)

    from ..ops.sampling import sample_logits

    def sample(logits, key):
        return sample_logits(logits, key, temperature, top_k, top_p)

    first = sample(logits, rng)

    def step(carry, key):
        token, cache = carry
        logits, cache = forward_cached(cfg, params, token[:, None].astype(input_ids.dtype), cache)
        nxt = sample(logits, key)
        return (nxt, cache), token

    if max_new_tokens == 1:
        return first[:, None]
    # each step consumes token t_i, emits it, and produces t_{i+1};
    # N-1 steps yield [t_1..t_{N-1}] with t_N left in the carry
    keys = jax.random.split(jax.random.fold_in(rng, 1), max_new_tokens - 1)
    (last, _), tokens = lax.scan(step, (first, cache), keys)
    return jnp.concatenate([jnp.moveaxis(tokens, 0, 1), last[:, None]], axis=1)


def make_block_api(cfg: GPT2Config):
    """Block-structured view for ZeRO-Infinity parameter streaming
    (runtime/zero/infinity.py) — the analog of the reference's per-submodule
    fetch/release cycle (partitioned_param_coordinator.py:237,356) expressed
    as explicit embed/block/head programs. Persistent part = wte/wpe/ln_f
    (tied head), matching stage3_param_persistence_threshold semantics."""
    from ..runtime.zero.infinity import BlockAPI

    assert not cfg.is_moe, "block streaming: dense blocks only (v1)"
    E, V, P, L = cfg.n_embd, cfg.vocab_size, cfg.n_positions, cfg.n_layer
    std = 0.02
    pstd = std / float(np.sqrt(2.0 * L))
    dt = cfg.dtype
    eps = cfg.layer_norm_epsilon

    def init_persistent(rng):
        k1, k2 = jax.random.split(rng)
        wte = (jax.random.normal(k1, (cfg.padded_vocab_size, E)) * std).astype(dt)
        if cfg.padded_vocab_size > V:
            wte = wte.at[V:].set(0)
        return {
            "wte": wte,
            "wpe": (jax.random.normal(k2, (P, E)) * std).astype(dt),
            "ln_f": {"scale": jnp.ones((E,), dt), "bias": jnp.zeros((E,), dt)},
        }

    def init_block(rng, i):
        k = iter(jax.random.split(jax.random.fold_in(rng, i), 8))

        def normal(key, shape, s):
            return (jax.random.normal(key, shape) * s).astype(dt)

        return {
            "ln_1": {"scale": jnp.ones((E,), dt), "bias": jnp.zeros((E,), dt)},
            "ln_2": {"scale": jnp.ones((E,), dt), "bias": jnp.zeros((E,), dt)},
            "attn": {
                "c_attn_w": normal(next(k), (E, 3 * E), std),
                "c_attn_b": jnp.zeros((3 * E,), dt),
                "c_proj_w": normal(next(k), (E, E), pstd),
                "c_proj_b": jnp.zeros((E,), dt),
            },
            "mlp": {
                "c_fc_w": normal(next(k), (E, 4 * E), std),
                "c_fc_b": jnp.zeros((4 * E,), dt),
                "c_proj_w": normal(next(k), (4 * E, E), pstd),
                "c_proj_b": jnp.zeros((E,), dt),
            },
        }

    def embed_fwd(pers, batch, rng, train):
        ids = batch["input_ids"]
        S = ids.shape[1]
        h = pers["wte"][ids] + pers["wpe"][:S][None, :, :]
        if train and cfg.dropout > 0.0:
            h = _dropout(h, cfg.dropout, rng, train)
        return h

    def block_fwd(blk, h, rng, train):
        key = rng if (train and cfg.dropout > 0.0) else None
        h, _aux = _block(cfg, blk, h, train, key)
        return h

    def head_loss(pers, h, batch):
        h = _layer_norm(h, pers["ln_f"]["scale"], pers["ln_f"]["bias"], eps)
        loss, _ntok = _head_token_loss(cfg, pers["wte"], h, batch)
        return loss

    def split_params(params):
        pers = {"wte": params["wte"], "wpe": params["wpe"], "ln_f": params["ln_f"]}
        blocks = [
            jax.tree.map(lambda x: x[i], params["blocks"]) for i in range(L)
        ]
        return pers, blocks

    # numpy-native init (InfinityEngine host_init): same structure and
    # distribution as the device init, built straight into DRAM — at 13B the
    # device path would stream ~50 GB of initial masters D2H before step 0
    def host_init_persistent(gen):
        wte = gen.standard_normal((cfg.padded_vocab_size, E), dtype=np.float32) * std
        if cfg.padded_vocab_size > V:
            wte[V:] = 0
        return {
            "wte": wte,
            "wpe": gen.standard_normal((P, E), dtype=np.float32) * std,
            "ln_f": {"scale": np.ones((E,), np.float32), "bias": np.zeros((E,), np.float32)},
        }

    def host_init_block(gen, i):
        def normal(shape, s):
            return gen.standard_normal(shape, dtype=np.float32) * s

        return {
            "ln_1": {"scale": np.ones((E,), np.float32), "bias": np.zeros((E,), np.float32)},
            "ln_2": {"scale": np.ones((E,), np.float32), "bias": np.zeros((E,), np.float32)},
            "attn": {
                "c_attn_w": normal((E, 3 * E), std),
                "c_attn_b": np.zeros((3 * E,), np.float32),
                "c_proj_w": normal((E, E), pstd),
                "c_proj_b": np.zeros((E,), np.float32),
            },
            "mlp": {
                "c_fc_w": normal((E, 4 * E), std),
                "c_fc_b": np.zeros((4 * E,), np.float32),
                "c_proj_w": normal((4 * E, E), pstd),
                "c_proj_b": np.zeros((E,), np.float32),
            },
        }

    return BlockAPI(
        num_blocks=L,
        init_persistent=init_persistent,
        init_block=init_block,
        embed_fwd=embed_fwd,
        block_fwd=block_fwd,
        head_loss=head_loss,
        split_params=split_params,
        host_init_persistent=host_init_persistent,
        host_init_block=host_init_block,
    )


def make_module(cfg: GPT2Config) -> ModuleSpec:
    return ModuleSpec(
        init=lambda rng: init_params(cfg, rng),
        loss_fn=lambda params, batch, rng, train: lm_loss(cfg, params, batch, rng, train),
        pld_loss_fn=lambda params, batch, rng, train, theta: lm_loss(
            cfg, params, batch, rng, train, pld_theta=theta
        ),
        apply_fn=lambda params, batch: forward(cfg, params, batch["input_ids"], train=False),
        logical_axes=logical_axes(cfg),
        num_layers=cfg.n_layer,
        pipeline_loss_fn=None if cfg.is_moe else (
            lambda params, batch, rng, train, mesh: pipeline_lm_loss(cfg, params, batch, rng, train, mesh)
        ),
        extra={
            "config": cfg,
            # lazy: built only when the engine engages the param-offload tier
            "block_api": (None if cfg.is_moe else (lambda: make_block_api(cfg))),
        },
    )
