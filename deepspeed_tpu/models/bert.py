"""BERT encoder, TPU-first (scan-over-layers, post-LN).

The reference's BERT support is its oldest surface: the fused training
transformer kernel (csrc/transformer/ds_transformer_cuda.cpp) is benchmarked
against BERT modules (tests/unit/test_cuda_forward.py vs tests/unit/
modeling.py), BERT-large pretraining is the headline number (BASELINE.md),
and inference injection starts at HFBertLayerPolicy (replace_policy.py:66).
This module is the TPU workload for those same surfaces: the "fused layer" is
this jitted block (XLA fuses gemm+bias+gelu+layernorm), driven by the same
policy-converted HF checkpoints.

Post-LN residual layout (original BERT): h = LN(h + attn(h)); h = LN(h + mlp(h)).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from ..ops.layer_norm import layer_norm
from ..ops.quantizer import maybe_dequantize as _deq
from ..runtime.module import ModuleSpec

PyTree = Any


@dataclass(frozen=True)
class BertConfig:
    vocab_size: int = 30522
    n_positions: int = 512
    n_embd: int = 768
    n_layer: int = 12
    n_head: int = 12
    ffn_dim: int = 3072
    type_vocab_size: int = 2
    layer_norm_epsilon: float = 1e-12
    dropout: float = 0.0
    # adds the MLM transform/decoder + NSP heads and a training loss_fn —
    # the BERT-large pretraining objective that is the reference's headline
    # workload (docs/_pages/training.md:42 "44 min on 1024 V100")
    pretraining: bool = False
    # encoder attention dispatch: auto | pallas | jnp | sparse. "sparse"
    # routes through the block-sparse kernel (reference SparseAttentionUtils
    # .replace_model_self_attention_with_sparse_self_attention:85);
    # sparsity_config is a SparsityConfig (None → Fixed at n_head)
    attn_impl: str = "auto"
    sparsity_config: object = None

    @property
    def head_dim(self) -> int:
        return self.n_embd // self.n_head


PRESETS: Dict[str, Dict] = {
    "bert-tiny": dict(n_embd=64, n_layer=2, n_head=4, ffn_dim=256, vocab_size=512, n_positions=128),
    "bert-base": dict(n_embd=768, n_layer=12, n_head=12, ffn_dim=3072),
    "bert-large": dict(n_embd=1024, n_layer=24, n_head=16, ffn_dim=4096),
}


def get_config(name: str, **overrides) -> BertConfig:
    base = dict(PRESETS[name])
    base.update(overrides)
    return BertConfig(**base)


def _ln(x, scale, bias, eps):
    return layer_norm(x, scale, bias, eps)


def init_params(cfg: BertConfig, rng) -> PyTree:
    E, L, F = cfg.n_embd, cfg.n_layer, cfg.ffn_dim
    k = iter(jax.random.split(rng, 16))
    std = 0.02

    def nrm(key, shape):
        return jax.random.normal(key, shape) * std

    ln = {"scale": jnp.ones((L, E)), "bias": jnp.zeros((L, E))}
    return {
        "wte": nrm(next(k), (cfg.vocab_size, E)),
        "wpe": nrm(next(k), (cfg.n_positions, E)),
        "wtt": nrm(next(k), (cfg.type_vocab_size, E)),
        "emb_ln": {"scale": jnp.ones((E,)), "bias": jnp.zeros((E,))},
        "blocks": {
            "attn": {
                "wq": nrm(next(k), (L, E, E)), "bq": jnp.zeros((L, E)),
                "wk": nrm(next(k), (L, E, E)), "bk": jnp.zeros((L, E)),
                "wv": nrm(next(k), (L, E, E)), "bv": jnp.zeros((L, E)),
                "wo": nrm(next(k), (L, E, E)), "bo": jnp.zeros((L, E)),
            },
            "attn_ln": dict(ln),
            "mlp": {
                "fc_in_w": nrm(next(k), (L, E, F)), "fc_in_b": jnp.zeros((L, F)),
                "fc_out_w": nrm(next(k), (L, F, E)), "fc_out_b": jnp.zeros((L, E)),
            },
            "out_ln": dict(ln),
        },
        "pooler": {"w": nrm(next(k), (E, E)), "b": jnp.zeros((E,))},
        **(
            {
                # MLM transform + tied decoder bias, NSP classifier
                # (HF BertForPreTraining cls.predictions / cls.seq_relationship)
                "mlm": {
                    "w": nrm(next(k), (E, E)), "b": jnp.zeros((E,)),
                    "ln": {"scale": jnp.ones((E,)), "bias": jnp.zeros((E,))},
                    "decoder_b": jnp.zeros((cfg.vocab_size,)),
                },
                "nsp": {"w": nrm(next(k), (E, 2)), "b": jnp.zeros((2,))},
            }
            if cfg.pretraining
            else {}
        ),
    }


def logical_axes(cfg: Optional[BertConfig] = None) -> PyTree:
    attn = {
        "wq": ("layers", "embed", "heads"), "bq": ("layers", "heads"),
        "wk": ("layers", "embed", "heads"), "bk": ("layers", "heads"),
        "wv": ("layers", "embed", "heads"), "bv": ("layers", "heads"),
        "wo": ("layers", "heads", "embed"), "bo": ("layers", "embed"),
    }
    ln = {"scale": ("layers", "embed"), "bias": ("layers", "embed")}
    return {
        "wte": ("vocab", "embed"),
        "wpe": (None, "embed"),
        "wtt": (None, "embed"),
        "emb_ln": {"scale": ("embed",), "bias": ("embed",)},
        "blocks": {
            "attn": attn,
            "attn_ln": ln,
            "mlp": {
                "fc_in_w": ("layers", "embed", "mlp"), "fc_in_b": ("layers", "mlp"),
                "fc_out_w": ("layers", "mlp", "embed"), "fc_out_b": ("layers", "embed"),
            },
            "out_ln": ln,
        },
        "pooler": {"w": ("embed", "embed"), "b": ("embed",)},
        **(
            {
                "mlm": {
                    "w": ("embed", "embed"), "b": ("embed",),
                    "ln": {"scale": ("embed",), "bias": ("embed",)},
                    "decoder_b": ("vocab",),
                },
                "nsp": {"w": ("embed", None), "b": (None,)},
            }
            if cfg is not None and cfg.pretraining
            else {}
        ),
    }


def _block(cfg: BertConfig, lp, h, attention_mask):
    B, S, E = h.shape
    H, D = cfg.n_head, cfg.head_dim
    a = lp["attn"]
    q = (h @ _deq(a["wq"], h.dtype) + a["bq"]).reshape(B, S, H, D)
    k_ = (h @ _deq(a["wk"], h.dtype) + a["bk"]).reshape(B, S, H, D)
    v = (h @ _deq(a["wv"], h.dtype) + a["bv"]).reshape(B, S, H, D)
    if cfg.attn_impl == "sparse":
        from ..ops.sparse_attention import FixedSparsityConfig, sparse_attention

        sc = cfg.sparsity_config or FixedSparsityConfig(num_heads=H)
        o = sparse_attention(
            q, k_, v, sc, causal=False, key_mask=attention_mask
        ).reshape(B, S, E)
    else:
        # shared encoder-attention dispatcher: Pallas flash on TPU when
        # unmasked/shape-admitted, f32-softmax jnp path otherwise —
        # BERT-large inference rides the same kernel as the decoder families
        from ..ops.attention import bidirectional_attention

        o = bidirectional_attention(
            q, k_, v, mask=attention_mask, impl=cfg.attn_impl
        ).reshape(B, S, E)
    h = _ln(h + (o @ _deq(a["wo"], o.dtype) + a["bo"]), lp["attn_ln"]["scale"], lp["attn_ln"]["bias"], cfg.layer_norm_epsilon)
    m = lp["mlp"]
    y = jax.nn.gelu(h @ _deq(m["fc_in_w"], h.dtype) + m["fc_in_b"], approximate=False)
    y = y @ _deq(m["fc_out_w"], y.dtype) + m["fc_out_b"]
    return _ln(h + y, lp["out_ln"]["scale"], lp["out_ln"]["bias"], cfg.layer_norm_epsilon)


def forward(
    cfg: BertConfig,
    params: PyTree,
    input_ids: jnp.ndarray,
    attention_mask: Optional[jnp.ndarray] = None,
    token_type_ids: Optional[jnp.ndarray] = None,
) -> Tuple[jnp.ndarray, Optional[jnp.ndarray]]:
    """→ (last_hidden_state [B,S,E], pooled [B,E] or None)."""
    B, S = input_ids.shape
    tt = token_type_ids if token_type_ids is not None else jnp.zeros_like(input_ids)
    h = params["wte"][input_ids] + params["wpe"][:S][None] + params["wtt"][tt]
    h = _ln(h, params["emb_ln"]["scale"], params["emb_ln"]["bias"], cfg.layer_norm_epsilon)
    def body(h, lp):
        return _block(cfg, lp, h, attention_mask), None

    h, _ = lax.scan(body, h, params["blocks"])
    pooled = None
    if params.get("pooler") is not None:
        pooled = jnp.tanh(h[:, 0] @ params["pooler"]["w"] + params["pooler"]["b"])
    return h, pooled


def pretraining_loss(cfg: BertConfig, params: PyTree, batch, rng=None, train: bool = True):
    """Masked-LM + next-sentence-prediction loss (the BERT pretraining
    objective; reference bing_bert workload semantics).

    Batch keys: ``input_ids`` [B,S]; ``labels`` [B,S] with -100 on unmasked
    positions; optional ``attention_mask``/``token_type_ids``;
    optional ``next_sentence_label`` [B]."""
    h, pooled = forward(
        cfg, params, batch["input_ids"],
        batch.get("attention_mask"), batch.get("token_type_ids"),
    )
    m = params["mlm"]
    t = jax.nn.gelu(h @ m["w"] + m["b"], approximate=False)
    t = _ln(t, m["ln"]["scale"], m["ln"]["bias"], cfg.layer_norm_epsilon)
    logits = (
        jnp.einsum("bse,ve->bsv", t, params["wte"].astype(t.dtype))
        + m["decoder_b"]
    ).astype(jnp.float32)

    labels = batch["labels"]
    mask = (labels != -100).astype(jnp.float32)
    safe = jnp.where(labels == -100, 0, labels)
    logp = jax.nn.log_softmax(logits, axis=-1)
    tok_ll = jnp.take_along_axis(logp, safe[..., None], axis=-1)[..., 0]
    mlm_loss = -(tok_ll * mask).sum() / jnp.maximum(mask.sum(), 1.0)

    metrics = {"mlm_loss": mlm_loss}
    loss = mlm_loss
    nsl = batch.get("next_sentence_label")
    if nsl is not None:
        cls_logits = (pooled @ params["nsp"]["w"] + params["nsp"]["b"]).astype(jnp.float32)
        nsp_loss = -jnp.take_along_axis(
            jax.nn.log_softmax(cls_logits, axis=-1), nsl[:, None], axis=-1
        ).mean()
        metrics["nsp_loss"] = nsp_loss
        loss = loss + nsp_loss
    return loss, metrics


def make_module(cfg: BertConfig) -> ModuleSpec:
    return ModuleSpec(
        init=lambda rng: init_params(cfg, rng),
        loss_fn=(
            (lambda params, batch, rng, train: pretraining_loss(cfg, params, batch, rng, train))
            if cfg.pretraining
            else None
        ),
        apply_fn=lambda params, batch: forward(
            cfg, params, batch["input_ids"],
            batch.get("attention_mask"), batch.get("token_type_ids"),
        )[0],
        logical_axes=logical_axes(cfg),
        num_layers=cfg.n_layer,
        extra={"config": cfg},
    )
