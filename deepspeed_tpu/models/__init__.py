from . import gpt2
