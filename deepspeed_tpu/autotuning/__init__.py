from .autotuner import Autotuner
from .scheduler import PodSweep, ResourceManager
from .tuner import GridSearchTuner, ModelBasedTuner, RandomTuner

__all__ = [
    "Autotuner",
    "GridSearchTuner",
    "ModelBasedTuner",
    "PodSweep",
    "RandomTuner",
    "ResourceManager",
]
