from .autotuner import Autotuner
from .tuner import GridSearchTuner, ModelBasedTuner, RandomTuner

__all__ = ["Autotuner", "GridSearchTuner", "ModelBasedTuner", "RandomTuner"]
