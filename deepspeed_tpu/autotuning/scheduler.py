"""Pod-sweep experiment orchestration: real training jobs per candidate config.

Analog of reference ``deepspeed/autotuning/scheduler.py`` (ResourceManager:27
+ run_job/experiment queue): the reference allocates experiments to free
nodes through the launcher, polls for completion, and scrapes metrics files.
The TPU single-controller formulation: every experiment is a SUBPROCESS
running the user's training script against its own generated ds_config JSON,
so each candidate gets a clean backend (a TPU chip admits one process at a
time — the default is one slot, sequential). Metrics come back as the
script's final JSON line (the ``bench.py`` contract: one line, one dict), so
no shared-filesystem metrics protocol is needed.

The in-process :class:`~.autotuner.Autotuner` remains the cheap path when
trials can share one process; ``PodSweep`` is the "run N configs on the pod,
pick the winner" path (VERDICT r3 missing #5), and reuses the same tuner
strategies — including the least-squares cost model — for trial selection.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time
from typing import Any, Dict, List, Optional, Sequence, Tuple

from ..utils.logging import log_dist
from .tuner import GridSearchTuner, ModelBasedTuner, RandomTuner

Experiment = Dict[str, Any]

TUNERS = {"gridsearch": GridSearchTuner, "random": RandomTuner, "model_based": ModelBasedTuner}


def _parse_metric_line(stdout: str, metric_key: str) -> Optional[Dict[str, Any]]:
    """Last JSON object line carrying ``metric_key`` wins (bench.py contract)."""
    found = None
    for line in stdout.splitlines():
        line = line.strip()
        if line.startswith("{") and line.endswith("}"):
            try:
                doc = json.loads(line)
            except json.JSONDecodeError:
                continue
            if metric_key in doc:
                found = doc
    return found


class ResourceManager:
    """Run experiment jobs over ``num_slots`` concurrent subprocess slots.

    Reference ResourceManager (scheduler.py:27) schedules onto free
    node-slots; here a slot is one accelerator-capable process. With the
    default single slot jobs run strictly sequentially — required on a
    single chip, where two concurrent JAX processes deadlock.
    """

    def __init__(self, num_slots: int = 1, env: Optional[Dict[str, str]] = None,
                 timeout: float = 1800.0):
        self.num_slots = max(1, int(num_slots))
        self.env = env
        self.timeout = float(timeout)

    def run_job(self, cmd: Sequence[str], cwd: Optional[str] = None) -> Tuple[int, str, str]:
        env = dict(os.environ)
        if self.env:
            env.update(self.env)
        try:
            proc = subprocess.run(
                list(cmd), cwd=cwd, env=env, capture_output=True, text=True,
                timeout=self.timeout, stdin=subprocess.DEVNULL,
            )
            return proc.returncode, proc.stdout, proc.stderr
        except subprocess.TimeoutExpired as e:
            return -1, (e.stdout or ""), f"timeout after {self.timeout}s"

    def run_batch(self, jobs: Sequence[Tuple[Any, Sequence[str]]], cwd=None):
        """[(tag, cmd)] -> [(tag, rc, stdout, stderr)], ``num_slots`` at a time."""
        out = []
        pending = list(jobs)
        while pending:
            wave, pending = pending[: self.num_slots], pending[self.num_slots :]
            if self.num_slots == 1:
                for tag, cmd in wave:
                    rc, so, se = self.run_job(cmd, cwd=cwd)
                    out.append((tag, rc, so, se))
                continue
            env = dict(os.environ)
            if self.env:
                env.update(self.env)
            procs = [
                (tag, subprocess.Popen(list(cmd), cwd=cwd, env=env, text=True,
                                       stdout=subprocess.PIPE, stderr=subprocess.PIPE))
                for tag, cmd in wave
            ]
            deadline = time.monotonic() + self.timeout
            for tag, p in procs:
                try:
                    so, se = p.communicate(timeout=max(1.0, deadline - time.monotonic()))
                    out.append((tag, p.returncode, so, se))
                except subprocess.TimeoutExpired:
                    p.kill()
                    try:
                        # reap + keep partial output (a job that printed its
                        # metric line before stalling still scores normally,
                        # matching run_job's e.stdout preservation)
                        so, se = p.communicate(timeout=10)
                    except subprocess.TimeoutExpired:
                        so, se = "", ""
                    out.append((tag, -1, so, (se or "") + f"\ntimeout after {self.timeout}s"))
        return out


class PodSweep:
    """Sweep K ds_configs by launching the user's training script per config.

    ``script`` must accept ``--deepspeed_config <path>`` (the standard
    ``add_config_arguments`` surface) and print one JSON line containing
    ``metric_key`` — exactly what ``bench.py`` does. Experiments are dicts of
    {zero_stage, micro_batch, gradient_accumulation_steps, config} where the
    optional ``config`` entry deep-merges arbitrary ds_config overrides.
    """

    def __init__(
        self,
        script: str,
        base_config: Dict[str, Any],
        experiments: Sequence[Experiment],
        results_dir: str = "autotuning_results",
        metric_key: str = "samples_per_sec",
        num_slots: int = 1,
        env: Optional[Dict[str, str]] = None,
        timeout: float = 1800.0,
        script_args: Sequence[str] = (),
        tuner_type: str = "gridsearch",
        python: Optional[str] = None,
    ):
        self.script = str(script)
        self.base_config = base_config
        self.experiments = list(experiments)
        self.results_dir = results_dir
        self.metric_key = metric_key
        self.rm = ResourceManager(num_slots=num_slots, env=env, timeout=timeout)
        self.script_args = list(script_args)
        self.tuner_type = tuner_type
        self.python = python or sys.executable

    # -- config materialization --------------------------------------------
    @staticmethod
    def _deep_merge(dst: Dict[str, Any], src: Dict[str, Any]) -> None:
        for k, v in src.items():
            if isinstance(v, dict) and isinstance(dst.get(k), dict):
                PodSweep._deep_merge(dst[k], v)
            else:
                dst[k] = v

    def _cfg_for(self, exp: Experiment) -> Dict[str, Any]:
        cfg = json.loads(json.dumps(self.base_config))  # deep copy
        if "micro_batch" in exp:
            cfg["train_micro_batch_size_per_gpu"] = int(exp["micro_batch"])
        if "gradient_accumulation_steps" in exp:
            cfg["gradient_accumulation_steps"] = int(exp["gradient_accumulation_steps"])
        if "zero_stage" in exp:
            cfg.setdefault("zero_optimization", {})["stage"] = int(exp["zero_stage"])
        self._deep_merge(cfg, exp.get("config") or {})
        return cfg

    def _exp_dir(self, i: int) -> str:
        d = os.path.join(self.results_dir, f"exp_{i:03d}")
        os.makedirs(d, exist_ok=True)
        return d

    def _prepare(self, i: int, exp: Experiment) -> List[str]:
        d = self._exp_dir(i)
        cfg_path = os.path.join(d, "ds_config.json")
        with open(cfg_path, "w") as fh:
            json.dump(self._cfg_for(exp), fh, indent=2)
        return [self.python, self.script, "--deepspeed_config", cfg_path, *self.script_args]

    def _collect(self, i: int, exp: Experiment, rc: int, stdout: str, stderr: str) -> float:
        d = self._exp_dir(i)
        with open(os.path.join(d, "stdout.log"), "w") as fh:
            fh.write(stdout)
        with open(os.path.join(d, "stderr.log"), "w") as fh:
            fh.write(stderr)
        doc = _parse_metric_line(stdout, self.metric_key)
        if rc != 0 or doc is None:
            log_dist(
                f"pod-sweep exp_{i:03d} {exp} infeasible "
                f"(rc={rc}, metric line {'missing' if doc is None else 'ok'})"
            )
            return float("-inf")
        metric = float(doc[self.metric_key])
        log_dist(f"pod-sweep exp_{i:03d} {exp} -> {metric:.2f} {self.metric_key}")
        return metric

    def _launch(self, i: int, exp: Experiment) -> float:
        rc, stdout, stderr = self.rm.run_job(self._prepare(i, exp))
        return self._collect(i, exp, rc, stdout, stderr)

    # -- the sweep ----------------------------------------------------------
    def run(self, max_trials: Optional[int] = None) -> Dict[str, Any]:
        import numpy as np

        os.makedirs(self.results_dir, exist_ok=True)
        if self.tuner_type == "gridsearch" and self.rm.num_slots > 1:
            # gridsearch has no measurement-dependent trial selection, so it
            # can fan out num_slots-wide waves through the ResourceManager
            exps = self.experiments[: max_trials or len(self.experiments)]
            raw = self.rm.run_batch(
                [(i, self._prepare(i, e)) for i, e in enumerate(exps)]
            )
            trials = [
                (exps[i], self._collect(i, exps[i], rc, so, se))
                for i, rc, so, se in raw
            ]
            best_exp, best_metric = None, float("-inf")
            for e, m in trials:
                if m > best_metric:
                    best_exp, best_metric = e, m
        else:
            if self.rm.num_slots > 1:
                log_dist(
                    f"pod-sweep: tuner '{self.tuner_type}' selects trials from "
                    "measurements, so experiments run sequentially "
                    f"(num_slots={self.rm.num_slots} ignored)"
                )
            index = {id(e): i for i, e in enumerate(self.experiments)}
            tuner_cls = TUNERS[self.tuner_type]
            kwargs = {}
            if self.tuner_type == "model_based":
                feats = [
                    k for k in ("zero_stage", "micro_batch", "gradient_accumulation_steps")
                    if all(k in e for e in self.experiments)
                ]
                kwargs = {"features": feats}
            tuner = tuner_cls(
                self.experiments, lambda e: self._launch(index[id(e)], e), **kwargs
            )
            best_exp, best_metric = tuner.tune(max_trials)
            trials = tuner.results

        result = {
            "best": best_exp,
            self.metric_key: best_metric if np.isfinite(best_metric) else None,
            "trials": [
                {"exp": e, self.metric_key: m if np.isfinite(m) else None}
                for e, m in trials
            ],
        }
        with open(os.path.join(self.results_dir, "autotuning_results.json"), "w") as fh:
            json.dump(result, fh, indent=2)
        if best_exp is not None and np.isfinite(best_metric):
            best_cfg = self._cfg_for(best_exp)
            with open(os.path.join(self.results_dir, "ds_config_optimal.json"), "w") as fh:
                json.dump(best_cfg, fh, indent=2)
            result["ds_config"] = best_cfg
        return result
