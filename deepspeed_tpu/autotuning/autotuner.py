"""Autotuner: sweep ZeRO stage / micro-batch configs, measure, pick the best.

Analog of reference ``deepspeed/autotuning/autotuner.py`` (Autotuner:26,
2760 LoC with ResourceManager-launched experiment jobs). The reference forks
whole training jobs per experiment because torch state is process-bound; a
JAX single-controller retunes *in process* — each trial builds an engine,
measures steady-state throughput of the compiled step, frees it, and moves
on. OOM during compile/run marks the config infeasible (the reference's
micro-batch binary sweep, run_tuning_micro_batch_sizes:744).

Metric: samples/sec (reference ``throughput``); results land in
``autotuning_results.json`` with the winning ds_config.
"""

from __future__ import annotations

import itertools
import json
import os
import time
from typing import Any, Dict, List, Optional, Sequence

import jax
import numpy as np

from ..utils.logging import log_dist
from .tuner import GridSearchTuner, ModelBasedTuner, RandomTuner

TUNERS = {"gridsearch": GridSearchTuner, "random": RandomTuner, "model_based": ModelBasedTuner}


class Autotuner:
    def __init__(
        self,
        model_factory,  # () -> ModuleSpec
        base_config: Dict[str, Any],
        make_batch,  # (train_batch_size) -> host batch pytree
        mesh=None,
        zero_stages: Sequence[int] = (0, 1, 2, 3),
        micro_batches: Sequence[int] = (1, 2, 4, 8),
        steps_per_trial: int = 3,
        tuner_type: str = "gridsearch",
        results_dir: str = "autotuning_results",
    ):
        self.model_factory = model_factory
        self.base_config = base_config
        self.make_batch = make_batch
        self.mesh = mesh
        self.zero_stages = list(zero_stages)
        self.micro_batches = list(micro_batches)
        self.steps_per_trial = steps_per_trial
        self.tuner_type = tuner_type
        self.results_dir = results_dir

    def _experiments(self) -> List[Dict[str, Any]]:
        return [
            {"zero_stage": z, "micro_batch": m}
            for z, m in itertools.product(self.zero_stages, self.micro_batches)
        ]

    def _run_experiment(self, exp: Dict[str, Any]) -> float:
        """Returns samples/sec (−inf when infeasible)."""
        from ..runtime.config import DeepSpeedConfig
        from ..runtime.engine import DeepSpeedEngine

        cfg = json.loads(json.dumps(self.base_config))  # deep copy
        cfg["train_micro_batch_size_per_gpu"] = exp["micro_batch"]
        cfg.setdefault("zero_optimization", {})["stage"] = exp["zero_stage"]
        try:
            engine = DeepSpeedEngine(
                self.model_factory(), DeepSpeedConfig.load(cfg, dp_world_size=None),
                mesh=self.mesh,
            )
            batch = self.make_batch(engine.train_batch_size)
            m = engine.train_batch(batch)  # compile + warmup
            jax.block_until_ready(m["loss"])
            t0 = time.perf_counter()
            for _ in range(self.steps_per_trial):
                m = engine.train_batch(batch)
            jax.block_until_ready(m["loss"])
            dt = time.perf_counter() - t0
            tput = engine.train_batch_size * self.steps_per_trial / dt
            log_dist(f"autotuner: {exp} → {tput:.1f} samples/s")
            return float(tput)
        except (RuntimeError, ValueError, MemoryError) as e:
            log_dist(f"autotuner: {exp} infeasible ({type(e).__name__}: {e})")
            return float("-inf")

    def tune(self, max_trials: Optional[int] = None) -> Dict[str, Any]:
        exps = self._experiments()
        tuner_cls = TUNERS[self.tuner_type]
        kwargs = {}
        if self.tuner_type == "model_based":
            kwargs = {"features": ["zero_stage", "micro_batch"]}
        tuner = tuner_cls(exps, self._run_experiment, **kwargs)
        best_exp, best_metric = tuner.tune(max_trials)
        result = {
            "best": best_exp,
            "throughput": best_metric,
            "trials": [
                {"exp": e, "throughput": m if np.isfinite(m) else None}
                for e, m in tuner.results
            ],
        }
        os.makedirs(self.results_dir, exist_ok=True)
        with open(os.path.join(self.results_dir, "autotuning_results.json"), "w") as fh:
            json.dump(result, fh, indent=2)
        if best_exp is not None:
            best_cfg = json.loads(json.dumps(self.base_config))
            best_cfg["train_micro_batch_size_per_gpu"] = best_exp["micro_batch"]
            best_cfg.setdefault("zero_optimization", {})["stage"] = best_exp["zero_stage"]
            with open(os.path.join(self.results_dir, "ds_config_optimal.json"), "w") as fh:
                json.dump(best_cfg, fh, indent=2)
            result["ds_config"] = best_cfg
        return result
