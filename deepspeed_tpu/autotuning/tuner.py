"""Experiment-selection strategies for the autotuner.

Analogs of reference ``autotuning/tuner/index_based_tuner.py``
(RandomTuner:6, GridSearchTuner:21) and ``model_based_tuner.py``
(ModelBasedTuner:14 with XGBoostCostModel:9). XGBoost is not in the TPU
image; the cost model here is a least-squares polynomial over the numeric
config features — the same explore/exploit structure with a dependency-free
estimator.
"""

from __future__ import annotations

import random
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

Experiment = Dict[str, Any]


class BaseTuner:
    def __init__(self, exps: Sequence[Experiment], metric_fn: Callable[[Experiment], float]):
        self.all_exps = list(exps)
        self.metric_fn = metric_fn
        self.results: List[Tuple[Experiment, float]] = []
        self.best_exp: Optional[Experiment] = None
        self.best_metric = -np.inf

    def _record(self, exp: Experiment, metric: float) -> None:
        self.results.append((exp, metric))
        if metric > self.best_metric:
            self.best_metric = metric
            self.best_exp = exp

    def tune(self, max_trials: Optional[int] = None) -> Tuple[Optional[Experiment], float]:
        for exp in self.order(max_trials):
            self._record(exp, self.metric_fn(exp))
        return self.best_exp, self.best_metric

    def order(self, max_trials: Optional[int]) -> List[Experiment]:
        raise NotImplementedError


class GridSearchTuner(BaseTuner):
    def order(self, max_trials=None):
        return self.all_exps[: max_trials or len(self.all_exps)]


class RandomTuner(BaseTuner):
    def __init__(self, exps, metric_fn, seed: int = 0):
        super().__init__(exps, metric_fn)
        self.seed = seed

    def order(self, max_trials=None):
        rng = random.Random(self.seed)
        exps = list(self.all_exps)
        rng.shuffle(exps)
        return exps[: max_trials or len(exps)]


class ModelBasedTuner(BaseTuner):
    """Measure a seed set, fit a quadratic cost model over numeric features,
    then evaluate only the predicted-best remainder."""

    def __init__(self, exps, metric_fn, features: Sequence[str], seed_trials: int = 3, top_k: int = 2):
        super().__init__(exps, metric_fn)
        self.features = list(features)
        self.seed_trials = seed_trials
        self.top_k = top_k

    def _featurize(self, exp: Experiment) -> np.ndarray:
        x = np.asarray([float(exp[f]) for f in self.features])
        return np.concatenate([[1.0], x, x * x])

    def tune(self, max_trials: Optional[int] = None):
        seed = self.all_exps[: self.seed_trials]
        rest = self.all_exps[self.seed_trials :]
        for exp in seed:
            self._record(exp, self.metric_fn(exp))
        # infeasible trials measure as -inf; they must not enter the fit or
        # the least-squares turns NaN and "predicted-best" becomes arbitrary
        finite = [(e, m) for e, m in self.results if np.isfinite(m)]
        if rest:
            budget = self.top_k if max_trials is None else max(0, max_trials - len(seed))
            if len(finite) >= 2:
                X = np.stack([self._featurize(e) for e, _ in finite])
                y = np.asarray([m for _, m in finite])
                coef, *_ = np.linalg.lstsq(X, y, rcond=None)
                preds = [(float(self._featurize(e) @ coef), e) for e in rest]
                preds.sort(key=lambda t: -t[0])
                ordered = [e for _, e in preds]
            else:
                # too few feasible seeds to fit a model: keep exploring in
                # order rather than abandoning the (possibly feasible) rest
                ordered = list(rest)
            for exp in ordered[:budget]:
                self._record(exp, self.metric_fn(exp))
        return self.best_exp, self.best_metric
