"""Multi-host command execution: ssh / pdsh fan-out.

Analog of reference ``deepspeed/launcher/multinode_runner.py``
(MultiNodeRunner:13, PDSHRunner:45, OpenMPIRunner:109, MVAPICHRunner:164).
MPI runners don't transfer — JAX multi-host uses its own coordinator
rendezvous — so the set is ssh (portable) and pdsh (fan-out with prefixed
output). Child processes are tracked and killed as a tree on first failure
(reference launch.py terminate_process_tree semantics).
"""

from __future__ import annotations

import shutil
import signal
import subprocess
import sys
from typing import List, Tuple


class MultiNodeRunner:
    def launch(self, cmds: List[Tuple[str, str]]) -> int:
        raise NotImplementedError


class SSHRunner(MultiNodeRunner):
    def __init__(self, ssh_args: Tuple[str, ...] = ("-o", "StrictHostKeyChecking=no")):
        self.ssh_args = list(ssh_args)

    def launch(self, cmds: List[Tuple[str, str]]) -> int:
        procs = []
        for host, cmd in cmds:
            if host in ("localhost", "127.0.0.1"):
                p = subprocess.Popen(cmd, shell=True)
            else:
                p = subprocess.Popen(["ssh", *self.ssh_args, host, cmd])
            procs.append((host, p))
        rc = 0
        try:
            for host, p in procs:
                code = p.wait()
                if code != 0:
                    print(f"[{host}] exited with {code}", file=sys.stderr)
                    rc = rc or code
                    # kill the rest (reference sigkill_handler fan-out)
                    for _, q in procs:
                        if q.poll() is None:
                            q.send_signal(signal.SIGTERM)
        except KeyboardInterrupt:
            for _, p in procs:
                if p.poll() is None:
                    p.send_signal(signal.SIGTERM)
            raise
        return rc


class PDSHRunner(MultiNodeRunner):
    def __init__(self):
        if shutil.which("pdsh") is None:
            raise RuntimeError("pdsh not found; use --launcher ssh")

    def launch(self, cmds: List[Tuple[str, str]]) -> int:
        # pdsh requires one command for all hosts; per-host env differs, so
        # fan out one pdsh per unique command batch (hosts grouped by cmd)
        procs = []
        for host, cmd in cmds:
            procs.append(subprocess.Popen(["pdsh", "-w", host, cmd]))
        rc = 0
        for p in procs:
            rc = rc or p.wait()
        return rc
