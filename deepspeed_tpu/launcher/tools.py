"""Auxiliary CLI entry points (reference ``bin/ds_ssh``, ``bin/ds_bench``,
``bin/ds_elastic``; installed via setup.py console_scripts).

- ``ds_ssh``: run a shell command on every host of a hostfile (the
  cluster-wide fan-out the reference implements with a pdsh loop).
- ``ds_bench``: sweep the collective micro-benchmarks on the local mesh —
  reuses ``CommsLogger.measure`` so the numbers match ``comms_summary``.
- ``ds_elastic``: pretty-print the elastic batch ladder for a config
  (reference ds_elastic: compute_elastic_config from a ds_config JSON).
"""

from __future__ import annotations

import argparse
import json
import shlex
import subprocess
import sys

from ..utils.logging import logger


def ds_ssh(argv=None) -> int:
    p = argparse.ArgumentParser("ds_ssh", description="run a command on all hosts")
    p.add_argument("-f", "--hostfile", default="/job/hostfile")
    p.add_argument("command", nargs=argparse.REMAINDER)
    args = p.parse_args(argv)
    from .runner import fetch_hostfile

    hosts = fetch_hostfile(args.hostfile)
    if not hosts:
        print(f"ds_ssh: no hosts in {args.hostfile}", file=sys.stderr)
        return 1
    if not args.command:
        p.error("no command given")
    cmd = shlex.join(args.command)  # preserve quoting on the remote shell
    # pdsh-style parallel fan-out: launch every host, then collect
    procs = {
        host: subprocess.Popen(
            ["ssh", "-o", "StrictHostKeyChecking=no", host, cmd],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
        )
        for host in hosts
    }
    rc = 0
    for host, proc in procs.items():
        out, _ = proc.communicate()
        print(f"--- {host} ---")
        if out:
            print(out, end="")
        rc = rc or proc.returncode
    return rc


def ds_bench(argv=None) -> int:
    p = argparse.ArgumentParser("ds_bench", description="collective micro-bench")
    p.add_argument("--ops", default="all_reduce,all_gather,reduce_scatter,all_to_all")
    p.add_argument("--bytes", type=int, default=16 * 1024 * 1024)
    p.add_argument("--iters", type=int, default=10)
    args = p.parse_args(argv)
    import jax

    from ..comm import comm as dscomm
    from ..parallel.topology import MeshSpec

    n = len(jax.devices())
    mesh = MeshSpec(dp=n).build_mesh()
    dscomm.comms_logger.configure(enabled=True)
    for op in args.ops.split(","):
        dscomm.comms_logger.comms_dict[(op.strip(), "dp")] = {
            "count": 1, "bytes": args.bytes, "time_ms": None, "world": None,
        }
    dscomm.comms_logger.measure(mesh, iters=args.iters)
    print(dscomm.log_summary())
    return 0


def _watch_and_run(cmd, probe_timeout_s: float, backoff_s: float,
                   max_runs: int, probe_fn=None, sleep_fn=None) -> int:
    """Wait for a healthy accelerator, run ``cmd``, re-probe and retry on
    failure — the preemption/wedge-recovery loop (the pattern that captured
    this build's own hardware evidence through a flaky single-tenant
    tunnel, productized). The command should be idempotent/resumable (e.g.
    training with checkpoint auto-resume). ``max_runs`` 0 = retry until the
    command succeeds."""
    import time as _time

    from ..elasticity.elastic_agent import _default_probe

    probe = probe_fn or _default_probe
    sleep = sleep_fn or _time.sleep
    runs = 0
    rc = 1
    while True:
        if probe(probe_timeout_s):
            runs += 1
            logger.info(f"ds_elastic --watch: accelerator healthy, run {runs}: {cmd}")
            rc = subprocess.call(cmd)
            if rc == 0:
                return 0
            logger.warning(f"ds_elastic --watch: command exited rc={rc}")
            if max_runs and runs >= max_runs:
                return rc
        else:
            logger.info("ds_elastic --watch: accelerator unhealthy, backing off")
        sleep(backoff_s)


def ds_elastic(argv=None) -> int:
    p = argparse.ArgumentParser("ds_elastic", description="elastic config ladder")
    p.add_argument("-c", "--config", required=False, help="ds_config JSON path")
    p.add_argument("-w", "--world-size", type=int, default=0)
    p.add_argument(
        "--verify-resize",
        default=None,
        metavar="W1,W2,...",
        help="validate that a job could resize across these world sizes: each "
        "must sit on the ladder with the SAME effective batch; prints the "
        "micro x gas x dp split per size (rc 1 if any is incompatible)",
    )
    p.add_argument(
        "--watch", action="store_true",
        help="wait for a healthy accelerator, run CMD (everything after "
        "--), and retry with backoff while it fails — wedge/preemption "
        "recovery for an idempotent, checkpoint-resumable command",
    )
    p.add_argument("--probe-timeout", type=float, default=90.0)
    p.add_argument("--backoff", type=float, default=240.0)
    p.add_argument("--max-runs", type=int, default=0, help="0 = until success")
    p.add_argument("cmd", nargs=argparse.REMAINDER)
    args = p.parse_args(argv)
    if args.watch:
        # drop only the LEADING separator: an inner "--" belongs to the
        # wrapped command (e.g. --watch -- ds_ssh -f hosts -- echo hi)
        cmd = args.cmd[1:] if args.cmd[:1] == ["--"] else args.cmd
        if not cmd:
            p.error("--watch needs a command after --")
        return _watch_and_run(
            cmd, args.probe_timeout, args.backoff, args.max_runs
        )
    if args.cmd:
        p.error(f"unrecognized arguments: {' '.join(args.cmd)} (a trailing "
                "command is only accepted with --watch)")
    if not args.config:
        p.error("-c/--config is required (unless --watch)")
    from ..elasticity.elasticity import ElasticityError, compute_elastic_config

    with open(args.config) as f:
        doc = json.load(f)
    if args.verify_resize:
        sizes = [int(s) for s in args.verify_resize.split(",") if s]
        plan, ok = [], True
        for ws in sizes:
            try:
                batch, _, micro = compute_elastic_config(
                    doc, world_size=ws, return_microbatch=True
                )
                if micro is None:
                    raise ElasticityError(f"no micro batch for world size {ws}")
                plan.append({
                    "world_size": ws, "final_batch_size": batch,
                    "micro_batch_per_gpu": micro,
                    "gradient_accumulation_steps": batch // (micro * ws),
                })
            except ElasticityError as e:
                ok = False
                plan.append({"world_size": ws, "error": str(e)})
        batches = {e["final_batch_size"] for e in plan if "final_batch_size" in e}
        ok = ok and len(batches) == 1
        print(json.dumps({"resize_ok": ok, "plan": plan}, indent=2))
        return 0 if ok else 1
    res = compute_elastic_config(
        doc, world_size=args.world_size, return_microbatch=args.world_size > 0
    )
    out = {"final_batch_size": res[0], "valid_gpus": res[1]}
    if len(res) > 2:
        out["micro_batch_per_gpu"] = res[2]
    print(json.dumps(out, indent=2))
    return 0
