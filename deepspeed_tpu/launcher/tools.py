"""Auxiliary CLI entry points (reference ``bin/ds_ssh``, ``bin/ds_bench``,
``bin/ds_elastic``; installed via setup.py console_scripts).

- ``ds_ssh``: run a shell command on every host of a hostfile (the
  cluster-wide fan-out the reference implements with a pdsh loop).
- ``ds_bench``: sweep the collective micro-benchmarks on the local mesh —
  reuses ``CommsLogger.measure`` so the numbers match ``comms_summary``.
- ``ds_elastic``: pretty-print the elastic batch ladder for a config
  (reference ds_elastic: compute_elastic_config from a ds_config JSON).
"""

from __future__ import annotations

import argparse
import json
import shlex
import subprocess
import sys


def ds_ssh(argv=None) -> int:
    p = argparse.ArgumentParser("ds_ssh", description="run a command on all hosts")
    p.add_argument("-f", "--hostfile", default="/job/hostfile")
    p.add_argument("command", nargs=argparse.REMAINDER)
    args = p.parse_args(argv)
    from .runner import fetch_hostfile

    hosts = fetch_hostfile(args.hostfile)
    if not hosts:
        print(f"ds_ssh: no hosts in {args.hostfile}", file=sys.stderr)
        return 1
    if not args.command:
        p.error("no command given")
    cmd = shlex.join(args.command)  # preserve quoting on the remote shell
    # pdsh-style parallel fan-out: launch every host, then collect
    procs = {
        host: subprocess.Popen(
            ["ssh", "-o", "StrictHostKeyChecking=no", host, cmd],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
        )
        for host in hosts
    }
    rc = 0
    for host, proc in procs.items():
        out, _ = proc.communicate()
        print(f"--- {host} ---")
        if out:
            print(out, end="")
        rc = rc or proc.returncode
    return rc


def ds_bench(argv=None) -> int:
    p = argparse.ArgumentParser("ds_bench", description="collective micro-bench")
    p.add_argument("--ops", default="all_reduce,all_gather,reduce_scatter,all_to_all")
    p.add_argument("--bytes", type=int, default=16 * 1024 * 1024)
    p.add_argument("--iters", type=int, default=10)
    args = p.parse_args(argv)
    import jax

    from ..comm import comm as dscomm
    from ..parallel.topology import MeshSpec

    n = len(jax.devices())
    mesh = MeshSpec(dp=n).build_mesh()
    dscomm.comms_logger.configure(enabled=True)
    for op in args.ops.split(","):
        dscomm.comms_logger.comms_dict[(op.strip(), "dp")] = {
            "count": 1, "bytes": args.bytes, "time_ms": None, "world": None,
        }
    dscomm.comms_logger.measure(mesh, iters=args.iters)
    print(dscomm.log_summary())
    return 0


def ds_elastic(argv=None) -> int:
    p = argparse.ArgumentParser("ds_elastic", description="elastic config ladder")
    p.add_argument("-c", "--config", required=True, help="ds_config JSON path")
    p.add_argument("-w", "--world-size", type=int, default=0)
    p.add_argument(
        "--verify-resize",
        default=None,
        metavar="W1,W2,...",
        help="validate that a job could resize across these world sizes: each "
        "must sit on the ladder with the SAME effective batch; prints the "
        "micro x gas x dp split per size (rc 1 if any is incompatible)",
    )
    args = p.parse_args(argv)
    from ..elasticity.elasticity import ElasticityError, compute_elastic_config

    with open(args.config) as f:
        doc = json.load(f)
    if args.verify_resize:
        sizes = [int(s) for s in args.verify_resize.split(",") if s]
        plan, ok = [], True
        for ws in sizes:
            try:
                batch, _, micro = compute_elastic_config(
                    doc, world_size=ws, return_microbatch=True
                )
                if micro is None:
                    raise ElasticityError(f"no micro batch for world size {ws}")
                plan.append({
                    "world_size": ws, "final_batch_size": batch,
                    "micro_batch_per_gpu": micro,
                    "gradient_accumulation_steps": batch // (micro * ws),
                })
            except ElasticityError as e:
                ok = False
                plan.append({"world_size": ws, "error": str(e)})
        batches = {e["final_batch_size"] for e in plan if "final_batch_size" in e}
        ok = ok and len(batches) == 1
        print(json.dumps({"resize_ok": ok, "plan": plan}, indent=2))
        return 0 if ok else 1
    res = compute_elastic_config(
        doc, world_size=args.world_size, return_microbatch=args.world_size > 0
    )
    out = {"final_batch_size": res[0], "valid_gpus": res[1]}
    if len(res) > 2:
        out["micro_batch_per_gpu"] = res[2]
    print(json.dumps(out, indent=2))
    return 0
