"""``deepspeed`` CLI — launch training across TPU hosts.

Analog of reference ``deepspeed/launcher/runner.py`` (main:351,
fetch_hostfile:176, parse_resource_filter:217, 529 LoC). Topology mapping:

- reference: 1 process per GPU, NCCL rendezvous via MASTER_ADDR/PORT.
- TPU: 1 process per HOST (each host owns its local chips); JAX multi-host
  init rendezvouses at a coordinator via ``jax.distributed.initialize``
  driven by env: COORDINATOR_ADDRESS / NUM_PROCESSES / PROCESS_ID.

Hostfile syntax is unchanged (``hostname slots=N`` — N = chips on that
host), and --include/--exclude filters keep reference semantics
(``host1@host2:0,2`` style). Single host → exec in place; multi-host → ssh
fan-out (pdsh when available).
"""

from __future__ import annotations

import argparse
import os
import shlex
import subprocess
import sys
from collections import OrderedDict
from typing import Dict, List, Optional, Tuple

DLTS_HOSTFILE = "/job/hostfile"
COORD_PORT_DEFAULT = 8476


def fetch_hostfile(hostfile_path: str) -> Optional["OrderedDict[str, int]"]:
    """Parse ``host slots=N`` lines (reference fetch_hostfile:176)."""
    if not os.path.isfile(hostfile_path):
        return None
    resources: "OrderedDict[str, int]" = OrderedDict()
    with open(hostfile_path) as fh:
        for line in fh:
            line = line.strip()
            if not line or line.startswith("#"):
                continue
            try:
                host, slots = line.split()
                key, count = slots.split("=")
                if key != "slots":
                    raise ValueError(f"expected 'slots=N', got {slots!r}")
                resources[host] = int(count)
            except ValueError as e:
                raise ValueError(f"hostfile line not 'host slots=N': {line!r}") from e
    return resources or None


def _parse_filter(spec: str) -> Dict[str, Optional[List[int]]]:
    """``worker-0:0,2@worker-1`` → {host: [slot,...] or None=all}."""
    out: Dict[str, Optional[List[int]]] = {}
    for part in spec.split("@"):
        if not part:
            continue
        if ":" in part:
            host, slots = part.split(":")
            out[host] = [int(s) for s in slots.split(",")]
        else:
            out[part] = None
    return out


def parse_resource_filter(
    resources: "OrderedDict[str, int]",
    include_str: str = "",
    exclude_str: str = "",
) -> "OrderedDict[str, List[int]]":
    """Apply --include/--exclude (reference parse_resource_filter:217)."""
    if include_str and exclude_str:
        raise ValueError("--include and --exclude are mutually exclusive")
    full: "OrderedDict[str, List[int]]" = OrderedDict(
        (h, list(range(n))) for h, n in resources.items()
    )
    if include_str:
        inc = _parse_filter(include_str)
        out: "OrderedDict[str, List[int]]" = OrderedDict()
        for host, slots in inc.items():
            if host not in full:
                raise ValueError(f"include host {host} not in hostfile")
            chosen = slots if slots is not None else full[host]
            bad = set(chosen) - set(full[host])
            if bad:
                raise ValueError(f"include slots {sorted(bad)} not on {host}")
            out[host] = sorted(chosen)
        return out
    if exclude_str:
        exc = _parse_filter(exclude_str)
        out = OrderedDict()
        for host, slots in full.items():
            if host in exc:
                if exc[host] is None:
                    continue
                keep = [s for s in slots if s not in exc[host]]
                if keep:
                    out[host] = keep
            else:
                out[host] = slots
        return out
    return full


def encode_world_info(active: "OrderedDict[str, List[int]]") -> str:
    import base64
    import json

    return base64.urlsafe_b64encode(json.dumps(active).encode()).decode()


def build_launch_commands(
    active: "OrderedDict[str, List[int]]",
    user_script: str,
    user_args: List[str],
    master_addr: Optional[str] = None,
    master_port: int = COORD_PORT_DEFAULT,
) -> List[Tuple[str, str]]:
    """(host, command) per host: each host runs ONE process with JAX
    multi-host env (process_id = host index)."""
    hosts = list(active.keys())
    master_addr = master_addr or hosts[0]
    n = len(hosts)
    cmds = []
    for pid, host in enumerate(hosts):
        env = (
            f"COORDINATOR_ADDRESS={master_addr}:{master_port} "
            f"NUM_PROCESSES={n} PROCESS_ID={pid} "
            f"TPU_VISIBLE_CHIPS={','.join(map(str, active[host]))}"
        )
        cmd = f"{env} {sys.executable} {shlex.quote(user_script)} {' '.join(shlex.quote(a) for a in user_args)}"
        cmds.append((host, cmd.strip()))
    return cmds


def main(args: Optional[List[str]] = None) -> int:
    parser = argparse.ArgumentParser(
        prog="deepspeed", description="DeepSpeed-TPU launcher"
    )
    parser.add_argument("-H", "--hostfile", default=DLTS_HOSTFILE)
    parser.add_argument("-i", "--include", default="")
    parser.add_argument("-e", "--exclude", default="")
    parser.add_argument("--num_nodes", type=int, default=-1)
    parser.add_argument("--num_gpus", "--num_chips", dest="num_gpus", type=int, default=-1)
    parser.add_argument("--master_addr", default=None)
    parser.add_argument("--master_port", type=int, default=COORD_PORT_DEFAULT)
    parser.add_argument("--launcher", default="ssh", choices=["ssh", "pdsh", "local"])
    parser.add_argument("--dry_run", action="store_true", help="print commands only")
    parser.add_argument("user_script")
    parser.add_argument("user_args", nargs=argparse.REMAINDER)
    a = parser.parse_args(args)

    resources = fetch_hostfile(a.hostfile)
    if resources is None:
        # single-host: exec in place (reference single-node path)
        cmd = [sys.executable, a.user_script, *a.user_args]
        if a.dry_run:
            print(" ".join(cmd))
            return 0
        return subprocess.call(cmd)

    active = parse_resource_filter(resources, a.include, a.exclude)
    if a.num_nodes > 0:
        active = OrderedDict(list(active.items())[: a.num_nodes])
    cmds = build_launch_commands(
        active, a.user_script, a.user_args, a.master_addr, a.master_port
    )
    if a.dry_run:
        for host, cmd in cmds:
            print(f"[{host}] {cmd}")
        return 0

    from .multinode_runner import PDSHRunner, SSHRunner

    runner = PDSHRunner() if a.launcher == "pdsh" else SSHRunner()
    return runner.launch(cmds)


if __name__ == "__main__":
    raise SystemExit(main())
