"""Unified telemetry plane: metrics registry + step tracer + exporters.

The observability spine of the runtime (ISSUE 1 tentpole). One
:class:`Telemetry` object per engine bundles:

- :class:`~.registry.MetricsRegistry` — named counters/gauges/histograms fed
  by the wall-clock/throughput timers, ``memory_breakdown()`` HBM stats,
  trace-time ``CommsLogger`` totals and jax compile events;
- :class:`~.tracer.StepTracer` — one structured JSONL record per sampled
  train/inference step (span tree, loss/lr/gnorm, HBM, per-axis comm bytes);
- exporters — Prometheus textfile snapshots and the MonitorBridge fan-out to
  TensorBoard/W&B/CSV.

Everything is opt-in via the ``telemetry`` config section
(:class:`~deepspeed_tpu.runtime.config.TelemetryConfig`); a disabled config
constructs nothing — the engine holds ``telemetry=None`` and pays only a
None check per step.
"""

from __future__ import annotations

import os
import time
from typing import Any, Dict, List, Optional

from . import compile_stats, introspect
from . import watchdog as watchdog_mod
from .exporters import MonitorBridge, PrometheusTextfileExporter
from .kv_heat import KVHeatLedger, KVHeatTracer
from .registry import Counter, Gauge, Histogram, MetricsRegistry
from .request_trace import RequestTracer
from .timeseries import MetricsJournal
from .tracer import Span, StepTracer, aggregate_scalars, spans_to_tree
from .watchdog import AnomalyError, AnomalyWatchdog

__all__ = [
    "AnomalyError", "AnomalyWatchdog",
    "Counter", "Gauge", "Histogram", "KVHeatLedger", "KVHeatTracer",
    "MetricsJournal", "MetricsRegistry", "MonitorBridge",
    "PrometheusTextfileExporter",
    "RequestTracer", "Span", "StepTracer", "Telemetry",
    "aggregate_scalars", "device_hbm_stats", "from_config", "introspect",
    "spans_to_tree",
]

# histogram buckets for step latency (seconds): tighter than the generic
# defaults around the 10ms-10s band where train/decode steps live
STEP_BUCKETS = (
    0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.0, 5.0, 10.0, 30.0, 60.0,
)


def device_hbm_stats() -> Dict[str, int]:
    """First addressable device's HBM stats (zeros on backends without
    memory_stats, e.g. CPU) — the ``memory_breakdown()`` source."""
    try:
        import jax

        stats = jax.local_devices()[0].memory_stats() or {}
    except Exception:
        stats = {}
    return {
        k: int(stats.get(k, 0))
        for k in ("bytes_in_use", "peak_bytes_in_use", "bytes_limit")
    }


class Telemetry:
    """Per-engine telemetry bundle; construct via :func:`from_config`."""

    def __init__(self, config, process_index: Optional[int] = None):
        self.config = config
        self.registry = MetricsRegistry()
        self.tracer = (
            StepTracer(
                config.trace_path,
                flush_interval=config.flush_interval,
                sample_every=config.sample_every,
                process_index=process_index,
                max_bytes=int(getattr(config, "trace_max_mb", 0) or 0) * 2**20,
            )
            if config.trace_path
            else None
        )
        self.prometheus = (
            PrometheusTextfileExporter(self.registry, config.prometheus_path)
            if config.prometheus_path
            else None
        )
        self.monitor_bridge: Optional[MonitorBridge] = None
        self._records_since_export = 0
        # ISSUE 5: performance-introspection plane — the HLO cost/MFU
        # analyzer config rides here (the engine drives the analysis; see
        # introspect.py) and the anomaly watchdog is constructed iff enabled
        self.introspection = getattr(config, "introspection", None)
        self.watchdog: Optional[AnomalyWatchdog] = watchdog_mod.from_config(
            getattr(config, "watchdog", None),
            registry=self.registry,
            tracer=self.tracer,
        )
        # ISSUE 11: request-lifecycle tracing — picked up by ServingEngine
        # (the scheduler is the event source; nothing here is per-step)
        self.request_tracer: Optional[RequestTracer] = None
        rt = getattr(config, "request_trace", None)
        if rt is not None and getattr(rt, "enabled", False):
            self.request_tracer = RequestTracer(
                rt.path or os.path.join(config.trace_path or ".", "requests.jsonl"),
                flush_interval=int(rt.flush_interval),
                max_bytes=int(rt.max_mb) * 2**20,
                max_events_per_request=int(rt.max_events_per_request),
                process_index=process_index,
            )
        # ISSUE 16: page-lifetime / session-heat tracing — picked up by
        # ServingEngine (the scheduler attaches per-placement pool ledgers)
        self.kv_heat_tracer: Optional[KVHeatTracer] = None
        kh = getattr(config, "kv_heat", None)
        if kh is not None and getattr(kh, "enabled", False):
            self.kv_heat_tracer = KVHeatTracer(
                kh.path or os.path.join(config.trace_path or ".", "kv_heat.jsonl"),
                flush_interval=int(kh.flush_interval),
                max_bytes=int(kh.max_mb) * 2**20,
                segment_events=int(kh.segment_events),
                idle_thresholds_s=tuple(kh.idle_thresholds_s),
                process_index=process_index,
            )
        # ISSUE 20: metrics time-series journal — picked up by ServingEngine
        # / FleetRouter (they drive maybe_snapshot off the engine clock)
        self.metrics_journal: Optional[MetricsJournal] = None
        ts = getattr(config, "timeseries", None)
        if ts is not None and getattr(ts, "enabled", False):
            self.metrics_journal = MetricsJournal(
                ts.path or os.path.join(config.trace_path or ".", "metrics_tsdb.jsonl"),
                registry=self.registry,
                interval_s=float(ts.interval_s),
                flush_interval=int(ts.flush_interval),
                max_bytes=int(ts.max_mb) * 2**20,
                retention_s=float(ts.retention_s) or 3600.0,
                process_index=process_index,
            )
        compile_stats.install(self.registry)

    # -- wiring --------------------------------------------------------
    def attach_monitor(self, monitor) -> None:
        """Route the full registry through MonitorMaster's backends."""
        self.monitor_bridge = MonitorBridge(self.registry, monitor)

    # -- sampling ------------------------------------------------------
    def should_sample(self, step: int) -> bool:
        if self.tracer is not None:
            return self.tracer.should_sample(step)
        return step % max(1, self.config.sample_every) == 0

    def force_sample(self) -> None:
        if self.tracer is not None:
            self.tracer.force_next()

    # -- recording -----------------------------------------------------
    def record_step(
        self,
        kind: str,
        step: int,
        duration_s: float,
        scalars: Optional[Dict[str, float]] = None,
        spans: Optional[List[Span]] = None,
        hbm: Optional[Dict[str, int]] = None,
        comm_bytes: Optional[Dict[str, float]] = None,
        comm_wire_bytes: Optional[Dict[str, float]] = None,
        extra: Optional[Dict[str, Any]] = None,
        aggregate: bool = False,
    ) -> Dict[str, Any]:
        """Fold one step into the registry and append its JSONL record.

        ``kind`` labels the step family (``train`` / ``inference``);
        ``scalars`` are step-level floats (loss, lr, …); ``spans`` a flat
        (name, ms) list of host-side phases; ``comm_bytes`` per-mesh-axis
        collective byte totals of the compiled step (HLO-derived — already
        wire precision); ``comm_wire_bytes`` the compressed layer's own
        on-wire totals, whose quotient against
        ``extra["comm_compression"][axis]["logical_bytes"]`` is exported as
        the ``comm_compression_ratio`` gauge.
        """
        scalars = scalars or {}
        self.registry.counter(
            "steps_total", "executed steps", labelnames=("kind",)
        ).inc(kind=kind)
        self.registry.histogram(
            "step_seconds", "end-to-end step latency", labelnames=("kind",),
            buckets=STEP_BUCKETS,
        ).observe(duration_s, kind=kind)
        for k, v in scalars.items():
            try:
                self.registry.gauge(f"{kind}_{k}", f"last sampled {k}").set(float(v))
            except (TypeError, ValueError):
                pass
        if hbm:
            for k, v in hbm.items():
                self.registry.gauge(f"hbm_{k}", "device 0 HBM (memory_stats)").set(v)
        if comm_bytes:
            g = self.registry.gauge(
                "comm_bytes_per_step",
                "collective payload per compiled step, by mesh axis",
                labelnames=("axis",),
            )
            for axis, b in comm_bytes.items():
                g.set(b, axis=axis)
        if comm_wire_bytes:
            gw = self.registry.gauge(
                "comm_wire_bytes_per_step",
                "actual on-wire collective bytes per compiled step (compressed "
                "collectives), by mesh axis",
                labelnames=("axis",),
            )
            gr = self.registry.gauge(
                "comm_compression_ratio",
                "logical/wire byte ratio of compressed collectives, by mesh axis",
                labelnames=("axis",),
            )
            for axis, w in comm_wire_bytes.items():
                gw.set(w, axis=axis)
                # logical comes ONLY from the compressed layer's own stats
                # (extra["comm_compression"]) — comm_bytes is HLO-derived and
                # already wire precision (an int8 collective counts 1 B/elem),
                # so dividing by it would report ~1x for compressed runs
                logical = (
                    (extra or {}).get("comm_compression", {}).get(axis, {}).get("logical_bytes")
                )
                if logical and w:
                    gr.set(logical / w, axis=axis)

        dur_ms = duration_s * 1e3
        record: Dict[str, Any] = {
            "kind": f"{kind}_step",
            "step": int(step),
            "dur_ms": round(dur_ms, 3),
            **{k: _as_float(v) for k, v in scalars.items()},
            "spans": spans_to_tree(spans or [], dur_ms),
            "hbm": hbm or {},
            "comm_bytes": comm_bytes or {},
        }
        if comm_wire_bytes:
            record["comm_wire_bytes"] = comm_wire_bytes
        if extra:
            record.update(extra)
        if self.tracer is not None:
            self.tracer.emit(record)
            if aggregate:
                agg = aggregate_scalars(
                    {k: v for k, v in scalars.items() if _is_num(v)}
                )
                if agg is not None:
                    self.tracer.emit_aggregate(
                        {"kind": f"{kind}_step_aggregate", "step": int(step), **agg}
                    )
        self._maybe_export()
        return record

    def record_event(
        self, kind: str, duration_s: float, extra: Optional[Dict[str, Any]] = None
    ) -> None:
        """Non-step events (checkpoint save/load, comms measurement, …):
        a counter + summed-duration counter + one JSONL record."""
        self.registry.counter(f"{kind}_total", f"{kind} events").inc()
        self.registry.counter(
            f"{kind}_seconds_total", f"summed {kind} wall time"
        ).inc(duration_s)
        if self.tracer is not None:
            self.tracer.emit(
                {"kind": kind, "dur_ms": round(duration_s * 1e3, 3), **(extra or {})}
            )

    # -- export --------------------------------------------------------
    def _maybe_export(self) -> None:
        self._records_since_export += 1
        if self._records_since_export >= max(1, self.config.flush_interval):
            self._records_since_export = 0
            if self.prometheus is not None:
                self.prometheus.export()

    def export_monitor(self, step: int) -> int:
        """Fan the registry's scalar samples to the Monitor backends; returns
        the event count (0 when no monitor attached)."""
        if self.monitor_bridge is None:
            return 0
        return self.monitor_bridge.export(step)

    def flush(self) -> None:
        if self.tracer is not None:
            self.tracer.flush()
        if self.request_tracer is not None:
            self.request_tracer.flush()
        if self.kv_heat_tracer is not None:
            self.kv_heat_tracer.flush()
        if self.metrics_journal is not None:
            self.metrics_journal.flush()
        if self.prometheus is not None:
            self.prometheus.export()

    def close(self) -> None:
        self.flush()
        if self.tracer is not None:
            self.tracer.close()
        if self.request_tracer is not None:
            self.request_tracer.close()
        if self.kv_heat_tracer is not None:
            self.kv_heat_tracer.close()
        if self.metrics_journal is not None:
            self.metrics_journal.close()


def _is_num(v) -> bool:
    try:
        float(v)
        return True
    except (TypeError, ValueError):
        return False


def _as_float(v):
    try:
        return float(v)
    except (TypeError, ValueError):
        return v


def from_config(config, monitor=None, process_index: Optional[int] = None) -> Optional[Telemetry]:
    """``TelemetryConfig`` → :class:`Telemetry`, or None when disabled (the
    zero-overhead contract: nothing is constructed, no listener installed,
    no file touched)."""
    if config is None or not getattr(config, "enabled", False):
        return None
    tel = Telemetry(config, process_index=process_index)
    if monitor is not None and getattr(monitor, "enabled", False):
        tel.attach_monitor(monitor)
    return tel
