"""Registry exporters: Prometheus textfile snapshots + Monitor fan-out.

Two sinks for one :class:`~deepspeed_tpu.telemetry.registry.MetricsRegistry`:

- :class:`PrometheusTextfileExporter` renders the registry to the text
  exposition format and atomically replaces a ``.prom`` file that a
  node-exporter textfile collector (or any file-scraping agent) picks up.
- :class:`MonitorBridge` converts every scalar sample into the Monitor
  ``(tag, value, step)`` event tuples, so the full registry fans out to the
  existing TensorBoard / W&B / CSV backends instead of the hand-picked two
  events the engine used to write (reference MonitorMaster write_events
  contract, monitor/monitor.py).
"""

from __future__ import annotations

from typing import List, Tuple

from .registry import MetricsRegistry


class PrometheusTextfileExporter:
    def __init__(self, registry: MetricsRegistry, path: str):
        self.registry = registry
        self.path = path

    def export(self) -> str:
        return self.registry.write_textfile(self.path)


def _tag(sample_name: str) -> str:
    """``comm_bytes_per_step{axis="dp",op="all_reduce"}`` →
    ``comm_bytes_per_step/axis=dp,op=all_reduce`` — TensorBoard rejects
    braces/quotes in tags; '/' groups families into one dashboard section."""
    if "{" not in sample_name:
        return sample_name
    base, labels = sample_name.split("{", 1)
    labels = labels.rstrip("}").replace('"', "")
    return f"{base}/{labels}"


class MonitorBridge:
    def __init__(self, registry: MetricsRegistry, monitor, prefix: str = "Telemetry/"):
        self.registry = registry
        self.monitor = monitor
        self.prefix = prefix

    def events(self, step: int) -> List[Tuple[str, float, int]]:
        return [
            (self.prefix + _tag(name), value, step)
            for name, value in self.registry.scalar_samples()
        ]

    def export(self, step: int) -> int:
        events = self.events(step)
        if events:
            self.monitor.write_events(events)
        return len(events)
