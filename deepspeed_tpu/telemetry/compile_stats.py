"""Compile-pipeline statistics via ``jax.monitoring`` listeners.

XLA compiles are the TPU analog of the reference's CUDA-extension JIT builds:
invisible until they eat minutes of wall clock. jax publishes them on its
monitoring bus (``/jax/core/compile/backend_compile_duration``,
``/jax/compilation_cache/cache_hit|miss`` with the persistent cache on);
this module subscribes once per process and forwards into whatever
:class:`~deepspeed_tpu.telemetry.registry.MetricsRegistry` is currently
installed — counters:

- ``jit_compiles_total``            backend-compile events
- ``jit_compile_seconds_total``     summed backend-compile wall time
- ``jit_trace_seconds_total``       summed jaxpr-trace wall time
- ``jit_cache_hits_total`` / ``jit_cache_misses_total``  persistent-cache outcome

Listeners cannot be unregistered in jax (only globally cleared), so they are
installed once and fan out to every live installed registry (a WeakSet —
compiles are process-global, so a training and an inference engine in one
process both see them, and a dropped engine's registry just falls out). With
no sink installed the callbacks are a substring check and an empty loop —
effectively free — and a disabled-telemetry process never installs them.
"""

from __future__ import annotations

import threading
import weakref

from .registry import MetricsRegistry

_lock = threading.Lock()
_sinks: "weakref.WeakSet[MetricsRegistry]" = weakref.WeakSet()
_listeners_registered = False


def _on_event(event: str, **kw) -> None:
    if "cache_hit" in event:
        name = "jit_cache_hits_total"
    elif "cache_miss" in event:
        name = "jit_cache_misses_total"
    else:
        return
    for reg in list(_sinks):
        reg.counter(name).inc()


def _on_duration(event: str, duration: float, **kw) -> None:
    if "backend_compile" in event:
        for reg in list(_sinks):
            reg.counter("jit_compiles_total").inc()
            reg.counter("jit_compile_seconds_total").inc(duration)
    elif "trace" in event:
        for reg in list(_sinks):
            reg.counter("jit_trace_seconds_total").inc(duration)


def install(registry: MetricsRegistry) -> None:
    """Subscribe ``registry`` to the monitoring listeners (registering them
    on first call). Declares the counters eagerly so a scrape before the
    first compile still sees the families at 0."""
    global _listeners_registered
    with _lock:
        for name, help in (
            ("jit_compiles_total", "XLA backend compile events"),
            ("jit_compile_seconds_total", "summed XLA backend compile wall time"),
            ("jit_trace_seconds_total", "summed jaxpr trace wall time"),
            ("jit_cache_hits_total", "persistent compilation cache hits"),
            ("jit_cache_misses_total", "persistent compilation cache misses"),
        ):
            registry.counter(name, help)
        _sinks.add(registry)
        if not _listeners_registered:
            import jax.monitoring as monitoring

            monitoring.register_event_listener(_on_event)
            monitoring.register_event_duration_secs_listener(_on_duration)
            _listeners_registered = True


def uninstall() -> None:
    """Detach every sink (listeners stay registered but become no-ops;
    jax.monitoring offers no targeted deregistration)."""
    with _lock:
        _sinks.clear()
