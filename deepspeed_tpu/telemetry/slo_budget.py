"""SLO error-budget accounting + multi-window burn-rate alerting (ISSUE 20).

The classic SRE construction over the PR-20 metrics journal: with an
attainment ``objective`` (say 0.99), the **error budget** is the
``1 - objective`` fraction of requests allowed to miss; the **burn rate**
over a window is ``(observed miss fraction) / (budget fraction)`` — 1.0
spends the budget exactly at the allowed pace, 14.4 exhausts a 3-day
budget in 5 hours. Two rules evaluate per SLO class:

- **fast** (default 5m/1h short/long at 14.4x): catches cliffs within
  minutes; the long window de-flaps it — a single bad scrape cannot fire;
- **slow** (default 6h/3d at 1.0x): catches slow grinds the fast rule's
  threshold never sees.

A rule's condition is ``burn(short) >= threshold AND burn(long) >=
threshold``. Windows are **virtual-timebase seconds** read off the
journal's clock — tests and the bench compress them exactly like the
PR-16 idle thresholds, the state machine neither knows nor cares.

Per (class, rule) the alert runs ``inactive → pending → firing →
resolved``: the condition starts a pending dwell (``for_s``; 0 promotes
immediately), sustained condition fires, condition clearing resolves (one
evaluation in ``resolved`` then back to ``inactive``). Transitions to
firing/resolved emit deterministic ``slo_alert`` records into the journal
and bump ``slo_alerts_total{slo_class,rule,state}``; every evaluation
refreshes ``slo_error_budget_remaining{slo_class}`` and
``slo_burn_rate{slo_class,window}`` gauges.

The fleet hook: :meth:`SLOBudgetEngine.firing` feeds
``FleetRouter._should_shed`` when ``serving.fleet.slo_alerts.backpressure``
is on — admission shedding then reacts to *sustained* burn instead of the
instantaneous attainment floor, and a **pending** alert never sheds
(test-pinned).

Counter sources (written by the scheduler's ``_req_terminal`` funnel):
``serving_slo_evaluated_total{slo_class}`` /
``serving_slo_met_total{slo_class}`` — monotone counters, so the journal's
reset-tolerant ``increase()`` is exact over any window.
"""

from __future__ import annotations

import time
from typing import Any, Dict, List, Optional, Tuple

from .timeseries import MetricsJournal

EVALUATED = "serving_slo_evaluated_total"
MET = "serving_slo_met_total"

# gauge window label values, in (rule, position) order
WINDOW_LABELS = ("fast_short", "fast_long", "slow_short", "slow_long")


def _class_sid(name: str, slo_class: str) -> str:
    """The journal series id the scheduler's labeled counter lands under
    (must mirror registry._label_str's escaping)."""
    esc = (
        str(slo_class).replace("\\", r"\\").replace('"', r"\"")
        .replace("\n", r"\n")
    )
    return f'{name}{{slo_class="{esc}"}}'


def _class_of_sid(sid: str) -> Optional[str]:
    """Inverse of :func:`_class_sid` for discovery (single-label series)."""
    pre = '{slo_class="'
    i = sid.find(pre)
    if i < 0 or not sid.endswith('"}'):
        return None
    raw = sid[i + len(pre):-2]
    return (
        raw.replace(r"\n", "\n").replace(r"\"", '"').replace(r"\\", "\\")
    )


class SLOBudgetEngine:
    """Error budget + burn-rate alerts over one :class:`MetricsJournal`.

    ``evaluate()`` is cheap (a few windowed ``increase()`` queries per
    class) but still gated to journal-snapshot cadence via
    :meth:`maybe_evaluate` — the fleet calls that once per step."""

    def __init__(self, journal: MetricsJournal, config, registry=None,
                 clock=None):
        self.journal = journal
        self.cfg = config
        self.clock = clock if clock is not None else journal.clock
        # the in-memory mirror must hold the widest window we will query
        journal.ensure_retention(config.max_window_s())
        self.rules: List[Tuple[str, float, float, float]] = [
            ("fast", float(config.fast_short_s), float(config.fast_long_s),
             float(config.fast_burn_threshold)),
            ("slow", float(config.slow_short_s), float(config.slow_long_s),
             float(config.slow_burn_threshold)),
        ]
        # (slo_class, rule) -> state dict
        self._states: Dict[Tuple[str, str], Dict[str, Any]] = {}
        self.alerts_fired = 0
        self.alerts_resolved = 0
        self._last_eval_t: Optional[float] = None
        self._g_budget = self._g_burn = self._c_alerts = None
        if registry is not None:
            self.bind_registry(registry)

    # -- wiring --------------------------------------------------------
    def bind_registry(self, registry) -> None:
        """Idempotent gauge/counter declaration on the shared registry."""
        self._g_budget = registry.gauge(
            "slo_error_budget_remaining",
            "fraction of the per-class error budget left (1 = untouched, "
            "0 = spent, negative = overspent) at the configured objective",
            labelnames=("slo_class",),
        )
        self._g_burn = registry.gauge(
            "slo_burn_rate",
            "error-budget burn rate per class and alert window "
            "(1.0 = spending exactly the budget over the objective period)",
            labelnames=("slo_class", "window"),
        )
        self._c_alerts = registry.counter(
            "slo_alerts_total",
            "burn-rate alert transitions by class, rule and new state",
            labelnames=("slo_class", "rule", "state"),
        )

    # -- math ----------------------------------------------------------
    def classes(self) -> List[str]:
        """SLO classes observed in the journal (from the evaluated-counter
        series ids)."""
        out = []
        for sid in self.journal.sids(EVALUATED):
            cls = _class_of_sid(sid)
            if cls is not None:
                out.append(cls)
        return sorted(set(out))

    def burn_rate(self, slo_class: str, window_s: float, now: float) -> float:
        """(bad fraction over the trailing window) / (1 - objective)."""
        ev = self.journal.increase(
            _class_sid(EVALUATED, slo_class), now - window_s, now
        )
        if ev <= 0.0:
            return 0.0
        met = self.journal.increase(
            _class_sid(MET, slo_class), now - window_s, now
        )
        bad = max(0.0, ev - met) / ev
        return bad / (1.0 - float(self.cfg.objective))

    def budget_remaining(self, slo_class: str,
                         now: Optional[float] = None) -> float:
        """Cumulative budget left: 1 - bad_total / (evaluated_total *
        (1 - objective)). 1.0 with nothing evaluated; negative =
        overspent."""
        ev = self.journal.latest(_class_sid(EVALUATED, slo_class), now) or 0.0
        if ev <= 0.0:
            return 1.0
        met = self.journal.latest(_class_sid(MET, slo_class), now) or 0.0
        bad = max(0.0, ev - met)
        return 1.0 - bad / (ev * (1.0 - float(self.cfg.objective)))

    # -- the state machine ---------------------------------------------
    def maybe_evaluate(self) -> List[dict]:
        """Evaluate at the journal's last snapshot time, once per snapshot
        (the fleet's per-step call — a no-op between snapshots)."""
        lt = self.journal.last_t
        if lt is None or lt == self._last_eval_t:
            return []
        self._last_eval_t = lt
        return self.evaluate(lt)

    def evaluate(self, now: Optional[float] = None) -> List[dict]:
        """One alerting pass: refresh burn/budget gauges for every class,
        advance each (class, rule) state machine, emit ``slo_alert``
        journal events on firing/resolved transitions. Returns the
        transition records."""
        if now is None:
            now = self.clock()
        transitions: List[dict] = []
        for cls in self.classes():
            for rule, short_s, long_s, threshold in self.rules:
                bs = self.burn_rate(cls, short_s, now)
                bl = self.burn_rate(cls, long_s, now)
                cond = bs >= threshold and bl >= threshold
                st = self._states.setdefault((cls, rule), {
                    "state": "inactive", "t_pending": None,
                    "t_fired": None, "t_resolved": None,
                })
                if cond:
                    if st["state"] in ("inactive", "resolved"):
                        st["state"] = "pending"
                        st["t_pending"] = now
                    if (st["state"] == "pending"
                            and now - st["t_pending"] >= float(self.cfg.for_s)):
                        st["state"] = "firing"
                        st["t_fired"] = now
                        self.alerts_fired += 1
                        transitions.append(self._transition(
                            cls, rule, "firing", bs, bl, threshold, now
                        ))
                else:
                    if st["state"] == "firing":
                        st["state"] = "resolved"
                        st["t_resolved"] = now
                        self.alerts_resolved += 1
                        transitions.append(self._transition(
                            cls, rule, "resolved", bs, bl, threshold, now
                        ))
                    elif st["state"] == "pending":
                        st["state"] = "inactive"
                        st["t_pending"] = None
                    elif st["state"] == "resolved":
                        st["state"] = "inactive"
                st["burn_short"] = bs
                st["burn_long"] = bl
                if self._g_burn is not None:
                    self._g_burn.set(bs, slo_class=cls, window=f"{rule}_short")
                    self._g_burn.set(bl, slo_class=cls, window=f"{rule}_long")
            if self._g_budget is not None:
                self._g_budget.set(self.budget_remaining(cls, now),
                                   slo_class=cls)
        return transitions

    def _transition(self, cls: str, rule: str, state: str, bs: float,
                    bl: float, threshold: float, now: float) -> dict:
        rec = {
            "burn_long": round(bl, 6),
            "burn_short": round(bs, 6),
            "kind": "slo_alert",
            "rule": rule,
            "slo_class": cls,
            "state": state,
            "t": now,
            "threshold": threshold,
        }
        self.journal.emit_event(rec)
        if self._c_alerts is not None:
            self._c_alerts.inc(slo_class=cls, rule=rule, state=state)
        return rec

    # -- consumers ------------------------------------------------------
    def firing(self) -> bool:
        """True while ANY (class, rule) alert is in the firing state — the
        fleet's backpressure signal. Pending never counts."""
        return any(st["state"] == "firing" for st in self._states.values())

    def firing_classes(self) -> List[str]:
        return sorted({
            cls for (cls, _r), st in self._states.items()
            if st["state"] == "firing"
        })

    def states(self) -> Dict[str, Any]:
        """Per-class alert/budget summary for ``stats()`` and the
        dashboard."""
        out: Dict[str, Any] = {}
        for (cls, rule), st in sorted(self._states.items()):
            ent = out.setdefault(cls, {
                "budget_remaining": self.budget_remaining(cls),
                "rules": {},
            })
            ent["rules"][rule] = {
                "state": st["state"],
                "burn_short": st.get("burn_short", 0.0),
                "burn_long": st.get("burn_long", 0.0),
                "t_fired": st.get("t_fired"),
                "t_resolved": st.get("t_resolved"),
            }
        return out
