"""Anomaly watchdog: a production run surfaces its own pathologies.

Detectors
---------
- **non-finite**: NaN/Inf in the step's loss or grad-norm. The engine folds
  a ``jnp.isfinite`` bitmask into the compiled step when
  ``telemetry.watchdog.nan_check`` is on (``anomaly_flags`` metric — zero
  extra host callbacks; the flag rides out with the metrics the sampled
  path already fetches), and the host check here is the fallback for
  host-driven paths.
- **spike**: EMA z-score on watched scalars (loss, grad_norm, step time).
  Each signal keeps an exponentially-weighted mean/variance; after
  ``warmup_steps`` observations, ``(x - mean) / std > zscore`` trips.
  One-sided by design — for every watched signal UP is the pathology, and a
  two-sided test fires on healthy fast-descending loss. The std is floored
  at ``min_rel_std``·|mean| so a near-constant signal (variance ≈ 0) needs
  a material relative jump, not an epsilon. The EMA only absorbs an
  observation AFTER it was judged (spikes clamped to the trip boundary),
  so one spike cannot mask itself or drag the baseline.
- **straggler** (serving): a request resident in a decode slot far beyond
  its expected budget (``straggler_factor`` × max_new_tokens × EMA decode
  step time) — see ``ServingEngine.step``.

On trip
-------
1. a structured ``anomaly`` event lands in the step trace (kind, signal,
   value, z-score, step) and ``anomalies_total{kind}`` increments;
2. an automatic ``jax.profiler`` trace capture of the NEXT executed step is
   scheduled into ``capture_dir/anomaly-step-<N>`` — the anomalous step
   itself already ran, so the capture records the (usually persistent)
   pathology right after detection. The directory is bounded:
   ``max_captures`` total, oldest pruned;
3. ``policy`` decides what happens to the run: ``"continue"`` (default —
   log and keep going), ``"kill"`` (raise :class:`AnomalyError` so the
   training loop stops at the step that went bad instead of burning
   TPU-hours on a diverged run), or ``"rollback"`` (ISSUE 7: the engine
   restores the last good in-memory snapshot and skips the poisoned batch
   — the watchdog only detects and records; the state surgery lives in
   ``runtime/engine.py`` + ``resilience/recovery.py``).

A disabled watchdog config constructs nothing: the engine holds
``watchdog=None`` and the step path pays one ``None`` check.
"""

from __future__ import annotations

import math
import os
import shutil
import time
from typing import Any, Dict, List, Optional

# bit layout of the in-graph anomaly_flags metric (runtime/engine.py)
FLAG_LOSS_NONFINITE = 1
FLAG_GRAD_NONFINITE = 2


class AnomalyError(RuntimeError):
    """Raised by policy="kill" after the anomaly event is recorded."""


class _EmaStat:
    """EWMA mean/variance with an observation count for warmup gating."""

    def __init__(self, alpha: float, min_rel_std: float = 0.02):
        self.alpha = alpha
        self.min_rel_std = min_rel_std
        self.mean = 0.0
        self.var = 0.0
        self.count = 0

    def _std(self) -> float:
        """EWMA std, floored at ``min_rel_std``·|mean|: a near-constant
        signal must jump by a material fraction to register as a spike."""
        return max(
            math.sqrt(max(self.var, 0.0)),
            self.min_rel_std * abs(self.mean),
            1e-12,
        )

    def zscore(self, x: float) -> Optional[float]:
        """z of ``x`` against the CURRENT estimate (pre-update)."""
        if self.count == 0:
            return None
        return (x - self.mean) / self._std()

    def update(self, x: float) -> None:
        if self.count == 0:
            self.mean = x
        else:
            d = x - self.mean
            self.mean += self.alpha * d
            self.var = (1 - self.alpha) * (self.var + self.alpha * d * d)
        self.count += 1


class AnomalyWatchdog:
    """Host-side detector + capture scheduler. One per engine; the serving
    scheduler shares the engine's instance for straggler events."""

    WATCHED = ("loss", "grad_norm", "step_time_s")

    def __init__(self, config, registry=None, tracer=None):
        self.config = config
        self.registry = registry
        self.tracer = tracer
        self.policy = str(getattr(config, "policy", "continue")).lower()
        self.zscore = float(config.zscore)
        self.warmup = int(config.warmup_steps)
        self.check_every = max(1, int(config.check_every))
        self.capture_dir = str(config.capture_dir)
        self.max_captures = max(0, int(config.max_captures))
        self._stats: Dict[str, _EmaStat] = {}
        self._captures_started = 0
        self._capture_pending = False
        self._capture_active: Optional[str] = None
        self.anomalies: List[Dict[str, Any]] = []  # bounded ring, newest last
        self._flagged_stragglers: set = set()
        if registry is not None:
            # declare eagerly so a scrape before the first trip sees zeros
            self._c_anom = registry.counter(
                "anomalies_total", "watchdog trips by kind", labelnames=("kind",)
            )
            self._c_capt = registry.counter(
                "anomaly_captures_total", "profiler captures written by the watchdog"
            )
        else:
            self._c_anom = self._c_capt = None

    # -- detection -----------------------------------------------------
    def observe_step(
        self,
        step: int,
        scalars: Dict[str, float],
        flags: Optional[int] = None,
    ) -> List[Dict[str, Any]]:
        """Judge one step's scalars; returns the anomalies tripped (possibly
        empty). Raises :class:`AnomalyError` under policy="kill" AFTER every
        anomaly of the step is recorded."""
        tripped: List[Dict[str, Any]] = []
        if flags:
            if flags & FLAG_LOSS_NONFINITE:
                tripped.append(self._trip(step, "nonfinite", "loss",
                                          scalars.get("loss"), None))
            if flags & FLAG_GRAD_NONFINITE:
                tripped.append(self._trip(step, "nonfinite", "grad_norm",
                                          scalars.get("grad_norm"), None))
        for name in self.WATCHED:
            v = scalars.get(name)
            if v is None:
                continue
            v = float(v)
            if not math.isfinite(v):
                # host fallback for paths without the in-graph flag; don't
                # double-report a signal the flags already tripped
                if not any(a["signal"] == name and a["anomaly_kind"] == "nonfinite"
                           for a in tripped):
                    tripped.append(self._trip(step, "nonfinite", name, v, None))
                continue
            st = self._stats.setdefault(
                name,
                _EmaStat(
                    float(self.config.ema_alpha),
                    float(getattr(self.config, "min_rel_std", 0.02)),
                ),
            )
            z = st.zscore(v)
            # one-sided: UP is the pathology for every watched signal (a
            # fast-improving loss must not trip)
            if z is not None and st.count >= self.warmup and z > self.zscore:
                tripped.append(self._trip(step, "spike", name, v, z))
                # a judged spike must not drag the baseline toward itself:
                # clamp the absorbed value to the trip boundary
                v = st.mean + self.zscore * st._std()
            st.update(v)
        if tripped and self.policy == "kill":
            a = tripped[0]
            raise AnomalyError(
                f"watchdog[kill]: {a['anomaly_kind']} on {a['signal']} at step {step} "
                f"(value={a['value']}, z={a['z']}) — anomaly event recorded"
                + (f", capture pending in {self.capture_dir}" if self._capture_pending else "")
            )
        return tripped

    def observe_straggler(self, step: int, request_id: int, detail: str) -> bool:
        """Serving-slot straggler: trip once per request."""
        if request_id in self._flagged_stragglers:
            return False
        self._flagged_stragglers.add(request_id)
        self._trip(step, "straggler", f"request_{request_id}", None, None,
                   detail=detail, schedule_capture=False)
        return True

    def _trip(self, step, kind, signal, value, z, detail: str = "",
              schedule_capture: bool = True) -> Dict[str, Any]:
        rec = {
            "kind": "anomaly",
            "anomaly_kind": kind,
            "signal": signal,
            "step": int(step),
            "value": None if value is None or not math.isfinite(float(value)) else float(value),
            "z": round(float(z), 3) if z is not None else None,
            "policy": self.policy,
            "ts": time.time(),
        }
        if detail:
            rec["detail"] = detail
        if self._c_anom is not None:
            self._c_anom.inc(kind=kind)
        if self.tracer is not None:
            self.tracer.emit(rec)
            self.tracer.flush()  # an anomaly must hit disk even if the run dies
        self.anomalies.append(rec)
        del self.anomalies[:-64]
        if schedule_capture and self._captures_started < self.max_captures:
            self._capture_pending = True
        return rec

    # -- profiler capture (driven by the engine's step loop) -----------
    @property
    def capture_pending(self) -> bool:
        return self._capture_pending

    def start_capture(self, step: int) -> Optional[str]:
        """Begin a bounded ``jax.profiler`` capture for the step about to
        run. Returns the capture directory (None when the budget is spent or
        the profiler is unavailable)."""
        if not self._capture_pending or self._capture_active is not None:
            return None
        self._capture_pending = False
        if self._captures_started >= self.max_captures:
            return None
        target = os.path.join(self.capture_dir, f"anomaly-step-{int(step):08d}")
        try:
            self._prune_captures(keep=self.max_captures - 1)
            os.makedirs(target, exist_ok=True)
            import jax.profiler as _prof

            _prof.start_trace(target)
        except Exception:
            return None  # capture is best-effort; never sink the step
        self._capture_active = target
        self._captures_started += 1
        return target

    def stop_capture(self) -> Optional[str]:
        if self._capture_active is None:
            return None
        target, self._capture_active = self._capture_active, None
        try:
            import jax.profiler as _prof

            _prof.stop_trace()
        except Exception:
            return None
        if self._c_capt is not None:
            self._c_capt.inc()
        if self.tracer is not None:
            self.tracer.emit({"kind": "anomaly_capture", "path": target})
        return target

    def _prune_captures(self, keep: int) -> None:
        """Keep the capture directory bounded: newest ``keep`` survive."""
        try:
            entries = sorted(
                e for e in os.listdir(self.capture_dir)
                if e.startswith("anomaly-step-")
            )
        except OSError:
            return
        for e in entries[: max(0, len(entries) - max(0, keep))]:
            shutil.rmtree(os.path.join(self.capture_dir, e), ignore_errors=True)


def from_config(config, registry=None, tracer=None) -> Optional[AnomalyWatchdog]:
    """``WatchdogConfig`` → watchdog, or None when disabled (nothing
    constructed, no counters declared — the zero-overhead contract)."""
    if config is None or not getattr(config, "enabled", False):
        return None
    return AnomalyWatchdog(config, registry=registry, tracer=tracer)
