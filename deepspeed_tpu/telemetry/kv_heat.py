"""Page-lifetime & session-heat tracing (ISSUE 16 tentpole): the memory
measurement plane for KV tiering.

ROADMAP item 2 (ZeRO-Infinity-style spill of cold KV pages to host/NVMe,
PAPERS.md 2104.07857) needs a signal nothing measured before this plane:
which pages are *hot*, which sessions are *idle*, and how big the true
working set is versus the resident set. Following the repo's proven pattern
(PR 11 landed the request-trace plane before item 5's mechanisms), this
module records a per-page lifecycle event stream and derives the
cold-fraction / idle-age curves the tiering PR will ship against.

Architecture — one :class:`KVHeatLedger` per pool (placement), composed by
one :class:`KVHeatTracer` per engine:

- The **ledger** is the lock-free main-thread half. ``PageAllocator`` /
  ``PrefixCache`` / the scheduler each hold it as an optional ``heat``
  attribute (one None check when tracing is off — the PR-11 contract) and
  call plain-append hooks: ``alloc``/``retain``/``free`` from the
  allocator, ``register``/``hit``/``evict`` from the prefix index,
  ``session_start``/``session_end``/``touch_step`` from the scheduler.
  Each hook both appends a compact event tuple to the segment buffer AND
  updates derived state (a refcount mirror, the prefix-held set, per-page
  last-touch, per-slot session activity) — so live gauges need no trace
  round-trip and the fuzz harness can :meth:`~KVHeatLedger.reconcile` the
  mirror bit-exactly against ``PageAllocator.check_consistent()`` state
  after every op.
- The **tracer** owns the JSONL emission: sealed segments ride the
  existing :class:`~deepspeed_tpu.telemetry.tracer.StepTracer` machinery
  (buffered appends, size-capped atomic rotation to ``<file>.1``,
  dsan-shimmed locking) and a background daemon thread does the
  ``json.dumps`` — the scheduler pays list appends, never dtoa (the
  RequestTracer serializer pattern, ISSUE 11).

Event encoding (schema :data:`SCHEMA`). Per-pool ``kv_heat`` records carry
two columnar series:

- ``events`` — low-frequency lifecycle tuples::

      ["A", t, [pages...]]                  alloc (refcount 1 each)
      ["R", t, [pages...]]                  retain (+1 ref each)
      ["F", t, [pages...]]                  free (-1 ref each)
      ["G", t, [pages...]]                  prefix index registered pages
      ["H", t, [pages...], kind]            prefix lookup hit (full/partial)
      ["E", t, page]                        prefix index evicted page
      ["S", t, slot, rid, tenant, [pages]]  session start (block-table order)
      ["X", t, slot]                        session end
      ["B", t, [[page, refs]...], [prefix]] attach-time state snapshot

- ``touches`` — the hottest hook gets the leanest shape (the PR-11 decode
  series rule): one ``[t, step, [[slot, write_page, n_pages]...]]`` entry
  per decode step, one inner triple per active slot. ``write_page`` is the
  page the step's KV write landed in; ``n_pages`` the slot's attended
  block-table prefix length — with the session's ``S`` page list this
  reconstructs the full per-page touch set offline without serializing it
  per step.

All timestamps come from the engine's injectable clock, and the records
carry NO wall-clock field — a seeded replay under ``ReplayClock``
(serving/replay.py) produces a byte-deterministic stream, which is what
lets BENCH_pr16.json commit cold-fraction curves and the what-if spill
comparison as stable artifacts.

Offline, :func:`load_heat_records` (same tolerance contract as the request
trace: rolled ``.1`` generation first, one torn tail line forgiven) feeds
:func:`replay_heat` — which reconstructs a ledger at any point in trace
time — and :func:`evaluate_spill_policies`, the **what-if evaluator**: the
recorded stream replayed against a hypothetically smaller resident set
under candidate eviction policies (idle-age LRU / prefix-aware /
slot-priority), reporting the restore stalls and host traffic each policy
would have cost. The CLI (``tools/kv_heat.py``) renders reports, page
timelines, pool heatmaps, diffs and gates from the same records.
"""

from __future__ import annotations

import json
import os
import sys
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Sequence, Set, Tuple

from .registry import quantile_from_buckets
from .tracer import StepTracer

SCHEMA = "dstpu-kvheat-v1"

# default idle-age thresholds (seconds) for the cold-page-fraction gauges —
# configurable via telemetry.kv_heat.idle_thresholds_s
IDLE_THRESHOLDS_S = (1.0, 5.0, 30.0)

# page-lifetime histogram bounds (seconds): lifetimes span request service
# times, the same band the serving latency buckets cover
LIFETIME_BUCKETS = (
    0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5,
    1.0, 2.5, 5.0, 10.0, 30.0,
)

SPILL_POLICIES = ("idle_lru", "prefix_aware", "slot_priority")


class KVHeatError(Exception):
    """A heat-trace file that cannot be used: wrong schema or corrupt.
    The CLI exits 2 with the message instead of a traceback."""


# ---------------------------------------------------------------------------
# the per-pool ledger: lock-free hooks + derived mirror state
# ---------------------------------------------------------------------------


class KVHeatLedger:
    """One pool's heat state: event buffer + derived accounting mirror.

    Main-thread only (the ServingEngine scheduler is single-threaded by
    contract and is the sole event source) — every hook is plain dict/list
    work, no locks, no device syncs. A ledger is usable standalone (the
    lockstep fuzz drives one with ``sink=None``: derived state updates,
    nothing buffers); under a :class:`KVHeatTracer` sink, full segments are
    sealed into the tracer's encode queue.
    """

    def __init__(
        self,
        pool: str,
        capacity: int,
        *,
        clock: Callable[[], float] = time.monotonic,
        page_bytes: int = 0,
        page_size: int = 0,
        sink: Optional["KVHeatTracer"] = None,
        segment_events: int = 256,
    ):
        self.pool = str(pool)
        self.capacity = int(capacity)
        self.page_bytes = int(page_bytes)
        self.page_size = int(page_size)
        self._clock = clock
        self._sink = sink
        self._segment_events = max(1, int(segment_events))
        # -- derived mirror (reconciles against PageAllocator/PrefixCache) --
        self.refs: Dict[int, int] = {}          # page -> refcount
        self.prefix_pages: Set[int] = set()     # pages the prefix index holds
        self.page_alloc_t: Dict[int, float] = {}  # page -> current lease start
        self.page_last: Dict[int, float] = {}   # page -> last direct touch
        self.owner: Dict[int, int] = {}         # page -> owning slot
        # slot -> {"rid", "tenant", "t0", "last"}
        self.sessions: Dict[int, Dict[str, Any]] = {}
        # -- counters -------------------------------------------------------
        self.allocs = 0
        self.frees = 0
        self.retains = 0
        self.prefix_registered = 0
        self.prefix_hits = 0
        self.prefix_evictions = 0
        self.touch_steps = 0
        self.sessions_started = 0
        self.sessions_ended = 0
        # -- ISSUE 17: host-tier mirror ------------------------------------
        # live host handles (reconciles against HostPageStore.handles())
        self.host_handles: Set[int] = set()
        self.demotions = 0
        self.restores_up = 0
        self.host_drops = 0
        # -- segment buffers (sealed into the sink) -------------------------
        self._events: List[Tuple] = []
        self._touches: List[Tuple] = []
        self._seq = 0

    # -- internal ------------------------------------------------------
    def _ev(self, ev: Tuple) -> None:
        if self._sink is None:
            return
        self._events.append(ev)
        if len(self._events) + len(self._touches) >= self._segment_events:
            self._sink._seal(self)

    # -- attach-time seeding -------------------------------------------
    def seed(self, refs: Dict[int, int], prefix_pages: Sequence[int],
             t: float) -> None:
        """Snapshot the pool's CURRENT state into the mirror (and the
        stream, as a ``B`` event) — attaching mid-run must reconcile from
        the first event, and an offline replay must start from the same
        point the live ledger did."""
        self.refs = {int(p): int(c) for p, c in refs.items()}
        self.prefix_pages = {int(p) for p in prefix_pages}
        for p in self.refs:
            self.page_alloc_t[p] = t
            self.page_last[p] = t
        self._ev((
            "B", t, sorted([p, c] for p, c in self.refs.items()),
            sorted(self.prefix_pages),
        ))

    # -- allocator-facing hooks (PageAllocator.heat) -------------------
    def alloc(self, pages: Sequence[int]) -> None:
        t = self._clock()
        refs, at, last = self.refs, self.page_alloc_t, self.page_last
        for p in pages:
            refs[p] = 1
            at[p] = t
            last[p] = t
        self.allocs += len(pages)
        self._ev(("A", t, list(pages)))

    def retain(self, pages: Sequence[int]) -> None:
        t = self._clock()
        refs, last = self.refs, self.page_last
        for p in pages:
            p = int(p)
            refs[p] = refs.get(p, 0) + 1
            last[p] = t
        self.retains += len(pages)
        self._ev(("R", t, [int(p) for p in pages]))

    def free(self, pages: Sequence[int]) -> None:
        t = self._clock()
        refs = self.refs
        ids = []
        for p in pages:
            p = int(p)
            ids.append(p)
            c = refs.get(p)
            if c is None:
                # a pool freeing pages leased before this ledger attached
                # (no B snapshot covered them) — tolerated, not mirrored
                continue
            if c > 1:
                refs[p] = c - 1
            else:
                del refs[p]
                t0 = self.page_alloc_t.pop(p, None)
                self.page_last.pop(p, None)
                self.owner.pop(p, None)
                self.prefix_pages.discard(p)
                if self._sink is not None and t0 is not None:
                    self._sink._observe_lifetime(self.pool, t - t0)
        self.frees += len(ids)
        self._ev(("F", t, ids))

    # -- prefix-index-facing hooks (PrefixCache.heat) ------------------
    def register(self, pages: Sequence[int]) -> None:
        t = self._clock()
        self.prefix_pages.update(int(p) for p in pages)
        self.prefix_registered += len(pages)
        self._ev(("G", t, [int(p) for p in pages]))

    def hit(self, pages: Sequence[int], kind: str) -> None:
        t = self._clock()
        last = self.page_last
        for p in pages:
            last[int(p)] = t
        self.prefix_hits += 1
        self._ev(("H", t, [int(p) for p in pages], kind))

    def evict(self, page: int) -> None:
        t = self._clock()
        self.prefix_pages.discard(int(page))
        self.prefix_evictions += 1
        self._ev(("E", t, int(page)))

    # -- host-tier-facing hooks (ISSUE 17: KVTieringEngine.ledger) ------
    def demote(self, page: int, hid: int) -> None:
        """Device page ``page`` is spilling to host handle ``hid``. Emitted
        BEFORE the device-side free's F/E pair (PrefixCache._evict_one), so
        every trace prefix shows the page owned by at least one tier."""
        t = self._clock()
        self.host_handles.add(int(hid))
        self.demotions += 1
        self._ev(("D", t, int(page), int(hid)))

    def restore_up(self, hid: int, page: int) -> None:
        """Host handle ``hid`` restored into freshly allocated device page
        ``page`` — the host copy retires (exactly-one-tier)."""
        t = self._clock()
        self.host_handles.discard(int(hid))
        self.page_last[int(page)] = t
        self.restores_up += 1
        self._ev(("U", t, int(hid), int(page)))

    def host_drop(self, hid: int) -> None:
        """Host handle ``hid`` evicted from the host tier (LRU pressure) —
        the page now lives in NEITHER tier; a future hit is a cold miss."""
        t = self._clock()
        self.host_handles.discard(int(hid))
        self.host_drops += 1
        self._ev(("V", t, int(hid)))

    # -- scheduler-facing hooks ----------------------------------------
    def session_start(self, t: float, slot: int, rid: int, tenant: str,
                      pages: Sequence[int]) -> None:
        """A request took a slot: ``pages`` is its reservation in
        block-table order (the touch series' ``n_pages`` prefix indexes
        into it offline)."""
        pages = [int(p) for p in pages]
        self.sessions[slot] = {"rid": rid, "tenant": tenant, "t0": t, "last": t}
        owner = self.owner
        for p in pages:
            owner[p] = slot
        self.sessions_started += 1
        self._ev(("S", t, int(slot), rid, tenant, pages))

    def session_end(self, t: float, slot: int) -> None:
        self.sessions.pop(slot, None)
        self.sessions_ended += 1
        self._ev(("X", t, int(slot)))

    def touch_step(self, t: float, step: int, batch: Sequence[Tuple]) -> None:
        """One decode step's write/attend touches, columnar:
        ``batch = [(slot, write_page, n_pages), ...]``. The hottest hook in
        the plane — per step it costs one tuple append plus two dict writes
        per active slot."""
        sessions, last = self.sessions, self.page_last
        for slot, wp, _n in batch:
            ss = sessions.get(slot)
            if ss is not None:
                ss["last"] = t
            last[wp] = t
        self.touch_steps += 1
        if self._sink is not None:
            # shallow copy only: the per-slot tuples are immutable and
            # JSON-serialize exactly like lists (the hot hook — every
            # decode step pays this line)
            self._touches.append((t, step, list(batch)))
            if len(self._events) + len(self._touches) >= self._segment_events:
                self._sink._seal(self)

    # -- derived views -------------------------------------------------
    @property
    def pages_in_use(self) -> int:
        return len(self.refs)

    @property
    def free_count(self) -> int:
        return self.capacity - len(self.refs)

    def occupancy(self, now: float,
                  thresholds: Sequence[float] = IDLE_THRESHOLDS_S) -> Dict[str, Any]:
        """The pool's occupancy split + heat summary at ``now``:
        ``pages`` by category (``active`` — owned by a live session;
        ``prefix`` — else held by the prefix index; ``shared`` — else
        refcount > 1; ``other`` — in use, unattributed; ``free``),
        ``cold_fraction`` per idle threshold (a page is hot if its owning
        session was active, or it was directly touched, within the
        threshold) and free-list ``fragmentation``."""
        refs = self.refs
        sessions = self.sessions
        cat = {"active": 0, "prefix": 0, "shared": 0, "other": 0}
        cold = {th: 0 for th in thresholds}
        last = self.page_last
        owner = self.owner
        for p, c in refs.items():
            slot = owner.get(p)
            ss = sessions.get(slot) if slot is not None else None
            if ss is not None:
                cat["active"] += 1
            elif p in self.prefix_pages:
                cat["prefix"] += 1
            elif c > 1:
                cat["shared"] += 1
            else:
                cat["other"] += 1
            hot_t = ss["last"] if ss is not None else None
            pl = last.get(p)
            if pl is not None and (hot_t is None or pl > hot_t):
                hot_t = pl
            age = now - hot_t if hot_t is not None else float("inf")
            for th in thresholds:
                if age > th:
                    cold[th] += 1
        in_use = len(refs)
        return {
            "pages": {**cat, "free": self.capacity - in_use},
            "pages_in_use": in_use,
            "capacity": self.capacity,
            "cold_fraction": {
                str(th): (cold[th] / in_use) if in_use else None
                for th in thresholds
            },
            "fragmentation": self.fragmentation(),
            "sessions": len(sessions),
        }

    def fragmentation(self) -> float:
        """1 − (longest run of consecutive free page ids / free pages): 0.0
        when the free ids form one contiguous block (or the pool is full) —
        the page granularity makes this advisory (any page serves any
        request), but a scattered free set is exactly what a future
        contiguous host-spill DMA would pay for."""
        in_use = self.refs
        free = [p for p in range(1, self.capacity + 1) if p not in in_use]
        if not free:
            return 0.0
        longest = run = 1
        for i in range(1, len(free)):
            run = run + 1 if free[i] == free[i - 1] + 1 else 1
            if run > longest:
                longest = run
        return 1.0 - longest / len(free)

    def session_idle_ages(self, now: float) -> List[float]:
        return [now - ss["last"] for ss in self.sessions.values()]

    def reconcile(self, allocator, prefix_cache=None,
                  host_store=None) -> Optional[str]:
        """Bit-exact cross-check of the derived mirror against the live
        allocator (and prefix index): the ISSUE 16 lockstep acceptance.
        Returns None when they agree, else a one-line mismatch."""
        err = allocator.check_consistent()
        if err is not None:
            return f"allocator corrupt: {err}"
        theirs = allocator.refcounts()
        if self.refs != theirs:
            diff = {
                p: (self.refs.get(p), theirs.get(p))
                for p in set(self.refs) | set(theirs)
                if self.refs.get(p) != theirs.get(p)
            }
            return f"refcount mirror diverged: {dict(sorted(diff.items())[:4])}"
        if self.free_count != allocator.free_pages:
            return (
                f"free accounting diverged: ledger {self.free_count} != "
                f"allocator {allocator.free_pages}"
            )
        if prefix_cache is not None:
            held = {int(p) for p in prefix_cache.held_pages}
            if self.prefix_pages != held:
                return (
                    f"prefix-held mirror diverged: ledger "
                    f"{sorted(self.prefix_pages)[:6]} != index {sorted(held)[:6]}"
                )
        if host_store is not None:
            theirs = host_store.handles()
            if self.host_handles != theirs:
                return (
                    f"host-handle mirror diverged: ledger "
                    f"{sorted(self.host_handles)[:6]} != store "
                    f"{sorted(theirs)[:6]}"
                )
        return None

    def ledger_bytes(self) -> int:
        """Rough host-side footprint of the mirror + segment buffers — the
        heat plane's own entry in the host-metadata budget (satellite 1)."""
        total = 0
        for d in (self.refs, self.page_alloc_t, self.page_last, self.owner):
            total += sys.getsizeof(d) + 56 * len(d)
        total += sys.getsizeof(self.prefix_pages) + 28 * len(self.prefix_pages)
        total += sys.getsizeof(self.host_handles) + 28 * len(self.host_handles)
        total += sys.getsizeof(self.sessions) + 256 * len(self.sessions)
        total += sys.getsizeof(self._events) + 96 * len(self._events)
        total += sys.getsizeof(self._touches) + 96 * len(self._touches)
        return total


# ---------------------------------------------------------------------------
# the tracer: pools + background JSONL emission
# ---------------------------------------------------------------------------


class KVHeatTracer:
    """Per-engine heat-event emitter over the StepTracer JSONL machinery.

    Owns one :class:`KVHeatLedger` per pool (placement) and the encode
    pipeline: sealed segments queue under a dsan-shimmed lock and a daemon
    thread json-encodes them (the ISSUE 11 serializer pattern — the
    scheduler never waits on a dumps; a drop-oldest backstop bounds memory
    and counts ``records_lost``). ``bind_registry`` wires the derived
    gauges; the scheduler refreshes them through :meth:`refresh_gauges`.
    """

    def __init__(
        self,
        path: str,
        flush_interval: int = 20,
        max_bytes: int = 64 * 2**20,
        segment_events: int = 256,
        idle_thresholds_s: Sequence[float] = IDLE_THRESHOLDS_S,
        process_index: Optional[int] = None,
        clock: Callable[[], float] = time.monotonic,
    ):
        if not path.endswith(".jsonl"):
            path = os.path.join(path, "kv_heat.jsonl")
        self._writer = StepTracer(
            path,
            flush_interval=flush_interval,
            sample_every=1,
            process_index=process_index,
            max_bytes=max_bytes,
        )
        self.clock = clock
        self.idle_thresholds_s = tuple(float(t) for t in idle_thresholds_s)
        self._segment_events = max(1, int(segment_events))
        self._ledgers: Dict[str, KVHeatLedger] = {}
        self.records_emitted = 0
        # registry families (bind_registry); None until an engine attaches
        self._g_pages = None
        self._g_cold = None
        self._g_frag = None
        self._g_idle = None
        self._g_bytes = None
        self._h_lifetime = None
        # (pool, dt) lifetime observations deferred out of the free() hook
        # — drained into the histogram at gauge-refresh/flush cadence
        self._pending_lifetimes: List[Tuple[str, float]] = []
        # cross-thread encode queue (dsan-shimmed lock, ISSUE 8)
        self._lock = StepTracer._new_lock()
        self._pending: List[Dict[str, Any]] = []
        self._inflight = 0
        self._closed = False
        self._draining = False
        self.records_lost = 0
        self._encode_error: Optional[str] = None
        self._encode_batch = max(1, int(flush_interval))
        self._wake = threading.Event()
        self._thread = threading.Thread(
            target=self._serialize_loop, name="kv-heat-serializer", daemon=True,
        )
        self._thread.start()

    # -- pools ---------------------------------------------------------
    def pool(self, name: str, capacity: int, *, page_size: int = 0,
             page_bytes: int = 0,
             clock: Optional[Callable[[], float]] = None) -> KVHeatLedger:
        """Create (or return) the ledger for pool ``name``; first creation
        emits the pool's ``kv_heat_meta`` record (capacity, page geometry —
        what the offline evaluator sizes its hypothetical resident set
        against)."""
        led = self._ledgers.get(name)
        if led is not None:
            return led
        if clock is not None:
            self.clock = clock
        led = KVHeatLedger(
            name, capacity, clock=clock or self.clock, page_bytes=page_bytes,
            page_size=page_size, sink=self, segment_events=self._segment_events,
        )
        self._ledgers[name] = led
        self._enqueue({
            "kind": "kv_heat_meta", "schema": SCHEMA, "pool": name,
            "capacity": int(capacity), "page_size": int(page_size),
            "page_bytes": int(page_bytes),
            "idle_thresholds_s": list(self.idle_thresholds_s),
        })
        return led

    @property
    def ledgers(self) -> Dict[str, KVHeatLedger]:
        return self._ledgers

    # -- emission ------------------------------------------------------
    def _seal(self, ledger: KVHeatLedger) -> None:
        """Package a ledger's buffered events into one segment record and
        queue it for background encode. Called from the hooks at the
        segment threshold and from :meth:`flush` — always the scheduler
        thread, so the swap needs no lock."""
        if not ledger._events and not ledger._touches:
            return
        events, ledger._events = ledger._events, []
        touches, ledger._touches = ledger._touches, []
        rec = {
            "kind": "kv_heat", "schema": SCHEMA, "pool": ledger.pool,
            "seq": ledger._seq, "events": events, "touches": touches,
        }
        ledger._seq += 1
        self._enqueue(rec)

    def _enqueue(self, rec: Dict[str, Any]) -> None:
        self.records_emitted += 1
        with self._lock:
            self._pending.append(rec)
            if len(self._pending) > 16 * self._encode_batch:
                del self._pending[0]
                self.records_lost += 1
            wake = len(self._pending) >= self._encode_batch
        if wake:
            self._wake.set()

    def _serialize_loop(self) -> None:
        """Background encoder — the RequestTracer drain discipline: take
        only full batches while the server is live, drain sub-batch tails
        on flush/close or after a quiet idle window, and survive write
        failures (count ``records_lost``, keep serving)."""
        stale_pending = -1
        while True:
            timed_out = not self._wake.wait(timeout=2.0)
            self._wake.clear()
            while True:
                with self._lock:
                    n = len(self._pending)
                    take = n > 0 and (
                        n >= self._encode_batch
                        or self._draining or self._closed
                        or (timed_out and n == stale_pending)
                    )
                    if take:
                        batch = self._pending
                        self._pending = []
                        self._inflight += len(batch)
                    elif self._closed:
                        return
                    else:
                        break
                handed = 0
                try:
                    for rec in batch:
                        self._writer.emit_serialized(
                            json.dumps(rec, default=str)
                        )
                        handed += 1
                except Exception as e:  # noqa: BLE001 — daemon must survive
                    with self._lock:
                        self.records_lost += len(batch) - handed
                        self._encode_error = f"{type(e).__name__}: {e}"
                finally:
                    with self._lock:
                        self._inflight -= len(batch)
            if timed_out:
                with self._lock:
                    stale_pending = len(self._pending)
            else:
                stale_pending = -1

    # -- derived gauges ------------------------------------------------
    def bind_registry(self, registry) -> None:
        """Declare the derived gauge/histogram families on ``registry``
        (idempotent — get-or-create semantics both here and in the
        registry)."""
        if self._g_pages is not None:
            return
        self._g_pages = registry.gauge(
            "serving_kv_heat_pages",
            "pool occupancy split: active (live-session-owned) / prefix "
            "(index-held) / shared (multi-ref, unattributed) / other / free",
            labelnames=("pool", "category"),
        )
        self._g_cold = registry.gauge(
            "serving_kv_heat_cold_fraction",
            "fraction of in-use pages idle beyond the threshold (seconds) — "
            "the working-set-vs-resident-set signal KV tiering spills by",
            labelnames=("pool", "threshold"),
        )
        self._g_frag = registry.gauge(
            "serving_kv_heat_fragmentation",
            "1 - longest contiguous free run / free pages (0 = one block)",
            labelnames=("pool",),
        )
        self._g_idle = registry.gauge(
            "serving_kv_heat_session_idle_age_seconds",
            "live-session idle-age quantiles (time since last touch)",
            labelnames=("q",),
        )
        self._g_bytes = registry.gauge(
            "serving_kv_heat_ledger_bytes",
            "host-side footprint of the heat ledgers (mirror + buffers)",
        )
        self._h_lifetime = registry.histogram(
            "serving_kv_page_lifetime_seconds",
            "page lease lifetime, alloc to final free (per pool)",
            labelnames=("pool",),
            buckets=LIFETIME_BUCKETS,
        )

    def _observe_lifetime(self, pool: str, dt: float) -> None:
        # called from free() — the hot path stays a list append; the
        # histogram bisect + label resolution runs at drain cadence
        if self._h_lifetime is not None:
            self._pending_lifetimes.append((pool, dt))

    def _drain_lifetimes(self) -> None:
        if not self._pending_lifetimes:
            return
        obs, self._pending_lifetimes = self._pending_lifetimes, []
        h = self._h_lifetime
        for pool, dt in obs:
            h.observe(dt, pool=pool)

    def refresh_gauges(self, now: Optional[float] = None) -> None:
        """Recompute the derived gauges from the ledgers — O(pages), called
        at the scheduler's stats cadence, never per step."""
        if self._g_pages is None:
            return
        self._drain_lifetimes()
        now = self.clock() if now is None else now
        ages: List[float] = []
        for led in self._ledgers.values():
            occ = led.occupancy(now, self.idle_thresholds_s)
            for catg, n in occ["pages"].items():
                self._g_pages.set(n, pool=led.pool, category=catg)
            for th, frac in occ["cold_fraction"].items():
                if frac is not None:
                    self._g_cold.set(frac, pool=led.pool, threshold=th)
            self._g_frag.set(occ["fragmentation"], pool=led.pool)
            ages.extend(led.session_idle_ages(now))
        if ages:
            ages.sort()
            for q, label in ((0.5, "p50"), (0.95, "p95"), (0.99, "p99")):
                self._g_idle.set(
                    ages[min(len(ages) - 1, int(q * len(ages)))], q=label
                )
        self._g_bytes.set(self.ledger_bytes())

    def ledger_bytes(self) -> int:
        return sum(led.ledger_bytes() for led in self._ledgers.values())

    # -- plumbing ------------------------------------------------------
    def flush(self) -> None:
        """Seal every ledger's buffered tail, block until all queued
        segments are encoded + buffered in the writer, then flush the
        writer to disk."""
        self._drain_lifetimes()
        for led in self._ledgers.values():
            self._seal(led)
        with self._lock:
            self._draining = True
        try:
            while self._thread.is_alive():
                with self._lock:
                    if not self._pending and self._inflight == 0:
                        break
                self._wake.set()
                time.sleep(0.0005)
        finally:
            with self._lock:
                self._draining = False
        self._writer.flush()

    def close(self) -> None:
        self.flush()
        with self._lock:
            self._closed = True
        self._wake.set()
        self._thread.join(timeout=5.0)
        self._writer.close()

    @property
    def file_path(self) -> str:
        return self._writer.file_path

    @property
    def rotations(self) -> int:
        return self._writer.rotations

    @property
    def encode_error(self) -> Optional[str]:
        with self._lock:
            return self._encode_error


# ---------------------------------------------------------------------------
# loading
# ---------------------------------------------------------------------------


def load_heat_records(path: str) -> List[Dict[str, Any]]:
    """The ``kv_heat`` / ``kv_heat_meta`` records of one JSONL trace, in
    file order — the same tolerance contract as
    ``telemetry.request_trace.load_request_records``: a rolled ``.1``
    generation is read first, one torn TAIL line (killed run) is forgiven,
    anything else corrupt or claiming an unknown schema raises
    :class:`KVHeatError`."""
    paths = [p for p in (path + ".1", path) if os.path.exists(p)]
    if not paths:
        raise KVHeatError(f"{path}: no such trace file")
    out: List[Dict[str, Any]] = []
    for p in paths:
        torn: List[int] = []
        try:
            with open(p, encoding="utf-8") as fh:
                lines = fh.readlines()
        except UnicodeDecodeError as e:
            raise KVHeatError(
                f"{p}: not a text JSONL trace ({e.reason} at byte {e.start})"
            ) from e
        for lineno, line in enumerate(lines, start=1):
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except json.JSONDecodeError:
                torn.append(lineno)
                continue
            if not isinstance(rec, dict):
                raise KVHeatError(
                    f"{p}:{lineno}: JSON line is {type(rec).__name__}, not "
                    "an object — this is not a KV heat trace"
                )
            if rec.get("kind") not in ("kv_heat", "kv_heat_meta"):
                continue  # request/step records share the telemetry dir
            schema = rec.get("schema")
            if schema != SCHEMA:
                raise KVHeatError(
                    f"{p}:{lineno}: schema {schema!r} != {SCHEMA!r} — trace "
                    "written by an incompatible version"
                )
            out.append(rec)
        if torn and torn != [len(lines)]:
            raise KVHeatError(
                f"{p}: {len(torn)} undecodable line(s) (first at line "
                f"{torn[0]}) — truncated or corrupt beyond a torn tail"
            )
    return out


def pools_in(records: Sequence[Dict[str, Any]]) -> List[str]:
    """Pool names present in a record set, meta-record order first."""
    seen: List[str] = []
    for rec in records:
        pl = rec.get("pool")
        if pl is not None and pl not in seen:
            seen.append(pl)
    return seen


# ---------------------------------------------------------------------------
# offline replay: reconstruct ledger state from a trace
# ---------------------------------------------------------------------------


class _TraceClock:
    """Settable clock for offline replay: ledger hooks read the timestamp
    of the event currently being applied."""

    def __init__(self):
        self.t = 0.0

    def __call__(self) -> float:
        return self.t


def iter_pool_events(records: Sequence[Dict[str, Any]], pool: str):
    """One pool's merged event stream in time order: yields
    ``("touch", t, step, batch)`` and ``(op, t, *payload)`` lifecycle
    tuples, merged from the segment records' two columnar series."""
    merged: List[Tuple[float, int, Tuple]] = []
    for rec in records:
        if rec.get("kind") != "kv_heat" or rec.get("pool") != pool:
            continue
        for ev in rec.get("events") or ():
            merged.append((float(ev[1]), 0, tuple(ev)))
        for tch in rec.get("touches") or ():
            merged.append((float(tch[0]), 1, ("touch", *tch)))
    # stable by (time, lifecycle-before-touch): events within one segment
    # are already ordered; the sort only interleaves the two series
    merged.sort(key=lambda x: (x[0], x[1]))
    for _t, _k, ev in merged:
        yield ev


def replay_heat(
    records: Sequence[Dict[str, Any]],
    pool: str,
    on_event: Optional[Callable[[Tuple, KVHeatLedger], None]] = None,
) -> KVHeatLedger:
    """Rebuild a :class:`KVHeatLedger` (sink-less: derived state only) by
    replaying one pool's recorded stream. ``on_event(ev, ledger)`` fires
    after each applied event — the hook the cold-fraction curves and the
    lockstep tests sample through. Returns the end-of-trace ledger."""
    meta = next(
        (r for r in records
         if r.get("kind") == "kv_heat_meta" and r.get("pool") == pool),
        None,
    )
    if meta is None:
        raise KVHeatError(f"pool {pool!r}: no kv_heat_meta record in trace")
    clk = _TraceClock()
    led = KVHeatLedger(
        pool, int(meta["capacity"]), clock=clk,
        page_bytes=int(meta.get("page_bytes") or 0),
        page_size=int(meta.get("page_size") or 0),
    )
    for ev in iter_pool_events(records, pool):
        op = ev[0]
        clk.t = float(ev[1])
        if op == "touch":
            _, t, step, batch = ev
            led.touch_step(float(t), int(step), [tuple(b) for b in batch])
        elif op == "A":
            led.alloc(ev[2])
        elif op == "R":
            led.retain(ev[2])
        elif op == "F":
            led.free(ev[2])
        elif op == "G":
            led.register(ev[2])
        elif op == "H":
            led.hit(ev[2], ev[3] if len(ev) > 3 else "")
        elif op == "E":
            led.evict(ev[2])
        elif op == "D":
            led.demote(ev[2], ev[3])
        elif op == "U":
            led.restore_up(ev[2], ev[3])
        elif op == "V":
            led.host_drop(ev[2])
        elif op == "S":
            led.session_start(float(ev[1]), int(ev[2]), ev[3], ev[4], ev[5])
        elif op == "X":
            led.session_end(float(ev[1]), int(ev[2]))
        elif op == "B":
            led.seed({int(p): int(c) for p, c in ev[2]}, ev[3], float(ev[1]))
        if on_event is not None:
            on_event(ev, led)
    return led


def cold_fraction_curve(
    records: Sequence[Dict[str, Any]],
    pool: str,
    threshold_s: float,
    bins: int = 10,
) -> List[Dict[str, Any]]:
    """The pool's cold-page fraction sampled at ``bins`` equal windows of
    trace time — the BENCH_pr16 curve shape (cold fraction vs load)."""
    times = [
        float(ev[1]) for ev in iter_pool_events(records, pool)
    ]
    if not times:
        return []
    t0, t1 = min(times), max(times)
    width = max((t1 - t0) / max(1, bins), 1e-12)
    edges = [t0 + (b + 1) * width for b in range(bins)]
    out: List[Dict[str, Any]] = []
    state = {"i": 0}

    def sample(now: float, led: KVHeatLedger) -> None:
        occ = led.occupancy(now, (threshold_s,))
        out.append({
            "t": now,
            "pages_in_use": occ["pages_in_use"],
            "cold_fraction": occ["cold_fraction"][str(threshold_s)],
            "sessions": occ["sessions"],
        })

    def on_event(ev: Tuple, led: KVHeatLedger) -> None:
        t = float(ev[1])
        while state["i"] < len(edges) and t >= edges[state["i"]]:
            sample(edges[state["i"]], led)
            state["i"] += 1

    led = replay_heat(records, pool, on_event=on_event)
    while state["i"] < len(edges):
        sample(edges[state["i"]], led)
        state["i"] += 1
    return out


# ---------------------------------------------------------------------------
# the what-if spill evaluator
# ---------------------------------------------------------------------------


def evaluate_spill_policies(
    records: Sequence[Dict[str, Any]],
    pool: str,
    resident_fraction: float = 0.5,
    policies: Sequence[str] = SPILL_POLICIES,
) -> Dict[str, Any]:
    """Replay one pool's recorded heat stream against a hypothetical
    resident set of ``resident_fraction × capacity`` pages under each
    candidate eviction policy, and report what the run WOULD have cost:

    - ``spills`` / ``spilled_bytes`` — pages pushed to host when the
      resident set overflowed (host write traffic),
    - ``restore_stalls`` — events (an admission's page reuse, or a decode
      step-slot touch) that found a needed page spilled and would have
      stalled on the restore,
    - ``restored_bytes`` — host read traffic bringing those pages back.

    Policies (the ROADMAP item 2 candidates):

    - ``idle_lru`` — spill the page with the oldest direct touch.
    - ``prefix_aware`` — spill non-prefix-held pages first (index pages
      are the ones future admissions re-hit), idle-age LRU within a class.
    - ``slot_priority`` — spill pages of idle/ended sessions before pages
      of recently-active ones (session recency, then page idle age).

    Deterministic: pure function of the recorded stream (ties break on
    page id), so the PR-11 seeded replay harness makes the whole
    comparison a committed artifact."""
    meta = next(
        (r for r in records
         if r.get("kind") == "kv_heat_meta" and r.get("pool") == pool),
        None,
    )
    if meta is None:
        raise KVHeatError(f"pool {pool!r}: no kv_heat_meta record in trace")
    capacity = int(meta["capacity"])
    page_bytes = int(meta.get("page_bytes") or 0)
    cap = max(1, int(capacity * float(resident_fraction)))
    results: Dict[str, Any] = {}
    for policy in policies:
        if policy not in SPILL_POLICIES:
            raise KVHeatError(
                f"unknown spill policy {policy!r} (one of {SPILL_POLICIES})"
            )
        results[policy] = _simulate_policy(
            records, pool, policy, cap, page_bytes
        )
    return {
        "pool": pool,
        "capacity": capacity,
        "resident_cap": cap,
        "resident_fraction": float(resident_fraction),
        "page_bytes": page_bytes,
        "policies": results,
    }


def _simulate_policy(
    records: Sequence[Dict[str, Any]],
    pool: str,
    policy: str,
    cap: int,
    page_bytes: int,
) -> Dict[str, Any]:
    # simulator state beside the ledger: which in-use pages are resident
    resident: Set[int] = set()
    spilled: Set[int] = set()
    stats = {"spills": 0, "restore_stalls": 0}
    st = {"led": None}

    def victim_key(p: int, led: KVHeatLedger, now: float):
        age = now - led.page_last.get(p, now)
        if policy == "idle_lru":
            return (-age, p)
        if policy == "prefix_aware":
            # non-prefix pages first (False < True), then oldest
            return (p in led.prefix_pages, -age, p)
        # slot_priority: pages of live recently-active sessions last
        slot = led.owner.get(p)
        ss = led.sessions.get(slot) if slot is not None else None
        sess_last = ss["last"] if ss is not None else -float("inf")
        return (ss is not None, sess_last, -age, p)

    def make_room(n: int, led: KVHeatLedger, now: float,
                  pinned: Set[int]) -> None:
        while len(resident) + n > cap:
            candidates = [p for p in resident if p not in pinned]
            if not candidates:
                break  # everything resident is pinned by the current event
            victim = min(candidates, key=lambda p: victim_key(p, led, now))
            resident.discard(victim)
            spilled.add(victim)
            stats["spills"] += 1

    def admit(pages: Sequence[int], led: KVHeatLedger, now: float) -> None:
        pages = [int(p) for p in pages]
        new = [p for p in pages if p not in resident]
        if not new:
            return
        make_room(len(new), led, now, pinned=set(pages))
        for p in new:
            spilled.discard(p)
            resident.add(p)

    def require(pages: Sequence[int], led: KVHeatLedger, now: float) -> int:
        """Touched pages must be resident: restore any spilled ones;
        returns the number restored (0 = no stall)."""
        need = [int(p) for p in pages if int(p) in spilled]
        if not need:
            return 0
        make_room(len(need), led, now, pinned={int(p) for p in pages})
        for p in need:
            spilled.discard(p)
            resident.add(p)
        return len(need)

    restored_pages = 0

    def on_event(ev: Tuple, led: KVHeatLedger) -> None:
        nonlocal restored_pages
        op = ev[0]
        now = float(ev[1])
        if op == "A":
            admit(ev[2], led, now)
        elif op == "B":
            admit([p for p, _c in ev[2]], led, now)
        elif op in ("R", "H"):
            n = require(ev[2], led, now)
            if n:
                stats["restore_stalls"] += 1
                restored_pages += n
        elif op == "F":
            for p in ev[2]:
                p = int(p)
                if p not in led.refs:  # final free: page left the pool
                    resident.discard(p)
                    spilled.discard(p)
        elif op == "touch":
            _, t, _step, batch = ev
            sess = led.sessions
            stalls = 0
            for slot, wp, n_pages in batch:
                # reconstruct the slot's attended prefix from its session's
                # block-table-ordered page list
                ss = sess.get(slot)
                if ss is not None and "pages" in ss:
                    pages = ss["pages"][: int(n_pages)]
                else:
                    pages = [int(wp)]
                n = require(pages, led, float(t))
                if n:
                    stalls += 1
                    restored_pages += n
            stats["restore_stalls"] += stalls
        elif op == "S":
            # stash the block-table-ordered reservation on the session so
            # touch events can expand their attended prefixes
            ss = led.sessions.get(int(ev[2]))
            if ss is not None:
                ss["pages"] = [int(p) for p in ev[5]]
            admit(ev[5], led, now)

    replay_heat(records, pool, on_event=on_event)
    return {
        "spills": stats["spills"],
        "spilled_bytes": stats["spills"] * page_bytes,
        "restore_stalls": stats["restore_stalls"],
        "restored_pages": restored_pages,
        "restored_bytes": restored_pages * page_bytes,
    }


# ---------------------------------------------------------------------------
# aggregate report (CLI + bench)
# ---------------------------------------------------------------------------


def lifetime_quantile(lifetimes: Sequence[float], q: float) -> Optional[float]:
    """Prometheus-style quantile over lifetimes bucketed into
    :data:`LIFETIME_BUCKETS` — the estimator the registry histogram runs,
    so trace-derived numbers reproduce the exported metric."""
    if not lifetimes:
        return None
    bs = list(LIFETIME_BUCKETS) + [float("inf")]
    counts = [0] * len(bs)
    for v in lifetimes:
        for i, b in enumerate(bs):
            if v <= b:
                counts[i] += 1
    return quantile_from_buckets(bs, counts, len(lifetimes), q)


def heat_report(records: Sequence[Dict[str, Any]]) -> Dict[str, Any]:
    """Aggregate one trace into the per-pool heat summary: event counts,
    occupancy + cold fractions + fragmentation at end-of-trace, page
    lifetime quantiles (completed leases), session stats."""
    if not records:
        raise KVHeatError("empty trace: no kv_heat records")
    out: Dict[str, Any] = {"schema": SCHEMA, "pools": {}}
    for pool in pools_in(records):
        meta = next(
            (r for r in records
             if r.get("kind") == "kv_heat_meta" and r.get("pool") == pool),
            None,
        )
        if meta is None:
            continue
        lifetimes: List[float] = []
        leases = {}

        def on_event(ev, led, _lt=lifetimes, _ls=leases):
            op = ev[0]
            if op == "A":
                for p in ev[2]:
                    _ls[int(p)] = float(ev[1])
            elif op == "F":
                for p in ev[2]:
                    p = int(p)
                    if p not in led.refs and p in _ls:
                        _lt.append(float(ev[1]) - _ls.pop(p))

        led = replay_heat(records, pool, on_event=on_event)
        times = [float(ev[1]) for ev in iter_pool_events(records, pool)]
        t_end = max(times) if times else 0.0
        occ = led.occupancy(t_end, tuple(meta.get("idle_thresholds_s")
                                         or IDLE_THRESHOLDS_S))
        ages = sorted(led.session_idle_ages(t_end))
        out["pools"][pool] = {
            "capacity": led.capacity,
            "page_bytes": led.page_bytes,
            "span_s": (t_end - min(times)) if times else 0.0,
            "allocs": led.allocs,
            "frees": led.frees,
            "retains": led.retains,
            "prefix_registered": led.prefix_registered,
            "prefix_hits": led.prefix_hits,
            "prefix_evictions": led.prefix_evictions,
            "touch_steps": led.touch_steps,
            "sessions_started": led.sessions_started,
            "sessions_ended": led.sessions_ended,
            "demotions": led.demotions,
            "restores_up": led.restores_up,
            "host_drops": led.host_drops,
            "host_handles": len(led.host_handles),
            "occupancy": occ,
            "page_lifetime_s": {
                "count": len(lifetimes),
                "mean": (sum(lifetimes) / len(lifetimes)) if lifetimes else None,
                "p50": lifetime_quantile(lifetimes, 0.5),
                "p99": lifetime_quantile(lifetimes, 0.99),
            },
            "session_idle_age_p50_s": (
                ages[len(ages) // 2] if ages else None
            ),
        }
    if not out["pools"]:
        raise KVHeatError("trace holds no kv_heat_meta record for any pool")
    return out
