"""Request-lifecycle tracing (ISSUE 11 tentpole): one span-structured JSONL
record per serving request.

The serving engine (serving/scheduler.py) can see a *step*; until this plane
it could not see a *request* — three timestamps on the Request and
engine-wide histogram quantiles, no queue-wait attribution, no tenant
dimension, no causality between "this slot stalled" and "that request's
TTFT blew its SLO". The :class:`RequestTracer` records the full timeline:

- ``submit`` — arrival, with tenant / SLO class / prompt length,
- admission waits, attributed by cause (``page_budget`` — the KV pool gated
  the head of line; ``backoff`` — a retried request inside its backoff
  window; ``no_free_slot`` — all slots busy, i.e. queue depth),
- ``admit`` — queue wait ends; prefix-cache outcome (hit kind, shared
  tokens, copy-on-write fork) and pages allocated,
- ``prefill`` / ``prefill_chunk`` — whole-prompt or per-chunk prefill,
- ``first_token`` — TTFT (chunked prefill: the FIRST SAMPLED token, which
  the last chunk emits — not the last chunk's dispatch),
- ``decode`` / ``verify`` — one entry per slot per batched step, keyed by
  ``(step, slot)`` so entries correlate across requests sharing a batched
  step and with engine step records. Plain decode advances (1 token each)
  are a columnar ``[t, step, slot]`` series on the record — the
  highest-frequency span gets the leanest shape; verify events are full
  spans carrying emitted (up to k+1 at one instant) and drafted/accepted
  counts,
- ``retry`` — a transient failure evicted the slot and re-queued the
  request (deadline timeouts and drain preemptions emit no event; they
  land as the terminal record's ``status``),
- ``kv_handoff`` — disaggregated serving (ISSUE 14): the prompt KV copied
  from the prefill placement's pool into the decode placement's, with
  pages/bytes moved and the copy latency (timed to completion;
  prefill-terminal requests skip the copy and the event),
- one terminal record per request: the event list plus derived summaries
  (queue wait, TTFT, per-emission timestamps → streaming-client inter-token
  gaps) and the SLO verdict against the request's class targets.

Records are schema-versioned (:data:`SCHEMA`) and emitted through the
existing :class:`~deepspeed_tpu.telemetry.tracer.StepTracer` machinery, so
they inherit buffered appends, the size-capped atomic rotation
(``<file>.1``) and the dsan-instrumented locking (ISSUE 8). All recording
is host-side list appends — no device syncs, no jnp dispatch — cheap enough
to run always-on (the bench pins overhead ≤ 2% on the offered-load sweep;
dslint Engine B stays clean over the instrumented hot functions).

Scoring (:func:`score_requests`) turns a set of records into per-tenant /
per-SLO-class **goodput** (tokens from SLO-met requests per second of wall
clock) and **SLO attainment** (fraction of completed requests meeting both
TTFT and TPOT targets) — the measurement plane ROADMAP item 5's elastic
fleet schedules against. The CLI (``tools/request_trace.py``) renders
waterfalls, aggregate reports and diffs from the same records.
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Sequence

from .registry import quantile_from_buckets
from .tracer import StepTracer

SCHEMA = "dstpu-reqtrace-v1"

# TTFT/TPOT/queue-wait histogram bucket bounds (seconds). The serving
# engine's latency histograms use EXACTLY these buckets
# (serving/scheduler.py imports them), so quantiles recomputed from a trace
# via histogram_quantile() reproduce ServingEngine.stats() — the acceptance
# cross-check the CLI and tests pin.
LATENCY_BUCKETS = (
    0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5,
    1.0, 2.5, 5.0, 10.0, 30.0,
)

# admission-wait causes the scheduler attributes (span catalog, docs/REQUEST_TRACING.md);
# kv_restore (ISSUE 17): steps spent restoring demoted prefix pages from
# the host tier before the request could be costed for admission
WAIT_CAUSES = ("no_free_slot", "page_budget", "backoff", "kv_restore")


class RequestTraceError(Exception):
    """A request-trace file that cannot be used: wrong schema or corrupt.
    The CLI exits 2 with the message instead of a traceback."""


class RequestTracer:
    """Per-request timeline recorder over the StepTracer JSONL machinery.

    Host-side buffering: live requests accumulate plain-python event dicts
    in ``_live``; a terminal request folds them into ONE record and hands it
    to the underlying :class:`StepTracer` (buffered append + size-capped
    atomic rotation). The lock is built through the dsan shim — sanitizer-
    enabled runs must observe the real schedule (ISSUE 8).

    JSON encoding happens on a background daemon thread (the ISSUE 7
    AsyncCheckpointWriter pattern): a terminal record is ~2 timestamps per
    token and float dtoa dominates its encode cost (~50 µs/record — real
    money against a sub-ms serving step), so ``finish()`` only appends the
    raw record and the serializer thread encodes it while jax holds the
    device (the GIL is released during compute). ``flush()`` drains the
    thread; ``close()`` joins it.
    """

    def __init__(
        self,
        path: str,
        flush_interval: int = 20,
        max_bytes: int = 64 * 2**20,
        max_events_per_request: int = 4096,
        process_index: Optional[int] = None,
    ):
        if not path.endswith(".jsonl"):
            path = os.path.join(path, "requests.jsonl")
        self._writer = StepTracer(
            path,
            flush_interval=flush_interval,
            sample_every=1,
            process_index=process_index,
            max_bytes=max_bytes,
        )
        self.max_events_per_request = max(1, int(max_events_per_request))
        # main-thread-only state (the ServingEngine scheduler is single-
        # threaded by contract and is the sole event source): _live and the
        # ledger counters are written by the recording hooks and read by
        # stats() on the same thread — the hot per-step hooks are therefore
        # LOCK-FREE. The serializer thread touches none of this.
        # req id -> {"events": [...], "waits": {cause: steps}, "dropped": n}
        self._live: Dict[int, Dict[str, Any]] = {}
        self.status_counts: Dict[str, int] = {}
        self.records_emitted = 0
        self.events_dropped = 0
        # cross-thread state (dsan-shimmed lock): raw terminal records
        # awaiting background encode; _inflight counts a batch the
        # serializer popped but has not yet handed to the writer (flush()
        # must wait for those too).
        self._lock = StepTracer._new_lock()
        self._pending: List[Dict[str, Any]] = []
        self._inflight = 0
        self._closed = False
        self._draining = False
        # records dropped because encoding/writing failed (disk full, dir
        # removed) or because _pending hit its memory backstop
        self.records_lost = 0
        self._encode_error: Optional[str] = None
        # records per encode burst: the thread sleeps until this many are
        # pending (or a flush/close), then drains — not per-record wakes
        self._encode_batch = max(1, int(flush_interval))
        self._wake = threading.Event()
        self._thread = threading.Thread(
            target=self._serialize_loop, name="request-trace-serializer",
            daemon=True,
        )
        self._thread.start()

    # -- recording (scheduler-facing) ----------------------------------
    def submit(self, req, t: float) -> None:
        ev = {
            "e": "submit", "t": t,
            "prompt_len": req.prompt_len,
            "max_new_tokens": req.max_new_tokens,
        }
        # "room" counts event slots left under max_events_per_request: a
        # countdown int keeps the per-step cap check at one compare
        # instead of two len() calls (hot-path, every slot every step)
        self._live[req.id] = {
            "events": [ev], "decode": [], "waits": {}, "dropped": 0,
            "room": self.max_events_per_request - 1,
        }

    def note_wait(self, req, cause: str) -> None:
        """One scheduler step during which ``req`` stayed queued for
        ``cause`` (page_budget | backoff | no_free_slot). Aggregated as
        counts, not events — a long wait is one dict entry, not a record
        per step."""
        buf = self._live.get(req.id)
        if buf is not None:
            buf["waits"][cause] = buf["waits"].get(cause, 0) + 1

    def event(self, req, kind: str, t: float, **fields) -> None:
        # reuse the kwargs dict as the event record — one dict per event,
        # not two (this is a per-step hot path under a sub-ms step budget)
        fields["e"] = kind
        fields["t"] = t
        buf = self._live.get(req.id)
        if buf is None:
            return
        if buf["room"] <= 0:
            buf["dropped"] += 1
            self.events_dropped += 1
            return
        buf["room"] -= 1
        buf["events"].append(fields)

    def step_events(self, pairs: Sequence) -> None:
        """Batched ingestion of one scheduler step's verify events:
        ``pairs`` is ``[(request_id, event_dict), ...]`` with each event
        dict already in final ``{"e", "t", ...}`` shape — the scheduler
        builds dict literals straight into the batch, so the per-step
        tracer cost is a handful of appends."""
        live = self._live
        for rid, ev in pairs:
            buf = live.get(rid)
            if buf is None:
                continue
            if buf["room"] <= 0:
                buf["dropped"] += 1
                self.events_dropped += 1
                continue
            buf["room"] -= 1
            buf["events"].append(ev)

    def decode_events(self, pairs: Sequence) -> None:
        """Batched ingestion of one scheduler step's plain decode
        advances: ``pairs`` is ``[(request_id, (t, step, slot)), ...]``.
        Stored as the record's columnar ``decode`` series (one compact
        JSON triple per step, ``emitted`` is always 1) instead of an
        ``events[]`` dict per step — this is the hottest tracer path in
        the engine AND the bulk of a terminal record's encode cost, so it
        gets the leanest possible shape on both sides."""
        live = self._live
        for rid, tup in pairs:
            buf = live.get(rid)
            if buf is None:
                continue
            if buf["room"] <= 0:
                buf["dropped"] += 1
                self.events_dropped += 1
                continue
            buf["room"] -= 1
            buf["decode"].append(tup)

    def finish(self, req, t: float, slo: Optional[Dict[str, Any]] = None) -> None:
        """Terminal transition: fold the live buffer into one schema-v1
        record and emit it. ``slo`` is the scheduler's verdict block
        (targets + met flag), embedded so scoring needs no config."""
        buf = self._live.pop(
            req.id, {"events": [], "decode": [], "waits": {}, "dropped": 0}
        )
        self.status_counts[req.status] = self.status_counts.get(req.status, 0) + 1
        self.records_emitted += 1
        rec: Dict[str, Any] = {
            "kind": "request",
            "schema": SCHEMA,
            "id": req.id,
            "tenant": req.tenant,
            "slo_class": req.slo_class,
            # fleet replica that finished the request (ISSUE 18; "" = no
            # fleet) — the router stamps it at routing time and restamps
            # on migration, so --by replica aggregates post-migration
            "replica": getattr(req, "replica", ""),
            "status": req.status,
            "detail": req.detail,
            "prompt_len": req.prompt_len,
            "max_new_tokens": req.max_new_tokens,
            "n_tokens": len(req.tokens),
            "retries": req.retries,
            "t_submit": req.t_submit,
            "t_admit": req.t_admit,
            "t_requeue": req.t_requeue,
            "t_first_token": req.t_first_token,
            "t_finish": t,
            "queue_wait_s": req.queue_wait_s,
            "ttft_s": req.ttft_s,
            "tpot_mean_s": req.tpot_s,
            "emissions": list(req.t_emissions),
            "prefix": {
                "shared_tokens": req.prefix_shared_tokens,
                "cow": bool(req.cow_forked),
            },
            "waits": buf["waits"],
            "events_dropped": buf["dropped"],
            "events": buf["events"],
            # plain decode advances, columnar: [[t, step, slot], ...] — one
            # entry per decode step, one token emitted at each
            "decode": buf["decode"],
        }
        if slo is not None:
            rec["slo"] = slo
        rec["ts"] = time.time()
        rec["host"] = self._writer.process_index
        # hand the RAW record to the serializer thread: the scheduler pays
        # one list append, not the float-heavy json encode. The thread is
        # only woken once a full encode batch piles up — low duty cycle, so
        # serving steps don't share cores with dtoa (flush() drains the
        # remainder). The backstop cap bounds memory if encoding can't
        # keep up (or the thread died): drop-oldest, counted.
        with self._lock:
            self._pending.append(rec)
            if len(self._pending) > 16 * self._encode_batch:
                del self._pending[0]
                self.records_lost += 1
            wake = len(self._pending) >= self._encode_batch
        if wake:
            self._wake.set()

    def _serialize_loop(self) -> None:
        """Background encoder: drain ``_pending`` batches, json-encode each
        record OUTSIDE the lock (the scheduler must never wait on a dumps)
        and hand the lines to the StepTracer. Every field is JSON-native by
        construction (the scheduler gives the tracer host scalars, never
        device arrays), so the StepTracer's defensive sanitize pass is
        skipped; ``default=str`` is the safety net."""
        # pending count at the previous idle-timeout check: a timeout only
        # drains when this is unchanged (the server went quiet). Waking on
        # a bare timeout would encode mid-burst and steal scheduler cores
        # whenever a serving span outlives the timeout window
        stale_pending = -1
        while True:
            # the timeout is only the durability backstop for a sub-batch
            # tail on an idle server (worst case two windows); every other
            # drain is event-driven (batch threshold, flush, close)
            timed_out = not self._wake.wait(timeout=2.0)
            self._wake.clear()
            while True:
                with self._lock:
                    # take only FULL batches while the server is live —
                    # nibbling records as they arrive would keep this
                    # thread hot for the whole run, contending for cores
                    # with the step; a flush/close/idle-drain takes the
                    # sub-batch tail
                    n = len(self._pending)
                    take = n > 0 and (
                        n >= self._encode_batch
                        or self._draining or self._closed
                        or (timed_out and n == stale_pending)
                    )
                    if take:
                        batch = self._pending
                        self._pending = []
                        self._inflight += len(batch)
                    elif self._closed:
                        return
                    else:
                        break
                handed = 0
                try:
                    for rec in batch:
                        self._writer.emit_serialized(
                            json.dumps(rec, default=str)
                        )
                        handed += 1
                except Exception as e:  # noqa: BLE001 — daemon must survive
                    # a full disk / vanished trace dir must not silently
                    # kill the serializer (finish() would then grow
                    # _pending forever while flush() reports success);
                    # count the unhanded tail lost (records already in the
                    # writer buffer may still reach disk) and keep serving
                    with self._lock:
                        self.records_lost += len(batch) - handed
                        self._encode_error = f"{type(e).__name__}: {e}"
                finally:
                    with self._lock:
                        self._inflight -= len(batch)
            if timed_out:
                with self._lock:
                    stale_pending = len(self._pending)
            else:
                # an event-driven wake means the server is live again;
                # require a fresh full quiet window before an idle drain
                stale_pending = -1

    # -- plumbing ------------------------------------------------------
    def flush(self) -> None:
        """Blocks until every record handed to :meth:`finish` is encoded
        and buffered in the writer, then flushes the writer to disk."""
        with self._lock:
            self._draining = True
        try:
            while self._thread.is_alive():
                with self._lock:
                    if not self._pending and self._inflight == 0:
                        break
                self._wake.set()
                time.sleep(0.0005)
        finally:
            with self._lock:
                self._draining = False
        self._writer.flush()

    def close(self) -> None:
        with self._lock:
            self._closed = True
        self._wake.set()
        self._thread.join(timeout=5.0)
        self._writer.close()

    @property
    def file_path(self) -> str:
        return self._writer.file_path

    @property
    def rotations(self) -> int:
        return self._writer.rotations

    @property
    def live_requests(self) -> int:
        with self._lock:
            return len(self._live)

    @property
    def encode_error(self) -> Optional[str]:
        """Last serializer failure ("Type: message"), None when healthy —
        the why behind a nonzero ``records_lost``."""
        with self._lock:
            return self._encode_error


# ---------------------------------------------------------------------------
# loading
# ---------------------------------------------------------------------------

def load_request_records(path: str) -> List[Dict[str, Any]]:
    """The ``kind == "request"`` records of one JSONL trace, in file order.

    Same tolerance contract as ``tools/trace_diff.py``: one torn TAIL line
    (killed run, mid-rotation) is fine; torn lines elsewhere, binary
    garbage, or records claiming an unknown schema raise
    :class:`RequestTraceError`. A rolled generation (``<file>.1``) is read
    first when present, so a rotated run scores over its full history.

    One path = one logical stream: the writer APPENDS (StepTracer
    contract), so pointing a fresh run at a used path concatenates runs —
    in the main file and the rolled generation alike. Give each run a
    fresh path (or clear the directory, as ``bench.py`` does) when runs
    must score separately."""
    paths = [p for p in (path + ".1", path) if os.path.exists(p)]
    if not paths:
        raise RequestTraceError(f"{path}: no such trace file")
    out: List[Dict[str, Any]] = []
    for p in paths:
        torn: List[int] = []
        try:
            with open(p, encoding="utf-8") as fh:
                lines = fh.readlines()
        except UnicodeDecodeError as e:
            raise RequestTraceError(
                f"{p}: not a text JSONL trace ({e.reason} at byte {e.start})"
            ) from e
        for lineno, line in enumerate(lines, start=1):
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except json.JSONDecodeError:
                torn.append(lineno)
                continue
            if not isinstance(rec, dict):
                raise RequestTraceError(
                    f"{p}:{lineno}: JSON line is {type(rec).__name__}, not "
                    "an object — this is not a request trace"
                )
            if rec.get("kind") != "request":
                continue  # step/event records share the telemetry dir
            schema = rec.get("schema")
            if schema != SCHEMA:
                raise RequestTraceError(
                    f"{p}:{lineno}: schema {schema!r} != {SCHEMA!r} — trace "
                    "written by an incompatible version"
                )
            out.append(rec)
        if torn and torn != [len(lines)]:
            raise RequestTraceError(
                f"{p}: {len(torn)} undecodable line(s) (first at line "
                f"{torn[0]}) — truncated or corrupt beyond a torn tail"
            )
    return out


# ---------------------------------------------------------------------------
# derived latencies + quantiles
# ---------------------------------------------------------------------------

def inter_token_gaps(emissions: Sequence[float]) -> List[float]:
    """Streaming-client inter-token deltas from per-emission timestamps.
    Tokens emitted by one speculative verify step share a timestamp, so
    their gaps are 0 — the client really does receive them at once."""
    return [emissions[i] - emissions[i - 1] for i in range(1, len(emissions))]


def queue_waits(rec: Dict[str, Any]) -> List[float]:
    """EVERY admission's queue wait for one record. A retried request is
    admitted more than once and ``serving_queue_wait_seconds`` observed
    each admission; the summary ``queue_wait_s`` field keeps only the
    final one, but the ``admit`` events carry them all — scoring from
    these keeps trace-derived quantiles equal to ``stats()`` under
    retries."""
    waits = [
        e["queue_wait_s"] for e in rec.get("events") or []
        if e.get("e") == "admit" and e.get("queue_wait_s") is not None
    ]
    if waits:
        return waits
    qw = rec.get("queue_wait_s")
    return [qw] if qw is not None else []


def ttfts(rec: Dict[str, Any]) -> List[float]:
    """EVERY attempt's TTFT for one record — the retry twin of
    :func:`queue_waits`: an attempt that emitted a first token before a
    transient failure observed ``serving_ttft_seconds`` and cannot
    un-observe, and its ``first_token`` event carries that ``ttft_s``; the
    summary field keeps only the final attempt's."""
    vals = [
        e["ttft_s"] for e in rec.get("events") or []
        if e.get("e") == "first_token" and e.get("ttft_s") is not None
    ]
    if vals:
        return vals
    tt = rec.get("ttft_s")
    return [tt] if tt is not None else []


def histogram_quantile(
    values: Sequence[float], q: float,
    buckets: Sequence[float] = LATENCY_BUCKETS,
) -> Optional[float]:
    """The Prometheus ``histogram_quantile`` estimator over ``values``
    bucketed into ``buckets`` — literally
    :func:`telemetry.registry.quantile_from_buckets`, the same code
    :meth:`~telemetry.registry.Histogram.quantile` runs, so trace-derived
    quantiles reproduce the engine's own ``stats()``."""
    if not values:
        return None
    bs = list(buckets)
    if not bs or bs[-1] != float("inf"):
        bs = bs + [float("inf")]
    counts = [0] * len(bs)
    for v in values:
        for i, b in enumerate(bs):
            if v <= b:
                counts[i] += 1
    return quantile_from_buckets(bs, counts, len(values), q)


def request_phases(rec: Dict[str, Any]) -> Dict[str, Optional[float]]:
    """One record's queue / prefill / decode phase durations (seconds).
    ``prefill`` = admission → first sampled token (chunked prefill included:
    every chunk is prefill work); ``decode`` = first token → finish. A
    retried request's queue phase measures from its re-queue (the failed
    attempt's service time is not admission pressure — the phases then sum
    short of ``total_s`` by exactly that attempt's span)."""
    ts, ta = rec.get("t_submit"), rec.get("t_admit")
    tf, te = rec.get("t_first_token"), rec.get("t_finish")
    tq = rec.get("t_requeue")
    q0 = tq if tq is not None else ts
    return {
        "queue_s": (ta - q0) if ta is not None and q0 is not None else None,
        "prefill_s": (tf - ta) if tf is not None and ta is not None else None,
        "decode_s": (te - tf) if te is not None and tf is not None else None,
        "total_s": (te - ts) if te is not None and ts is not None else None,
    }


def slo_met(rec: Dict[str, Any]) -> Optional[bool]:
    """The record's embedded SLO verdict; None when the run had no SLO
    config (nothing to attain) or the request never completed cleanly."""
    slo = rec.get("slo")
    if not slo:
        return None
    return slo.get("met")


# ---------------------------------------------------------------------------
# scoring: goodput + SLO attainment
# ---------------------------------------------------------------------------

def score_requests(
    records: Sequence[Dict[str, Any]],
    key: Callable[[Dict[str, Any]], str] = lambda r: r.get("slo_class") or "",
) -> Dict[str, Any]:
    """Aggregate a set of request records into goodput / SLO-attainment /
    latency summaries, grouped by ``key`` (default: SLO class; pass
    ``lambda r: r["tenant"]`` for the tenant view).

    Definitions (docs/REQUEST_TRACING.md):

    - **attainment** — SLO-met requests / SLO-evaluated requests. A
      request is evaluated when it reached ANY terminal status and its
      class declared targets; only FINISHED requests can meet, so
      rejections/timeouts/failures count as misses (capacity pressure IS
      an SLO breach — matching ``ServingEngine._slo_verdict``).
    - **goodput** — tokens of SLO-met requests / wall-clock span of the
      whole record set (first submit → last finish). Tokens from late or
      failed requests are throughput, not goodput.
    - latency quantiles use :func:`histogram_quantile`, matching
      ``ServingEngine.stats()``.
    """
    if not records:
        return {"wall_s": 0.0, "groups": {}, "overall": None}
    t0 = min(r["t_submit"] for r in records if r.get("t_submit") is not None)
    t1 = max(r["t_finish"] for r in records if r.get("t_finish") is not None)
    wall = max(t1 - t0, 1e-12)
    groups: Dict[str, Dict[str, Any]] = {}
    for rec in records:
        g = groups.setdefault(str(key(rec)), {
            "requests": 0, "by_status": {}, "tokens": 0,
            "evaluated": 0, "met": 0, "good_tokens": 0,
            "_ttft": [], "_tpot_gaps": [], "_qwait": [],
        })
        g["requests"] += 1
        g["by_status"][rec["status"]] = g["by_status"].get(rec["status"], 0) + 1
        g["tokens"] += int(rec.get("n_tokens") or 0)
        g["_ttft"].extend(ttfts(rec))
        g["_qwait"].extend(queue_waits(rec))
        # FAILED records keep their partial attempt's emissions in the
        # trace, but the engine only observes inter-token gaps on the
        # _finish_slot path (finished/truncated/deadline-preempted) —
        # skip them here so trace-derived TPOT reproduces stats()
        if rec["status"] != "failed":
            g["_tpot_gaps"].extend(
                inter_token_gaps(rec.get("emissions") or [])
            )
        met = slo_met(rec)
        if met is not None:
            g["evaluated"] += 1
            if met:
                g["met"] += 1
                g["good_tokens"] += int(rec.get("n_tokens") or 0)
    out_groups = {}
    tot_eval = tot_met = tot_good = tot_tokens = 0
    all_ttft: List[float] = []
    all_gaps: List[float] = []
    all_qwait: List[float] = []
    for name, g in sorted(groups.items()):
        entry = {
            "requests": g["requests"],
            "by_status": g["by_status"],
            "tokens": g["tokens"],
            "slo_evaluated": g["evaluated"],
            "slo_met": g["met"],
            "slo_attainment": (g["met"] / g["evaluated"]) if g["evaluated"] else None,
            "goodput_tokens_per_sec": g["good_tokens"] / wall,
            "throughput_tokens_per_sec": g["tokens"] / wall,
        }
        for metric, vals in (
            ("ttft", g["_ttft"]), ("tpot", g["_tpot_gaps"]), ("queue_wait", g["_qwait"]),
        ):
            entry[f"{metric}_p50_s"] = histogram_quantile(vals, 0.5)
            entry[f"{metric}_p99_s"] = histogram_quantile(vals, 0.99)
        out_groups[name] = entry
        tot_eval += g["evaluated"]
        tot_met += g["met"]
        tot_good += g["good_tokens"]
        tot_tokens += g["tokens"]
        all_ttft.extend(g["_ttft"])
        all_gaps.extend(g["_tpot_gaps"])
        all_qwait.extend(g["_qwait"])
    overall = {
        "requests": len(records),
        "tokens": tot_tokens,
        "slo_evaluated": tot_eval,
        "slo_met": tot_met,
        "slo_attainment": (tot_met / tot_eval) if tot_eval else None,
        "goodput_tokens_per_sec": tot_good / wall,
        "throughput_tokens_per_sec": tot_tokens / wall,
    }
    # run-level latency quantiles ride along so callers (CLI report/diff,
    # bench) score the record set ONCE instead of re-walking every record
    for metric, vals in (
        ("ttft", all_ttft), ("tpot", all_gaps), ("queue_wait", all_qwait),
    ):
        overall[f"{metric}_p50_s"] = histogram_quantile(vals, 0.5)
        overall[f"{metric}_p99_s"] = histogram_quantile(vals, 0.99)
    return {
        "wall_s": wall,
        "groups": out_groups,
        "overall": overall,
    }


def time_binned(
    records: Sequence[Dict[str, Any]], bins: int = 10
) -> List[Dict[str, Any]]:
    """Bin records by submit time into ``bins`` equal windows; per bin the
    mean queue/prefill/decode split and the arrival count — the bursty
    replay workload's load/latency shape at a glance."""
    recs = [r for r in records if r.get("t_submit") is not None]
    if not recs:
        return []
    t0 = min(r["t_submit"] for r in recs)
    t1 = max(r["t_submit"] for r in recs)
    width = max((t1 - t0) / max(1, bins), 1e-12)
    out = []
    for b in range(bins):
        lo, hi = t0 + b * width, t0 + (b + 1) * width
        # the last bin is closed above by ">= lo" alone: recomputing its
        # upper edge as t0 + bins*width can land a float ulp BELOW the true
        # max submit time, which would silently drop the latest arrival
        last = b == bins - 1
        sel = [
            r for r in recs
            if (r["t_submit"] >= lo if last else lo <= r["t_submit"] < hi)
        ]
        phases = [request_phases(r) for r in sel]
        def _mean(k):
            vals = [p[k] for p in phases if p[k] is not None]
            return (sum(vals) / len(vals)) if vals else None
        out.append({
            "t_start": lo,
            "t_end": hi,
            "arrivals": len(sel),
            "queue_mean_s": _mean("queue_s"),
            "prefill_mean_s": _mean("prefill_s"),
            "decode_mean_s": _mean("decode_s"),
        })
    return out
