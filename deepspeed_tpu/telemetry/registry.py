"""Process-local metrics registry: counters / gauges / histograms with labels.

The single metrics plane every subsystem reports through (ISSUE 1 tentpole).
The reference DeepSpeed scatters its numbers across ``SynchronizedWallClockTimer``
log lines, the flops profiler's stdout table, ``comms_logging`` summaries and
the Monitor fan-out; here they all land in ONE registry that renders to
Prometheus text format (``to_prometheus`` / ``write_textfile`` for the
node-exporter textfile collector) and fans out to the Monitor backends via
:class:`~deepspeed_tpu.telemetry.exporters.MonitorBridge`.

Thread-safety: a single coarse lock guards every mutation — jax.monitoring
listeners (compile_stats) and async checkpoint threads report from off the
main thread.
"""

from __future__ import annotations

import math
import os
import threading
from typing import Dict, Iterator, List, Optional, Sequence, Tuple

# default histogram buckets (seconds): spans sub-ms host ops to multi-minute
# compiles
DEFAULT_BUCKETS = (
    0.001, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0,
    30.0, 60.0, 120.0, 300.0,
)

_INF = float("inf")


def quantile_from_buckets(buckets, counts, n, q):
    """The Prometheus ``histogram_quantile`` estimator over CUMULATIVE
    bucket ``counts`` (``counts[i]`` = observations <= ``buckets[i]``):
    linear interpolation inside the landing bucket; the +Inf bucket
    clamps to its lower edge. The ONE shared implementation —
    ``Histogram.quantile`` and ``telemetry.request_trace`` both call it,
    which is what keeps trace-derived quantiles equal to ``stats()``."""
    if n == 0:
        return None
    rank = q * n
    prev_bound, prev_cum = 0.0, 0
    for bound, cum in zip(buckets, counts):
        if cum >= rank:
            if bound == _INF:
                return prev_bound
            if cum == prev_cum:
                return bound
            frac = (rank - prev_cum) / (cum - prev_cum)
            return prev_bound + (bound - prev_bound) * frac
        prev_bound, prev_cum = (0.0 if bound == _INF else bound), cum
    return prev_bound


def _escape_label(v: str) -> str:
    return str(v).replace("\\", r"\\").replace('"', r"\"").replace("\n", r"\n")


def _label_str(labelnames: Sequence[str], labelvalues: Sequence[str]) -> str:
    if not labelnames:
        return ""
    inner = ",".join(
        f'{k}="{_escape_label(v)}"' for k, v in zip(labelnames, labelvalues)
    )
    return "{" + inner + "}"


class _Metric:
    """One metric family: a name plus per-label-value children."""

    kind = "untyped"

    def __init__(self, name: str, help: str, labelnames: Sequence[str], lock: threading.Lock):
        self.name = name
        self.help = help
        self.labelnames = tuple(labelnames)
        self._lock = lock
        self._children: Dict[Tuple[str, ...], float] = {}

    def _key(self, labels: Dict[str, str]) -> Tuple[str, ...]:
        if set(labels) != set(self.labelnames):
            raise ValueError(
                f"{self.name}: labels {sorted(labels)} != declared {sorted(self.labelnames)}"
            )
        return tuple(str(labels[k]) for k in self.labelnames)

    def samples(self) -> Iterator[Tuple[str, str, float]]:
        """(name, label_str, value) triples for the text exposition.
        Snapshots under the lock: off-thread inc() during an export must not
        mutate the dict mid-iteration."""
        with self._lock:
            items = sorted(self._children.items())
        for key, value in items:
            yield self.name, _label_str(self.labelnames, key), value

    def value(self, **labels) -> float:
        return self._children.get(self._key(labels), 0.0)


class Counter(_Metric):
    kind = "counter"

    def inc(self, amount: float = 1.0, **labels) -> None:
        if amount < 0:
            raise ValueError(f"{self.name}: counters only go up (got {amount})")
        key = self._key(labels)
        with self._lock:
            self._children[key] = self._children.get(key, 0.0) + amount


class Gauge(_Metric):
    kind = "gauge"

    def set(self, value: float, **labels) -> None:
        key = self._key(labels)
        with self._lock:
            self._children[key] = float(value)

    def inc(self, amount: float = 1.0, **labels) -> None:
        key = self._key(labels)
        with self._lock:
            self._children[key] = self._children.get(key, 0.0) + amount


class Histogram(_Metric):
    kind = "histogram"

    def __init__(self, name, help, labelnames, lock, buckets: Optional[Sequence[float]] = None):
        super().__init__(name, help, labelnames, lock)
        bs = tuple(sorted(buckets if buckets is not None else DEFAULT_BUCKETS))
        if not bs or bs[-1] != _INF:
            bs = bs + (_INF,)
        self.buckets = bs
        # per-label-key: (bucket counts, sum, count)
        self._hist: Dict[Tuple[str, ...], Tuple[List[int], float, int]] = {}

    def observe(self, value: float, **labels) -> None:
        key = self._key(labels)
        with self._lock:
            counts, total, n = self._hist.get(
                key, ([0] * len(self.buckets), 0.0, 0)
            )
            for i, b in enumerate(self.buckets):
                if value <= b:
                    counts[i] += 1
            self._hist[key] = (counts, total + float(value), n + 1)

    def samples(self):
        with self._lock:  # deep-copy: observe() mutates counts in place
            snapshot = [
                (k, (list(c), t, n)) for k, (c, t, n) in sorted(self._hist.items())
            ]
        for key, (counts, total, n) in snapshot:
            for b, c in zip(self.buckets, counts):
                le = "+Inf" if b == _INF else repr(b)
                yield (
                    self.name + "_bucket",
                    _label_str(self.labelnames + ("le",), key + (le,)),
                    float(c),
                )
            yield self.name + "_sum", _label_str(self.labelnames, key), total
            yield self.name + "_count", _label_str(self.labelnames, key), float(n)

    def stats(self, **labels) -> Tuple[float, int]:
        """(sum, count) for one label set."""
        with self._lock:
            _, total, n = self._hist.get(self._key(labels), ([], 0.0, 0))
        return total, n

    def quantile(self, q: float, **labels) -> Optional[float]:
        """Estimate the q-quantile (0 < q < 1) from the bucket counts by
        linear interpolation inside the landing bucket — the standard
        Prometheus ``histogram_quantile`` estimator, so dashboards and
        these in-process summaries agree. Returns None with no
        observations; the +Inf bucket clamps to its lower edge (the
        estimator cannot extrapolate past the last finite bound)."""
        if not 0.0 < q < 1.0:
            raise ValueError(f"{self.name}: quantile q must be in (0, 1), got {q}")
        with self._lock:
            counts, _, n = self._hist.get(
                self._key(labels), ([0] * len(self.buckets), 0.0, 0)
            )
            counts = list(counts)  # buckets are cumulative (observe() adds
        return quantile_from_buckets(self.buckets, counts, n, q)

    def value(self, **labels) -> float:
        raise TypeError(
            f"{self.name}: histograms have no single value — use stats() "
            "for (sum, count) or samples() for buckets"
        )


class MetricsRegistry:
    """Named metric families; idempotent declaration (same name + kind returns
    the existing family, a kind clash raises)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._metrics: Dict[str, _Metric] = {}

    # -- declaration ---------------------------------------------------
    def _declare(self, cls, name: str, help: str, labelnames: Sequence[str], **kw) -> _Metric:
        with self._lock:
            existing = self._metrics.get(name)
            if existing is not None:
                if not isinstance(existing, cls) or existing.labelnames != tuple(labelnames):
                    raise ValueError(
                        f"metric {name!r} already declared as {existing.kind} "
                        f"with labels {existing.labelnames}"
                    )
                return existing
            m = cls(name, help, labelnames, self._lock, **kw)
            self._metrics[name] = m
            return m

    def counter(self, name: str, help: str = "", labelnames: Sequence[str] = ()) -> Counter:
        return self._declare(Counter, name, help, labelnames)

    def gauge(self, name: str, help: str = "", labelnames: Sequence[str] = ()) -> Gauge:
        return self._declare(Gauge, name, help, labelnames)

    def histogram(
        self, name: str, help: str = "", labelnames: Sequence[str] = (),
        buckets: Optional[Sequence[float]] = None,
    ) -> Histogram:
        return self._declare(Histogram, name, help, labelnames, buckets=buckets)

    def get(self, name: str) -> Optional[_Metric]:
        return self._metrics.get(name)

    # -- export --------------------------------------------------------
    def _families(self) -> List[_Metric]:
        with self._lock:  # _declare can insert concurrently
            return sorted(self._metrics.values(), key=lambda m: m.name)

    def scalar_samples(self) -> List[Tuple[str, float]]:
        """Flat ("name{labels}", value) pairs for counters and gauges —
        what the MonitorBridge fans out to TensorBoard/W&B/CSV (histograms
        export their _sum/_count)."""
        out = []
        for m in self._families():
            if isinstance(m, Histogram):
                with self._lock:
                    hist = sorted((k, (t, n)) for k, (_, t, n) in m._hist.items())
                for key, (total, n) in hist:
                    ls = _label_str(m.labelnames, key)
                    out.append((m.name + "_sum" + ls, total))
                    out.append((m.name + "_count" + ls, float(n)))
            else:
                for name, ls, v in m.samples():
                    out.append((name + ls, v))
        return out

    def to_prometheus(self) -> str:
        """Prometheus text exposition format v0.0.4."""
        lines = []
        for m in self._families():
            if m.help:
                lines.append(f"# HELP {m.name} {m.help}")
            lines.append(f"# TYPE {m.name} {m.kind}")
            for name, ls, v in m.samples():
                # NaN/±Inf are legal exposition values (Go ParseFloat forms,
                # which repr() matches) — a diverged loss must not crash the
                # exporter observing it
                if math.isfinite(v) and v == int(v) and abs(v) < 2**53:
                    lines.append(f"{name}{ls} {int(v)}")
                else:
                    lines.append(f"{name}{ls} {v}")
        return "\n".join(lines) + ("\n" if lines else "")

    def write_textfile(self, path: str) -> str:
        """Atomic snapshot for the node-exporter textfile collector: write to
        a temp file in the target directory, then rename (a scraper never
        sees a torn file)."""
        d = os.path.dirname(os.path.abspath(path))
        os.makedirs(d, exist_ok=True)
        tmp = path + f".tmp.{os.getpid()}"
        with open(tmp, "w") as fh:
            fh.write(self.to_prometheus())
        os.replace(tmp, path)
        return path
