"""Metrics time-series journal (ISSUE 20 tentpole): the fleet's history.

Every signal this repo grew — PR-1's registry gauges, PR-11's SLO
counters, PR-16's heat occupancy, PR-18's per-replica fleet gauges — is
*instantaneous*: the registry holds the current value and nothing else.
This module gives the control plane a time axis: a
:class:`MetricsJournal` snapshots the whole
:class:`~deepspeed_tpu.telemetry.registry.MetricsRegistry` (counters,
gauges, full histogram bucket vectors) on a configurable cadence off the
engine's **injectable clock** into a schema-versioned (``dstpu-tsdb-v1``)
delta-encoded JSONL ring, reusing the StepTracer machinery — buffered
appends, size-capped atomic ``<file>.1`` rotation, dsan-shimmed locking.

Design rules, in the kv-heat discipline:

- **no wall-clock fields**: every timestamp is the engine clock's value,
  so a seeded virtual-clock replay produces a byte-identical journal
  (acceptance-pinned);
- **delta-encoded, absolute values**: a snapshot records only series
  whose value changed since the previous snapshot, but records the
  ABSOLUTE value (never a diff) — a lost or rotated-away record degrades
  resolution, never correctness, and ``rate()`` stays counter-reset
  tolerant by construction;
- **self-contained generations**: after a size-capped rotation the next
  snapshot re-emits the meta records and a full baseline, so each file
  generation can be read alone;
- **one quantile estimator**: ``quantile_over_time()`` feeds windowed
  bucket-count differences through the same
  :func:`~deepspeed_tpu.telemetry.registry.quantile_from_buckets` that
  ``Histogram.quantile`` uses — a full-range journal quantile reproduces
  the live ``stats()`` quantile *exactly* (acceptance-pinned).

Record kinds::

    {"kind": "tsdb_meta", "schema": "dstpu-tsdb-v1", "interval_s": ...}
    {"kind": "tsdb_hist_meta", "name": <family>, "buckets": [finite...]}
    {"kind": "tsdb", "t": <clock>, "seq": N,
     "set": {"<name>{labels}": value, ...},                 # scalars
     "h": {"<name>{labels}": {"c": [...], "s": S, "n": N}}} # histograms
    {"kind": "slo_alert", ...}   # events appended via emit_event()

Consumers: ``ServingEngine`` (step-cadence ``maybe_snapshot`` hook +
journal-backed windowed goodput), ``telemetry/slo_budget.py`` (error
budget / burn-rate alerting over the in-memory mirror),
``tools/fleet_dash.py`` (offline :func:`load_journal` + the query API)
and bench.py's ``run_tsdb_bench`` (≤2% snapshot-hook overhead pin).
"""

from __future__ import annotations

import json
import os
import time
from typing import Any, Dict, List, Optional, Tuple

from .registry import Histogram, MetricsRegistry, _label_str, quantile_from_buckets
from .tracer import StepTracer

SCHEMA = "dstpu-tsdb-v1"

_INF = float("inf")


class TimeseriesError(ValueError):
    """Unreadable / wrong-schema journal (CLI consumers exit 2 on it)."""


def _bisect_le(samples: List[tuple], t: float) -> int:
    """Index of the LAST sample with ``sample[0] <= t``, or -1. Binary
    search over the (time, ...) tuples — windows over hours of samples
    must not pay a linear scan per query."""
    lo, hi = 0, len(samples)
    while lo < hi:
        mid = (lo + hi) // 2
        if samples[mid][0] <= t:
            lo = mid + 1
        else:
            hi = mid
    return lo - 1


class SeriesStore:
    """In-memory mirror of a journal: per-series absolute-value sample
    lists plus the query API. The live :class:`MetricsJournal` maintains
    one (retention-trimmed) for burn-rate / windowed-goodput queries;
    :func:`load_journal` builds one offline from the JSONL files."""

    def __init__(self):
        # sid ("name{labels}") -> [(t, value), ...] ascending by t
        self.scalars: Dict[str, List[Tuple[float, float]]] = {}
        # sid -> [(t, cumulative bucket counts, sum, count), ...]
        self.hists: Dict[str, List[tuple]] = {}
        # histogram family name -> bucket bounds (incl. trailing +Inf)
        self.hist_buckets: Dict[str, tuple] = {}
        self.meta: Dict[str, Any] = {}
        self.events: List[dict] = []  # non-snapshot records (slo_alert, ...)
        self.records = 0              # tsdb snapshot records ingested

    # -- ingest --------------------------------------------------------
    def add_scalar(self, t: float, sid: str, value: float) -> None:
        samples = self.scalars.setdefault(sid, [])
        if samples and samples[-1][0] == t:  # rotation re-baseline at one t
            samples[-1] = (t, float(value))
        else:
            samples.append((t, float(value)))

    def add_hist(self, t: float, sid: str, counts: List[int], total: float,
                 n: int) -> None:
        samples = self.hists.setdefault(sid, [])
        if samples and samples[-1][0] == t:
            samples[-1] = (t, tuple(counts), total, n)
        else:
            samples.append((t, tuple(counts), total, n))

    def trim(self, cutoff: float) -> None:
        """Drop samples before ``cutoff``, always keeping the last one at
        or before it — the baseline ``increase()`` subtracts from."""
        for table in (self.scalars, self.hists):
            for sid, samples in table.items():
                idx = _bisect_le(samples, cutoff)
                if idx > 0:
                    table[sid] = samples[idx:]

    # -- discovery -----------------------------------------------------
    def sids(self, name: str) -> List[str]:
        """Every stored series id of one metric family (exact name, any
        label set)."""
        out = [
            sid for sid in self.scalars
            if sid == name or sid.startswith(name + "{")
        ]
        out += [
            sid for sid in self.hists
            if sid == name or sid.startswith(name + "{")
        ]
        return sorted(out)

    def span(self) -> Tuple[Optional[float], Optional[float]]:
        """(first, last) sample time across every series, or (None, None)."""
        t0: Optional[float] = None
        t1: Optional[float] = None
        for table in (self.scalars, self.hists):
            for samples in table.values():
                if samples:
                    t0 = samples[0][0] if t0 is None else min(t0, samples[0][0])
                    t1 = samples[-1][0] if t1 is None else max(t1, samples[-1][0])
        return t0, t1

    # -- queries -------------------------------------------------------
    def range(self, sid: str, t0: Optional[float] = None,
              t1: Optional[float] = None) -> List[Tuple[float, float]]:
        """Scalar samples with ``t0 <= t <= t1`` (either bound optional)."""
        samples = self.scalars.get(sid, [])
        lo = 0 if t0 is None else _bisect_le(samples, t0 - 1e-12) + 1
        hi = len(samples) if t1 is None else _bisect_le(samples, t1) + 1
        return list(samples[lo:hi])

    def latest(self, sid: str, t: Optional[float] = None) -> Optional[float]:
        """Last scalar value at or before ``t`` (default: newest)."""
        samples = self.scalars.get(sid)
        if not samples:
            return None
        if t is None:
            return samples[-1][1]
        idx = _bisect_le(samples, t)
        return samples[idx][1] if idx >= 0 else None

    def increase(self, sid: str, t0: float, t1: float) -> float:
        """Counter increase over ``(t0, t1]``, tolerant of counter resets:
        sum the positive sample-to-sample deltas; a NEGATIVE delta means
        the counter restarted from zero, so the new absolute value *is*
        the increase since the reset. Baseline is the last sample at or
        before ``t0`` (a counter unseen before ``t0`` baselines at 0 —
        counters start at 0). Unknown series → 0.0."""
        samples = self.scalars.get(sid)
        if not samples:
            return 0.0
        idx0 = _bisect_le(samples, t0)
        prev = samples[idx0][1] if idx0 >= 0 else 0.0
        total = 0.0
        for i in range(idx0 + 1, len(samples)):
            t, v = samples[i]
            if t > t1:
                break
            delta = v - prev
            total += delta if delta >= 0.0 else v
            prev = v
        return total

    def rate(self, sid: str, t0: float, t1: float) -> float:
        """Per-second increase over the window (0.0 on an empty window)."""
        dur = t1 - t0
        if dur <= 0.0:
            return 0.0
        return self.increase(sid, t0, t1) / dur

    def hist_window(self, sid: str, t0: Optional[float],
                    t1: Optional[float]) -> Optional[tuple]:
        """(bucket-count diff, sum diff, count diff) between the histogram
        states at ``t1`` and ``t0``, or None without data."""
        samples = self.hists.get(sid)
        if not samples:
            return None
        idx1 = len(samples) - 1 if t1 is None else _bisect_le(samples, t1)
        if idx1 < 0:
            return None
        _, c1, s1, n1 = samples[idx1]
        c0: Optional[tuple] = None
        s0, n0 = 0.0, 0
        if t0 is not None:
            idx0 = _bisect_le(samples, t0)
            if idx0 >= 0:
                _, c0, s0, n0 = samples[idx0]
        if c0 is None:
            return list(c1), s1, n1
        if len(c0) != len(c1):
            raise TimeseriesError(
                f"{sid}: bucket layout changed mid-journal "
                f"({len(c0)} -> {len(c1)} buckets)"
            )
        return [a - b for a, b in zip(c1, c0)], s1 - s0, n1 - n0

    def quantile_over_time(self, sid: str, q: float,
                           t0: Optional[float] = None,
                           t1: Optional[float] = None) -> Optional[float]:
        """The q-quantile of one histogram series over a window, via the
        SAME estimator ``Histogram.quantile`` uses over the windowed
        cumulative-count difference — a full-range query reproduces the
        live ``stats()`` quantile exactly."""
        win = self.hist_window(sid, t0, t1)
        if win is None:
            return None
        counts, _, n = win
        if n <= 0:
            return None
        family = sid.split("{", 1)[0]
        buckets = self.hist_buckets.get(family)
        if buckets is None or len(buckets) != len(counts):
            return None
        return quantile_from_buckets(buckets, counts, n, q)


class MetricsJournal:
    """Cadenced registry → JSONL snapshot writer plus the live query
    mirror. Single-writer by design: ``maybe_snapshot`` runs on the
    engine's step path (the StepTracer underneath serializes the actual
    file appends). Construct standalone or let
    :class:`~deepspeed_tpu.telemetry.Telemetry` build one from the
    ``telemetry.timeseries`` config section."""

    def __init__(
        self,
        path: str,
        registry: Optional[MetricsRegistry] = None,
        clock=time.monotonic,
        interval_s: float = 1.0,
        flush_interval: int = 20,
        max_bytes: int = 0,
        retention_s: float = 3600.0,
        process_index: Optional[int] = None,
    ):
        self._tracer = StepTracer(
            path, flush_interval=flush_interval, sample_every=1,
            process_index=process_index, max_bytes=max_bytes,
        )
        self.registry = registry
        self.clock = clock
        self.interval_s = float(interval_s)
        self.retention_s = float(retention_s)
        self.store = SeriesStore()
        self.last_t: Optional[float] = None  # time of the last snapshot()
        self.snapshots = 0       # snapshot() calls (incl. no-change ones)
        self.records_emitted = 0  # tsdb records actually written
        self.encode_error: Optional[str] = None
        self._seq = 0
        self._last_scalar: Dict[str, float] = {}
        self._last_hist: Dict[str, tuple] = {}
        self._meta_emitted = False
        self._hist_meta_done: set = set()
        self._rot_seen = 0

    # -- wiring --------------------------------------------------------
    def bind(self, registry: Optional[MetricsRegistry] = None,
             clock=None) -> None:
        """Late-bind the registry and/or rebind the clock (the kv-heat
        ``pool()`` idiom: an engine attaching the journal installs its own
        injectable clock so replayed timestamps stay virtual)."""
        if registry is not None:
            self.registry = registry
        if clock is not None:
            self.clock = clock

    def ensure_retention(self, window_s: float) -> None:
        """Grow the in-memory retention to cover ``window_s`` — the SLO
        budget engine calls this with its widest alert window."""
        self.retention_s = max(self.retention_s, float(window_s))

    # -- snapshotting --------------------------------------------------
    def maybe_snapshot(self, now: Optional[float] = None) -> bool:
        """Snapshot iff ``interval_s`` has elapsed since the last one (the
        engine's per-step hook — one float compare when it is not time)."""
        if now is None:
            now = self.clock()
        if self.last_t is not None and now - self.last_t < self.interval_s:
            return False
        self.snapshot(now)
        return True

    def snapshot(self, now: Optional[float] = None) -> int:
        """Record every changed series at ``now``; returns the changed
        series count. Emits nothing when nothing changed (an idle engine
        journals zero bytes)."""
        if self.registry is None:
            return 0
        if now is None:
            now = self.clock()
        n = self._write_changed(now)
        if self._tracer.rotations != self._rot_seen:
            # this snapshot's own emit rolled the live file (rotation
            # happens inside the tracer's flush, after the size check):
            # re-baseline NOW so the fresh generation carries its meta and
            # full values even if the process stops before the next tick
            n = max(n, self._write_changed(now))
        self.last_t = now
        self.snapshots += 1
        if self.retention_s > 0.0:
            self.store.trim(now - self.retention_s)
        return n

    def _write_changed(self, now: float) -> int:
        tr = self._tracer
        if tr.rotations != self._rot_seen:
            # the live file just rolled to <file>.1: re-baseline so the
            # fresh generation is self-contained (meta + full values)
            self._rot_seen = tr.rotations
            self._meta_emitted = False
            self._hist_meta_done.clear()
            self._last_scalar.clear()
            self._last_hist.clear()
        if not self._meta_emitted:
            tr.emit_serialized(json.dumps(
                {"interval_s": self.interval_s, "kind": "tsdb_meta",
                 "schema": SCHEMA},
                sort_keys=True,
            ))
            self._meta_emitted = True
        set_d: Dict[str, float] = {}
        hist_d: Dict[str, dict] = {}
        for fam in self.registry._families():
            if isinstance(fam, Histogram):
                if fam.name not in self._hist_meta_done:
                    # +Inf is not valid JSON: persist the finite bounds,
                    # load_journal re-appends the +Inf bucket
                    tr.emit_serialized(json.dumps(
                        {"buckets": [b for b in fam.buckets if b != _INF],
                         "kind": "tsdb_hist_meta", "name": fam.name},
                        sort_keys=True,
                    ))
                    self._hist_meta_done.add(fam.name)
                    self.store.hist_buckets[fam.name] = tuple(fam.buckets)
                with fam._lock:  # deep-copy: observe() mutates in place
                    items = [
                        (k, (list(c), t, n))
                        for k, (c, t, n) in sorted(fam._hist.items())
                    ]
                for key, (counts, total, n) in items:
                    sid = fam.name + _label_str(fam.labelnames, key)
                    cur = (tuple(counts), total, n)
                    if self._last_hist.get(sid) != cur:
                        self._last_hist[sid] = cur
                        hist_d[sid] = {"c": counts, "n": n, "s": total}
                        self.store.add_hist(now, sid, counts, total, n)
            else:
                for name, ls, v in fam.samples():
                    sid = name + ls
                    v = float(v)
                    if self._last_scalar.get(sid) != v:
                        self._last_scalar[sid] = v
                        set_d[sid] = v
                        self.store.add_scalar(now, sid, v)
        if set_d or hist_d:
            rec: Dict[str, Any] = {"kind": "tsdb", "seq": self._seq, "t": now}
            if set_d:
                rec["set"] = set_d
            if hist_d:
                rec["h"] = hist_d
            try:
                tr.emit_serialized(json.dumps(rec, sort_keys=True))
                self.records_emitted += 1
                self.store.records += 1
            except (TypeError, ValueError) as e:  # never crash the step path
                self.encode_error = f"{type(e).__name__}: {e}"
            self._seq += 1
        return len(set_d) + len(hist_d)

    def emit_event(self, record: Dict[str, Any]) -> None:
        """Append one non-snapshot event record (``slo_alert``, …) through
        the same buffered/rotating writer, byte-deterministically (sorted
        keys, caller supplies the clock-derived ``t``)."""
        self._tracer.emit_serialized(json.dumps(record, sort_keys=True))
        self.store.events.append(record)

    # -- query passthroughs (live, retention-bounded) -------------------
    def range(self, sid, t0=None, t1=None):
        return self.store.range(sid, t0, t1)

    def latest(self, sid, t=None):
        return self.store.latest(sid, t)

    def increase(self, sid, t0, t1):
        return self.store.increase(sid, t0, t1)

    def rate(self, sid, t0, t1):
        return self.store.rate(sid, t0, t1)

    def quantile_over_time(self, sid, q, t0=None, t1=None):
        return self.store.quantile_over_time(sid, q, t0, t1)

    def sids(self, name):
        return self.store.sids(name)

    # -- lifecycle ------------------------------------------------------
    def flush(self) -> None:
        self._tracer.flush()

    def close(self) -> None:
        # final snapshot: counters that moved since the last interval tick
        # (completion counts, end-of-run gauges) would otherwise never land
        self.snapshot()
        self._tracer.close()

    @property
    def file_path(self) -> str:
        return self._tracer.file_path

    @property
    def rotations(self) -> int:
        return self._tracer.rotations


def load_journal(path: str) -> SeriesStore:
    """Offline reader: ``<path>.1`` (the rolled generation) first, then the
    live file. Tolerates ONE torn line at a file's tail (a crash
    mid-append); any other undecodable line, a missing file, or a schema
    mismatch raises :class:`TimeseriesError` (CLI consumers exit 2)."""
    paths = [p for p in (path + ".1", path) if os.path.exists(p)]
    if not paths:
        raise TimeseriesError(f"no journal at {path}")
    store = SeriesStore()
    saw_meta = False
    for p in paths:
        with open(p) as fh:
            lines = fh.read().splitlines()
        for i, line in enumerate(lines):
            if not line.strip():
                continue
            try:
                rec = json.loads(line)
            except ValueError:
                if i == len(lines) - 1:
                    continue  # torn tail: the crash-truncated final append
                raise TimeseriesError(f"{p}:{i + 1}: undecodable record")
            kind = rec.get("kind")
            if kind == "tsdb_meta":
                if rec.get("schema") != SCHEMA:
                    raise TimeseriesError(
                        f"{p}: schema {rec.get('schema')!r} != {SCHEMA!r}"
                    )
                saw_meta = True
                store.meta = rec
            elif kind == "tsdb_hist_meta":
                store.hist_buckets[rec["name"]] = (
                    tuple(float(b) for b in rec["buckets"]) + (_INF,)
                )
            elif kind == "tsdb":
                t = float(rec["t"])
                store.records += 1
                for sid, v in (rec.get("set") or {}).items():
                    store.add_scalar(t, sid, float(v))
                for sid, hv in (rec.get("h") or {}).items():
                    store.add_hist(
                        t, sid, [int(c) for c in hv["c"]],
                        float(hv["s"]), int(hv["n"]),
                    )
            else:
                store.events.append(rec)
    if not saw_meta:
        raise TimeseriesError(
            f"{path}: no tsdb_meta record (not a {SCHEMA} journal)"
        )
    return store
