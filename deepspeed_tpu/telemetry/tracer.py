"""Structured step traces: one JSONL record per train/inference step.

Each record is a self-contained JSON object (span tree + scalars + HBM +
per-axis comm bytes) appended to a per-host file under ``trace_path``.
Buffered writes (``flush_interval`` records per fsync-able append) keep the
hot loop free of per-step filesystem syscalls; ``sample_every`` thins the
record stream (and the device sync each record implies) for long runs.

Rank-0 aggregation: on multi-host runs every host writes its own file;
:func:`aggregate_scalars` all-gathers a record's scalar fields over
``deepspeed_tpu.comm``'s process set and returns the cross-host mean on
rank 0 (None elsewhere), which the tracer appends to ``trace-aggregate.jsonl``.
"""

from __future__ import annotations

import atexit
import json
import os
import time
from typing import Any, Dict, List, Optional, Tuple

Span = Tuple[str, float]  # (name, duration_ms); flat span list, parents first


def _jsonable(v: Any) -> Any:
    """Scalars only: device arrays / numpy types → python floats/ints."""
    try:
        import numpy as np

        if isinstance(v, (np.generic,)):
            return v.item()
        if hasattr(v, "item") and getattr(v, "ndim", 1) == 0:
            return v.item()
    except Exception:
        pass
    return v


def spans_to_tree(spans: List[Span], total_ms: float) -> Dict[str, Any]:
    """Flat (name, ms) list → {name: ms} child map under a root span, with the
    unattributed remainder reported as ``other`` (the span tree is one level
    deep: the fused XLA step leaves no host-visible fwd/bwd boundary, so the
    host-side phases — prepare/dispatch/sync — are the children)."""
    children = {name: round(ms, 3) for name, ms in spans}
    accounted = sum(ms for _, ms in spans)
    if total_ms > accounted:
        children["other"] = round(total_ms - accounted, 3)
    return {"total_ms": round(total_ms, 3), "children": children}


def aggregate_scalars(scalars: Dict[str, float]) -> Optional[Dict[str, float]]:
    """Cross-host mean of a record's scalar fields (rank-0 aggregation over
    the jax process set). Returns the aggregate on process 0, None on other
    processes, and the input unchanged on single-host runs."""
    import jax

    if jax.process_count() == 1:
        return dict(scalars)
    import numpy as np
    from jax.experimental import multihost_utils

    keys = sorted(scalars)
    vec = np.asarray([float(scalars[k]) for k in keys], np.float64)
    gathered = multihost_utils.process_allgather(vec)
    if jax.process_index() != 0:
        return None
    return {k: float(np.asarray(gathered)[:, i].mean()) for i, k in enumerate(keys)}


class StepTracer:
    """Append-only JSONL step-trace writer (per-host file)."""

    def __init__(
        self,
        trace_path: str,
        flush_interval: int = 20,
        sample_every: int = 1,
        process_index: Optional[int] = None,
        max_bytes: int = 0,
    ):
        self.trace_path = trace_path
        self.flush_interval = max(1, int(flush_interval))
        self.sample_every = max(1, int(sample_every))
        # size-capped rotation (telemetry.trace_max_mb): at the cap the live
        # file atomically rolls to <file>.1 and a fresh file starts — a
        # long run's disk use stays bounded at ~2x the cap. 0 = unbounded.
        self.max_bytes = max(0, int(max_bytes))
        self._bytes_written: Optional[int] = None  # lazily from getsize
        self.rotations = 0
        self._buffer: List[str] = []
        self._force_next = False
        self._closed = False
        # emit() is called from the train step, the watchdog trip path AND
        # the async checkpoint writer's background thread (record_event on
        # commit/failure) — buffer appends, the size-capped rotation and
        # close() must serialize or a roll can tear/drop records mid-append.
        # Built through the dsan shim so sanitizer-enabled runs observe the
        # real acquisition schedule (ISSUE 8).
        self._lock = self._new_lock()
        self._dsan = self._dsan_module()
        if process_index is None:
            try:
                import jax

                process_index = jax.process_index()
            except Exception:
                process_index = 0
        self.process_index = process_index
        if trace_path.endswith(".jsonl"):
            root, name = os.path.split(trace_path)
            self._dir = root or "."
            # explicit file: keep the name on host 0, suffix other hosts
            self._file = (
                os.path.join(self._dir, name)
                if process_index == 0
                else os.path.join(self._dir, f"{name[:-6]}-{process_index:05d}.jsonl")
            )
        else:
            self._dir = trace_path
            self._file = os.path.join(trace_path, f"trace-{process_index:05d}.jsonl")
        self._agg_file = os.path.join(self._dir, "trace-aggregate.jsonl")
        self._dir_made = False  # lazily: a tracer that never emits writes nothing
        atexit.register(self.close)

    @staticmethod
    def _dsan_module():
        """The runtime sanitizer, when importable (deferred: the analysis
        package reads telemetry.introspect, so a module-level import here
        would be circular)."""
        try:
            from ..analysis import runtime_sanitizer

            return runtime_sanitizer
        except Exception:
            return None

    @classmethod
    def _new_lock(cls):
        dsan = cls._dsan_module()
        if dsan is not None:
            return dsan.maybe_lock("StepTracer._lock")
        import threading

        return threading.Lock()

    def _note_buffer_write(self) -> None:
        if self._dsan is not None:
            self._dsan.note_write(self, "_buffer")

    # -- sampling ------------------------------------------------------
    def should_sample(self, step: int) -> bool:
        if self._force_next:
            return True
        return step % self.sample_every == 0

    def force_next(self) -> None:
        """Make the next step emit a record regardless of ``sample_every``
        (bench.py uses this: zero-overhead timed loop, one recorded step)."""
        self._force_next = True

    # -- emission ------------------------------------------------------
    def emit(self, record: Dict[str, Any]) -> None:
        if str(record.get("kind", "")).endswith("_step"):
            # only a step record consumes a pending force_next — an
            # interleaved event (checkpoint save, …) must not cancel it
            self._force_next = False
        record.setdefault("ts", time.time())
        record.setdefault("host", self.process_index)
        clean = {k: _jsonable(v) for k, v in record.items()}
        line = json.dumps(clean, default=str)
        with self._lock:
            self._note_buffer_write()
            self._buffer.append(line)
            if len(self._buffer) >= self.flush_interval:
                self._flush_locked()

    def emit_serialized(self, line: str) -> None:
        """Append one ALREADY-SERIALIZED JSONL line, skipping the
        ``_jsonable`` sanitize + re-encode of :meth:`emit`. For callers
        that construct records JSON-native end to end (RequestTracer's
        terminal records — ISSUE 11): the defensive per-record sanitize
        pass was the request-tracing plane's single biggest hot-path cost.
        Same buffering, flush cadence and size-capped rotation as emit."""
        with self._lock:
            self._note_buffer_write()
            self._buffer.append(line)
            if len(self._buffer) >= self.flush_interval:
                self._flush_locked()

    def emit_aggregate(self, record: Dict[str, Any]) -> None:
        """Rank-0-only aggregated record (caller runs aggregate_scalars)."""
        clean = {k: _jsonable(v) for k, v in record.items()}
        with self._lock:
            self._ensure_dir()
            # the append IS the serialized section: aggregate records are
            # rare (rank-0, once per sampled step) and the file must not
            # interleave with a concurrent rotation of the live trace
            with open(self._agg_file, "a") as fh:  # dslint: disable=blocking-under-lock
                fh.write(json.dumps(clean, default=str) + "\n")

    def _ensure_dir(self) -> None:
        if not self._dir_made:
            os.makedirs(self._dir, exist_ok=True)
            self._dir_made = True

    def flush(self) -> None:
        with self._lock:
            self._flush_locked()

    def _flush_locked(self) -> None:
        """Buffer → file append (+ size-capped roll); caller holds _lock."""
        if not self._buffer:
            return
        self._note_buffer_write()
        data = "\n".join(self._buffer) + "\n"
        self._ensure_dir()
        if self.max_bytes:
            if self._bytes_written is None:  # resumed run: adopt on-disk size
                try:
                    self._bytes_written = os.path.getsize(self._file)
                except OSError:
                    self._bytes_written = 0
            if self._bytes_written and self._bytes_written + len(data) > self.max_bytes:
                # atomic roll: the live file becomes the (single) rolled
                # generation; a concurrent reader sees either whole file,
                # never a torn one
                os.replace(self._file, self._file + ".1")
                self._bytes_written = 0
                self.rotations += 1
        with open(self._file, "a") as fh:
            fh.write(data)
        if self._bytes_written is not None:
            self._bytes_written += len(data)
        self._buffer = []

    def close(self) -> None:
        with self._lock:
            if self._closed:
                return
            self._flush_locked()
            self._closed = True
        atexit.unregister(self.close)  # don't pin closed tracers for life

    @property
    def file_path(self) -> str:
        return self._file
