"""HLO cost / MFU analyzer: interpret a compiled step, not just time it.

PR 1 gave the runtime raw metrics; this module turns a compiled XLA program
into *answers*: what fraction of the chip's peak the step achieved (MFU),
where its flops and bytes go (matmul / attention / collective / elementwise),
and what bounds it (compute vs memory vs communication — a roofline
classification against a per-chip peak table, CPU fallback included).

Method: walk the **post-optimization HLO text** of the compiled executable
(the same source of truth ``comm/comm.py record_from_compiled`` uses for the
collective mix) and cost each instruction analytically:

- ``dot``: flops = 2 · |output| · Π(contracted dims) — exact, from the
  printed shapes and ``lhs_contracting_dims``. Categorized ``attention``
  when the instruction's metadata (op_name / source_file) points into an
  attention module, ``matmul`` otherwise.
- collectives (``all-reduce`` / ``all-gather`` / ``reduce-scatter`` /
  ``all-to-all`` / ``collective-permute``): payload bytes from the operand
  shapes (post-opt dtypes ⇒ wire precision). Async ``-start``/``-done``
  pairs are counted once and tallied as *overlappable* — the latency-hiding
  scheduler split them so compute can run between start and done; the
  ``overlap_fraction`` estimate is overlappable bytes / total collective
  bytes.
- elementwise arithmetic + reduces: 1 flop per output (resp. input) element,
  mirroring XLA's own HloCostAnalysis convention, so the parsed total stays
  comparable to ``compiled.cost_analysis()['flops']``
  (``profiling.flops_profiler.verify_against_hlo`` pins the two within 5%).

Known limits (inherited from HLO-as-text, same as bench.py's cost_analysis
caveats): a ``while`` body (gradient-accumulation scan) prints once but runs
``loop_iterations`` times — pass the trip count (the engine passes its gas)
and in-loop costs are multiplied; Pallas custom-calls report zero flops
(their cost is invisible to XLA too), so TPU flash-attention steps
under-count — the ``attention`` category still *counts* the calls.
"""

from __future__ import annotations

import math
import re
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

# ---------------------------------------------------------------------------
# per-chip peak table
# ---------------------------------------------------------------------------

# bf16 matmul peak flop/s, HBM bytes/s, and per-link ICI bytes/s by device
# kind (published TPU specs; bench.py's PEAK_TFLOPS agrees on the flops
# column). Keys match ``jax.Device.device_kind`` substrings, checked longest
# first so "TPU v5p" wins over "TPU v5".
PEAK_TABLE: Dict[str, Dict[str, float]] = {
    "TPU v4": dict(peak_flops=275e12, hbm_bytes_per_s=1.23e12, ici_bytes_per_s=4.8e10),
    "TPU v5 lite": dict(peak_flops=197e12, hbm_bytes_per_s=8.19e11, ici_bytes_per_s=4.0e10),
    "TPU v5e": dict(peak_flops=197e12, hbm_bytes_per_s=8.19e11, ici_bytes_per_s=4.0e10),
    "TPU v5p": dict(peak_flops=459e12, hbm_bytes_per_s=2.765e12, ici_bytes_per_s=9.0e10),
    "TPU v6e": dict(peak_flops=918e12, hbm_bytes_per_s=1.64e12, ici_bytes_per_s=4.0e10),
    "TPU v6 lite": dict(peak_flops=918e12, hbm_bytes_per_s=1.64e12, ici_bytes_per_s=4.0e10),
}

# nominal CPU host fallback (one modern server core group): keeps MFU /
# roofline DEFINED on the CPU test mesh, clearly labeled estimated. The
# absolute numbers matter less than the ratios being finite and stable.
CPU_FALLBACK = dict(peak_flops=2.0e11, hbm_bytes_per_s=5.0e10, ici_bytes_per_s=2.0e10)


@dataclass(frozen=True)
class PeakSpec:
    """Resolved peak capabilities of the chip the program runs on."""

    device_kind: str
    peak_flops: float
    hbm_bytes_per_s: float
    ici_bytes_per_s: float
    source: str  # "table" | "fallback" | "override"

    def to_dict(self) -> Dict[str, Any]:
        return {
            "device_kind": self.device_kind,
            "peak_flops": self.peak_flops,
            "hbm_bytes_per_s": self.hbm_bytes_per_s,
            "ici_bytes_per_s": self.ici_bytes_per_s,
            "source": self.source,
        }


def chip_peak(device_kind: Optional[str] = None,
              peak_flops_override: float = 0.0) -> PeakSpec:
    """Look up the peak entry for ``device_kind`` (default: first jax device).

    Unknown kinds get the CPU fallback entry, flagged ``source="fallback"``
    so dashboards can render the MFU as an estimate.
    ``peak_flops_override`` (e.g. ``telemetry.introspection.peak_tflops``)
    replaces the flops column only.
    """
    if device_kind is None:
        try:
            import jax

            device_kind = jax.devices()[0].device_kind
        except Exception:
            device_kind = "unknown"
    entry, source = CPU_FALLBACK, "fallback"
    for key in sorted(PEAK_TABLE, key=len, reverse=True):
        if key.lower() in str(device_kind).lower():
            entry, source = PEAK_TABLE[key], "table"
            break
    flops = float(peak_flops_override) or entry["peak_flops"]
    if peak_flops_override:
        source = "override"
    return PeakSpec(
        device_kind=str(device_kind),
        peak_flops=flops,
        hbm_bytes_per_s=entry["hbm_bytes_per_s"],
        ici_bytes_per_s=entry["ici_bytes_per_s"],
        source=source,
    )


# ---------------------------------------------------------------------------
# HLO text walk
# ---------------------------------------------------------------------------

_DTYPE_BYTES = {
    "pred": 1, "s8": 1, "u8": 1, "f8e4m3fn": 1, "f8e5m2": 1,
    "s16": 2, "u16": 2, "f16": 2, "bf16": 2,
    "s32": 4, "u32": 4, "f32": 4, "s64": 8, "u64": 8, "f64": 8,
}

_COLLECTIVE_OPS = (
    "all-reduce", "all-gather", "reduce-scatter", "all-to-all",
    "collective-permute",
)

# elementwise arithmetic counted at 1 flop / output element (HloCostAnalysis
# convention; transcendentals land in the same bucket here — they execute on
# the same units and the counts are dominated by dots anyway)
_ELEMENTWISE_OPS = frozenset((
    "add", "subtract", "multiply", "divide", "maximum", "minimum", "power",
    "exponential", "log", "tanh", "rsqrt", "sqrt", "negate", "abs",
    "exponential-minus-one", "log-plus-one", "logistic", "cbrt",
))

_ATTN_HINT = re.compile(r"attention|attn|flash|softmax_qk|scaled_dot", re.I)

# one HLO instruction: "%name = type[dims]{layout} opcode("
_INSTR = re.compile(
    r"=\s*(?P<dtype>[\w]+)\[(?P<dims>[0-9,]*)\][^\s]*\s*"
    r"(?P<op>[\w\-]+)\("
)
# tuple-typed result: "%name = (type[dims]{l}, ...) opcode(" — the form the
# latency-hiding scheduler emits for async collective starts (all-gather-start
# returns (operand-alias, result)); tuple element shapes never nest parens
_INSTR_TUPLE = re.compile(
    r"=\s*\((?P<shapes>[^()]*)\)\s*(?P<op>[\w\-]+)\("
)
_SHAPE = re.compile(r"(\w+)\[([0-9,]*)\]")
def _numel(dims: str) -> int:
    n = 1
    for d in dims.split(","):
        if d:
            n *= int(d)
    return n


def _shape_bytes(dtype: str, dims: str) -> int:
    return _numel(dims) * _DTYPE_BYTES.get(dtype, 4)


def _operand_shapes(line: str) -> List[tuple]:
    """Typed operand shapes inside the instruction's call parens."""
    start = line.find("(", line.find("= "))
    if start < 0:
        return []
    depth, end = 0, len(line)
    for i in range(start, len(line)):
        if line[i] == "(":
            depth += 1
        elif line[i] == ")":
            depth -= 1
            if depth == 0:
                end = i
                break
    return _SHAPE.findall(line[start:end])


def _dot_flops(line: str, out_dims: str) -> float:
    """2 · |out| · Π(lhs contracted dims) — exact from the printed attrs."""
    ops = _operand_shapes(line)
    if not ops:
        return 0.0
    lhs_dims = [int(d) for d in ops[0][1].split(",") if d]
    m = re.search(r"lhs_contracting_dims=\{([0-9,]*)\}", line)
    contracted = 1
    if m and lhs_dims:
        for idx in m.group(1).split(","):
            if idx and int(idx) < len(lhs_dims):
                contracted *= lhs_dims[int(idx)]
    return 2.0 * _numel(out_dims) * contracted


@dataclass
class CategoryCost:
    flops: float = 0.0
    bytes: float = 0.0
    count: int = 0

    def add(self, flops: float, nbytes: float) -> None:
        self.flops += flops
        self.bytes += nbytes
        self.count += 1

    def to_dict(self) -> Dict[str, float]:
        return {"flops": self.flops, "bytes": self.bytes, "count": self.count}


@dataclass
class HloAnalysis:
    """Per-category cost of one compiled program (per-device module)."""

    categories: Dict[str, CategoryCost] = field(default_factory=dict)
    total_flops: float = 0.0
    total_bytes: float = 0.0
    collective_bytes: float = 0.0
    overlappable_collective_bytes: float = 0.0
    loop_iterations: int = 1
    xla_flops: Optional[float] = None
    xla_bytes: Optional[float] = None

    @property
    def overlap_fraction(self) -> float:
        """Collective bytes issued as async start/done pairs (schedulable
        under compute) over all collective bytes; 1.0 when there is nothing
        to hide."""
        if self.collective_bytes <= 0:
            return 1.0
        return self.overlappable_collective_bytes / self.collective_bytes

    def to_dict(self) -> Dict[str, Any]:
        return {
            "total_flops": self.total_flops,
            "total_bytes": self.total_bytes,
            "collective_bytes": self.collective_bytes,
            "overlap_fraction": round(self.overlap_fraction, 4),
            "loop_iterations": self.loop_iterations,
            "xla_flops": self.xla_flops,
            "xla_bytes": self.xla_bytes,
            "categories": {k: v.to_dict() for k, v in self.categories.items()},
        }


_CALLED_COMPS = re.compile(r"(?:body|condition|calls|to_apply)=\{?%?([\w.\-]+)")


def _split_computations(txt: str) -> Dict[str, List[str]]:
    """Computation name → its instruction lines (HLO text is one flat file
    of ``%comp (params) -> type { ... }`` blocks plus the ENTRY block)."""
    comps: Dict[str, List[str]] = {}
    cur = "_module"
    # header: "[ENTRY ]%name (params...) -> type {" — params can nest
    # parens (tuple-typed args), so key on the "-> ... {" tail and the
    # absence of an "=" (instructions always assign)
    header = re.compile(r"^\s*(?:ENTRY\s+)?%?([\w.\-]+)\s*\(")
    for line in txt.splitlines():
        stripped = line.rstrip()
        hm = header.match(line)
        if (
            hm
            and stripped.endswith("{")
            and "->" in stripped
            and " = " not in stripped
        ):
            cur = hm.group(1)
            comps.setdefault(cur, [])
            continue
        comps.setdefault(cur, []).append(line)
    return comps


def _loop_computations(comps: Dict[str, List[str]]) -> set:
    """Computations that execute once PER while-loop iteration: the bodies/
    conditions named on ``while(`` instructions, closed transitively over
    the call graph (fusions/calls/reduces nested inside a loop body run per
    iteration too)."""
    refs: Dict[str, List[str]] = {
        name: [r for line in lines for r in _CALLED_COMPS.findall(line)]
        for name, lines in comps.items()
    }
    seeds = [
        r
        for lines in comps.values()
        for line in lines
        if " while(" in line or "= while(" in line
        for r in _CALLED_COMPS.findall(line)
    ]
    in_loop: set = set()
    stack = list(seeds)
    while stack:
        c = stack.pop()
        if c in in_loop:
            continue
        in_loop.add(c)
        stack.extend(refs.get(c, ()))
    return in_loop


# -- public instruction grammar (ISSUE 6) -----------------------------------
# the dslint program verifiers (analysis/hlo_rules.py) read the same HLO
# text; exporting the grammar keeps the two HLO readers from drifting

DTYPE_BYTES = _DTYPE_BYTES
shape_bytes = _shape_bytes
operand_shapes = _operand_shapes


def parse_instruction(line: str):
    """One HLO instruction line → ``(op, result_bytes, tuple_shapes)``.

    ``tuple_shapes`` is the parsed ``[(dtype, dims), ...]`` list for
    tuple-typed results (async collective starts) and None for plain
    results; ``result_bytes`` is the result size (largest tuple element
    for tuples, 0 for unknown dtypes). Returns ``(None, 0, None)`` for
    non-instruction lines."""
    m = _INSTR.search(line)
    if m:
        dtype, dims = m.group("dtype"), m.group("dims")
        nbytes = _shape_bytes(dtype, dims) if dtype in _DTYPE_BYTES else 0
        return m.group("op"), nbytes, None
    tm = _INSTR_TUPLE.search(line)
    if tm:
        shapes = _SHAPE.findall(tm.group("shapes"))
        sizes = [
            _shape_bytes(dt, dd) for dt, dd in shapes if dt in _DTYPE_BYTES
        ]
        return tm.group("op"), (max(sizes) if sizes else 0), shapes
    return None, 0, None


_NAMED_INSTR = re.compile(
    r"^\s*(?P<root>ROOT\s+)?%(?P<name>[\w.\-]+)\s*=\s*(?P<rest>.*)$"
)
_RESTYPE_PLAIN = re.compile(r"[\w]+\[[0-9,]*\](\{[^}]*\})?(\S*)")
_OP_AFTER_TYPE = re.compile(r"\s*(?P<op>[\w\-]+)\(")


@dataclass
class NamedInstruction:
    """One parsed HLO instruction with buffer-level detail (ISSUE 9).

    The dsmem liveness walker (``analysis/memory_rules.py``) needs more than
    :func:`parse_instruction`'s (op, bytes) view: the instruction NAME (the
    def in the def-use chain), the operand names (the uses), the typed
    result shapes (tuple elements are separate buffers), the attribute tail
    (``index=``/``body=``/``metadata=``) and whether this is the ROOT.
    Shares the byte/shape grammar above so the three HLO readers (cost walk,
    Engine A/D rules, Engine E liveness) cannot drift."""

    name: str
    op: str
    result_shapes: List[tuple]   # [(dtype, dims), ...]; >1 for tuple results
    result_bytes: int            # sum over known-dtype result shapes
    operands: List[str]          # %names referenced inside the call parens
    attrs: str                   # text after the call parens (index=, body=)
    is_root: bool
    line: str


def parse_named_instruction(line: str) -> Optional[NamedInstruction]:
    """One HLO instruction line → :class:`NamedInstruction`, or None for
    non-instruction lines (headers, braces, comments)."""
    m = _NAMED_INSTR.match(line.strip())
    if not m:
        return None
    name, rest = m.group("name"), m.group("rest")
    if rest.startswith("("):
        depth = 0
        end = -1
        for i, ch in enumerate(rest):
            if ch == "(":
                depth += 1
            elif ch == ")":
                depth -= 1
                if depth == 0:
                    end = i
                    break
        if end < 0:
            return None
        restype, tail = rest[: end + 1], rest[end + 1:]
    else:
        tm = _RESTYPE_PLAIN.match(rest)
        if not tm:
            return None
        restype, tail = rest[: tm.end()], rest[tm.end():]
    om = _OP_AFTER_TYPE.match(tail)
    if not om:
        return None
    call_start = tail.find("(")
    depth, call_end = 0, len(tail)
    for i in range(call_start, len(tail)):
        if tail[i] == "(":
            depth += 1
        elif tail[i] == ")":
            depth -= 1
            if depth == 0:
                call_end = i
                break
    shapes = _SHAPE.findall(restype)
    return NamedInstruction(
        name=name,
        op=om.group("op"),
        result_shapes=shapes,
        result_bytes=sum(
            _shape_bytes(dt, dd) for dt, dd in shapes if dt in _DTYPE_BYTES
        ),
        operands=re.findall(r"%([\w.\-]+)", tail[call_start:call_end]),
        attrs=tail[call_end + 1:],
        is_root=m.group("root") is not None,
        line=line,
    )


def split_computations(txt: str) -> Dict[str, List[str]]:
    """Public alias of the computation splitter (ISSUE 9): computation name
    → its instruction lines. The ENTRY computation's name is recoverable by
    scanning for a line starting with ``ENTRY``; see ``entry_computation``."""
    return _split_computations(txt)


def entry_computation(txt: str) -> Optional[str]:
    """Name of the ENTRY computation in ``txt`` (None if absent)."""
    m = re.search(r"^\s*ENTRY\s+%?([\w.\-]+)\s*\(", txt, re.M)
    return m.group(1) if m else None


def analyze_hlo_text(txt: str, loop_iterations: int = 1) -> HloAnalysis:
    """Walk post-optimization HLO text into a per-category cost breakdown.

    ``loop_iterations`` multiplies costs found inside ``while``-loop bodies
    (a gas scan prints its body once but executes it gas times); the caller
    knows the trip count, the text does not. Loop membership is derived
    from the while instructions' ``body=``/``condition=`` attributes, closed
    over the call graph, so fusions nested in a scan body count correctly.
    """
    ana = HloAnalysis(loop_iterations=max(1, int(loop_iterations)))
    cats = ana.categories
    for name in ("matmul", "attention", "collective", "elementwise", "other"):
        cats[name] = CategoryCost()

    comps = _split_computations(txt)
    in_loop_comps = _loop_computations(comps) if ana.loop_iterations > 1 else set()

    for comp_name, lines in comps.items():
        mult = ana.loop_iterations if comp_name in in_loop_comps else 1
        for line in lines:
            _cost_line(line, mult, ana, cats)

    ana.total_flops = sum(c.flops for c in cats.values())
    ana.total_bytes = sum(c.bytes for c in cats.values())
    return ana


def _cost_line(line: str, mult: int, ana: HloAnalysis, cats) -> None:
    """Cost one HLO instruction line into the category breakdown."""
    m = _INSTR.search(line)
    tuple_shapes = None
    if not m:
        tm = _INSTR_TUPLE.search(line)
        if not tm:
            return
        m, tuple_shapes = tm, tm.group("shapes")
    op = m.group("op")
    base_op = re.sub(r"-(start|done)$", "", op)

    if base_op in _COLLECTIVE_OPS:
        if op.endswith("-done"):
            return  # counted at -start
        # payload = largest typed buffer: async starts return an
        # (operand-alias, result) tuple whose biggest element — operand for
        # all-reduce, gathered result for all-gather — upper-bounds the wire
        # volume (same convention as comm.record_from_compiled); sync forms
        # read it off the call operands
        if tuple_shapes is not None:
            shapes = _SHAPE.findall(tuple_shapes)
        else:
            shapes = list(_operand_shapes(line))
        sizes = [
            _shape_bytes(dt, dd) for dt, dd in shapes if dt in _DTYPE_BYTES
        ]
        nbytes = (max(sizes) if sizes else 0) * mult
        cats["collective"].add(0.0, nbytes)
        ana.collective_bytes += nbytes
        if op.endswith("-start"):
            ana.overlappable_collective_bytes += nbytes
        return

    if tuple_shapes is not None:
        return  # other tuple-result ops (variadic reduce, rng) are uncosted
    dtype, dims = m.group("dtype"), m.group("dims")
    if dtype is None or dtype not in _DTYPE_BYTES:
        return
    out_bytes = _shape_bytes(dtype, dims)

    if op == "dot":
        flops = _dot_flops(line, dims) * mult
        nbytes = (
            out_bytes
            + sum(_shape_bytes(dt, dd) for dt, dd in _operand_shapes(line)
                  if dt in _DTYPE_BYTES)
        ) * mult
        cat = "attention" if _ATTN_HINT.search(line) else "matmul"
        cats[cat].add(flops, nbytes)
    elif op == "custom-call":
        cat = "attention" if _ATTN_HINT.search(line) else "other"
        # Pallas / library custom-calls: flops invisible (see module
        # docstring); count the call and its result bytes
        cats[cat].add(0.0, out_bytes * mult)
    elif op in _ELEMENTWISE_OPS:
        flops = float(_numel(dims)) * mult
        cats["elementwise"].add(flops, 2.0 * out_bytes * mult)
    elif op == "reduce":
        ops_ = _operand_shapes(line)
        in_elems = max((_numel(dd) for _, dd in ops_), default=0)
        flops = float(max(0, in_elems - _numel(dims))) * mult
        nbytes = (out_bytes + sum(
            _shape_bytes(dt, dd) for dt, dd in ops_ if dt in _DTYPE_BYTES
        )) * mult
        cats["elementwise"].add(flops, nbytes)


def analyze_compiled(compiled, loop_iterations: int = 1) -> HloAnalysis:
    """Analyze a ``jax.stages.Compiled`` (or anything with ``as_text()``);
    attaches XLA's own ``cost_analysis()`` totals for cross-checking."""
    txt = compiled.as_text() if hasattr(compiled, "as_text") else str(compiled)
    ana = analyze_hlo_text(txt, loop_iterations=loop_iterations)
    try:
        ca = compiled.cost_analysis()
        if isinstance(ca, (list, tuple)):
            ca = ca[0] if ca else {}
        ca = dict(ca or {})
        ana.xla_flops = float(ca.get("flops", 0.0)) or None
        ana.xla_bytes = float(ca.get("bytes accessed", 0.0)) or None
    except Exception:
        pass
    return ana


# ---------------------------------------------------------------------------
# MFU + roofline report
# ---------------------------------------------------------------------------

def step_report(
    analysis: HloAnalysis,
    duration_s: float,
    peak: Optional[PeakSpec] = None,
) -> Dict[str, Any]:
    """One measured step + one analyzed program → the introspection record.

    Everything is per-device: the analyzed module is the SPMD-partitioned
    per-device program and ``peak`` is one chip's table entry, so the MFU
    is the per-chip utilization regardless of mesh size.

    Roofline: estimated compute / memory / communication times from the
    peak table; the largest wins as ``bound``. ``comm`` additionally
    discounts collective time by the overlap fraction — fully-async
    collectives only bound the step through their unhidden remainder.
    """
    peak = peak or chip_peak()
    dur = max(float(duration_s), 1e-9)
    flops = analysis.total_flops
    nbytes = analysis.total_bytes
    mfu = flops / dur / peak.peak_flops
    t_compute = flops / peak.peak_flops
    t_memory = nbytes / peak.hbm_bytes_per_s
    unhidden = analysis.collective_bytes * (1.0 - analysis.overlap_fraction)
    t_comm = unhidden / peak.ici_bytes_per_s
    bound = max(
        (("compute", t_compute), ("memory", t_memory), ("comm", t_comm)),
        key=lambda kv: kv[1],
    )[0]
    intensity = flops / nbytes if nbytes > 0 else float("inf")
    ridge = peak.peak_flops / peak.hbm_bytes_per_s
    report = {
        "mfu": round(mfu, 9),
        "flops_per_step": flops,
        "bytes_per_step": nbytes,
        "arithmetic_intensity": round(intensity, 3) if math.isfinite(intensity) else None,
        "ridge_intensity": round(ridge, 3),
        "roofline_bound": bound,
        "est_compute_s": t_compute,
        "est_memory_s": t_memory,
        "est_comm_s": t_comm,
        "overlap_fraction": round(analysis.overlap_fraction, 4),
        "flops_per_category": {
            k: v.flops for k, v in analysis.categories.items() if v.count or v.flops
        },
        "bytes_per_category": {
            k: v.bytes for k, v in analysis.categories.items() if v.count or v.bytes
        },
        "peak": peak.to_dict(),
        "loop_iterations": analysis.loop_iterations,
    }
    if analysis.xla_flops:
        report["xla_flops"] = analysis.xla_flops
    return report


def export_to_registry(registry, report: Dict[str, Any]) -> None:
    """Fold one step report into the PR-1 metrics registry: ``step_mfu``,
    per-category flop/byte gauges, ``overlap_fraction``, and a one-hot
    ``roofline_bound{bound}`` family (the current bound reads 1)."""
    registry.gauge(
        "step_mfu", "model flops utilization of the last sampled step"
    ).set(report["mfu"])
    registry.gauge(
        "overlap_fraction",
        "collective bytes hidden under compute (HLO-schedule estimate)",
    ).set(report["overlap_fraction"])
    if report.get("arithmetic_intensity") is not None:
        registry.gauge(
            "step_arithmetic_intensity", "flops per HBM byte of the step"
        ).set(report["arithmetic_intensity"])
    gf = registry.gauge(
        "flops_per_category", "per-step flops by HLO category",
        labelnames=("category",),
    )
    for k, v in report["flops_per_category"].items():
        gf.set(v, category=k)
    gb = registry.gauge(
        "bytes_per_category", "per-step bytes by HLO category",
        labelnames=("category",),
    )
    for k, v in report["bytes_per_category"].items():
        gb.set(v, category=k)
    gr = registry.gauge(
        "roofline_bound", "roofline classification (current bound = 1)",
        labelnames=("bound",),
    )
    for b in ("compute", "memory", "comm"):
        gr.set(1.0 if report["roofline_bound"] == b else 0.0, bound=b)
