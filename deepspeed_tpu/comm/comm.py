"""``deepspeed_tpu.comm`` façade — single namespace for collectives + logging.

Analog of reference ``deepspeed/comm/comm.py`` (750 LoC): one module every
subsystem imports for collectives, with optional per-op accounting. Two big
differences, both TPU-native:

1. Collectives are *traceable* (used inside jit/shard_map); there is no
   eager NCCL call to time. Accounting therefore happens at **trace time**
   (shapes are static, so op counts and byte volumes per compiled step are
   exact), and wall-time attribution comes from the XLA profiler rather than
   wrapping each call (reference ``timed_op`` decorator, comm.py:111).
2. "Process groups" are mesh axis names; there is no ``new_group``.

``init_distributed`` (reference comm.py:577) maps to multi-host JAX init with
the same env-discovery behavior (MASTER_ADDR/PORT, WORLD_SIZE, RANK …).
"""

from __future__ import annotations

import os
from typing import Optional

import jax.numpy as jnp
import numpy as np

from ..utils.logging import log_dist, logger
from .backend import Backend
from .xla import (  # noqa: F401  (re-exported primitives)
    XLABackend,
    all_gather,
    all_reduce,
    all_to_all,
    axis_index,
    axis_size,
    barrier,
    broadcast,
    ppermute,
    reduce_scatter,
    ring_shift,
)

cdb: Optional[Backend] = None  # "communication data backend", name kept for parity


class CommsLogger:
    """Trace-time collective accounting (reference utils/comms_logging.py:56).

    Because shapes are static under jit, recording at trace time yields the
    exact per-compiled-step op mix; multiply by executed steps for totals.
    """

    def __init__(self, enabled: bool = False, verbose: bool = False, prof_all: bool = True, debug: bool = False):
        self.enabled = enabled
        self.verbose = verbose
        self.prof_all = prof_all
        self.debug = debug
        self.comms_dict = {}

    def configure(self, enabled=None, verbose=None, prof_all=None, debug=None):
        if enabled is not None:
            self.enabled = enabled
        if verbose is not None:
            self.verbose = verbose
        if prof_all is not None:
            self.prof_all = prof_all
        if debug is not None:
            self.debug = debug

    def append(self, op_name: str, axis, nbytes: int, wire_bytes: Optional[int] = None):
        """Record one collective: ``nbytes`` is the LOGICAL payload (what the
        op carries at its source precision); ``wire_bytes`` the actual
        on-wire volume when a compressed layer shrank it (defaults to
        ``nbytes`` — uncompressed ops have ratio 1)."""
        if not self.enabled:
            return
        key = (op_name, str(axis))
        rec = self.comms_dict.setdefault(
            key, {"count": 0, "bytes": 0, "wire_bytes": 0, "time_ms": None, "world": None}
        )
        rec["count"] += 1
        rec["bytes"] += nbytes
        rec["wire_bytes"] += wire_bytes if wire_bytes is not None else nbytes
        if rec["world"] is None:
            # called at trace time with the mesh axis in scope: psum of a
            # literal constant folds to the axis size (no HLO emitted), so
            # the summary's world/busbw columns are right without measure()
            try:
                from jax import lax

                rec["world"] = int(lax.psum(1, axis))
            except Exception:
                pass
        if self.verbose:
            log_dist(f"comm op: {op_name} | axis: {axis} | bytes: {nbytes}")

    # busbw correction factors per ring algorithm (reference
    # utils/comms_logging.py get_bw: allreduce moves 2(n-1)/n of the payload,
    # all_gather / reduce_scatter / all_to_all move (n-1)/n)
    @staticmethod
    def _bus_factor(op: str, n: int) -> float:
        if n <= 1:
            return 1.0
        if op == "all_reduce":
            return 2.0 * (n - 1) / n
        if op in ("all_gather", "reduce_scatter", "all_to_all"):
            return (n - 1) / n
        return 1.0

    def measure(self, mesh, iters: int = 5) -> None:
        """Fill measured latency for every recorded (op, axis) by running that
        collective at the recorded payload size on ``mesh`` and timing it —
        the eager-measurement analog of the reference's ``timed_op`` CUDA-event
        timing (comm/comm.py:111 + comms_logging.py:56).

        Rows recorded from compiled HLO carry axis ``"xla"`` (the inserting
        axis isn't recoverable from the op name) or ``"xla-loop"`` (the op
        sits inside a while/scan body, so its count is per-iteration rather
        than per-step); both are measured over the mesh's largest axis — an
        attribution approximation, stated here.
        """
        import time

        import jax
        from ..utils.compat import shard_map
        from jax.sharding import PartitionSpec as P

        from . import xla as _xla

        def _a2a(x, ax):
            n = _xla.axis_size(ax)
            return _xla.all_to_all(
                x.reshape(n, -1), ax, split_dim=0, concat_dim=0
            ).reshape(-1)

        fns = {
            "all_reduce": lambda x, ax: _xla.all_reduce(x, ax),
            "all_gather": lambda x, ax: _xla.all_gather(x, ax),
            "reduce_scatter": lambda x, ax: _xla.reduce_scatter(x, ax),
            "all_to_all": _a2a,
            "broadcast": lambda x, ax: _xla.broadcast(x, ax),
            "ppermute": lambda x, ax: _xla.ring_shift(x, ax),
        }
        biggest_axis = max(mesh.axis_names, key=lambda a: mesh.shape[a])
        # the wrappers being timed call _record at trace time; don't let the
        # measurement pollute the statistics it measures
        prev_enabled, self.enabled = self.enabled, False
        try:
            for (op, axis), rec in self.comms_dict.items():
                fn = fns.get(op)
                ax = axis if axis in mesh.axis_names else (
                    biggest_axis if axis in ("xla", "xla-loop") else None
                )
                if fn is None or ax is None:
                    continue
                n = mesh.shape[ax]
                # replay at the WIRE size (what actually moved): log_summary
                # divides wire bytes by this latency, so sizing the replay
                # from logical bytes would understate compressed rows ~4x
                per_call = max(
                    4, (rec.get("wire_bytes") or rec["bytes"]) // max(1, rec["count"])
                )
                nelem = max(1, per_call // 4)
                nelem = -(-nelem // n) * n  # pad to axis-divisible (scatter dims)
                x = jnp.zeros((nelem,), jnp.float32)
                spec = P()
                mapped = jax.jit(
                    shard_map(
                        lambda v, fn=fn, ax=ax: fn(v, ax),
                        mesh=mesh,
                        in_specs=(spec,),
                        out_specs=spec if op not in ("all_gather", "reduce_scatter") else P(ax),
                        check_vma=False,
                    )
                )
                out = mapped(x)
                jax.block_until_ready(out)
                t0 = time.perf_counter()
                for _ in range(iters):
                    out = mapped(x)
                jax.block_until_ready(out)
                rec["time_ms"] = (time.perf_counter() - t0) / iters * 1e3
                rec["world"] = n
        finally:
            self.enabled = prev_enabled

    # nominal per-chip interconnect bus bandwidth (GB/s) by TPU generation,
    # used to ESTIMATE latency/bandwidth for rows recorded at trace time but
    # never measured ("~"-prefixed columns); override with
    # DS_COMM_ASSUMED_BUSBW_GBPS. ICI per-chip order-of-magnitude figures.
    ASSUMED_BUSBW_GBPS = {"v4": 90.0, "v5e": 45.0, "v5p": 180.0, "v6e": 180.0}

    @classmethod
    def _assumed_busbw_gbps(cls) -> float:
        env = os.environ.get("DS_COMM_ASSUMED_BUSBW_GBPS")
        if env:
            return float(env)
        gen = os.environ.get("PALLAS_AXON_TPU_GEN", "v5e")
        return cls.ASSUMED_BUSBW_GBPS.get(gen, 45.0)

    def log_summary(self) -> str:
        """Reference-style per-op table (utils/comms_logging.py:56 columns:
        op, size, count, world, avg latency, algbw, busbw) extended with
        wire-bytes and compression-ratio columns: ``msg size`` is the logical
        payload, ``wire size`` the actual on-wire volume (they differ only
        for ops issued through the compressed layer, comm/compressed.py),
        ``ratio`` their quotient. Measured rows (after :meth:`measure`) show
        exact numbers; trace-time-only rows show "~"-prefixed estimates from
        the nominal interconnect bandwidth so the table always matches the
        reference output shape. Latency/bandwidth are computed from the WIRE
        volume — what actually moves.

        The table mixes two accounting sources that are NOT additive: rows
        keyed by a mesh-axis name come from trace-time wrapper/compressed-
        layer records, rows keyed ``xla``/``xla-loop`` from compiled HLO
        (``record_from_compiled``). A compressed step's all_to_all/all_gather
        appear in BOTH — the ``dp`` rows carry the logical-vs-wire split,
        the ``xla`` rows the compiler's physical op mix (payload and scale
        transfers counted separately). Do not sum across sources. Returns
        the rendered text (also logged)."""
        lines = ["Communication summary (per traced step):"]
        header = (
            f"  {'op':<16s}{'axis':<10s}{'count':>6s}{'world':>7s}{'msg size':>12s}"
            f"{'wire size':>12s}{'ratio':>7s}"
            f"{'avg lat(ms)':>13s}{'algbw(GB/s)':>13s}{'busbw(GB/s)':>13s}"
        )
        lines.append(header)
        for (op, axis), rec in sorted(self.comms_dict.items()):
            per_call = rec["bytes"] / max(1, rec["count"])
            wire_total = rec.get("wire_bytes") or rec["bytes"]
            wire_call = wire_total / max(1, rec["count"])
            ratio = rec["bytes"] / wire_total if wire_total else 1.0
            lat = rec.get("time_ms")
            world = rec.get("world")
            factor = self._bus_factor(op, world or 1)
            if lat:
                algbw = wire_call / (lat / 1e3) / 1e9
                busbw = algbw * factor
                lat_s, alg_s, bus_s = f"{lat:.3f}", f"{algbw:.2f}", f"{busbw:.2f}"
            elif wire_call > 0:
                # estimate from the nominal bus bandwidth: on-wire bytes are
                # wire_call * busbw-factor, so est busbw == the assumed figure
                # and algbw/latency follow from it
                bw = self._assumed_busbw_gbps() * 1e9
                est_lat_s = max(wire_call * factor / bw, 1e-9)
                algbw = wire_call / est_lat_s / 1e9
                lat_s = f"~{est_lat_s * 1e3:.3f}"
                alg_s = f"~{algbw:.2f}"
                bus_s = f"~{algbw * factor:.2f}"
            else:
                lat_s = alg_s = bus_s = "-"
            lines.append(
                f"  {op:<16s}{axis:<10s}{rec['count']:>6d}"
                f"{world if world else '-':>7}{per_call / 1e6:>10.2f}MB"
                f"{wire_call / 1e6:>10.2f}MB{ratio:>6.2f}x"
                f"{lat_s:>13s}{alg_s:>13s}{bus_s:>13s}"
            )
        text = "\n".join(lines)
        log_dist(text)
        return text

    def reset(self):
        self.comms_dict = {}


comms_logger = CommsLogger()


def configure(config=None, enabled=None, verbose=None, prof_all=None, debug=None):
    """Analog of reference comm.py:82."""
    if config is not None and getattr(config, "comms_logger", None) is not None:
        c = config.comms_logger
        comms_logger.configure(c.enabled, c.verbose, c.prof_all, c.debug)
    comms_logger.configure(enabled, verbose, prof_all, debug)


def record(op_name: str, axis, array) -> None:
    """Account a collective at trace time. Called by comm-aware layers."""
    try:
        nbytes = int(np.prod(array.shape)) * array.dtype.itemsize
    except Exception:
        nbytes = 0
    comms_logger.append(op_name, axis, nbytes)


_HLO_DTYPE_BYTES = {
    "pred": 1, "s8": 1, "u8": 1, "s16": 2, "u16": 2, "f16": 2, "bf16": 2,
    "s32": 4, "u32": 4, "f32": 4, "s64": 8, "u64": 8, "f64": 8,
}
_HLO_COLLECTIVES = (
    "all-reduce", "all-gather", "reduce-scatter", "all-to-all",
    "collective-permute",
)


def _replica_group_size(line: str) -> Optional[int]:
    """Participant count of a collective from its HLO ``replica_groups``
    attribute — both the explicit ``{{0,1},{2,3}}`` form and the iota
    ``[groups,size]<=[n]`` form (group size is the second dim)."""
    import re

    m = re.search(r"replica_groups=\[(\d+),(\d+)\]<=", line)
    if m:
        return int(m.group(2))
    m = re.search(r"replica_groups=\{\{([^}]*)\}", line)
    if m:
        ids = [t for t in m.group(1).split(",") if t.strip()]
        return len(ids) or None
    return None


def record_from_compiled(compiled, reset: bool = False) -> dict:
    """Derive the exact collective mix of a compiled step from its
    post-optimization HLO and merge it into the comms logger.

    This is the accounting path for SPMD programs where XLA *inserts* the
    collectives from sharding annotations (ZeRO's grad reduce-scatter /
    param all-gather never go through the Python wrappers — reference
    stage3.py issues them by hand and logs via timed_op; here the compiler
    is the issuer, so the compiled HLO is the source of truth).
    """
    import re

    if reset:
        comms_logger.reset()
    txt = compiled.as_text() if hasattr(compiled, "as_text") else str(compiled)
    found = {}
    pat = re.compile(
        r"=\s*(?:\(([^)]*)\)|(\w+)\[([0-9,]*)\][^\s]*)\s+("
        + "|".join(_HLO_COLLECTIVES) + r")(?:-(?:start|done))?\("
    )
    # Track computation boundaries: a collective inside a while-loop body
    # (gas scan, decode loop) executes once PER ITERATION but prints once in
    # HLO — the same scan-counted-once pitfall as cost_analysis (bench.py
    # docstring). Those rows get axis "xla-loop" so the table says
    # per-iteration, not per-step.
    cur_computation = ""
    comp_pat = re.compile(r"^\s*%?([\w.\-]+)\s*(?:\([^)]*\))?\s*(?:->[^{]*)?\{")
    for line in txt.splitlines():
        cm = comp_pat.match(line)
        if cm and "{" in line and "=" not in line.split("{")[0]:
            cur_computation = cm.group(1)
        m = pat.search(line)
        if not m:
            continue
        tuple_shapes, dtype, dims, op = m.group(1), m.group(2), m.group(3), m.group(4)
        # async pairs appear as op-start + op-done; count the start only
        if f"{op}-done(" in line:
            continue
        shapes = []
        if tuple_shapes is not None:
            shapes = re.findall(r"(\w+)\[([0-9,]*)\]", tuple_shapes)
        elif dtype is not None:
            shapes = [(dtype, dims)]
        sizes = []
        for dt, dd in shapes:
            if dt not in _HLO_DTYPE_BYTES:
                continue
            n = 1
            for d in dd.split(","):
                if d:
                    n *= int(d)
            sizes.append(n * _HLO_DTYPE_BYTES[dt])
        # async '-start' ops return (operand-alias, result) tuples: counting
        # both would double the payload; take the largest element as the
        # transfer size (== operand for all-reduce, == gathered result for
        # all-gather — an upper bound on the wire payload)
        nbytes = max(sizes) if sizes else 0
        name = op.replace("-", "_").replace("collective_permute", "ppermute")
        in_loop = any(t in cur_computation.lower() for t in ("while", "body", "cond"))
        key = (name, "xla-loop" if in_loop else "xla")
        rec = found.setdefault(key, {"count": 0, "bytes": 0})
        rec["count"] += 1
        rec["bytes"] += nbytes
        world = _replica_group_size(line)
        if world:
            rec["world"] = max(world, rec.get("world") or 0)
    was_enabled = comms_logger.enabled
    comms_logger.enabled = True
    for (op, axis), rec in found.items():
        entry = comms_logger.comms_dict.setdefault(
            (op, axis),
            {"count": 0, "bytes": 0, "wire_bytes": 0, "time_ms": None, "world": None},
        )
        entry["count"] += rec["count"]
        entry["bytes"] += rec["bytes"]
        # post-optimization HLO shapes carry the op's real dtype, so these
        # bytes are already on-wire volume (an int8 collective reads int8)
        entry["wire_bytes"] += rec["bytes"]
        if entry["world"] is None and rec.get("world"):
            entry["world"] = rec["world"]
    comms_logger.enabled = was_enabled
    return found


def log_summary():
    return comms_logger.log_summary()


# ---------------------------------------------------------------------------
# Process-level init (multi-host)
# ---------------------------------------------------------------------------

def is_initialized() -> bool:
    return cdb is not None and cdb.is_initialized()


def init_distributed(
    dist_backend: str = "xla",
    auto_mpi_discovery: bool = True,
    distributed_port: int = 29500,
    verbose: bool = True,
    timeout=None,
    init_method: Optional[str] = None,
    dist_init_required: Optional[bool] = None,
    config=None,
    rank: int = -1,
    world_size: int = -1,
) -> None:
    """Initialize multi-host communication (reference comm/comm.py:577).

    Environment discovery order mirrors the reference: explicit args →
    ``COORDINATOR_ADDRESS``/``MASTER_ADDR`` env → OpenMPI env (``OMPI_COMM_*``)
    → single-process fallback. On TPU pods launched through standard tooling
    (GKE/queued resources) ``jax.distributed.initialize()`` auto-discovers, so
    all of this collapses to one call.
    """
    global cdb
    if is_initialized():
        return
    configure(config=config)

    if world_size < 0:
        world_size = int(os.environ.get("WORLD_SIZE", os.environ.get("OMPI_COMM_WORLD_SIZE", "1")))
    if rank < 0:
        rank = int(os.environ.get("RANK", os.environ.get("OMPI_COMM_WORLD_RANK", "0")))
    coord = os.environ.get("COORDINATOR_ADDRESS")
    if coord is None and "MASTER_ADDR" in os.environ:
        port = os.environ.get("MASTER_PORT", str(distributed_port))
        coord = f"{os.environ['MASTER_ADDR']}:{port}"

    backend = XLABackend()
    if world_size > 1:
        # NOTHING may touch the jax backend before jax.distributed.initialize
        # — log_dist queries jax.process_index(), which initializes it and
        # makes multi-host init raise. Log only AFTER the rendezvous (bug
        # caught by tests/unit/test_init_distributed.py).
        backend.init_process_group(coordinator_address=coord, num_processes=world_size, process_id=rank)
        if verbose:
            log_dist(f"Initialized distributed: world_size={world_size} rank={rank} coordinator={coord}")
    else:
        backend.init_process_group()
    cdb = backend


def get_world_size(group=None) -> int:
    import jax

    return jax.process_count()


def get_rank(group=None) -> int:
    import jax

    return jax.process_index()


def get_local_rank() -> int:
    return int(os.environ.get("LOCAL_RANK", "0"))


def destroy_process_group():
    global cdb
    if cdb is not None:
        cdb.destroy_process_group()
        cdb = None
