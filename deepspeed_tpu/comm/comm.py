"""``deepspeed_tpu.comm`` façade — single namespace for collectives + logging.

Analog of reference ``deepspeed/comm/comm.py`` (750 LoC): one module every
subsystem imports for collectives, with optional per-op accounting. Two big
differences, both TPU-native:

1. Collectives are *traceable* (used inside jit/shard_map); there is no
   eager NCCL call to time. Accounting therefore happens at **trace time**
   (shapes are static, so op counts and byte volumes per compiled step are
   exact), and wall-time attribution comes from the XLA profiler rather than
   wrapping each call (reference ``timed_op`` decorator, comm.py:111).
2. "Process groups" are mesh axis names; there is no ``new_group``.

``init_distributed`` (reference comm.py:577) maps to multi-host JAX init with
the same env-discovery behavior (MASTER_ADDR/PORT, WORLD_SIZE, RANK …).
"""

from __future__ import annotations

import os
from typing import Optional

import numpy as np

from ..utils.logging import log_dist, logger
from .backend import Backend
from .xla import (  # noqa: F401  (re-exported primitives)
    XLABackend,
    all_gather,
    all_reduce,
    all_to_all,
    axis_index,
    axis_size,
    barrier,
    broadcast,
    ppermute,
    reduce_scatter,
    ring_shift,
)

cdb: Optional[Backend] = None  # "communication data backend", name kept for parity


class CommsLogger:
    """Trace-time collective accounting (reference utils/comms_logging.py:56).

    Because shapes are static under jit, recording at trace time yields the
    exact per-compiled-step op mix; multiply by executed steps for totals.
    """

    def __init__(self, enabled: bool = False, verbose: bool = False, prof_all: bool = True, debug: bool = False):
        self.enabled = enabled
        self.verbose = verbose
        self.prof_all = prof_all
        self.debug = debug
        self.comms_dict = {}

    def configure(self, enabled=None, verbose=None, prof_all=None, debug=None):
        if enabled is not None:
            self.enabled = enabled
        if verbose is not None:
            self.verbose = verbose
        if prof_all is not None:
            self.prof_all = prof_all
        if debug is not None:
            self.debug = debug

    def append(self, op_name: str, axis, nbytes: int):
        if not self.enabled:
            return
        key = (op_name, str(axis))
        rec = self.comms_dict.setdefault(key, {"count": 0, "bytes": 0})
        rec["count"] += 1
        rec["bytes"] += nbytes
        if self.verbose:
            log_dist(f"comm op: {op_name} | axis: {axis} | bytes: {nbytes}")

    def log_summary(self):
        log_dist("Communication summary (per traced step):")
        for (op, axis), rec in sorted(self.comms_dict.items()):
            mb = rec["bytes"] / 1e6
            log_dist(f"  {op:<16s} axis={axis:<12s} calls={rec['count']:<5d} volume={mb:.2f} MB")

    def reset(self):
        self.comms_dict = {}


comms_logger = CommsLogger()


def configure(config=None, enabled=None, verbose=None, prof_all=None, debug=None):
    """Analog of reference comm.py:82."""
    if config is not None and getattr(config, "comms_logger", None) is not None:
        c = config.comms_logger
        comms_logger.configure(c.enabled, c.verbose, c.prof_all, c.debug)
    comms_logger.configure(enabled, verbose, prof_all, debug)


def record(op_name: str, axis, array) -> None:
    """Account a collective at trace time. Called by comm-aware layers."""
    try:
        nbytes = int(np.prod(array.shape)) * array.dtype.itemsize
    except Exception:
        nbytes = 0
    comms_logger.append(op_name, axis, nbytes)


def log_summary():
    comms_logger.log_summary()


# ---------------------------------------------------------------------------
# Process-level init (multi-host)
# ---------------------------------------------------------------------------

def is_initialized() -> bool:
    return cdb is not None and cdb.is_initialized()


def init_distributed(
    dist_backend: str = "xla",
    auto_mpi_discovery: bool = True,
    distributed_port: int = 29500,
    verbose: bool = True,
    timeout=None,
    init_method: Optional[str] = None,
    dist_init_required: Optional[bool] = None,
    config=None,
    rank: int = -1,
    world_size: int = -1,
) -> None:
    """Initialize multi-host communication (reference comm/comm.py:577).

    Environment discovery order mirrors the reference: explicit args →
    ``COORDINATOR_ADDRESS``/``MASTER_ADDR`` env → OpenMPI env (``OMPI_COMM_*``)
    → single-process fallback. On TPU pods launched through standard tooling
    (GKE/queued resources) ``jax.distributed.initialize()`` auto-discovers, so
    all of this collapses to one call.
    """
    global cdb
    if is_initialized():
        return
    configure(config=config)

    if world_size < 0:
        world_size = int(os.environ.get("WORLD_SIZE", os.environ.get("OMPI_COMM_WORLD_SIZE", "1")))
    if rank < 0:
        rank = int(os.environ.get("RANK", os.environ.get("OMPI_COMM_WORLD_RANK", "0")))
    coord = os.environ.get("COORDINATOR_ADDRESS")
    if coord is None and "MASTER_ADDR" in os.environ:
        port = os.environ.get("MASTER_PORT", str(distributed_port))
        coord = f"{os.environ['MASTER_ADDR']}:{port}"

    backend = XLABackend()
    if world_size > 1:
        if verbose:
            log_dist(f"Initializing distributed: world_size={world_size} rank={rank} coordinator={coord}")
        backend.init_process_group(coordinator_address=coord, num_processes=world_size, process_id=rank)
    else:
        backend.init_process_group()
    cdb = backend


def get_world_size(group=None) -> int:
    import jax

    return jax.process_count()


def get_rank(group=None) -> int:
    import jax

    return jax.process_index()


def get_local_rank() -> int:
    return int(os.environ.get("LOCAL_RANK", "0"))


def destroy_process_group():
    global cdb
    if cdb is not None:
        cdb.destroy_process_group()
        cdb = None
