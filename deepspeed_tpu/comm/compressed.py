"""Compressed gradient collectives: block-scaled low-precision reduce.

The reference DeepSpeed spends most of its scaling budget on gradient
communication (ZeRO's reduce-scatter / allreduce over NCCL). EQuARX
(arXiv:2506.17615) shows a quantized allreduce inside XLA recovers 1.4-2x
collective throughput with negligible quality loss; this module is that idea
as a first-class layer over ``jax.lax`` collectives, generalizing the 1-bit
``runtime/comm/compressed.py`` precedent from sign-bits to block-scaled
int8 / fp8 (e4m3):

    quantize per block -> all_to_all low-precision -> dequantize+reduce
    -> requantize -> all_gather low-precision -> dequantize

Two-stage, like the reference's NcclBackend.compressed_allreduce (nccl.py:51):
rank r "serves" chunk r — it receives every rank's r-th chunk, reduces in
fp32, recompresses, and broadcasts the result. Wire volume per collective is
``n * 1 + (n/block) * 4`` bytes instead of ``4n`` (≈3.9x less at block 256).

Error feedback: quantization error is *returned to the caller* so it can be
carried into the next step (per-leaf residuals in ``TrainState.comm_error``)
— compensated compression preserves convergence where plain rounding biases
it (1-bit Adam lineage; same EF algebra, milder quantizer).

Bucketing: :func:`build_bucket_plan` packs gradient leaves into size-capped
flat buckets (``zero_optimization.reduce_bucket_size``), each reduced by an
INDEPENDENT collective — giving XLA's latency-hiding scheduler separate ops
to overlap with backward compute (T3, arXiv:2401.16677) instead of one fused
tree-allreduce that walls the step.

Accounting: every compressed collective records (logical fp32 bytes, actual
wire bytes) at trace time — into the module registry (:func:`records_by_axis`,
always on; the telemetry plane's source of truth) and into the shared
``CommsLogger`` when enabled (wire/ratio columns in ``log_summary``).
"""

from __future__ import annotations

from contextlib import contextmanager
from dataclasses import dataclass
from typing import Any, Dict, List, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

PyTree = Any

METHODS = ("int8", "fp8")

# quantization range per method: int8 symmetric [-127, 127]; fp8 e4m3 has
# max finite 448 (we scale amax onto it, mantissa rounding does the rest)
_INT8_QMAX = 127.0
_FP8_QMAX = 448.0


# ---------------------------------------------------------------------------
# block-scaled quantizers
# ---------------------------------------------------------------------------

def _quantize_exact(xb: jnp.ndarray, method: str) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Core block codec: ``[..., block]`` fp32 -> (payload, scale ``[..., 1]``).
    Scale = amax/qmax per block (zero blocks get scale 1 so the payload is
    exactly zero). The ONE place the scale/round/clip rule lives — the grad
    collectives, the weight quantizer (``ops/quantizer``) and the KV page
    codec all route here."""
    amax = jnp.max(jnp.abs(xb), axis=-1, keepdims=True)
    qmax = _INT8_QMAX if method == "int8" else _FP8_QMAX
    scale = jnp.where(amax > 0, amax / qmax, 1.0)
    y = xb / scale
    if method == "int8":
        q = jnp.clip(jnp.round(y), -_INT8_QMAX, _INT8_QMAX).astype(jnp.int8)
    else:
        q = y.astype(jnp.float8_e4m3fn)
    return q, scale


def quantize_blocks(x: jnp.ndarray, method: str = "int8", block: int = 256) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """fp32 ``[..., n]`` -> (payload ``[..., n]`` int8/fp8, scales
    ``[..., ceil(n/block)]`` fp32).

    When ``n % block == 0`` — every hot caller: the grad buckets are padded
    to the collective multiple, and KV pages are exact multiples by
    construction (``block = page * head_dim``) — this is a pure reshape, no
    copy. A trailing remainder is quantized as one short block with its own
    scale (head reshaped + tail sliced in place — never a padded copy of
    the whole array)."""
    if method not in METHODS:
        raise ValueError(f"unknown compression method {method!r}; use one of {METHODS}")
    n = x.shape[-1]
    rem = n % block
    if rem == 0:
        xb = x.reshape(x.shape[:-1] + (n // block, block)).astype(jnp.float32)
        q, scale = _quantize_exact(xb, method)
        return q.reshape(x.shape), scale.reshape(x.shape[:-1] + (n // block,))
    head = n - rem
    hb = x[..., :head].reshape(x.shape[:-1] + (head // block, block)).astype(jnp.float32)
    q_h, s_h = _quantize_exact(hb, method)
    q_t, s_t = _quantize_exact(x[..., head:].astype(jnp.float32), method)
    q = jnp.concatenate([q_h.reshape(x.shape[:-1] + (head,)), q_t], axis=-1)
    s = jnp.concatenate([s_h.reshape(x.shape[:-1] + (head // block,)),
                         s_t.reshape(x.shape[:-1] + (1,))], axis=-1)
    return q, s


def dequantize_blocks(payload: jnp.ndarray, scales: jnp.ndarray, block: int = 256) -> jnp.ndarray:
    """Inverse of :func:`quantize_blocks`: low-precision payload -> fp32.
    Mirrors its remainder handling (the tail is one short block)."""
    n = payload.shape[-1]
    rem = n % block
    if rem == 0:
        pb = payload.reshape(payload.shape[:-1] + (n // block, block)).astype(jnp.float32)
        out = pb * scales[..., None]
        return out.reshape(payload.shape)
    head = n - rem
    hb = payload[..., :head].reshape(
        payload.shape[:-1] + (head // block, block)
    ).astype(jnp.float32)
    out_h = (hb * scales[..., : head // block, None]).reshape(
        payload.shape[:-1] + (head,)
    )
    out_t = payload[..., head:].astype(jnp.float32) * scales[..., -1:]
    return jnp.concatenate([out_h, out_t], axis=-1)


def wire_bytes(n: int, method: str = "int8", block: int = 256) -> int:
    """Actual bytes on the wire for ``n`` compressed elements: 1-byte payload
    plus one fp32 scale per (possibly short trailing) block."""
    return n + (-(-n // block)) * 4


# ---------------------------------------------------------------------------
# trace-time compression accounting
# ---------------------------------------------------------------------------

# {(op, axis): {count, logical_bytes, wire_bytes}} — recorded at trace time
# (shapes are static under jit, so this is the exact per-compiled-step mix)
_records: Dict[Tuple[str, str], Dict[str, float]] = {}
_suspended = False


def _record_compressed(op: str, axis, logical: int, wire: int) -> None:
    if _suspended:
        return
    rec = _records.setdefault(
        (op, str(axis)), {"count": 0, "logical_bytes": 0, "wire_bytes": 0}
    )
    rec["count"] += 1
    rec["logical_bytes"] += logical
    rec["wire_bytes"] += wire
    # fold into the shared comms logger (wire/ratio columns) when enabled
    from .comm import comms_logger

    comms_logger.append(op, axis, logical, wire_bytes=wire)


@contextmanager
def suspend_records():
    """Silence trace-time recording while DELIBERATELY re-tracing an
    already-accounted program (the engine's comms accounting ``.lower()``) —
    otherwise every re-trace duplicates the compressed ops' rows in the
    shared CommsLogger and this registry."""
    global _suspended
    prev, _suspended = _suspended, True
    try:
        yield
    finally:
        _suspended = prev


def reset_records() -> None:
    _records.clear()


def records() -> Dict[Tuple[str, str], Dict[str, float]]:
    return {k: dict(v) for k, v in _records.items()}


def records_by_axis() -> Dict[str, Dict[str, float]]:
    """Per-axis {logical_bytes, wire_bytes, ratio} aggregate of everything
    recorded so far. NOTE: like the CommsLogger wrappers, records accrue on
    every trace — deliberately re-lowering the same program (bench's
    device-only loop, ``Compiled``-based accounting) inflates the absolute
    byte totals, though the ratio survives. The engine's per-step numbers
    (``_compression_stats``) are therefore derived analytically from the
    bucket plan instead of from this registry."""
    out: Dict[str, Dict[str, float]] = {}
    for (_, axis), rec in _records.items():
        agg = out.setdefault(axis, {"logical_bytes": 0, "wire_bytes": 0})
        agg["logical_bytes"] += rec["logical_bytes"]
        agg["wire_bytes"] += rec["wire_bytes"]
    for agg in out.values():
        agg["ratio"] = (
            agg["logical_bytes"] / agg["wire_bytes"] if agg["wire_bytes"] else 1.0
        )
    return out


# ---------------------------------------------------------------------------
# compressed collectives (call inside shard_map with the axis in scope)
# ---------------------------------------------------------------------------

def compressed_all_reduce(
    x: jnp.ndarray,
    axis_name: str,
    world: int,
    method: str = "int8",
    block: int = 256,
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Mean of ``x`` across ``axis_name`` with both transfer stages in low
    precision; returns ``(mean, residual)``.

    ``x``: ``[n]`` flat fp32, ``n % (world * block) == 0`` (caller pads —
    see :func:`build_bucket_plan`). ``residual`` is the local quantization
    error in units of ``x``: feed it back by adding it to next step's input
    (error-feedback / compensated compression). It is rank-divergent — carry
    it per-rank (e.g. a ``[world, ...]`` buffer sharded over the axis).
    """
    n = x.shape[0]
    assert n % world == 0 and (n // world) % block == 0, (n, world, block)
    chunk = n // world

    # -- stage A (reduce-scatter shape): quantize, route chunks to servers --
    q, s = quantize_blocks(x, method, block)
    local_deq = dequantize_blocks(q, s, block)
    worker_err = x - local_deq

    _record_compressed("all_to_all", axis_name, 4 * n, wire_bytes(n, method, block))
    q_r = lax.all_to_all(q.reshape(world, chunk), axis_name, split_axis=0, concat_axis=0, tiled=False)
    s_r = lax.all_to_all(
        s.reshape(world, chunk // block), axis_name, split_axis=0, concat_axis=0, tiled=False
    )

    # -- server side: dequantize every rank's contribution, reduce in fp32 --
    vals = dequantize_blocks(q_r, s_r, block)  # [world, chunk] fp32
    reduced = jnp.sum(vals, axis=0) / world  # [chunk] — the mean's r-th chunk

    # -- stage B (broadcast shape): recompress the served chunk, all-gather --
    q2, s2 = quantize_blocks(reduced, method, block)
    server_err = reduced - dequantize_blocks(q2, s2, block)
    _record_compressed("all_gather", axis_name, 4 * chunk, wire_bytes(chunk, method, block))
    all_q = lax.all_gather(q2, axis_name, axis=0, tiled=False)  # [world, chunk]
    all_s = lax.all_gather(s2, axis_name, axis=0, tiled=False)
    mean = dequantize_blocks(all_q, all_s, block).reshape(n)

    # residual: own worker error, plus the served chunk's stage-B error
    # scaled by world (next step's reduction divides by world, so carrying
    # world*e_B recovers e_B exactly once, on this rank)
    rank = lax.axis_index(axis_name)
    residual = worker_err + lax.dynamic_update_slice(
        jnp.zeros_like(x), world * server_err, (rank * chunk,)
    )
    return mean, residual


def compressed_reduce_scatter(
    x: jnp.ndarray,
    axis_name: str,
    world: int,
    method: str = "int8",
    block: int = 256,
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Stage A only: mean-reduce ``x`` across ``axis_name`` and keep this
    rank's ``[n/world]`` chunk (the ZeRO ``grad_spec`` reduce-scatter in low
    precision). Returns ``(chunk_mean, residual)`` with ``residual`` the
    full-length worker error (stage-B error does not exist here — the chunk
    stays fp32 on its owner)."""
    n = x.shape[0]
    assert n % world == 0 and (n // world) % block == 0, (n, world, block)
    chunk = n // world

    q, s = quantize_blocks(x, method, block)
    residual = x - dequantize_blocks(q, s, block)

    _record_compressed("all_to_all", axis_name, 4 * n, wire_bytes(n, method, block))
    q_r = lax.all_to_all(q.reshape(world, chunk), axis_name, split_axis=0, concat_axis=0, tiled=False)
    s_r = lax.all_to_all(
        s.reshape(world, chunk // block), axis_name, split_axis=0, concat_axis=0, tiled=False
    )
    vals = dequantize_blocks(q_r, s_r, block)
    return jnp.sum(vals, axis=0) / world, residual


def compressed_all_gather(
    x: jnp.ndarray,
    axis_name: str,
    world: int,
    method: str = "int8",
    block: int = 256,
) -> jnp.ndarray:
    """Low-precision all-gather (ISSUE 12): replicate every rank's ``[n]``
    shard across ``axis_name`` with the payload on the wire as int8/fp8 +
    per-block scales — the ZeRO-3 param all-gather's wire format
    (``runtime/zero/partitioning.gather_full_compressed``). Returns the
    gathered ``[world * n]`` fp32 array.

    Unlike the reduce collectives there is NO error-feedback residual: a
    gather is pure data movement, not an accumulating reduction — the
    quantization error is a one-shot, per-element bounded rounding (the
    round-trip tests pin it), and every rank dequantizes the SAME codes, so
    the gathered copy is bit-identical across ranks (the property a
    replicated param tree must keep).

    Ledger convention (module-wide, PR-2): logical bytes are
    fp32-NORMALIZED (4 per element) regardless of the source dtype —
    against a bf16 baseline the true reduction is ~half the recorded
    ratio."""
    n = x.shape[0]
    q, s = quantize_blocks(x.astype(jnp.float32), method, block)
    _record_compressed("all_gather", axis_name, 4 * n, wire_bytes(n, method, block))
    all_q = lax.all_gather(q, axis_name, axis=0, tiled=False)  # [world, n]
    all_s = lax.all_gather(s, axis_name, axis=0, tiled=False)
    return dequantize_blocks(all_q, all_s, block).reshape(world * n)


def compressed_all_to_all(
    x: jnp.ndarray,
    axis_name: str,
    world: int,
    method: str = "int8",
    block: int = 256,
) -> jnp.ndarray:
    """Low-precision all-to-all (ISSUE 12): rank r's chunk ``x[r]`` travels
    to rank r as int8/fp8 + per-chunk block scales — the MoE expert
    all-to-all's wire format (``moe/sharded_moe.moe_mlp_ep``). ``x`` is
    ``[world, chunk]``; returns the exchanged ``[world, chunk]`` fp32.

    Like the gather, this is pure data movement: no reduction, no error
    feedback — the parity tests bound the one-shot rounding against the
    uncompressed exchange. ``chunk`` need not divide ``block`` (the codec's
    trailing-remainder path covers ragged expert capacities). Logical
    bytes in the ledger are fp32-normalized, as everywhere in this
    module."""
    w, chunk = x.shape
    assert w == world, (w, world)
    q, s = quantize_blocks(x.astype(jnp.float32), method, block)
    _record_compressed(
        "all_to_all", axis_name, 4 * world * chunk,
        world * wire_bytes(chunk, method, block),
    )
    q_r = lax.all_to_all(q, axis_name, split_axis=0, concat_axis=0, tiled=False)
    s_r = lax.all_to_all(s, axis_name, split_axis=0, concat_axis=0, tiled=False)
    return dequantize_blocks(q_r, s_r, block)


# ---------------------------------------------------------------------------
# bucket plan: leaves -> size-capped flat buckets (independent collectives)
# ---------------------------------------------------------------------------

@dataclass(frozen=True)
class BucketPlan:
    """Static grouping of flat leaf sizes into size-capped buckets.

    ``entries[b]`` is a list of ``(leaf_index, offset, size)`` rows: leaf
    ``leaf_index`` occupies ``bucket[b][offset:offset+size]``. ``padded[b]``
    is the bucket length after rounding up to ``multiple`` (zero-padded —
    exact under sum reductions). Leaves are never split across buckets; a
    leaf larger than the cap gets a bucket of its own (the reference splits
    flat buffers instead; leaf-aligned buckets keep the unflatten free)."""

    entries: Tuple[Tuple[Tuple[int, int, int], ...], ...]
    padded: Tuple[int, ...]
    multiple: int
    cap_elems: int

    @property
    def num_buckets(self) -> int:
        return len(self.entries)


def build_bucket_plan(
    sizes: Sequence[int],
    bucket_bytes: int,
    itemsize: int = 4,
    multiple: int = 1,
) -> BucketPlan:
    """Greedily pack leaf sizes (in flatten order) into buckets of at most
    ``bucket_bytes`` (``zero_optimization.reduce_bucket_size`` semantics),
    each padded up to ``multiple`` elements (axis divisibility for the
    collective: ``world * block`` for compressed reduces, the dp size for
    flat-sharded constraints)."""
    cap_elems = max(1, int(bucket_bytes) // max(1, itemsize))
    buckets: List[List[Tuple[int, int, int]]] = []
    cur: List[Tuple[int, int, int]] = []
    cur_n = 0
    for i, size in enumerate(sizes):
        size = int(size)
        if cur and cur_n + size > cap_elems:
            buckets.append(cur)
            cur, cur_n = [], 0
        cur.append((i, cur_n, size))
        cur_n += size
    if cur:
        buckets.append(cur)
    padded = tuple(
        int(-(-sum(e[2] for e in b) // multiple) * multiple) for b in buckets
    )
    return BucketPlan(
        entries=tuple(tuple(b) for b in buckets),
        padded=padded,
        multiple=int(multiple),
        cap_elems=cap_elems,
    )


def flatten_to_buckets(leaves: Sequence[jnp.ndarray], plan: BucketPlan, dtype=None) -> List[jnp.ndarray]:
    """Leaves (flatten order) -> list of flat zero-padded bucket arrays."""
    out = []
    for rows, pad_n in zip(plan.entries, plan.padded):
        parts = [leaves[i].reshape(-1) for i, _, _ in rows]
        flat = jnp.concatenate(parts) if len(parts) > 1 else parts[0]
        if dtype is not None:
            flat = flat.astype(dtype)
        if flat.shape[0] < pad_n:
            flat = jnp.pad(flat, (0, pad_n - flat.shape[0]))
        out.append(flat)
    return out


def unflatten_from_buckets(
    buckets: Sequence[jnp.ndarray], plan: BucketPlan, shapes: Sequence[Tuple[int, ...]]
) -> List[jnp.ndarray]:
    """Inverse of :func:`flatten_to_buckets` (padding dropped)."""
    leaves: List[Any] = [None] * len(shapes)
    for flat, rows in zip(buckets, plan.entries):
        for i, off, size in rows:
            leaves[i] = flat[off:off + size].reshape(shapes[i])
    assert all(l is not None for l in leaves), "plan does not cover all leaves"
    return leaves


def leaf_sizes(tree: PyTree) -> List[int]:
    """Flat element counts of a pytree's leaves, in flatten order."""
    return [int(np.prod(l.shape)) if getattr(l, "shape", ()) else 1 for l in jax.tree.leaves(tree)]
