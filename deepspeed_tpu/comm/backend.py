"""Pluggable communication backend shell.

Analog of reference ``deepspeed/comm/backend.py`` (Backend ABC). The reference
ships only a TorchBackend (NCCL/Gloo/MPI); here the default — and primary —
backend is XLA collectives over ICI/DCN (``deepspeed_tpu/comm/xla.py``).
"""

from __future__ import annotations


class Backend:
    def __init__(self, name: str = "backend", rank: int = 0, size: int = 1):
        self.name = name
        self.world_group = None
        self.world_size = size
        self.world_rank = rank
        self.initialized = False

    def is_initialized(self) -> bool:
        return self.initialized

    def new_group(self, ranks):
        raise NotImplementedError

    def init_process_group(self):
        self.initialized = True

    def destroy_process_group(self):
        self.initialized = False
