"""XLA-collective backend: named-mesh-axis collectives over ICI/DCN.

TPU-native replacement for reference ``deepspeed/comm/torch.py`` (TorchBackend
→ torch.distributed → NCCL). Every primitive here is a thin, *traceable*
wrapper over ``jax.lax`` collectives and is meant to be called inside
``shard_map``/``pjit`` where a named mesh axis is in scope. XLA lowers them to
ICI (intra-slice) or DCN (cross-slice) collectives — the analog of NCCL ring
algorithms, chosen by the compiler instead of hand-tuned.

Primitive mapping (reference comm/comm.py op → here):

- all_reduce           → ``jax.lax.psum`` / ``pmean`` / ``pmax`` / ``pmin``
- all_gather(_base)    → ``jax.lax.all_gather``
- reduce_scatter(_base)→ ``jax.lax.psum_scatter``
- all_to_all_single    → ``jax.lax.all_to_all``
- broadcast            → gather-from-root trick over the axis
- send/recv (pipeline) → ``jax.lax.ppermute`` ring shifts
- barrier              → trivially a psum of a scalar (rarely needed; XLA
                         sequencing makes most barriers implicit)
"""

from __future__ import annotations

from typing import Optional, Sequence, Tuple, Union

import jax
import jax.numpy as jnp
from jax import lax

from .backend import Backend

AxisName = Union[str, Tuple[str, ...]]

REDUCE_OPS = {"sum", "mean", "max", "min", "prod"}


class XLABackend(Backend):
    """Process-level init + traceable collectives. Analog of TorchBackend."""

    def __init__(self):
        super().__init__(name="xla")

    def init_process_group(self, coordinator_address: Optional[str] = None, num_processes: Optional[int] = None, process_id: Optional[int] = None):
        # Multi-host: jax.distributed.initialize is the NCCL-rendezvous analog
        # (reference comm/comm.py:577 init_distributed). Single-host jobs skip it.
        if num_processes is not None and num_processes > 1:
            jax.distributed.initialize(
                coordinator_address=coordinator_address,
                num_processes=num_processes,
                process_id=process_id,
            )
        self.world_size = jax.process_count()
        self.world_rank = jax.process_index()
        self.initialized = True


# ---------------------------------------------------------------------------
# Traceable collectives (call inside shard_map / pjit with axis in scope)
# ---------------------------------------------------------------------------

def _record(op_name: str, axis, x) -> None:
    """Trace-time accounting hook → CommsLogger (reference timed_op decorator,
    comm/comm.py:111). Runs once per trace; shapes are static so the recorded
    op mix is the exact per-compiled-step traffic. Lazy import breaks the
    comm.py → xla.py cycle."""
    from .comm import record

    record(op_name, axis, x)


def all_reduce(x, axis: AxisName, op: str = "sum"):
    _record("all_reduce", axis, x)
    if op == "sum":
        return lax.psum(x, axis)
    if op == "mean":
        return lax.pmean(x, axis)
    if op == "max":
        return lax.pmax(x, axis)
    if op == "min":
        return lax.pmin(x, axis)
    if op == "prod":
        return jnp.exp(lax.psum(jnp.log(x), axis))
    raise ValueError(f"unknown reduce op {op}")


def all_gather(x, axis: AxisName, *, gather_dim: int = 0, tiled: bool = True):
    """Concatenate shards along ``gather_dim`` (reference all_gather_base)."""
    _record("all_gather", axis, x)
    return lax.all_gather(x, axis, axis=gather_dim, tiled=tiled)


def reduce_scatter(x, axis: AxisName, *, scatter_dim: int = 0, tiled: bool = True):
    """Sum across the axis then keep this rank's shard (reduce_scatter_base)."""
    _record("reduce_scatter", axis, x)
    return lax.psum_scatter(x, axis, scatter_dimension=scatter_dim, tiled=tiled)


def all_to_all(x, axis: AxisName, *, split_dim: int, concat_dim: int, tiled: bool = True):
    """MoE dispatch collective (reference all_to_all_single, comm/comm.py:355)."""
    _record("all_to_all", axis, x)
    return lax.all_to_all(x, axis, split_axis=split_dim, concat_axis=concat_dim, tiled=tiled)


def broadcast(x, axis: AxisName, root: int = 0):
    """Every rank gets root's value. Lowered as a one-hot psum (XLA optimizes
    to an actual broadcast); analog of reference broadcast (comm.py:424)."""
    _record("broadcast", axis, x)
    idx = lax.axis_index(axis)
    mask = (idx == root).astype(x.dtype)
    return lax.psum(x * mask, axis)


def ppermute(x, axis: AxisName, perm: Sequence[Tuple[int, int]]):
    """Point-to-point pattern; the pipeline send/recv analog (pipe/p2p.py)."""
    _record("ppermute", axis, x)
    return lax.ppermute(x, axis, perm=perm)


def ring_shift(x, axis: AxisName, shift: int = 1, axis_size: Optional[int] = None):
    """Shift values around the ring: rank i → rank (i+shift) % N."""
    n = axis_size if axis_size is not None else lax.axis_size(axis)
    perm = [(i, (i + shift) % n) for i in range(n)]
    return lax.ppermute(x, axis, perm=perm)


def axis_index(axis: AxisName):
    return lax.axis_index(axis)


def axis_size(axis: AxisName) -> int:
    return lax.axis_size(axis)


def barrier(axis: AxisName):
    """Explicit sync point. Mostly unnecessary under XLA (data dependencies
    order collectives), but kept for API parity (reference comm.py:456)."""
    return lax.psum(jnp.zeros((), jnp.int32), axis)
