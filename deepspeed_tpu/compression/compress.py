"""Compression orchestration over param trees.

Analog of reference ``deepspeed/compression/compress.py``
(init_compression:97, redundancy_clean:127): walk the model, attach
compression specs to matching modules, apply them on schedule. Here the
"module walk" is a path-pattern match over the param pytree, and
``apply_compression`` returns a new tree (masks and/or fake-quantized
weights) — pure-functional, jit-compatible.

Config shape (reference ``compression_training`` section vocabulary):
    {
      "weight_quantization": {"enabled": true, "bits": 8, "modules": ["attn", "mlp"], "start_step": 100},
      "embedding_quantization": {"enabled": true, "bits": 2, "modules": ["wte"], "start_step": 0},
      "sparse_pruning":      {"enabled": true, "ratio": 0.5, "modules": ["mlp"], "start_step": 200},
      "row_pruning":         {"enabled": false, "ratio": 0.25, "modules": [...]},
      "head_pruning":        {"enabled": false, "ratio": 0.25, "num_heads": 12, "modules": [...]},
      "channel_pruning":     {"enabled": false, "ratio": 0.25, "modules": ["conv"]}
    }

``embedding_quantization`` is the reference's weight-quantization group
targeting Embedding modules (Embedding_Compress, basic_layer.py:61 —
token-wise scales, ternary/binary capable); ``channel_pruning`` is the conv
variant (Conv2dLayer_Compress:444). TP composition needs no special classes
(reference Column/RowParallelLinear_Compress, basic_layer.py:834,877):
these transforms act on the logically-global arrays, and the logical-axis
sharding annotations carry through masking/fake-quant untouched — proven by
the tp-mesh compression test.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp

from .basic_layer import (
    channel_pruning_mask,
    head_pruning_mask,
    quantize_embedding_ste,
    quantize_weight_ste,
    row_pruning_mask,
    sparse_pruning_mask,
)

PyTree = Any


def _leaf_paths(tree: PyTree) -> List[Tuple[str, Any]]:
    from ..utils.pytree import leaf_paths

    return leaf_paths(tree)


def _matches(path: str, modules: List[str]) -> bool:
    return any(m in path for m in modules) if modules else True


@dataclass
class CompressionScheduler:
    """Tracks which techniques are active at a given step (reference
    compression/scheduler.py)."""

    config: Dict[str, Any] = field(default_factory=dict)

    def active(self, technique: str, step: int) -> bool:
        t = self.config.get(technique, {})
        if not t.get("enabled", False):
            return False
        return step >= int(t.get("start_step", 0)) and (
            "end_step" not in t or step < int(t["end_step"])
        )


def init_compression(params: PyTree, config: Dict[str, Any]) -> Dict[str, PyTree]:
    """Precompute pruning masks from the current weights.

    Returns {"sparse": mask_tree, "row": ..., "head": ...} with None where a
    technique is disabled; masks are static once computed (reference
    fix_compression semantics)."""
    masks: Dict[str, Optional[PyTree]] = {}

    def build(technique, fn, ndim_ok=lambda n: n >= 2):
        t = config.get(technique, {})
        if not t.get("enabled", False):
            return None
        modules = t.get("modules", [])

        def visit(path, leaf):
            if hasattr(leaf, "ndim") and ndim_ok(leaf.ndim) and _matches(path, modules):
                return fn(leaf, t)
            return None

        flat = [(p, l) for p, l in _leaf_paths(params)]
        return {p: visit(p, l) for p, l in flat}

    masks["sparse"] = build("sparse_pruning", lambda w, t: sparse_pruning_mask(w, float(t.get("ratio", 0.5))))
    masks["row"] = build("row_pruning", lambda w, t: row_pruning_mask(w, float(t.get("ratio", 0.25))))
    masks["head"] = build(
        "head_pruning",
        lambda w, t: head_pruning_mask(w, float(t.get("ratio", 0.25)), int(t.get("num_heads", 12))),
    )
    # conv channels: only 4D (HWIO) leaves qualify
    masks["channel"] = build(
        "channel_pruning",
        lambda w, t: channel_pruning_mask(w, float(t.get("ratio", 0.25))),
        ndim_ok=lambda n: n == 4,
    )
    return masks


def apply_compression(
    params: PyTree,
    config: Dict[str, Any],
    masks: Optional[Dict[str, PyTree]] = None,
    step: int = 0,
) -> PyTree:
    """Return the compressed view of ``params`` for this step (QAT forward /
    redundancy_clean when all techniques are past start_step)."""
    sched = CompressionScheduler(config)
    flat = _leaf_paths(params)
    q = config.get("weight_quantization", {})
    q_on = sched.active("weight_quantization", step)
    eq = config.get("embedding_quantization", {})
    eq_on = sched.active("embedding_quantization", step)
    if eq_on and not eq.get("modules"):
        # an empty pattern would claim EVERY 2D weight (shadowing
        # weight_quantization on attn/mlp); embeddings must be named
        raise ValueError(
            "embedding_quantization requires explicit 'modules' patterns "
            "naming the embedding tables (e.g. [\"wte\"])"
        )
    # rounding: "nearest" (default) | "stochastic" — the reference's
    # WEIGHT_QUANTIZE_ROUNDING knob (compression/constants.py:60). SR keys
    # derive from (step, leaf index): fresh noise per step (unbiased across
    # steps), bit-reproducible on same-step replay (checkpoint resume).
    rounding = str(q.get("rounding", "nearest"))
    if rounding not in ("nearest", "stochastic"):
        raise ValueError(
            f"weight_quantization.rounding must be 'nearest' or 'stochastic', got {rounding!r}"
        )
    sr_base = jax.random.PRNGKey(step) if rounding == "stochastic" else None
    out = {}
    for path, leaf in flat:
        w = leaf
        if masks:
            for kind in ("sparse", "row", "head", "channel"):
                tech = {
                    "sparse": "sparse_pruning",
                    "row": "row_pruning",
                    "head": "head_pruning",
                    "channel": "channel_pruning",
                }[kind]
                mtree = masks.get(kind)
                if mtree and mtree.get(path) is not None and sched.active(tech, step):
                    w = w * mtree[path].astype(w.dtype)
        if (
            eq_on
            and hasattr(w, "ndim")
            and w.ndim == 2
            and _matches(path, eq.get("modules", []))
        ):
            # embedding tables: token-wise scales, ternary/binary capable
            w = quantize_embedding_ste(
                w, int(eq.get("bits", 8)), bool(eq.get("symmetric", True))
            )
        elif q_on and hasattr(w, "ndim") and w.ndim >= 2 and _matches(path, q.get("modules", [])):
            key = (
                jax.random.fold_in(sr_base, len(out)) if sr_base is not None else None
            )
            w = quantize_weight_ste(
                w, int(q.get("bits", 8)), bool(q.get("symmetric", True)), key=key
            )
        out[path] = w
    # rebuild tree
    leaves_in_order = [out[p] for p, _ in flat]
    treedef = jax.tree.structure(params)
    return jax.tree.unflatten(treedef, leaves_in_order)


def redundancy_clean(params: PyTree, config: Dict[str, Any], masks: Dict[str, PyTree]) -> PyTree:
    """Bake all compression permanently into the weights (reference
    redundancy_clean:127): final masked+quantized tree for export.

    Always rounds to NEAREST: SR is a training-time de-biasing device; the
    exported weights must be the deterministic grid values inference
    expects, not a one-shot random draw."""
    if config.get("weight_quantization", {}).get("rounding") == "stochastic":
        config = dict(config)
        config["weight_quantization"] = dict(config["weight_quantization"], rounding="nearest")
    return apply_compression(params, config, masks, step=10**12)


def compression_scheduler_from_config(ds_config):
    """Build a CompressionScheduler from a DeepSpeed config document
    (reference compression/scheduler.py entry)."""
    return CompressionScheduler(config=ds_config.get("compression_training", {}))


def shrink_row_pruned(w, b, w_next, row_mask):
    """Physically remove pruned output rows (reference redundancy_clean's
    structural shrink: a row-pruned Linear drops rows AND the consumer layer
    drops the matching input columns, yielding genuinely smaller matmuls
    rather than zero-masked ones).

    Args:
      w:        [in, out] weight whose OUTPUT features were row-pruned.
      b:        [out] bias or None.
      w_next:   [out, anything] consumer weight, or None.
      row_mask: [out] bool keep-mask (from row_pruning_mask, reduced over in).
    Returns (w_small, b_small, w_next_small) with out' = mask.sum() columns.
    """
    import numpy as np

    keep = np.asarray(row_mask).nonzero()[0]
    w_small = jnp.take(w, keep, axis=-1)
    b_small = jnp.take(b, keep, axis=-1) if b is not None else None
    w_next_small = jnp.take(w_next, keep, axis=0) if w_next is not None else None
    return w_small, b_small, w_next_small
