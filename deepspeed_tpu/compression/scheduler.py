"""Compression schedule helper (reference compression/scheduler.py)."""

from __future__ import annotations

from typing import Any, Dict

from .compress import CompressionScheduler


def compression_scheduler_from_config(ds_config: Dict[str, Any]) -> CompressionScheduler:
    return CompressionScheduler(config=ds_config.get("compression_training", {}))
