from .basic_layer import (
    channel_pruning_mask,
    head_pruning_mask,
    quantize_activation_ste,
    quantize_embedding_ste,
    quantize_weight_ste,
    row_pruning_mask,
    sparse_pruning_mask,
)
from .compress import (
    CompressionScheduler,
    apply_compression,
    compression_scheduler_from_config,
    init_compression,
    redundancy_clean,
    shrink_row_pruned,
)

__all__ = [
    "CompressionScheduler",
    "apply_compression",
    "channel_pruning_mask",
    "compression_scheduler_from_config",
    "head_pruning_mask",
    "init_compression",
    "quantize_activation_ste",
    "quantize_embedding_ste",
    "quantize_weight_ste",
    "redundancy_clean",
    "row_pruning_mask",
    "shrink_row_pruned",
    "sparse_pruning_mask",
]
