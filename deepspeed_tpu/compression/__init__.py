from .basic_layer import (
    head_pruning_mask,
    quantize_weight_ste,
    row_pruning_mask,
    sparse_pruning_mask,
)
from .compress import CompressionScheduler, apply_compression, init_compression
from .compress import compression_scheduler_from_config

__all__ = [
    "CompressionScheduler",
    "apply_compression",
    "compression_scheduler_from_config",
    "head_pruning_mask",
    "init_compression",
    "quantize_weight_ste",
    "row_pruning_mask",
    "sparse_pruning_mask",
]
