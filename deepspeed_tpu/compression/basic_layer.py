"""Compression primitives: QAT quantization + pruning masks.

Analog of reference ``deepspeed/compression/basic_layer.py`` (2483-LoC
package: LinearLayer_Compress:134 with weight/activation quantization and
sparse/row/head pruning, plus Column/RowParallelLinear_Compress variants).
The reference subclasses nn.Linear and mutates weights through hooks; here
the primitives are pure functions applied inside the model's forward (QAT
with straight-through gradients) or to the param tree (mask application), so
they compose with jit/pjit — the TP-parallel variants need no special
classes because sharding comes from the logical-axis annotations.
"""

from __future__ import annotations

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp


def quantize_weight_ste(w: jnp.ndarray, bits: int = 8, symmetric: bool = True,
                        key=None) -> jnp.ndarray:
    """Fake-quantize with a straight-through estimator (QAT forward).

    Reference LinearLayer_Compress weight quantization; gradients pass
    through unchanged (STE), so the training loop needs no changes.
    ``key`` engages unbiased stochastic rounding (the reference's
    quantizer.cu:1037 SR path — at 4-6 bits RTN bias visibly skews MoQ
    training; SR keeps E[q(w)] == w). The SR path can't ride the
    custom_vjp (a traced key is not a static nondiff arg), so it uses the
    equivalent stop-gradient STE identity.
    """
    if key is None:
        return _quantize_weight_rtn(w, bits, symmetric)
    return w + jax.lax.stop_gradient(_fake_quant(w, bits, symmetric, key=key) - w)


@partial(jax.custom_vjp, nondiff_argnums=(1, 2))
def _quantize_weight_rtn(w: jnp.ndarray, bits: int = 8, symmetric: bool = True) -> jnp.ndarray:
    return _fake_quant(w, bits, symmetric)


def _round(x, key):
    if key is None:
        return jnp.round(x)
    import jax

    return jnp.floor(x + jax.random.uniform(key, x.shape, x.dtype))


def _fake_quant(w, bits, symmetric, axis=None, key=None):
    """Shared fake-quant math; ``axis`` selects per-row (dynamic per-token)
    vs whole-tensor scales; ``key`` selects stochastic rounding."""
    kd = axis is not None
    qmax = 2.0 ** (bits - 1) - 1
    if symmetric:
        scale = jnp.maximum(jnp.max(jnp.abs(w), axis=axis, keepdims=kd), 1e-8) / qmax
        return jnp.clip(_round(w / scale, key), -qmax - 1, qmax) * scale
    lo = jnp.min(w, axis=axis, keepdims=kd)
    hi = jnp.max(w, axis=axis, keepdims=kd)
    scale = jnp.maximum(hi - lo, 1e-8) / (2.0**bits - 1)
    zp = jnp.round(-lo / scale)
    return (jnp.clip(_round(w / scale, key) + zp, 0, 2.0**bits - 1) - zp) * scale


def _qw_fwd(w, bits, symmetric):
    return _fake_quant(w, bits, symmetric), None


def _qw_bwd(bits, symmetric, _res, g):
    return (g,)  # straight-through


_quantize_weight_rtn.defvjp(_qw_fwd, _qw_bwd)


def sparse_pruning_mask(w: jnp.ndarray, ratio: float, method: str = "l1") -> jnp.ndarray:
    """Unstructured mask keeping the top-(1-ratio) weights by |magnitude|
    (reference sparse_pruning, method l1/topk)."""
    if ratio <= 0.0:
        return jnp.ones_like(w, dtype=bool)
    scores = jnp.abs(w).reshape(-1)
    k = int(scores.size * ratio)
    if k <= 0:
        return jnp.ones_like(w, dtype=bool)
    thresh = jnp.sort(scores)[k - 1]
    return (jnp.abs(w) > thresh).reshape(w.shape)


def row_pruning_mask(w: jnp.ndarray, ratio: float) -> jnp.ndarray:
    """Structured mask zeroing the lowest-L1 output rows (reference
    row_pruning; w is [in, out] so 'rows' = output columns here)."""
    if ratio <= 0.0:
        return jnp.ones_like(w, dtype=bool)
    norms = jnp.sum(jnp.abs(w), axis=0)  # per output feature
    k = int(norms.size * ratio)
    if k <= 0:
        return jnp.ones_like(w, dtype=bool)
    thresh = jnp.sort(norms)[k - 1]
    return jnp.broadcast_to((norms > thresh)[None, :], w.shape)


def head_pruning_mask(w: jnp.ndarray, ratio: float, num_heads: int) -> jnp.ndarray:
    """Structured mask zeroing whole attention heads of an output-projection
    weight [E(heads*dim), E] by per-head L1 (reference head_pruning)."""
    if ratio <= 0.0:
        return jnp.ones_like(w, dtype=bool)
    E_in = w.shape[0]
    head_dim = E_in // num_heads
    per_head = jnp.sum(jnp.abs(w.reshape(num_heads, head_dim, -1)), axis=(1, 2))
    k = int(num_heads * ratio)
    if k <= 0:
        return jnp.ones_like(w, dtype=bool)
    thresh = jnp.sort(per_head)[k - 1]
    keep = per_head > thresh  # [H]
    mask = jnp.broadcast_to(keep[:, None, None], (num_heads, head_dim, w.shape[1]))
    return mask.reshape(w.shape)


def channel_pruning_mask(w: jnp.ndarray, ratio: float) -> jnp.ndarray:
    """Structured mask zeroing the lowest-L1 output CHANNELS of a conv
    weight (reference channel_pruning, constants.py:155; Conv2dLayer_Compress
    basic_layer.py:444). JAX conv kernels are [kH, kW, in_ch, out_ch] (HWIO):
    the channel dim is the last one, scored by L1 over all other axes."""
    if ratio <= 0.0:
        return jnp.ones_like(w, dtype=bool)
    axes = tuple(range(w.ndim - 1))
    norms = jnp.sum(jnp.abs(w), axis=axes)  # [out_ch]
    k = int(norms.size * ratio)
    if k <= 0:
        return jnp.ones_like(w, dtype=bool)
    thresh = jnp.sort(norms)[k - 1]
    keep = norms > thresh
    return jnp.broadcast_to(keep, w.shape)


def _quant_embedding(w, bits, symmetric):
    """Token-wise (per-row) embedding quantization down to ternary/binary
    (reference Embedding_Compress.enable_weight_quantization,
    basic_layer.py:76-101: num_groups = vocab size, i.e. one scale per row;
    bits==2 ternary and bits==1 binary are symmetric-only)."""
    # checked here (shared by the primal AND the vjp fwd) so the invariant
    # fires on the first training step, not at export time; a real raise, not
    # an assert, so python -O launchers can't strip it
    if bits < 3 and not symmetric:
        raise ValueError("ternary/binary quantization is symmetric-only")
    if bits >= 3:
        return _fake_quant(w, bits, symmetric, axis=-1)
    absw = jnp.abs(w)
    if bits == 2:  # ternary: {-a, 0, +a} with delta = 0.7 * mean|w| per row
        delta = 0.7 * jnp.mean(absw, axis=-1, keepdims=True)
        mask = absw > delta
        alpha = jnp.sum(absw * mask, axis=-1, keepdims=True) / jnp.maximum(
            jnp.sum(mask, axis=-1, keepdims=True), 1
        )
        return jnp.sign(w) * mask * alpha
    # binary: sign(w) * mean|w| per row
    alpha = jnp.mean(absw, axis=-1, keepdims=True)
    return jnp.sign(w) * alpha


@partial(jax.custom_vjp, nondiff_argnums=(1, 2))
def quantize_embedding_ste(w: jnp.ndarray, bits: int = 8, symmetric: bool = True) -> jnp.ndarray:
    """Fake-quantize an embedding table token-wise with a straight-through
    estimator. Supports 8..3-bit (sym/asym), 2-bit ternary, 1-bit binary —
    the reference Embedding_Compress technique ladder (basic_layer.py:61)."""
    return _quant_embedding(w, bits, symmetric)


def _qe_fwd(w, bits, symmetric):
    return _quant_embedding(w, bits, symmetric), None


def _qe_bwd(bits, symmetric, _res, g):
    return (g,)  # straight-through


quantize_embedding_ste.defvjp(_qe_fwd, _qe_bwd)


@partial(jax.custom_vjp, nondiff_argnums=(1, 2, 3))
def quantize_activation_ste(
    x: jnp.ndarray, bits: int = 8, symmetric: bool = True, per_token: bool = True
) -> jnp.ndarray:
    """Fake-quantize activations with a straight-through estimator.

    Reference LinearLayer_Compress activation quantization (dynamic range per
    token row, basic_layer.py activation_quantization branch). ``per_token``
    computes the scale over the last dim per row — the reference's dynamic
    per-token mode; otherwise one scale for the whole tensor.
    """
    return _fake_quant_act(x, bits, symmetric, per_token)


def _fake_quant_act(x, bits, symmetric, per_token):
    return _fake_quant(x, bits, symmetric, axis=-1 if per_token else None)


def _qa_fwd(x, bits, symmetric, per_token):
    return _fake_quant_act(x, bits, symmetric, per_token), None


def _qa_bwd(bits, symmetric, per_token, _res, g):
    return (g,)  # straight-through


quantize_activation_ste.defvjp(_qa_fwd, _qa_bwd)
