"""Compression primitives: QAT quantization + pruning masks.

Analog of reference ``deepspeed/compression/basic_layer.py`` (2483-LoC
package: LinearLayer_Compress:134 with weight/activation quantization and
sparse/row/head pruning, plus Column/RowParallelLinear_Compress variants).
The reference subclasses nn.Linear and mutates weights through hooks; here
the primitives are pure functions applied inside the model's forward (QAT
with straight-through gradients) or to the param tree (mask application), so
they compose with jit/pjit — the TP-parallel variants need no special
classes because sharding comes from the logical-axis annotations.
"""

from __future__ import annotations

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp


@partial(jax.custom_vjp, nondiff_argnums=(1, 2))
def quantize_weight_ste(w: jnp.ndarray, bits: int = 8, symmetric: bool = True) -> jnp.ndarray:
    """Fake-quantize with a straight-through estimator (QAT forward).

    Reference LinearLayer_Compress weight quantization; gradients pass
    through unchanged (STE), so the training loop needs no changes.
    """
    return _fake_quant(w, bits, symmetric)


def _fake_quant(w, bits, symmetric, axis=None):
    """Shared fake-quant math; ``axis`` selects per-row (dynamic per-token)
    vs whole-tensor scales."""
    kd = axis is not None
    qmax = 2.0 ** (bits - 1) - 1
    if symmetric:
        scale = jnp.maximum(jnp.max(jnp.abs(w), axis=axis, keepdims=kd), 1e-8) / qmax
        return jnp.round(w / scale) * scale
    lo = jnp.min(w, axis=axis, keepdims=kd)
    hi = jnp.max(w, axis=axis, keepdims=kd)
    scale = jnp.maximum(hi - lo, 1e-8) / (2.0**bits - 1)
    zp = jnp.round(-lo / scale)
    return (jnp.clip(jnp.round(w / scale) + zp, 0, 2.0**bits - 1) - zp) * scale


def _qw_fwd(w, bits, symmetric):
    return _fake_quant(w, bits, symmetric), None


def _qw_bwd(bits, symmetric, _res, g):
    return (g,)  # straight-through


quantize_weight_ste.defvjp(_qw_fwd, _qw_bwd)


def sparse_pruning_mask(w: jnp.ndarray, ratio: float, method: str = "l1") -> jnp.ndarray:
    """Unstructured mask keeping the top-(1-ratio) weights by |magnitude|
    (reference sparse_pruning, method l1/topk)."""
    if ratio <= 0.0:
        return jnp.ones_like(w, dtype=bool)
    scores = jnp.abs(w).reshape(-1)
    k = int(scores.size * ratio)
    if k <= 0:
        return jnp.ones_like(w, dtype=bool)
    thresh = jnp.sort(scores)[k - 1]
    return (jnp.abs(w) > thresh).reshape(w.shape)


def row_pruning_mask(w: jnp.ndarray, ratio: float) -> jnp.ndarray:
    """Structured mask zeroing the lowest-L1 output rows (reference
    row_pruning; w is [in, out] so 'rows' = output columns here)."""
    if ratio <= 0.0:
        return jnp.ones_like(w, dtype=bool)
    norms = jnp.sum(jnp.abs(w), axis=0)  # per output feature
    k = int(norms.size * ratio)
    if k <= 0:
        return jnp.ones_like(w, dtype=bool)
    thresh = jnp.sort(norms)[k - 1]
    return jnp.broadcast_to((norms > thresh)[None, :], w.shape)


def head_pruning_mask(w: jnp.ndarray, ratio: float, num_heads: int) -> jnp.ndarray:
    """Structured mask zeroing whole attention heads of an output-projection
    weight [E(heads*dim), E] by per-head L1 (reference head_pruning)."""
    if ratio <= 0.0:
        return jnp.ones_like(w, dtype=bool)
    E_in = w.shape[0]
    head_dim = E_in // num_heads
    per_head = jnp.sum(jnp.abs(w.reshape(num_heads, head_dim, -1)), axis=(1, 2))
    k = int(num_heads * ratio)
    if k <= 0:
        return jnp.ones_like(w, dtype=bool)
    thresh = jnp.sort(per_head)[k - 1]
    keep = per_head > thresh  # [H]
    mask = jnp.broadcast_to(keep[:, None, None], (num_heads, head_dim, w.shape[1]))
    return mask.reshape(w.shape)


@partial(jax.custom_vjp, nondiff_argnums=(1, 2, 3))
def quantize_activation_ste(
    x: jnp.ndarray, bits: int = 8, symmetric: bool = True, per_token: bool = True
) -> jnp.ndarray:
    """Fake-quantize activations with a straight-through estimator.

    Reference LinearLayer_Compress activation quantization (dynamic range per
    token row, basic_layer.py activation_quantization branch). ``per_token``
    computes the scale over the last dim per row — the reference's dynamic
    per-token mode; otherwise one scale for the whole tensor.
    """
    return _fake_quant_act(x, bits, symmetric, per_token)


def _fake_quant_act(x, bits, symmetric, per_token):
    return _fake_quant(x, bits, symmetric, axis=-1 if per_token else None)


def _qa_fwd(x, bits, symmetric, per_token):
    return _fake_quant_act(x, bits, symmetric, per_token), None


def _qa_bwd(bits, symmetric, per_token, _res, g):
    return (g,)  # straight-through


quantize_activation_ste.defvjp(_qa_fwd, _qa_bwd)
