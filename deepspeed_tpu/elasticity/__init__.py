from .elasticity import (
    ElasticityConfigError,
    ElasticityError,
    compute_elastic_config,
    get_compatible_gpus,
)
from .elastic_agent import (
    DeviceMonitor,
    ElasticAgent,
    choose_compatible_world_size,
    make_progress_probe,
    resize_restart,
)

__all__ = [
    "DeviceMonitor",
    "ElasticAgent",
    "choose_compatible_world_size",
    "ElasticityConfigError",
    "ElasticityError",
    "compute_elastic_config",
    "get_compatible_gpus",
    "make_progress_probe",
    "resize_restart",
]
