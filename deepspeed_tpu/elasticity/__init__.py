from .elasticity import (
    ElasticityConfigError,
    ElasticityError,
    compute_elastic_config,
    get_compatible_gpus,
)
from .elastic_agent import ElasticAgent

__all__ = [
    "ElasticAgent",
    "ElasticityConfigError",
    "ElasticityError",
    "compute_elastic_config",
    "get_compatible_gpus",
]
