from .elasticity import (
    ElasticityConfigError,
    ElasticityError,
    compute_elastic_config,
    get_compatible_gpus,
)
from .elastic_agent import ElasticAgent, resize_restart

__all__ = [
    "ElasticAgent",
    "ElasticityConfigError",
    "ElasticityError",
    "compute_elastic_config",
    "get_compatible_gpus",
    "resize_restart",
]
