"""Preemption-aware checkpointing for TPU slices.

SURVEY §5 failure-detection analog: the reference's launcher watches child
processes and kills the tree on failure (launcher/launch.py:109,284) and
recovery is restart-from-checkpoint. On Cloud TPU the failure signal ARRIVES
IN-PROCESS: maintenance events / spot reclaims deliver SIGTERM with a grace
window. :class:`PreemptionGuard` turns that into a clean
checkpoint-then-exit at the next step boundary — the jitted step itself is
never interrupted mid-dispatch.

Usage::

    with PreemptionGuard(engine, save_dir) as guard:   # installs handlers
        for batch in loader:
            engine.train_batch(batch)
            if guard.should_stop():                    # signal seen?
                guard.checkpoint_and_log()             # save + grace flush
                break
    # handlers restored on exit — no leak across tests / callers

or the engine-integrated form: ``initialize(...)`` callers poll
``engine.preempted`` when a guard is attached.

Resilience semantics (ISSUE 7):

- ``checkpoint_and_log`` flushes any in-flight *async* checkpoint write
  inside ``grace_window_s`` (``resilience.grace_window_s`` when the engine
  carries a resilience config); an overrun forces a fresh BLOCKING snapshot
  under ``<tag>-final`` so the process never exits with only a torn write
  on disk.
- a SECOND termination signal while the final save is running escalates to
  immediate exit (flushed log line, exit code 128+signum) instead of
  re-entering the save — the platform is done waiting; re-entering would
  corrupt the write it interrupts.
"""

from __future__ import annotations

import os
import signal
import threading
from typing import Optional

from ..utils.logging import log_dist

# SIGTERM is what TPU maintenance/reclaim delivers. SIGINT is NOT a default:
# its prior handler raises KeyboardInterrupt, which would unwind the loop
# before the step-boundary checkpoint this class exists for.
_DEFAULT_SIGNALS = ("SIGTERM",)


class PreemptionGuard:
    """Installs signal handlers that request a graceful stop.

    Handlers chain to any previously installed handler (the launcher's
    tree-kill propagation still works). Thread-safe: the flag is a simple
    event set from the signal context. Usable as a context manager —
    ``__exit__`` uninstalls, so handler chains don't leak across tests.
    """

    def __init__(
        self,
        engine=None,
        save_dir: Optional[str] = None,
        signals=_DEFAULT_SIGNALS,
        install: bool = True,
        grace_window_s: Optional[float] = None,
    ):
        self.engine = engine
        self.save_dir = save_dir
        self._stop = threading.Event()
        self._prev = {}
        self._signals = []
        self._in_final_save = False
        # injectable for tests: escalation must really exit in production
        # (os._exit — a raise from a signal frame could be swallowed), but a
        # test asserting the escalation can't survive that
        self._exit = os._exit
        if grace_window_s is None:
            rcfg = getattr(getattr(engine, "config", None), "resilience", None)
            grace_window_s = float(getattr(rcfg, "grace_window_s", 30.0))
        self.grace_window_s = float(grace_window_s)
        if install:
            self.install(signals)
        if engine is not None:
            # engine.preempted polls this guard (DeepSpeedEngine property)
            engine._preemption_guard = self

    def __enter__(self) -> "PreemptionGuard":
        return self

    def __exit__(self, exc_type, exc, tb) -> bool:
        self.uninstall()
        return False

    def install(self, signals=_DEFAULT_SIGNALS) -> None:
        for name in signals:
            sig = getattr(signal, name, None)
            if sig is None:
                continue
            if signal.getsignal(sig) == self._handler:
                # already armed — re-storing would self-chain (== not `is`:
                # each self._handler access builds a fresh bound method)
                continue
            try:
                self._prev[sig] = signal.signal(sig, self._handler)
                self._signals.append(sig)
            except (ValueError, OSError):  # non-main thread / unsupported
                continue

    def uninstall(self) -> None:
        for sig in self._signals:
            try:
                signal.signal(sig, self._prev.get(sig) or signal.SIG_DFL)
            except (ValueError, OSError):
                pass
        self._signals.clear()
        if self.engine is not None and getattr(self.engine, "_preemption_guard", None) is self:
            self.engine._preemption_guard = None

    def _handler(self, signum, frame):
        name = signal.Signals(signum).name
        if self._stop.is_set() and self._in_final_save:
            # double-signal during the final save: the platform's grace
            # window is over. Re-entering the save would corrupt the write
            # it interrupts — flush one log line and go. The committed (or
            # walked-back) previous tag is the recovery point.
            # logging from a handler is formally signal-unsafe, but these
            # are the process's deliberate last words before _exit: CPython
            # delivers signals between bytecodes on the main thread, and a
            # rare deadlocked log here loses nothing — the exit was already
            # the outcome. Waived, not allowlisted, so new handlers still
            # get checked.
            log_dist(  # dslint: disable=signal-unsafe-handler
                f"second {name} during preemption checkpoint — exiting "
                "immediately (previous committed tag is the recovery point)"
            )
            self._flush_logs()  # dslint: disable=signal-unsafe-handler
            self._exit(128 + signum)
            return  # only reached when _exit is stubbed (tests)
        self._stop.set()
        # same deliberate last-words waiver as above: the graceful path sets
        # only the Event flag for correctness; the log line is operator UX
        log_dist(  # dslint: disable=signal-unsafe-handler
            f"preemption signal {name} received — "
            "will checkpoint at the next step boundary"
        )
        # dict.get allocates nothing and touches handler-local state only
        prev = self._prev.get(signum)  # dslint: disable=signal-unsafe-handler
        # chain, except to handlers that raise (default SIGINT raises
        # KeyboardInterrupt — that would defeat the graceful checkpoint).
        # Chaining an arbitrary prev handler is unverifiable by the rule;
        # it preserves the launcher's tree-kill semantics by contract.
        if callable(prev) and prev is not signal.default_int_handler:
            prev(signum, frame)  # dslint: disable=signal-unsafe-handler

    @staticmethod
    def _flush_logs() -> None:
        import logging
        import sys

        for h in logging.getLogger().handlers + logging.getLogger("deepspeed_tpu").handlers:
            try:
                h.flush()
            except Exception:
                pass
        try:
            sys.stderr.flush()
            sys.stdout.flush()
        except Exception:
            pass

    def request_stop(self) -> None:
        """Programmatic trigger (tests; cooperative shutdown)."""
        self._stop.set()

    def should_stop(self) -> bool:
        return self._stop.is_set()

    def checkpoint_and_log(self, tag: Optional[str] = None) -> Optional[str]:
        """Save via the attached engine (no-op without one), then flush any
        in-flight async write inside the grace window; an overrun forces a
        fresh BLOCKING save under ``<tag>-final``. Returns the path."""
        if self.engine is None or self.save_dir is None:
            return None
        self._in_final_save = True
        try:
            path = self.engine.save_checkpoint(self.save_dir, tag=tag)
            flush = getattr(self.engine, "flush_checkpoints", None)
            flushed = flush(timeout=self.grace_window_s) if callable(flush) else True
            # `flushed` only proves the queue drained — a write that DIED
            # also drains. The committed tag directory exists iff the
            # atomic rename happened (a torn write leaves only <tag>.tmp),
            # so probe the path before trusting the async save.
            if not flushed or not os.path.isdir(str(path)):
                log_dist(
                    "async checkpoint did not commit "
                    + ("within the grace window" if not flushed else "(write failed)")
                    + " — forcing a fresh blocking snapshot"
                )
                final_tag = f"{tag}-final" if tag else "preempt-final"
                path = self.engine.save_checkpoint(
                    self.save_dir, tag=final_tag, blocking=True
                )
            log_dist(f"preemption checkpoint saved: {path}")
            return path
        finally:
            self._in_final_save = False
