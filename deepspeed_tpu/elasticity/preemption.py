"""Preemption-aware checkpointing for TPU slices.

SURVEY §5 failure-detection analog: the reference's launcher watches child
processes and kills the tree on failure (launcher/launch.py:109,284) and
recovery is restart-from-checkpoint. On Cloud TPU the failure signal ARRIVES
IN-PROCESS: maintenance events / spot reclaims deliver SIGTERM with a grace
window. :class:`PreemptionGuard` turns that into a clean
checkpoint-then-exit at the next step boundary — the jitted step itself is
never interrupted mid-dispatch.

Usage::

    guard = PreemptionGuard(engine, save_dir)           # installs handlers
    for batch in loader:
        engine.train_batch(batch)
        if guard.should_stop():                          # signal seen?
            guard.checkpoint_and_log()                   # save + latest tag
            break

or as the engine-integrated form, ``initialize(...)`` callers can poll
``engine.preempted`` when a guard is attached.
"""

from __future__ import annotations

import signal
import threading
from typing import Optional

from ..utils.logging import log_dist

# SIGTERM is what TPU maintenance/reclaim delivers. SIGINT is NOT a default:
# its prior handler raises KeyboardInterrupt, which would unwind the loop
# before the step-boundary checkpoint this class exists for.
_DEFAULT_SIGNALS = ("SIGTERM",)


class PreemptionGuard:
    """Installs signal handlers that request a graceful stop.

    Handlers chain to any previously installed handler (the launcher's
    tree-kill propagation still works). Thread-safe: the flag is a simple
    event set from the signal context.
    """

    def __init__(self, engine=None, save_dir: Optional[str] = None, signals=_DEFAULT_SIGNALS, install: bool = True):
        self.engine = engine
        self.save_dir = save_dir
        self._stop = threading.Event()
        self._prev = {}
        self._signals = []
        if install:
            self.install(signals)
        if engine is not None:
            # engine.preempted polls this guard (DeepSpeedEngine property)
            engine._preemption_guard = self

    def install(self, signals=_DEFAULT_SIGNALS) -> None:
        for name in signals:
            sig = getattr(signal, name, None)
            if sig is None:
                continue
            if signal.getsignal(sig) == self._handler:
                # already armed — re-storing would self-chain (== not `is`:
                # each self._handler access builds a fresh bound method)
                continue
            try:
                self._prev[sig] = signal.signal(sig, self._handler)
                self._signals.append(sig)
            except (ValueError, OSError):  # non-main thread / unsupported
                continue

    def uninstall(self) -> None:
        for sig in self._signals:
            try:
                signal.signal(sig, self._prev.get(sig) or signal.SIG_DFL)
            except (ValueError, OSError):
                pass
        self._signals.clear()
        if self.engine is not None and getattr(self.engine, "_preemption_guard", None) is self:
            self.engine._preemption_guard = None

    def _handler(self, signum, frame):
        self._stop.set()
        log_dist(
            f"preemption signal {signal.Signals(signum).name} received — "
            "will checkpoint at the next step boundary"
        )
        prev = self._prev.get(signum)
        # chain, except to handlers that raise (default SIGINT raises
        # KeyboardInterrupt — that would defeat the graceful checkpoint)
        if callable(prev) and prev is not signal.default_int_handler:
            prev(signum, frame)

    def request_stop(self) -> None:
        """Programmatic trigger (tests; cooperative shutdown)."""
        self._stop.set()

    def should_stop(self) -> bool:
        return self._stop.is_set()

    def checkpoint_and_log(self, tag: Optional[str] = None) -> Optional[str]:
        """Save via the attached engine (no-op without one). Returns path."""
        if self.engine is None or self.save_dir is None:
            return None
        path = self.engine.save_checkpoint(self.save_dir, tag=tag)
        log_dist(f"preemption checkpoint saved: {path}")
        return path
