"""Elastic restart agent for TPU slices.

Analog of reference ``deepspeed/elasticity/elastic_agent.py`` (DSElasticAgent
:23, a torch-elastic LocalElasticAgent subclass): keep a training job alive
across membership changes by restarting from checkpoint at a compatible
scale. Torch-elastic's rendezvous does not exist on TPU; the equivalent
events are slice preemption/resize, surfaced to a single-controller JAX job
as device loss. The agent:

1. derives the compatible-batch ladder once (``compute_elastic_config``),
2. runs the user's train function,
3. on a registered failure, re-derives batch/micro-batch for the NEW chip
   count and reruns from the latest checkpoint — reference semantics
   (recovery is restart-from-checkpoint, not in-run healing).
"""

from __future__ import annotations

import os
import subprocess
import sys
import threading
import time
from typing import Any, Callable, Dict, Optional, Tuple

from ..utils.logging import log_dist
from .elasticity import ElasticityError, compute_elastic_config


def choose_compatible_world_size(
    ds_config: Dict[str, Any], available: int, valid: Optional[list] = None
) -> int:
    """Largest ladder-compatible world size <= ``available`` chips.

    The restart arm of the reference's rendezvous: after losing devices a
    job re-joins at whatever compatible scale the surviving slice admits
    (DSElasticAgent re-rendezvous; our ladder fixes the effective batch so
    any compatible count converges identically). Pass ``valid`` to reuse an
    already-derived ladder."""
    if valid is None:
        _, valid = compute_elastic_config(ds_config)
    fitting = [g for g in valid if g <= available]
    if not fitting:
        raise ElasticityError(
            f"no ladder-compatible world size fits {available} available "
            f"chips (ladder: {valid})"
        )
    return max(fitting)


def _default_probe(timeout_s: float) -> bool:
    """Device liveness = a tiny compute completing ON THE EXPECTED PLATFORM,
    probed in a KILLABLE subprocess — an in-process probe of a wedged
    accelerator plugin hangs unrecoverably (the exact failure mode this
    monitor exists to detect).

    Scope: valid where a second process can reach the accelerator (remote
    tunnel / proxy runtimes, CPU meshes). On classic TPU VMs the training
    process holds libtpu exclusively, so a child CANNOT init the backend —
    use :func:`make_progress_probe` there instead (no subprocess; watches
    the training step counter). The child prints its backend and the probe
    fails on a platform mismatch, so a silent CPU fallback can never report
    a wedged accelerator as healthy."""
    expected = (os.environ.get("JAX_PLATFORMS") or "").split(",")[0].strip()
    code = (
        "import jax, jax.numpy as jnp;"
        "x = jnp.ones((64, 64), jnp.bfloat16);"
        "(x @ x).block_until_ready();"
        "print('PROBE_BACKEND', jax.default_backend())"
    )
    try:
        proc = subprocess.run(
            [sys.executable, "-c", code], capture_output=True, text=True,
            timeout=timeout_s, stdin=subprocess.DEVNULL,
        )
    except subprocess.TimeoutExpired:
        return False
    if proc.returncode != 0 or "PROBE_BACKEND" not in proc.stdout:
        return False
    backend = proc.stdout.strip().split()[-1]
    return not expected or backend == expected


def make_progress_probe(get_step: Callable[[], int], stall_s: float = 300.0):
    """Probe from TRAINING PROGRESS instead of a subprocess: healthy while
    ``get_step()`` advances within ``stall_s``. Works on exclusive-libtpu
    deployments where no second process can touch the chip (the reference's
    worker monitoring also watches the worker, not the device). Pass e.g.
    ``lambda: engine.global_steps``."""
    state = {"step": None, "t": time.monotonic()}

    def probe(_timeout_s: float) -> bool:
        step = int(get_step())
        now = time.monotonic()
        if state["step"] is None or step != state["step"]:
            state["step"], state["t"] = step, now
            return True
        return (now - state["t"]) < stall_s

    def reset() -> None:
        state["step"], state["t"] = None, time.monotonic()

    # progress can only resume once training relaunches, so the agent must
    # NOT block in _await_healthy on this probe (deadlock: progress needs
    # training, training needs _await_healthy to return) — reset and go
    probe.waitable = False
    probe.reset = reset
    return probe


class DeviceMonitor:
    """Background accelerator health watcher.

    Analog of the reference elastic agent's worker-monitoring loop
    (``DSElasticAgent`` polls worker processes and triggers restart on
    failure, elastic_agent.py:23). The monitor probes liveness on an
    interval and flips ``healthy`` on consecutive failures.

    Scope of the trip: the reference supervises worker PROCESSES it can
    kill; here ``train_fn`` runs in the agent's own process, so a trip
    cannot preempt a train_fn that is HUNG inside a blocking device call
    (no raise to catch). What the trip does do: (a) fires ``on_trip`` once
    — wire it to ``PreemptionGuard``'s checkpoint path, a process-exit, or
    an orchestrator signal for hang recovery; (b) makes the agent wait for
    recovery before RELAUNCHING after a raised failure, instead of
    crash-looping into a wedged runtime; (c) exposes ``healthy`` for
    external health endpoints."""

    def __init__(
        self,
        interval_s: float = 60.0,
        probe_timeout_s: float = 90.0,
        failures_to_trip: int = 2,
        probe_fn: Optional[Callable[[float], bool]] = None,
        on_trip: Optional[Callable[[], None]] = None,
    ):
        self.interval_s = float(interval_s)
        self.probe_timeout_s = float(probe_timeout_s)
        self.failures_to_trip = int(failures_to_trip)
        self.probe_fn = probe_fn or _default_probe
        self.on_trip = on_trip
        self.consecutive_failures = 0
        self.probes = 0
        self._healthy = True
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        # probe_once is called from the background thread AND from the
        # agent's _await_healthy; serializing it keeps the trip counter
        # coherent and prevents duplicate concurrent (expensive) probes
        self._probe_lock = threading.Lock()

    @property
    def healthy(self) -> bool:
        # deliberately lock-free: a single GIL-atomic bool read on the hot
        # polling path; the probe thread's writes are serialized under
        # _probe_lock and a stale read here only delays the trip by one poll
        return self._healthy  # dslint: disable=shared-state-unlocked

    def probe_once(self) -> bool:
        with self._probe_lock:
            self.probes += 1
            ok = bool(self.probe_fn(self.probe_timeout_s))
            if ok:
                self.consecutive_failures = 0
                self._healthy = True
            else:
                self.consecutive_failures += 1
                if self.consecutive_failures >= self.failures_to_trip:
                    tripping = self._healthy
                    if tripping:
                        log_dist(
                            f"device monitor: {self.consecutive_failures} consecutive "
                            "probe failures — marking accelerator unhealthy"
                        )
                    self._healthy = False
                    if tripping and self.on_trip is not None:
                        try:
                            self.on_trip()
                        except Exception as e:
                            log_dist(f"device monitor: on_trip raised {e!r}")
            return ok

    def start(self) -> None:
        if self._thread is not None:
            if self._thread.is_alive():
                # previous loop still draining an in-flight probe (stop was
                # called with _stop set): wait it out before a fresh start
                self._thread.join(timeout=self.probe_timeout_s + 5)
            if self._thread.is_alive():
                raise RuntimeError(
                    "device monitor: previous probe loop did not exit"
                )
            self._thread = None
        self._stop.clear()

        def loop():
            while not self._stop.wait(self.interval_s):
                try:
                    self.probe_once()
                except Exception as e:  # user probe_fn raised: keep watching
                    log_dist(
                        f"device monitor: probe raised {type(e).__name__}: {e} "
                        "(counted as a failure; monitoring continues)"
                    )
                    with self._probe_lock:
                        self.consecutive_failures += 1
                        if self.consecutive_failures >= self.failures_to_trip:
                            self._healthy = False

        self._thread = threading.Thread(target=loop, daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
            if self._thread.is_alive():
                # a probe (up to probe_timeout_s) is still in flight: leave
                # the handle so a later start() can't clear _stop and revive
                # this loop alongside a fresh one
                log_dist("device monitor: stop() leaving in-flight probe to drain")
            else:
                self._thread = None


def resize_restart(
    engine_factory: Callable[[int, int, int], Any],
    ds_config: Dict[str, Any],
    ckpt_dir: str,
    world_size: int,
    tag: Optional[str] = None,
):
    """Resume training at a NEW slice size from the universal checkpoint.

    The slice-resize arm of the reference's elastic restart (DSElasticAgent
    restart + compute_elastic_config:287): the elastic ladder fixes ONE
    effective batch size across every compatible chip count, so a resize is

    1. look up ``world_size``'s micro batch on the ladder (convergence
       contract preserved: same effective batch, new micro x gas x dp split),
    2. build the engine at the new mesh geometry via ``engine_factory
       (world_size, train_batch, micro_batch)``,
    3. restore the mesh-agnostic universal checkpoint into the resized
       shardings (params AND optimizer state reshard on load).

    Returns the restored engine; training continues with an identical loss
    trajectory to an uninterrupted run (rehearsed in
    tests/unit/test_aux_subsystems.py::TestElasticResize).
    """
    batch, _, micro = compute_elastic_config(
        ds_config, world_size=world_size, return_microbatch=True
    )
    if micro is None:
        raise ElasticityError(f"no micro batch for world size {world_size}")
    engine = engine_factory(world_size, batch, micro)
    engine.load_checkpoint(ckpt_dir, tag=tag)
    log_dist(
        f"elastic resize: resumed at world_size={world_size} "
        f"batch={batch} micro={micro} from {ckpt_dir}"
    )
    return engine


class ElasticAgent:
    def __init__(
        self,
        ds_config: Dict[str, Any],
        train_fn: Callable[..., Any],
        max_restarts: int = 100,
        restart_delay_s: float = 5.0,
        retryable: Tuple[type, ...] = (RuntimeError, OSError),
        monitor: Optional[DeviceMonitor] = None,
    ):
        """``train_fn(world_size, train_batch_size, micro_batch)`` runs (and
        internally resumes from its latest checkpoint); the agent restarts it
        with recomputed batch geometry after retryable failures. A
        :class:`DeviceMonitor` (optional) runs alongside: when it trips, the
        agent waits for the accelerator to answer again before relaunching
        (rather than crash-looping into a wedged runtime)."""
        self.ds_config = ds_config
        self.train_fn = train_fn
        self.max_restarts = max_restarts
        self.restart_delay_s = restart_delay_s
        self.retryable = retryable
        self.restart_count = 0
        self.monitor = monitor

    def _current_world_size(self) -> int:
        import jax

        return jax.device_count()

    def geometry(self, world_size: int) -> Tuple[int, int, int]:
        """(world_size', train_batch, micro_batch) for the LARGEST
        ladder-compatible world size <= ``world_size`` — a post-resize chip
        count that is off-ladder (e.g. 7 of 8 chips healthy) steps down to
        the nearest compatible, and the RETURNED world size is the one to
        launch with (batch % (micro * ws') == 0 holds for it, not for the
        raw count)."""
        ws = choose_compatible_world_size(self.ds_config, world_size)
        batch, _, micro = compute_elastic_config(
            self.ds_config, world_size=ws, return_microbatch=True
        )
        if micro is None:
            raise ElasticityError(f"no micro batch for world size {ws}")
        return ws, batch, micro

    def _await_healthy(self, max_wait_s: float = 3600.0) -> None:
        """Block until the monitor reports the accelerator answering again
        (the re-rendezvous wait: no point relaunching into a dead runtime).
        Bounded: a permanently revoked slice raises instead of burning the
        allocation forever, so an orchestrator can reschedule. Progress-based
        probes (``probe.waitable = False``) skip the wait entirely — their
        signal can only recover once training relaunches — and are reset so
        the stalled window doesn't instantly re-trip."""
        if self.monitor is None:
            return
        if not getattr(self.monitor.probe_fn, "waitable", True):
            getattr(self.monitor.probe_fn, "reset", lambda: None)()
            self.monitor.consecutive_failures = 0
            self.monitor._healthy = True
            return
        deadline = time.monotonic() + max_wait_s
        while not self.monitor.probe_once():
            if time.monotonic() >= deadline:
                raise ElasticityError(
                    f"accelerator unhealthy for {max_wait_s:.0f}s "
                    "(slice revoked, not resized?) — giving up"
                )
            log_dist("elastic agent: accelerator still unhealthy; waiting")
            time.sleep(self.monitor.interval_s)

    def run(self) -> Any:
        if self.monitor is not None:
            self.monitor.start()
        try:
            while True:
                ws, batch, micro = self.geometry(self._current_world_size())
                log_dist(
                    f"elastic agent: starting at world_size={ws} "
                    f"batch={batch} micro={micro} (restart #{self.restart_count})"
                )
                try:
                    return self.train_fn(ws, batch, micro)
                except self.retryable as e:
                    self.restart_count += 1
                    if self.restart_count > self.max_restarts:
                        raise ElasticityError(
                            f"exceeded max_restarts={self.max_restarts}"
                        ) from e
                    log_dist(f"elastic agent: retryable failure {e!r}; restarting")
                    self._await_healthy()
                    time.sleep(self.restart_delay_s)
        finally:
            if self.monitor is not None:
                self.monitor.stop()
