"""Elastic restart agent for TPU slices.

Analog of reference ``deepspeed/elasticity/elastic_agent.py`` (DSElasticAgent
:23, a torch-elastic LocalElasticAgent subclass): keep a training job alive
across membership changes by restarting from checkpoint at a compatible
scale. Torch-elastic's rendezvous does not exist on TPU; the equivalent
events are slice preemption/resize, surfaced to a single-controller JAX job
as device loss. The agent:

1. derives the compatible-batch ladder once (``compute_elastic_config``),
2. runs the user's train function,
3. on a registered failure, re-derives batch/micro-batch for the NEW chip
   count and reruns from the latest checkpoint — reference semantics
   (recovery is restart-from-checkpoint, not in-run healing).
"""

from __future__ import annotations

import time
from typing import Any, Callable, Dict, Optional, Tuple

from ..utils.logging import log_dist
from .elasticity import ElasticityError, compute_elastic_config


def resize_restart(
    engine_factory: Callable[[int, int, int], Any],
    ds_config: Dict[str, Any],
    ckpt_dir: str,
    world_size: int,
    tag: Optional[str] = None,
):
    """Resume training at a NEW slice size from the universal checkpoint.

    The slice-resize arm of the reference's elastic restart (DSElasticAgent
    restart + compute_elastic_config:287): the elastic ladder fixes ONE
    effective batch size across every compatible chip count, so a resize is

    1. look up ``world_size``'s micro batch on the ladder (convergence
       contract preserved: same effective batch, new micro x gas x dp split),
    2. build the engine at the new mesh geometry via ``engine_factory
       (world_size, train_batch, micro_batch)``,
    3. restore the mesh-agnostic universal checkpoint into the resized
       shardings (params AND optimizer state reshard on load).

    Returns the restored engine; training continues with an identical loss
    trajectory to an uninterrupted run (rehearsed in
    tests/unit/test_aux_subsystems.py::TestElasticResize).
    """
    batch, _, micro = compute_elastic_config(
        ds_config, world_size=world_size, return_microbatch=True
    )
    if micro is None:
        raise ElasticityError(f"no micro batch for world size {world_size}")
    engine = engine_factory(world_size, batch, micro)
    engine.load_checkpoint(ckpt_dir, tag=tag)
    log_dist(
        f"elastic resize: resumed at world_size={world_size} "
        f"batch={batch} micro={micro} from {ckpt_dir}"
    )
    return engine


class ElasticAgent:
    def __init__(
        self,
        ds_config: Dict[str, Any],
        train_fn: Callable[..., Any],
        max_restarts: int = 100,
        restart_delay_s: float = 5.0,
        retryable: Tuple[type, ...] = (RuntimeError, OSError),
    ):
        """``train_fn(world_size, train_batch_size, micro_batch)`` runs (and
        internally resumes from its latest checkpoint); the agent restarts it
        with recomputed batch geometry after retryable failures."""
        self.ds_config = ds_config
        self.train_fn = train_fn
        self.max_restarts = max_restarts
        self.restart_delay_s = restart_delay_s
        self.retryable = retryable
        self.restart_count = 0

    def _current_world_size(self) -> int:
        import jax

        return jax.device_count()

    def geometry(self, world_size: int) -> Tuple[int, int]:
        batch, valid, micro = compute_elastic_config(
            self.ds_config, world_size=world_size, return_microbatch=True
        )
        if micro is None:
            raise ElasticityError(f"no micro batch for world size {world_size}")
        return batch, micro

    def run(self) -> Any:
        while True:
            ws = self._current_world_size()
            batch, micro = self.geometry(ws)
            log_dist(
                f"elastic agent: starting at world_size={ws} "
                f"batch={batch} micro={micro} (restart #{self.restart_count})"
            )
            try:
                return self.train_fn(ws, batch, micro)
            except self.retryable as e:
                self.restart_count += 1
                if self.restart_count > self.max_restarts:
                    raise ElasticityError(
                        f"exceeded max_restarts={self.max_restarts}"
                    ) from e
                log_dist(f"elastic agent: retryable failure {e!r}; restarting")
                time.sleep(self.restart_delay_s)
