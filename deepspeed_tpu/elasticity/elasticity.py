"""Elastic training: the compatible-batch ladder.

Analog of reference ``deepspeed/elasticity/elasticity.py`` (844 LoC:
compute_elastic_config:287, _get_compatible_gpus_v01:125 / v02:173). The
contract: pick ONE effective batch size B such that a job can restart on any
chip count g in a known set with identical convergence — i.e. for every
compatible g there is a micro-batch m in the allowed list and integer
gradient-accumulation k with  B = m * k * g.

On TPU "gpu count" becomes chip count (slice size); v02's
``num_gpus_per_node`` divisibility constraint maps to hosts (chips per host,
typically 4) so a restart lands on whole hosts.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence, Tuple


class ElasticityError(Exception):
    pass


class ElasticityConfigError(ElasticityError):
    pass


LATEST_ELASTICITY_VERSION = 0.2
MINIMUM_DEEPSPEED_VERSION = "0.3.8"


def _valid_gpus(
    batch: int, micro_batches: Sequence[int], min_gpus: int, max_gpus: int,
    unit: int = 1,
) -> List[int]:
    """Chip counts g that can realise ``batch`` with some micro batch:
    exists m, k >= 1 with batch == m * k * g. ``unit`` > 1 admits only
    whole-host counts (v0.2 node granularity)."""
    out = []
    for g in range(min_gpus, max_gpus + 1):
        if g % unit:
            continue
        if any(batch % (m * g) == 0 for m in micro_batches):
            out.append(g)
    return out


def get_compatible_gpus(
    micro_batches: Sequence[int],
    max_acceptable_batch_size: int,
    min_gpus: int = 1,
    max_gpus: Optional[int] = None,
    prefer_larger: bool = True,
    unit: int = 1,
) -> Tuple[int, List[int]]:
    """v0.1 algorithm: choose the batch size <= max that maximises the number
    of compatible chip counts (ties → larger batch when prefer_larger).
    ``unit`` applies the v0.2 whole-host constraint DURING the search
    (reference _get_compatible_gpus_v02 evaluates candidates at node
    granularity, elasticity.py:173 — filtering after choosing the batch
    would pick batches that maximize counts the constraint then removes)."""
    if not micro_batches or any(m <= 0 for m in micro_batches):
        raise ElasticityConfigError(f"invalid micro_batches {micro_batches}")
    max_gpus = max_gpus or max_acceptable_batch_size // min(micro_batches)
    best: Tuple[int, List[int]] = (0, [])
    for batch in range(1, max_acceptable_batch_size + 1):
        if not any(batch % m == 0 for m in micro_batches):
            continue
        gpus = _valid_gpus(batch, micro_batches, min_gpus, max_gpus, unit)
        better = len(gpus) > len(best[1]) or (
            len(gpus) == len(best[1]) and best[0] and (
                batch > best[0] if prefer_larger else batch < best[0]
            )
        )
        if better:
            best = (batch, gpus)
    if best[0] == 0:
        raise ElasticityError(
            f"no batch <= {max_acceptable_batch_size} compatible with micro_batches "
            f"{micro_batches} and gpus [{min_gpus}, {max_gpus}]"
        )
    return best


def compute_elastic_config(
    ds_config: Dict[str, Any],
    target_deepspeed_version: str = MINIMUM_DEEPSPEED_VERSION,
    world_size: int = 0,
    return_microbatch: bool = False,
):
    """Reference compute_elastic_config:287 surface.

    Returns (final_batch_size, valid_gpus[, micro_batch]) — and when
    ``world_size`` > 0 validates it is compatible and computes that world
    size's micro batch.
    """
    e = ds_config.get("elasticity")
    if not e or not e.get("enabled", False):
        raise ElasticityConfigError("'elasticity' section missing or disabled")
    micro_batches = sorted(e.get("micro_batch_sizes", []), reverse=True)
    max_batch = int(e.get("max_train_batch_size", 0))
    min_gpus = int(e.get("min_gpus", 1))
    max_gpus = int(e.get("max_gpus", max_batch // max(1, min(micro_batches or [1]))))
    prefer_larger = bool(e.get("prefer_larger_batch", True))
    version = float(e.get("version", 0.1))
    if not micro_batches or max_batch <= 0:
        raise ElasticityConfigError("micro_batch_sizes and max_train_batch_size required")
    min_time = int(e.get("min_time", 0))  # accepted for parity; not used here

    # v0.2 searches at whole-host granularity so the chosen batch maximises
    # counts that actually survive the node constraint. g counts chips, so
    # "(g*mp) % (mp*per_node) == 0" reduces to "g % per_node == 0".
    unit = 1
    if version >= 0.2:
        per_node = int(e.get("num_gpus_per_node", 4))  # chips per TPU host
        unit = per_node
    try:
        final_batch, valid_gpus = get_compatible_gpus(
            micro_batches, max_batch, min_gpus, max_gpus, prefer_larger, unit=unit
        )
    except ElasticityError:
        if unit == 1:
            raise
        # no whole-host count fits [min_gpus, max_gpus] (e.g. a sub-host
        # dev slice): lenient fallback to the unconstrained ladder, matching
        # the reference's keep-going behavior when the node filter empties
        final_batch, valid_gpus = get_compatible_gpus(
            micro_batches, max_batch, min_gpus, max_gpus, prefer_larger, unit=1
        )

    if world_size > 0:
        if world_size not in valid_gpus:
            raise ElasticityError(
                f"world size {world_size} not in compatible set {valid_gpus} "
                f"for batch {final_batch}"
            )
    if return_microbatch or world_size > 0:
        micro = None
        candidates = sorted(micro_batches, reverse=prefer_larger)
        ws = world_size or valid_gpus[-1]
        for m in candidates:
            if final_batch % (m * ws) == 0:
                micro = m
                break
        if world_size > 0 and return_microbatch:
            return final_batch, valid_gpus, micro
        if return_microbatch:
            return final_batch, valid_gpus, micro
    return final_batch, valid_gpus


def ensure_immutable_elastic_config(runtime_config: Dict[str, Any], saved_config: Dict[str, Any]):
    """Restarts must not change the elasticity contract
    (reference elasticity.py:254)."""
    for key in ("max_train_batch_size", "micro_batch_sizes", "version"):
        a = runtime_config.get("elasticity", {}).get(key)
        b = saved_config.get("elasticity", {}).get(key)
        if a != b:
            raise ElasticityConfigError(
                f"elastic config field {key!r} changed across restart: {b} → {a}"
            )
