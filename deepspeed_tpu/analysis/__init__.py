"""dslint — the graph & sharding static-analysis plane (ISSUE 6 tentpole).

Two engines over one findings/severity/suppression model:

- **Engine A** (``hlo_rules``): program verifiers over post-optimization HLO
  text — replication, buffer donation, precision, collective overlap, and
  executable-count budgets, checked on the already-compiled train/serving
  programs (``DeepSpeedEngine.verify_program()``, ``ServingEngine.verify()``).
- **Engine B** (``ast_rules``): a Python AST lint for JAX footguns — host
  syncs and device-op dispatch in per-step code, tracer branching, missing
  donation, unstable compile-cache keys.

Front ends: the ``python -m deepspeed_tpu.tools.dslint`` CLI (with the
committed-baseline CI gate), the ``lint``-marked tier-1 tests, and
``bench.py``'s ``dslint_findings_total``. See ``docs/ANALYSIS.md`` for the
rule catalog and the suppression / baseline workflow.
"""

from .ast_rules import (  # noqa: F401
    DEFAULT_DONATE_PATTERNS,
    DEFAULT_HOT_PATTERNS,
    lint_file,
    lint_source,
)
from .ast_rules import RULES as AST_RULES  # noqa: F401
from .baseline import DEFAULT_BASELINE_NAME, Baseline  # noqa: F401
from .findings import (  # noqa: F401
    SEVERITY_ERROR,
    SEVERITY_WARNING,
    Finding,
    SuppressionIndex,
)
from .hlo_rules import (  # noqa: F401
    RuleContext,
    check_program_budget,
    hlo_dtype,
    verify_compiled,
    verify_hlo_text,
)
from .hlo_rules import RULES as HLO_RULES  # noqa: F401


def all_rules():
    """rule id → one-line description, both engines."""
    out = dict(HLO_RULES)
    out.update(AST_RULES)
    return out


def lint_paths(paths, hot_patterns=None, donate_patterns=None):
    """Lint every ``*.py`` under ``paths`` (files or directories) with
    Engine B → (findings, suppressed_count, files_scanned).

    Unparseable files surface as SyntaxError, bogus path arguments as
    ValueError — callers decide whether that is fatal (the CLI reports
    both as usage-class errors; a typo'd path must NOT make the CI gate
    pass vacuously by scanning nothing)."""
    import os

    files = []
    for p in paths:
        if os.path.isdir(p):
            for root, dirs, names in os.walk(p):
                dirs[:] = sorted(
                    d for d in dirs
                    if d not in ("__pycache__", ".git", ".pytest_cache")
                )
                files.extend(
                    os.path.join(root, n) for n in sorted(names)
                    if n.endswith(".py")
                )
        elif p.endswith(".py") and os.path.exists(p):
            files.append(p)
        else:
            raise ValueError(
                f"dslint path {p!r} is not a directory or an existing "
                ".py file"
            )
    findings, suppressed = [], 0
    for f in files:
        got, waived = lint_file(
            f, hot_patterns=hot_patterns, donate_patterns=donate_patterns
        )
        findings.extend(got)
        suppressed += waived
    return findings, suppressed, files
