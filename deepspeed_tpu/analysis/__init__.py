"""dslint — the static-analysis plane (ISSUE 6 tentpole, ISSUE 8 dsan).

Four engines over one findings/severity/suppression model:

- **Engine A** (``hlo_rules``): program verifiers over post-optimization HLO
  text — replication, buffer donation, precision, collective overlap, and
  executable-count budgets, checked on the already-compiled train/serving
  programs (``DeepSpeedEngine.verify_program()``, ``ServingEngine.verify()``).
- **Engine B** (``ast_rules``): a Python AST lint for JAX footguns — host
  syncs and device-op dispatch in per-step code, tracer branching, missing
  donation, unstable compile-cache keys.
- **Engine C** (``concurrency_rules``): the AST concurrency sanitizer —
  per-module thread/lock/shared-attribute model reporting unlocked shared
  state, lock-order cycles, signal-unsafe handlers, thread leaks and
  blocking calls under locks. Its dynamic half, ``runtime_sanitizer``,
  records REAL lock orders and cross-thread accesses in ``dsan``-marked
  tests and reports through the same Finding stream.
- **Engine D** (``collective_rules``): the HLO collective-consistency
  verifier — channel-id uniqueness, async start/done pairing and FIFO
  order, and cross-program collective-order agreement on shared mesh
  groups (the SPMD desync/deadlock shape).
- **Engine E** (``memory_rules``, ISSUE 9): the static HBM liveness
  verifier — a def-use live-range walk over the scheduled post-opt HLO
  computes peak resident bytes and a categorized live-at-peak ledger,
  gated against committed per-program byte budgets
  (``.dsmem-budgets.json``): over-budget peaks, missed donations,
  oversized collective scratch, layout padding waste.
- **Engine F** (``sharding_rules``, ISSUE 9): the pre-compile sharding-spec
  verifier — ``match_partition_rules``-style regex tables checked against
  real ``jax.eval_shape`` param trees and the mesh: dead rules, rank/axis
  mismatches, silently replicated large leaves.
- **Engine G** (``protocol_rules`` + ``protocol_model``, ISSUE 15): the
  serving-protocol plane. An AST ownership-dataflow lint tracks every
  ``PageAllocator.alloc/retain/free`` through branches, early returns and
  exception paths (page-leak-on-path, double-free, use-after-free,
  refcount-escape, dual-reserve-unbalanced), and a bounded explicit-state
  model checker explores the scheduler's event interleavings against
  refcount-conservation / leak / use-after-free / wedge / dual-reserve
  invariants, emitting minimal counterexample traces that
  ``protocol_model.replay_trace`` confirms on the real ``ServingEngine``.

Front ends: the ``python -m deepspeed_tpu.tools.dslint`` CLI (with the
committed-baseline CI gate, ``--engines a..g`` selection, and ``--sarif``
export), the ``lint``/``dsan``/``dsmem``-marked tier-1 tests, and
``bench.py``'s finding counters. Engine F has no file form — it runs where
live param trees exist (``engine.verify_program()``, the dsmem tests). See
``docs/ANALYSIS.md`` for the rule catalog and the suppression / baseline
workflow.
"""

from .ast_rules import (  # noqa: F401
    DEFAULT_DONATE_PATTERNS,
    DEFAULT_HOT_PATTERNS,
    lint_file,
    lint_source,
)
from .ast_rules import RULES as AST_RULES  # noqa: F401
from .baseline import DEFAULT_BASELINE_NAME, Baseline  # noqa: F401
from .collective_rules import (  # noqa: F401
    CollectiveOp,
    extract_collectives,
    verify_collective_text,
    verify_compiled_set,
    verify_program_set,
)
from .collective_rules import RULES as COLLECTIVE_RULES  # noqa: F401
from .concurrency_rules import (  # noqa: F401
    build_model,
    check_file,
    check_source,
)
from .concurrency_rules import RULES as CONCURRENCY_RULES  # noqa: F401
from .findings import (  # noqa: F401
    SEVERITY_ERROR,
    SEVERITY_WARNING,
    Finding,
    SuppressionIndex,
)
from .hlo_rules import (  # noqa: F401
    RuleContext,
    check_program_budget,
    hlo_dtype,
    verify_compiled,
    verify_hlo_text,
)
from .hlo_rules import RULES as HLO_RULES  # noqa: F401
from .memory_rules import (  # noqa: F401
    DEFAULT_BUDGET_NAME,
    MemoryAnalysis,
    MemoryRuleContext,
    analyze_memory_text,
    find_budget_file,
    load_budgets,
    resolve_budget,
    verify_memory_compiled,
    verify_memory_text,
    xla_peak_bytes,
)
from .memory_rules import RULES as MEMORY_RULES  # noqa: F401
from .sharding_rules import (  # noqa: F401
    ShardingRuleContext,
    match_partition_rules,
    verify_spec_table,
    verify_tree_shardings,
)
from .sharding_rules import RULES as SHARDING_RULES  # noqa: F401
from .protocol_model import (  # noqa: F401
    ProtoModelConfig,
    ProtocolMonitor,
    apply_engine_mutation,
    default_model_configs,
    explore,
    model_findings,
    replay_fleet_trace,
    replay_trace,
)
from .protocol_model import MODEL_RULES as PROTOCOL_MODEL_RULES  # noqa: F401
from .protocol_rules import (  # noqa: F401
    check_file as check_protocol_file,
    check_source as check_protocol_source,
)
from .protocol_rules import RULES as PROTOCOL_RULES  # noqa: F401

# engine letter → rule catalog (the CLI's --engines selector)
ENGINE_RULES = {
    "a": HLO_RULES,
    "b": AST_RULES,
    "c": CONCURRENCY_RULES,
    "d": COLLECTIVE_RULES,
    "e": MEMORY_RULES,
    "f": SHARDING_RULES,
    "g": {**PROTOCOL_RULES, **PROTOCOL_MODEL_RULES},
}
ALL_ENGINES = frozenset(ENGINE_RULES)

# HLO text dumps the CLI can verify with Engines A/D without a live engine
HLO_SUFFIXES = (".hlo",)


def all_rules(engines=None):
    """rule id → one-line description for the selected engines (default
    all four)."""
    out = {}
    for letter in sorted(engines or ALL_ENGINES):
        out.update(ENGINE_RULES[letter])
    return out


def lint_paths(paths, hot_patterns=None, donate_patterns=None, engines=None):
    """Lint files under ``paths`` (files or directories) →
    (findings, suppressed_count, files_scanned).

    ``*.py`` files go through the source engines (B and/or C per
    ``engines``); ``*.hlo`` text dumps go through the program engines (A
    with a default declaration context, D — including the cross-program
    order-divergence check over every dump in the run — and E, whose
    budget gate resolves the dump's program name against the nearest
    committed ``.dsmem-budgets.json``). Engine F needs a live param tree
    and has no file form.

    Unparseable files surface as SyntaxError, bogus path arguments as
    ValueError — callers decide whether that is fatal (the CLI reports
    both as usage-class errors; a typo'd path must NOT make the CI gate
    pass vacuously by scanning nothing)."""
    import os

    engines = frozenset(engines or ALL_ENGINES)
    py_files, hlo_files = [], []

    def _route(f):
        if f.endswith(".py"):
            py_files.append(f)
        elif f.endswith(HLO_SUFFIXES):
            hlo_files.append(f)

    for p in paths:
        if os.path.isdir(p):
            for root, dirs, names in os.walk(p):
                dirs[:] = sorted(
                    d for d in dirs
                    if d not in ("__pycache__", ".git", ".pytest_cache")
                )
                for n in sorted(names):
                    _route(os.path.join(root, n))
        elif os.path.exists(p) and (
            p.endswith(".py") or p.endswith(HLO_SUFFIXES)
        ):
            _route(p)
        else:
            raise ValueError(
                f"dslint path {p!r} is not a directory or an existing "
                ".py/.hlo file"
            )
    findings, suppressed = [], 0
    for f in py_files:
        if "b" in engines:
            got, waived = lint_file(
                f, hot_patterns=hot_patterns, donate_patterns=donate_patterns
            )
            findings.extend(got)
            suppressed += waived
        if "c" in engines:
            got, waived = check_file(f)
            findings.extend(got)
            suppressed += waived
        if "g" in engines:
            got, waived = check_protocol_file(f)
            findings.extend(got)
            suppressed += waived
    if "g" in engines and any(
        os.path.basename(os.path.dirname(os.path.abspath(f))) == "serving"
        for f in py_files
    ):
        # the model checker has no per-file form: it verifies the serving
        # protocol itself, so it joins any scan that covers serving/
        for cfg in default_model_configs().values():
            findings.extend(model_findings(explore(cfg)))
    hlo_texts = {}
    for f in hlo_files:
        with open(f, encoding="utf-8") as fh:
            hlo_texts[f] = fh.read()

    if "e" in engines and hlo_texts:
        # Engine E gates each dump's program name against the nearest
        # committed ledger (resolved upward from the dump itself, so a
        # dump in another checkout meets THAT repo's budgets); everything
        # else in the context stays at defaults
        class _DumpBudgetCfg:
            budgets = {}
            budget_file = ""
            default_budget_bytes = 0

    for f, txt in hlo_texts.items():
        program = os.path.splitext(os.path.basename(f))[0]
        if "a" in engines:
            got = verify_hlo_text(txt, RuleContext(program=program))
            for x in got:
                x.path = f  # real file provenance beats hlo://<program>
            findings.extend(got)
        if "d" in engines:
            got = verify_collective_text(txt, program)
            for x in got:
                x.path = f
            findings.extend(got)
        if "e" in engines:
            ectx = MemoryRuleContext(
                program=program,
                budget_bytes=resolve_budget(
                    _DumpBudgetCfg, program, search_from=f
                ),
            )
            got, _ = verify_memory_text(txt, ectx)
            for x in got:
                x.path = f
            findings.extend(got)
    if "d" in engines and len(hlo_texts) > 1:
        # program name = basename when unique; colliding basenames (e.g.
        # runA/step.hlo vs runB/step.hlo — the natural two-run compare)
        # keep their full paths so neither dump silently shadows the other
        short = {}
        for f in hlo_texts:
            short.setdefault(
                os.path.splitext(os.path.basename(f))[0], []
            ).append(f)
        by_program = {
            (name if len(files) == 1 else f): hlo_texts[f]
            for name, files in short.items() for f in files
        }
        from .collective_rules import (
            extract_collectives as _ext,
            rule_order_divergence as _div,
        )

        findings.extend(_div({p: _ext(t) for p, t in by_program.items()}))
    return findings, suppressed, py_files + hlo_files
