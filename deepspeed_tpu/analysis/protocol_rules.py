"""Engine G (static half, ISSUE 15): the page-ownership dataflow lint.

The serving scheduler is protocol code: every KV page moves through an
acquire (``PageAllocator.alloc`` / ``retain``) → hold → release (``free``)
lifecycle, with the prefix index (``PrefixCache``) holding long-lived
references and disaggregated admission reserving on TWO allocators at once.
Example-based tests pin the happy paths; this lint walks the OWNERSHIP of
those pages through every branch, early return, and exception edge of each
function and fires when a path can drop, double-release, or alias a page
the protocol says it must not.

Analysis model (intraprocedural, path-sensitive with state merging):

- An *acquisition* is the result list of an ``<...allocator>.alloc(n)``
  call or the argument of ``<...allocator>.retain(pages)``. The resource is
  tracked by the set of local names aliased to it (assignments whose RHS
  mentions an owned name extend the alias set — ``pages = shared + priv``
  makes ``pages`` an alias of both).
- A resource is *discharged* by a ``free`` whose argument mentions an
  alias, or by *escaping*: stored into an attribute/subscript (the slot,
  the table, the index) or returned — ownership transfers to a longer-lived
  holder that the drain invariant (``check_no_leaks``) audits instead.
- ``alloc``/``retain``/``free`` can raise ``PageAllocatorError`` (pool
  exhausted, foreign page) — each such call is an *exception edge*. Holding
  an undischarged resource across one is a leak unless an enclosing
  ``try``'s handler (or ``finally``) visibly frees an alias of it. The ops
  themselves validate-then-mutate (atomic), so a handler's rollback is
  exact.

Rules (all ``severity=error``, engine ``protocol``):

- ``page-leak-on-path`` — an acquiring path reaches a terminal edge (fall
  off the end, ``return``, ``raise``, or an uncovered exception edge)
  without releasing or escaping the pages; also fires when a slot is reset
  (``self.slots[i] = ...``) in a function that never frees ``.pages``.
- ``double-free`` — one path frees the same expression twice with no
  rebinding in between.
- ``use-after-free`` — a freed expression is re-installed (``.assign``,
  ``.insert``, ``retain``, or a subscript store) after its owning free.
- ``refcount-escape`` — the COW page of a full prefix hit (the third
  element of ``PrefixCache.lookup``'s result) flows into a writable page
  set (``.pages`` / ``.prefill_pages`` / ``.row`` stores, block-table
  writes, ``table.assign``) without an alloc-backed fork: decode/chunk
  writes would mutate a page other holders read.
- ``dual-reserve-unbalanced`` — a function that retires a slot releases
  only one of the two reservations disaggregated admission took (frees
  ``.pages`` but not ``.prefill_pages``, or vice versa).

Same front end as Engines B/C: :func:`check_source` / :func:`check_file`
→ ``(findings, suppressed)`` through the shared Finding / suppression /
baseline machinery (``# dslint: disable=<rule>`` waives with a visible
count). ``tools/dslint.py --engines g`` selects it; the dynamic half —
the bounded model checker over the same protocol — lives in
``protocol_model.py``.
"""

from __future__ import annotations

import ast
from typing import Dict, FrozenSet, List, Optional, Set, Tuple

from .findings import (
    SEVERITY_ERROR,
    Finding,
    SuppressionIndex,
    apply_suppressions,
)

RULES = {
    "page-leak-on-path": (
        "an acquiring path (alloc/retain) reaches a terminal or exception "
        "edge without freeing or storing the pages"
    ),
    "double-free": (
        "one path frees the same page expression twice without an "
        "intervening rebind"
    ),
    "use-after-free": (
        "a freed page expression is re-installed (table assign / index "
        "insert / retain) after its owning free"
    ),
    "refcount-escape": (
        "the COW page of a full prefix hit flows into a writable page set "
        "without an alloc-backed fork"
    ),
    "dual-reserve-unbalanced": (
        "slot teardown releases only one of the two reservations "
        "disaggregated admission took (.pages vs .prefill_pages)"
    ),
}

_PROTO_OPS = ("alloc", "retain", "free")
# host-tier handoff ops (ISSUE 17): the callee takes ownership of the page
# argument — ``PrefixCache.adopt`` installs a restored page into the index
# and ``KVTieringEngine.demote_begin`` moves a page's KV into the host
# store. Both sides are audited holders (``check_no_leaks`` reconciles the
# index and the host store), so a handoff discharges like an escape.
_HANDOFF_OPS = ("adopt", "demote", "demote_begin")
# attribute names whose stores mean "this is now a writable page set"
_PAGE_ATTRS = ("pages", "prefill_pages", "row")
# per-function path-state cap: states merge aggressively (most statements
# do not touch protocol state), so this only bounds pathological inputs
_MAX_STATES = 128


def _chain(node: ast.AST) -> Optional[str]:
    """Dotted chain for a Name/Attribute expression (``self.a.b`` →
    ``"self.a.b"``), else None."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def _names(node: ast.AST) -> Set[str]:
    return {n.id for n in ast.walk(node) if isinstance(n, ast.Name)}


def _proto_call(node: ast.Call) -> Optional[Tuple[str, str]]:
    """→ (op, receiver chain) when ``node`` is an allocator protocol call
    (``<chain ending in an allocator-ish name>.alloc/retain/free(...)``)."""
    if not isinstance(node.func, ast.Attribute):
        return None
    op = node.func.attr
    if op not in _PROTO_OPS or not node.args:
        return None
    recv = _chain(node.func.value)
    if recv is None:
        return None
    if "alloc" not in recv.split(".")[-1]:
        return None
    return op, recv


def _free_keys(call: ast.Call) -> Set[str]:
    """Expression keys a ``free`` discharges: dotted chains of the args
    plus names inside list-literal args (``free([pid])``)."""
    keys: Set[str] = set()
    for a in call.args:
        k = _chain(a)
        if k is not None:
            keys.add(k)
        elif isinstance(a, (ast.List, ast.Tuple)):
            keys.update(_names(a))
        else:
            keys.update(_names(a))
    return keys


# a tracked resource: (acquire line, op, receiver chain, alias names)
_Own = Tuple[int, str, str, FrozenSet[str]]
# path state: (live resources, freed expression keys)
_State = Tuple[FrozenSet[_Own], FrozenSet[str]]


class _FunctionCheck:
    """Path-sensitive ownership walk over one function body."""

    def __init__(self, linter: "_Linter", qualname: str):
        self.linter = linter
        self.qualname = qualname
        # stack of frozensets: names an enclosing try's handlers/finally
        # visibly free (covers exception edges inside that try's body)
        self.covers: List[FrozenSet[str]] = []

    # -- reporting -----------------------------------------------------

    def _emit(self, rule: str, line: int, message: str) -> None:
        self.linter.emit(rule, line, message, self.qualname)

    def _leak(self, own: _Own, line: int, how: str) -> None:
        names = "/".join(sorted(own[3])) or f"{own[2]}.{own[1]}(...)"
        self._emit(
            "page-leak-on-path", own[0],
            f"pages acquired by {own[2]}.{own[1]}() (held as '{names}') "
            f"are dropped when this path {how} at line {line} — free them "
            "or store them on an audited holder first",
        )

    # -- state transitions ---------------------------------------------

    def _exception_edge(
        self, st: _State, line: int, releasing: FrozenSet[str]
    ) -> None:
        """alloc/retain/free at ``line`` may raise PageAllocatorError —
        every held resource not being released by this very call must be
        covered by an enclosing handler's rollback."""
        cover: Set[str] = set()
        for c in self.covers:
            cover |= c
        for own in st[0]:
            if own[3] & releasing:
                continue  # this call IS the release
            if own[3] & cover:
                continue  # an enclosing handler frees an alias
            self._leak(own, line, "raises PageAllocatorError")

    def _terminal(self, st: _State, line: int, how: str) -> None:
        for own in st[0]:
            self._leak(own, line, how)

    def _use_after_free(
        self, st: _State, node: ast.AST, line: int, context: str
    ) -> None:
        for sub in ast.walk(node):
            key = _chain(sub)
            if key is not None and key in st[1]:
                self._emit(
                    "use-after-free", line,
                    f"'{key}' was freed earlier on this path but is "
                    f"re-installed via {context} — the pages may already "
                    "belong to another request",
                )

    # -- statement dispatch --------------------------------------------

    def block(self, stmts: List[ast.stmt], states: Set[_State]) -> Set[_State]:
        for s in stmts:
            if not states:
                break
            states = self.stmt(s, states)
            if len(states) > _MAX_STATES:
                states = set(list(states)[:_MAX_STATES])
        return states

    def stmt(self, s: ast.stmt, states: Set[_State]) -> Set[_State]:
        if isinstance(s, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)):
            return states  # nested defs are analyzed as their own functions
        if isinstance(s, (ast.Assign, ast.AnnAssign, ast.AugAssign)):
            out: Set[_State] = set()
            for st in states:
                out.add(self.assign(s, st))
            return out
        if isinstance(s, ast.Expr):
            out = set()
            for st in states:
                out.add(self.expr_stmt(s, st))
            return out
        if isinstance(s, ast.Return):
            for st in states:
                live = st[0]
                if s.value is not None:
                    rn = _names(s.value)
                    live = frozenset(o for o in live if not (o[3] & rn))
                self._terminal((live, st[1]), s.lineno, "returns")
            return set()
        if isinstance(s, ast.Raise):
            for st in states:
                self._exception_edge(st, s.lineno, frozenset())
            return set()
        if isinstance(s, ast.If):
            # guard-empty idiom: on the false branch of ``if pages:`` the
            # guarded name is provably empty, so owns it aliases are vacuous
            else_states = set(states)
            if isinstance(s.test, ast.Name):
                g = s.test.id
                else_states = {
                    (
                        frozenset(o for o in st[0] if g not in o[3]),
                        st[1],
                    )
                    for st in states
                }
            return (
                self.block(s.body, set(states))
                | self.block(s.orelse, else_states)
            )
        if isinstance(s, (ast.For, ast.AsyncFor, ast.While)):
            once = self.block(s.body, set(states))
            skip = self.block(s.orelse, set(states)) if s.orelse else states
            return once | skip | states
        if isinstance(s, ast.Try):
            self.covers.append(self._handler_cover(s))
            body_states = self.block(s.body, set(states))
            self.covers.pop()
            if s.orelse:
                body_states = self.block(s.orelse, body_states)
            handler_states: Set[_State] = set()
            for h in s.handlers:
                # handlers also run standalone from the try-entry state so
                # rollback code gets its own double-free/UAF audit
                handler_states |= self.block(h.body, set(states))
            after = body_states | handler_states
            if s.finalbody:
                after = self.block(s.finalbody, after)
            return after
        if isinstance(s, (ast.With, ast.AsyncWith)):
            return self.block(s.body, states)
        if isinstance(s, ast.Delete):
            dead = set()
            for t in s.targets:
                if isinstance(t, ast.Name):
                    dead.add(t.id)
            if dead:
                return {self._kill_names(st, dead) for st in states}
            return states
        return states

    def _handler_cover(self, t: ast.Try) -> FrozenSet[str]:
        names: Set[str] = set()
        # simple name flows inside the handler count: the common rollback
        # idiom is ``both = a + b; allocator.free(both)`` — freeing ``both``
        # covers ``a`` and ``b``
        flows: dict = {}
        for body in [h.body for h in t.handlers] + [t.finalbody]:
            for node in body:
                for sub in ast.walk(node):
                    if isinstance(sub, ast.Assign):
                        for tgt in sub.targets:
                            if isinstance(tgt, ast.Name):
                                flows.setdefault(tgt.id, set()).update(
                                    _names(sub.value)
                                )
                    elif isinstance(sub, ast.Call):
                        m = _proto_call(sub)
                        if m is not None and m[0] == "free":
                            for a in sub.args:
                                names |= _names(a)
        for _ in range(4):  # transitive closure, tiny bound
            extra = set()
            for n in names:
                extra |= flows.get(n, set())
            if extra <= names:
                break
            names |= extra
        return frozenset(names)

    @staticmethod
    def _kill_names(st: _State, dead: Set[str]) -> _State:
        owns = frozenset(
            (o[0], o[1], o[2], o[3] - frozenset(dead)) for o in st[0]
        )
        freed = frozenset(
            k for k in st[1]
            if k not in dead and k.split(".")[0] not in dead
        )
        return owns, freed

    # -- expressions ----------------------------------------------------

    def _process_calls(self, node: ast.AST, st: _State) -> _State:
        """Apply every protocol call inside ``node`` (in source order) to
        the state; acquisitions from ``alloc`` are left pending for the
        enclosing assignment to bind (an unbound alloc is itself a leak —
        handled by the caller)."""
        owns, freed = set(st[0]), set(st[1])
        for call in [
            c for c in ast.walk(node) if isinstance(c, ast.Call)
        ]:
            m = _proto_call(call)
            if m is None:
                # non-protocol call: the re-install half of use-after-free
                if (
                    isinstance(call.func, ast.Attribute)
                    and call.func.attr in ("assign", "insert")
                ):
                    for a in call.args:
                        self._use_after_free(
                            (frozenset(owns), frozenset(freed)),
                            a, call.lineno, f".{call.func.attr}()",
                        )
                # host-tier handoff: ownership of the page args transfers
                # to an audited holder (index / host store) — discharge
                if (
                    isinstance(call.func, ast.Attribute)
                    and call.func.attr in _HANDOFF_OPS
                    and call.args
                ):
                    handed = frozenset().union(
                        *[_names(a) for a in call.args]
                    )
                    owns = {o for o in owns if not (o[3] & handed)}
                continue
            op, recv = m
            arg_names = frozenset().union(
                *[_names(a) for a in call.args]
            ) if call.args else frozenset()
            self._exception_edge(
                (frozenset(owns), frozenset(freed)), call.lineno,
                arg_names if op == "free" else frozenset(),
            )
            if op == "retain":
                self._use_after_free(
                    (frozenset(owns), frozenset(freed)),
                    call, call.lineno, "retain()",
                )
                if arg_names:
                    owns.add((call.lineno, "retain", recv, arg_names))
            elif op == "free":
                owns = {o for o in owns if not (o[3] & arg_names)}
                for key in _free_keys(call):
                    if key in freed:
                        self._emit(
                            "double-free", call.lineno,
                            f"'{key}' is freed twice on this path — the "
                            "second free throws or releases another "
                            "request's pages",
                        )
                    else:
                        freed.add(key)
        return frozenset(owns), frozenset(freed)

    def expr_stmt(self, s: ast.Expr, st: _State) -> _State:
        st = self._process_calls(s.value, st)
        # a bare alloc whose result is discarded leaks immediately
        if isinstance(s.value, ast.Call):
            m = _proto_call(s.value)
            if m is not None and m[0] == "alloc":
                self._emit(
                    "page-leak-on-path", s.lineno,
                    f"{m[1]}.alloc() result is discarded — the pages can "
                    "never be freed",
                )
        return st

    def assign(self, s: ast.stmt, st: _State) -> _State:
        value = s.value
        if value is None:  # bare annotation
            return st
        targets = (
            s.targets if isinstance(s, ast.Assign) else [s.target]
        )
        st = self._process_calls(value, st)
        owns, freed = set(st[0]), set(st[1])

        target_names = {t.id for t in targets if isinstance(t, ast.Name)}
        stored = any(
            isinstance(t, (ast.Attribute, ast.Subscript)) for t in targets
        )
        value_names = _names(value)

        # freed-key UAF: a freed expression flowing into an attr/subscript
        # store is a re-install
        if stored:
            self._use_after_free(
                (frozenset(owns), frozenset(freed)), value, s.lineno,
                "an attribute/subscript store",
            )

        # alias extension / escape / rebind-kill for existing resources
        next_owns: Set[_Own] = set()
        for own in owns:
            aliases = own[3]
            if aliases & value_names:
                if stored:
                    continue  # escaped to a longer-lived holder
                aliases = aliases | frozenset(target_names)
            else:
                rebound = aliases & target_names
                if rebound:
                    aliases = aliases - rebound
                    if not aliases:
                        self._emit(
                            "page-leak-on-path", s.lineno,
                            f"the last name holding pages from "
                            f"{own[2]}.{own[1]}() (line {own[0]}) is "
                            "rebound here — the pages can never be freed",
                        )
                        continue
            next_owns.add((own[0], own[1], own[2], aliases))

        # bind fresh alloc acquisitions from this RHS (after the rebind
        # pass: the acquisition's own target must not kill it)
        for call in ast.walk(value):
            if isinstance(call, ast.Call):
                m = _proto_call(call)
                if m is not None and m[0] == "alloc":
                    if stored and not target_names:
                        continue  # stored directly: escaped on arrival
                    if not target_names:
                        self._emit(
                            "page-leak-on-path", call.lineno,
                            f"{m[1]}.alloc() result is never bound to a "
                            "releasable name",
                        )
                        continue
                    next_owns.add((
                        call.lineno, "alloc", m[1], frozenset(target_names)
                    ))

        # rebinding an expression key ends its freed-ness
        killed = set(target_names)
        for t in targets:
            k = _chain(t)
            if k is not None:
                killed.add(k)
        freed = {
            k for k in freed
            if k not in killed
            and not any(k.startswith(dead + ".") for dead in killed)
        }
        return frozenset(next_owns), frozenset(freed)


class _Linter:
    """Per-module driver: function discovery, per-function path walk,
    whole-function obligations (slot teardown + COW taint)."""

    def __init__(self, path: str, source: str):
        self.path = path
        self.lines = source.splitlines()
        self.findings: List[Finding] = []
        self._seen: Set[Tuple[str, int, str]] = set()

    def emit(self, rule: str, line: int, message: str, symbol: str) -> None:
        key = (rule, line, symbol)
        if key in self._seen:
            return
        self._seen.add(key)
        snippet = (
            self.lines[line - 1].strip()
            if 0 < line <= len(self.lines) else ""
        )
        self.findings.append(Finding(
            rule=rule, severity=SEVERITY_ERROR, message=message,
            path=self.path, line=line, symbol=symbol, snippet=snippet,
            engine="protocol",
        ))

    def run(self, tree: ast.Module) -> List[Finding]:
        for qualname, fn in self._functions(tree):
            # fall-through states: every resource still live leaks
            chk = _FunctionCheck(self, qualname)
            final = chk.block(fn.body, {(frozenset(), frozenset())})
            for st in final:
                chk._terminal(
                    st, getattr(fn, "end_lineno", fn.lineno) or fn.lineno,
                    "falls off the end of the function",
                )
            self._teardown_obligations(qualname, fn)
            self._cow_taint(qualname, fn)
        return self.findings

    @staticmethod
    def _functions(tree: ast.Module):
        out = []

        def walk(node, prefix):
            for child in ast.iter_child_nodes(node):
                if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    q = f"{prefix}{child.name}"
                    out.append((q, child))
                    walk(child, q + ".")
                elif isinstance(child, ast.ClassDef):
                    walk(child, f"{prefix}{child.name}.")

        walk(tree, "")
        return out

    # -- whole-function obligations ------------------------------------

    def _teardown_obligations(self, qualname: str, fn: ast.AST) -> None:
        """A function that resets a slot (``self.slots[i] = ...``) retires
        both reservations: some ``free`` must mention ``.pages`` and —
        when the function handles prefill-side state at all — some
        ``free`` must mention ``.prefill_pages``."""
        reset_line = None
        frees: Set[str] = set()
        reads_prefill = False
        for node in ast.walk(fn):
            if isinstance(node, ast.Assign):
                for t in node.targets:
                    if (
                        isinstance(t, ast.Subscript)
                        and _chain(t.value) is not None
                        and _chain(t.value).endswith("slots")
                    ):
                        reset_line = reset_line or node.lineno
            if isinstance(node, ast.Attribute):
                if node.attr == "prefill_pages":
                    reads_prefill = True
            if isinstance(node, ast.Call):
                m = _proto_call(node)
                if m is not None and m[0] == "free":
                    for a in node.args:
                        for sub in ast.walk(a):
                            if isinstance(sub, ast.Attribute) and (
                                sub.attr in ("pages", "prefill_pages")
                            ):
                                frees.add(sub.attr)
                            elif isinstance(sub, ast.Name) and (
                                sub.id in ("pages", "prefill_pages")
                            ):
                                frees.add(sub.id)
        if reset_line is None:
            return
        if "pages" not in frees:
            rule = (
                "dual-reserve-unbalanced" if "prefill_pages" in frees
                else "page-leak-on-path"
            )
            detail = (
                "frees the prefill-side reservation but not the slot's "
                "decode pages" if rule == "dual-reserve-unbalanced"
                else "never frees the slot's pages"
            )
            self.emit(
                rule, reset_line,
                f"slot reset {detail} — the reservation outlives the slot",
                qualname,
            )
        elif reads_prefill and "prefill_pages" not in frees:
            self.emit(
                "dual-reserve-unbalanced", reset_line,
                "slot reset frees .pages but not .prefill_pages — under "
                "disaggregation the prefill-side reservation leaks",
                qualname,
            )

    def _cow_taint(self, qualname: str, fn: ast.AST) -> None:
        """Flow-insensitive taint from ``lookup()``'s COW page into any
        writable page set: the COW page is SHARED (the index and possibly
        other slots hold it) — decode/chunk writes must target an
        alloc-backed fork instead."""
        tainted: Set[str] = set()
        assigns: List[ast.Assign] = []
        for node in ast.walk(fn):
            if not isinstance(node, ast.Assign):
                continue
            assigns.append(node)
            v = node.value
            if (
                isinstance(v, ast.Call)
                and isinstance(v.func, ast.Attribute)
                and v.func.attr == "lookup"
            ):
                for t in node.targets:
                    if isinstance(t, ast.Tuple) and len(t.elts) == 3 and (
                        isinstance(t.elts[2], ast.Name)
                    ):
                        tainted.add(t.elts[2].id)
        if not tainted:
            return
        changed = True
        while changed:
            changed = False
            for a in assigns:
                if _names(a.value) & tainted:
                    for t in a.targets:
                        if isinstance(t, ast.Name) and t.id not in tainted:
                            tainted.add(t.id)
                            changed = True

        def flag(line: int, where: str) -> None:
            self.emit(
                "refcount-escape", line,
                f"the COW page of a full prefix hit reaches {where} "
                "without an alloc-backed fork — writes would mutate a "
                "page other holders read (fork by recomputing into a "
                "private page instead)",
                qualname,
            )

        for node in ast.walk(fn):
            if isinstance(node, ast.Assign):
                if not (_names(node.value) & tainted):
                    continue
                for t in node.targets:
                    if isinstance(t, ast.Attribute) and (
                        t.attr in _PAGE_ATTRS
                    ):
                        flag(node.lineno, f"a .{t.attr} store")
                    elif isinstance(t, ast.Subscript):
                        base = _chain(t.value) or ""
                        leaf = base.split(".")[-1]
                        if leaf in ("row", "block_tables"):
                            flag(node.lineno, f"a {leaf} write")
            elif isinstance(node, ast.Call) and isinstance(
                node.func, ast.Attribute
            ):
                if node.func.attr == "assign" and any(
                    _names(a) & tainted for a in node.args
                ):
                    flag(node.lineno, "table.assign()")


def check_source(
    source: str, path: str = "<source>"
) -> Tuple[List[Finding], int]:
    """Engine G static pass over one module → (findings, suppressed)."""
    if not any(
        tok in source for tok in (".alloc(", ".retain(", ".free(")
    ):
        return [], 0
    tree = ast.parse(source, filename=path)
    findings = _Linter(path, source).run(tree)
    findings.sort(key=lambda f: (f.path, f.line, f.rule))
    return apply_suppressions(
        findings, SuppressionIndex.from_source(source)
    )


def check_file(path: str) -> Tuple[List[Finding], int]:
    with open(path, encoding="utf-8") as fh:
        return check_source(fh.read(), path=path)
