"""Engine C: AST concurrency sanitizer — host-thread race/deadlock rules.

PR 7 made host-side concurrency load-bearing: a background checkpoint-writer
thread, SIGTERM handlers, the elastic-agent probe loop, serving drain/retry.
None of it runs under a compiler that checks interleavings — but the
dangerous shapes are visible in the AST. This engine builds a per-module
model of threads (``threading.Thread`` targets and their transitive
same-module call closure), locks (``threading.Lock/RLock/Condition``
assignments and the ``with <lock>:`` blocks that hold them), and the
attributes each context reads/writes, then reports:

- ``shared-state-unlocked``: an attribute written from thread-target code
  and read/written from non-thread code with no common lock held at every
  site. Attributes bound in ``__init__`` to thread-safe primitives
  (``Event``/``Queue``/locks) are exempt, as is ``__init__`` itself
  (happens-before the thread starts).
- ``lock-order-cycle``: the lock-acquisition graph (lock A held while lock
  B is acquired, lexically or through a same-module call) has a cycle —
  the classic ABBA deadlock, latent until the schedule lines up.
- ``signal-unsafe-handler``: a registered signal handler calling anything
  beyond flag-sets (``Event.set``), ``os.write``/``os._exit``/``os.kill``,
  and ``signal.*`` introspection. CPython handlers run between bytecodes on
  the main thread, but they still interrupt arbitrary code — allocation,
  logging, and lock acquisition inside one can deadlock or corrupt the very
  state being saved.
- ``thread-leak``: a non-daemon thread constructed with no reachable
  ``join()`` on its binding — process exit blocks on it forever.
- ``blocking-under-lock``: ``time.sleep``/file IO/``subprocess``/
  ``jax.device_get``/``Thread.join`` while holding a lock — every other
  thread contending that lock stalls for the full blocking call.

All rules silence with ``# dslint: disable=<rule>`` exactly like Engines
A/B; waivers are counted, never hidden.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Set, Tuple

from .findings import (
    SEVERITY_ERROR,
    SEVERITY_WARNING,
    Finding,
    SuppressionIndex,
    apply_suppressions,
)

RULES = {
    "shared-state-unlocked":
        "attribute shared between a thread target and other code with no "
        "common lock",
    "lock-order-cycle":
        "lock-acquisition graph has a cycle (ABBA deadlock shape)",
    "signal-unsafe-handler":
        "signal handler calls beyond flag-sets/os.write/reentrant-safe ops",
    "thread-leak":
        "non-daemon thread with no reachable join()",
    "blocking-under-lock":
        "blocking call (sleep/IO/device_get/join) while holding a lock",
}

_LOCK_CTORS = (
    "threading.Lock", "threading.RLock", "threading.Condition",
    "threading.Semaphore", "threading.BoundedSemaphore",
    "Lock", "RLock", "Condition",
)
# attributes bound to these in __init__ are thread-safe by construction:
# cross-thread use through their methods is their whole point
_SAFE_CTORS = _LOCK_CTORS + (
    "threading.Event", "Event",
    "queue.Queue", "queue.SimpleQueue", "queue.LifoQueue",
    "queue.PriorityQueue", "Queue", "SimpleQueue",
    "threading.local",
)
_THREAD_CTORS = ("threading.Thread", "Thread")

# method calls that mutate their receiver (a write to the attribute even
# though the AST context is Load)
_MUTATORS = frozenset((
    "append", "extend", "insert", "remove", "clear", "update", "add",
    "discard", "pop", "popleft", "appendleft", "setdefault", "put",
    "sort", "reverse", "write",
))

# calls that block: holding a lock across one serializes every contender
_BLOCKING_PREFIXES = (
    "time.sleep", "sleep", "subprocess.", "requests.", "urllib.",
    "socket.", "os.fsync", "os.replace", "os.rename", "os.remove",
    "os.makedirs", "shutil.", "jax.device_get",
)
_BLOCKING_SUFFIXES = (".block_until_ready",)

# the async-signal-safe allowlist: flag sets, raw fd writes, process exit,
# signal introspection, and a few pure builtins
_HANDLER_SAFE_SUFFIXES = (".set", ".is_set", ".clear", "._exit", ".write",
                          ".kill")
_HANDLER_SAFE_CHAINS = (
    "os.write", "os._exit", "os.kill", "signal.signal", "signal.getsignal",
    "signal.Signals", "callable", "isinstance", "getattr", "len", "int",
    "str",
)


def _chain(node: ast.AST) -> str:
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return ""


def _is_lockish(chain: str) -> bool:
    """A ``with`` subject that is plausibly a lock even without a matching
    ``threading.Lock()`` assignment in this module (injected locks)."""
    last = chain.split(".")[-1].lower()
    return any(k in last for k in ("lock", "mutex"))


@dataclass
class _Access:
    attr: str          # canonical "Class.attr" / module-level name
    kind: str          # "read" | "write"
    line: int
    locks: frozenset   # canonical lock ids held at the site


@dataclass
class _Func:
    node: ast.AST
    name: str
    qualname: str
    cls: str = ""                   # enclosing class name, "" at module level
    accesses: List[_Access] = field(default_factory=list)
    # every lock this function acquires directly: (lock id, line)
    acquired: List[Tuple[str, int]] = field(default_factory=list)
    # (outer lock, inner lock, line) from lexical `with` nesting
    nest_edges: List[Tuple[str, str, int]] = field(default_factory=list)
    # calls made: (dotted chain, line, locks held at the call site)
    calls: List[Tuple[str, int, frozenset]] = field(default_factory=list)


@dataclass
class _ThreadSite:
    target: str                     # bare function/method name
    target_cls: str                 # class of `self.X` targets ("" otherwise)
    binding: str                    # "self._thread" / "t" / "" if unbound
    daemon: bool
    line: int


@dataclass
class ModuleModel:
    """Everything the concurrency rules need to know about one module."""

    path: str
    lines: List[str]
    funcs: Dict[str, _Func] = field(default_factory=dict)  # qualname → func
    locks: Set[str] = field(default_factory=set)           # canonical ids
    safe_attrs: Set[str] = field(default_factory=set)      # "Class.attr"
    threads: List[_ThreadSite] = field(default_factory=list)
    handlers: List[Tuple[str, str, int]] = field(default_factory=list)
    # thread attrs ("Class.attr" / name) bound to Thread(...) — join targets
    thread_attrs: Set[str] = field(default_factory=set)


# ---------------------------------------------------------------------------
# model construction
# ---------------------------------------------------------------------------

def _canon(target: ast.AST, cls: str) -> Optional[str]:
    """Canonical id of an assignment target / with-subject: ``Class.attr``
    for ``self.attr`` (scoped per class), bare name at module level."""
    if isinstance(target, ast.Attribute) and isinstance(target.value, ast.Name) \
            and target.value.id == "self":
        return f"{cls}.{target.attr}" if cls else target.attr
    if isinstance(target, ast.Name):
        return target.id
    return None


class _ModelBuilder(ast.NodeVisitor):
    """First pass: locks, safe attrs, thread sites, handlers, join targets."""

    def __init__(self, model: ModuleModel):
        self.m = model
        self._cls = ""

    def visit_ClassDef(self, node: ast.ClassDef):
        prev, self._cls = self._cls, node.name
        self.generic_visit(node)
        self._cls = prev

    def _record_assign(self, target: ast.AST, value: ast.AST, line: int):
        name = _canon(target, self._cls)
        if name is None or not isinstance(value, ast.Call):
            return
        ctor = _chain(value.func)
        if ctor in _LOCK_CTORS:
            self.m.locks.add(name)
        if ctor in _SAFE_CTORS:
            self.m.safe_attrs.add(name)
        if ctor in _THREAD_CTORS:
            self.m.thread_attrs.add(name)
            self._record_thread(value, binding=name, line=line)

    def visit_Assign(self, node: ast.Assign):
        for t in node.targets:
            self._record_assign(t, node.value, node.lineno)
        self.generic_visit(node)

    def visit_AnnAssign(self, node: ast.AnnAssign):
        if node.value is not None:
            self._record_assign(node.target, node.value, node.lineno)
        self.generic_visit(node)

    def _record_thread(self, call: ast.Call, binding: str, line: int):
        target = None
        for kw in call.keywords:
            if kw.arg == "target":
                target = kw.value
        if target is None and len(call.args) >= 2:
            target = call.args[1]
        if target is None:
            return
        tname, tcls = "", ""
        if isinstance(target, ast.Name):
            tname = target.id
        elif isinstance(target, ast.Attribute) and \
                isinstance(target.value, ast.Name) and target.value.id == "self":
            tname, tcls = target.attr, self._cls
        if not tname:
            return
        daemon = any(
            kw.arg == "daemon" and isinstance(kw.value, ast.Constant)
            and kw.value.value is True
            for kw in call.keywords
        )
        self.m.threads.append(_ThreadSite(
            target=tname, target_cls=tcls, binding=binding,
            daemon=daemon, line=line,
        ))

    def visit_Call(self, node: ast.Call):
        chain = _chain(node.func)
        if chain in _THREAD_CTORS:
            # unbound construction: threading.Thread(...).start()
            parent_bound = False
            # bound constructions were already recorded via visit_Assign
            for t in self.m.threads:
                if t.line == node.lineno:
                    parent_bound = True
            if not parent_bound:
                self._record_thread(node, binding="", line=node.lineno)
        elif chain == "signal.signal" and len(node.args) >= 2:
            h = node.args[1]
            hname, hcls = "", ""
            if isinstance(h, ast.Name):
                hname = h.id
            elif isinstance(h, ast.Attribute) and \
                    isinstance(h.value, ast.Name) and h.value.id == "self":
                hname, hcls = h.attr, self._cls
            if hname:
                self.m.handlers.append((hname, hcls, node.lineno))
        self.generic_visit(node)


class _FuncScanner:
    """Second pass: per-function accesses, lock acquisitions, calls."""

    def __init__(self, model: ModuleModel):
        self.m = model

    def scan_module(self, tree: ast.Module):
        self._scan_block(tree.body, prefix="", cls="")

    def _scan_block(self, stmts, prefix: str, cls: str):
        for stmt in stmts:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self._scan_function(stmt, f"{prefix}{stmt.name}", cls)
            elif isinstance(stmt, ast.ClassDef):
                self._scan_block(stmt.body, f"{stmt.name}.", stmt.name)

    def _scan_function(self, fn, qualname: str, cls: str):
        func = _Func(node=fn, name=fn.name, qualname=qualname, cls=cls)
        self.m.funcs[qualname] = func
        self._walk(fn.body, func, held=())
        for sub in self._nested_defs(fn):
            self._scan_function(sub, f"{qualname}.{sub.name}", cls)

    def _nested_defs(self, fn):
        out, stack = [], list(fn.body)
        while stack:
            node = stack.pop()
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                out.append(node)
                continue
            stack.extend(ast.iter_child_nodes(node))
        return out

    def _lock_id(self, item: ast.withitem, cls: str) -> Optional[str]:
        chain = _chain(item.context_expr)
        if not chain:
            return None
        canon = _canon(item.context_expr, cls)
        if canon in self.m.locks:
            return canon
        if _is_lockish(chain):
            return canon or chain
        return None

    def _walk(self, stmts, func: _Func, held: tuple):
        for node in stmts:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.ClassDef)):
                continue  # scanned separately with a fresh context
            if isinstance(node, ast.With):
                inner = list(held)
                for item in node.items:
                    lock = self._lock_id(item, func.cls)
                    if lock is not None:
                        func.acquired.append((lock, node.lineno))
                        for outer in inner:
                            func.nest_edges.append((outer, lock, node.lineno))
                        inner.append(lock)
                    else:
                        # a later item's expression runs with the earlier
                        # items' locks already held
                        self._visit_expr(item.context_expr, func, tuple(inner))
                self._walk(node.body, func, tuple(inner))
                continue
            # this statement's own expressions (tests, targets, values)
            for child in ast.iter_child_nodes(node):
                if not isinstance(child, (ast.stmt, ast.excepthandler)):
                    self._visit_expr(child, func, held)
            # nested statement blocks keep the current lock context
            for fname in ("body", "orelse", "finalbody"):
                sub = getattr(node, fname, None)
                if sub and isinstance(sub, list):
                    self._walk(sub, func, held)
            for h in getattr(node, "handlers", None) or []:
                self._walk(h.body, func, held)

    def _visit_expr(self, expr: ast.AST, func: _Func, held: tuple):
        for node in ast.walk(expr):
            if isinstance(node, ast.Attribute):
                self._record_attr(node, func, held)
            elif isinstance(node, ast.Call):
                chain = _chain(node.func)
                if chain:
                    func.calls.append((chain, node.lineno, frozenset(held)))
                # receiver-mutating method call = a write to the receiver,
                # even though its AST context is Load
                if isinstance(node.func, ast.Attribute) and \
                        node.func.attr in _MUTATORS:
                    name = _canon(node.func.value, func.cls)
                    if name and "." in name:
                        func.accesses.append(_Access(
                            attr=name, kind="write", line=node.lineno,
                            locks=frozenset(held),
                        ))
            elif isinstance(node, ast.Subscript) and \
                    isinstance(node.ctx, (ast.Store, ast.Del)):
                name = _canon(node.value, func.cls)
                if name and "." in name:
                    func.accesses.append(_Access(
                        attr=name, kind="write", line=node.lineno,
                        locks=frozenset(held),
                    ))

    def _record_attr(self, node: ast.Attribute, func: _Func, held: tuple):
        if not (isinstance(node.value, ast.Name) and node.value.id == "self"):
            return
        name = f"{func.cls}.{node.attr}" if func.cls else node.attr
        if "." not in name:
            return
        kind = "write" if isinstance(node.ctx, (ast.Store, ast.Del)) else "read"
        func.accesses.append(_Access(
            attr=name, kind=kind, line=node.lineno, locks=frozenset(held),
        ))


def build_model(source: str, path: str = "<string>") -> ModuleModel:
    tree = ast.parse(source, filename=path)
    model = ModuleModel(path=path, lines=source.splitlines())
    _ModelBuilder(model).visit(tree)
    _FuncScanner(model).scan_module(tree)
    return model


# ---------------------------------------------------------------------------
# closures over the module call graph
# ---------------------------------------------------------------------------

def _resolve_call(model: ModuleModel, chain: str, caller: _Func) -> Optional[str]:
    """Map a call chain to a qualname of a function in this module."""
    if chain.startswith("self.") and caller.cls:
        cand = f"{caller.cls}.{chain[5:]}"
        if cand in model.funcs:
            return cand
        return None
    if chain in model.funcs:
        return chain
    # a bare name may be a nested def in the same scope
    cand = f"{caller.qualname}.{chain}"
    if cand in model.funcs:
        return cand
    return None


def _is_target(model: ModuleModel, f: _Func) -> bool:
    return any(
        f.name == t.target and (not t.target_cls or f.cls == t.target_cls)
        for t in model.threads
    )


def _thread_closure(model: ModuleModel) -> Set[str]:
    """Qualnames of functions reachable from any thread target."""
    seeds = [qn for qn, f in model.funcs.items() if _is_target(model, f)]
    seen: Set[str] = set()
    stack = list(seeds)
    while stack:
        qn = stack.pop()
        if qn in seen:
            continue
        seen.add(qn)
        f = model.funcs[qn]
        for chain, _, _ in f.calls:
            callee = _resolve_call(model, chain, f)
            if callee is not None and callee not in seen:
                stack.append(callee)
    return seen


def _main_closure(model: ModuleModel) -> Set[str]:
    """Qualnames reachable from NON-thread entry points (a function in both
    closures — e.g. a worker body also called synchronously — counts on both
    sides; that dual use is exactly where races live)."""
    seeds = [
        qn for qn, f in model.funcs.items()
        if not _is_target(model, f) and f.name != "__init__"
    ]
    seen: Set[str] = set()
    stack = seeds
    while stack:
        qn = stack.pop()
        if qn in seen:
            continue
        seen.add(qn)
        f = model.funcs[qn]
        for chain, _, _ in f.calls:
            callee = _resolve_call(model, chain, f)
            if callee is not None and callee not in seen:
                stack.append(callee)
    return seen


# ---------------------------------------------------------------------------
# rules
# ---------------------------------------------------------------------------

def _mk(model, rule, severity, message, line, symbol) -> Finding:
    snippet = (
        model.lines[line - 1].strip()
        if 0 < line <= len(model.lines) else ""
    )
    return Finding(
        rule=rule, severity=severity, message=message, path=model.path,
        line=line, symbol=symbol, snippet=snippet, engine="concurrency",
    )


def rule_shared_state_unlocked(model: ModuleModel) -> List[Finding]:
    if not model.threads:
        return []
    thread_funcs = _thread_closure(model)
    if not thread_funcs:
        return []
    main_funcs = _main_closure(model)
    # collect per-attribute access sites on each side (skip __init__: it
    # happens-before the thread starts; skip thread-safe primitives)
    t_writes: Dict[str, List[Tuple[_Access, str]]] = {}
    m_access: Dict[str, List[Tuple[_Access, str]]] = {}
    for qn, f in model.funcs.items():
        if f.name == "__init__":
            continue
        for a in f.accesses:
            if a.attr in model.safe_attrs or a.attr in model.locks:
                continue
            if qn in thread_funcs and a.kind == "write":
                t_writes.setdefault(a.attr, []).append((a, qn))
            if qn in main_funcs:
                m_access.setdefault(a.attr, []).append((a, qn))
    out = []
    for attr, writes in sorted(t_writes.items()):
        others = m_access.get(attr, [])
        if not others:
            continue
        # a common lock held at EVERY thread-side write and EVERY other
        # access proves mutual exclusion; anything less is a race window
        common = frozenset.intersection(
            *[a.locks for a, _ in writes], *[a.locks for a, _ in others]
        )
        if common:
            continue
        # anchor at the first under-locked site (prefer the non-thread one:
        # that is where the missing `with lock:` usually belongs, and where
        # a justified waiver reads best)
        anchor = next(
            ((a, qn) for a, qn in others if not a.locks), None
        ) or next(
            ((a, qn) for a, qn in writes if not a.locks), (writes[0])
        )
        a, qn = anchor
        out.append(_mk(
            model, "shared-state-unlocked", SEVERITY_ERROR,
            f"`{attr}` is written from thread code "
            f"({writes[0][1]}) and accessed from {others[0][1]} with no "
            "common lock — torn/lost updates under a real schedule",
            a.line, qn,
        ))
    return out


def rule_lock_order_cycle(model: ModuleModel) -> List[Finding]:
    # edges from lexical nesting + one-level call closure: holding L1 while
    # calling a same-module function that acquires L2 is an L1→L2 edge too
    edges: Dict[Tuple[str, str], Tuple[int, str]] = {}
    for qn, f in model.funcs.items():
        for outer, inner, line in f.nest_edges:
            edges.setdefault((outer, inner), (line, qn))
        for chain, line, held in f.calls:
            if not held:
                continue
            callee = _resolve_call(model, chain, f)
            if callee is None:
                continue
            for lock, _ in model.funcs[callee].acquired:
                for outer in held:
                    if outer != lock:
                        edges.setdefault((outer, lock), (line, qn))
    graph: Dict[str, Set[str]] = {}
    for (a, b) in edges:
        graph.setdefault(a, set()).add(b)
    # DFS cycle detection, reporting each cycle once (canonical rotation)
    out, reported = [], set()

    def dfs(node, stack, on_stack):
        for nxt in sorted(graph.get(node, ())):
            if nxt in on_stack:
                cyc = stack[stack.index(nxt):] + [nxt]
                key = frozenset(cyc)
                if key in reported:
                    continue
                reported.add(key)
                line, qn = edges[(node, nxt)]
                out.append(_mk(
                    model, "lock-order-cycle", SEVERITY_ERROR,
                    "lock-acquisition cycle "
                    + " -> ".join(cyc)
                    + " — two threads taking these in opposite order "
                    "deadlock",
                    line, qn,
                ))
            elif nxt not in visited:
                visited.add(nxt)
                dfs(nxt, stack + [nxt], on_stack | {nxt})

    visited: Set[str] = set()
    for start in sorted(graph):
        if start not in visited:
            visited.add(start)
            dfs(start, [start], {start})
    return out


def rule_signal_unsafe_handler(model: ModuleModel) -> List[Finding]:
    out = []
    for hname, hcls, _ in model.handlers:
        for qn, f in model.funcs.items():
            # exact class match: a module-level handler name must not drag
            # in an unrelated same-named method (hcls is "" for both
            # module-level and nested-in-function handlers)
            if f.name != hname or f.cls != hcls:
                continue
            for chain, line, _ in f.calls:
                if chain in _HANDLER_SAFE_CHAINS:
                    continue
                if any(chain.endswith(s) for s in _HANDLER_SAFE_SUFFIXES):
                    continue
                if chain.startswith("signal."):
                    continue
                out.append(_mk(
                    model, "signal-unsafe-handler", SEVERITY_ERROR,
                    f"signal handler calls {chain}() — only flag-sets, "
                    "os.write/_exit/kill and signal.* are reentrant-safe "
                    "inside a handler",
                    line, qn,
                ))
    return out


def rule_thread_leak(model: ModuleModel) -> List[Finding]:
    out = []
    for t in model.threads:
        if t.daemon:
            continue
        joined = False
        if t.binding:
            needle = t.binding.split(".")[-1]
            for f in model.funcs.values():
                for chain, _, _ in f.calls:
                    parts = chain.split(".")
                    if parts[-1] == "join" and len(parts) >= 2 and \
                            parts[-2] == needle:
                        joined = True
        if not joined:
            out.append(_mk(
                model, "thread-leak", SEVERITY_WARNING,
                f"non-daemon thread (target={t.target}) has no reachable "
                "join() — process exit blocks on it forever",
                t.line, t.binding or t.target,
            ))
    return out


def rule_blocking_under_lock(model: ModuleModel) -> List[Finding]:
    out = []
    for qn, f in model.funcs.items():
        for chain, line, held in f.calls:
            if not held:
                continue
            blocking = (
                chain == "open"
                or any(chain == p or chain.startswith(p)
                       for p in _BLOCKING_PREFIXES)
                or any(chain.endswith(s) for s in _BLOCKING_SUFFIXES)
            )
            if not blocking:
                # Thread.join on a known thread attr while holding a lock:
                # if that thread needs the same lock to finish, deadlock
                parts = chain.split(".")
                if parts[-1] == "join" and len(parts) >= 2:
                    base = ".".join(parts[:-1])
                    canon = base.replace("self.", f"{f.cls}.") if f.cls else base
                    blocking = canon in model.thread_attrs
            if blocking:
                out.append(_mk(
                    model, "blocking-under-lock", SEVERITY_WARNING,
                    f"{chain}() while holding {sorted(held)[0]} — every "
                    "contender stalls for the full blocking call",
                    line, qn,
                ))
    return out


ALL_RULES = (
    rule_shared_state_unlocked,
    rule_lock_order_cycle,
    rule_signal_unsafe_handler,
    rule_thread_leak,
    rule_blocking_under_lock,
)


def check_source(source: str, path: str = "<string>"):
    """Engine C over one source string → (findings, suppressed_count).
    Raises SyntaxError upward like ``ast_rules.lint_source``."""
    model = build_model(source, path=path)
    findings: List[Finding] = []
    for rule in ALL_RULES:
        findings.extend(rule(model))
    findings.sort(key=lambda f: (f.path, f.line, f.rule))
    seen, unique = set(), []
    for f in findings:
        key = (f.rule, f.path, f.line, f.message)
        if key not in seen:
            seen.add(key)
            unique.append(f)
    return apply_suppressions(unique, SuppressionIndex.from_source(source))


def check_file(path: str):
    with open(path, encoding="utf-8") as fh:
        return check_source(fh.read(), path=path)
