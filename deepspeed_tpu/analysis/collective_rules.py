"""Engine D: HLO collective-consistency verifier — SPMD ordering rules.

A multichip program deadlocks the way ROADMAP item 3's hand-pipelined
``ppermute`` chains will: two programs (or two branches of one) disagree
about which collective happens next on a shared mesh axis, every chip waits
for a partner that is executing a different collective, and the run hangs
with zero error text. The compiled HLO states the full collective schedule
— op kind, ``channel_id``, ``replica_groups``/``source_target_pairs``,
async ``-start``/``-done`` pairing — so the desync shapes are checkable at
verify time:

- ``collective-channel-reuse``: one ``channel_id`` claimed by two distinct
  collective ops in a program. XLA assigns channels uniquely; a reused one
  (hand-written ``Send``/``Recv`` ladders, manual channel plumbing) makes
  two logically different collectives rendezvous with each other.
- ``collective-start-orphan``: an async ``-start`` whose result no ``-done``
  consumes (the transfer is never awaited — its buffer lifetime is a race),
  or a ``-done`` with no matching start.
- ``collective-order-inversion``: two async collectives of the same kind on
  the same group set whose dones complete in the opposite order to their
  starts — an in-flight FIFO inversion; legal to XLA's scheduler only when
  it proves independence, a deadlock when a manual pipeline gets it wrong.
- ``collective-order-divergence``: across a program SET (the engine's
  compiled-step cache, both serving executables), programs sharing a
  replica-group signature must issue the same ordered kind-sequence on it.
  Two programs that may run concurrently on one mesh axis but disagree on
  the collective order are the textbook SPMD desync.

All shape/size parsing reuses ``telemetry.introspect.parse_instruction`` —
the third HLO reader in the codebase shares the first one's grammar.
"""

from __future__ import annotations

import re
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from ..telemetry.introspect import parse_instruction
from .findings import SEVERITY_ERROR, SEVERITY_WARNING, Finding

RULES = {
    "collective-channel-reuse":
        "one channel_id claimed by two distinct collectives in a program",
    "collective-start-orphan":
        "async collective start never awaited (or done without start)",
    "collective-order-inversion":
        "async dones complete in the opposite order to their starts",
    "collective-order-divergence":
        "programs sharing a mesh group issue different collective orders",
}

_COLLECTIVE_KINDS = (
    "all-reduce", "all-gather", "reduce-scatter", "all-to-all",
    "collective-permute",
)

_RESULT_NAME = re.compile(r"^\s*(?:ROOT\s+)?%(?P<name>[\w.\-]+)\s*=")
_CHANNEL = re.compile(r"channel_id=(\d+)")
_OPERAND = re.compile(r"%([\w.\-]+)")


def _group_signature(line: str) -> str:
    """Canonical replica-group text: ``replica_groups={{...}}`` (or
    ``source_target_pairs`` for collective-permute), braces matched so the
    nested form survives. '' when absent (full-world default)."""
    for key in ("replica_groups=", "source_target_pairs="):
        at = line.find(key)
        if at < 0:
            continue
        i = line.find("{", at)
        if i < 0:
            continue
        depth = 0
        for j in range(i, len(line)):
            if line[j] == "{":
                depth += 1
            elif line[j] == "}":
                depth -= 1
                if depth == 0:
                    return line[i:j + 1].replace(" ", "")
    return ""


@dataclass
class CollectiveOp:
    """One collective instruction, in program text order."""

    op: str                       # full opcode, e.g. "all-gather-start"
    kind: str                     # base kind, e.g. "all-gather"
    name: str                     # SSA result name (without %)
    channel_id: Optional[int]
    groups: str                   # canonical replica-group signature
    nbytes: int
    line_no: int
    snippet: str
    operands: List[str] = field(default_factory=list)

    @property
    def is_start(self) -> bool:
        return self.op.endswith("-start")

    @property
    def is_done(self) -> bool:
        return self.op.endswith("-done")


def extract_collectives(txt: str) -> List[CollectiveOp]:
    """Ordered collective sequence of one HLO module text."""
    out: List[CollectiveOp] = []
    for i, line in enumerate(txt.splitlines(), start=1):
        op, nbytes, _ = parse_instruction(line)
        if op is None:
            continue
        kind = re.sub(r"-(start|done)$", "", op)
        if kind not in _COLLECTIVE_KINDS:
            continue
        nm = _RESULT_NAME.match(line)
        name = nm.group("name") if nm else ""
        ch = _CHANNEL.search(line)
        # operand names: %refs inside the call parens, minus the result
        call_at = line.find("(", line.find("= "))
        operands = _OPERAND.findall(line[call_at:]) if call_at >= 0 else []
        out.append(CollectiveOp(
            op=op, kind=kind, name=name,
            channel_id=int(ch.group(1)) if ch else None,
            groups=_group_signature(line), nbytes=nbytes,
            line_no=i, snippet=line.strip()[:160],
        ))
        out[-1].operands = operands
    return out


def _finding(program, rule, severity, message, line_no=0, snippet=""):
    return Finding(
        rule=rule, severity=severity, message=message,
        path=f"hlo://{program}", line=line_no, symbol=program,
        snippet=snippet[:160], engine="collective",
    )


# ---------------------------------------------------------------------------
# per-program rules
# ---------------------------------------------------------------------------

def rule_channel_unique(seq: List[CollectiveOp], program: str) -> List[Finding]:
    seen: Dict[int, CollectiveOp] = {}
    out = []
    for c in seq:
        if c.channel_id is None or c.is_done:
            continue  # a -done legitimately echoes its start's channel
        prev = seen.get(c.channel_id)
        if prev is None:
            seen[c.channel_id] = c
        elif (prev.kind, prev.groups) != (c.kind, c.groups) or \
                prev.name != c.name:
            out.append(_finding(
                program, "collective-channel-reuse", SEVERITY_ERROR,
                f"channel_id={c.channel_id} claimed by {prev.op} "
                f"(line {prev.line_no}) and {c.op} (line {c.line_no}) — "
                "two distinct collectives rendezvousing on one channel "
                "cross-match across chips",
                line_no=c.line_no, snippet=c.snippet,
            ))
    return out


def rule_start_done(seq: List[CollectiveOp], program: str) -> List[Finding]:
    """Start/done matching + in-flight FIFO order on (kind, groups)."""
    out = []
    starts = {c.name: c for c in seq if c.is_start}
    consumed: Dict[str, CollectiveOp] = {}
    done_order: List[CollectiveOp] = []
    for c in seq:
        if not c.is_done:
            continue
        src = next((op for op in c.operands if op in starts), None)
        if src is None:
            out.append(_finding(
                program, "collective-start-orphan", SEVERITY_ERROR,
                f"{c.op} (line {c.line_no}) consumes no known "
                f"{c.kind}-start — an unmatched done waits forever",
                line_no=c.line_no, snippet=c.snippet,
            ))
            continue
        consumed[src] = c
        done_order.append(c)
    for name, s in starts.items():
        if name not in consumed:
            out.append(_finding(
                program, "collective-start-orphan", SEVERITY_ERROR,
                f"{s.op} %{name} (line {s.line_no}) is never awaited by a "
                f"{s.kind}-done — the transfer's buffer lifetime is a race",
                line_no=s.line_no, snippet=s.snippet,
            ))

    # FIFO inversion per (kind, groups): dones must retire in start order
    by_key: Dict[Tuple[str, str], List[str]] = {}
    for c in seq:
        if c.is_start and c.name in consumed:
            by_key.setdefault((c.kind, c.groups), []).append(c.name)
    for (kind, groups), names in by_key.items():
        if len(names) < 2:
            continue
        done_pos = {
            src: i for i, d in enumerate(done_order)
            for src in d.operands if src in names
        }
        positions = [done_pos[n] for n in names if n in done_pos]
        if positions != sorted(positions):
            first_bad = names[
                next(i for i in range(len(positions) - 1)
                     if positions[i] > positions[i + 1]) + 1
            ]
            s = starts[first_bad]
            out.append(_finding(
                program, "collective-order-inversion", SEVERITY_WARNING,
                f"in-flight {kind} ops on group {groups or '<world>'} "
                "retire out of start order — a manually pipelined chain "
                "with this shape deadlocks when the inversion is real",
                line_no=s.line_no, snippet=s.snippet,
            ))
    return out


def verify_collective_text(txt: str, program: str = "program") -> List[Finding]:
    """All per-program Engine D rules over one HLO module text."""
    seq = extract_collectives(txt)
    out = rule_channel_unique(seq, program)
    out.extend(rule_start_done(seq, program))
    return out


# ---------------------------------------------------------------------------
# cross-program rule
# ---------------------------------------------------------------------------

def rule_order_divergence(
    sequences: Dict[str, List[CollectiveOp]]
) -> List[Finding]:
    """Programs sharing a replica-group signature must agree on the ordered
    collective kind-sequence they issue on it (SPMD desync check)."""
    per_group: Dict[str, Dict[str, List[CollectiveOp]]] = {}
    for prog, seq in sequences.items():
        for c in seq:
            if c.is_done or not c.groups:
                continue
            per_group.setdefault(c.groups, {}).setdefault(prog, []).append(c)
    out = []
    for groups, progs in sorted(per_group.items()):
        if len(progs) < 2:
            continue
        kinds = {p: [c.kind for c in seq] for p, seq in progs.items()}
        names = sorted(kinds)
        ref = kinds[names[0]]
        for other in names[1:]:
            if kinds[other] != ref:
                c = progs[other][0]
                out.append(_finding(
                    other, "collective-order-divergence", SEVERITY_ERROR,
                    f"programs {names[0]} and {other} share mesh group "
                    f"{groups} but issue different collective orders "
                    f"({'/'.join(ref)} vs {'/'.join(kinds[other])}) — "
                    "run concurrently, every chip waits on a partner doing "
                    "a different collective (SPMD desync)",
                    line_no=c.line_no, snippet=c.snippet,
                ))
    return out


def verify_program_set(programs: Dict[str, str]) -> List[Finding]:
    """Per-program rules over each text + the cross-program divergence
    check; ``programs`` maps program name → post-opt HLO text."""
    out: List[Finding] = []
    sequences = {}
    for name, txt in programs.items():
        sequences[name] = extract_collectives(txt)
        out.extend(rule_channel_unique(sequences[name], name))
        out.extend(rule_start_done(sequences[name], name))
    out.extend(rule_order_divergence(sequences))
    return out


def verify_compiled_set(compiled: Dict[str, object]) -> List[Finding]:
    """``verify_program_set`` over compiled executables (``as_text()``)."""
    return verify_program_set({
        name: (exe.as_text() if hasattr(exe, "as_text") else str(exe))
        for name, exe in compiled.items()
    })
