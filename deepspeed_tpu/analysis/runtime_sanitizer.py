"""Runtime concurrency sanitizer: observed schedules cross-check Engine C.

Engine C (``concurrency_rules``) reasons about locks and threads statically;
this module is the dynamic half. When enabled (the ``analysis.sanitizer``
config knob, or directly in ``dsan``-marked tests), concurrency-bearing
modules build their locks through :func:`maybe_lock` and annotate shared
attribute accesses with :func:`note_read`/:func:`note_write`. The sanitizer
then records, from REAL executions:

- the lock-acquisition order actually observed per thread (edges ``A→B``
  when ``B`` is acquired while ``A`` is held), and
- every cross-thread attribute access with the lock set held at that
  instant.

:meth:`RuntimeSanitizer.findings` converts violations into the same
:class:`~.findings.Finding` model the static engines report (engine
``"dsan"``, pseudo-path ``dsan://runtime``): an observed lock-order cycle is
a ``lock-order-cycle``, and a key written by one thread and touched by
another with disjoint held-lock sets is a ``shared-state-unlocked``. The
static graph says what *could* interleave; the sanitizer says what *did* —
a rule firing in both is a confirmed bug, one firing only statically is a
candidate for a justified waiver.

Cost: one tuple append per lock acquire and one dict update per annotated
access — and ONLY while a sanitizer is installed. When
``analysis.sanitizer`` is disabled, ``note_read``/``note_write`` are
rebound to empty no-op functions and :class:`SanitizedLock` skips its
recording branch, so the instrumented hot paths (``StepTracer.emit``, the
checkpoint writer) pay nothing but the call itself (ISSUE 9 satellite —
BENCH_pr8 measured 35.7% overhead on the instrumented emit micro-path with
the recorder active; BENCH_pr9 re-measures both modes).
"""

from __future__ import annotations

import threading
from typing import Dict, List, Optional, Set, Tuple

from .findings import SEVERITY_ERROR, Finding

RULES = {
    "shared-state-unlocked":
        "observed cross-thread access with disjoint held-lock sets",
    "lock-order-cycle":
        "observed lock-acquisition orders form a cycle",
}

_ACTIVE: Optional["RuntimeSanitizer"] = None


def enable(sanitizer: "RuntimeSanitizer") -> "RuntimeSanitizer":
    """Install ``sanitizer`` as the process-wide active recorder (and swap
    the live ``note_*`` implementations in)."""
    global _ACTIVE, note_read, note_write
    _ACTIVE = sanitizer
    note_read, note_write = _note_read_active, _note_write_active
    return sanitizer


def disable() -> None:
    """Uninstall the recorder and rebind ``note_*`` to the no-ops, so
    disabled runs pay nothing on the instrumented paths (ISSUE 9)."""
    global _ACTIVE, note_read, note_write
    _ACTIVE = None
    note_read, note_write = _note_noop, _note_noop


def active() -> Optional["RuntimeSanitizer"]:
    return _ACTIVE


def from_config(config) -> Optional["RuntimeSanitizer"]:
    """Build + install from an ``analysis.sanitizer`` config section.

    A config with ``enabled=False`` actively UNINSTALLS any process-wide
    sanitizer (the engine's config owns the global: an engine that opted
    out must not inherit a previous engine's instrumentation or keep its
    record tables alive). ``config=None`` (no section at all) leaves a
    manually ``enable()``-d sanitizer untouched."""
    if config is None:
        return None
    if not getattr(config, "enabled", False):
        disable()
        return None
    return enable(RuntimeSanitizer(
        max_events=int(getattr(config, "max_events", 65536))
    ))


def maybe_lock(name: str):
    """A lock for ``name``: instrumented under an active sanitizer, a plain
    ``threading.Lock`` otherwise (the zero-cost passthrough). A
    ``SanitizedLock`` created while enabled also stops recording the moment
    its sanitizer is uninstalled, so a long-lived lock never pins a dead
    recorder's overhead."""
    if _ACTIVE is not None:
        return _ACTIVE.lock(name)
    return threading.Lock()


def _note_noop(owner, attr: str) -> None:
    """The disabled-mode ``note_*``: an empty function — no global read,
    no branch. ``enable()``/``disable()`` rebind the module-level names."""


def _note_read_active(owner, attr: str) -> None:
    san = _ACTIVE
    if san is not None:
        san.note(owner, attr, "read")


def _note_write_active(owner, attr: str) -> None:
    san = _ACTIVE
    if san is not None:
        san.note(owner, attr, "write")


# live bindings: enable()/disable() swap these between the active
# implementations and the no-op (import the MODULE, not the function, to
# observe the swap — tracer.py and writer.py already do)
note_read = _note_noop
note_write = _note_noop


class SanitizedLock:
    """``threading.Lock`` wrapper that reports acquisition order."""

    def __init__(self, sanitizer: "RuntimeSanitizer", name: str):
        self._san = sanitizer
        self.name = name
        self._lock = threading.Lock()

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        ok = self._lock.acquire(blocking, timeout)
        # record only while OUR sanitizer is still the installed one — a
        # lock that outlives its sanitizer degrades to a plain mutex
        # (ISSUE 9: no-op passthrough when analysis.sanitizer is disabled)
        if ok and _ACTIVE is self._san:
            self._san._on_acquire(self.name)
        return ok

    def release(self) -> None:
        # unconditional: _on_release only pops this lock from the thread's
        # held tuple (a no-op if acquire skipped the push), so a disable()
        # that lands mid-hold cannot strand a stale held entry that would
        # fabricate order edges after a later re-enable()
        self._san._on_release(self.name)
        self._lock.release()

    def locked(self) -> bool:
        return self._lock.locked()

    def __enter__(self) -> "SanitizedLock":
        self.acquire()
        return self

    def __exit__(self, *exc) -> bool:
        self.release()
        return False


class RuntimeSanitizer:
    """Records observed lock orders + cross-thread attribute accesses."""

    def __init__(self, max_events: int = 65536):
        self.max_events = int(max_events)
        self._mu = threading.Lock()   # guards the record tables only
        self._tls = threading.local()
        # (held, acquired) lock-name pairs actually observed
        self.order_edges: Dict[Tuple[str, str], int] = {}
        # access key → set of (thread ident, kind, frozenset(held locks))
        self.accesses: Dict[str, Set[Tuple[int, str, frozenset]]] = {}
        self.events = 0
        self.dropped = 0

    # -- recording ------------------------------------------------------
    def lock(self, name: str) -> SanitizedLock:
        return SanitizedLock(self, name)

    def _held(self) -> tuple:
        return getattr(self._tls, "held", ())

    def _on_acquire(self, name: str) -> None:
        held = self._held()
        if held:
            with self._mu:
                for h in held:
                    if h != name:
                        edge = (h, name)
                        self.order_edges[edge] = \
                            self.order_edges.get(edge, 0) + 1
        self._tls.held = held + (name,)

    def _on_release(self, name: str) -> None:
        held = list(self._held())
        for i in range(len(held) - 1, -1, -1):
            if held[i] == name:
                del held[i]
                break
        self._tls.held = tuple(held)

    def note(self, owner, attr: str, kind: str) -> None:
        key = attr if isinstance(owner, str) else \
            f"{type(owner).__name__}.{attr}"
        rec = (threading.get_ident(), kind, frozenset(self._held()))
        with self._mu:
            if self.events >= self.max_events:
                self.dropped += 1
                return
            self.events += 1
            self.accesses.setdefault(key, set()).add(rec)

    def clear(self) -> None:
        with self._mu:
            self.order_edges.clear()
            self.accesses.clear()
            self.events = 0
            self.dropped = 0

    # -- reporting ------------------------------------------------------
    def _mk(self, rule: str, message: str, symbol: str) -> Finding:
        return Finding(
            rule=rule, severity=SEVERITY_ERROR, message=message,
            path="dsan://runtime", line=0, symbol=symbol,
            snippet=message, engine="dsan",
        )

    def findings(self) -> List[Finding]:
        """Violations observed so far, as dslint Findings."""
        out: List[Finding] = []
        with self._mu:
            edges = dict(self.order_edges)
            accesses = {k: set(v) for k, v in self.accesses.items()}

        graph: Dict[str, Set[str]] = {}
        for a, b in edges:
            graph.setdefault(a, set()).add(b)
        reported: Set[frozenset] = set()
        visited: Set[str] = set()

        def dfs(node, stack, on_stack):
            for nxt in sorted(graph.get(node, ())):
                if nxt in on_stack:
                    cyc = stack[stack.index(nxt):] + [nxt]
                    key = frozenset(cyc)
                    if key not in reported:
                        reported.add(key)
                        out.append(self._mk(
                            "lock-order-cycle",
                            "observed acquisition orders form a cycle: "
                            + " -> ".join(cyc),
                            symbol=cyc[0],
                        ))
                elif nxt not in visited:
                    visited.add(nxt)
                    dfs(nxt, stack + [nxt], on_stack | {nxt})

        for start in sorted(graph):
            if start not in visited:
                visited.add(start)
                dfs(start, [start], {start})

        for key, recs in sorted(accesses.items()):
            writes = [r for r in recs if r[1] == "write"]
            if not writes:
                continue
            racy = any(
                w[0] != o[0] and not (w[2] & o[2])
                for w in writes for o in recs
            )
            if racy:
                threads = len({r[0] for r in recs})
                out.append(self._mk(
                    "shared-state-unlocked",
                    f"`{key}` touched by {threads} threads with at least "
                    "one write under disjoint lock sets — a real schedule "
                    "already reached this interleaving",
                    symbol=key,
                ))
        return out
