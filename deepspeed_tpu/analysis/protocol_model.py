"""Engine G (dsproto), pass 2 — bounded explicit-state protocol model checker.

Companion to :mod:`deepspeed_tpu.analysis.protocol_rules` (the AST ownership
lint).  Where the lint proves per-function release obligations, this module
proves the *global* serving protocol: it builds a small counting abstraction
of the scheduler — requests x lifecycle states x per-allocator free-page
counts x prefix-index refcounts — and exhaustively explores every
interleaving of the protocol events (submit / admit / prefill-complete /
disagg handoff / decode / retry-rewind / timeout-evict / prefix-evict /
drain-SIGTERM / preempt) up to a configurable state bound, checking on every
reachable state:

* **refcounts conserved and >= 0** — for each pool,
  ``free + sum(owned) + index_entries == capacity`` and no counter goes
  negative (``proto-refcount-conservation``);
* **zero leaked pages at quiescence** — when every request is terminal and
  the engine has drained, no request still owns pages or holds refs
  (``proto-page-leak``; a single-pool imbalance under disaggregation is
  classified ``proto-dual-reserve``);
* **no use-after-free** — no decode step targets a slot whose pages were
  already released (``proto-use-after-free``);
* **no write into a shared page** — a COW-mapped prefix page is never a
  write target unless it was forked first (``proto-write-shared-page``);
* **no wedge** — every non-terminal state has at least one enabled event,
  so every request eventually reaches a terminal status
  (``proto-request-wedged``).

The abstraction is exact for the quantities it tracks: admission, prefix
lookup/registration, COW forking, disaggregated dual reservation and
handoff, retry rewind, timeout eviction, LRU prefix eviction, and drain all
mirror the accounting the real ``ServingEngine`` performs against
``PageAllocator`` / ``PrefixCache``.  A violation therefore comes with a
*minimal* counterexample (BFS guarantees shortest event trace), and
:func:`replay_trace` drives that trace through the **real** engine — with an
injectable clock and a :class:`ProtocolMonitor` asserting the same
invariants against the live allocators — so counterexamples are
machine-confirmed, not speculative.

Known-bug mutations (``ProtoModelConfig.mutations``) re-introduce specific
defects into the abstract transition relation; the PR gate asserts each one
produces a counterexample and that :func:`apply_engine_mutation` makes the
same defect reproduce on the real engine under replay.
"""

from __future__ import annotations

import re
from collections import deque
from dataclasses import dataclass, field
from typing import Dict, FrozenSet, Iterator, List, Optional, Tuple

from .findings import SEVERITY_ERROR, Finding

__all__ = [
    "MODEL_RULES",
    "MUTATIONS",
    "ProtoModelConfig",
    "ProtoReport",
    "ProtoViolation",
    "ProtocolMonitor",
    "ReplayClock",
    "apply_engine_mutation",
    "default_model_configs",
    "explore",
    "model_findings",
    "replay_fleet_trace",
    "replay_trace",
]


MODEL_RULES: Dict[str, str] = {
    "proto-refcount-conservation": (
        "pool accounting violated: free + owned + index != capacity, or a "
        "refcount went negative"
    ),
    "proto-page-leak": (
        "pages still owned (or prefix refs still held) after every request "
        "reached a terminal status and the engine drained"
    ),
    "proto-use-after-free": (
        "a decode step targeted a slot whose KV pages were already released"
    ),
    "proto-write-shared-page": (
        "a prefill/decode write landed in a prefix-shared page without a "
        "COW fork"
    ),
    "proto-request-wedged": (
        "a reachable state has a non-terminal request but no enabled event "
        "(the request can never finish)"
    ),
    "proto-dual-reserve": (
        "disaggregated admission reserved on both allocators but a terminal "
        "path released only one pool"
    ),
    "proto-host-tier-bound": (
        "host-tier occupancy left the [0, host_budget] envelope: a demotion "
        "or restore miscounted the host-resident pages"
    ),
    "proto-dual-emit": (
        "a migrating (or migrated) session emitted a token on more than one "
        "replica: the source kept decoding after the payload left, or the "
        "destination decoded a slot the source still owns"
    ),
    "proto-replica-page-leak": (
        "a replica died still holding pages (or index refs) owned by "
        "sessions that no longer run there — a migration's source-side "
        "release was skipped"
    ),
}

#: Known-bug mutations for the self-test gate.  Each flips one guard in the
#: abstract transition relation; ``apply_engine_mutation`` mirrors the first
#: two on the real engine.
MUTATIONS: FrozenSet[str] = frozenset(
    {
        "drop-drain-free",    # drain preemption skips the slot's page frees
        "skip-cow-fork",      # full prefix hit maps the shared tail page writable
        "drop-handoff-free",  # disagg handoff never releases the prefill pool
        "double-free-finish", # finish releases the slot's pages twice
        "decode-after-free",  # retry rewind frees pages but keeps decoding
        "skip-queue-drain",   # drain forgets to reject the queued backlog
        "drop-host-free",     # prefix demotion copies to host but skips the
                              # device-side free (page owned by neither tier)
        "drop-migration-free",  # migrate_commit forgets the SOURCE replica's
                                # release: pages/refs leak across replica death
    }
)

# request lifecycle states of the abstraction.  _MIGRATE and _DECODE_B are
# fleet-only (ISSUE 18): a migrating session is dual-owned — source pages
# still held while the destination's reservation exists, exactly like the
# disaggregated dual-reserve window — and _DECODE_B decodes on the peer.
_NEW, _QUEUED, _PREFILL, _HANDOFF, _DECODE, _DONE, _MIGRATE, _DECODE_B = range(8)
_STATUS_NAMES = (
    "new", "queued", "prefill", "handoff", "decode", "done",
    "migrate", "decode_b",
)

# ``draining`` bitfield (plain bool pre-ISSUE-18 traces == bit 0):
_DRAIN = 1       # full drain: admissions stopped fleet-wide
_PREEMPT_A = 2   # replica A received its SIGTERM: migrating sessions out
_DEAD_A = 4      # replica A retired: nothing may touch its pools again

# request tuple layout: (status, own, d_own, sref, reg, cow, emitted, retries)
# own    -- private pages held on the prefill-side pool (sole pool when shared)
# d_own  -- private pages held on the decode pool (disaggregated only)
# sref   -- refs this request holds on prefix-index chain pages
# reg    -- pages this request registered into the index and still refs
#           (non-disagg only: the slot keeps its refs until finish)
# cow    -- 1 when the writable row maps a shared page (skip-cow-fork)


@dataclass(frozen=True)
class ProtoModelConfig:
    """Bounds for one exploration of the abstract serving protocol."""

    requests: int = 2
    slots: int = 2
    prompt_pages: int = 2      # full pages per prompt (page-aligned prompts)
    new_tokens: int = 2        # decode steps per request before finish
    disaggregated: bool = False
    prefix_cache: bool = True
    retry_max: int = 1
    allow_timeout: bool = True
    tiering: bool = False      # host-DRAM second tier for evicted prefix pages
    host_budget: int = 1       # host-tier slots (page capacity of the store)
    # fleet mode (ISSUE 18): replica A is modeled concretely (prefill pool +
    # index), replica B's pool rides the decode-pool machinery — migration
    # dual-owns a session across both exactly like dual-reserve does
    fleet: bool = False
    mutations: FrozenSet[str] = frozenset()
    max_states: int = 200_000

    def __post_init__(self) -> None:
        bad = set(self.mutations) - set(MUTATIONS)
        if bad:
            raise ValueError(f"unknown protocol mutations: {sorted(bad)}")
        if self.tiering and not self.prefix_cache:
            raise ValueError("tiering requires prefix_cache (demotion source)")
        if self.tiering and self.host_budget < 1:
            raise ValueError("tiering requires host_budget >= 1")
        if self.fleet and self.disaggregated:
            raise ValueError(
                "fleet mode reuses the decode pool as replica B; combine "
                "with disaggregated later if both are ever needed at once"
            )

    # Pools are sized so admission can transiently block (pool pressure is
    # part of the explored behaviour) but never permanently starve: enough
    # for every request in flight at once plus one resident index chain.
    @property
    def reserve_pages(self) -> int:
        """Pages a request reserves on its decode-capable pool."""
        return self.prompt_pages + 1

    @property
    def prefill_capacity(self) -> int:
        if self.disaggregated:
            return self.requests * self.prompt_pages + self.prompt_pages
        return self.requests * self.reserve_pages + self.prompt_pages

    @property
    def decode_capacity(self) -> int:
        if self.disaggregated or self.fleet:
            return self.requests * self.reserve_pages
        return 0


@dataclass(frozen=True)
class ProtoViolation:
    rule: str
    message: str
    trace: Tuple[str, ...]   # minimal counterexample event sequence


@dataclass
class ProtoReport:
    config: ProtoModelConfig
    states: int = 0
    transitions: int = 0
    complete: bool = True    # False when max_states truncated the search
    violations: List[ProtoViolation] = field(default_factory=list)

    @property
    def ok(self) -> bool:
        return not self.violations


def default_model_configs() -> Dict[str, ProtoModelConfig]:
    """The stock configurations the dslint gate / bench explore."""
    return {
        "shared": ProtoModelConfig(disaggregated=False),
        "disaggregated": ProtoModelConfig(disaggregated=True),
        "fleet": ProtoModelConfig(fleet=True),
    }


# --------------------------------------------------------------------------
# transition relation
# --------------------------------------------------------------------------

def _initial(cfg: ProtoModelConfig):
    req = (_NEW, 0, 0, 0, 0, 0, 0, 0)
    return (
        (req,) * cfg.requests,
        cfg.prefill_capacity,
        cfg.decode_capacity,
        0,       # index_pages: full pages resident in the prefix chain
        0,       # host_pages: prefix pages demoted to the host-DRAM tier
        0,       # draining bitfield: _DRAIN | _PREEMPT_A | _DEAD_A
    )


def _ev(name: str, i: Optional[int] = None) -> str:
    return name if i is None else f"{name}(r{i})"


def _enabled(cfg: ProtoModelConfig, st) -> List[str]:
    reqs, free_p, free_d, index, host, draining = st
    P, R = cfg.prompt_pages, cfg.reserve_pages
    active = sum(1 for r in reqs if r[0] in (_PREFILL, _HANDOFF, _DECODE))
    # replica B slot pressure (fleet): a migrating session holds its B
    # reservation from migrate_begin on, so it occupies a B slot already
    b_active = sum(1 for r in reqs if r[0] in (_MIGRATE, _DECODE_B))
    out: List[str] = []
    for i, r in enumerate(reqs):
        status = r[0]
        if status == _NEW and not (draining & _DRAIN):
            out.append(_ev("submit", i))
        elif status == _QUEUED:
            if draining == 0 and active < cfg.slots:
                shared = min(index, P - 1) if cfg.prefix_cache else 0
                cow_hit = cfg.prefix_cache and index >= P
                skip_cow = cow_hit and "skip-cow-fork" in cfg.mutations
                if cfg.disaggregated:
                    p_need = P - shared - (1 if skip_cow else 0)
                    if free_p >= p_need and free_d >= R:
                        out.append(_ev("admit", i))
                else:
                    need = R - shared - (1 if skip_cow else 0)
                    if free_p >= need:
                        out.append(_ev("admit", i))
            # ISSUE 18: once replica A drains, the router lands new (and
            # re-queued) work on replica B — its own pool and slots
            if (cfg.fleet and (draining & _PREEMPT_A)
                    and not (draining & _DRAIN)
                    and free_d >= R and b_active < cfg.slots):
                out.append(_ev("admit_b", i))
        elif status == _PREFILL:
            out.append(_ev("prefill_done", i))
            if cfg.allow_timeout:
                out.append(_ev("timeout_evict", i))
            if draining & _DRAIN:
                out.append(_ev("preempt", i))
        elif status == _HANDOFF:
            out.append(_ev("handoff", i))
            if cfg.allow_timeout:
                out.append(_ev("timeout_evict", i))
            if draining & _DRAIN:
                out.append(_ev("preempt", i))
        elif status == _DECODE:
            if not (cfg.fleet and (draining & _PREEMPT_A)):
                # a preempted replica A emits NOTHING more: its sessions
                # migrate or restart — decode here would be dual-emission
                out.append(_ev("decode", i))
                if r[7] < cfg.retry_max and draining == 0:
                    out.append(_ev("retry", i))
            elif not (draining & _DEAD_A) and free_d >= R and b_active < cfg.slots:
                out.append(_ev("migrate_begin", i))
            if cfg.allow_timeout:
                out.append(_ev("timeout_evict", i))
            if draining & _DRAIN:
                out.append(_ev("preempt", i))
        elif status == _MIGRATE:
            out.append(_ev("migrate_commit", i))
            out.append(_ev("migrate_abort", i))
        elif status == _DECODE_B:
            out.append(_ev("decode_b", i))
            if cfg.allow_timeout:
                out.append(_ev("timeout_evict", i))
    if not (draining & _DRAIN):
        out.append("drain")
    if cfg.fleet and draining == 0:
        out.append("replica_preempt")
    if (cfg.fleet and (draining & _PREEMPT_A) and not (draining & _DEAD_A)
            and not any(r[0] in (_PREFILL, _HANDOFF, _DECODE, _MIGRATE)
                        for r in reqs)):
        # A may retire only once nothing still runs (or is mid-flight) there
        out.append("replica_die")
    if (index > 0 and not (draining & _DEAD_A)
            and all(r[3] == 0 and r[4] == 0 for r in reqs)):
        # With a host tier configured the LRU prefix eviction *demotes* the
        # page to host DRAM instead of dropping it (ISSUE 17); the device
        # page is freed either way.  A dead replica's index is frozen.
        out.append("demote_prefix" if cfg.tiering else "evict_prefix")
    if cfg.tiering and host > 0 and free_p > 0 and not (draining & _DEAD_A):
        out.append("restore_prefix")
    return out


def _apply(cfg: ProtoModelConfig, st, ev: str):
    """Apply ``ev`` to ``st``; return ``(next_state, violation_rule|None)``."""
    reqs, free_p, free_d, index, host, draining = st
    reqs = list(reqs)
    P, R = cfg.prompt_pages, cfg.reserve_pages
    vio: Optional[str] = None
    m = re.match(r"(\w+)(?:\(r(\d+)\))?$", ev)
    name, idx = m.group(1), (int(m.group(2)) if m.group(2) else None)

    def release(i: int, skip_free: bool = False) -> None:
        """Terminal release of everything request ``i`` holds."""
        nonlocal free_p, free_d
        s, own, d_own, sref, reg, cow, emitted, retries = reqs[i]
        # pages orphaned by a skipped handoff-free stay leaked: the slot no
        # longer records them, so no terminal path can reclaim them
        orphaned = (
            cfg.disaggregated
            and "drop-handoff-free" in cfg.mutations
            and s == _DECODE
        ) or (
            # a committed migration that skipped the source-side release left
            # the A-pool pages behind permanently: B's terminal path only
            # frees B's reservation
            cfg.fleet
            and "drop-migration-free" in cfg.mutations
            and s == _DECODE_B
        )
        if not skip_free:
            free_d += d_own
            d_own = 0
            if not orphaned:
                free_p += own
                own = sref = reg = 0
            cow = 0
        reqs[i] = (_DONE, own, d_own, sref, reg, cow, emitted, retries)

    if name == "submit":
        s = reqs[idx]
        reqs[idx] = (_QUEUED,) + s[1:]
    elif name == "admit":
        shared = min(index, P - 1) if cfg.prefix_cache else 0
        cow_hit = cfg.prefix_cache and index >= P
        skip_cow = cow_hit and "skip-cow-fork" in cfg.mutations
        sref = shared + (1 if skip_cow else 0)
        cow = 1 if skip_cow else 0
        retries = reqs[idx][7]
        if cfg.disaggregated:
            p_need = P - shared - (1 if skip_cow else 0)
            free_p -= p_need
            free_d -= R
            reqs[idx] = (_PREFILL, p_need, R, sref, 0, cow, 0, retries)
        else:
            need = R - shared - (1 if skip_cow else 0)
            free_p -= need
            reqs[idx] = (_PREFILL, need, 0, sref, 0, cow, 0, retries)
    elif name == "prefill_done":
        s, own, d_own, sref, reg, cow, emitted, retries = reqs[idx]
        if cow:
            # the tail chunk recomputes into the COW-mapped shared page
            vio = vio or "proto-write-shared-page"
            cow = 0
        if cfg.disaggregated:
            reqs[idx] = (_HANDOFF, own, d_own, sref, reg, cow, emitted, retries)
        else:
            k = max(0, P - index) if cfg.prefix_cache else 0
            k = min(k, own)        # only privately-owned pages register
            own -= k
            reg += k
            index += k
            emitted = 1
            reqs[idx] = (_DECODE, own, d_own, sref, reg, cow, emitted, retries)
            if emitted >= cfg.new_tokens:
                pre_own, pre_d = own, d_own
                release(idx)
                if "double-free-finish" in cfg.mutations:
                    free_p += pre_own
                    free_d += pre_d
    elif name == "handoff":
        s, own, d_own, sref, reg, cow, emitted, retries = reqs[idx]
        k = max(0, P - index) if cfg.prefix_cache else 0
        k = min(k, own)
        index += k
        if "drop-handoff-free" in cfg.mutations:
            # registered pages moved to the index; the rest leak with the refs
            own -= k
        else:
            # insert retains registered pages for the index, then the slot's
            # refs on the whole prefill row are dropped: request holds nothing
            free_p += own - k
            own = 0
            sref = 0
        emitted = 1
        reqs[idx] = (_DECODE, own, d_own, sref, reg, cow, emitted, retries)
    elif name == "decode":
        s, own, d_own, sref, reg, cow, emitted, retries = reqs[idx]
        if cow:
            vio = vio or "proto-write-shared-page"
            cow = 0
        if own + d_own == 0:
            # writable row holds no live private pages
            vio = vio or "proto-use-after-free"
        emitted += 1
        reqs[idx] = (s, own, d_own, sref, reg, cow, emitted, retries)
        if emitted >= cfg.new_tokens:
            pre_own, pre_d = own, d_own
            release(idx)
            if "double-free-finish" in cfg.mutations:
                free_p += pre_own
                free_d += pre_d
    elif name == "retry":
        s, own, d_own, sref, reg, cow, emitted, retries = reqs[idx]
        free_p += own
        free_d += d_own
        if "decode-after-free" in cfg.mutations:
            # rewind released the pages but forgot to vacate the slot
            reqs[idx] = (_DECODE, 0, 0, 0, 0, 0, emitted, retries + 1)
        else:
            reqs[idx] = (_QUEUED, 0, 0, 0, 0, 0, 0, retries + 1)
    elif name == "timeout_evict":
        release(idx)
    elif name == "preempt":
        release(idx, skip_free="drop-drain-free" in cfg.mutations)
    elif name == "drain":
        draining |= _DRAIN
        for i, r in enumerate(reqs):
            if r[0] in (_NEW, _QUEUED):
                if "skip-queue-drain" in cfg.mutations and r[0] == _QUEUED:
                    continue        # backlog forgotten: wedged forever
                reqs[i] = (_DONE,) + r[1:]
    elif name == "evict_prefix":
        index -= 1
        free_p += 1
    elif name == "demote_prefix":
        # LRU prefix eviction with a host tier: the page's KV moves to a
        # host slot (evicting the host LRU first when the store is full, so
        # host occupancy saturates at the budget) and the device page is
        # freed.  ``drop-host-free`` skips that free: the page is then owned
        # by neither tier and device conservation breaks.
        index -= 1
        if "drop-host-free" not in cfg.mutations:
            free_p += 1
        host = min(host + 1, cfg.host_budget)
    elif name == "restore_prefix":
        # A prefix hit on a demoted chain restores the page into a freshly
        # allocated device page and drops the host copy.
        host -= 1
        index += 1
        free_p -= 1
    elif name == "replica_preempt":
        # SIGTERM on replica A: the router marks it draining-for-retirement.
        # New admissions land on replica B; live decodes migrate or restart.
        draining |= _PREEMPT_A
    elif name == "replica_die":
        draining |= _DEAD_A
    elif name == "admit_b":
        # router re-lands a queued request on replica B (fresh restart —
        # prefix reuse on B is out of scope for the abstract model, so B
        # sessions are modeled decode-pool-only like a disaggregated row)
        retries = reqs[idx][7]
        free_d -= R
        emitted = 1
        reqs[idx] = (_DECODE_B, 0, R, 0, 0, 0, emitted, retries)
        if emitted >= cfg.new_tokens:
            release(idx)
    elif name == "migrate_begin":
        # session becomes dual-owned (like dual-reserve during handoff): A
        # still holds its pages, B's destination reservation is charged now
        s, own, d_own, sref, reg, cow, emitted, retries = reqs[idx]
        free_d -= R
        d_own += R
        reqs[idx] = (_MIGRATE, own, d_own, sref, reg, cow, emitted, retries)
    elif name == "migrate_commit":
        s, own, d_own, sref, reg, cow, emitted, retries = reqs[idx]
        if s != _MIGRATE:
            vio = vio or "proto-dual-emit"
        if "drop-migration-free" in cfg.mutations:
            # source-side release skipped: A's pages/refs stay charged to the
            # request but no slot records them — leaked across A's death
            pass
        else:
            free_p += own
            own = sref = reg = 0
        cow = 0
        reqs[idx] = (_DECODE_B, own, d_own, sref, reg, cow, emitted, retries)
    elif name == "migrate_abort":
        # crc-failed / no-capacity payload: B's reservation returns, A's
        # pages are released and the request restarts from the queue — or,
        # when the fleet already drained, fails terminally (PREEMPTED): the
        # router never requeues into a drained fleet
        s, own, d_own, sref, reg, cow, emitted, retries = reqs[idx]
        free_p += own
        free_d += d_own
        if draining & _DRAIN:
            reqs[idx] = (_DONE, 0, 0, 0, 0, 0, emitted, retries)
        else:
            reqs[idx] = (_QUEUED, 0, 0, 0, 0, 0, 0, retries)
    elif name == "decode_b":
        s, own, d_own, sref, reg, cow, emitted, retries = reqs[idx]
        if s != _DECODE_B:
            vio = vio or "proto-dual-emit"
        if d_own == 0:
            vio = vio or "proto-use-after-free"
        emitted += 1
        reqs[idx] = (s, own, d_own, sref, reg, cow, emitted, retries)
        if emitted >= cfg.new_tokens:
            release(idx)
    else:  # pragma: no cover - defensive
        raise ValueError(f"unknown event {ev!r}")

    nxt = (tuple(reqs), free_p, free_d, index, host, draining)
    return nxt, vio


def _check_state(cfg: ProtoModelConfig, st) -> Optional[Tuple[str, str]]:
    """Invariant check; returns ``(rule, message)`` or ``None``."""
    reqs, free_p, free_d, index, host, draining = st
    if free_p < 0 or free_d < 0 or index < 0:
        return (
            "proto-refcount-conservation",
            f"negative counter: free_p={free_p} free_d={free_d} index={index}",
        )
    if host < 0 or host > cfg.host_budget:
        return (
            "proto-host-tier-bound",
            f"host tier holds {host} page(s), budget {cfg.host_budget}",
        )
    if host and not cfg.tiering:
        return (
            "proto-host-tier-bound",
            f"host tier holds {host} page(s) with tiering disabled",
        )
    if any(min(r[1:6]) < 0 for r in reqs):
        return ("proto-refcount-conservation", "negative per-request counter")
    held_p = sum(r[1] for r in reqs)
    held_d = sum(r[2] for r in reqs)
    if free_p + held_p + index != cfg.prefill_capacity:
        return (
            "proto-refcount-conservation",
            f"prefill pool: free {free_p} + owned {held_p} + index {index} "
            f"!= capacity {cfg.prefill_capacity}",
        )
    if (cfg.disaggregated or cfg.fleet) and free_d + held_d != cfg.decode_capacity:
        return (
            "proto-refcount-conservation",
            f"decode pool: free {free_d} + owned {held_d} "
            f"!= capacity {cfg.decode_capacity}",
        )
    if cfg.fleet and (draining & _DEAD_A):
        # replica_die is gated on no session running (or migrating) on A, so
        # anything still charged to the A-side pools at death is leaked — a
        # migration's source-side release was skipped
        a_leak = sum(r[1] + r[3] + r[4] for r in reqs)
        if a_leak:
            return (
                "proto-replica-page-leak",
                f"replica A died holding {a_leak} page(s)/ref(s) charged to "
                f"sessions that no longer run there",
            )
    if draining and all(r[0] == _DONE for r in reqs):
        p_leak = sum(r[1] + r[3] + r[4] for r in reqs)
        d_leak = held_d
        if p_leak or d_leak:
            if cfg.disaggregated and (p_leak == 0) != (d_leak == 0):
                return (
                    "proto-dual-reserve",
                    f"one-sided release at quiescence: prefill-side leak "
                    f"{p_leak} page(s)/ref(s), decode-side {d_leak}",
                )
            return (
                "proto-page-leak",
                f"{p_leak + d_leak} page(s)/ref(s) still held at quiescence",
            )
    return None


def explore(cfg: ProtoModelConfig) -> ProtoReport:
    """BFS over the abstract protocol; shortest-trace counterexamples."""
    report = ProtoReport(config=cfg)
    init = _initial(cfg)
    parent: Dict[tuple, Optional[Tuple[tuple, str]]] = {init: None}
    q = deque([init])
    seen_rules: Dict[str, ProtoViolation] = {}

    def trace_to(st, extra: Optional[str] = None) -> Tuple[str, ...]:
        evs: List[str] = []
        cur = st
        while parent[cur] is not None:
            prev, ev = parent[cur]
            evs.append(ev)
            cur = prev
        evs.reverse()
        if extra is not None:
            evs.append(extra)
        return tuple(evs)

    def record(rule: str, message: str, trace: Tuple[str, ...]) -> None:
        if rule not in seen_rules:
            v = ProtoViolation(rule=rule, message=message, trace=trace)
            seen_rules[rule] = v
            report.violations.append(v)

    bad = _check_state(cfg, init)
    if bad:
        record(bad[0], bad[1], ())
    while q:
        if report.states >= cfg.max_states:
            report.complete = False
            break
        st = q.popleft()
        report.states += 1
        evs = _enabled(cfg, st)
        if not evs:
            if any(r[0] != _DONE for r in st[0]):
                stuck = [
                    f"r{i}:{_STATUS_NAMES[r[0]]}"
                    for i, r in enumerate(st[0])
                    if r[0] != _DONE
                ]
                record(
                    "proto-request-wedged",
                    "no enabled event but non-terminal request(s): "
                    + ", ".join(stuck),
                    trace_to(st),
                )
            continue
        for ev in evs:
            report.transitions += 1
            nxt, vio = _apply(cfg, st, ev)
            if vio:
                record(vio, MODEL_RULES[vio], trace_to(st, ev))
            bad = _check_state(cfg, nxt)
            if bad:
                record(bad[0], bad[1], trace_to(st, ev))
                continue   # don't explore past a corrupted state
            if nxt not in parent:
                parent[nxt] = (st, ev)
                q.append(nxt)
    return report


def model_findings(
    report: ProtoReport, program: str = "serving"
) -> List[Finding]:
    """Render a report's violations as standard Engine-G findings."""
    mode = "disagg" if report.config.disaggregated else "shared"
    if report.config.tiering:
        mode += "+tiered"
    out = []
    for v in report.violations:
        trace = " -> ".join(v.trace) if v.trace else "<initial state>"
        out.append(
            Finding(
                rule=v.rule,
                severity=SEVERITY_ERROR,
                message=f"[{mode}] {v.message}; counterexample: {trace}",
                path=f"model://{program}/{mode}",
                line=0,
                symbol=v.rule,
                snippet=trace,
                engine="protocol",
            )
        )
    return out


# --------------------------------------------------------------------------
# counterexample replay on the real engine
# --------------------------------------------------------------------------

class ReplayClock:
    """Injectable monotonic clock for deterministic timeout replay."""

    def __init__(self, start: float = 0.0) -> None:
        self.t = float(start)

    def __call__(self) -> float:
        return self.t

    def advance(self, dt: float) -> None:
        self.t += float(dt)


class ProtocolMonitor:
    """Machine-checks model invariants against a live ``ServingEngine``.

    ``check_step()`` is called between engine steps: every page the next
    decode/chunk-prefill launch will write must be privately owned
    (refcount 1), and both allocators' internal accounting must be
    consistent.  ``check_quiescent()`` additionally runs the engine's own
    ``check_no_leaks``.
    """

    def __init__(self, srv, hook: bool = True) -> None:
        self.srv = srv
        self.violations: List[str] = []
        self._undo_hook = None
        if hook:
            self.install()

    def install(self) -> None:
        """Hook the chunk-prefill launch: an admit can complete its whole
        prefill inside one ``step()``, so the shared-page write-target check
        must run at the launch site, not just between steps."""
        if self._undo_hook is not None:
            return
        srv = self.srv
        orig = srv._advance_chunk
        page = srv.page_size

        def advance(slot_i):
            slot = srv.slots[slot_i]
            req = slot.request
            if req is not None and slot.row is not None:
                alloc = srv.prefill_set.allocator
                lo = slot.prefill_pos // page
                hi = (
                    min(slot.prefill_pos + srv.chunk_width, req.prompt_len)
                    - 1
                ) // page
                for pi in range(lo, hi + 1):
                    self._shared_write(
                        alloc,
                        int(slot.row[0, pi]),
                        f"chunk prefill slot {slot_i}",
                    )
            return orig(slot_i)

        srv._advance_chunk = advance

        def undo():
            srv._advance_chunk = orig

        self._undo_hook = undo

    def uninstall(self) -> None:
        if self._undo_hook is not None:
            self._undo_hook()
            self._undo_hook = None

    def _allocators(self):
        seen = []
        for aset in (self.srv.prefill_set, self.srv.decode_set):
            if all(a is not aset.allocator for a in seen):
                seen.append(aset.allocator)
        return seen

    def _shared_write(self, alloc, pid: int, what: str) -> None:
        if pid and alloc.refcount(pid) > 1:
            self.violations.append(
                f"proto-write-shared-page: {what} targets page {pid} "
                f"with refcount {alloc.refcount(pid)}"
            )

    def check_step(self) -> List[str]:
        srv = self.srv
        start = len(self.violations)
        for alloc in self._allocators():
            err = alloc.check_consistent()
            if err:
                self.violations.append(f"proto-refcount-conservation: {err}")
        page = srv.page_size
        spec_k = getattr(srv, "spec_k", 0) or 0
        for i, slot in enumerate(srv.slots):
            req = slot.request
            if req is None:
                continue
            if slot.prefilling and slot.row is not None:
                # next chunk writes [prefill_pos, prompt_len) through the row
                alloc = srv.prefill_set.allocator
                lo = slot.prefill_pos // page
                hi = (req.prompt_len - 1) // page
                for pi in range(lo, hi + 1):
                    self._shared_write(
                        alloc, int(slot.row[0, pi]), f"chunk prefill slot {i}"
                    )
            elif not slot.prefilling and slot.pos > 0:
                # decode/verify writes [pos, pos + spec_k] through the table
                alloc = srv.decode_set.allocator
                lo = slot.pos // page
                hi = min(
                    (slot.pos + spec_k) // page, srv.pages_per_slot - 1
                )
                for pi in range(lo, hi + 1):
                    self._shared_write(
                        alloc,
                        int(srv.table.block_tables[i, pi]),
                        f"decode slot {i}",
                    )
                live = set(srv.allocator._refs)
                used = {
                    int(p)
                    for p in srv.table.block_tables[i, : slot.pos // page + 1]
                    if int(p) != 0
                }
                dead = used - live
                if dead:
                    self.violations.append(
                        f"proto-use-after-free: decode slot {i} row maps "
                        f"freed page(s) {sorted(dead)}"
                    )
        return self.violations[start:]

    def check_quiescent(self) -> List[str]:
        start = len(self.violations)
        try:
            self.srv.check_no_leaks()
        except Exception as e:
            self.violations.append(f"proto-page-leak: {e}")
        for alloc in self._allocators():
            err = alloc.check_consistent()
            if err:
                self.violations.append(f"proto-refcount-conservation: {err}")
        return self.violations[start:]


def apply_engine_mutation(srv, name: str):
    """Re-introduce a model mutation into a live engine; returns an undo().

    Only the gate mutations are supported on the real engine:

    * ``drop-drain-free`` — preempted slots keep their pages (the drain
      path's frees are skipped), reproducing the leak the model finds;
    * ``skip-cow-fork`` — a full prefix hit maps the shared tail page into
      the writable row instead of forking it by recompute;
    * ``drop-host-free`` — prefix demotion copies the page into the host
      tier but skips the device-side free, so the page is owned by neither
      tier (needs ``serving.tiering`` enabled);
    * ``drop-migration-free`` — a migration's source-side release keeps the
      slot-table bookkeeping but skips the allocator frees, leaking the
      source replica's pages across its death (``srv`` must be a
      :class:`~deepspeed_tpu.serving.fleet.FleetRouter`).
    """
    from deepspeed_tpu.serving.request import RequestStatus

    if name == "drop-drain-free":
        orig_finish = srv._finish_slot

        def finish(slot_i, status, detail, now):
            if status == RequestStatus.PREEMPTED:
                allocs = {id(srv.allocator): srv.allocator,
                          id(srv.prefill_set.allocator):
                          srv.prefill_set.allocator}
                saved = [(a, a.free) for a in allocs.values()]
                for a, _ in saved:
                    a.free = lambda pages: None
                try:
                    return orig_finish(slot_i, status, detail, now)
                finally:
                    for a, f in saved:
                        a.free = f
            return orig_finish(slot_i, status, detail, now)

        srv._finish_slot = finish

        def undo():
            srv._finish_slot = orig_finish

        return undo

    if name == "skip-cow-fork":
        if srv.prefix_cache is None:
            raise ValueError("skip-cow-fork needs prefix_cache enabled")
        if srv.disaggregated:
            raise ValueError("skip-cow-fork replay supports shared mode only")
        cache = srv.prefix_cache
        alloc = srv.allocator
        orig_lookup = cache.lookup
        orig_alloc = alloc.alloc
        pending: List[int] = []

        def lookup(prompt):
            pages, shared_tokens, cow_page = orig_lookup(prompt)
            if cow_page is not None:
                # defeat the fork: remember the shared page; the admission
                # alloc right after this lookup gets it spliced in writable
                pending.append(cow_page)
                return pages, shared_tokens, None
            return pages, shared_tokens, cow_page

        def alloc_fn(n):
            out = orig_alloc(n)
            if pending and out:
                cow = pending.pop()
                alloc.retain([cow])
                alloc.free([out[0]])
                out[0] = cow
            return out

        cache.lookup = lookup
        alloc.alloc = alloc_fn

        def undo():
            cache.lookup = orig_lookup
            alloc.alloc = orig_alloc

        return undo

    if name == "drop-host-free":
        if getattr(srv, "tiering", None) is None:
            raise ValueError("drop-host-free needs serving.tiering enabled")
        cache = srv.prefix_cache
        alloc = cache.allocator
        orig_evict_one = cache._evict_one

        def evict_one():
            # demotion runs inside _evict_one; silence the device-side free
            # for its duration so the demoted page stays allocated
            orig_free = alloc.free
            alloc.free = lambda pages: None
            try:
                return orig_evict_one()
            finally:
                alloc.free = orig_free

        cache._evict_one = evict_one

        def undo():
            cache._evict_one = orig_evict_one

        return undo

    if name == "drop-migration-free":
        reps = getattr(srv, "replicas", None)
        if reps is None:
            raise ValueError("drop-migration-free needs a FleetRouter")
        saved = []
        for rep in reps:
            eng = rep.srv
            orig_release = eng.release_slot

            def release(slot_i, now=None, *, _eng=eng, _orig=orig_release):
                # the migration path frees the source pages via release_slot
                # right before the payload leaves; silence both allocators
                # for its duration so the bookkeeping proceeds pages-in-hand
                allocs = {id(_eng.allocator): _eng.allocator,
                          id(_eng.prefill_set.allocator):
                          _eng.prefill_set.allocator}
                frees = [(a, a.free) for a in allocs.values()]
                for a, _ in frees:
                    a.free = lambda pages: None
                try:
                    return _orig(slot_i, now=now)
                finally:
                    for a, f in frees:
                        a.free = f

            eng.release_slot = release
            saved.append((eng, orig_release))

        def undo():
            for eng, orig in saved:
                eng.release_slot = orig

        return undo

    raise ValueError(f"unsupported engine mutation: {name!r}")


_EV_RE = re.compile(r"(\w+)(?:\(r(\d+)\))?$")


def replay_trace(
    srv,
    trace,
    prompts,
    max_new_tokens: int = 2,
    clock: Optional[ReplayClock] = None,
    max_steps: int = 200,
) -> dict:
    """Drive a counterexample event trace through a real ``ServingEngine``.

    Each abstract event maps onto the concrete API (``submit`` / ``step`` /
    ``drain`` / clock advance for timeouts); a :class:`ProtocolMonitor`
    checks the model's invariants against the live allocators after every
    step and ``check_no_leaks`` at quiescence.  Returns a dict with ``ok``,
    the recorded ``violations``, and the request handles.
    """
    mon = ProtocolMonitor(srv)
    handles: Dict[int, object] = {}
    drained = False
    preempts = sum(1 for ev in trace if ev.startswith("preempt"))
    steps = 0
    for ev in trace:
        m = _EV_RE.match(ev)
        name, idx = m.group(1), (int(m.group(2)) if m.group(2) else None)
        if name == "submit":
            handles[idx] = srv.submit(
                prompts[idx % len(prompts)],
                max_new_tokens=max_new_tokens,
                seed=7 + (idx or 0),
            )
        elif name == "drain":
            srv.drain(deadline_s=0.0 if preempts else 5.0)
            drained = True
        elif name == "timeout_evict":
            if clock is not None:
                clock.advance(1e6)
            srv.step()
            steps += 1
        elif name == "demote_prefix":
            # tiered LRU eviction: force one leaf out of the index; with the
            # tier wired its KV demotes to the host store
            pc = srv.prefix_cache
            if pc is not None and len(pc):
                pc.evict(keep=len(pc) - 1)
            if getattr(srv, "tiering", None) is not None:
                srv.tiering.flush()
        elif name in ("admit", "prefill_done", "handoff", "decode", "retry",
                      "preempt", "evict_prefix", "restore_prefix"):
            if not drained:
                srv.step()
                steps += 1
        mon.check_step()
    # settle: run the engine to quiescence, then drain and leak-check
    while not drained and steps < max_steps and any(
        s.request is not None for s in srv.slots
    ):
        srv.step()
        steps += 1
        mon.check_step()
    if not drained:
        srv.drain(deadline_s=5.0)
    mon.check_quiescent()
    return {
        "ok": not mon.violations,
        "violations": list(mon.violations),
        "steps": steps,
        "handles": handles,
    }


def replay_fleet_trace(
    fleet,
    trace,
    prompts,
    max_new_tokens: int = 2,
    clock: Optional[ReplayClock] = None,
    max_steps: int = 300,
) -> dict:
    """Drive a fleet-model counterexample through a real ``FleetRouter``.

    Replica events map onto the router API (``replica_preempt`` triggers
    :meth:`FleetRouter.preempt` on the first live replica; migration and
    replica-B events advance the fleet), with one :class:`ProtocolMonitor`
    per replica.  A leak the retirement path detects (``check_no_leaks``
    raising inside :meth:`FleetRouter.step`) is recorded as
    ``proto-replica-page-leak`` rather than propagated, so a mutated fleet
    replays red instead of crashing the harness.
    """
    monitors = {rep.rid: ProtocolMonitor(rep.srv) for rep in fleet.replicas}
    violations: List[str] = []
    handles: Dict[int, object] = {}
    drained = False
    steps = 0

    def step_all() -> None:
        nonlocal steps
        try:
            fleet.step()
        except Exception as e:  # retirement leak-check tripping mid-step
            violations.append(f"proto-replica-page-leak: {e}")
        steps += 1
        for rep in fleet.replicas:
            if rep.alive:
                monitors[rep.rid].check_step()

    for ev in trace:
        m = _EV_RE.match(ev)
        name, idx = m.group(1), (int(m.group(2)) if m.group(2) else None)
        if name == "submit":
            handles[idx] = fleet.submit(
                prompts[idx % len(prompts)],
                max_new_tokens=max_new_tokens,
                seed=7 + (idx or 0),
            )
        elif name == "replica_preempt":
            # the abstract model preempts "the" replica running work; pick
            # the most-loaded live replica so the victim actually holds the
            # trace's sessions (mirrors the router's default victim policy)
            alive = fleet.alive()
            if alive:
                victim = max(alive, key=fleet._load)
                fleet.preempt(victim.rid)
        elif name == "drain":
            try:
                fleet.drain(deadline_s=5.0)
            except Exception as e:
                violations.append(f"proto-replica-page-leak: {e}")
            drained = True
        elif name == "timeout_evict":
            if clock is not None:
                clock.advance(1e6)
            step_all()
        elif name in ("admit", "prefill_done", "handoff", "decode", "retry",
                      "preempt", "admit_b", "migrate_begin", "migrate_commit",
                      "migrate_abort", "decode_b", "replica_die",
                      "evict_prefix", "demote_prefix", "restore_prefix"):
            if not drained:
                step_all()
    # settle: run the fleet to quiescence, then drain and leak-check every
    # replica — the dead ones included; a retired replica must hold nothing
    while (not drained and steps < max_steps
           and any(rep.srv.queue or any(s.request is not None
                                        for s in rep.srv.slots)
                   for rep in fleet.alive())):
        step_all()
    if not drained:
        try:
            fleet.drain(deadline_s=5.0)
        except Exception as e:
            violations.append(f"proto-replica-page-leak: {e}")
    for rep in fleet.replicas:
        try:
            rep.srv.check_no_leaks()
        except Exception as e:
            violations.append(f"proto-replica-page-leak: {e}")
    violations.extend(
        v for mon in monitors.values() for v in mon.violations
    )
    return {
        "ok": not violations,
        "violations": violations,
        "steps": steps,
        "handles": handles,
    }
