"""Engine A: HLO program verifiers — rules over the compiled executable.

The post-optimization HLO text (the same source of truth the PR-5
introspection walk and the comms accounting read) states exactly what a
step will do: which buffers alias, which collectives run synchronously,
which dots run in which precision. These rules turn that text into findings
with HLO line provenance, so the failure modes the runtime can only observe
(HBM doubling, serialized collectives, recompilation storms) are caught at
verify time instead:

- ``no-unexpected-allgather``: param-sized all-gathers outside the declared
  ZeRO plan (stage < 3 keeps params resident — a big all-gather means
  accidental replication; compressed-bucket gathers are exempted by exact
  wire size via ``allowed_collective_sizes``).
- ``donation-honored``: the ``input_output_alias`` table must actually alias
  the buffers the caller donated (``TrainState``, the serving KV pools) —
  silent copy-instead-of-alias doubles resident HBM.
- ``no-fp32-upcast``: dot/convolution operands wider than the configured
  compute dtype (metadata matching ``upcast_allow`` — softmax/loss/norm —
  is deliberate mixed precision, everything else is a silent 2x).
- ``collective-overlap``: synchronous (non ``-start/-done``) collectives on
  the critical path while the latency-hiding scheduler flags are set —
  per T3, overlap is a property of the compiled schedule, so its absence
  is visible right here.
- ``static-shapes``: executable-count budgets (exactly 2 serving programs;
  a bounded number of train variants) — more programs means retracing,
  i.e. a recompilation storm in the making.

All size/shape parsing reuses ``telemetry.introspect``'s instruction
grammar so the two HLO readers cannot drift.
"""

from __future__ import annotations

import re
from dataclasses import dataclass
from typing import Dict, FrozenSet, List, Optional, Sequence, Tuple

from ..telemetry.introspect import (
    DTYPE_BYTES,
    operand_shapes,
    parse_instruction,
    shape_bytes,
)
from .findings import SEVERITY_ERROR, SEVERITY_WARNING, Finding

RULES = {
    "no-unexpected-allgather":
        "param-sized all-gather outside the declared ZeRO stage's plan",
    "donation-honored":
        "donated input not aliased to an output (buffer copied, HBM doubled)",
    "no-fp32-upcast":
        "dot/conv operand wider than the configured compute dtype",
    "collective-overlap":
        "synchronous collective on the critical path with overlap flags set",
    "static-shapes":
        "executable count over budget (recompilation storm)",
}

_NP_TO_HLO = {
    "float32": "f32", "float64": "f64", "float16": "f16", "bfloat16": "bf16",
    "int8": "s8", "uint8": "u8", "int16": "s16", "uint16": "u16",
    "int32": "s32", "uint32": "u32", "int64": "s64", "uint64": "u64",
    "bool": "pred", "float8_e4m3fn": "f8e4m3fn", "float8_e5m2": "f8e5m2",
}


def hlo_dtype(np_dtype) -> str:
    """numpy dtype (or name) → HLO element-type name."""
    name = getattr(np_dtype, "name", None) or str(np_dtype)
    return _NP_TO_HLO.get(name, name)


@dataclass
class RuleContext:
    """What the caller *declared* about a program — the rules verify the
    compiled text against this declaration."""

    program: str = "program"
    # -- no-unexpected-allgather --------------------------------------
    zero_stage: int = 0
    allgather_min_bytes: int = 1 << 20
    # exact wire sizes that ARE part of the plan (compressed buckets etc.)
    allowed_collective_sizes: FrozenSet[int] = frozenset()
    # -- donation-honored ---------------------------------------------
    # exact-shape mode: each (hlo_dtype, "d0,d1,...") must be aliased
    expect_aliased_shapes: Sequence[Tuple[str, str]] = ()
    # fraction mode: of entry params >= min_donatable_param_bytes, at least
    # this byte-fraction must be aliased (0 disables the fraction check)
    min_alias_fraction: float = 0.0
    min_donatable_param_bytes: int = 1 << 14
    # -- no-fp32-upcast ------------------------------------------------
    expected_dtype: Optional[str] = None  # "bf16" | "f16" | None = no check
    upcast_allow: str = "softmax|loss|norm|logit|cumsum"
    # -- collective-overlap --------------------------------------------
    overlap_expected: bool = False
    sync_collective_min_bytes: int = 1 << 16

    @property
    def allow_param_allgather(self) -> bool:
        return self.zero_stage >= 3


def _pseudo_path(ctx: RuleContext) -> str:
    return f"hlo://{ctx.program}"


def _finding(ctx, rule, severity, message, line_no=0, snippet=""):
    return Finding(
        rule=rule, severity=severity, message=message,
        path=_pseudo_path(ctx), line=line_no, symbol=ctx.program,
        snippet=snippet[:160], engine="hlo",
    )



# ---------------------------------------------------------------------------
# rules
# ---------------------------------------------------------------------------

def rule_no_unexpected_allgather(txt: str, ctx: RuleContext) -> List[Finding]:
    if ctx.allow_param_allgather:
        return []
    out = []
    for i, line in enumerate(txt.splitlines(), start=1):
        op, nbytes, _ = parse_instruction(line)
        if op is None or not op.startswith("all-gather") or op.endswith("-done"):
            continue
        if nbytes < ctx.allgather_min_bytes or nbytes in ctx.allowed_collective_sizes:
            continue
        out.append(_finding(
            ctx, "no-unexpected-allgather", SEVERITY_ERROR,
            f"{nbytes / 1e6:.1f} MB all-gather in a stage-{ctx.zero_stage} "
            "program — params should stay resident below stage 3; this is "
            "accidental full replication",
            line_no=i, snippet=line.strip(),
        ))
    return out


_ALIAS_ENTRY = re.compile(r"\{[0-9,\s]*\}:\s*\((\d+)\s*,")
_PARAM = re.compile(
    r"%?[\w.\-]+\s*=\s*(?P<dtype>\w+)\[(?P<dims>[0-9,]*)\][^\s]*\s*parameter\((?P<num>\d+)\)"
)


def _aliased_params(txt: str) -> FrozenSet[int]:
    """Parameter numbers the module header aliases to an output.

    The table nests braces (``{ {0}: (1, {}, may-alias) }``), so the body
    is cut by brace matching, not regex. The ONE alias-table parser —
    Engine E (``memory_rules``) reuses it so the two readers of the same
    header cannot drift. The scan cap covers a few thousand donated
    leaves; a table that big prints ~16 chars per entry."""
    start = txt.find("input_output_alias={")
    if start < 0:
        return frozenset()
    i = txt.find("{", start)
    depth, end = 0, len(txt)
    for j in range(i, min(len(txt), i + 65536)):
        if txt[j] == "{":
            depth += 1
        elif txt[j] == "}":
            depth -= 1
            if depth == 0:
                end = j
                break
    body = txt[i + 1: end]
    return frozenset(int(p) for p in _ALIAS_ENTRY.findall(body))


def _entry_params(txt: str) -> Dict[int, Tuple[str, str, int]]:
    """param number → (dtype, dims, line_no), from the ENTRY computation.

    Parameter instructions repeat in nested computations with reused
    numbers; entry params are the ones that matter for aliasing, so keep
    the LAST occurrence of each number (ENTRY prints last in post-opt
    text). Collisions on shape are harmless: donation checks only need
    sizes/shapes, which nested re-declarations share."""
    params: Dict[int, Tuple[str, str, int]] = {}
    entry_at = txt.find("ENTRY")
    scan_txt = txt[entry_at:] if entry_at >= 0 else txt
    offset = txt[:entry_at].count("\n") if entry_at >= 0 else 0
    for i, line in enumerate(scan_txt.splitlines(), start=offset + 1):
        m = _PARAM.search(line)
        if m:
            params[int(m.group("num"))] = (m.group("dtype"), m.group("dims"), i)
    return params


def rule_donation_honored(txt: str, ctx: RuleContext) -> List[Finding]:
    if not ctx.expect_aliased_shapes and ctx.min_alias_fraction <= 0:
        return []
    aliased = _aliased_params(txt)
    params = _entry_params(txt)
    out = []

    # duplicate expected shapes (the two serving pools share one shape)
    # demand that many DISTINCT aliased parameters of that shape
    want: Dict[Tuple[str, str], int] = {}
    for shape in ctx.expect_aliased_shapes:
        want[tuple(shape)] = want.get(tuple(shape), 0) + 1
    for (want_dtype, want_dims), n_want in want.items():
        matches = [
            (num, line_no) for num, (dt, dd, line_no) in params.items()
            if dt == want_dtype and dd == want_dims
        ]
        if len(matches) < n_want:
            out.append(_finding(
                ctx, "donation-honored", SEVERITY_ERROR,
                f"{len(matches)} entry parameter(s) of shape "
                f"{want_dtype}[{want_dims}] (need {n_want}) — a donated "
                "buffer is not an input of this program",
            ))
            continue
        n_aliased = sum(1 for num, _ in matches if num in aliased)
        if n_aliased < n_want:
            num, line_no = next(
                (num, ln) for num, ln in matches if num not in aliased
            )
            out.append(_finding(
                ctx, "donation-honored", SEVERITY_ERROR,
                f"parameter {num} ({want_dtype}[{want_dims}]) is not in the "
                "input_output_alias table — the donated buffer is copied, "
                "doubling its HBM footprint "
                f"({n_aliased}/{n_want} of this shape aliased)",
                line_no=line_no,
            ))

    if ctx.min_alias_fraction > 0:
        big = {
            num: shape_bytes(dt, dd)
            for num, (dt, dd, _) in params.items()
            if shape_bytes(dt, dd) >= ctx.min_donatable_param_bytes
        }
        total = sum(big.values())
        got = sum(b for num, b in big.items() if num in aliased)
        if total > 0 and got / total < ctx.min_alias_fraction:
            out.append(_finding(
                ctx, "donation-honored", SEVERITY_ERROR,
                f"only {got / 1e6:.2f} of {total / 1e6:.2f} MB of large "
                f"inputs are aliased ({got / total:.0%} < "
                f"{ctx.min_alias_fraction:.0%}) — donated state is being "
                "copied instead of reused",
            ))
    return out


def rule_no_fp32_upcast(txt: str, ctx: RuleContext) -> List[Finding]:
    if ctx.expected_dtype not in ("bf16", "f16"):
        return []
    allow = re.compile(ctx.upcast_allow, re.I) if ctx.upcast_allow else None
    expected_bytes = DTYPE_BYTES[ctx.expected_dtype]
    out = []
    for i, line in enumerate(txt.splitlines(), start=1):
        op, _, _ = parse_instruction(line)
        if op not in ("dot", "convolution"):
            continue
        if allow is not None and allow.search(line):
            continue
        wide = [
            f"{dt}[{dd}]" for dt, dd in operand_shapes(line)
            if DTYPE_BYTES.get(dt, 0) > expected_bytes
        ]
        if wide:
            out.append(_finding(
                ctx, "no-fp32-upcast", SEVERITY_WARNING,
                f"{op} consumes {', '.join(wide[:2])} in a "
                f"{ctx.expected_dtype} program — silently paying "
                "full-precision flops and bytes",
                line_no=i, snippet=line.strip(),
            ))
    return out


_SYNC_COLLECTIVES = (
    "all-reduce", "all-gather", "reduce-scatter", "all-to-all",
    "collective-permute",
)


def rule_collective_overlap(txt: str, ctx: RuleContext) -> List[Finding]:
    if not ctx.overlap_expected:
        return []
    out = []
    for i, line in enumerate(txt.splitlines(), start=1):
        op, nbytes, _ = parse_instruction(line)
        if op is None or op not in _SYNC_COLLECTIVES:
            continue  # -start/-done async forms are the overlapped good case
        if nbytes < ctx.sync_collective_min_bytes:
            continue
        out.append(_finding(
            ctx, "collective-overlap", SEVERITY_WARNING,
            f"synchronous {op} of {nbytes / 1e6:.2f} MB while the "
            "latency-hiding scheduler is enabled — this op walls the step "
            "instead of overlapping with compute (T3)",
            line_no=i, snippet=line.strip(),
        ))
    return out


def check_program_budget(
    n_programs: int, budget: int, ctx: RuleContext, exact: bool = False
) -> List[Finding]:
    """``static-shapes``: executable-count budget. ``exact`` demands ==
    (the serving contract: exactly two programs, ever)."""
    bad = (n_programs != budget) if exact else (n_programs > budget)
    if not bad:
        return []
    rel = "!=" if exact else ">"
    return [_finding(
        ctx, "static-shapes", SEVERITY_ERROR,
        f"{n_programs} compiled programs {rel} budget {budget} — input "
        "shapes are leaking into executables (recompilation storm)",
    )]


ALL_PROGRAM_RULES = (
    rule_no_unexpected_allgather,
    rule_donation_honored,
    rule_no_fp32_upcast,
    rule_collective_overlap,
)


def verify_hlo_text(txt: str, ctx: RuleContext) -> List[Finding]:
    """Run every per-program Engine-A rule over one HLO module text."""
    out: List[Finding] = []
    for rule in ALL_PROGRAM_RULES:
        out.extend(rule(txt, ctx))
    return out


def verify_compiled(compiled, ctx: RuleContext) -> List[Finding]:
    """``verify_hlo_text`` over anything with ``as_text()``."""
    txt = compiled.as_text() if hasattr(compiled, "as_text") else str(compiled)
    return verify_hlo_text(txt, ctx)
