"""Engine F: sharding-spec verification — regex spec tables vs real trees.

The TP/disaggregated-serving refactor (ROADMAP item 2, landed: ISSUE 14)
maps checkpoints onto a sharded serving model through
``match_partition_rules``-style tables:
an ordered list of ``(regex, partition_spec)`` pairs, first match wins, one
spec per parameter path. Every production JAX codebase that uses this
pattern hits the same three footguns, one checkpoint at a time:

- a typo'd or stale regex matches NOTHING — the parameter it was written
  for falls through the table and is silently replicated on every device
  (``unmatched-param-rule``);
- a spec names more dims than the leaf has, an axis the mesh doesn't have,
  or an axis whose size doesn't divide the dim — the first ``device_put``
  raises, or worse, silently pads (``spec-rank-mismatch``);
- a large leaf ends up with NO sharded dim after the table + mesh degrade
  — a multi-hundred-MB embedding quietly resident N times
  (``replicated-large-leaf``).

This engine checks the table *pre-compile*: evaluate the tree's shapes with
``jax.eval_shape`` (or pass real arrays — only ``.shape``/``.dtype`` are
read), resolve each leaf's spec through the table exactly the way
``match_partition_rules`` will, degrade axes the mesh cannot implement
(missing or size 1 — the same degrade ``logical_to_spec`` applies), and
report the three findings above with the leaf path as the symbol. No
compile, no device, no checkpoint load.
"""

from __future__ import annotations

import re
from dataclasses import dataclass, field
from typing import Any, Dict, List, Mapping, Optional, Sequence, Tuple

import numpy as np

from .findings import SEVERITY_ERROR, SEVERITY_WARNING, Finding

RULES = {
    "unmatched-param-rule":
        "spec-table regex matches no parameter (its target is silently "
        "replicated)",
    "spec-rank-mismatch":
        "partition spec incompatible with the leaf (rank / unknown mesh "
        "axis / indivisible dim)",
    "replicated-large-leaf":
        "large parameter resolves to fully replicated (no sharded dim)",
}

# a spec entry: None (replicated dim), one axis name, or a tuple of axes
SpecEntry = Any
SpecRule = Tuple[str, Sequence[SpecEntry]]


@dataclass
class ShardingRuleContext:
    """What the spec table is verified against."""

    program: str = "params"
    mesh_axes: Mapping[str, int] = field(default_factory=dict)
    replicated_min_bytes: int = 1 << 20
    # scalars / tiny leaves are never sharded; below this they are exempt
    # from every rule (match_partition_rules' own scalar exemption)
    min_shardable_elements: int = 2


def tree_paths(tree) -> Dict[str, Any]:
    """Flatten a pytree into ``{"a/b/0/c": leaf}`` slash-joined paths —
    the exact naming ``match_partition_rules`` tables are written against."""
    import jax

    flat = jax.tree_util.tree_flatten_with_path(tree)[0]
    out: Dict[str, Any] = {}
    for keypath, leaf in flat:
        parts = []
        for k in keypath:
            if hasattr(k, "key"):
                parts.append(str(k.key))
            elif hasattr(k, "idx"):
                parts.append(str(k.idx))
            elif hasattr(k, "name"):
                parts.append(str(k.name))
            else:
                parts.append(str(k))
        out["/".join(parts)] = leaf
    return out


def _leaf_bytes(leaf) -> int:
    shape = tuple(getattr(leaf, "shape", ()) or ())
    dt = getattr(leaf, "dtype", None)
    itemsize = np.dtype(dt).itemsize if dt is not None else 4
    return int(np.prod(shape, dtype=np.int64)) * itemsize if shape else itemsize


def _spec_entries(spec) -> List[SpecEntry]:
    """Normalize a spec (PartitionSpec, tuple, list, None) to a list."""
    if spec is None:
        return []
    return list(spec)


def _compile_table(rules: Sequence[SpecRule]):
    return [(pat, re.compile(pat), _spec_entries(spec))
            for pat, spec in rules]


def _first_match(compiled, path: str):
    """The one first-match-wins resolution (the SNIPPETS.md idiom):
    → (spec, matched). Both the production resolver and the verifier go
    through here, so they cannot disagree about which rule a path takes."""
    for _pat, rx, spec in compiled:
        if rx.search(path):
            return spec, True
    return (), False


def match_partition_rules(
    rules: Sequence[SpecRule], tree
) -> Dict[str, Sequence[SpecEntry]]:
    """path → spec via first-match-wins ``re.search``. Unmatched leaves map
    to ``()`` (replicated) rather than raising — the verifier reports them
    instead so ALL problems surface in one run."""
    compiled = _compile_table(rules)
    return {
        path: _first_match(compiled, path)[0]
        for path in tree_paths(tree)
    }


def resolve_spec(
    spec: Sequence[SpecEntry],
    shape: Sequence[int],
    mesh_axes: Mapping[str, int],
) -> List[Optional[Tuple[str, ...]]]:
    """The EFFECTIVE per-dim sharding after the mesh degrade: axes the mesh
    does not have, or of size 1, drop to replicated (``logical_to_spec``'s
    behavior). Returns one entry per leaf dim: a tuple of live axes or
    None."""
    out: List[Optional[Tuple[str, ...]]] = []
    for d in range(len(shape)):
        entry = spec[d] if d < len(spec) else None
        axes = entry if isinstance(entry, (tuple, list)) else (
            (entry,) if entry is not None else ()
        )
        live = tuple(
            a for a in axes
            if a is not None and int(mesh_axes.get(a, 1)) > 1
        )
        out.append(live or None)
    return out


def _finding(ctx, rule, severity, message, symbol=""):
    return Finding(
        rule=rule, severity=severity, message=message,
        path=f"spec://{ctx.program}", line=0,
        symbol=symbol or ctx.program, snippet=message[:160], engine="spec",
    )


def verify_spec_table(
    rules: Sequence[SpecRule],
    tree,
    ctx: Optional[ShardingRuleContext] = None,
) -> List[Finding]:
    """Every Engine-F rule over one spec table + one (abstract) param tree.

    ``tree`` may be real arrays, ``jax.eval_shape`` output, or any pytree
    of ``.shape``/``.dtype`` carriers."""
    ctx = ctx or ShardingRuleContext()
    mesh_axes = dict(ctx.mesh_axes)
    paths = tree_paths(tree)
    findings: List[Finding] = []

    compiled = _compile_table(rules)

    # -- unmatched-param-rule: dead table entries -----------------------
    for pat, rx, _spec in compiled:
        if not any(rx.search(p) for p in paths):
            findings.append(_finding(
                ctx, "unmatched-param-rule", SEVERITY_ERROR,
                f"spec-table rule {pat!r} matches no parameter path — the "
                "param it was written for falls through the table and is "
                "silently replicated",
                symbol=pat,
            ))

    for path, leaf in paths.items():
        shape = tuple(getattr(leaf, "shape", ()) or ())
        if int(np.prod(shape, dtype=np.int64) if shape else 1) < \
                ctx.min_shardable_elements:
            continue  # scalars are never sharded; exempt
        spec, matched = _first_match(compiled, path)

        # -- spec-rank-mismatch -----------------------------------------
        bad = False
        if len(spec) > len(shape):
            findings.append(_finding(
                ctx, "spec-rank-mismatch", SEVERITY_ERROR,
                f"spec {tuple(spec)!r} names {len(spec)} dims but "
                f"{path} has rank {len(shape)} (shape {shape})",
                symbol=path,
            ))
            bad = True
        else:
            for d, entry in enumerate(spec):
                axes = entry if isinstance(entry, (tuple, list)) else (
                    (entry,) if entry is not None else ()
                )
                for a in axes:
                    if a is None:
                        continue
                    if a not in mesh_axes:
                        findings.append(_finding(
                            ctx, "spec-rank-mismatch", SEVERITY_ERROR,
                            f"{path} dim {d} names mesh axis {a!r} but the "
                            f"mesh has axes {sorted(mesh_axes)}",
                            symbol=path,
                        ))
                        bad = True
                    elif int(mesh_axes[a]) > 1 and \
                            shape[d] % int(mesh_axes[a]) != 0:
                        findings.append(_finding(
                            ctx, "spec-rank-mismatch", SEVERITY_ERROR,
                            f"{path} dim {d} (size {shape[d]}) is not "
                            f"divisible by mesh axis {a!r} "
                            f"(size {mesh_axes[a]})",
                            symbol=path,
                        ))
                        bad = True
        if bad:
            continue  # a broken spec's replication status is meaningless

        # -- replicated-large-leaf --------------------------------------
        nbytes = _leaf_bytes(leaf)
        if nbytes < ctx.replicated_min_bytes:
            continue
        effective = resolve_spec(spec, shape, mesh_axes)
        if not any(e for e in effective):
            why = (
                f"rule matched but every axis degrades on mesh "
                f"{dict(mesh_axes)}" if matched
                else "no spec-table rule matches this path"
            )
            findings.append(_finding(
                ctx, "replicated-large-leaf", SEVERITY_WARNING,
                f"{path} ({nbytes / 1e6:.2f} MB, shape {shape}) resolves "
                f"to fully replicated — {why}; every device pays "
                f"{nbytes / 1e6:.2f} MB for it",
                symbol=path,
            ))
    return findings


def verify_tree_shardings(
    tree, ctx: Optional[ShardingRuleContext] = None
) -> List[Finding]:
    """``replicated-large-leaf`` over a tree of REAL sharded arrays: reads
    each leaf's actual ``.sharding`` spec (the propagated truth after
    ``device_put``) instead of a declared table. The post-compile
    cross-check to :func:`verify_spec_table`'s pre-compile one."""
    ctx = ctx or ShardingRuleContext()
    findings: List[Finding] = []
    for path, leaf in tree_paths(tree).items():
        nbytes = _leaf_bytes(leaf)
        if nbytes < ctx.replicated_min_bytes:
            continue
        sharding = getattr(leaf, "sharding", None)
        spec = getattr(sharding, "spec", None)
        if spec is None:
            continue
        effective = resolve_spec(
            _spec_entries(spec), tuple(leaf.shape), ctx.mesh_axes
        )
        if not any(e for e in effective):
            findings.append(_finding(
                ctx, "replicated-large-leaf", SEVERITY_WARNING,
                f"{path} ({nbytes / 1e6:.2f} MB) is resident fully "
                "replicated on every device (propagated sharding "
                f"{tuple(_spec_entries(spec))!r})",
                symbol=path,
            ))
    return findings


def rules_from_config(scfg) -> List[SpecRule]:
    """``analysis.sharding.rules`` JSON (``[[regex, [axes...]], ...]``) →
    the SpecRule list (JSON ``null`` → replicated dim)."""
    out: List[SpecRule] = []
    for entry in getattr(scfg, "rules", None) or ():
        pat, spec = entry[0], entry[1]
        out.append((str(pat), [
            tuple(a) if isinstance(a, list) else a for a in (spec or ())
        ]))
    return out
