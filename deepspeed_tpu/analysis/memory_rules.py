"""Engine E: static HBM liveness — what a compiled program *costs* in bytes.

dslint's Engines A/D verify what a program *does*; this engine verifies what
it costs. The post-optimization HLO text of a compiled executable is
scheduled (``is_scheduled=true``), so a def-use live-range walk over the
ENTRY instruction sequence reconstructs the resident-bytes curve the
runtime will actually trace out — before the program ever runs, and
therefore before an OOM or a silently shrunken KV page pool can happen at
3am. ZeRO-Infinity (arXiv:2104.07857) and DeepSpeed-Inference
(arXiv:2207.00032) both stand on exact per-tier byte accounting; this
module makes that accounting a static, CI-gated property.

The buffer model (validated within 10% of ``compiled.memory_analysis()``
on the gpt2-tiny train step and both serving executables — asserted in
``tests/unit/test_memory_analysis.py``):

- every allocating instruction defines a buffer of its printed result size,
  live from its def to its last use;
- ``bitcast`` / ``reshape`` / ``get-tuple-element`` / ``optimization-barrier``
  are views, not allocations — uses of the view keep the SOURCE alive;
- ``tuple`` carries its operands per element, ``while`` updates its init
  tuple in place (XLA's in-place while), ``get-tuple-element(index=k)``
  keeps only element k alive — so a loop-carried KV-pool double-buffer is
  charged exactly once, for exactly the loop's extent;
- ``dynamic-update-slice`` (and DUS-rooted fusions) update their target
  operand in place, matching XLA's emission;
- a ``while`` additionally charges its body's internal peak while it runs
  (the while-body closure), ``conditional`` the max over its branches;
- entry parameters are charged for the whole program (they are the caller's
  resident arrays); ROOT-reachable buffers stay live to the end.

``peak_bytes`` = entry-argument bytes + the walk's peak over live internal
buffers. The live-at-peak ledger is categorized — params / kv-pool /
activations / collective-scratch / temp — so a budget failure names the
tier that grew.

Rules:

- ``hbm-over-budget``: peak above the program's committed byte budget
  (``analysis.memory`` config + the committed ``.dsmem-budgets.json``
  ledger) — the CI gate for items 2/3/5 of the roadmap.
- ``donation-missed-bytes``: an undonated entry parameter that is dead
  before the peak — aliasing it (donate_argnums) would hand its bytes back
  to the allocator and cut the peak by up to its size.
- ``oversized-collective-scratch``: collective staging buffers holding an
  outsized share of the live-at-peak bytes.
- ``padding-waste``: a tiled layout (``{...:T(8,128)...}``) whose physical
  bytes exceed the logical bytes by more than the configured ratio.
"""

from __future__ import annotations

import json
import os
import re
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

from ..telemetry.introspect import (
    DTYPE_BYTES,
    NamedInstruction,
    entry_computation,
    parse_named_instruction,
    shape_bytes,
    split_computations,
)
from .findings import SEVERITY_ERROR, SEVERITY_WARNING, Finding

# the ONE alias-table parser (Engine A owns it; a second copy of the
# brace-matched cut would let the two readers of the same header drift)
from .hlo_rules import _PARAM as _PARAM_DECL
from .hlo_rules import _aliased_params as _aliased_param_numbers

RULES = {
    "hbm-over-budget":
        "static peak HBM above the program's committed byte budget",
    "donation-missed-bytes":
        "undonated input dead before the peak — donating it would cut peak",
    "oversized-collective-scratch":
        "collective staging buffers hold an outsized share of peak HBM",
    "padding-waste":
        "tiled layout's physical bytes far exceed the logical bytes",
}

DEFAULT_BUDGET_NAME = ".dsmem-budgets.json"

# buffer categories in the live-at-peak ledger. "metadata" (ISSUE 10) is
# the serving control plane: integer block tables, draft-token batches and
# page maps — the device shadow of the scheduler's host-side
# refcount/prefix-index state, labeled so the ledger separates them from
# model temps.
CATEGORIES = ("params", "kv-pool", "activations", "collective-scratch",
              "temp", "metadata")

_METADATA_DTYPES = frozenset(("s8", "s16", "s32", "s64", "u8", "u16", "u32",
                              "u64", "pred"))

_COLLECTIVE_BASES = (
    "all-reduce", "all-gather", "reduce-scatter", "all-to-all",
    "collective-permute",
)

# view ops: zero allocation, uses keep the source buffer alive
_VIEW_OPS = frozenset((
    "bitcast", "reshape", "optimization-barrier", "get-tuple-element",
    "copy-done",
))


@dataclass
class MemoryRuleContext:
    """Declared memory expectations the compiled text is verified against."""

    program: str = "program"
    # -- hbm-over-budget ----------------------------------------------
    budget_bytes: int = 0                 # 0 = no budget check
    # -- donation-missed-bytes ----------------------------------------
    check_donation: bool = True
    donation_min_bytes: int = 1 << 16
    # -- oversized-collective-scratch ---------------------------------
    scratch_max_fraction: float = 0.25
    scratch_min_bytes: int = 1 << 20
    # -- padding-waste -------------------------------------------------
    padding_waste_min_ratio: float = 1.5
    padding_waste_min_bytes: int = 1 << 16
    # -- categorization ------------------------------------------------
    # dim strings ("L,P,KV,page,D") whose buffers are the serving KV pool
    kv_pool_dims: Sequence[str] = ()
    # dim strings of integer control-plane buffers (block tables, draft
    # batches, page maps) labeled "metadata"; only integer/pred dtypes
    # match, so a float activation sharing a dim string stays put
    metadata_dims: Sequence[str] = ()
    # dim strings of the quantized KV pool's per-page scales ([L,P,KV,2]
    # fp32, ISSUE 12) — also "metadata" (they are bookkeeping beside the
    # pool, not page payload), but FLOAT, so they get their own declared
    # list instead of widening metadata_dims' dtype guard
    scales_dims: Sequence[str] = ()
    # metadata source/op hint that marks a temp buffer as an activation
    activation_hint: str = r"models/|attention|attn|mlp|embed|transformer"


@dataclass
class LiveBuffer:
    """One buffer in the live-at-peak ledger."""

    name: str
    nbytes: int
    category: str
    line: int = 0

    def to_dict(self) -> Dict:
        return {
            "name": self.name, "bytes": self.nbytes,
            "category": self.category, "line": self.line,
        }


@dataclass
class MemoryAnalysis:
    """Static memory profile of one compiled program."""

    program: str = "program"
    args_bytes: int = 0            # entry parameters (resident for the call)
    aliased_bytes: int = 0         # donated args (aliased input->output)
    walk_peak_bytes: int = 0       # peak over internal/output buffers
    peak_line: int = 0             # 1-based HLO line of the peak instruction
    live_at_peak: List[LiveBuffer] = field(default_factory=list)
    by_category: Dict[str, int] = field(default_factory=dict)
    # undonated params dead before the peak: (name, bytes, def_line)
    donation_candidates: List[Tuple[str, int, int]] = field(
        default_factory=list
    )
    n_buffers: int = 0

    @property
    def peak_bytes(self) -> int:
        return self.args_bytes + self.walk_peak_bytes

    def to_dict(self) -> Dict:
        return {
            "program": self.program,
            "peak_bytes": self.peak_bytes,
            "args_bytes": self.args_bytes,
            "aliased_bytes": self.aliased_bytes,
            "walk_peak_bytes": self.walk_peak_bytes,
            "peak_line": self.peak_line,
            "by_category": dict(self.by_category),
            "n_buffers": self.n_buffers,
            "donation_candidates": [
                {"param": n, "bytes": b, "line": ln}
                for n, b, ln in self.donation_candidates
            ],
        }


# ---------------------------------------------------------------------------
# the liveness walk
# ---------------------------------------------------------------------------

_TYPED_OPND = re.compile(
    r"(\w+)\[([0-9,]*)\](?:\{[^}]*\})?\s+%([\w.\-]+)"
)
_META_OP = re.compile(r'op_name="([^"]*)"')
_META_SRC = re.compile(r'source_file="([^"]*)"')


def _is_dus(inst: NamedInstruction) -> bool:
    return inst.op == "dynamic-update-slice" or (
        inst.op == "fusion" and "dynamic-update-slice" in inst.name
    )


def _dus_target(inst: NamedInstruction) -> Optional[str]:
    """The operand a dynamic-update-slice updates in place: the first
    operand printed with the result's own shape."""
    if not inst.result_shapes:
        return None
    want = inst.result_shapes[0]
    for dt, dd, name in _TYPED_OPND.findall(inst.line):
        if (dt, dd) == want and name != inst.name:
            return name
    return None


def _categorize(inst: NamedInstruction, ctx: MemoryRuleContext,
                act_re, pool_dims: frozenset) -> str:
    base = re.sub(r"-(start|done)$", "", inst.op)
    if base in _COLLECTIVE_BASES:
        return "collective-scratch"
    if pool_dims and any(dd in pool_dims for _, dd in inst.result_shapes):
        return "kv-pool"
    meta_dims = frozenset(ctx.metadata_dims)
    if meta_dims and any(
        dd in meta_dims and dt in _METADATA_DTYPES
        for dt, dd in inst.result_shapes
    ):
        return "metadata"
    scl_dims = frozenset(ctx.scales_dims)
    if scl_dims and any(dd in scl_dims for _, dd in inst.result_shapes):
        return "metadata"
    if act_re is not None:
        op_m = _META_OP.search(inst.line)
        src_m = _META_SRC.search(inst.line)
        hint = (op_m.group(1) if op_m else "") + " " + \
            (src_m.group(1) if src_m else "")
        if hint.strip() and act_re.search(hint):
            return "activations"
    return "temp"


class _Walker:
    """Def-use live-range pass over one computation's scheduled lines."""

    def __init__(self, comps: Dict[str, List[str]], ctx: MemoryRuleContext,
                 memo: Dict[str, int]):
        self.comps = comps
        self.ctx = ctx
        self.memo = memo  # computation name -> internal temp peak
        self.act_re = (
            re.compile(ctx.activation_hint, re.I)
            if ctx.activation_hint else None
        )
        self.pool_dims = frozenset(ctx.kv_pool_dims)

    def comp_peak(self, cname: str) -> int:
        """Internal peak of a nested computation (while body / branch)."""
        if cname in self.memo:
            return self.memo[cname]
        self.memo[cname] = 0  # recursion guard
        peak = self.walk(self.comps.get(cname, []))[0]
        self.memo[cname] = peak
        return peak

    def walk(self, lines: Sequence[str], line_base: int = 0,
             want_ledger: bool = False):
        """→ (peak_bytes, peak_line, live_at_peak ledger, param_last_use).

        ``param_last_use`` maps entry-parameter NAME → index of its last
        use (for the donation rule); only populated on the entry walk."""
        ctx = self.ctx
        insts: List[Tuple[int, NamedInstruction]] = []
        for off, line in enumerate(lines):
            p = parse_named_instruction(line)
            if p is not None:
                insts.append((line_base + off + 1, p))

        # value model: name -> frozenset of storage roots, or a list of
        # frozensets for tuple-typed values (per-element liveness)
        val: Dict[str, object] = {}
        size: Dict[str, int] = {}
        cat: Dict[str, str] = {}
        def_line: Dict[str, int] = {}
        param_names: Dict[str, int] = {}  # name -> def line

        def _flat(v) -> set:
            if isinstance(v, list):
                out: set = set()
                for s in v:
                    out |= s
                return out
            return set(v)

        def V(n):
            return val.get(n, frozenset())

        for idx, (lineno, inst) in enumerate(insts):
            name, op = inst.name, inst.op
            if op == "parameter":
                # a param's storage is tracked (for donation liveness) but
                # never counted in the walk — it lives in args_bytes
                val[name] = frozenset((f"param:{name}",))
                param_names[name] = lineno
            elif op == "get-tuple-element" and inst.operands:
                src = V(inst.operands[0])
                mi = re.search(r"index=(\d+)", inst.attrs)
                if isinstance(src, list) and mi and \
                        int(mi.group(1)) < len(src):
                    val[name] = src[int(mi.group(1))]
                else:
                    val[name] = frozenset(_flat(src))
            elif op in _VIEW_OPS and inst.operands:
                val[name] = V(inst.operands[0])
            elif op == "tuple":
                val[name] = [frozenset(_flat(V(o))) for o in inst.operands]
            elif op == "while" and inst.operands:
                val[name] = V(inst.operands[0])  # in-place while
            elif _is_dus(inst):
                tgt = _dus_target(inst)
                if tgt is not None and not isinstance(V(tgt), list):
                    val[name] = V(tgt)  # in-place update
                else:
                    size[name] = inst.result_bytes
                    val[name] = frozenset((name,))
            else:
                size[name] = inst.result_bytes
                val[name] = frozenset((name,))
            if name in size:
                cat[name] = _categorize(inst, ctx, self.act_re,
                                        self.pool_dims)
                def_line[name] = lineno

        # loop-carried refinement: buffers flowing into a while's init tuple
        # are the activation-stack shape (saved residuals / accumulators) —
        # their defining instruction is usually a bare copy with no
        # metadata, so the hint regex can't see them
        for lineno, inst in insts:
            if inst.op != "while" or not inst.operands:
                continue
            for r in _flat(V(inst.operands[0])):
                if cat.get(r) == "temp":
                    cat[r] = "activations"

        # last use per storage root (the def-use chain's "use" side)
        last: Dict[str, int] = {}
        n = len(insts)
        for idx, (lineno, inst) in enumerate(insts):
            if inst.op == "get-tuple-element":
                use = set(_flat(V(inst.name)))  # only the picked element
            else:
                use = set()
                for o in inst.operands:
                    use |= _flat(V(o))
            for r in use:
                last[r] = idx
            if inst.is_root:
                for r in _flat(V(inst.name)) | {inst.name}:
                    last[r] = n  # outputs live to the end

        live = peak = 0
        peak_idx = -1
        live_set: set = set()
        peak_set: set = set()
        ends: Dict[int, List[str]] = {}
        for idx, (lineno, inst) in enumerate(insts):
            transient = 0
            if inst.op == "while":
                m = re.search(r"body=%?([\w.\-]+)", inst.line)
                if m:
                    transient += self.comp_peak(m.group(1))
            elif inst.op == "conditional":
                # indexed form: branch_computations={%c0, %c1, ...};
                # predicated form: true_computation=%ct, false_computation=%cf
                brs = re.findall(
                    r"branch_computations=\{([^}]*)\}", inst.line
                )
                names = re.findall(r"%?([\w.\-]+)", brs[0]) if brs else \
                    re.findall(
                        r"(?:true|false)_computation=%?([\w.\-]+)",
                        inst.line,
                    )
                transient += max(
                    (self.comp_peak(c) for c in names if c), default=0
                )
            if inst.name in size:
                live += size[inst.name]
                live_set.add(inst.name)
                ends.setdefault(last.get(inst.name, idx), []).append(
                    inst.name
                )
            if live + transient > peak:
                peak, peak_idx = live + transient, idx
                peak_set = set(live_set)
            for dead in ends.pop(idx, ()):
                live -= size[dead]
                live_set.discard(dead)

        peak_line = insts[peak_idx][0] if 0 <= peak_idx < n else 0
        ledger = []
        if want_ledger:
            ledger = [
                LiveBuffer(name=b, nbytes=size[b], category=cat[b],
                           line=def_line.get(b, 0))
                for b in sorted(peak_set, key=lambda b: -size[b])
            ]
        param_last = {
            p: last.get(f"param:{p}", -1) for p in param_names
        }
        # resolve param last-use index -> "dead before peak?" for the caller
        param_dead_before_peak = {
            p: (ix < peak_idx) for p, ix in param_last.items()
        }
        return (peak, peak_line, ledger,
                {"def_line": param_names, "dead": param_dead_before_peak})


def analyze_memory_text(
    txt: str, ctx: Optional[MemoryRuleContext] = None
) -> MemoryAnalysis:
    """Walk one post-optimization HLO module into a :class:`MemoryAnalysis`.

    The text must be the scheduled post-opt dump (``compiled.as_text()``);
    an unscheduled module still parses but the peak is then an instruction-
    order estimate rather than the compiler's schedule."""
    ctx = ctx or MemoryRuleContext()
    ana = MemoryAnalysis(program=ctx.program)
    comps = split_computations(txt)
    entry = entry_computation(txt)
    if entry is None or entry not in comps:
        return ana

    aliased_nums = _aliased_param_numbers(txt)
    pool_dims = frozenset(ctx.kv_pool_dims)

    # entry params: args_bytes + the params/kv-pool categories of the ledger
    params: Dict[str, Tuple[str, str, int, int]] = {}
    entry_lines = comps[entry]
    for lineno, line in enumerate(entry_lines, start=1):
        m = _PARAM_DECL.search(line)
        if m:
            params[_param_name(line)] = (
                m.group("dtype"), m.group("dims"),
                int(m.group("num")), lineno,
            )
    meta_dims = frozenset(ctx.metadata_dims)
    args_by_cat = {"params": 0, "kv-pool": 0, "metadata": 0}
    param_buffers: List[LiveBuffer] = []
    for pname, (dt, dd, num, lineno) in params.items():
        b = shape_bytes(dt, dd) if dt in DTYPE_BYTES else 0
        if dd in pool_dims:
            category = "kv-pool"
        elif dd in meta_dims and dt in _METADATA_DTYPES:
            category = "metadata"
        elif dd in frozenset(ctx.scales_dims):
            category = "metadata"
        else:
            category = "params"
        args_by_cat[category] += b
        param_buffers.append(LiveBuffer(pname, b, category, lineno))
        ana.args_bytes += b
        if num in aliased_nums:
            ana.aliased_bytes += b

    walker = _Walker(comps, ctx, memo={})
    peak, peak_line, ledger, pinfo = walker.walk(
        entry_lines, want_ledger=True
    )
    ana.walk_peak_bytes = peak
    ana.peak_line = peak_line
    ana.live_at_peak = (
        sorted(param_buffers, key=lambda b: -b.nbytes) + ledger
    )
    ana.n_buffers = len(ana.live_at_peak)

    by_cat = {c: 0 for c in CATEGORIES}
    by_cat["params"] = args_by_cat["params"]
    by_cat["kv-pool"] = args_by_cat["kv-pool"]
    by_cat["metadata"] = args_by_cat["metadata"]
    for buf in ledger:
        by_cat[buf.category] = by_cat.get(buf.category, 0) + buf.nbytes
    # while-body internal peaks are charged transiently at the while line
    # but have no named ENTRY buffer — fold the remainder into temp so the
    # category breakdown always sums to peak_bytes
    residual = ana.peak_bytes - sum(by_cat.values())
    if residual > 0:
        by_cat["temp"] += residual
    ana.by_category = by_cat

    if ctx.check_donation:
        for pname, (dt, dd, num, lineno) in params.items():
            if num in aliased_nums or dt not in DTYPE_BYTES:
                continue
            b = shape_bytes(dt, dd)
            if b >= ctx.donation_min_bytes and pinfo["dead"].get(pname):
                ana.donation_candidates.append((pname, b, lineno))
    return ana


def _param_name(line: str) -> str:
    m = re.match(r"\s*(?:ROOT\s+)?%?([\w.\-]+)\s*=", line)
    return m.group(1) if m else line.strip()[:40]


# ---------------------------------------------------------------------------
# rules
# ---------------------------------------------------------------------------

def _finding(ctx, rule, severity, message, line_no=0, snippet=""):
    return Finding(
        rule=rule, severity=severity, message=message,
        path=f"hlo://{ctx.program}", line=line_no, symbol=ctx.program,
        snippet=(snippet or message)[:160], engine="mem",
    )


def rule_hbm_over_budget(
    ana: MemoryAnalysis, ctx: MemoryRuleContext
) -> List[Finding]:
    if ctx.budget_bytes <= 0 or ana.peak_bytes <= ctx.budget_bytes:
        return []
    cats = ", ".join(
        f"{k}={v / 1e6:.2f}MB" for k, v in ana.by_category.items() if v
    )
    return [_finding(
        ctx, "hbm-over-budget", SEVERITY_ERROR,
        f"static peak HBM {ana.peak_bytes / 1e6:.2f} MB exceeds the "
        f"committed budget {ctx.budget_bytes / 1e6:.2f} MB "
        f"(+{100.0 * (ana.peak_bytes - ctx.budget_bytes) / ctx.budget_bytes:.1f}%); "
        f"live at peak: {cats}",
        line_no=ana.peak_line,
    )]


def rule_donation_missed(
    ana: MemoryAnalysis, ctx: MemoryRuleContext
) -> List[Finding]:
    out = []
    for pname, b, lineno in ana.donation_candidates:
        out.append(_finding(
            ctx, "donation-missed-bytes", SEVERITY_WARNING,
            f"entry parameter %{pname} ({b / 1e6:.2f} MB) is dead before "
            "the peak and not donated — aliasing it (donate_argnums) would "
            f"cut peak HBM by up to {b / 1e6:.2f} MB",
            line_no=lineno, snippet=f"%{pname}",
        ))
    return out


def rule_oversized_collective_scratch(
    ana: MemoryAnalysis, ctx: MemoryRuleContext
) -> List[Finding]:
    scratch = ana.by_category.get("collective-scratch", 0)
    peak = max(1, ana.peak_bytes)
    if scratch < ctx.scratch_min_bytes:
        return []
    if scratch / peak <= ctx.scratch_max_fraction:
        return []
    return [_finding(
        ctx, "oversized-collective-scratch", SEVERITY_WARNING,
        f"collective staging buffers hold {scratch / 1e6:.2f} MB "
        f"({scratch / peak:.0%}) of the {peak / 1e6:.2f} MB peak — combine "
        "thresholds or bucket sizes are staging more than they hide",
        line_no=ana.peak_line,
    )]


_LAYOUT_TILED = re.compile(
    r"(?P<dtype>\w+)\[(?P<dims>[0-9,]+)\]\{(?P<perm>[0-9,]+):"
    r"(?P<tiles>[^}]*T\([^)]*\)[^}]*)\}"
)
_TILE = re.compile(r"T\(([0-9,*]+)\)")


def padded_bytes(dtype: str, dims: str, perm: str, tiles: str) -> int:
    """Physical bytes of a tiled layout: minor dims round up to the first
    tile's multiples (sub-tiles like ``(2,1)`` repack without padding
    beyond the major tile, so only ``T(...)`` is charged)."""
    sizes = [int(d) for d in dims.split(",") if d]
    order = [int(p) for p in perm.split(",") if p]
    m = _TILE.search(tiles)
    if not m or not sizes or len(order) != len(sizes):
        return shape_bytes(dtype, dims)
    tile = [t for t in m.group(1).split(",") if t and t != "*"]
    tile_sizes = [int(t) for t in tile]
    padded = list(sizes)
    # tile dims map onto the minor-most layout dims, innermost last
    for k, t in enumerate(reversed(tile_sizes)):
        if k >= len(order):
            break
        dim = order[k]  # k-th minor logical dim
        padded[dim] = -(-padded[dim] // t) * t
    n = 1
    for d in padded:
        n *= d
    return n * DTYPE_BYTES.get(dtype, 4)


def rule_padding_waste(txt: str, ctx: MemoryRuleContext) -> List[Finding]:
    out = []
    seen = set()
    for i, line in enumerate(txt.splitlines(), start=1):
        m = _LAYOUT_TILED.search(line)
        if not m:
            continue
        logical = shape_bytes(m.group("dtype"), m.group("dims"))
        physical = padded_bytes(
            m.group("dtype"), m.group("dims"), m.group("perm"),
            m.group("tiles"),
        )
        waste = physical - logical
        if logical <= 0 or waste < ctx.padding_waste_min_bytes:
            continue
        if physical / logical < ctx.padding_waste_min_ratio:
            continue
        key = (m.group("dtype"), m.group("dims"), m.group("tiles"))
        if key in seen:
            continue  # one finding per distinct padded shape
        seen.add(key)
        out.append(_finding(
            ctx, "padding-waste", SEVERITY_WARNING,
            f"{m.group('dtype')}[{m.group('dims')}] pads to "
            f"{physical / 1e6:.2f} MB physical for {logical / 1e6:.2f} MB "
            f"logical ({physical / logical:.1f}x) under tiling "
            f"{m.group('tiles').strip()} — reshape or re-layout to stop "
            "paying HBM for padding",
            line_no=i, snippet=line.strip(),
        ))
    return out


def verify_memory_text(
    txt: str, ctx: Optional[MemoryRuleContext] = None
) -> Tuple[List[Finding], MemoryAnalysis]:
    """Every Engine-E rule over one HLO module text → (findings, analysis)."""
    ctx = ctx or MemoryRuleContext()
    ana = analyze_memory_text(txt, ctx)
    findings: List[Finding] = []
    findings.extend(rule_hbm_over_budget(ana, ctx))
    findings.extend(rule_donation_missed(ana, ctx))
    findings.extend(rule_oversized_collective_scratch(ana, ctx))
    findings.extend(rule_padding_waste(txt, ctx))
    return findings, ana


def verify_memory_compiled(
    compiled, ctx: Optional[MemoryRuleContext] = None
) -> Tuple[List[Finding], MemoryAnalysis]:
    txt = compiled.as_text() if hasattr(compiled, "as_text") else str(compiled)
    return verify_memory_text(txt, ctx)


# ---------------------------------------------------------------------------
# the XLA cross-check + the committed budget ledger
# ---------------------------------------------------------------------------

def xla_peak_bytes(compiled) -> Optional[int]:
    """XLA's own accounting of the same peak: arguments + outputs − aliased
    + temp heap, from ``compiled.memory_analysis()``. None when the backend
    doesn't expose it. Engine E's estimate is pinned within 10% of this on
    the real train/serving programs (acceptance test).

    An executable deserialized from the persistent compilation cache
    reports ``alias_size_in_bytes=0`` even though its module header still
    carries the ``input_output_alias`` table — recompute the aliased bytes
    from the text in that case, or a cached bench run would inflate the
    reference by the whole donated state."""
    try:
        ma = compiled.memory_analysis()
        if isinstance(ma, (list, tuple)):
            ma = ma[0]
        alias = int(ma.alias_size_in_bytes)
        if alias == 0 and hasattr(compiled, "as_text"):
            txt = compiled.as_text()
            nums = _aliased_param_numbers(txt)
            if nums:
                entry = entry_computation(txt)
                lines = split_computations(txt).get(entry, []) if entry else []
                for line in lines:
                    m = _PARAM_DECL.search(line)
                    if m and int(m.group("num")) in nums and \
                            m.group("dtype") in DTYPE_BYTES:
                        alias += shape_bytes(m.group("dtype"),
                                             m.group("dims"))
        return int(
            ma.argument_size_in_bytes
            + ma.output_size_in_bytes
            - alias
            + ma.temp_size_in_bytes
        )
    except Exception:
        return None


def load_budgets(path: str) -> Dict[str, int]:
    """The committed per-program budget ledger: ``{program: budget_bytes}``.
    Raises ValueError on a corrupt file (a broken ledger must not pass the
    gate vacuously)."""
    try:
        with open(path, encoding="utf-8") as fh:
            doc = json.load(fh)
    except OSError:
        return {}
    except json.JSONDecodeError as e:
        raise ValueError(f"corrupt dsmem budget file {path!r}: {e}") from e
    if not isinstance(doc, dict):
        raise ValueError(f"dsmem budget file {path!r} is not an object")
    out = {}
    for k, v in doc.items():
        if k.startswith("_"):
            continue  # comment / metadata keys
        out[str(k)] = int(v)
    return out


def find_budget_file(start: Optional[str] = None) -> Optional[str]:
    """Nearest committed budget ledger, walking upward from ``start`` (same
    walk as the dslint baseline). Without ``start`` the walk is anchored at
    the CWD; with it, the anchor wins — a dump in another checkout must
    resolve against THAT repo's ledger, not the invoking repo's."""
    if start is None and os.path.exists(DEFAULT_BUDGET_NAME):
        return DEFAULT_BUDGET_NAME
    probe = os.path.abspath(start or os.getcwd())
    if os.path.isfile(probe):
        probe = os.path.dirname(probe)
    for _ in range(6):
        cand = os.path.join(probe, DEFAULT_BUDGET_NAME)
        if os.path.exists(cand):
            return cand
        parent = os.path.dirname(probe)
        if parent == probe:
            break
        probe = parent
    return None


def resolve_budget(mcfg, program: str,
                   search_from: Optional[str] = None) -> int:
    """Budget for ``program``: the explicit ``analysis.memory.budgets``
    entry wins, then the committed ledger file, then
    ``default_budget_bytes`` (0 = no gate)."""
    budgets = dict(getattr(mcfg, "budgets", {}) or {})
    if program in budgets:
        return int(budgets[program])
    explicit = getattr(mcfg, "budget_file", "")
    if explicit and os.path.exists(explicit):
        path = explicit
    elif search_from is not None:
        # anchored lookup (CLI *.hlo dumps): the ledger nearest the dump
        # wins over the invoking repo's
        path = find_budget_file(search_from) or ""
    else:
        path = explicit or DEFAULT_BUDGET_NAME
        if not os.path.exists(path):
            path = find_budget_file() or path
    if path and os.path.exists(path):
        ledger = load_budgets(path)
        if program in ledger:
            return int(ledger[program])
    return int(getattr(mcfg, "default_budget_bytes", 0) or 0)


def headroom_pct(budget_bytes: int, peak_bytes: int) -> Optional[float]:
    """Budget headroom as a percentage (positive = under budget), None when
    no positive budget is set — the ONE definition every report shares
    (engine/serving ``memory_report()``, bench, env_report)."""
    if not budget_bytes or budget_bytes <= 0:
        return None
    return round(100.0 * (budget_bytes - peak_bytes) / budget_bytes, 2)


def context_from_config(mcfg, program: str, **overrides) -> MemoryRuleContext:
    """Build a :class:`MemoryRuleContext` from an ``analysis.memory`` config
    section (thresholds + the resolved per-program budget)."""
    kw = dict(
        program=program,
        budget_bytes=resolve_budget(mcfg, program),
        check_donation=bool(getattr(mcfg, "check_donation", True)),
        donation_min_bytes=int(getattr(mcfg, "donation_min_bytes", 1 << 16)),
        scratch_max_fraction=float(
            getattr(mcfg, "scratch_max_fraction", 0.25)
        ),
        scratch_min_bytes=int(getattr(mcfg, "scratch_min_bytes", 1 << 20)),
        padding_waste_min_ratio=float(
            getattr(mcfg, "padding_waste_min_ratio", 1.5)
        ),
        padding_waste_min_bytes=int(
            getattr(mcfg, "padding_waste_min_bytes", 1 << 16)
        ),
    )
    kw.update(overrides)
    return MemoryRuleContext(**kw)
