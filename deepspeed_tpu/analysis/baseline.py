"""Committed findings baseline: existing debt doesn't block, new debt does.

The CI gate semantics (ISSUE 6): ``dslint`` compared against a committed
``.dslint-baseline.json`` exits 0 when every finding is already known and 1
the moment a NEW finding appears. ``--update-baseline`` re-records the
current findings — entries whose finding no longer exists EXPIRE (they are
dropped, so the debt ledger only shrinks by fixing, never silently grows).

Fingerprints (``findings.Finding.fingerprint``) key on rule + file + symbol
+ a hash of the offending line, not on line numbers, so edits elsewhere in
a file do not churn the baseline.
"""

from __future__ import annotations

import json
import os
from dataclasses import dataclass, field
from typing import Dict, Iterable, List, Tuple

from .findings import Finding

BASELINE_VERSION = 1
DEFAULT_BASELINE_NAME = ".dslint-baseline.json"


@dataclass
class Baseline:
    path: str = ""
    entries: Dict[str, Dict] = field(default_factory=dict)  # fingerprint → meta

    @classmethod
    def load(cls, path: str) -> "Baseline":
        """Missing file → empty baseline (first run bootstraps); a corrupt
        file raises ValueError with the path (the CLI maps it to exit 2)."""
        if not path or not os.path.exists(path):
            return cls(path=path)
        try:
            with open(path, encoding="utf-8") as fh:
                doc = json.load(fh)
            entries = {
                e["fingerprint"]: e for e in doc.get("findings", [])
                if isinstance(e, dict) and "fingerprint" in e
            }
        except (json.JSONDecodeError, KeyError, TypeError, AttributeError) as e:
            raise ValueError(f"corrupt dslint baseline {path!r}: {e}") from e
        return cls(path=path, entries=entries)

    def split(
        self, findings: Iterable[Finding]
    ) -> Tuple[List[Finding], List[Finding], List[str]]:
        """→ (new, known, stale_fingerprints)."""
        new, known, seen = [], [], set()
        for f in findings:
            fp = f.fingerprint()
            seen.add(fp)
            (known if fp in self.entries else new).append(f)
        stale = [fp for fp in self.entries if fp not in seen]
        return new, known, stale

    def update(
        self, findings: Iterable[Finding], scanned_paths=None
    ) -> None:
        """Re-record the ledger from the current findings (add + expire).

        ``scanned_paths`` scopes the expiry: entries for files NOT scanned
        this run are kept verbatim, so ``--changed --update-baseline`` on a
        subset cannot silently wipe the rest of the ledger. None = full
        replace."""
        if scanned_paths is None:
            self.entries = {}
        else:
            self.entries = {
                fp: e for fp, e in self.entries.items()
                if e.get("path") not in scanned_paths
            }
        for f in findings:
            fp = f.fingerprint()
            self.entries[fp] = {
                "fingerprint": fp,
                "rule": f.rule,
                "path": f.path,
                "symbol": f.symbol,
                "message": f.message,
            }

    def save(self) -> None:
        doc = {
            "version": BASELINE_VERSION,
            "tool": "dslint",
            "findings": sorted(
                self.entries.values(),
                key=lambda e: (e["path"], e["rule"], e["fingerprint"]),
            ),
        }
        with open(self.path, "w", encoding="utf-8") as fh:
            json.dump(doc, fh, indent=1)
            fh.write("\n")

    def __len__(self) -> int:
        return len(self.entries)
