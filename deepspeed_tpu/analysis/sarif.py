"""SARIF 2.1.0 export for dslint findings (ISSUE 15 satellite).

One ``run`` per engine letter so CI viewers group annotations by plane
(A:HLO, B:AST, C:concurrency, D:collective, E:memory, F:sharding,
G:protocol).  Fingerprints ride along in ``partialFingerprints`` under the
``dslintFingerprint`` key, and findings already accepted by the committed
baseline are marked ``baselineState: "unchanged"`` (new ones ``"new"``) so
an annotating CI can highlight only the regressions.
"""

from __future__ import annotations

from typing import Dict, Iterable, List, Optional

SARIF_VERSION = "2.1.0"
SARIF_SCHEMA = (
    "https://raw.githubusercontent.com/oasis-tcs/sarif-spec/master/"
    "Schemata/sarif-schema-2.1.0.json"
)

# Finding.engine tag → CLI engine letter (dsan reports through Engine C's
# run: same catalog, dynamic half)
ENGINE_LETTERS: Dict[str, str] = {
    "hlo": "a",
    "ast": "b",
    "concurrency": "c",
    "dsan": "c",
    "collective": "d",
    "mem": "e",
    "spec": "f",
    "protocol": "g",
}

_ENGINE_TITLES: Dict[str, str] = {
    "a": "HLO program verifier",
    "b": "AST JAX-footgun lint",
    "c": "concurrency sanitizer",
    "d": "collective-consistency verifier",
    "e": "static HBM liveness",
    "f": "sharding-spec verifier",
    "g": "serving-protocol checker",
}


def _level(severity: str) -> str:
    return "error" if severity == "error" else "warning"


def _uri(path: str) -> str:
    # hlo://<program> and model://<scope> pseudo-paths are already URIs;
    # real paths become relative file URIs
    if "://" in path:
        return path
    return path.replace("\\", "/")


def _result(finding, known: bool) -> dict:
    res = {
        "ruleId": finding.rule,
        "level": _level(finding.severity),
        "message": {"text": finding.message},
        "locations": [
            {
                "physicalLocation": {
                    "artifactLocation": {"uri": _uri(finding.path)},
                    "region": {"startLine": max(1, int(finding.line or 1))},
                }
            }
        ],
        "partialFingerprints": {"dslintFingerprint": finding.fingerprint()},
        "baselineState": "unchanged" if known else "new",
    }
    if finding.snippet:
        res["locations"][0]["physicalLocation"]["region"]["snippet"] = {
            "text": finding.snippet
        }
    return res


def sarif_report(
    findings: Iterable,
    known_fingerprints: Iterable[str] = (),
    engines: Optional[Iterable[str]] = None,
) -> dict:
    """Build a SARIF 2.1.0 document — one run per engine letter.

    ``engines`` forces a run object for every selected letter even when it
    produced no findings, so a CI consumer can distinguish "engine ran
    clean" from "engine not selected".
    """
    from . import ENGINE_RULES

    known = set(known_fingerprints)
    by_letter: Dict[str, List] = {
        letter: [] for letter in sorted(engines or ())
    }
    for f in findings:
        letter = ENGINE_LETTERS.get(f.engine)
        if letter is None:  # unknown plane: keep it visible under its tag
            letter = f.engine
        by_letter.setdefault(letter, []).append(f)

    runs = []
    for letter in sorted(by_letter):
        catalog = ENGINE_RULES.get(letter, {})
        runs.append(
            {
                "tool": {
                    "driver": {
                        "name": f"dslint-{letter}",
                        "informationUri": "https://example.invalid/dslint",
                        "semanticVersion": "1.0.0",
                        "shortDescription": {
                            "text": _ENGINE_TITLES.get(
                                letter, f"engine {letter}"
                            )
                        },
                        "rules": [
                            {
                                "id": rule,
                                "shortDescription": {"text": desc},
                            }
                            for rule, desc in sorted(catalog.items())
                        ],
                    }
                },
                "columnKind": "utf16CodeUnits",
                "results": [
                    _result(f, f.fingerprint() in known)
                    for f in sorted(
                        by_letter[letter],
                        key=lambda f: (f.path, f.line, f.rule),
                    )
                ],
            }
        )
    return {
        "$schema": SARIF_SCHEMA,
        "version": SARIF_VERSION,
        "runs": runs,
    }
