"""Shared findings / severity / suppression model for dslint (ISSUE 6).

Both analysis engines — the AST linter (``ast_rules``) and the HLO program
verifier (``hlo_rules``) — report through one :class:`Finding` shape so the
CLI, the baseline file, the pytest gate, and bench.py all consume a single
stream. A finding is identified across runs by its :meth:`Finding.fingerprint`
— rule + file (or pseudo-path ``hlo://<program>``) + enclosing symbol + a
hash of the offending line text — deliberately NOT the line number, so a
baseline survives unrelated edits above the finding.

Suppression: a ``# dslint: disable=<rule>[,<rule>...]`` comment on the
flagged line or the line directly above it silences that rule there (bare
``# dslint: disable`` silences every rule). Suppressions are counted, not
hidden: the analyzer reports how many findings were waived so a PR review
can see the justifications grow.
"""

from __future__ import annotations

import hashlib
import re
from dataclasses import dataclass, field
from typing import Dict, Iterable, List, Optional, Tuple

SEVERITY_ERROR = "error"
SEVERITY_WARNING = "warning"

_DISABLE = re.compile(r"#\s*dslint:\s*disable(?:=(?P<rules>[\w\-, ]+))?")


@dataclass
class Finding:
    """One rule violation, from either engine."""

    rule: str
    severity: str
    message: str
    path: str = ""        # source file, or "hlo://<program>" for Engine A
    line: int = 0         # 1-based line in the source / HLO text
    symbol: str = ""      # enclosing function qualname or HLO computation
    snippet: str = ""     # the offending line, stripped
    engine: str = "ast"   # "ast" | "hlo"

    def fingerprint(self) -> str:
        digest = hashlib.sha1(self.snippet.strip().encode()).hexdigest()[:12]
        return f"{self.rule}|{self.path}|{self.symbol}|{digest}"

    def to_dict(self) -> Dict:
        return {
            "rule": self.rule,
            "severity": self.severity,
            "message": self.message,
            "path": self.path,
            "line": self.line,
            "symbol": self.symbol,
            "snippet": self.snippet,
            "engine": self.engine,
            "fingerprint": self.fingerprint(),
        }

    def render(self) -> str:
        loc = f"{self.path}:{self.line}" if self.line else self.path
        sym = f" [{self.symbol}]" if self.symbol else ""
        return f"{loc}: {self.severity}: {self.rule}: {self.message}{sym}"


def _disabled_rules(line: str) -> Optional[set]:
    """Rules disabled by a ``# dslint: disable`` comment on ``line``;
    ``set()`` means "all rules", None means no suppression comment."""
    m = _DISABLE.search(line)
    if not m:
        return None
    rules = m.group("rules")
    if rules is None:
        return set()
    return {r.strip() for r in rules.split(",") if r.strip()}


@dataclass
class SuppressionIndex:
    """Per-file map of line → suppressed rules, built once from source.

    An inline comment suppresses its own line. A comment-only line
    suppresses the next code line, scanning past further comment lines —
    so a multi-line justification block above the statement works."""

    # line → set of rule names, or None meaning "all rules"
    by_line: Dict[int, Optional[set]] = field(default_factory=dict)

    def _register(self, line: int, rules: set) -> None:
        if not rules:  # bare "# dslint: disable" = every rule
            self.by_line[line] = None
        elif self.by_line.get(line, set()) is not None:
            self.by_line.setdefault(line, set()).update(rules)

    @classmethod
    def from_source(cls, text: str) -> "SuppressionIndex":
        idx = cls()
        lines = text.splitlines()
        for i, line in enumerate(lines, start=1):
            rules = _disabled_rules(line)
            if rules is None:
                continue
            idx._register(i, rules)
            if line.lstrip().startswith("#"):
                # standalone comment: also covers the next code line (a
                # justification block may continue over more comment lines)
                for j in range(i + 1, len(lines) + 1):
                    stripped = lines[j - 1].strip()
                    if stripped and not stripped.startswith("#"):
                        idx._register(j, rules)
                        break
        return idx

    def suppresses(self, rule: str, line: int) -> bool:
        for ln in (line, line - 1):
            if ln in self.by_line:
                rules = self.by_line[ln]
                if rules is None or rule in rules:
                    return True
        return False


def apply_suppressions(
    findings: Iterable[Finding], index: SuppressionIndex
) -> Tuple[List[Finding], int]:
    """→ (kept findings, suppressed count)."""
    kept, waived = [], 0
    for f in findings:
        if index.suppresses(f.rule, f.line):
            waived += 1
        else:
            kept.append(f)
    return kept, waived
