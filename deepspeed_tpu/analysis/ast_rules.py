"""Engine B: AST rules — JAX footguns visible in the Python source.

On TPU the per-step host code is as latency-critical as the compiled
program: one stray ``.item()`` in the decode loop serializes the host with
the device every step, one Python branch on a tracer turns a static program
into a recompilation storm. These are all visible in the AST, before
anything runs:

- ``host-sync-in-step``: device→host syncs (``.item()``, ``jax.device_get``,
  ``block_until_ready``, ``np.asarray(<jax expr>)``) inside *hot* functions
  (the scheduler slot loop, ``train_batch``, telemetry sampling —
  ``analysis.hot_function_patterns``).
- ``host-sync-in-traced``: the same calls inside *traced* code (jit-decorated
  or passed to ``jax.jit``/``lax.scan``/…) — there they either fail or
  silently fall out of the program.
- ``tracer-branch``: Python ``if``/``while`` on a traced value (a
  ``jnp``/``jax`` call or an ``.any()/.all()/.sum()``-style reduction in the
  test) inside traced code — retrace-per-value or ConcretizationTypeError.
- ``jnp-in-hot-loop``: ``jnp.*``/``jax.*`` device-op dispatch inside hot
  host functions — the scheduler's per-request/per-step path should hand the
  compiled executable plain numpy and let XLA do the rest.
- ``missing-donate-argnums``: ``jax.jit(<step/prefill/decode/train fn>)``
  without ``donate_argnums`` — a large-pytree program that copies instead of
  aliasing doubles its HBM footprint.
- ``unstable-cache-key``: compile-cache keys built from ``id(...)`` (unstable
  across runs and objects — cache never hits, executables pile up) or from
  unhashable literals.

Each rule can be silenced with ``# dslint: disable=<rule>`` on the flagged
line or the line above — the suppression carries the justification.
"""

from __future__ import annotations

import ast
import fnmatch
from typing import List, Optional, Sequence

from .findings import (
    SEVERITY_ERROR,
    SEVERITY_WARNING,
    Finding,
    SuppressionIndex,
    apply_suppressions,
)

RULES = {
    "host-sync-in-step":
        "device→host sync in a hot (per-step / per-request) host function",
    "host-sync-in-traced":
        "device→host sync inside traced (jit/scan) code",
    "tracer-branch":
        "Python branch on a traced value inside traced code",
    "jnp-in-hot-loop":
        "jnp/jax device-op dispatch in a hot host function",
    "missing-donate-argnums":
        "jax.jit of a step-like function without donate_argnums",
    "unstable-cache-key":
        "compile-cache keyed on id()/unhashable values",
}

DEFAULT_HOT_PATTERNS = [
    "ServingEngine.step", "ServingEngine.run", "ServingEngine._admit",
    "ServingEngine._finish_slot", "ServingEngine.submit",
    # ISSUE 10: chunked prefill runs once per scheduler step while a slot
    # prefills, and _start_decoding is the per-admission transition _admit
    # used to carry — both stay under the hot-path lint
    "ServingEngine._advance_chunk", "ServingEngine._start_decoding",
    "ServingEngine._draft", "ServingEngine._accept_tokens",
    "*.train_batch", "*.eval_batch",
    "*._telemetry_step", "*._watchdog_step",
    "InferenceEngine.generate",
]

DEFAULT_DONATE_PATTERNS = ["*step*", "*prefill*", "*decode*", "*train*"]

# entry points whose function-valued arguments become traced code
# (pallas_call included: an ops/pallas kernel body is traced code too — a
# host sync or value-branch inside one is exactly as fatal as under jit)
_TRACE_ENTRY = (
    "jax.jit", "jit", "pjit", "jax.pjit",
    "lax.scan", "jax.lax.scan", "lax.while_loop", "jax.lax.while_loop",
    "lax.cond", "jax.lax.cond", "lax.fori_loop", "jax.lax.fori_loop",
    "shard_map", "jax.checkpoint", "jax.remat", "checkpoint", "remat",
    "jax.vmap", "vmap", "jax.grad", "jax.value_and_grad",
    "pallas_call", "pl.pallas_call",
)

# jax.* call chains that are host-side bookkeeping, not device-op dispatch
_HOST_SIDE_JAX = (
    "jax.tree", "jax.tree_util", "jax.ShapeDtypeStruct", "jax.device_get",
    "jax.block_until_ready", "jax.profiler", "jax.monitoring", "jax.config",
    "jax.devices", "jax.local_devices", "jax.device_count",
    "jax.local_device_count", "jax.process_count", "jax.process_index",
    "jax.named_scope", "jax.debug", "jax.eval_shape", "jax.clear_caches",
    "jax.live_arrays", "jax.typeof",
)

_REDUCTION_ATTRS = ("any", "all", "sum", "max", "min", "mean", "item")


def _chain(node: ast.AST) -> str:
    """Dotted name of a Name/Attribute chain ('' when not a plain chain)."""
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return ""


def _is_device_chain(chain: str) -> bool:
    if not chain:
        return False
    root = chain.split(".", 1)[0]
    if root not in ("jax", "jnp"):
        return False
    return not any(
        chain == h or chain.startswith(h + ".") for h in _HOST_SIDE_JAX
    )


def _contains_device_call(node: ast.AST) -> bool:
    for sub in ast.walk(node):
        if isinstance(sub, ast.Call) and _is_device_chain(_chain(sub.func)):
            return True
    return False


def _host_sync_kind(call: ast.Call) -> Optional[str]:
    """Classify a Call as a device→host sync, or None."""
    chain = _chain(call.func)
    if chain.endswith(".item") and not call.args and not call.keywords:
        return ".item()"
    if chain.endswith("block_until_ready"):
        return "block_until_ready"
    if chain == "jax.device_get" or chain.endswith(".device_get"):
        return "jax.device_get"
    if chain in ("np.asarray", "numpy.asarray", "np.array", "numpy.array"):
        if any(_contains_device_call(a) for a in call.args):
            return f"{chain}(<jax expr>)"
    return None


class _FuncInfo:
    def __init__(self, node, qualname, traced, hot):
        self.node = node
        self.qualname = qualname
        self.traced = traced
        self.hot = hot


class _Linter:
    def __init__(self, path: str, tree: ast.Module, source: str,
                 hot_patterns: Sequence[str],
                 donate_patterns: Sequence[str]):
        self.path = path
        self.tree = tree
        self.lines = source.splitlines()
        self.hot_patterns = list(hot_patterns)
        self.donate_patterns = list(donate_patterns)
        self.findings: List[Finding] = []
        self.traced_names = self._collect_traced_names()

    # -- traced / hot classification ----------------------------------
    def _collect_traced_names(self) -> set:
        """Function names passed by name to a trace entry point anywhere in
        the module (``jax.jit(step_fn)``, ``lax.scan(body, ...)``)."""
        names = set()
        for node in ast.walk(self.tree):
            if not isinstance(node, ast.Call):
                continue
            if _chain(node.func) in _TRACE_ENTRY:
                for arg in list(node.args) + [kw.value for kw in node.keywords]:
                    if isinstance(arg, ast.Name):
                        names.add(arg.id)
        return names

    def _is_traced_def(self, node) -> bool:
        for dec in node.decorator_list:
            for sub in ast.walk(dec):
                chain = _chain(sub) if isinstance(sub, (ast.Name, ast.Attribute)) else ""
                if chain in _TRACE_ENTRY:
                    return True
        return node.name in self.traced_names

    def _is_hot(self, qualname: str, name: str) -> bool:
        return any(
            fnmatch.fnmatch(qualname, p) or fnmatch.fnmatch(name, p)
            for p in self.hot_patterns
        )

    # -- driving -------------------------------------------------------
    def run(self) -> List[Finding]:
        self._scan_block(self.tree.body, prefix="", symbol="<module>")
        return self.findings

    def _scan_block(self, stmts, prefix, symbol):
        """Module/class level: route function defs to the per-function
        checks, everything else to the everywhere-rules."""
        for stmt in stmts:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self._handle_function(
                    stmt, f"{prefix}{stmt.name}",
                    traced=self._is_traced_def(stmt),
                )
            elif isinstance(stmt, ast.ClassDef):
                self._scan_block(stmt.body, f"{stmt.name}.", stmt.name)
            else:
                for sub in ast.walk(stmt):
                    self._check_common_node(sub, symbol)

    def _handle_function(self, fn, qualname, traced):
        # a nested def inside a hot function is a traced closure being
        # built, not itself hot host code — hot never propagates down
        hot = (not traced) and self._is_hot(qualname, fn.name)
        self._check_function(fn, qualname, traced, hot)
        for sub in self._nested_defs(fn):
            self._handle_function(
                sub, f"{qualname}.{sub.name}",
                traced=traced or self._is_traced_def(sub),
            )

    def _nested_defs(self, fn):
        """Function defs directly nested in ``fn`` (not transitively)."""
        out, stack = [], list(fn.body)
        while stack:
            node = stack.pop()
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                out.append(node)
                continue
            if isinstance(node, ast.ClassDef):
                stack.extend(node.body)
                continue
            stack.extend(ast.iter_child_nodes(node))
        return out

    # -- per-function checks ------------------------------------------
    def _check_function(self, fn, qualname, traced, hot):
        for node in self._function_nodes(fn):
            self._check_common_node(node, qualname)
            if isinstance(node, ast.Call):
                sync = _host_sync_kind(node)
                if sync and traced:
                    self._emit(
                        "host-sync-in-traced", SEVERITY_ERROR, node, qualname,
                        f"{sync} inside traced code — the sync either fails "
                        "under jit or silently leaves the program",
                    )
                elif sync and hot:
                    self._emit(
                        "host-sync-in-step", SEVERITY_ERROR, node, qualname,
                        f"{sync} in a hot per-step path serializes the host "
                        "with the device every iteration",
                    )
                elif hot and not traced:
                    chain = _chain(node.func)
                    if _is_device_chain(chain):
                        self._emit(
                            "jnp-in-hot-loop", SEVERITY_WARNING, node,
                            qualname,
                            f"{chain}() dispatches a device op from the hot "
                            "host loop — precompute, or pass numpy straight "
                            "to the compiled executable",
                        )
            if traced and isinstance(node, (ast.If, ast.While)):
                test = node.test
                if self._is_traced_value(test):
                    self._emit(
                        "tracer-branch", SEVERITY_ERROR, node, qualname,
                        "Python branch on a traced value — use lax.cond / "
                        "jnp.where (this retraces per value or raises "
                        "ConcretizationTypeError)",
                    )

    def _function_nodes(self, fn):
        """Walk a function body, NOT descending into nested defs (they are
        classified and checked separately)."""
        stack = list(fn.body)
        while stack:
            node = stack.pop()
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.ClassDef, ast.Lambda)):
                continue
            yield node
            for child in ast.iter_child_nodes(node):
                stack.append(child)

    def _is_traced_value(self, test: ast.AST) -> bool:
        for sub in ast.walk(test):
            if isinstance(sub, ast.Call):
                chain = _chain(sub.func)
                if _is_device_chain(chain):
                    return True
                if chain.split(".")[-1] in _REDUCTION_ATTRS and \
                        isinstance(sub.func, ast.Attribute):
                    return True
        return False

    # -- everywhere checks --------------------------------------------
    def _check_common_node(self, node, symbol):
        if isinstance(node, ast.Call):
            self._check_missing_donate(node, symbol)
            self._check_cache_key_call(node, symbol)
        elif isinstance(node, ast.Subscript):
            self._check_cache_key_subscript(node, symbol)

    def _check_missing_donate(self, call: ast.Call, symbol):
        if _chain(call.func) not in ("jax.jit", "jit", "pjit", "jax.pjit"):
            return
        if not call.args or not isinstance(call.args[0], ast.Name):
            return
        name = call.args[0].id
        if not any(fnmatch.fnmatch(name.lower(), p)
                   for p in self.donate_patterns):
            return
        if any(kw.arg in ("donate_argnums", "donate_argnames")
               for kw in call.keywords):
            return
        self._emit(
            "missing-donate-argnums", SEVERITY_WARNING, call, symbol,
            f"jax.jit({name}) without donate_argnums — a step-like program "
            "that copies its state instead of aliasing doubles its HBM "
            "footprint",
        )

    def _cacheish(self, node) -> bool:
        chain = _chain(node)
        return "cache" in chain.split(".")[-1].lower() if chain else False

    def _check_cache_key_subscript(self, node: ast.Subscript, symbol):
        if not self._cacheish(node.value):
            return
        key = node.slice
        if any(isinstance(s, ast.Call) and _chain(s.func) == "id"
               for s in ast.walk(key)):
            self._emit(
                "unstable-cache-key", SEVERITY_WARNING, node, symbol,
                "cache keyed on id(...) — unstable across objects/runs, the "
                "cache never hits and executables pile up",
            )
        elif isinstance(key, (ast.List, ast.Dict, ast.Set)):
            self._emit(
                "unstable-cache-key", SEVERITY_WARNING, node, symbol,
                "unhashable literal used as a cache key",
            )

    def _check_cache_key_call(self, call: ast.Call, symbol):
        if not isinstance(call.func, ast.Attribute):
            return
        if call.func.attr not in ("get", "setdefault", "pop"):
            return
        if not self._cacheish(call.func.value) or not call.args:
            return
        if any(isinstance(s, ast.Call) and _chain(s.func) == "id"
               for s in ast.walk(call.args[0])):
            self._emit(
                "unstable-cache-key", SEVERITY_WARNING, call, symbol,
                "cache keyed on id(...) — unstable across objects/runs, the "
                "cache never hits and executables pile up",
            )

    def _emit(self, rule, severity, node, symbol, message):
        line = getattr(node, "lineno", 0)
        snippet = self.lines[line - 1].strip() if 0 < line <= len(self.lines) else ""
        self.findings.append(Finding(
            rule=rule, severity=severity, message=message, path=self.path,
            line=line, symbol=symbol, snippet=snippet, engine="ast",
        ))


def lint_source(
    source: str,
    path: str = "<string>",
    hot_patterns: Optional[Sequence[str]] = None,
    donate_patterns: Optional[Sequence[str]] = None,
):
    """Lint one Python source string → (findings, suppressed_count).

    Raises SyntaxError upward — an unparseable file is the caller's problem
    to report (the CLI turns it into a usage-class error)."""
    tree = ast.parse(source, filename=path)
    linter = _Linter(
        path, tree, source,
        hot_patterns if hot_patterns is not None else DEFAULT_HOT_PATTERNS,
        donate_patterns if donate_patterns is not None else DEFAULT_DONATE_PATTERNS,
    )
    findings = linter.run()
    findings.sort(key=lambda f: (f.path, f.line, f.rule))
    # two calls on one line produce identical fingerprints — report once
    seen, unique = set(), []
    for f in findings:
        key = (f.rule, f.path, f.line)
        if key not in seen:
            seen.add(key)
            unique.append(f)
    return apply_suppressions(unique, SuppressionIndex.from_source(source))


def lint_file(path: str, hot_patterns=None, donate_patterns=None):
    with open(path, encoding="utf-8") as fh:
        return lint_source(
            fh.read(), path=path,
            hot_patterns=hot_patterns, donate_patterns=donate_patterns,
        )
