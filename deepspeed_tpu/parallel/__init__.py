from .topology import (
    MeshSpec,
    PipeDataParallelTopology,
    PipeModelDataParallelTopology,
    ProcessTopology,
    single_device_mesh,
)
