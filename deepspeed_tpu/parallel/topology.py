"""Named-axis device topology → ``jax.sharding.Mesh``.

TPU-native analog of the reference's ``deepspeed/runtime/pipe/topology.py``
(``ProcessTopology`` at topology.py:9, ``PipeModelDataParallelTopology:243``,
``PipelineParallelGrid:249``) and ``deepspeed/utils/groups.py``. The reference
builds an N-D cartesian rank grid and carves NCCL process groups out of it; on
TPU the same object IS a ``jax.sharding.Mesh`` with named axes — XLA derives
every "process group" (collective subset) from the mesh axis names used by a
collective, so no explicit group objects are needed.

Canonical axis names (any subset may be present, sizes default to 1):

- ``pp``   pipeline-parallel stages
- ``dp``   data parallel (ZeRO shards over this axis)
- ``tp``   tensor/model parallel
- ``ep``   expert parallel (MoE); nested inside dp like groups.py:109
- ``sp``   sequence/context parallel (ring attention / Ulysses)
"""

from __future__ import annotations

import collections
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

import jax
from jax.sharding import Mesh

# Axis order matters for ICI locality: innermost (fastest-varying) axes get
# neighboring devices. tp wants the tightest coupling (per-layer collectives),
# then ep/sp, then dp, then pp (cheapest: one p2p per microbatch boundary).
CANONICAL_AXIS_ORDER = ("pp", "dp", "ep", "sp", "tp")

ProcessCoord = collections.namedtuple  # built per-topology below


class ProcessTopology:
    """Cartesian mapping of named parallelism axes onto a flat device list.

    API mirrors the reference ``ProcessTopology`` (rank↔coord queries, axis
    comms) but ``get_mesh()`` returns the ``jax.sharding.Mesh`` that the rest
    of the framework consumes.
    """

    def __init__(self, axes: Sequence[str], dims: Sequence[int], devices: Optional[Sequence] = None):
        assert len(axes) == len(dims), "axes and dims must align"
        self.axes = list(axes)
        self.dims = list(dims)
        self.ProcessCoord = collections.namedtuple("ProcessCoord", axes)
        self.mapping: Dict[Tuple[int, ...], int] = {}
        ranges = [range(d) for d in dims]
        import itertools

        for global_rank, coord in enumerate(itertools.product(*ranges)):
            self.mapping[coord] = global_rank
        self._devices = devices

    def world_size(self) -> int:
        return int(np.prod(self.dims)) if self.dims else 1

    def get_dim(self, axis: str) -> int:
        return self.dims[self.axes.index(axis)] if axis in self.axes else 1

    def get_rank(self, **coord_kwargs) -> int:
        key = tuple(coord_kwargs[a] for a in self.axes)
        assert key in self.mapping, f"invalid coord {coord_kwargs}"
        return self.mapping[key]

    def get_coord(self, rank: int):
        for coord, r in self.mapping.items():
            if r == rank:
                return self.ProcessCoord(*coord)
        raise ValueError(f"rank {rank} not in topology")

    def get_rank_repr(self, rank: int, omit_axes=("dp", "pp"), inner_sep="_", outer_sep="-") -> str:
        omit_axes = list(omit_axes)
        axes = [a for a in self.axes if a not in omit_axes]
        names = []
        coord = self.get_coord(rank)
        for ax in axes:
            names.append(f"{ax}{inner_sep}{getattr(coord, ax):02d}")
        return outer_sep.join(names)

    def get_axis_list(self, axis: str, idx: int) -> List[int]:
        """All ranks whose ``axis`` coordinate equals ``idx``."""
        pos = self.axes.index(axis)
        return sorted(r for coord, r in self.mapping.items() if coord[pos] == idx)

    def get_axis_comm_lists(self, axis: str) -> List[List[int]]:
        """Rank lists that would form communicators along ``axis``.

        Retained for parity with reference topology.py:155 — on TPU these are
        informational (XLA derives collective groups from mesh axis names).
        """
        if axis not in self.axes:
            return []
        pos = self.axes.index(axis)
        import itertools

        other_ranges = [range(d) for i, d in enumerate(self.dims) if i != pos]
        lists = []
        for other in itertools.product(*other_ranges):
            ranks = []
            for v in range(self.dims[pos]):
                coord = list(other)
                coord.insert(pos, v)
                ranks.append(self.mapping[tuple(coord)])
            lists.append(sorted(ranks))
        return lists

    def filter_match(self, **filter_kwargs) -> List[int]:
        def match(coord):
            return all(getattr(coord, k) == v for k, v in filter_kwargs.items())

        return sorted(r for coord, r in self.mapping.items() if match(self.ProcessCoord(*coord)))

    def get_mesh(self, devices: Optional[Sequence] = None) -> Mesh:
        """Materialize as a ``jax.sharding.Mesh`` over real (or given) devices."""
        devices = list(devices if devices is not None else (self._devices or jax.devices()))
        n = self.world_size()
        assert len(devices) >= n, f"need {n} devices, have {len(devices)}"
        dev_array = np.array(devices[:n], dtype=object).reshape(self.dims)
        return Mesh(dev_array, axis_names=tuple(self.axes))

    def __str__(self):
        return f"ProcessTopology(axes={self.axes}, dims={self.dims})"


class PipeModelDataParallelTopology(ProcessTopology):
    """3D pp×dp×tp topology; analog of reference topology.py:243."""

    def __init__(self, num_pp: int, num_mp: int, num_dp: int, devices=None):
        super().__init__(axes=["pp", "dp", "tp"], dims=[num_pp, num_dp, num_mp], devices=devices)


class PipeDataParallelTopology(ProcessTopology):
    def __init__(self, num_pp: int, num_dp: int, devices=None):
        super().__init__(axes=["pp", "dp"], dims=[num_pp, num_dp], devices=devices)


@dataclass
class MeshSpec:
    """Declarative mesh request: axis name → size. -1 means "fill remaining".

    ``deepspeed_tpu``'s analog of ``groups.initialize(ep_size, mpu)``: instead
    of mutating global process groups, callers build a MeshSpec and pass the
    resulting mesh into the engine.
    """

    dp: int = -1
    tp: int = 1
    pp: int = 1
    ep: int = 1
    sp: int = 1
    axis_order: Tuple[str, ...] = CANONICAL_AXIS_ORDER
    devices: Optional[Sequence] = None

    def resolve(self, n_devices: Optional[int] = None) -> "ProcessTopology":
        devices = list(self.devices) if self.devices is not None else jax.devices()
        n = n_devices if n_devices is not None else len(devices)
        sizes = {"dp": self.dp, "tp": self.tp, "pp": self.pp, "ep": self.ep, "sp": self.sp}
        fixed = int(np.prod([v for v in sizes.values() if v > 0]))
        n_fill = sum(1 for v in sizes.values() if v == -1)
        assert n_fill <= 1, "at most one axis may be -1"
        if n_fill:
            assert n % fixed == 0, f"{n} devices not divisible by fixed axes product {fixed}"
            fill_val = n // fixed
            sizes = {k: (fill_val if v == -1 else v) for k, v in sizes.items()}
        total = int(np.prod(list(sizes.values())))
        assert total == n, f"mesh {sizes} covers {total} devices but {n} are available"
        axes = [a for a in self.axis_order if sizes[a] > 1] or ["dp"]
        dims = [sizes[a] for a in axes]
        return ProcessTopology(axes=axes, dims=dims, devices=devices[:n])

    def build_mesh(self, n_devices: Optional[int] = None) -> Mesh:
        return self.resolve(n_devices).get_mesh()


def mesh_axis_size(mesh: Mesh, axis: str) -> int:
    return mesh.shape.get(axis, 1) if axis else 1


def dp_axis_names(mesh: Mesh) -> Tuple[str, ...]:
    """Axes grads are averaged over: dp (and sp — batch is also split over sp)."""
    return tuple(a for a in ("dp", "sp") if a in mesh.axis_names)


def single_device_mesh() -> Mesh:
    return Mesh(np.array(jax.devices()[:1]), axis_names=("dp",))
