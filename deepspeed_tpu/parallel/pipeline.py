"""Pipeline parallelism — SPMD fill-drain schedule over the ``pp`` mesh axis.

TPU-native redesign of reference ``deepspeed/runtime/pipe/`` (PipelineModule
module.py:85, PipelineEngine engine.py:294, TrainSchedule schedule.py:182,
p2p.py send/recv). The reference runs one process per stage and interprets an
instruction schedule (RecvActivation/ForwardPass/SendActivation/…) with NCCL
p2p. Here the whole pipeline is ONE compiled SPMD program:

- **stage partition**: layer-stacked params ([L, ...] leaves) are sharded over
  ``pp`` on the layer dim — stage p owns layers [p·L/P, (p+1)·L/P). This is
  the ``PipelineModule._partition_layers`` analog (uniform partition; the
  param-balanced variant is unnecessary for homogeneous stacked blocks).
- **schedule**: a ``lax.scan`` over T = M + P - 1 ticks inside ``shard_map``
  (manual over ``pp`` only — dp/tp/ep stay automatic). Each tick: take stage
  input (fresh microbatch on stage 0, else the activation ppermuted in last
  tick), run the local layer block, ``ppermute`` the result to the next stage.
  p2p send/recv (pipe/p2p.py:48,69) becomes a single ring ``ppermute``.
- **backward**: autodiff of the scan+ppermute program IS the reverse pipeline
  (drain-fill), including tied-embedding gradient reduction across stages —
  the ``_exec_reduce_tied_grads`` analog falls out of shard_map's replicated-
  gradient psum.

Losses are computed on the last stage and masked-psum'd so every stage runs
an identical program (SPMD requirement). Bubble fraction matches GPipe:
(P-1)/(M+P-1); memory is bounded by remat of the stage body.
"""

from __future__ import annotations

import functools
from typing import Any, Callable, Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from ..utils.compat import pcast as _pcast, shard_map as _shard_map
from jax.sharding import Mesh, PartitionSpec as P

PyTree = Any


def num_pp_stages(mesh: Mesh) -> int:
    return mesh.shape.get("pp", 1)


def pipeline_apply(
    stage_fn: Callable[..., jnp.ndarray],
    layer_params: PyTree,
    x_micro: jnp.ndarray,
    mesh: Mesh,
    *,
    layer_axis_specs: Optional[PyTree] = None,
    remat_stage: bool = True,
    rng=None,
) -> jnp.ndarray:
    """Run microbatches through a P-stage pipeline.

    Args:
      stage_fn: ``(local_layer_params, h) -> h`` applying one stage's layers
        (``(local_layer_params, h, key) -> h`` when ``rng`` is given).
        ``local_layer_params`` leaves have leading dim L/P.
      layer_params: pytree with leading layer dim (full L) on every leaf.
      x_micro: [M, mb, ...] microbatched stage-0 inputs (already embedded).
      mesh: the device mesh (must contain ``pp`` if P > 1).
      layer_axis_specs: optional per-leaf PartitionSpec for the manual pp dim;
        default P('pp') on dim 0 of every leaf.
      rng: optional PRNG key enabling stochastic stages (dropout): each stage
        invocation gets a distinct fold of (tick, stage) so no key is reused
        across microbatches or stages.
    Returns: [M, mb, ...] last-stage outputs (valid on every device — the
      result is psum-broadcast from the last stage).
    """
    Pn = num_pp_stages(mesh)
    if Pn == 1:
        body = stage_fn
        if remat_stage:
            body = jax.checkpoint(body, prevent_cse=False)
        if rng is None:
            return jax.vmap(lambda xb: body(layer_params, xb))(x_micro)
        keys = jax.random.split(rng, x_micro.shape[0])
        return jax.vmap(lambda xb, k: body(layer_params, xb, k))(x_micro, keys)

    L = jax.tree.leaves(layer_params)[0].shape[0]
    if L % Pn != 0:
        raise ValueError(
            f"pipeline_apply: layer count {L} not divisible by pp stages {Pn}"
        )
    M = x_micro.shape[0]
    T = M + Pn - 1
    if layer_axis_specs is None:
        layer_axis_specs = jax.tree.map(lambda _: P("pp"), layer_params)

    def pipe(local_layers, xm):
        p = lax.axis_index("pp")
        body = stage_fn
        if remat_stage:
            body = jax.checkpoint(body, prevent_cse=False)

        def tick(carry, t):
            recv = carry  # activation handed to us on the previous tick
            mb_idx = jnp.clip(t, 0, M - 1)
            first_in = xm[mb_idx]
            inp = jnp.where(p == 0, first_in, recv)
            if rng is None:
                out = body(local_layers, inp)
            else:
                key = jax.random.fold_in(jax.random.fold_in(rng, t), p)
                out = body(local_layers, inp, key)
            shifted = lax.ppermute(out, "pp", [(i, (i + 1) % Pn) for i in range(Pn)])
            return shifted, out

        carry0 = _pcast(jnp.zeros_like(x_micro[0]), ("pp",), to="varying")
        _, outs = lax.scan(tick, carry0, jnp.arange(T))  # [T, mb, ...]
        # last stage's outputs for ticks P-1..T-1 are microbatches 0..M-1
        results = lax.dynamic_slice_in_dim(outs, Pn - 1, M, axis=0)
        # broadcast from last stage to all (identical programs downstream)
        is_last = (p == Pn - 1).astype(results.dtype)
        return lax.psum(results * is_last, "pp")

    sharded = _shard_map(
        pipe,
        mesh=mesh,
        in_specs=(layer_axis_specs, P()),
        out_specs=P(),
        axis_names={"pp"},
    )
    # jit so eager grad-of-shard_map works (jax requires jit around shard_map
    # for autodiff; nested jit is free when already inside a trace).
    return jax.jit(sharded)(layer_params, x_micro)


def make_head_grad(head_loss_fn: Callable) -> Callable:
    """Wrap ``(head_params, h, aux) -> loss`` into the ``head_grad_fn``
    contract of ``pipeline_train_1f1b``. The cotangent seed is built with
    ``ones_like(loss)`` so it inherits the varying-over-pp type required
    inside shard_map (a plain 1.0 is rejected by the VJP type check)."""

    def head_grad(head_params, h, aux):
        loss, vjp = jax.vjp(lambda hp, hh: head_loss_fn(hp, hh, aux), head_params, h)
        d_hp, dh = vjp(jnp.ones_like(loss))
        return loss, d_hp, dh

    return head_grad


def pipeline_train_1f1b(
    stage_fn: Callable[..., jnp.ndarray],
    head_grad_fn: Callable,
    layer_params: PyTree,
    head_params: PyTree,
    x_micro: jnp.ndarray,
    aux_micro: PyTree,
    mesh: Mesh,
    *,
    layer_axis_specs: Optional[PyTree] = None,
    rng=None,
) -> Tuple[jnp.ndarray, PyTree, PyTree, jnp.ndarray]:
    """Memory-bounded 1F1B pipeline step: loss AND grads in one schedule.

    The fill-drain path (``pipeline_apply`` + autodiff) keeps every tick's
    boundary activation alive for the whole backward — O(M + P) microbatch
    slots per stage. The reference's ``TrainSchedule``
    (runtime/pipe/schedule.py:182, num_pipe_buffers:243) interleaves one
    backward after each forward so at most ~P microbatches are in flight.
    This is that schedule as a single SPMD ``lax.scan``: each tick every
    stage runs one forward sub-step and one backward sub-step (lockstep
    1F1B), with

    - a **ring buffer of 2P-1 boundary inputs** per stage (the
      ``num_pipe_buffers`` analog) instead of a [T, ...] activation stack —
      stage p's input for microbatch m is stored at tick m+p and consumed by
      its own backward at tick m + 2(P-1) - p, a liveness window ≤ 2P-1
      independent of M;
    - forward activations ``ppermute``d down the ring, grad-activations
      ``ppermute``d up (p2p.py send/recv in both directions);
    - backward = per-tick ``jax.vjp`` of the stage body (residuals live for
      one tick only — rematerialization inside the schedule);
    - the head (final norm + logits + loss) evaluated on the last stage the
      tick a microbatch's forward completes, seeding its backward wave.

    Args:
      stage_fn: ``(local_layers, h[, key]) -> h``.
      head_grad_fn: ``(head_params, h, aux) -> (loss, d_head_params, dh)``
        where ``loss`` is this microbatch's mean loss scaled by
        ``loss_seed/M`` contributions (caller builds it via jax.vjp).
      layer_params: [L, ...]-leading pytree, sharded over pp.
      head_params: replicated head/norm params (grads psum'd from last stage).
      x_micro: [M, mb, ...] embedded stage-0 inputs.
      aux_micro: [M, ...] per-microbatch targets for the head (seed the
        backward inside head_grad_fn with scale/M for mean semantics).

    Returns ``(loss_sum, d_layer_params, d_head_params, dx_micro)``:
      loss_sum — sum of per-microbatch head losses (caller divides by M);
      d_layer_params — layer-dim-sharded grads (match layer_params specs);
      d_head_params / dx_micro — replicated (psum from owning stage).
    """
    Pn = num_pp_stages(mesh)
    M = x_micro.shape[0]
    if layer_axis_specs is None:
        layer_axis_specs = jax.tree.map(lambda _: P("pp"), layer_params)
    R = 2 * Pn - 1  # ring slots: max boundary-input liveness window
    T = M + 2 * (Pn - 1)  # fill + steady 1F1B + drain

    def pipe(local_layers, head_p, xm, auxm):
        p = lax.axis_index("pp")
        is_last = p == Pn - 1
        f32 = lambda t: jax.tree.map(lambda x: jnp.zeros(x.shape, jnp.float32), t)

        def run_stage(Lp, h, m_idx):
            # dropout keys derive from (microbatch, stage), NOT the tick, so
            # the backward sub-step's recompute replays the forward's masks
            if rng is None:
                return stage_fn(Lp, h)
            key = jax.random.fold_in(jax.random.fold_in(rng, m_idx), p)
            return stage_fn(Lp, h, key)

        def masked_add(acc, upd, valid):
            return jax.tree.map(
                lambda a, u: a + jnp.where(valid, u, 0).astype(a.dtype), acc, upd
            )

        def tick(carry, t):
            ring, recv_act, recv_dh, gL, gH, loss_sum, dx_buf = carry

            # ---- forward sub-step: stage p runs microbatch m_f = t - p ----
            m_f = t - p
            fwd_valid = (m_f >= 0) & (m_f < M)
            m_f_c = jnp.clip(m_f, 0, M - 1)
            inp = jnp.where(p == 0, xm[m_f_c], recv_act)
            out = run_stage(local_layers, inp, m_f_c)
            ring = lax.dynamic_update_index_in_dim(ring, inp, t % R, axis=0)

            # head on the last stage the tick a microbatch's forward lands;
            # cond (not where) so other stages skip the logits matmul —
            # head_grad_fn must be collective-free
            aux_f = jax.tree.map(lambda x: x[m_f_c], auxm)
            head_valid = fwd_valid & is_last

            def do_head(_):
                return head_grad_fn(head_p, out, aux_f)

            def skip_head(_):
                # pcast: branch outputs must match do_head's varying-over-pp
                # type (its results depend on the stage-local ``out``)
                vary = lambda x: _pcast(x, ("pp",), to="varying")
                return (
                    vary(jnp.float32(0.0)),
                    jax.tree.map(lambda x: vary(jnp.zeros_like(x)), head_p),
                    jnp.zeros_like(out),  # already varying (out is stage-local)
                )

            loss_m, d_hp, dh_head = lax.cond(head_valid, do_head, skip_head, None)
            loss_sum = loss_sum + loss_m
            gH = masked_add(gH, d_hp, head_valid)

            # ---- backward sub-step: stage p bwds m_b = t - 2(P-1) + p -----
            m_b = t - 2 * (Pn - 1) + p
            bwd_valid = (m_b >= 0) & (m_b < M)
            m_b_c = jnp.clip(m_b, 0, M - 1)
            # last stage's dh comes from THIS tick's head (m_b == m_f there);
            # other stages consume the dh ppermuted up from stage p+1
            dh_in = jnp.where(is_last, dh_head.astype(jnp.float32), recv_dh)
            saved_inp = ring[(m_b_c + p) % R]
            _, stage_vjp = jax.vjp(
                lambda Lp, x: run_stage(Lp, x, m_b_c), local_layers, saved_inp
            )
            dL, dx_s = stage_vjp(dh_in.astype(saved_inp.dtype))
            dx_f32 = dx_s.astype(jnp.float32)
            gL = masked_add(gL, dL, bwd_valid)
            dx_buf = jnp.where(
                bwd_valid & (p == 0),
                lax.dynamic_update_index_in_dim(dx_buf, dx_f32, m_b_c, axis=0),
                dx_buf,
            )

            # ---- p2p for the next tick (p2p.py:48,69 analog) --------------
            next_act = lax.ppermute(out, "pp", [(i, (i + 1) % Pn) for i in range(Pn)])
            next_dh = lax.ppermute(dx_f32, "pp", [(i, (i - 1) % Pn) for i in range(Pn)])
            return (ring, next_act, next_dh, gL, gH, loss_sum, dx_buf), None

        mb_shape = xm.shape[1:]
        varying = lambda x: _pcast(x, ("pp",), to="varying")
        carry0 = (
            varying(jnp.zeros((R,) + mb_shape, xm.dtype)),  # ring
            varying(jnp.zeros(mb_shape, xm.dtype)),  # recv_act
            varying(jnp.zeros(mb_shape, jnp.float32)),  # recv_dh
            varying(f32(local_layers)),  # gL
            varying(f32(head_p)),  # gH
            varying(jnp.float32(0.0)),  # loss_sum
            varying(jnp.zeros(xm.shape, jnp.float32)),  # dx_buf
        )
        (ring, _, _, gL, gH, loss_sum, dx_buf), _ = lax.scan(
            tick, carry0, jnp.arange(T)
        )
        # loss/head grads/dx live on one stage each; psum broadcasts them
        loss = lax.psum(loss_sum, "pp")
        gH = jax.tree.map(lambda g: lax.psum(g, "pp"), gH)
        dx = lax.psum(dx_buf, "pp")
        return loss, gL, gH, dx

    sharded = _shard_map(
        pipe,
        mesh=mesh,
        in_specs=(layer_axis_specs, P(), P(), P()),
        out_specs=(P(), layer_axis_specs, P(), P()),
        axis_names={"pp"},
    )
    return jax.jit(sharded)(layer_params, head_params, x_micro, aux_micro)
