"""Pipeline parallelism — SPMD fill-drain schedule over the ``pp`` mesh axis.

TPU-native redesign of reference ``deepspeed/runtime/pipe/`` (PipelineModule
module.py:85, PipelineEngine engine.py:294, TrainSchedule schedule.py:182,
p2p.py send/recv). The reference runs one process per stage and interprets an
instruction schedule (RecvActivation/ForwardPass/SendActivation/…) with NCCL
p2p. Here the whole pipeline is ONE compiled SPMD program:

- **stage partition**: layer-stacked params ([L, ...] leaves) are sharded over
  ``pp`` on the layer dim — stage p owns layers [p·L/P, (p+1)·L/P). This is
  the ``PipelineModule._partition_layers`` analog (uniform partition; the
  param-balanced variant is unnecessary for homogeneous stacked blocks).
- **schedule**: a ``lax.scan`` over T = M + P - 1 ticks inside ``shard_map``
  (manual over ``pp`` only — dp/tp/ep stay automatic). Each tick: take stage
  input (fresh microbatch on stage 0, else the activation ppermuted in last
  tick), run the local layer block, ``ppermute`` the result to the next stage.
  p2p send/recv (pipe/p2p.py:48,69) becomes a single ring ``ppermute``.
- **backward**: autodiff of the scan+ppermute program IS the reverse pipeline
  (drain-fill), including tied-embedding gradient reduction across stages —
  the ``_exec_reduce_tied_grads`` analog falls out of shard_map's replicated-
  gradient psum.

Losses are computed on the last stage and masked-psum'd so every stage runs
an identical program (SPMD requirement). Bubble fraction matches GPipe:
(P-1)/(M+P-1); memory is bounded by remat of the stage body.
"""

from __future__ import annotations

import functools
from typing import Any, Callable, Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

PyTree = Any


def num_pp_stages(mesh: Mesh) -> int:
    return mesh.shape.get("pp", 1)


def pipeline_apply(
    stage_fn: Callable[..., jnp.ndarray],
    layer_params: PyTree,
    x_micro: jnp.ndarray,
    mesh: Mesh,
    *,
    layer_axis_specs: Optional[PyTree] = None,
    remat_stage: bool = True,
    rng=None,
) -> jnp.ndarray:
    """Run microbatches through a P-stage pipeline.

    Args:
      stage_fn: ``(local_layer_params, h) -> h`` applying one stage's layers
        (``(local_layer_params, h, key) -> h`` when ``rng`` is given).
        ``local_layer_params`` leaves have leading dim L/P.
      layer_params: pytree with leading layer dim (full L) on every leaf.
      x_micro: [M, mb, ...] microbatched stage-0 inputs (already embedded).
      mesh: the device mesh (must contain ``pp`` if P > 1).
      layer_axis_specs: optional per-leaf PartitionSpec for the manual pp dim;
        default P('pp') on dim 0 of every leaf.
      rng: optional PRNG key enabling stochastic stages (dropout): each stage
        invocation gets a distinct fold of (tick, stage) so no key is reused
        across microbatches or stages.
    Returns: [M, mb, ...] last-stage outputs (valid on every device — the
      result is psum-broadcast from the last stage).
    """
    Pn = num_pp_stages(mesh)
    if Pn == 1:
        body = stage_fn
        if remat_stage:
            body = jax.checkpoint(body, prevent_cse=False)
        if rng is None:
            return jax.vmap(lambda xb: body(layer_params, xb))(x_micro)
        keys = jax.random.split(rng, x_micro.shape[0])
        return jax.vmap(lambda xb, k: body(layer_params, xb, k))(x_micro, keys)

    L = jax.tree.leaves(layer_params)[0].shape[0]
    if L % Pn != 0:
        raise ValueError(
            f"pipeline_apply: layer count {L} not divisible by pp stages {Pn}"
        )
    M = x_micro.shape[0]
    T = M + Pn - 1
    if layer_axis_specs is None:
        layer_axis_specs = jax.tree.map(lambda _: P("pp"), layer_params)

    def pipe(local_layers, xm):
        p = lax.axis_index("pp")
        body = stage_fn
        if remat_stage:
            body = jax.checkpoint(body, prevent_cse=False)

        def tick(carry, t):
            recv = carry  # activation handed to us on the previous tick
            mb_idx = jnp.clip(t, 0, M - 1)
            first_in = xm[mb_idx]
            inp = jnp.where(p == 0, first_in, recv)
            if rng is None:
                out = body(local_layers, inp)
            else:
                key = jax.random.fold_in(jax.random.fold_in(rng, t), p)
                out = body(local_layers, inp, key)
            shifted = lax.ppermute(out, "pp", [(i, (i + 1) % Pn) for i in range(Pn)])
            return shifted, out

        carry0 = lax.pcast(jnp.zeros_like(x_micro[0]), ("pp",), to="varying")
        _, outs = lax.scan(tick, carry0, jnp.arange(T))  # [T, mb, ...]
        # last stage's outputs for ticks P-1..T-1 are microbatches 0..M-1
        results = lax.dynamic_slice_in_dim(outs, Pn - 1, M, axis=0)
        # broadcast from last stage to all (identical programs downstream)
        is_last = (p == Pn - 1).astype(results.dtype)
        return lax.psum(results * is_last, "pp")

    sharded = jax.shard_map(
        pipe,
        mesh=mesh,
        in_specs=(layer_axis_specs, P()),
        out_specs=P(),
        axis_names={"pp"},
    )
    # jit so eager grad-of-shard_map works (jax requires jit around shard_map
    # for autodiff; nested jit is free when already inside a trace).
    return jax.jit(sharded)(layer_params, x_micro)
