"""Sequence/context parallelism: ring attention + Ulysses over the ``sp`` axis.

The reference snapshot has NO sequence parallelism (SURVEY.md §5 long-context:
no ring/Ulysses hits in ``deepspeed/``); its long-sequence story is sparse
attention + partitioned activation checkpointing. This module fills that gap
natively — on TPU a sequence axis is just another mesh axis and both schemes
map directly onto ICI collectives:

- **Ulysses** (all-to-all, DeepSpeed-Ulysses style): activations arrive
  sharded over sequence; one ``all_to_all`` re-shards heads over ``sp`` and
  gathers the full sequence per head-group, dense attention runs locally, a
  second ``all_to_all`` restores the sequence sharding. Communication volume
  is O(B·S·E/n) per call — rides ICI.
- **Ring attention** (blockwise, ppermute): K/V blocks rotate around the
  ``sp`` ring while each device keeps its Q shard; online-softmax (flash
  style) accumulation makes the result exact. Memory per device is O(S/n);
  communication is overlapped with the per-block attention matmuls by XLA
  (each ppermute is independent of the current block's compute).

Both are exact (match dense causal attention bit-for-bit up to f32 softmax
reassociation) and are verified against the dense path in
``tests/unit/test_sequence_parallel.py``.

Layout convention: [B, S, H, D], sequence sharded over ``sp``, batch over
``dp``, heads optionally over ``tp``.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from ..utils.compat import shard_map
from jax.sharding import Mesh, PartitionSpec as P

_NEG_INF = -1e30


# ---------------------------------------------------------------------------
# ring attention (per-device function, runs under shard_map)
# ---------------------------------------------------------------------------

def _ring_attention_local(q, k, v, *, axis_name: str, sm_scale: Optional[float], causal: bool):
    """Exact blockwise attention with K/V rotating over the ``axis_name`` ring.

    q, k, v: [B, S_loc, H, D] — this device's sequence shard.
    Returns [B, S_loc, H, D].
    """
    n = lax.psum(1, axis_name)
    idx = lax.axis_index(axis_name)
    B, S, H, D = q.shape
    scale = sm_scale if sm_scale is not None else 1.0 / (D**0.5)

    q_pos = idx * S + jnp.arange(S)  # global positions of local queries

    # online-softmax accumulators (f32)
    o0 = jnp.zeros((B, H, S, D), jnp.float32)
    m0 = jnp.full((B, H, S), _NEG_INF, jnp.float32)
    l0 = jnp.zeros((B, H, S), jnp.float32)

    # each step processes the K/V block originating from device (idx + step) % n;
    # blocks move "backwards" around the ring so device idx sees src idx, idx+1, …
    perm = [(j, (j - 1) % n) for j in range(n)]

    # remat: without it, backward through the scan stores every ring step's
    # [B,H,S_loc,S_loc] probability block (O(n·S_loc²) residuals — the full
    # attention matrix, defeating the point of ring attention). Recomputing
    # one block pair per step bounds residuals to the carries.
    @jax.checkpoint
    def step(carry, step_i):
        o, m, l, k_blk, v_blk = carry
        src = (idx + step_i) % n

        # NOTE: for causal attention, blocks with src > idx are fully masked,
        # but skipping them cannot shorten the step — the ppermute chains each
        # step to the busiest device (device n-1 always attends). Balancing
        # needs a zigzag Q layout, not a per-step branch; until then the mask
        # handles it.
        k_pos = src * S + jnp.arange(S)
        logits = jnp.einsum(
            "bqhd,bkhd->bhqk", q, k_blk, preferred_element_type=jnp.float32
        ) * scale
        if causal:
            mask = q_pos[:, None] >= k_pos[None, :]
            logits = jnp.where(mask[None, None], logits, _NEG_INF)
        blk_max = jnp.max(logits, axis=-1)
        m_new = jnp.maximum(m, blk_max)
        # guard: fully-masked rows keep m == -inf; exp(-inf - -inf) would be NaN
        safe_m = jnp.where(m_new <= _NEG_INF, 0.0, m_new)
        p = jnp.exp(logits - safe_m[..., None])
        p = jnp.where(logits <= _NEG_INF, 0.0, p)
        alpha = jnp.where(m <= _NEG_INF, 0.0, jnp.exp(m - safe_m))
        l = l * alpha + jnp.sum(p, axis=-1)
        pv = jnp.einsum(
            "bhqk,bkhd->bhqd", p.astype(v_blk.dtype), v_blk,
            preferred_element_type=jnp.float32,
        )
        o = o * alpha[..., None] + pv
        m = m_new
        # rotate K/V to the next device; independent of this block's compute,
        # so XLA overlaps the ppermute with the matmuls above
        k_blk = lax.ppermute(k_blk, axis_name, perm)
        v_blk = lax.ppermute(v_blk, axis_name, perm)
        return (o, m, l, k_blk, v_blk), None

    (o, m, l, _, _), _ = lax.scan(step, (o0, m0, l0, k, v), jnp.arange(n))
    o = o / jnp.maximum(l, 1e-30)[..., None]
    return jnp.transpose(o, (0, 2, 1, 3)).astype(q.dtype)  # [B,S,H,D]


# ---------------------------------------------------------------------------
# Ulysses attention (per-device function, runs under shard_map)
# ---------------------------------------------------------------------------

def _ulysses_local(q, k, v, *, axis_name: str, sm_scale: Optional[float], causal: bool):
    """All-to-all seq↔head re-sharding around a dense local attention.

    q, k, v: [B, S_loc, H_loc, D]. Requires H_loc % sp == 0.
    """
    n = lax.psum(1, axis_name)
    B, S, H, D = q.shape
    assert H % n == 0, f"Ulysses needs heads per device ({H}) divisible by sp ({n})"

    def seq_to_heads(x):
        # [B, S_loc, H, D] → [B, S_full, H/n, D]
        return lax.all_to_all(x, axis_name, split_axis=2, concat_axis=1, tiled=True)

    def heads_to_seq(x):
        return lax.all_to_all(x, axis_name, split_axis=1, concat_axis=2, tiled=True)

    q, k, v = seq_to_heads(q), seq_to_heads(k), seq_to_heads(v)
    Sf = S * n
    if causal:
        # Pallas flash path on TPU: O(S_full) memory per device. The jnp
        # fallback (non-TPU, or shapes the kernel rejects) still materializes
        # the [B, H/n, S_full, S_full] logits — at that point prefer ring.
        from ..ops.attention import causal_attention

        o = causal_attention(q, k, v, sm_scale=sm_scale)
    else:
        from ..ops.attention import _pallas_ok

        if _pallas_ok(q):
            from ..ops.pallas.flash_attention import flash_attention

            o = flash_attention(q, k, v, causal=False, sm_scale=sm_scale)
        else:
            scale = sm_scale if sm_scale is not None else 1.0 / (D**0.5)
            logits = jnp.einsum(
                "bqhd,bkhd->bhqk", q, k, preferred_element_type=jnp.float32
            ) * scale
            probs = jax.nn.softmax(logits, axis=-1).astype(v.dtype)
            o = jnp.einsum("bhqk,bkhd->bqhd", probs, v)
    return heads_to_seq(o)


# ---------------------------------------------------------------------------
# public API
# ---------------------------------------------------------------------------

def sequence_parallel_attention(
    q,
    k,
    v,
    mesh: Mesh,
    impl: str = "ring",  # "ring" | "ring_flash" | "ulysses"
    causal: bool = True,
    sm_scale: Optional[float] = None,
    dp_axis: str = "dp",
    sp_axis: str = "sp",
    tp_axis: str = "tp",
    interpret: bool = False,
):
    """Sequence-parallel exact attention over a named mesh.

    Inputs [B, S, H, D] logically; S sharded over ``sp_axis``, B over
    ``dp_axis``, H over ``tp_axis`` (any axis absent from the mesh degrades to
    replicated). Output has the same sharding as q.

    ``impl="ring"`` auto-upgrades each ring step's blockwise compute to the
    Pallas flash kernels on TPU when the shard shapes allow
    (ops/pallas/ring_flash_attention.py); ``"ring_flash"`` forces that path
    (with ``interpret=True`` it runs on CPU for tests).
    """
    if impl not in ("ring", "ring_flash", "ulysses"):
        raise ValueError(f"unknown sequence-parallel impl {impl}")
    if mesh.shape.get("pp", 1) > 1 and mesh.shape.get(sp_axis, 1) > 1:
        raise NotImplementedError(
            "sequence-parallel attention (ring/ulysses) cannot run inside a "
            "pipeline-parallel stage: the sp shard_map would nest inside the "
            "pp shard_map. Use pp with attn_impl='flash'/'jnp', or drop pp."
        )
    axes = mesh.axis_names
    dp = dp_axis if dp_axis in axes else None
    sp = sp_axis if sp_axis in axes else None
    tp = tp_axis if tp_axis in axes else None
    if sp is None or mesh.shape.get(sp, 1) == 1:
        # no sequence axis — fall back to plain dense attention
        from ..ops.attention import causal_attention_jnp

        assert causal, "non-causal fallback not wired"
        return causal_attention_jnp(q, k, v, sm_scale)

    sp_size = mesh.shape[sp]
    tp_size = mesh.shape.get(tp, 1) if tp else 1
    heads_local = q.shape[2] // tp_size
    if impl == "ulysses" and heads_local % sp_size != 0:
        from ..utils.logging import warning_once

        warning_once(
            f"Ulysses needs local heads ({heads_local}) divisible by sp ({sp_size}); "
            "falling back to ring attention"
        )
        impl = "ring"
    if impl == "ring":
        # auto-upgrade the ring's inner blockwise compute to the flash
        # kernels when each device's shard is tile-aligned and within the
        # grid kernel's ceiling (past the whole-K/V VMEM budget the inner
        # compute streams K/V through the KV-blocked grid variant)
        from ..ops.pallas.ring_flash_attention import ring_flash_ok

        s_loc = q.shape[1] // sp_size
        if jax.default_backend() == "tpu" and ring_flash_ok(
            s_loc, q.shape[3], q.dtype.itemsize
        ):
            impl = "ring_flash"
    spec = P(dp, sp, tp, None)
    if impl == "ring_flash":
        from ..ops.pallas.ring_flash_attention import ring_flash_attention

        fn = functools.partial(
            ring_flash_attention, axis_name=sp, sm_scale=sm_scale,
            causal=causal, interpret=interpret,
        )
    else:
        local = _ring_attention_local if impl == "ring" else _ulysses_local
        fn = functools.partial(local, axis_name=sp, sm_scale=sm_scale, causal=causal)
    return shard_map(
        fn, mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec, check_vma=False
    )(q, k, v)


def shard_sequence(batch, mesh: Mesh, seq_dim: int = 1, dp_axis: str = "dp", sp_axis: str = "sp"):
    """Device-put a host batch with the sequence dim over ``sp`` (and batch
    over ``dp``) — the input-side hook for long-context training."""
    from jax.sharding import NamedSharding

    def put(x):
        spec = [None] * x.ndim
        if dp_axis in mesh.axis_names:
            spec[0] = dp_axis
        if x.ndim > seq_dim and sp_axis in mesh.axis_names:
            spec[seq_dim] = sp_axis
        return jax.device_put(x, NamedSharding(mesh, P(*spec)))

    return jax.tree.map(put, batch)
