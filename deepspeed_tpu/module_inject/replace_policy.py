"""Per-architecture injection policies: HF torch checkpoints → TPU decode graph.

Analog of reference ``deepspeed/module_inject/replace_policy.py`` (501 LoC:
HFBertLayerPolicy:66, HFGPTNEOLayerPolicy:129, HFGPTJLayerPolicy:174,
MegatronLayerPolicy:219, HFGPT2LayerPolicy:299, BLOOMLayerPolicy:339,
GPTNEOXLayerPolicy:381, HFOPTLayerPolicy:435). The reference's policy returns
the attention/MLP/LayerNorm tensors of ONE torch layer so replace_module can
rebuild it around fused CUDA kernels. Here a policy converts the WHOLE model
once: torch weights → a stacked (scan-over-layers) JAX param pytree + the
matching model config, after which the decode graph is an ordinary jitted
function (XLA is the fused kernel).

Policies register in ``POLICY_REGISTRY``; ``match_policy`` picks by HF class
name so ``init_inference(hf_model)`` needs no explicit policy argument
(reference ``replace_method="auto"``).
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

PyTree = Any


def _t(x) -> np.ndarray:
    """torch tensor → numpy fp32 (host-side; conversion happens once)."""
    return x.detach().cpu().float().numpy()


def _stack(layers: List[np.ndarray]) -> np.ndarray:
    return np.stack(layers, axis=0)


class DSPolicy:
    """Base policy. Subclasses set ``hf_class_names`` and implement
    ``convert(hf_model) -> (model_kind, config, params)``."""

    hf_class_names: Tuple[str, ...] = ()

    @classmethod
    def matches(cls, hf_model) -> bool:
        return type(hf_model).__name__ in cls.hf_class_names

    @classmethod
    def convert(cls, hf_model):
        raise NotImplementedError


class HFGPT2LayerPolicy(DSPolicy):
    """transformers GPT2LMHeadModel / GPT2Model → models.gpt2 stacked params.

    HF GPT-2 uses Conv1D with weight stored [in, out] — identical to our
    matmul layout, so tensors map 1:1 (reference HFGPT2LayerPolicy:299 also
    relies on this orientation)."""

    hf_class_names = ("GPT2LMHeadModel", "GPT2Model")

    @classmethod
    def convert(cls, hf_model):
        from ..models.gpt2 import GPT2Config

        t = hf_model.transformer if hasattr(hf_model, "transformer") else hf_model
        hf_cfg = hf_model.config
        cfg = GPT2Config(
            vocab_size=hf_cfg.vocab_size,
            n_positions=hf_cfg.n_positions,
            n_embd=hf_cfg.n_embd,
            n_layer=hf_cfg.n_layer,
            n_head=hf_cfg.n_head,
            layer_norm_epsilon=hf_cfg.layer_norm_epsilon,
            attn_impl="jnp",
        )
        hs = list(t.h)
        params = {
            "wte": _t(t.wte.weight),
            "wpe": _t(t.wpe.weight),
            "ln_f": {"scale": _t(t.ln_f.weight), "bias": _t(t.ln_f.bias)},
            "blocks": {
                "ln_1": {
                    "scale": _stack([_t(h.ln_1.weight) for h in hs]),
                    "bias": _stack([_t(h.ln_1.bias) for h in hs]),
                },
                "ln_2": {
                    "scale": _stack([_t(h.ln_2.weight) for h in hs]),
                    "bias": _stack([_t(h.ln_2.bias) for h in hs]),
                },
                "attn": {
                    "c_attn_w": _stack([_t(h.attn.c_attn.weight) for h in hs]),
                    "c_attn_b": _stack([_t(h.attn.c_attn.bias) for h in hs]),
                    "c_proj_w": _stack([_t(h.attn.c_proj.weight) for h in hs]),
                    "c_proj_b": _stack([_t(h.attn.c_proj.bias) for h in hs]),
                },
                "mlp": {
                    "c_fc_w": _stack([_t(h.mlp.c_fc.weight) for h in hs]),
                    "c_fc_b": _stack([_t(h.mlp.c_fc.bias) for h in hs]),
                    "c_proj_w": _stack([_t(h.mlp.c_proj.weight) for h in hs]),
                    "c_proj_b": _stack([_t(h.mlp.c_proj.bias) for h in hs]),
                },
            },
        }
        return "gpt2", cfg, params

    @classmethod
    def revert(cls, hf_model, params) -> None:
        """Inverse of :meth:`convert`: unstack the layer dim and copy each
        tensor back into the torch module in place (Conv1D layout is already
        ours, so the mapping is exact — fine-tune here, export to HF)."""
        import torch

        t = hf_model.transformer if hasattr(hf_model, "transformer") else hf_model

        def put(dst, src) -> None:
            arr = np.asarray(src, dtype=np.float32)
            with torch.no_grad():
                dst.copy_(torch.from_numpy(arr).to(dst.dtype))

        put(t.wte.weight, params["wte"])
        put(t.wpe.weight, params["wpe"])
        put(t.ln_f.weight, params["ln_f"]["scale"])
        put(t.ln_f.bias, params["ln_f"]["bias"])
        blocks = params["blocks"]
        for i, h in enumerate(t.h):
            put(h.ln_1.weight, blocks["ln_1"]["scale"][i])
            put(h.ln_1.bias, blocks["ln_1"]["bias"][i])
            put(h.ln_2.weight, blocks["ln_2"]["scale"][i])
            put(h.ln_2.bias, blocks["ln_2"]["bias"][i])
            put(h.attn.c_attn.weight, blocks["attn"]["c_attn_w"][i])
            put(h.attn.c_attn.bias, blocks["attn"]["c_attn_b"][i])
            put(h.attn.c_proj.weight, blocks["attn"]["c_proj_w"][i])
            put(h.attn.c_proj.bias, blocks["attn"]["c_proj_b"][i])
            put(h.mlp.c_fc.weight, blocks["mlp"]["c_fc_w"][i])
            put(h.mlp.c_fc.bias, blocks["mlp"]["c_fc_b"][i])
            put(h.mlp.c_proj.weight, blocks["mlp"]["c_proj_w"][i])
            put(h.mlp.c_proj.bias, blocks["mlp"]["c_proj_b"][i])


def _linear_w(layer) -> np.ndarray:
    """torch Linear weight [out, in] → matmul layout [in, out]."""
    return _t(layer.weight).T


def _maybe_b(layer, n: int) -> np.ndarray:
    return _t(layer.bias) if getattr(layer, "bias", None) is not None else np.zeros(n, np.float32)


def _split_fused_qkv(w: np.ndarray, b: np.ndarray, n_head: int):
    """De-interleave a fused query_key_value Linear (BLOOM/NeoX layout:
    out dim organised [H, 3, D]) into plain q/k/v [E, E] + biases."""
    E3, E = w.shape  # torch [out, in]
    D = E // n_head
    wr = w.reshape(n_head, 3, D, E)
    br = b.reshape(n_head, 3, D)
    out = []
    for i in range(3):
        out.append((wr[:, i].reshape(E, E).T.copy(), br[:, i].reshape(E).copy()))
    return out  # [(wq [E,E] in×out, bq), (wk, bk), (wv, bv)]


def _tree_stack(dicts: List[Dict]) -> Dict:
    out = {}
    for k in dicts[0]:
        vals = [d[k] for d in dicts]
        out[k] = _tree_stack(vals) if isinstance(vals[0], dict) else _stack(vals)
    return out


class HFOPTLayerPolicy(DSPolicy):
    """transformers OPTForCausalLM → unified decoder (reference HFOPTLayerPolicy:435)."""

    hf_class_names = ("OPTForCausalLM", "OPTModel")

    @classmethod
    def convert(cls, hf_model):
        from ..models.decoder import DecoderConfig

        hc = hf_model.config
        assert hc.word_embed_proj_dim == hc.hidden_size, "OPT embed projection unsupported"
        assert getattr(hc, "do_layer_norm_before", True), "post-LN OPT unsupported"
        dec = hf_model.model.decoder if hasattr(hf_model, "model") else hf_model.decoder
        E, F = hc.hidden_size, hc.ffn_dim
        cfg = DecoderConfig(
            vocab_size=hc.vocab_size, n_positions=hc.max_position_embeddings,
            n_embd=E, n_layer=hc.num_hidden_layers, n_head=hc.num_attention_heads,
            ffn_dim=F, pos_emb="learned", pos_offset=2,
            activation="relu" if hc.activation_function == "relu" else "gelu",
            tie_embeddings=True,
        )

        def get(l):
            return {
                "ln_1": {"scale": _t(l.self_attn_layer_norm.weight), "bias": _t(l.self_attn_layer_norm.bias)},
                "ln_2": {"scale": _t(l.final_layer_norm.weight), "bias": _t(l.final_layer_norm.bias)},
                "attn": {
                    "wq": _linear_w(l.self_attn.q_proj), "bq": _maybe_b(l.self_attn.q_proj, E),
                    "wk": _linear_w(l.self_attn.k_proj), "bk": _maybe_b(l.self_attn.k_proj, E),
                    "wv": _linear_w(l.self_attn.v_proj), "bv": _maybe_b(l.self_attn.v_proj, E),
                    "wo": _linear_w(l.self_attn.out_proj), "bo": _maybe_b(l.self_attn.out_proj, E),
                },
                "mlp": {
                    "fc_in_w": _linear_w(l.fc1), "fc_in_b": _maybe_b(l.fc1, F),
                    "fc_out_w": _linear_w(l.fc2), "fc_out_b": _maybe_b(l.fc2, E),
                },
            }

        params = {
            "wte": _t(dec.embed_tokens.weight),
            "wpe": _t(dec.embed_positions.weight),
            "ln_f": {"scale": _t(dec.final_layer_norm.weight), "bias": _t(dec.final_layer_norm.bias)},
            "blocks": _tree_stack([get(l) for l in dec.layers]),
        }
        return "decoder", cfg, params


class BLOOMLayerPolicy(DSPolicy):
    """transformers BloomForCausalLM → unified decoder with ALiBi
    (reference BLOOMLayerPolicy:339)."""

    hf_class_names = ("BloomForCausalLM", "BloomModel")

    @classmethod
    def convert(cls, hf_model):
        from ..models.decoder import DecoderConfig

        hc = hf_model.config
        t = hf_model.transformer if hasattr(hf_model, "transformer") else hf_model
        E, H = hc.hidden_size, hc.n_head
        F = 4 * E
        cfg = DecoderConfig(
            vocab_size=hc.vocab_size, n_positions=4096, n_embd=E,
            n_layer=hc.n_layer, n_head=H, ffn_dim=F,
            pos_emb="alibi", activation="gelu_new", embed_ln=True,
            layer_norm_epsilon=hc.layer_norm_epsilon,
        )

        def get(l):
            (wq, bq), (wk, bk), (wv, bv) = _split_fused_qkv(
                _t(l.self_attention.query_key_value.weight),
                _t(l.self_attention.query_key_value.bias), H,
            )
            return {
                "ln_1": {"scale": _t(l.input_layernorm.weight), "bias": _t(l.input_layernorm.bias)},
                "ln_2": {"scale": _t(l.post_attention_layernorm.weight), "bias": _t(l.post_attention_layernorm.bias)},
                "attn": {
                    "wq": wq, "bq": bq, "wk": wk, "bk": bk, "wv": wv, "bv": bv,
                    "wo": _linear_w(l.self_attention.dense), "bo": _maybe_b(l.self_attention.dense, E),
                },
                "mlp": {
                    "fc_in_w": _linear_w(l.mlp.dense_h_to_4h), "fc_in_b": _maybe_b(l.mlp.dense_h_to_4h, F),
                    "fc_out_w": _linear_w(l.mlp.dense_4h_to_h), "fc_out_b": _maybe_b(l.mlp.dense_4h_to_h, E),
                },
            }

        params = {
            "wte": _t(t.word_embeddings.weight),
            "emb_ln": {"scale": _t(t.word_embeddings_layernorm.weight), "bias": _t(t.word_embeddings_layernorm.bias)},
            "ln_f": {"scale": _t(t.ln_f.weight), "bias": _t(t.ln_f.bias)},
            "blocks": _tree_stack([get(l) for l in t.h]),
        }
        return "decoder", cfg, params


class HFGPTJLayerPolicy(DSPolicy):
    """transformers GPTJForCausalLM → unified decoder with interleaved RoPE +
    parallel residual, single shared LN (reference HFGPTJLayerPolicy:174)."""

    hf_class_names = ("GPTJForCausalLM", "GPTJModel")

    @classmethod
    def convert(cls, hf_model):
        from ..models.decoder import DecoderConfig

        hc = hf_model.config
        t = hf_model.transformer if hasattr(hf_model, "transformer") else hf_model
        E, F = hc.n_embd, 4 * hc.n_embd
        cfg = DecoderConfig(
            vocab_size=hc.vocab_size, n_positions=hc.n_positions, n_embd=E,
            n_layer=hc.n_layer, n_head=hc.n_head, ffn_dim=F,
            pos_emb="rope", rope_style="gptj", rotary_dim=hc.rotary_dim or 0,
            activation="gelu_new", parallel_residual=True, use_ln2=False,
            tie_embeddings=False, lm_head_bias=True,
            layer_norm_epsilon=hc.layer_norm_epsilon,
        )

        def get(l):
            z = np.zeros(E, np.float32)
            return {
                "ln_1": {"scale": _t(l.ln_1.weight), "bias": _t(l.ln_1.bias)},
                "attn": {
                    "wq": _linear_w(l.attn.q_proj), "bq": z,
                    "wk": _linear_w(l.attn.k_proj), "bk": z,
                    "wv": _linear_w(l.attn.v_proj), "bv": z,
                    "wo": _linear_w(l.attn.out_proj), "bo": z,
                },
                "mlp": {
                    "fc_in_w": _linear_w(l.mlp.fc_in), "fc_in_b": _maybe_b(l.mlp.fc_in, F),
                    "fc_out_w": _linear_w(l.mlp.fc_out), "fc_out_b": _maybe_b(l.mlp.fc_out, E),
                },
            }

        params = {
            "wte": _t(t.wte.weight),
            "ln_f": {"scale": _t(t.ln_f.weight), "bias": _t(t.ln_f.bias)},
            "blocks": _tree_stack([get(l) for l in t.h]),
            "lm_head_w": _linear_w(hf_model.lm_head),
            "lm_head_b": _maybe_b(hf_model.lm_head, hc.vocab_size),
        }
        return "decoder", cfg, params


class HFGPTNEOLayerPolicy(DSPolicy):
    """transformers GPTNeoForCausalLM → unified decoder, unscaled attention +
    alternating local windows (reference HFGPTNEOLayerPolicy:129)."""

    hf_class_names = ("GPTNeoForCausalLM", "GPTNeoModel")

    @classmethod
    def convert(cls, hf_model):
        from ..models.decoder import DecoderConfig

        hc = hf_model.config
        t = hf_model.transformer if hasattr(hf_model, "transformer") else hf_model
        E, F = hc.hidden_size, hc.intermediate_size or 4 * hc.hidden_size
        windows = tuple(
            hc.window_size if at == "local" else 0 for at in hc.attention_layers
        )
        cfg = DecoderConfig(
            vocab_size=hc.vocab_size, n_positions=hc.max_position_embeddings,
            n_embd=E, n_layer=hc.num_layers, n_head=hc.num_heads, ffn_dim=F,
            pos_emb="learned", activation="gelu_new", attn_scale=1.0,
            local_windows=windows, layer_norm_epsilon=hc.layer_norm_epsilon,
        )

        def get(l):
            a = l.attn.attention
            z = np.zeros(E, np.float32)
            return {
                "ln_1": {"scale": _t(l.ln_1.weight), "bias": _t(l.ln_1.bias)},
                "ln_2": {"scale": _t(l.ln_2.weight), "bias": _t(l.ln_2.bias)},
                "attn": {
                    "wq": _linear_w(a.q_proj), "bq": z,
                    "wk": _linear_w(a.k_proj), "bk": z,
                    "wv": _linear_w(a.v_proj), "bv": z,
                    "wo": _linear_w(a.out_proj), "bo": _maybe_b(a.out_proj, E),
                },
                "mlp": {
                    "fc_in_w": _linear_w(l.mlp.c_fc), "fc_in_b": _maybe_b(l.mlp.c_fc, F),
                    "fc_out_w": _linear_w(l.mlp.c_proj), "fc_out_b": _maybe_b(l.mlp.c_proj, E),
                },
            }

        params = {
            "wte": _t(t.wte.weight),
            "wpe": _t(t.wpe.weight),
            "ln_f": {"scale": _t(t.ln_f.weight), "bias": _t(t.ln_f.bias)},
            "blocks": _tree_stack([get(l) for l in t.h]),
        }
        return "decoder", cfg, params


class GPTNEOXLayerPolicy(DSPolicy):
    """transformers GPTNeoXForCausalLM → unified decoder with half-split RoPE
    + parallel residual (reference GPTNEOXLayerPolicy:381)."""

    hf_class_names = ("GPTNeoXForCausalLM", "GPTNeoXModel")

    @classmethod
    def convert(cls, hf_model):
        from ..models.decoder import DecoderConfig

        hc = hf_model.config
        t = hf_model.gpt_neox if hasattr(hf_model, "gpt_neox") else hf_model
        E, H = hc.hidden_size, hc.num_attention_heads
        F = hc.intermediate_size
        D = E // H
        cfg = DecoderConfig(
            vocab_size=hc.vocab_size, n_positions=hc.max_position_embeddings,
            n_embd=E, n_layer=hc.num_hidden_layers, n_head=H, ffn_dim=F,
            pos_emb="rope", rope_style="neox", rotary_dim=int(D * hc.rotary_pct),
            activation="gelu", parallel_residual=bool(hc.use_parallel_residual),
            use_ln2=True, tie_embeddings=False, layer_norm_epsilon=hc.layer_norm_eps,
        )

        def get(l):
            (wq, bq), (wk, bk), (wv, bv) = _split_fused_qkv(
                _t(l.attention.query_key_value.weight),
                _t(l.attention.query_key_value.bias), H,
            )
            return {
                "ln_1": {"scale": _t(l.input_layernorm.weight), "bias": _t(l.input_layernorm.bias)},
                "ln_2": {"scale": _t(l.post_attention_layernorm.weight), "bias": _t(l.post_attention_layernorm.bias)},
                "attn": {
                    "wq": wq, "bq": bq, "wk": wk, "bk": bk, "wv": wv, "bv": bv,
                    "wo": _linear_w(l.attention.dense), "bo": _maybe_b(l.attention.dense, E),
                },
                "mlp": {
                    "fc_in_w": _linear_w(l.mlp.dense_h_to_4h), "fc_in_b": _maybe_b(l.mlp.dense_h_to_4h, F),
                    "fc_out_w": _linear_w(l.mlp.dense_4h_to_h), "fc_out_b": _maybe_b(l.mlp.dense_4h_to_h, E),
                },
            }

        params = {
            "wte": _t(t.embed_in.weight),
            "ln_f": {"scale": _t(t.final_layer_norm.weight), "bias": _t(t.final_layer_norm.bias)},
            "blocks": _tree_stack([get(l) for l in t.layers]),
            "lm_head_w": _linear_w(hf_model.embed_out),
        }
        return "decoder", cfg, params


class MegatronLayerPolicy(DSPolicy):
    """Megatron-LM GPT-2 checkpoints (state-dict based) → unified decoder
    (reference MegatronLayerPolicy:219). Megatron fuses QKV like NeoX
    ([H, 3, D] interleave) and uses learned positions + gelu."""

    hf_class_names = ()  # matched explicitly via convert_state_dict

    @classmethod
    def convert_state_dict(cls, sd: Dict[str, Any], n_head: int, n_positions: Optional[int] = None):
        from ..models.decoder import DecoderConfig

        pre = "model.language_model." if any(k.startswith("model.") for k in sd) else "language_model."
        emb = sd[f"{pre}embedding.word_embeddings.weight"]
        pos = sd[f"{pre}embedding.position_embeddings.weight"]
        tkeys = sorted(
            {int(k.split(".")[-3]) for k in sd if ".layers." in k and k.endswith("input_layernorm.weight")}
        )
        V, E = np.asarray(emb).shape
        F = np.asarray(sd[f"{pre}transformer.layers.0.mlp.dense_h_to_4h.weight"]).shape[0]
        cfg = DecoderConfig(
            vocab_size=V, n_positions=n_positions or np.asarray(pos).shape[0],
            n_embd=E, n_layer=len(tkeys), n_head=n_head, ffn_dim=F,
            pos_emb="learned", activation="gelu", tie_embeddings=True,
        )

        def get(i):
            p = f"{pre}transformer.layers.{i}."
            (wq, bq), (wk, bk), (wv, bv) = _split_fused_qkv(
                np.asarray(sd[p + "attention.query_key_value.weight"], np.float32),
                np.asarray(sd[p + "attention.query_key_value.bias"], np.float32), n_head,
            )
            return {
                "ln_1": {"scale": np.asarray(sd[p + "input_layernorm.weight"], np.float32),
                         "bias": np.asarray(sd[p + "input_layernorm.bias"], np.float32)},
                "ln_2": {"scale": np.asarray(sd[p + "post_attention_layernorm.weight"], np.float32),
                         "bias": np.asarray(sd[p + "post_attention_layernorm.bias"], np.float32)},
                "attn": {
                    "wq": wq, "bq": bq, "wk": wk, "bk": bk, "wv": wv, "bv": bv,
                    "wo": np.asarray(sd[p + "attention.dense.weight"], np.float32).T,
                    "bo": np.asarray(sd[p + "attention.dense.bias"], np.float32),
                },
                "mlp": {
                    "fc_in_w": np.asarray(sd[p + "mlp.dense_h_to_4h.weight"], np.float32).T,
                    "fc_in_b": np.asarray(sd[p + "mlp.dense_h_to_4h.bias"], np.float32),
                    "fc_out_w": np.asarray(sd[p + "mlp.dense_4h_to_h.weight"], np.float32).T,
                    "fc_out_b": np.asarray(sd[p + "mlp.dense_4h_to_h.bias"], np.float32),
                },
            }

        params = {
            "wte": np.asarray(emb, np.float32),
            "wpe": np.asarray(pos, np.float32),
            "ln_f": {"scale": np.asarray(sd[f"{pre}transformer.final_layernorm.weight"], np.float32),
                     "bias": np.asarray(sd[f"{pre}transformer.final_layernorm.bias"], np.float32)},
            "blocks": _tree_stack([get(i) for i in tkeys]),
        }
        return "decoder", cfg, params


class HFBertLayerPolicy(DSPolicy):
    """transformers BertModel → models.bert encoder (reference HFBertLayerPolicy:66)."""

    hf_class_names = ("BertModel", "BertForSequenceClassification", "BertForQuestionAnswering")

    @classmethod
    def convert(cls, hf_model):
        from ..models.bert import BertConfig as DSBertConfig

        bert = getattr(hf_model, "bert", hf_model)
        hc = hf_model.config
        E, F = hc.hidden_size, hc.intermediate_size
        cfg = DSBertConfig(
            vocab_size=hc.vocab_size, n_positions=hc.max_position_embeddings,
            n_embd=E, n_layer=hc.num_hidden_layers, n_head=hc.num_attention_heads,
            ffn_dim=F, type_vocab_size=hc.type_vocab_size,
            layer_norm_epsilon=hc.layer_norm_eps,
        )

        def get(l):
            return {
                "attn": {
                    "wq": _linear_w(l.attention.self.query), "bq": _maybe_b(l.attention.self.query, E),
                    "wk": _linear_w(l.attention.self.key), "bk": _maybe_b(l.attention.self.key, E),
                    "wv": _linear_w(l.attention.self.value), "bv": _maybe_b(l.attention.self.value, E),
                    "wo": _linear_w(l.attention.output.dense), "bo": _maybe_b(l.attention.output.dense, E),
                },
                "attn_ln": {"scale": _t(l.attention.output.LayerNorm.weight), "bias": _t(l.attention.output.LayerNorm.bias)},
                "mlp": {
                    "fc_in_w": _linear_w(l.intermediate.dense), "fc_in_b": _maybe_b(l.intermediate.dense, F),
                    "fc_out_w": _linear_w(l.output.dense), "fc_out_b": _maybe_b(l.output.dense, E),
                },
                "out_ln": {"scale": _t(l.output.LayerNorm.weight), "bias": _t(l.output.LayerNorm.bias)},
            }

        emb = bert.embeddings
        params = {
            "wte": _t(emb.word_embeddings.weight),
            "wpe": _t(emb.position_embeddings.weight),
            "wtt": _t(emb.token_type_embeddings.weight),
            "emb_ln": {"scale": _t(emb.LayerNorm.weight), "bias": _t(emb.LayerNorm.bias)},
            "blocks": _tree_stack([get(l) for l in bert.encoder.layer]),
            "pooler": {"w": _linear_w(bert.pooler.dense), "b": _maybe_b(bert.pooler.dense, E)}
            if getattr(bert, "pooler", None) is not None
            else None,
        }
        return "bert", cfg, params


class HFLlamaLayerPolicy(DSPolicy):
    """transformers LlamaForCausalLM / MistralForCausalLM → unified decoder
    with RMSNorm + SwiGLU + GQA + neox-style RoPE. Beyond the reference
    snapshot's zoo (its newest arch is BLOOM); Mistral adds a sliding
    window, mapped onto the decoder's per-layer ``local_windows``."""

    # bare LlamaModel/MistralModel are excluded: without lm_head the
    # serving conversion would be incomplete
    hf_class_names = ("LlamaForCausalLM", "MistralForCausalLM")

    @classmethod
    def convert(cls, hf_model):
        from ..models.decoder import DecoderConfig

        hc = hf_model.config
        t = hf_model.model if hasattr(hf_model, "model") else hf_model
        E = hc.hidden_size
        L = hc.num_hidden_layers
        window = int(getattr(hc, "sliding_window", 0) or 0)
        cfg = DecoderConfig(
            vocab_size=hc.vocab_size,
            n_positions=hc.max_position_embeddings,
            n_embd=E,
            n_layer=L,
            n_head=hc.num_attention_heads,
            ffn_dim=hc.intermediate_size,
            pos_emb="rope",
            rope_style="neox",
            rope_theta=float(getattr(hc, "rope_theta", 10000.0)),
            norm="rmsnorm",
            mlp_type="swiglu",
            n_kv_head=int(getattr(hc, "num_key_value_heads", hc.num_attention_heads)),
            tie_embeddings=bool(getattr(hc, "tie_word_embeddings", False)),
            layer_norm_epsilon=hc.rms_norm_eps,
            local_windows=(window,) * L if window else (),
        )

        def get(l):
            return {
                "ln_1": {"scale": _t(l.input_layernorm.weight)},
                "ln_2": {"scale": _t(l.post_attention_layernorm.weight)},
                "attn": {
                    "wq": _linear_w(l.self_attn.q_proj),
                    "wk": _linear_w(l.self_attn.k_proj),
                    "wv": _linear_w(l.self_attn.v_proj),
                    "wo": _linear_w(l.self_attn.o_proj),
                },
                "mlp": {
                    "fc_gate_w": _linear_w(l.mlp.gate_proj),
                    "fc_in_w": _linear_w(l.mlp.up_proj),
                    "fc_out_w": _linear_w(l.mlp.down_proj),
                },
            }

        params = {
            "wte": _t(t.embed_tokens.weight),
            "ln_f": {"scale": _t(t.norm.weight)},
            "blocks": _tree_stack([get(l) for l in t.layers]),
        }
        if not cfg.tie_embeddings:
            params["lm_head_w"] = _linear_w(hf_model.lm_head)
        return "decoder", cfg, params


class HFMixtralLayerPolicy(DSPolicy):
    """transformers MixtralForCausalLM → unified decoder with per-layer
    SwiGLU MoE (top-2, no-drop eval routing — Mixtral-exact) + GQA +
    RMSNorm. The expert dim shards over the ep mesh axis when served with
    init_inference(ep_size=...)."""

    hf_class_names = ("MixtralForCausalLM",)

    @classmethod
    def convert(cls, hf_model):
        from ..models.decoder import DecoderConfig

        hc = hf_model.config
        t = hf_model.model
        E, L = hc.hidden_size, hc.num_hidden_layers
        window = int(getattr(hc, "sliding_window", 0) or 0)
        cfg = DecoderConfig(
            vocab_size=hc.vocab_size,
            n_positions=hc.max_position_embeddings,
            n_embd=E,
            n_layer=L,
            n_head=hc.num_attention_heads,
            ffn_dim=hc.intermediate_size,
            pos_emb="rope",
            rope_style="neox",
            rope_theta=float(getattr(hc, "rope_theta", 10000.0)),
            norm="rmsnorm",
            mlp_type="moe_swiglu",
            moe_experts=hc.num_local_experts,
            moe_top_k=hc.num_experts_per_tok,
            n_kv_head=int(getattr(hc, "num_key_value_heads", hc.num_attention_heads)),
            tie_embeddings=bool(getattr(hc, "tie_word_embeddings", False)),
            layer_norm_epsilon=hc.rms_norm_eps,
            local_windows=(window,) * L if window else (),
        )

        def get(l):
            m = l.block_sparse_moe
            return {
                "ln_1": {"scale": _t(l.input_layernorm.weight)},
                "ln_2": {"scale": _t(l.post_attention_layernorm.weight)},
                "attn": {
                    "wq": _linear_w(l.self_attn.q_proj),
                    "wk": _linear_w(l.self_attn.k_proj),
                    "wv": _linear_w(l.self_attn.v_proj),
                    "wo": _linear_w(l.self_attn.o_proj),
                },
                "mlp": {
                    "gate_w": _linear_w(m.gate),  # router [E_model, X]
                    "w_gate": _stack([_linear_w(x.w1) for x in m.experts]),
                    "w_in": _stack([_linear_w(x.w3) for x in m.experts]),
                    "w_out": _stack([_linear_w(x.w2) for x in m.experts]),
                },
            }

        params = {
            "wte": _t(t.embed_tokens.weight),
            "ln_f": {"scale": _t(t.norm.weight)},
            "blocks": _tree_stack([get(l) for l in t.layers]),
        }
        if not cfg.tie_embeddings:
            params["lm_head_w"] = _linear_w(hf_model.lm_head)
        return "decoder", cfg, params


POLICY_REGISTRY: List[type] = [
    HFGPT2LayerPolicy,
    HFOPTLayerPolicy,
    BLOOMLayerPolicy,
    HFGPTJLayerPolicy,
    HFGPTNEOLayerPolicy,
    GPTNEOXLayerPolicy,
    HFLlamaLayerPolicy,
    HFMixtralLayerPolicy,
    HFBertLayerPolicy,
]


def register_policy(policy: type) -> type:
    POLICY_REGISTRY.append(policy)
    return policy


def match_policy(hf_model) -> Optional[type]:
    for pol in POLICY_REGISTRY:
        if pol.matches(hf_model):
            return pol
    return None
