"""Per-architecture injection policies: HF torch checkpoints → TPU decode graph.

Analog of reference ``deepspeed/module_inject/replace_policy.py`` (501 LoC:
HFBertLayerPolicy:66, HFGPTNEOLayerPolicy:129, HFGPTJLayerPolicy:174,
MegatronLayerPolicy:219, HFGPT2LayerPolicy:299, BLOOMLayerPolicy:339,
GPTNEOXLayerPolicy:381, HFOPTLayerPolicy:435). The reference's policy returns
the attention/MLP/LayerNorm tensors of ONE torch layer so replace_module can
rebuild it around fused CUDA kernels. Here a policy converts the WHOLE model
once: torch weights → a stacked (scan-over-layers) JAX param pytree + the
matching model config, after which the decode graph is an ordinary jitted
function (XLA is the fused kernel).

Policies register in ``POLICY_REGISTRY``; ``match_policy`` picks by HF class
name so ``init_inference(hf_model)`` needs no explicit policy argument
(reference ``replace_method="auto"``).
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

PyTree = Any


def _t(x) -> np.ndarray:
    """torch tensor → numpy fp32 (host-side; conversion happens once)."""
    return x.detach().cpu().float().numpy()


def _stack(layers: List[np.ndarray]) -> np.ndarray:
    return np.stack(layers, axis=0)


class DSPolicy:
    """Base policy. Subclasses set ``hf_class_names`` and implement
    ``convert(hf_model) -> (model_kind, config, params)``."""

    hf_class_names: Tuple[str, ...] = ()

    @classmethod
    def matches(cls, hf_model) -> bool:
        return type(hf_model).__name__ in cls.hf_class_names

    @classmethod
    def convert(cls, hf_model):
        raise NotImplementedError


class HFGPT2LayerPolicy(DSPolicy):
    """transformers GPT2LMHeadModel / GPT2Model → models.gpt2 stacked params.

    HF GPT-2 uses Conv1D with weight stored [in, out] — identical to our
    matmul layout, so tensors map 1:1 (reference HFGPT2LayerPolicy:299 also
    relies on this orientation)."""

    hf_class_names = ("GPT2LMHeadModel", "GPT2Model")

    @classmethod
    def convert(cls, hf_model):
        from ..models.gpt2 import GPT2Config

        t = hf_model.transformer if hasattr(hf_model, "transformer") else hf_model
        hf_cfg = hf_model.config
        cfg = GPT2Config(
            vocab_size=hf_cfg.vocab_size,
            n_positions=hf_cfg.n_positions,
            n_embd=hf_cfg.n_embd,
            n_layer=hf_cfg.n_layer,
            n_head=hf_cfg.n_head,
            layer_norm_epsilon=hf_cfg.layer_norm_epsilon,
            attn_impl="jnp",
        )
        hs = list(t.h)
        params = {
            "wte": _t(t.wte.weight),
            "wpe": _t(t.wpe.weight),
            "ln_f": {"scale": _t(t.ln_f.weight), "bias": _t(t.ln_f.bias)},
            "blocks": {
                "ln_1": {
                    "scale": _stack([_t(h.ln_1.weight) for h in hs]),
                    "bias": _stack([_t(h.ln_1.bias) for h in hs]),
                },
                "ln_2": {
                    "scale": _stack([_t(h.ln_2.weight) for h in hs]),
                    "bias": _stack([_t(h.ln_2.bias) for h in hs]),
                },
                "attn": {
                    "c_attn_w": _stack([_t(h.attn.c_attn.weight) for h in hs]),
                    "c_attn_b": _stack([_t(h.attn.c_attn.bias) for h in hs]),
                    "c_proj_w": _stack([_t(h.attn.c_proj.weight) for h in hs]),
                    "c_proj_b": _stack([_t(h.attn.c_proj.bias) for h in hs]),
                },
                "mlp": {
                    "c_fc_w": _stack([_t(h.mlp.c_fc.weight) for h in hs]),
                    "c_fc_b": _stack([_t(h.mlp.c_fc.bias) for h in hs]),
                    "c_proj_w": _stack([_t(h.mlp.c_proj.weight) for h in hs]),
                    "c_proj_b": _stack([_t(h.mlp.c_proj.bias) for h in hs]),
                },
            },
        }
        return "gpt2", cfg, params


POLICY_REGISTRY: List[type] = [HFGPT2LayerPolicy]


def register_policy(policy: type) -> type:
    POLICY_REGISTRY.append(policy)
    return policy


def match_policy(hf_model) -> Optional[type]:
    for pol in POLICY_REGISTRY:
        if pol.matches(hf_model):
            return pol
    return None
