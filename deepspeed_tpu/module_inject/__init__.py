from .replace_module import replace_transformer_layer, revert_transformer_layer
from .replace_policy import (
    DSPolicy,
    HFGPT2LayerPolicy,
    POLICY_REGISTRY,
    match_policy,
)
from .tp_shard import permute_qkv_for_tp, tp_shard_serving_params

__all__ = [
    "DSPolicy",
    "HFGPT2LayerPolicy",
    "POLICY_REGISTRY",
    "match_policy",
    "permute_qkv_for_tp",
    "replace_transformer_layer",
    "revert_transformer_layer",
    "tp_shard_serving_params",
]
