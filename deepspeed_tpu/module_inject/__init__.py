from .replace_module import replace_transformer_layer, revert_transformer_layer
from .replace_policy import (
    DSPolicy,
    HFGPT2LayerPolicy,
    POLICY_REGISTRY,
    match_policy,
)

__all__ = [
    "DSPolicy",
    "HFGPT2LayerPolicy",
    "POLICY_REGISTRY",
    "match_policy",
    "replace_transformer_layer",
    "revert_transformer_layer",
]
