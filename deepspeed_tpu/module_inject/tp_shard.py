"""Tensor-parallel weight mapping for the injected serving tree (ISSUE 14).

The reference projects TP-slices torch weights at injection time
(``ReplaceWithTensorSlicing.copy``, module_inject/replace_module.py): each
rank keeps ``1/tp`` of every attention/MLP matrix, chosen so the per-rank
slice is a complete set of heads. Here the whole tree stays materialized
(JAX shards it with ``NamedSharding`` device_puts instead of per-rank
copies), so the only real work is the **layout fix** the reference hides in
its ``qkv`` copy path:

``c_attn_w`` is ``[L, E, 3E]`` with output columns ``[Q | K | V]``. A plain
``PartitionSpec(None, None, "tp")`` hands rank ``r`` the contiguous column
block ``[3E/tp * r, 3E/tp * (r+1))`` — a slice that straddles the Q/K/V
boundary and contains heads of *different roles*. For head-parallel
attention each rank needs ``[Q_r | K_r | V_r]``: its own ``H/tp`` heads of
each role. :func:`permute_qkv_for_tp` reorders the columns from role-major
``(3, tp, Hl*D)`` to rank-major ``(tp, 3, Hl*D)`` so the naive contiguous
slice IS the head-parallel slice; ``c_attn_b`` gets the same permutation.

Row-parallel matrices (``attn/c_proj_w``, ``mlp/c_proj_w``) need no
permutation: their *input* dim is heads-major (``[E, ...]`` with head ``h``
owning rows ``[h*D, (h+1)*D)``), already contiguous per rank. MLP ``c_fc``
column slices are role-free too. Everything else is replicated.
"""

from __future__ import annotations

from typing import Any

import jax.numpy as jnp

PyTree = Any


def permute_qkv_for_tp(w, b, tp: int):
    """Reorder fused-QKV output columns from role-major to rank-major.

    ``w``: ``[L, E, 3E]`` (or ``[E, 3E]``), ``b``: ``[L, 3E]`` (or
    ``[3E]``). Columns regrouped ``(3, tp, Hl*D) -> (tp, 3, Hl*D)`` so the
    contiguous ``tp``-slice ``r`` holds exactly ``[Q_r | K_r | V_r]``.
    Identity at ``tp == 1``. Returns ``(w, b)``."""
    tp = int(tp)
    if tp <= 1:
        return w, b
    three_e = int(w.shape[-1])
    if three_e % (3 * tp):
        raise ValueError(
            f"fused QKV width {three_e} not divisible by 3*tp={3 * tp}"
        )
    chunk = three_e // (3 * tp)  # Hl * D: one rank's heads of one role
    lead_w = tuple(w.shape[:-1])
    lead_b = tuple(b.shape[:-1])
    nw = len(lead_w)
    nb = len(lead_b)
    w = w.reshape(lead_w + (3, tp, chunk))
    w = jnp.swapaxes(w, nw, nw + 1).reshape(lead_w + (three_e,))
    b = b.reshape(lead_b + (3, tp, chunk))
    b = jnp.swapaxes(b, nb, nb + 1).reshape(lead_b + (three_e,))
    return w, b


def tp_shard_serving_params(params: PyTree, tp: int) -> PyTree:
    """The injected gpt2 serving tree, QKV-permuted for a ``tp``-way mesh.

    Pure layout transform — values identical up to column order, so the
    TP=1 tree passes through untouched and checkpoint round-trips stay
    byte-stable. The caller device_puts the result with the sharding
    table (``serving.placement.GPT2_SERVING_RULES``)."""
    if int(tp) <= 1:
        return params
    out = dict(params)
    blocks = dict(out["blocks"])
    attn = dict(blocks["attn"])
    attn["c_attn_w"], attn["c_attn_b"] = permute_qkv_for_tp(
        attn["c_attn_w"], attn["c_attn_b"], tp
    )
    blocks["attn"] = attn
    out["blocks"] = blocks
    return out
