"""Layer-streaming HF checkpoint loader — no torch module materialized.

Analog of reference ``deepspeed/module_inject/load_checkpoint.py:241``
(load_model_with_checkpoint: walks the injected module layer-by-layer,
copying tensors out of per-shard state dicts so an OPT-13B-class model never
needs model+state_dict resident at once). The TPU-native equivalent skips the
torch module entirely: checkpoint shards (safetensors or torch .bin) are
opened lazily, each tensor is read once, written into its slot of the stacked
JAX param layout, and released. Peak host RAM ≈ the final param stack in the
target dtype (2 B/param for bf16) + one tensor — vs the policy path's full
fp32 torch model + converted copy (~6x more for a 13B model).

Per-architecture key maps register like injection policies; GPT-2 ships
built-in, others convert via ``replace_transformer_layer`` (live module) or
register a map with :func:`register_checkpoint_map`.
"""

from __future__ import annotations

import json
import os
from typing import Any, Callable, Dict, Optional, Tuple

import numpy as np

PyTree = Any


class _CkptReader:
    """Lazy tensor access across sharded safetensors / torch .bin files."""

    def __init__(self, model_dir: str):
        self.dir = model_dir
        self._key_to_file: Dict[str, str] = {}
        self._open_safetensors: Dict[str, Any] = {}
        self._bin_cache: Dict[str, Dict[str, Any]] = {}
        st = [f for f in os.listdir(model_dir) if f.endswith(".safetensors")]
        bins = [f for f in os.listdir(model_dir) if f.endswith(".bin")]
        idx_st = os.path.join(model_dir, "model.safetensors.index.json")
        idx_bin = os.path.join(model_dir, "pytorch_model.bin.index.json")
        if os.path.exists(idx_st):
            for k, f in json.load(open(idx_st))["weight_map"].items():
                self._key_to_file[k] = f
        elif os.path.exists(idx_bin):
            for k, f in json.load(open(idx_bin))["weight_map"].items():
                self._key_to_file[k] = f
        elif st:
            from safetensors import safe_open

            for f in st:
                with safe_open(os.path.join(model_dir, f), framework="np") as h:
                    for k in h.keys():
                        self._key_to_file[k] = f
        elif bins:
            import torch

            for f in bins:
                # mmap keeps storages on disk until sliced
                sd = torch.load(
                    os.path.join(model_dir, f), map_location="cpu", mmap=True,
                    weights_only=True,
                )
                self._bin_cache[f] = sd
                for k in sd:
                    self._key_to_file[k] = f
        else:
            raise FileNotFoundError(f"no checkpoint files in {model_dir}")

    def keys(self):
        return self._key_to_file.keys()

    def get(self, key: str) -> np.ndarray:
        f = self._key_to_file[key]
        path = os.path.join(self.dir, f)
        if f.endswith(".safetensors"):
            from safetensors import safe_open

            h = self._open_safetensors.get(f)
            if h is None:
                h = safe_open(path, framework="np")
                self._open_safetensors[f] = h
            t = h.get_tensor(key)
            if t.dtype.kind == "V":  # bf16 surfaces as a void dtype in numpy
                import ml_dtypes

                t = t.view(ml_dtypes.bfloat16)
            # source dtype kept — the layer loop casts ONCE to the target
            # dtype, avoiding a transient fp32 copy of every tensor
            return t
        # torch .bin shard (mmap'd)
        if f not in self._bin_cache:
            import torch

            self._bin_cache[f] = torch.load(
                path, map_location="cpu", mmap=True, weights_only=True
            )
        return self._bin_cache[f][key].float().numpy()


# arch name → (match_fn(config_dict) -> bool, loader_fn(reader, config_dict, dtype))
_CKPT_MAPS: Dict[str, Tuple[Callable, Callable]] = {}


def register_checkpoint_map(name: str, match, loader) -> None:
    _CKPT_MAPS[name] = (match, loader)


def load_checkpoint_streamed(model_dir: str, dtype=None) -> Tuple[str, Any, PyTree]:
    """Stream an HF checkpoint directory into (kind, model_config, params).

    Drop-in alternative to ``replace_transformer_layer`` for checkpoints too
    big to instantiate as a torch model (reference load_checkpoint.py:241).
    """
    import jax.numpy as jnp

    dtype = dtype or jnp.bfloat16
    with open(os.path.join(model_dir, "config.json")) as f:
        hf_cfg = json.load(f)
    reader = _CkptReader(model_dir)
    for name, (match, loader) in _CKPT_MAPS.items():
        if match(hf_cfg):
            return loader(reader, hf_cfg, dtype)
    raise ValueError(
        f"no streaming checkpoint map for model_type={hf_cfg.get('model_type')}; "
        "registered: " + ", ".join(_CKPT_MAPS) + ". Use replace_transformer_layer "
        "or register_checkpoint_map."
    )


# ---------------------------------------------------------------------------
# GPT-2 (flagship): transformer.h.{i}.* → stacked blocks
# ---------------------------------------------------------------------------

def _load_gpt2(reader: _CkptReader, hf_cfg: dict, dtype) -> Tuple[str, Any, PyTree]:
    import jax.numpy as jnp
    import ml_dtypes

    from ..models.gpt2 import GPT2Config

    L = hf_cfg["n_layer"]
    E = hf_cfg["n_embd"]
    cfg = GPT2Config(
        vocab_size=hf_cfg["vocab_size"],
        n_positions=hf_cfg["n_positions"],
        n_embd=E,
        n_layer=L,
        n_head=hf_cfg["n_head"],
        layer_norm_epsilon=hf_cfg.get("layer_norm_epsilon", 1e-5),
        dtype=dtype,
    )
    np_dt = ml_dtypes.bfloat16 if dtype == jnp.bfloat16 else np.dtype(dtype)
    pref = "transformer." if any(k.startswith("transformer.") for k in reader.keys()) else ""

    def g(key):
        return reader.get(pref + key)

    # stacked block leaves preallocated in the TARGET dtype; each layer's
    # tensors are read, written, and freed — the streaming property
    blocks = {
        "ln_1": {"scale": np.empty((L, E), np_dt), "bias": np.empty((L, E), np_dt)},
        "ln_2": {"scale": np.empty((L, E), np_dt), "bias": np.empty((L, E), np_dt)},
        "attn": {
            "c_attn_w": np.empty((L, E, 3 * E), np_dt),
            "c_attn_b": np.empty((L, 3 * E), np_dt),
            "c_proj_w": np.empty((L, E, E), np_dt),
            "c_proj_b": np.empty((L, E), np_dt),
        },
        "mlp": {
            "c_fc_w": np.empty((L, E, 4 * E), np_dt),
            "c_fc_b": np.empty((L, 4 * E), np_dt),
            "c_proj_w": np.empty((L, 4 * E, E), np_dt),
            "c_proj_b": np.empty((L, E), np_dt),
        },
    }
    # HF Conv1D stores [in, out] — already our h @ w layout, no transpose
    per_layer = [
        ("ln_1.weight", lambda b, i, t: b["ln_1"]["scale"].__setitem__(i, t)),
        ("ln_1.bias", lambda b, i, t: b["ln_1"]["bias"].__setitem__(i, t)),
        ("ln_2.weight", lambda b, i, t: b["ln_2"]["scale"].__setitem__(i, t)),
        ("ln_2.bias", lambda b, i, t: b["ln_2"]["bias"].__setitem__(i, t)),
        ("attn.c_attn.weight", lambda b, i, t: b["attn"]["c_attn_w"].__setitem__(i, t)),
        ("attn.c_attn.bias", lambda b, i, t: b["attn"]["c_attn_b"].__setitem__(i, t)),
        ("attn.c_proj.weight", lambda b, i, t: b["attn"]["c_proj_w"].__setitem__(i, t)),
        ("attn.c_proj.bias", lambda b, i, t: b["attn"]["c_proj_b"].__setitem__(i, t)),
        ("mlp.c_fc.weight", lambda b, i, t: b["mlp"]["c_fc_w"].__setitem__(i, t)),
        ("mlp.c_fc.bias", lambda b, i, t: b["mlp"]["c_fc_b"].__setitem__(i, t)),
        ("mlp.c_proj.weight", lambda b, i, t: b["mlp"]["c_proj_w"].__setitem__(i, t)),
        ("mlp.c_proj.bias", lambda b, i, t: b["mlp"]["c_proj_b"].__setitem__(i, t)),
    ]
    for i in range(L):
        for suffix, write in per_layer:
            t = g(f"h.{i}.{suffix}")
            write(blocks, i, t.astype(np_dt))
            del t

    params = {
        "wte": g("wte.weight").astype(np_dt),
        "wpe": g("wpe.weight").astype(np_dt),
        "ln_f": {
            "scale": g("ln_f.weight").astype(np_dt),
            "bias": g("ln_f.bias").astype(np_dt),
        },
        "blocks": blocks,
    }
    return "gpt2", cfg, params


register_checkpoint_map(
    "gpt2", lambda c: c.get("model_type") == "gpt2", _load_gpt2
)
