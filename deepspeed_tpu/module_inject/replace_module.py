"""Model surgery: swap an HF torch model for the TPU-native decode graph.

Analog of reference ``deepspeed/module_inject/replace_module.py``
(replace_transformer_layer:190, generic walker replace_module:1069,
ReplaceWithTensorSlicing:18, GroupQuantizer:139, 1124 LoC). The reference
walks the torch module tree swapping layers for fused-kernel modules and
hand-slices weights per TP rank. Here the whole model converts ONCE through a
policy into a stacked JAX pytree; "tensor slicing" is a NamedSharding
device_put chosen by the model's logical axes (XLA materialises each rank's
slice), and the fused module is the jitted decode function.
"""

from __future__ import annotations

from typing import Any, Optional, Tuple

import jax
import jax.numpy as jnp

from ..utils.logging import log_dist
from .replace_policy import match_policy

PyTree = Any


def replace_transformer_layer(
    hf_model,
    policy: Optional[type] = None,
    dtype=jnp.bfloat16,
    quantize_bits: int = 0,
    quantize_groups: int = 1,  # reference _init_quantization_setting default
) -> Tuple[str, Any, PyTree]:
    """Convert an HF torch model via its injection policy.

    Returns (model_kind, model_config, params). ``quantize_bits=8`` stores the
    large matmul weights int8 group-quantized (GroupQuantizer analog);
    everything else is cast to ``dtype``.
    """
    pol = policy or match_policy(hf_model)
    if pol is None:
        raise ValueError(
            f"no injection policy for {type(hf_model).__name__}; known: "
            "GPT2LMHeadModel/GPT2Model (register more via "
            "module_inject.replace_policy.register_policy)"
        )
    kind, cfg, params_np = pol.convert(hf_model)
    log_dist(f"module_inject: {type(hf_model).__name__} → {kind} via {pol.__name__}")

    if quantize_bits == 8:
        from ..ops.quantizer import quantize_tree

        params = quantize_tree(
            jax.tree.map(jnp.asarray, params_np),
            groups=quantize_groups,
            dtype=dtype,
        )
    else:
        params = jax.tree.map(
            lambda x: jnp.asarray(x, dtype) if np_floating(x) else jnp.asarray(x),
            params_np,
        )
    return kind, cfg, params


def revert_transformer_layer(hf_model, params: PyTree, policy: Optional[type] = None):
    """Write a (possibly fine-tuned) converted param tree BACK into the HF
    torch model — the reference's reverse surgery
    (``module_inject/replace_module.py:1001`` restores original layers from
    the fused modules). Our conversion is whole-model, so revert is the
    per-policy inverse tensor mapping; policies declare it via a ``revert``
    classmethod (GPT-2's mapping is 1:1, so it round-trips exactly).

    Returns ``hf_model`` with weights updated in place.
    """
    pol = policy or match_policy(hf_model)
    if pol is None:
        raise ValueError(f"no injection policy matches {type(hf_model).__name__}")
    if not hasattr(pol, "revert"):
        raise NotImplementedError(
            f"{pol.__name__} defines no inverse mapping (revert); only "
            "policies with a declared revert support writing weights back "
            "into the HF model"
        )
    from ..ops.quantizer import QuantizedWeight

    if any(
        isinstance(l, QuantizedWeight)
        for l in jax.tree.leaves(
            params, is_leaf=lambda x: isinstance(x, QuantizedWeight)
        )
    ):
        raise ValueError(
            "cannot revert int8-quantized params (replace_transformer_layer "
            "with quantize_bits>0); convert with quantize_bits=0 to round-trip"
        )
    pol.revert(hf_model, params)
    log_dist(f"revert_transformer_layer: restored HF weights via {pol.__name__}")
    return hf_model


def np_floating(x) -> bool:
    import numpy as np

    return np.issubdtype(np.asarray(x).dtype, np.floating)
