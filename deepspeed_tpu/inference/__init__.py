from .engine import InferenceEngine
