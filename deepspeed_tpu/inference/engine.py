"""Inference engine — ``deepspeed_tpu.init_inference`` backend.

Analog of reference ``deepspeed/inference/engine.py`` (InferenceEngine:28):
wraps a model for serving — dtype conversion, tensor-parallel sharding over a
mesh, compiled forward. Where the reference injects fused CUDA kernels
(module_inject/replace_module.py) and captures CUDA graphs, the TPU version
jit-compiles the forward with TP shardings (XLA performs the fusion and the
"graph capture" is the compiled executable itself).

Current scope: compiled sharded forward + greedy/temperature generation by
full-prefix recompute. The KV-cache incremental decode path (reference
``softmax_context`` kernels) lands with the Pallas decode-attention kernel.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec

from ..parallel.topology import MeshSpec
from ..runtime.module import ModuleSpec
from ..runtime.zero.partitioning import ZeroShardingPolicy
from ..utils.logging import log_dist

PyTree = Any


class InferenceEngine:
    def __init__(
        self,
        model: Optional[ModuleSpec] = None,
        params: Optional[PyTree] = None,
        mp_size: int = 1,
        dtype=jnp.bfloat16,
        mesh: Optional[Mesh] = None,
        replace_with_kernel_inject: bool = False,
        seed: int = 0,
        **kwargs,
    ):
        assert model is not None and model.apply_fn is not None, (
            "init_inference requires a ModuleSpec with apply_fn"
        )
        self.module = model
        self.dtype = dtype
        if mesh is None:
            mesh = MeshSpec(dp=1, tp=mp_size, devices=jax.devices()[: max(1, mp_size)]).build_mesh()
        self.mesh = mesh
        # TP-only sharding (stage 0 → no dp sharding of weights)
        self.policy = ZeroShardingPolicy(mesh, stage=0)

        init_rng = jax.random.PRNGKey(seed)
        abstract = jax.eval_shape(model.init, init_rng)
        self.param_shardings = self.policy.param_shardings(abstract, model.logical_axes)
        if params is None:
            params = jax.jit(model.init, out_shardings=self.param_shardings)(init_rng)
        else:
            params = jax.tree.map(jax.device_put, params, self.param_shardings)
        # dtype conversion (reference _convert_to_dtype, engine.py:464)
        self.params = jax.tree.map(
            lambda p: p.astype(dtype) if jnp.issubdtype(p.dtype, jnp.floating) else p, params
        )
        self._forward = jax.jit(model.apply_fn)
        log_dist(f"InferenceEngine: mesh={dict(mesh.shape)} dtype={dtype.__name__ if hasattr(dtype,'__name__') else dtype}")

    def forward(self, batch: PyTree):
        """Compiled forward (reference engine.forward:515)."""
        return self._forward(self.params, batch)

    __call__ = forward

    def generate(
        self,
        input_ids: np.ndarray,
        max_new_tokens: int = 20,
        temperature: float = 0.0,
        seed: int = 0,
    ) -> np.ndarray:
        """Autoregressive generation (full-prefix recompute path)."""
        ids = jnp.asarray(input_ids)
        rng = jax.random.PRNGKey(seed)
        for _ in range(max_new_tokens):
            logits = self._forward(self.params, {"input_ids": ids})
            last = logits[:, -1, :].astype(jnp.float32)
            if temperature and temperature > 0.0:
                rng, k = jax.random.split(rng)
                nxt = jax.random.categorical(k, last / temperature, axis=-1)
            else:
                nxt = jnp.argmax(last, axis=-1)
            ids = jnp.concatenate([ids, nxt[:, None].astype(ids.dtype)], axis=1)
        return np.asarray(jax.device_get(ids))
